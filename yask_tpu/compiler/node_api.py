"""Node-factory API for building ASTs without operator overloading.

Counterpart of ``yc_node_factory`` (``include/aux/yc_node_api.hpp``,
``yask_compiler_api.hpp``): every expression kind is constructible through an
explicit factory method, which is the surface third-party front-ends (and the
reference's Python API tests) use.
"""

from __future__ import annotations

from typing import Optional, Sequence

from yask_tpu.compiler.expr import (
    AddExpr,
    AndExpr,
    BoolExpr,
    CompExpr,
    ConstExpr,
    DivExpr,
    EqualsExpr,
    FirstIndexExpr,
    FuncExpr,
    IndexExpr,
    IndexType,
    LastIndexExpr,
    ModExpr,
    MultExpr,
    NegExpr,
    NotExpr,
    NumExpr,
    OrExpr,
    SubExpr,
    VarPoint,
    _coerce_num,
)


class yc_node_factory:
    """Explicit AST-node factory (``yc_node_factory``)."""

    # ---- indices ---------------------------------------------------------

    def new_step_index(self, name: str) -> IndexExpr:
        return IndexExpr(name, IndexType.STEP)

    def new_domain_index(self, name: str) -> IndexExpr:
        return IndexExpr(name, IndexType.DOMAIN)

    def new_misc_index(self, name: str) -> IndexExpr:
        return IndexExpr(name, IndexType.MISC)

    def new_first_domain_index(self, dim: IndexExpr) -> FirstIndexExpr:
        return FirstIndexExpr(dim)

    def new_last_domain_index(self, dim: IndexExpr) -> LastIndexExpr:
        return LastIndexExpr(dim)

    # ---- numeric nodes ---------------------------------------------------

    def new_const_number_node(self, val) -> ConstExpr:
        return ConstExpr(val)

    def new_negate_node(self, arg) -> NumExpr:
        return NegExpr(_coerce_num(arg))

    def new_add_node(self, lhs, rhs) -> NumExpr:
        return AddExpr.make([_coerce_num(lhs), _coerce_num(rhs)])

    def new_subtract_node(self, lhs, rhs) -> NumExpr:
        return SubExpr(_coerce_num(lhs), _coerce_num(rhs))

    def new_multiply_node(self, lhs, rhs) -> NumExpr:
        return MultExpr.make([_coerce_num(lhs), _coerce_num(rhs)])

    def new_divide_node(self, lhs, rhs) -> NumExpr:
        return DivExpr(_coerce_num(lhs), _coerce_num(rhs))

    def new_mod_node(self, lhs, rhs) -> NumExpr:
        return ModExpr(_coerce_num(lhs), _coerce_num(rhs))

    def new_math_func_node(self, name: str, args: Sequence) -> FuncExpr:
        return FuncExpr(name, [_coerce_num(a) for a in args])

    # ---- boolean nodes ---------------------------------------------------

    def new_equals_node(self, lhs, rhs) -> CompExpr:
        return CompExpr("==", _coerce_num(lhs), _coerce_num(rhs))

    def new_not_equals_node(self, lhs, rhs) -> CompExpr:
        return CompExpr("!=", _coerce_num(lhs), _coerce_num(rhs))

    def new_less_than_node(self, lhs, rhs) -> CompExpr:
        return CompExpr("<", _coerce_num(lhs), _coerce_num(rhs))

    def new_greater_than_node(self, lhs, rhs) -> CompExpr:
        return CompExpr(">", _coerce_num(lhs), _coerce_num(rhs))

    def new_not_less_than_node(self, lhs, rhs) -> CompExpr:
        return CompExpr(">=", _coerce_num(lhs), _coerce_num(rhs))

    def new_not_greater_than_node(self, lhs, rhs) -> CompExpr:
        return CompExpr("<=", _coerce_num(lhs), _coerce_num(rhs))

    def new_and_node(self, lhs: BoolExpr, rhs: BoolExpr) -> AndExpr:
        return AndExpr(lhs, rhs)

    def new_or_node(self, lhs: BoolExpr, rhs: BoolExpr) -> OrExpr:
        return OrExpr(lhs, rhs)

    def new_not_node(self, arg: BoolExpr) -> NotExpr:
        return NotExpr(arg)

    # ---- equations -------------------------------------------------------

    def new_equation_node(self, lhs: VarPoint, rhs,
                          cond: Optional[BoolExpr] = None) -> EqualsExpr:
        """Build an equation and register it with the LHS var's solution
        (matches the reference's auto-registration behavior)."""
        eq = EqualsExpr(lhs, _coerce_num(rhs), cond)
        soln = lhs.var.get_solution()
        if soln is not None:
            soln._register_eq(eq)
        return eq

    # ---- var-point builders + v2 aliases (yc_node_api.hpp) ------------

    def new_number_node(self, val) -> NumExpr:
        """Coerce a Python number (or pass through a node) —
        ``yc_node_factory::new_number_node`` / the ``yc_number_any_arg``
        conversions."""
        return _coerce_num(val)

    def new_var_point(self, var, index_exprs) -> VarPoint:
        """Access point from explicit index expressions
        (``new_var_point``)."""
        return var(*index_exprs)

    def new_relative_var_point(self, var, dim_offsets) -> VarPoint:
        """Access point from integer offsets relative to each of the
        var's declared dims (``new_relative_var_point``)."""
        args = []
        for d, o in zip(var.get_dims(), dim_offsets):
            args.append(d + int(o) if int(o) != 0 else d)
        return var(*args)

    new_grid_point = new_var_point
    new_relative_grid_point = new_relative_var_point
