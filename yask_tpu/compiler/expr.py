"""Stencil-equation expression AST.

TPU-native counterpart of the reference's expression layer
(``src/compiler/lib/Expr.hpp:96-730``, ``Expr.cpp``): numeric and boolean
expression nodes built via operator overloading, index expressions
(step/domain/misc), var access points, math functions, and the ``EQUALS``
equation former with optional domain/step conditions.

Differences from the reference are deliberate TPU-first choices:

* nodes are immutable and hashable by structure, so common-subexpression
  elimination is a dictionary, not a visitor pass;
* the AST lowers to traced JAX computations, so there is no printer-oriented
  string plumbing in the nodes themselves (printers are visitors in
  ``yask_tpu.compiler.printers``).
"""

from __future__ import annotations

import enum
from typing import Dict, List, Optional, Sequence, Tuple, Union

from yask_tpu.utils.exceptions import YaskException

Number = Union[int, float]


class IndexType(enum.Enum):
    """Kind of a solution index (``yc_index_node`` kinds in the reference:
    ``new_step_index``/``new_domain_index``/``new_misc_index``,
    ``yask_compiler_api.hpp``)."""
    STEP = "step"
    DOMAIN = "domain"
    MISC = "misc"


# ---------------------------------------------------------------------------
# base classes
# ---------------------------------------------------------------------------


class Expr:
    """Base of all AST nodes. Immutable; structural equality and hashing.

    NOTE: on NumExpr, Python ``==`` is overloaded to *build a comparison
    node* (for conditions), so structural identity must never go through
    ``==`` of children. :func:`structural_key` produces a primitives-only
    key; ``same()`` and ``__hash__`` use it, making nodes safe as dict/set
    keys (the basis of CSE).
    """

    __slots__ = ("_skey",)

    def _key(self) -> tuple:
        raise NotImplementedError

    @staticmethod
    def _to_skey(v):
        if isinstance(v, Expr):
            return v.skey()
        if isinstance(v, tuple):
            return tuple(Expr._to_skey(x) for x in v)
        return v

    def skey(self) -> tuple:
        """Fully-recursive structural key made only of primitives."""
        k = getattr(self, "_skey", None)
        if k is None:
            k = (type(self).__name__,) + tuple(
                self._to_skey(c) for c in self._key())
            object.__setattr__(self, "_skey", k)
        return k

    def __eq__(self, other):
        return NotImplemented

    def same(self, other) -> bool:
        """Structural equality (the reference's ``Expr::is_same``)."""
        return isinstance(other, Expr) and self.skey() == other.skey()

    def __hash__(self):
        return hash(self.skey())

    def accept(self, visitor: "ExprVisitor"):
        raise NotImplementedError

    def get_children(self) -> Sequence["Expr"]:
        return ()

    def format_simple(self) -> str:
        """Human-readable rendering (the reference's ``make_str``)."""
        from yask_tpu.compiler.printers import format_expr
        return format_expr(self)

    def clone_ast(self) -> "Expr":
        """Deep clone of this AST (``yc_expr_node::clone_ast``).  Vars
        are identities (storage declarations, not AST nodes) and stay
        shared — ``Var.__deepcopy__`` returns self."""
        import copy
        return copy.deepcopy(self)

    def get_num_nodes(self) -> int:
        """Total node count of this subtree
        (``yc_expr_node::get_num_nodes``)."""
        return 1 + sum(c.get_num_nodes() for c in self.get_children())

    def __repr__(self):
        return f"<{type(self).__name__} {self.format_simple()}>"


def _coerce_num(v) -> "NumExpr":
    if isinstance(v, NumExpr):
        return v
    if isinstance(v, (int, float)):
        return ConstExpr(v)
    raise YaskException(f"cannot use {v!r} in a stencil expression")


class NumExpr(Expr):
    """Numeric-valued expression; operator overloading builds the AST
    (reference ``Expr.cpp:407-442`` operator definitions)."""

    __slots__ = ()

    # arithmetic -----------------------------------------------------------
    def __add__(self, other):
        return AddExpr.make([self, _coerce_num(other)])

    def __radd__(self, other):
        return AddExpr.make([_coerce_num(other), self])

    def __sub__(self, other):
        return SubExpr(self, _coerce_num(other))

    def __rsub__(self, other):
        return SubExpr(_coerce_num(other), self)

    def __mul__(self, other):
        return MultExpr.make([self, _coerce_num(other)])

    def __rmul__(self, other):
        return MultExpr.make([_coerce_num(other), self])

    def __truediv__(self, other):
        return DivExpr(self, _coerce_num(other))

    def __rtruediv__(self, other):
        return DivExpr(_coerce_num(other), self)

    def __neg__(self):
        return NegExpr(self)

    def __pow__(self, other):
        return FuncExpr("pow", (self, _coerce_num(other)))

    def __mod__(self, other):
        return ModExpr(self, _coerce_num(other))

    # comparisons → boolean AST (for sub-domain/step conditions) ----------
    def __eq__(self, other):  # type: ignore[override]
        return CompExpr("==", self, _coerce_num(other))

    def __ne__(self, other):  # type: ignore[override]
        return CompExpr("!=", self, _coerce_num(other))

    def __lt__(self, other):
        return CompExpr("<", self, _coerce_num(other))

    def __le__(self, other):
        return CompExpr("<=", self, _coerce_num(other))

    def __gt__(self, other):
        return CompExpr(">", self, _coerce_num(other))

    def __ge__(self, other):
        return CompExpr(">=", self, _coerce_num(other))

    __hash__ = Expr.__hash__


# ---------------------------------------------------------------------------
# leaf nodes
# ---------------------------------------------------------------------------


class ConstExpr(NumExpr):
    """Floating-point constant (reference ``ConstExpr``)."""

    __slots__ = ("value",)

    def __init__(self, value: Number):
        object.__setattr__(self, "value", float(value))

    def _key(self):
        return (self.value,)

    def get_value(self) -> float:
        return self.value

    def set_value(self, val) -> None:
        """Mutate the constant (``yc_const_number_node::set_value``).
        Only safe BEFORE the node is registered in an equation: skeys
        (the CSE identities) are cached on first use."""
        object.__setattr__(self, "value", float(val))
        object.__setattr__(self, "_skey", None)

    def accept(self, visitor):
        return visitor.visit_const(self)


class IndexExpr(NumExpr):
    """A solution index (step/domain/misc dim), usable both as a var
    subscript and as a numeric value in equations (reference ``IndexExpr``)."""

    __slots__ = ("name", "type")

    def __init__(self, name: str, index_type: IndexType):
        object.__setattr__(self, "name", name)
        object.__setattr__(self, "type", index_type)

    def _key(self):
        return (self.name, self.type)

    def accept(self, visitor):
        return visitor.visit_index(self)


class FirstIndexExpr(NumExpr):
    """Runtime-bound first valid domain index in a dim
    (``yc_node_factory::new_first_domain_index``)."""

    __slots__ = ("dim",)

    def __init__(self, dim: IndexExpr):
        if dim.type != IndexType.DOMAIN:
            raise YaskException(
                f"first_domain_index requires a domain index, got '{dim.name}'")
        object.__setattr__(self, "dim", dim)

    def _key(self):
        return (self.dim.name,)

    def accept(self, visitor):
        return visitor.visit_first_index(self)


class LastIndexExpr(NumExpr):
    """Runtime-bound last valid domain index in a dim
    (``yc_node_factory::new_last_domain_index``)."""

    __slots__ = ("dim",)

    def __init__(self, dim: IndexExpr):
        if dim.type != IndexType.DOMAIN:
            raise YaskException(
                f"last_domain_index requires a domain index, got '{dim.name}'")
        object.__setattr__(self, "dim", dim)

    def _key(self):
        return (self.dim.name,)

    def accept(self, visitor):
        return visitor.visit_last_index(self)


# ---------------------------------------------------------------------------
# compound numeric nodes
# ---------------------------------------------------------------------------


class NegExpr(NumExpr):
    """Unary negation (reference ``UnaryNumExpr`` '-')"""

    __slots__ = ("arg",)

    def __init__(self, arg: NumExpr):
        object.__setattr__(self, "arg", _coerce_num(arg))

    def _key(self):
        return (self.arg,)

    def get_children(self):
        return (self.arg,)

    def accept(self, visitor):
        return visitor.visit_neg(self)


class CommutativeExpr(NumExpr):
    """N-ary commutative op (reference ``CommutativeExpr``); subclasses fix
    the operator. ``make`` flattens nested same-op nodes and folds consts."""

    __slots__ = ("args",)
    OP = "?"
    IDENT = 0.0

    def __init__(self, args: Sequence[NumExpr]):
        object.__setattr__(self, "args", tuple(_coerce_num(a) for a in args))

    def get_operands(self):
        """``yc_commutative_number_node::get_operands``."""
        return list(self.args)

    def get_num_operands(self) -> int:
        return len(self.args)

    def add_operand(self, arg) -> None:
        """Append an operand (pre-registration only, like
        ``set_value``)."""
        object.__setattr__(self, "args", self.args + (_coerce_num(arg),))
        object.__setattr__(self, "_skey", None)

    @classmethod
    def make(cls, args: Sequence[NumExpr]) -> NumExpr:
        flat: List[NumExpr] = []
        const_val: Optional[float] = None
        for a in args:
            a = _coerce_num(a)
            if type(a) is cls:
                flat.extend(a.args)
            elif isinstance(a, ConstExpr):
                const_val = a.value if const_val is None else \
                    cls._fold(const_val, a.value)
            else:
                flat.append(a)
        if const_val is not None and (const_val != cls.IDENT or not flat):
            flat.append(ConstExpr(const_val))
        if len(flat) == 1:
            return flat[0]
        return cls(flat)

    @classmethod
    def _fold(cls, a: float, b: float) -> float:
        raise NotImplementedError

    def _key(self):
        return (self.OP, self.args)

    def get_children(self):
        return self.args


class AddExpr(CommutativeExpr):
    __slots__ = ()
    OP = "+"
    IDENT = 0.0

    @classmethod
    def _fold(cls, a, b):
        return a + b

    def accept(self, visitor):
        return visitor.visit_add(self)


class MultExpr(CommutativeExpr):
    __slots__ = ()
    OP = "*"
    IDENT = 1.0

    @classmethod
    def _fold(cls, a, b):
        return a * b

    def accept(self, visitor):
        return visitor.visit_mult(self)


class SubExpr(NumExpr):
    __slots__ = ("lhs", "rhs")

    def __init__(self, lhs: NumExpr, rhs: NumExpr):
        object.__setattr__(self, "lhs", _coerce_num(lhs))
        object.__setattr__(self, "rhs", _coerce_num(rhs))

    def _key(self):
        return (self.lhs, self.rhs)

    def get_children(self):
        return (self.lhs, self.rhs)

    def accept(self, visitor):
        return visitor.visit_sub(self)


class DivExpr(NumExpr):
    __slots__ = ("lhs", "rhs")

    def __init__(self, lhs: NumExpr, rhs: NumExpr):
        object.__setattr__(self, "lhs", _coerce_num(lhs))
        object.__setattr__(self, "rhs", _coerce_num(rhs))

    def _key(self):
        return (self.lhs, self.rhs)

    def get_children(self):
        return (self.lhs, self.rhs)

    def accept(self, visitor):
        return visitor.visit_div(self)


class ModExpr(NumExpr):
    __slots__ = ("lhs", "rhs")

    def __init__(self, lhs: NumExpr, rhs: NumExpr):
        object.__setattr__(self, "lhs", _coerce_num(lhs))
        object.__setattr__(self, "rhs", _coerce_num(rhs))

    def _key(self):
        return (self.lhs, self.rhs)

    def get_children(self):
        return (self.lhs, self.rhs)

    def accept(self, visitor):
        return visitor.visit_mod(self)


#: Math functions supported by the DSL (reference ``Expr.cpp`` FuncExpr set).
FUNC_NAMES = frozenset({
    "sqrt", "cbrt", "fabs", "erf", "exp", "log", "atan",
    "sin", "cos", "tan", "asin", "acos", "pow", "max", "min",
})


class FuncExpr(NumExpr):
    """Math function call (reference ``FuncExpr``)."""

    __slots__ = ("name", "args")

    def __init__(self, name: str, args: Sequence[NumExpr]):
        if name not in FUNC_NAMES:
            raise YaskException(f"unknown stencil function '{name}'")
        object.__setattr__(self, "name", name)
        object.__setattr__(self, "args", tuple(_coerce_num(a) for a in args))

    def _key(self):
        return (self.name, self.args)

    def get_children(self):
        return self.args

    def accept(self, visitor):
        return visitor.visit_func(self)


def _make_func1(name: str):
    def fn(x):
        return FuncExpr(name, (_coerce_num(x),))
    fn.__name__ = name
    fn.__doc__ = f"Build a '{name}' node (reference math-function operator)."
    return fn


sqrt = _make_func1("sqrt")
cbrt = _make_func1("cbrt")
fabs = _make_func1("fabs")
erf = _make_func1("erf")
exp = _make_func1("exp")
log = _make_func1("log")
atan = _make_func1("atan")
sin = _make_func1("sin")
cos = _make_func1("cos")
tan = _make_func1("tan")


def pow_fn(x, y):
    return FuncExpr("pow", (_coerce_num(x), _coerce_num(y)))


def max_fn(x, y):
    return FuncExpr("max", (_coerce_num(x), _coerce_num(y)))


def min_fn(x, y):
    return FuncExpr("min", (_coerce_num(x), _coerce_num(y)))


# ---------------------------------------------------------------------------
# var access points
# ---------------------------------------------------------------------------


def decompose_index_arg(arg) -> Tuple[Optional[str], int]:
    """Reduce a var-subscript expression to ``(index_name | None, offset)``.

    The DSL restricts subscripts to ``index ± const`` for step/domain dims
    and plain consts for misc dims (reference LHS/RHS access rules enforced
    in ``Eqs.cpp:364-470``); this helper normalizes the sugar produced by
    operator overloading (``t+1`` → AddExpr(IndexExpr, ConstExpr)).
    """
    if isinstance(arg, (int, float)):
        return None, int(arg)
    if isinstance(arg, ConstExpr):
        return None, int(arg.value)
    if isinstance(arg, IndexExpr):
        return arg.name, 0
    if isinstance(arg, AddExpr):
        name = None
        ofs = 0
        for a in arg.args:
            if isinstance(a, IndexExpr):
                if name is not None:
                    raise YaskException(
                        f"var subscript uses two indices: {arg.format_simple()}")
                name = a.name
            elif isinstance(a, ConstExpr):
                ofs += int(a.value)
            else:
                raise YaskException(
                    f"unsupported var subscript: {arg.format_simple()}")
        return name, ofs
    if isinstance(arg, SubExpr):
        if isinstance(arg.lhs, IndexExpr) and isinstance(arg.rhs, ConstExpr):
            return arg.lhs.name, -int(arg.rhs.value)
        raise YaskException(
            f"unsupported var subscript: {arg.format_simple()}")
    if isinstance(arg, NegExpr) and isinstance(arg.arg, ConstExpr):
        return None, -int(arg.arg.value)
    raise YaskException(
        f"unsupported var subscript: {arg!r} (must be 'index ± const' "
        "or a constant for misc dims)")


class VarPoint(NumExpr):
    """One access to a var at given index offsets (reference ``VarPoint``,
    ``src/compiler/lib/VarPoint.hpp:34``).

    ``offsets`` maps each of the var's dim names to either an int offset
    relative to its index (step/domain dims) or an absolute int (misc dims).
    """

    __slots__ = ("var", "offsets")

    def __init__(self, var, args: Sequence):
        from yask_tpu.compiler.var import Var  # local to avoid cycle
        if not isinstance(var, Var):
            raise YaskException("VarPoint needs a Var")
        dims = var.get_dims()
        if len(args) != len(dims):
            raise YaskException(
                f"var '{var.get_name()}' has {len(dims)} dims "
                f"but was accessed with {len(args)} subscripts")
        offsets: Dict[str, int] = {}
        for dim, arg in zip(dims, args):
            name, ofs = decompose_index_arg(arg)
            if dim.type == IndexType.MISC:
                if name is not None:
                    raise YaskException(
                        f"misc dim '{dim.name}' of var '{var.get_name()}' "
                        "must be accessed with a constant index")
            else:
                if name is None:
                    raise YaskException(
                        f"dim '{dim.name}' of var '{var.get_name()}' must be "
                        f"accessed via its index (e.g. '{dim.name}+1')")
                if name != dim.name:
                    raise YaskException(
                        f"dim '{dim.name}' of var '{var.get_name()}' accessed "
                        f"with wrong index '{name}'")
            offsets[dim.name] = ofs
        object.__setattr__(self, "var", var)
        object.__setattr__(self, "offsets", offsets)

    # -- accessors ---------------------------------------------------------

    def get_var(self):
        return self.var

    def var_name(self) -> str:
        return self.var.get_name()

    def step_offset(self) -> Optional[int]:
        sd = self.var.step_dim()
        return self.offsets[sd.name] if sd is not None else None

    def domain_offsets(self) -> Dict[str, int]:
        return {d.name: self.offsets[d.name]
                for d in self.var.get_dims() if d.type == IndexType.DOMAIN}

    def misc_vals(self) -> Dict[str, int]:
        return {d.name: self.offsets[d.name]
                for d in self.var.get_dims() if d.type == IndexType.MISC}

    def _key(self):
        return (self.var.get_name(), tuple(sorted(self.offsets.items())))

    def accept(self, visitor):
        return visitor.visit_var_point(self)

    # -- equation former ---------------------------------------------------

    def EQUALS(self, rhs) -> "EqualsExpr":
        """Form an equation writing this point (reference ``EQUALS`` macro /
        ``operator EQUALS``, ``VarPoint.hpp:219``). The equation is
        automatically registered with the var's solution, as in the
        reference."""
        eq = EqualsExpr(self, _coerce_num(rhs))
        soln = self.var.get_solution()
        if soln is not None:
            soln._register_eq(eq)
        return eq

    def __lshift__(self, rhs) -> "EqualsExpr":
        """``lhs << rhs`` sugar for :meth:`EQUALS`."""
        return self.EQUALS(rhs)


# ---------------------------------------------------------------------------
# boolean nodes (sub-domain & step conditions)
# ---------------------------------------------------------------------------


class BoolExpr(Expr):
    """Boolean-valued expression for conditions (reference bool exprs used by
    ``IF_DOMAIN``/``IF_STEP``)."""

    __slots__ = ()

    def __and__(self, other):
        return AndExpr(self, other)

    def __or__(self, other):
        return OrExpr(self, other)

    def __invert__(self):
        return NotExpr(self)


class CompExpr(BoolExpr):
    __slots__ = ("op", "lhs", "rhs")
    OPS = {"==", "!=", "<", "<=", ">", ">="}

    def __init__(self, op: str, lhs: NumExpr, rhs: NumExpr):
        if op not in self.OPS:
            raise YaskException(f"bad comparison op {op}")
        object.__setattr__(self, "op", op)
        object.__setattr__(self, "lhs", _coerce_num(lhs))
        object.__setattr__(self, "rhs", _coerce_num(rhs))

    def _key(self):
        return (self.op, self.lhs, self.rhs)

    def get_children(self):
        return (self.lhs, self.rhs)

    def accept(self, visitor):
        return visitor.visit_comp(self)

    def __bool__(self):
        # Guard against Python `==` being used where `same()` was meant.
        raise YaskException(
            "a stencil comparison is an AST node, not a Python bool; "
            "use it as an IF_DOMAIN/IF_STEP condition")


class AndExpr(BoolExpr):
    __slots__ = ("lhs", "rhs")

    def __init__(self, lhs: BoolExpr, rhs: BoolExpr):
        object.__setattr__(self, "lhs", lhs)
        object.__setattr__(self, "rhs", rhs)

    def _key(self):
        return (self.lhs, self.rhs)

    def get_children(self):
        return (self.lhs, self.rhs)

    def accept(self, visitor):
        return visitor.visit_and(self)


class OrExpr(BoolExpr):
    __slots__ = ("lhs", "rhs")

    def __init__(self, lhs: BoolExpr, rhs: BoolExpr):
        object.__setattr__(self, "lhs", lhs)
        object.__setattr__(self, "rhs", rhs)

    def _key(self):
        return (self.lhs, self.rhs)

    def get_children(self):
        return (self.lhs, self.rhs)

    def accept(self, visitor):
        return visitor.visit_or(self)


class NotExpr(BoolExpr):
    __slots__ = ("arg",)

    def __init__(self, arg: BoolExpr):
        object.__setattr__(self, "arg", arg)

    def _key(self):
        return (self.arg,)

    def get_children(self):
        return (self.arg,)

    def accept(self, visitor):
        return visitor.visit_not(self)


# ---------------------------------------------------------------------------
# equations
# ---------------------------------------------------------------------------


class EqualsExpr(Expr):
    """An equation: ``lhs_point EQUALS rhs [IF_DOMAIN cond] [IF_STEP cond]``
    (reference ``EqualsExpr``, ``VarPoint.hpp:219``)."""

    __slots__ = ("lhs", "rhs", "cond", "step_cond")

    def __init__(self, lhs: VarPoint, rhs: NumExpr,
                 cond: Optional[BoolExpr] = None,
                 step_cond: Optional[BoolExpr] = None):
        if not isinstance(lhs, VarPoint):
            raise YaskException("LHS of EQUALS must be a var access point")
        object.__setattr__(self, "lhs", lhs)
        object.__setattr__(self, "rhs", _coerce_num(rhs))
        object.__setattr__(self, "cond", cond)
        object.__setattr__(self, "step_cond", step_cond)

    def get_lhs(self) -> VarPoint:
        return self.lhs

    def get_rhs(self) -> NumExpr:
        return self.rhs

    def get_cond(self) -> Optional[BoolExpr]:
        return self.cond

    def set_cond(self, cond: Optional[BoolExpr]) -> None:
        """``yc_equation_node::set_cond`` (mutating form of IF_DOMAIN).
        An explicit ``None`` REMOVES the condition (reference
        ``yc_node_api.hpp:207``: nullptr clears)."""
        self._replace(cond=cond)

    def set_step_cond(self, cond: Optional[BoolExpr]) -> None:
        """Like :meth:`set_cond` for the step condition; ``None``
        removes it."""
        self._replace(step_cond=cond)

    def IF_DOMAIN(self, cond: BoolExpr) -> "EqualsExpr":
        """Attach a sub-domain condition (reference ``IF_DOMAIN``). Mutates
        registration in place by replacing this eq in the solution."""
        return self._replace(cond=cond)

    def IF_STEP(self, cond: BoolExpr) -> "EqualsExpr":
        """Attach a step condition (reference ``IF_STEP``)."""
        return self._replace(step_cond=cond)

    _KEEP = object()  # sentinel: "leave this condition unchanged"

    def _replace(self, cond=_KEEP, step_cond=_KEEP) -> "EqualsExpr":
        new = EqualsExpr(self.lhs, self.rhs,
                         self.cond if cond is EqualsExpr._KEEP else cond,
                         self.step_cond if step_cond is EqualsExpr._KEEP
                         else step_cond)
        soln = self.lhs.var.get_solution()
        if soln is not None:
            soln._replace_eq(self, new)
        return new

    def _key(self):
        return (self.lhs, self.rhs, self.cond, self.step_cond)

    def get_children(self):
        out = [self.lhs, self.rhs]
        if self.cond is not None:
            out.append(self.cond)
        if self.step_cond is not None:
            out.append(self.step_cond)
        return tuple(out)

    def accept(self, visitor):
        return visitor.visit_equals(self)


# ---------------------------------------------------------------------------
# visitors
# ---------------------------------------------------------------------------


class ExprVisitor:
    """Base visitor; default behavior visits children (reference
    ``ExprVisitor``, ``src/compiler/lib/Visitor.hpp``)."""

    def _visit_children(self, node: Expr):
        res = None
        for c in node.get_children():
            res = c.accept(self)
        return res

    def visit_const(self, node: ConstExpr):
        return None

    def visit_index(self, node: IndexExpr):
        return None

    def visit_first_index(self, node: FirstIndexExpr):
        return None

    def visit_last_index(self, node: LastIndexExpr):
        return None

    def visit_neg(self, node: NegExpr):
        return self._visit_children(node)

    def visit_add(self, node: AddExpr):
        return self._visit_children(node)

    def visit_mult(self, node: MultExpr):
        return self._visit_children(node)

    def visit_sub(self, node: SubExpr):
        return self._visit_children(node)

    def visit_div(self, node: DivExpr):
        return self._visit_children(node)

    def visit_mod(self, node: ModExpr):
        return self._visit_children(node)

    def visit_func(self, node: FuncExpr):
        return self._visit_children(node)

    def visit_var_point(self, node: VarPoint):
        return None

    def visit_comp(self, node: CompExpr):
        return self._visit_children(node)

    def visit_and(self, node: AndExpr):
        return self._visit_children(node)

    def visit_or(self, node: OrExpr):
        return self._visit_children(node)

    def visit_not(self, node: NotExpr):
        return self._visit_children(node)

    def visit_equals(self, node: EqualsExpr):
        return self._visit_children(node)


class PointVisitor(ExprVisitor):
    """Collects all var access points in an expression tree (used throughout
    analysis; reference's ``PointVisitor`` in ``Eqs.cpp``)."""

    def __init__(self):
        self.points: List[VarPoint] = []

    def visit_var_point(self, node: VarPoint):
        self.points.append(node)


class CounterVisitor(ExprVisitor):
    """Counts ops and points for FLOP/memory estimates (reference
    ``CounterVisitor``, ``ExprUtils.hpp``). ``sincos_args`` holds the
    structural keys of arguments whose sin AND cos both occur — the
    pair is charged one transcendental (reference ``PairingVisitor``,
    ``ExprUtils.hpp:137``; the cos half rides the sin visit)."""

    def __init__(self, sincos_args=None):
        self.num_ops = 0
        self.num_reads = 0
        self.num_writes = 0
        self.num_paired = 0
        self._sincos = sincos_args or set()

    def visit_neg(self, node):
        self.num_ops += 1
        return self._visit_children(node)

    def visit_add(self, node):
        self.num_ops += len(node.args) - 1
        return self._visit_children(node)

    def visit_mult(self, node):
        self.num_ops += len(node.args) - 1
        return self._visit_children(node)

    def visit_sub(self, node):
        self.num_ops += 1
        return self._visit_children(node)

    def visit_div(self, node):
        self.num_ops += 1
        return self._visit_children(node)

    def visit_mod(self, node):
        self.num_ops += 1
        return self._visit_children(node)

    def visit_func(self, node):
        if node.name == "cos" and node.args[0].skey() in self._sincos:
            self.num_paired += 1   # charged on the paired sin visit
        else:
            self.num_ops += 1
        return self._visit_children(node)

    def visit_var_point(self, node):
        self.num_reads += 1

    def visit_equals(self, node):
        self.num_writes += 1
        node.rhs.accept(self)
        if node.cond is not None:
            node.cond.accept(self)
        if node.step_cond is not None:
            node.step_cond.accept(self)


def count_points(expr: Expr) -> List[VarPoint]:
    v = PointVisitor()
    expr.accept(v)
    return v.points


def uses_misc_index(*exprs) -> bool:
    """True when any expression reads a MISC index as a value (its value
    is the equation's pinned LHS misc index — constant per equation, so
    eval memos must not be shared across equations)."""
    class _MV(ExprVisitor):
        found = False

        def visit_index(self, node):
            if node.type == IndexType.MISC:
                self.found = True

    v = _MV()
    for e in exprs:
        if e is not None:
            e.accept(v)
    return v.found


def used_domain_dims(*exprs) -> set:
    """Names of domain dims an expression's VALUE can vary along: via
    domain-index values or var-point reads (a read varies along every
    domain dim of its var).  ``first/last_domain_index`` are run-time
    constants and do not count."""
    names: set = set()

    class _DV(ExprVisitor):
        def visit_index(self, node):
            if node.type == IndexType.DOMAIN:
                names.add(node.name)

        def visit_var_point(self, node):
            names.update(node.get_var().domain_dim_names())

    v = _DV()
    for e in exprs:
        if e is not None:
            e.accept(v)
    return names


def paired_func_eval(ops_func, e: "FuncExpr", args, memo, sincos_args):
    """Evaluate a FuncExpr with sin/cos pairing: when the argument's sin
    AND cos both occur in the solution (``SolutionAnalysis.sincos_args``,
    reference ``PairingVisitor`` ``ExprUtils.hpp:137``), the partner is
    materialized under its own CSE key in this same visit. THE single
    definition — both the XLA and Pallas eval dispatchers call this, so
    pairing semantics cannot drift between backends."""
    r = ops_func(e.name, args)
    if e.name in ("sin", "cos") and e.args[0].skey() in sincos_args:
        partner = "cos" if e.name == "sin" else "sin"
        pk = FuncExpr(partner, e.args).skey()
        if pk not in memo:
            memo[pk] = ops_func(partner, args)
    return r
