"""Compiler-side solution: the ``yc_solution`` / ``yc_factory`` API.

Counterpart of the reference's ``yc_solution``
(``include/yask_compiler_api.hpp:409-575``, impl
``src/compiler/lib/Solution.cpp``): owns indices, vars, and equations; runs
the analysis pipeline (``analyze_solution``, ``Solution.cpp:127-160``); and
"outputs" the solution for a target. Where the reference emits C++ source
text per target (``Solution.cpp:241-259``), the TPU targets here produce a
:class:`~yask_tpu.compiler.lowering.CompiledSolution` executing as JAX/XLA —
plus the same debug text formats (``pseudo``, ``dot``) for inspection.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from yask_tpu.utils.exceptions import YaskException
from yask_tpu.utils.idx_tuple import IdxTuple
from yask_tpu.compiler.expr import (
    BoolExpr,
    EqualsExpr,
    IndexExpr,
    IndexType,
    NumExpr,
    VarPoint,
    _coerce_num,
)
from yask_tpu.compiler.var import Var


#: Supported lowering/output targets. The first group are TPU lowerings; the
#: second group are debug text formats mirroring the reference's
#: pseudo/dot printers (``Solution.cpp:241-259``).
TPU_TARGETS = ("tpu", "jnp", "pallas")
TEXT_TARGETS = ("pseudo", "pseudo-long", "dot", "dot-lite", "povray",
                "py-api")
ALL_TARGETS = TPU_TARGETS + TEXT_TARGETS


class CompilerSettings:
    """Compiler knobs (reference ``CompilerSettings``,
    ``src/compiler/lib/Settings.hpp:39-75``). Vectorization/prefetch options
    become tile-planning hints for the Pallas backend; options that have no
    TPU meaning are accepted and recorded for API parity."""

    def __init__(self):
        self.target: str = "tpu"
        self.elem_bytes: int = 4            # -elem-bytes {4|8}
        self.fold: IdxTuple = IdxTuple()    # -fold x=8,y=128 style tile hints
        self.cluster: IdxTuple = IdxTuple()  # accepted; unused on TPU
        self.do_cse: bool = True            # -[no]-cse
        self.do_pairs: bool = True          # -[no]-pair-funcs (sincos etc.)
        self.max_expr_size: int = 0         # accepted; XLA does its own CSE
        self.step_alloc: int = 0            # -step-alloc override (0 = auto)
        self.min_buffer_len: int = 0
        self.bundle_scratch: bool = True


class yc_solution:
    """A stencil solution being built & compiled (``yc_solution``)."""

    def __init__(self, name: str):
        self._name = name
        self._desc = ""
        self._settings = CompilerSettings()
        self._indices: Dict[str, IndexExpr] = {}
        self._vars: Dict[str, Var] = {}
        self._eqs: List[EqualsExpr] = []
        self._analysis = None  # cached SolutionAnalysis
        # dependency-checker toggle (yc_solution::set_dependency_checker_enabled,
        # yask_compiler_api.hpp:575): when disabled, declared step-race eqs
        # are allowed through.
        self._dep_check = True

    # ---- identity & settings --------------------------------------------

    def get_name(self) -> str:
        return self._name

    def set_name(self, name: str) -> None:
        self._name = name

    def get_description(self) -> str:
        return self._desc or self._name

    def set_description(self, d: str) -> None:
        self._desc = d

    def get_settings(self) -> CompilerSettings:
        return self._settings

    def set_target(self, target: str) -> None:
        if target not in ALL_TARGETS:
            raise YaskException(
                f"unknown target '{target}'; expected one of {ALL_TARGETS}")
        self._settings.target = target

    def get_target(self) -> str:
        return self._settings.target

    def is_target_set(self) -> bool:
        return True

    def set_element_bytes(self, n: int) -> None:
        if n not in (2, 4, 8):
            raise YaskException("element bytes must be 2, 4, or 8")
        self._settings.elem_bytes = n

    def get_element_bytes(self) -> int:
        return self._settings.elem_bytes

    def set_fold_len(self, dim, length: int) -> None:
        """Vector-fold hint: on TPU this biases which dims map onto the
        (sublane, lane) register tile in the Pallas tile planner (SURVEY
        'fold↔(8,128)' note) rather than choosing a SIMD layout."""
        name = dim.name if isinstance(dim, IndexExpr) else str(dim)
        if self._settings.fold.has_dim(name):
            self._settings.fold[name] = length
        else:
            self._settings.fold.add_dim_back(name, length)

    def clear_folding(self) -> None:
        self._settings.fold = IdxTuple()

    def set_cluster_mult(self, dim, mult: int) -> None:
        """Accepted for API parity; XLA unrolling replaces clustering."""
        name = dim.name if isinstance(dim, IndexExpr) else str(dim)
        if self._settings.cluster.has_dim(name):
            self._settings.cluster[name] = mult
        else:
            self._settings.cluster.add_dim_back(name, mult)

    def clear_clustering(self) -> None:
        self._settings.cluster = IdxTuple()

    def set_dependency_checker_enabled(self, enable: bool) -> None:
        self._dep_check = enable

    def is_dependency_checker_enabled(self) -> bool:
        return self._dep_check

    # ---- indices ---------------------------------------------------------

    def _new_index(self, name: str, t: IndexType) -> IndexExpr:
        if name in self._indices:
            existing = self._indices[name]
            if existing.type != t:
                raise YaskException(
                    f"index '{name}' already exists with type "
                    f"{existing.type.value}")
            return existing
        idx = IndexExpr(name, t)
        self._indices[name] = idx
        return idx

    def new_step_index(self, name: str) -> IndexExpr:
        return self._new_index(name, IndexType.STEP)

    def new_domain_index(self, name: str) -> IndexExpr:
        return self._new_index(name, IndexType.DOMAIN)

    def new_misc_index(self, name: str) -> IndexExpr:
        return self._new_index(name, IndexType.MISC)

    def get_indices(self) -> Dict[str, IndexExpr]:
        return dict(self._indices)

    def step_dim_name(self) -> Optional[str]:
        for idx in self._indices.values():
            if idx.type == IndexType.STEP:
                return idx.name
        return None

    def domain_dim_names(self) -> List[str]:
        # Explicit order when set_domain_dims was called; else ordered
        # by first var using them (reference orders by declaration).
        if getattr(self, "_explicit_domain_dims", None):
            return list(self._explicit_domain_dims)
        out: List[str] = []
        for v in self._vars.values():
            for d in v.get_dims():
                if d.type == IndexType.DOMAIN and d.name not in out:
                    out.append(d.name)
        for idx in self._indices.values():
            if idx.type == IndexType.DOMAIN and idx.name not in out:
                out.append(idx.name)
        return out

    def set_domain_dims(self, dims: Sequence[IndexExpr]) -> None:
        """Explicitly declare and ORDER the domain dims
        (``yask_compiler_api.hpp:538``): the order drives memory layout
        (the last one becomes the lane axis), looping, and rank
        layout — and covers solutions where no var carries every dim."""
        names = []
        for d in dims:
            if not isinstance(d, IndexExpr) or d.type != IndexType.DOMAIN:
                raise YaskException(
                    "set_domain_dims takes domain index nodes")
            self._indices.setdefault(d.name, d)
            names.append(d.name)
        self._explicit_domain_dims = names
        self._analysis = None

    def set_step_dim(self, dim: IndexExpr) -> None:
        """Explicitly declare the step dim (``yask_compiler_api.hpp``)."""
        if not isinstance(dim, IndexExpr) or dim.type != IndexType.STEP:
            raise YaskException("set_step_dim takes a step index node")
        self._indices.setdefault(dim.name, dim)

    # ---- vars ------------------------------------------------------------

    def new_var(self, name: str, dims: Sequence[IndexExpr]) -> Var:
        """Create an N-D var (``yc_solution::new_var``)."""
        if name in self._vars:
            raise YaskException(f"duplicate var '{name}'")
        for d in dims:
            if isinstance(d, IndexExpr):
                self._indices.setdefault(d.name, d)
        v = Var(name, dims, solution=self)
        self._vars[name] = v
        return v

    def new_scratch_var(self, name: str, dims: Sequence[IndexExpr]) -> Var:
        """Create a scratch var: storage-only-within-a-step temporary
        (``yc_solution::new_scratch_var``; reference scratch semantics in
        ``Eqs.cpp`` scratch dep chains)."""
        if name in self._vars:
            raise YaskException(f"duplicate var '{name}'")
        v = Var(name, dims, solution=self, is_scratch=True)
        self._vars[name] = v
        return v

    def get_var(self, name: str) -> Var:
        if name not in self._vars:
            raise YaskException(f"no var named '{name}'")
        return self._vars[name]

    def get_vars(self) -> List[Var]:
        return list(self._vars.values())

    def get_num_vars(self) -> int:
        return len(self._vars)

    # ---- equations -------------------------------------------------------

    def _register_eq(self, eq: EqualsExpr) -> None:
        self._eqs.append(eq)
        self._analysis = None

    def _replace_eq(self, old: EqualsExpr, new: EqualsExpr) -> None:
        for i, e in enumerate(self._eqs):
            if e is old:
                self._eqs[i] = new
                self._analysis = None
                return
        # not registered (eq built via node factory w/o auto-registration)
        self._eqs.append(new)
        self._analysis = None

    def add_eq(self, lhs: VarPoint, rhs, cond: Optional[BoolExpr] = None,
               step_cond: Optional[BoolExpr] = None) -> EqualsExpr:
        """Explicitly add an equation (node-factory style)."""
        eq = EqualsExpr(lhs, _coerce_num(rhs), cond, step_cond)
        self._register_eq(eq)
        return eq

    def get_equations(self) -> List[EqualsExpr]:
        return list(self._eqs)

    def get_num_equations(self) -> int:
        return len(self._eqs)

    def clear_equations(self) -> None:
        self._eqs.clear()
        self._analysis = None

    # ---- v2 "grid" aliases + advanced hooks (yask_compiler_api.hpp) --

    new_grid = new_var
    new_scratch_grid = new_scratch_var
    get_grid = get_var
    get_grids = get_vars
    get_num_grids = get_num_vars

    def add_flow_dependency(self, from_eq: EqualsExpr,
                            to_eq: EqualsExpr) -> None:
        """Declare that ``from_eq`` evaluates before ``to_eq``
        (``yask_compiler_api.hpp:657``) — the manual channel when the
        automatic dependency checker is disabled; edges merge into the
        analysis dep graph either way."""
        if not hasattr(self, "_manual_deps"):
            self._manual_deps = []
        self._manual_deps.append((from_eq, to_eq))
        self._analysis = None

    def clear_dependencies(self) -> None:
        """Remove edges added via ``add_flow_dependency``."""
        self._manual_deps = []
        self._analysis = None

    def call_after_new_solution(self, code) -> None:
        """Register code to run right after the KERNEL solution is
        constructed (``yask_compiler_api.hpp:515``).  The reference
        injects a C++ block; here pass a callable taking the kernel
        solution, or a Python source string executed with
        ``kernel_soln`` bound."""
        if not hasattr(self, "_after_new_solution"):
            self._after_new_solution = []
        self._after_new_solution.append(code)

    def call_before_output(self, hook) -> None:
        """Register ``hook(soln, output)`` to run during
        ``output_solution`` after optimization, before writing
        (``yask_compiler_api.hpp:486``)."""
        if not hasattr(self, "_before_output"):
            self._before_output = []
        self._before_output.append(hook)

    # ---- analysis & output ----------------------------------------------

    def analyze(self):
        """Run the analysis pipeline and cache the result (counterpart of
        ``Solution::analyze_solution``, ``Solution.cpp:127-160``)."""
        if self._analysis is None:
            from yask_tpu.compiler.analysis import SolutionAnalysis
            self._analysis = SolutionAnalysis(self)
        return self._analysis

    def compile(self, **kwargs):
        """Lower to an executable :class:`CompiledSolution` for the current
        TPU target (the runtime's entry point into the compiler)."""
        from yask_tpu.compiler.lowering import CompiledSolution
        return CompiledSolution(self, self.analyze(), **kwargs)

    def output_solution(self, output) -> None:
        """Write the solution in the selected target format (counterpart of
        ``yc_solution::output_solution``, ``Solution.cpp:211``). For text
        targets this writes pseudo/dot text; for TPU targets it writes a
        self-contained Python module that rebuilds and compiles the solution
        (the analog of the reference emitting a C++ header)."""
        from yask_tpu.compiler import printers
        target = self._settings.target
        self.analyze()
        for hook in getattr(self, "_before_output", ()):
            hook(self, output)
        if target in ("pseudo", "pseudo-long"):
            text = printers.print_pseudo(self, long=target == "pseudo-long")
        elif target in ("dot", "dot-lite"):
            text = printers.print_dot(self, lite=target == "dot-lite")
        elif target == "povray":
            text = printers.print_povray(self)
        elif target == "py-api" or target in TPU_TARGETS:
            text = printers.print_py_module(self)
        else:  # pragma: no cover
            raise YaskException(f"unknown target '{target}'")
        output.write(text)

    # ---- CLI parity ------------------------------------------------------

    def apply_command_line_options(self, args) -> List[str]:
        """Apply compiler options from a command line
        (``yc_solution::apply_command_line_options``)."""
        if isinstance(args, str):
            args = args.split()
        from yask_tpu.utils.cli import CommandLineParser

        class _Tgt:
            pass

        tgt = _Tgt()
        tgt.target = self._settings.target
        tgt.elem_bytes = self._settings.elem_bytes
        tgt.fold = ""
        tgt.cse = self._settings.do_cse
        p = CommandLineParser()
        p.add_string_option("target", "Lowering target.", tgt, "target")
        p.add_int_option("elem-bytes", "FP element size.", tgt, "elem_bytes")
        p.add_string_option("fold", "Tile-shape hint, e.g. 'x=8,y=128'.",
                            tgt, "fold")
        p.add_bool_option("cse", "Common-subexpr elimination.", tgt, "cse")
        rest = p.parse_args(list(args))
        self.set_target(tgt.target)
        self.set_element_bytes(tgt.elem_bytes)
        self._settings.do_cse = tgt.cse
        if tgt.fold:
            from yask_tpu.utils.idx_tuple import parse_dim_val_str
            self._settings.fold = parse_dim_val_str(tgt.fold)
        return rest

    def get_command_line_help(self) -> str:
        return ("-target <tpu|jnp|pallas|pseudo|pseudo-long|dot|dot-lite|"
                "py-api>\n-elem-bytes <2|4|8>\n-fold <dim=val,...>\n"
                "-[no-]cse\n")

    def __repr__(self):
        return (f"<yc_solution '{self._name}': {len(self._vars)} vars, "
                f"{len(self._eqs)} eqs>")


class yc_factory:
    """Factory mirroring ``yc_factory`` (``yask_compiler_api.hpp:112``)."""

    def new_solution(self, name: str) -> yc_solution:
        return yc_solution(name)

    def get_version_string(self) -> str:
        from yask_tpu import __version__
        return __version__
