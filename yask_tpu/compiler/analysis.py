"""Equation analysis: validity, dependencies, parts, stages, halos.

TPU-native counterpart of the reference's analysis pipeline
(``src/compiler/lib/Eqs.cpp``):

* ``analyze_eqs`` (:364): LHS form validation (step ``t±1`` on non-scratch
  vars, plain domain indices, constant misc indices), step-direction
  consistency, and eq↔eq dependency discovery with cycle detection;
* ``make_parts`` (:1170): grouping equations into *parts* — same
  domain/step conditions, no unresolved intra-part deps;
* ``make_stages`` (:1523): grouping parts into sequential *stages* (halo
  exchange happens between stages in the runtime);
* ``calc_halos`` (:1614): per-var halo growth from read offsets, including
  write-halo propagation through scratch-var chains
  (``find_scratch_write_halos``, ``setup.cpp:1044``);
* ``calc_lifespans`` (:1912): #step slots each var needs.

The result object is consumed by ``yask_tpu.compiler.lowering`` and by the
kernel runtime for allocation geometry.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from yask_tpu.utils.exceptions import YaskException
from yask_tpu.compiler.expr import (
    CounterVisitor,
    EqualsExpr,
    IndexType,
    PointVisitor,
    VarPoint,
)
from yask_tpu.compiler.var import Var


class Part:
    """A group of equations with identical conditions and no internal
    dependencies (reference 'part'/'bundle')."""

    def __init__(self, name: str, cond, step_cond, is_scratch: bool):
        self.name = name
        self.eqs: List[EqualsExpr] = []
        self.cond = cond                # BoolExpr | None (domain condition)
        self.step_cond = step_cond      # BoolExpr | None
        self.is_scratch = is_scratch    # all eqs write scratch vars
        self.deps: Set["Part"] = set()  # parts that must run before this one
        self.stage_index: int = -1

    def lhs_vars(self) -> List[Var]:
        out = []
        for eq in self.eqs:
            v = eq.lhs.get_var()
            if v not in out:
                out.append(v)
        return out

    def __repr__(self):
        return f"<Part {self.name}: {len(self.eqs)} eq(s)>"


class Stage:
    """A sequence point: all parts in a stage can be evaluated with halos
    exchanged once before it (reference 'stage', ``Eqs.cpp:1523``)."""

    def __init__(self, index: int):
        self.index = index
        self.parts: List[Part] = []

    def __repr__(self):
        return f"<Stage {self.index}: {[p.name for p in self.parts]}>"


def _cond_key(cond) -> tuple:
    return cond.skey() if cond is not None else ()


def missing_dim_race(eq: EqualsExpr, domain_dims: Sequence[str]) -> Set[str]:
    """The dims along which ``eq``'s RHS/conditions VARY while its LHS
    var lacks them — each such dim is an intra-step race: every point of
    the missing extent would demand a different value for the single
    stored slab.  Returns the racy dim set (empty = fine).

    THE single definition of the missing-dim race rule:
    ``_validate_and_scan`` raises on it during analysis, and the static
    checker (``yask_tpu.checker.races``) reports it as a non-raising
    diagnostic over un-analyzed solutions."""
    var = eq.lhs.get_var()
    lhs_dd = set(var.domain_dim_names())
    missing = [d for d in domain_dims if d not in lhs_dd]
    if not missing:
        return set()
    from yask_tpu.compiler.expr import used_domain_dims
    return used_domain_dims(eq.rhs, eq.cond, eq.step_cond) & set(missing)


class SolutionAnalysis:
    """Full analysis result for one solution (the pipeline of
    ``Solution::analyze_solution``, ``Solution.cpp:127-160``)."""

    def __init__(self, soln):
        self.soln = soln
        eqs: List[EqualsExpr] = soln.get_equations()
        # Zero equations is legal (reference test_empty/test_empty_2d,
        # ``TestStencils.cpp:999-1035``): the solution prepares and steps
        # as a no-op, so every pass below just sees empty collections.
        self.eqs = eqs
        self.step_dim: Optional[str] = soln.step_dim_name()
        self.domain_dims: List[str] = soln.domain_dim_names()
        self.step_dir: int = 0

        self._validate_and_scan()
        self._find_deps()
        self._make_parts()
        self._make_stages()
        self._calc_scratch_halos()
        self._count()

    # ------------------------------------------------------------------
    # validation & var stats (analyze_eqs LHS rules, Eqs.cpp:364-470)
    # ------------------------------------------------------------------

    def _validate_and_scan(self) -> None:
        soln = self.soln
        for eq in self.eqs:
            lhs = eq.lhs
            var = lhs.get_var()
            var.is_written = True
            # LHS domain indices must be plain (offset 0).
            for d, ofs in lhs.domain_offsets().items():
                if ofs != 0:
                    raise YaskException(
                        f"LHS of '{eq.format_simple()}' uses domain offset "
                        f"{d}{ofs:+d}; LHS domain indices must be plain "
                        "(reference rule, Eqs.cpp:364)")
            # LHS step index must be ±1 and consistent across equations.
            if not var.is_scratch():
                so = lhs.step_offset()
                if so is None:
                    raise YaskException(
                        f"non-scratch var '{var.get_name()}' written without "
                        "a step index")
                if so not in (1, -1):
                    raise YaskException(
                        f"LHS step offset must be +1 or -1, got {so} in "
                        f"'{eq.format_simple()}'")
                if self.step_dir == 0:
                    self.step_dir = so
                elif self.step_dir != so:
                    raise YaskException(
                        "all equations must step in the same direction "
                        f"(got both {self.step_dir:+d} and {so:+d})")
                var.step_offsets_used.append(so)
            # LHS misc indices: record.
            for d, val in lhs.misc_vals().items():
                var.update_misc_range(d, val)

            # A write to a var lacking some solution domain dims must
            # not read anything that varies along those dims — an
            # intra-step race.  (The reference cannot even express
            # this: its loop nest is the LHS var's dims,
            # Eqs.cpp:364-470.)  All lowering backends then agree on
            # collapsing the constant extent.  missing_dim_race is the
            # single definition, shared with the static checker.
            varying = missing_dim_race(eq, self.domain_dims)
            if varying:
                raise YaskException(
                    f"'{eq.format_simple()}' writes var "
                    f"'{var.get_name()}' (no dim "
                    f"{sorted(varying)}) but its RHS/condition "
                    f"varies along {sorted(varying)} — an "
                    "intra-step race")

            # Scan RHS (and conditions) reads: halos, misc ranges, steps.
            pv = PointVisitor()
            eq.rhs.accept(pv)
            if eq.cond is not None:
                eq.cond.accept(pv)
            if eq.step_cond is not None:
                eq.step_cond.accept(pv)
            for p in pv.points:
                rvar = p.get_var()
                rvar.is_read = True
                spatial = 0
                for d, ofs in p.domain_offsets().items():
                    rvar.update_halo(d, ofs)
                    spatial = max(spatial, abs(ofs))
                for d, val in p.misc_vals().items():
                    rvar.update_misc_range(d, val)
                so = p.step_offset()
                if so is not None:
                    rvar.step_offsets_used.append(so)
                    # Max spatial reach per step offset — drives the
                    # write-back ring-slot optimization (the reference
                    # reduces step allocation when the extreme step offset
                    # carries no halo, Var.cpp write-back analysis).
                    rvar.step_read_halo[so] = max(
                        rvar.step_read_halo.get(so, 0), spatial)
        if self.step_dir == 0:
            self.step_dir = 1

    # ------------------------------------------------------------------
    # dependency graph (find_all_deps, Eqs.hpp:252)
    # ------------------------------------------------------------------

    def _reads_of(self, eq: EqualsExpr) -> List[VarPoint]:
        pv = PointVisitor()
        eq.rhs.accept(pv)
        if eq.cond is not None:
            eq.cond.accept(pv)
        if eq.step_cond is not None:
            eq.step_cond.accept(pv)
        return pv.points

    def _find_deps(self) -> None:
        """eq j depends on eq i when j reads a value i writes *within the
        same step evaluation*: a non-scratch var at the written step offset,
        or any scratch var (scratch values live only within a step)."""
        eqs = self.eqs
        # writers: var name -> list of eq indices writing it this step
        writers: Dict[str, List[int]] = {}
        for i, eq in enumerate(eqs):
            writers.setdefault(eq.lhs.var_name(), []).append(i)

        n = len(eqs)
        self.eq_deps: List[Set[int]] = [set() for _ in range(n)]
        for j, eq in enumerate(eqs):
            for p in self._reads_of(eq):
                vname = p.var_name()
                if vname not in writers:
                    continue
                rvar = p.get_var()
                if rvar.is_scratch():
                    for i in writers[vname]:
                        if i != j:
                            self.eq_deps[j].add(i)
                else:
                    so = p.step_offset()
                    if so is not None and so == self.step_dir:
                        # Reading the value being computed this step.
                        for i in writers[vname]:
                            if i != j:
                                self.eq_deps[j].add(i)
                        if j in writers[vname] and len(writers[vname]) == 1 \
                                and self.soln.is_dependency_checker_enabled():
                            raise YaskException(
                                f"equation '{eq.format_simple()}' reads the "
                                "point it is writing in the same step "
                                "(intra-step race; reference rejects this, "
                                "Eqs.cpp:364-470)")

        # Write-after-write: multiple eqs writing the same var this step
        # (e.g. a bulk update plus IF_DOMAIN boundary overrides) execute in
        # registration order — later writers depend on earlier ones, giving
        # deterministic last-write-wins semantics.
        for vname, ws in writers.items():
            for a, b in zip(ws, ws[1:]):
                self.eq_deps[b].add(a)

        # User-declared edges (yc_solution::add_flow_dependency,
        # yask_compiler_api.hpp:657): 'from' DEPENDS ON 'to' — i.e.
        # 'to' evaluates first; the primary channel when the automatic
        # checker is disabled.
        for f_eq, t_eq in getattr(self.soln, "_manual_deps", ()):
            fi = ti = None
            for i, eq in enumerate(eqs):
                if eq.same(f_eq):
                    fi = i
                if eq.same(t_eq):
                    ti = i
            if fi is None or ti is None:
                raise YaskException(
                    "add_flow_dependency references an equation not in "
                    "this solution")
            self.eq_deps[fi].add(ti)

        # Cycle detection via DFS (reference DFS path visitors, Eqs.hpp).
        color = [0] * n  # 0=white 1=grey 2=black
        order: List[int] = []

        def dfs(u: int, stack: List[int]):
            color[u] = 1
            stack.append(u)
            for v in self.eq_deps[u]:
                if color[v] == 1:
                    cyc = " -> ".join(
                        eqs[k].lhs.format_simple()
                        for k in stack[stack.index(v):] + [v])
                    raise YaskException(
                        f"circular dependency among equations: {cyc}")
                if color[v] == 0:
                    dfs(v, stack)
            stack.pop()
            color[u] = 2
            order.append(u)

        for u in range(n):
            if color[u] == 0:
                dfs(u, [])
        self.eq_topo_order = order  # deps before dependents

    # ------------------------------------------------------------------
    # parts (make_parts, Eqs.cpp:1170)
    # ------------------------------------------------------------------

    def _make_parts(self) -> None:
        eqs = self.eqs
        parts: List[Part] = []
        eq_part: Dict[int, Part] = {}

        for idx in self.eq_topo_order:
            eq = eqs[idx]
            var = eq.lhs.get_var()
            ckey = (_cond_key(eq.cond), _cond_key(eq.step_cond),
                    var.is_scratch())
            # Earliest part this eq may join: after every part containing a
            # dependency.
            min_pos = -1
            for dep in self.eq_deps[idx]:
                dp = eq_part[dep]
                min_pos = max(min_pos, parts.index(dp))
            placed = None
            for pos in range(min_pos + 1, len(parts)):
                p = parts[pos]
                if (_cond_key(p.cond), _cond_key(p.step_cond),
                        p.is_scratch) == ckey:
                    placed = p
                    break
            if placed is None:
                placed = Part(f"part_{len(parts)}", eq.cond, eq.step_cond,
                              var.is_scratch())
                parts.append(placed)
            placed.eqs.append(eq)
            eq_part[idx] = placed

        # Part-level deps.
        for idx in range(len(eqs)):
            p = eq_part[idx]
            for dep in self.eq_deps[idx]:
                dp = eq_part[dep]
                if dp is not p:
                    p.deps.add(dp)

        self.parts = parts
        self._eq_part = eq_part

    # ------------------------------------------------------------------
    # stages (make_stages, Eqs.cpp:1523)
    # ------------------------------------------------------------------

    def _make_stages(self) -> None:
        """Assign each part a stage level = 1 + max(level of deps); scratch
        parts are pulled into the stage of their first consumer so each
        stage is self-contained (scratch chains run inside the consumer's
        stage, as in the reference's micro-block scratch evaluation,
        ``stencil_calc.cpp:40-289``)."""
        level: Dict[Part, int] = {}

        def get_level(p: Part, seen: Tuple[Part, ...] = ()) -> int:
            if p in level:
                return level[p]
            if p in seen:
                raise YaskException("circular dependency among parts")
            lv = 0
            for d in p.deps:
                lv = max(lv, get_level(d, seen + (p,)) + 1)
            level[p] = lv
            return lv

        for p in self.parts:
            get_level(p)

        # Pull scratch parts up to the min level of their consumers.
        consumers: Dict[Part, List[Part]] = {p: [] for p in self.parts}
        for p in self.parts:
            for d in p.deps:
                consumers[d].append(p)
        changed = True
        while changed:
            changed = False
            for p in self.parts:
                if p.is_scratch and consumers[p]:
                    tgt = min(level[c] for c in consumers[p])
                    if level[p] != tgt and level[p] < tgt:
                        level[p] = tgt
                        changed = True

        # Scratch levels may now exceed their consumers'; clamp: scratch part
        # runs in the stage of its earliest consumer.
        for p in self.parts:
            if p.is_scratch and consumers[p]:
                level[p] = min(level[c] for c in consumers[p])

        nlevels = max(level.values()) + 1 if level else 1
        stages = [Stage(i) for i in range(nlevels)]
        # Keep topological part order within a stage: scratch producers
        # first, then in part-creation order.
        for p in self.parts:
            p.stage_index = level[p]
        for p in sorted(self.parts,
                        key=lambda q: (level[q], not q.is_scratch,
                                       self.parts.index(q))):
            stages[level[p]].parts.append(p)
        self.stages = [s for s in stages if s.parts]
        for i, s in enumerate(self.stages):
            s.index = i
            for p in s.parts:
                p.stage_index = i

    # ------------------------------------------------------------------
    # scratch write-halo propagation (find_scratch_write_halos,
    # setup.cpp:1044; calc_halos, Eqs.cpp:1614)
    # ------------------------------------------------------------------

    def _calc_scratch_halos(self) -> None:
        """Scratch vars are evaluated over the consumer's domain *expanded*
        by the consumer's read offsets into them (write-halo); the vars the
        scratch eq reads then need their halos grown by that expansion.
        Iterate to fixpoint to handle scratch→scratch chains."""
        # write_halo[var_name][dim] = (left, right) area beyond the domain
        # over which the scratch var must be computed.
        self.scratch_write_halo: Dict[str, Dict[str, Tuple[int, int]]] = {}
        scratch_vars = [v for v in self.soln.get_vars() if v.is_scratch()]
        for v in scratch_vars:
            self.scratch_write_halo[v.get_name()] = {
                d: (0, 0) for d in v.domain_dim_names()}

        for _ in range(len(scratch_vars) + 2):
            changed = False
            # 1) write-halo of scratch var s = union over all reads of s of
            #    (reader offset extent + write-halo of reader's LHS if the
            #    reader itself writes a scratch var).
            for eq in self.eqs:
                lhs_var = eq.lhs.get_var()
                lhs_wh = self.scratch_write_halo.get(lhs_var.get_name())
                for p in self._reads_of(eq):
                    rv = p.get_var()
                    if not rv.is_scratch():
                        continue
                    wh = self.scratch_write_halo[rv.get_name()]
                    for d, ofs in p.domain_offsets().items():
                        if d not in wh:
                            continue
                        l, r = wh[d]
                        base_l = base_r = 0
                        if lhs_wh is not None and d in lhs_wh:
                            base_l, base_r = lhs_wh[d]
                        nl = max(l, base_l + max(0, -ofs))
                        nr = max(r, base_r + max(0, ofs))
                        if (nl, nr) != (l, r):
                            wh[d] = (nl, nr)
                            changed = True
            if not changed:
                break

        # 2) grow halos of vars read by scratch-writing eqs: the scratch is
        #    computed over domain+write_halo, so its inputs are read at
        #    write_halo + read offset.
        for eq in self.eqs:
            lhs_var = eq.lhs.get_var()
            if not lhs_var.is_scratch():
                continue
            wh = self.scratch_write_halo[lhs_var.get_name()]
            for p in self._reads_of(eq):
                rv = p.get_var()
                for d, ofs in p.domain_offsets().items():
                    if d not in wh:
                        continue
                    wl, wr = wh[d]
                    if d in rv.halo:
                        rv.update_halo(d, -(wl + max(0, -ofs)))
                        rv.update_halo(d, wr + max(0, ofs))

    # ------------------------------------------------------------------
    # counters (CounterVisitor, ExprUtils.hpp)
    # ------------------------------------------------------------------

    def _count(self) -> None:
        # sin/cos pairing (reference PairingVisitor, ExprUtils.hpp:137):
        # sin(x) and cos(x) on structurally identical arguments are one
        # paired evaluation — both lowering backends materialize the
        # partner under its own CSE key in the same visit, and the op
        # model charges the pair one transcendental (TTI's ti0–ti3 trig
        # chains are the motivating case).
        from yask_tpu.compiler.expr import ExprVisitor, FuncExpr

        sin_args, cos_args = set(), set()

        class _Trig(ExprVisitor):
            def visit_func(self, node: FuncExpr):
                if node.name == "sin":
                    sin_args.add(node.args[0].skey())
                elif node.name == "cos":
                    cos_args.add(node.args[0].skey())
                for a in node.args:
                    a.accept(self)

        tv = _Trig()
        for eq in self.eqs:
            eq.accept(tv)
        self.sincos_args = sin_args & cos_args

        c = CounterVisitor(sincos_args=self.sincos_args)
        for eq in self.eqs:
            eq.accept(c)
        self.counters = c

    # ------------------------------------------------------------------

    def stage_read_widths_split(self) -> List[Dict[str, Dict]]:
        """Per stage, ghost widths split by which BUFFER the read hits:
        ``"computed"`` — reads at the written step offset (this step's
        output, an earlier stage's `computed` array); ``"ring"`` — every
        other read (previous-step ring slots, read-only vars). The
        distributed refresh must exchange BOTH when a stage does both —
        a later stage can read an already-computed var's previous-step
        ring values with ghost offsets, and refreshing only the computed
        array leaves the ring slot (which the rotation carries into the
        next step) with stale shard ghosts."""
        out: List[Dict[str, Dict]] = []
        for stage in self.stages:
            kinds = {"ring": {}, "computed": {}}
            for part in stage.parts:
                for eq in part.eqs:
                    lhs_wh = self.scratch_write_halo.get(
                        eq.lhs.var_name(), {})
                    for p in self._reads_of(eq):
                        v = p.get_var()
                        if v.is_scratch():
                            continue
                        so = p.step_offset()
                        kind = "computed" if (so is not None
                                              and so == self.step_dir
                                              and v.is_written) else "ring"
                        entry = kinds[kind].setdefault(v.get_name(), {})
                        for d, ofs in p.domain_offsets().items():
                            wl, wr = lhs_wh.get(d, (0, 0))
                            l, r = entry.get(d, (0, 0))
                            entry[d] = (max(l, wl - min(ofs, 0)),
                                        max(r, wr + max(ofs, 0)))
            for kind in kinds:
                kinds[kind] = {
                    k: {d: lr for d, lr in vv.items() if lr != (0, 0)}
                    for k, vv in kinds[kind].items()}
                kinds[kind] = {k: vv for k, vv in kinds[kind].items()
                               if vv}
            out.append(kinds)
        return out

    def stage_read_widths(self) -> List[Dict[str, Dict[str, Tuple[int, int]]]]:
        """Per stage: vars (non-scratch) read with nonzero domain offsets
        and the (left, right) ghost widths needed — the UNION over both
        read kinds of :meth:`stage_read_widths_split`. Drives the Pallas
        per-stage margin accounting and the overlap split's core shrink;
        the exchange planner uses the split form."""
        out: List[Dict[str, Dict[str, Tuple[int, int]]]] = []
        for kinds in self.stage_read_widths_split():
            reads: Dict[str, Dict[str, Tuple[int, int]]] = {}
            for kind in ("ring", "computed"):
                for vname, widths in kinds[kind].items():
                    entry = reads.setdefault(vname, {})
                    for d, (l, r) in widths.items():
                        cl, cr = entry.get(d, (0, 0))
                        entry[d] = (max(cl, l), max(cr, r))
            out.append(reads)
        return out

    def read_var_names(self) -> Set[str]:
        """Names of every non-scratch var READ by any equation, at ANY
        offset — including pure same-point (zero-domain-offset) reads,
        which :meth:`stage_read_widths` deliberately omits (they need no
        ghost margin).  The Pallas skew carry must consult THIS set: a
        written var consumed only at the same point (awp's anelastic
        memory vars — ``r(t+1) = q·(r(t)+el)`` read back by the stress
        stage) still crosses sub-steps, so its slid-region left strips
        ride the inter-tile carry exactly like offset reads do."""
        out: Set[str] = set()
        for eq in self.eqs:
            for p in self._reads_of(eq):
                v = p.get_var()
                if not v.is_scratch():
                    out.add(v.get_name())
        return out

    def fused_step_radius(self) -> Dict[str, int]:
        """Per domain dim, the (symmetric) margin ONE full step consumes
        when fused in-tile: the sum over stages of each stage's max ghost
        width (same-step chains eat margin stage by stage). Both the
        Pallas kernel's shrink accounting and the runtime's pad planning
        use exactly this number."""
        out = {d: 0 for d in self.domain_dims}
        for reads in self.stage_read_widths():
            sm = {d: 0 for d in self.domain_dims}
            for vv in reads.values():
                for d, (l, r) in vv.items():
                    sm[d] = max(sm[d], l, r)
            out = {d: out[d] + sm[d] for d in self.domain_dims}
        return out

    def max_halos(self) -> Dict[str, Tuple[int, int]]:
        """Per-domain-dim max (left, right) halo over all non-scratch vars —
        what the runtime uses for pad geometry and ghost-exchange width."""
        out: Dict[str, Tuple[int, int]] = {d: (0, 0) for d in self.domain_dims}
        for v in self.soln.get_vars():
            extra: Dict[str, Tuple[int, int]] = {}
            if v.is_scratch():
                extra = self.scratch_write_halo.get(v.get_name(), {})
            for d, (l, r) in v.halo.items():
                el, er = extra.get(d, (0, 0))
                L, R = out.get(d, (0, 0))
                out[d] = (max(L, l + el), max(R, r + er))
        return out

    def summary(self) -> str:
        return (f"{len(self.eqs)} eq(s) in {len(self.parts)} part(s) over "
                f"{len(self.stages)} stage(s); step dir {self.step_dir:+d}")
