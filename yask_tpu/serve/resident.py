"""Device-resident multi-step serving executable.

The BatchScheduler pays per-REQUEST dispatch overhead by design: every
request takes the queue lock, waits the batching window, extracts a
rollback snapshot, runs, then materializes + host-transfers its
outputs before the next request touches the device.  That is the right
shape for independent tenants with SLOs — and pure overhead for the
bulk pattern the RTM drivers actually have: ONE caller holding a work
list of (session, steps) items that only needs every answer at the
end.

:class:`ResidentExecutor` is the push-memory idea applied to serving:
state STAYS device-resident across the whole queue.  Items are
dispatched back-to-back under one device-lock hold — no batching
window, no per-item snapshot, no per-item host sync — then ONE
``block_until_ready`` sweep retires the queue and each touched
session's outputs are extracted once.  Responses are bit-identical to
solo runs BY CONSTRUCTION: the executor calls the same
``run_solution`` on the same per-session RunStates the scheduler path
uses; only synchronization timing differs, and jax's dispatch order is
program order per buffer.

The scheduler's one-worker-owns-the-device invariant makes this a
drop-in opt-in: :meth:`BatchScheduler.run_resident` delegates here
under the SAME ``_dev_lock``, so resident queues serialize against
in-flight request traffic instead of racing it.

Fault surface: the queue entry is a ``fault_point("serve.resident")``,
every item's run rides ``guarded_call`` at the same site (relay-down /
device-hang retry + classification), and extracted outputs pass
``maybe_corrupt("serve.resident")`` — the A/B session stage withholds
corrupt arms from its bit-equality gate like every other corruptible
site.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

from yask_tpu.utils.exceptions import YaskException

#: one work item: (session id, first step, last step)
WorkItem = Tuple[str, int, int]


class ResidentExecutor:
    """Drain a queue of (session, first, last) work items with
    device-resident state and a single end-of-queue sync.

    ``dev_lock`` is the scheduler's ``_dev_lock`` when attached to a
    live server (all context/state access serializes with request
    traffic); standalone use (bench A/B, tests) may pass None for a
    private lock.
    """

    def __init__(self, registry, journal=None, dev_lock=None):
        import threading
        self._registry = registry
        self._journal = journal
        self._dev_lock = dev_lock or threading.RLock()
        self._next_qid = 0

    # ------------------------------------------------------------------

    def _record(self, qid: str, sid: str, event: str, **detail) -> None:
        if self._journal is not None:
            self._journal.record(qid, sid, event, **detail)

    def run_queue(self, items: Sequence[WorkItem],
                  outputs: Sequence[str] = (),
                  deadline_secs: Optional[float] = None) -> Dict[str, Dict]:
        """Run every item in order; return {session id: {"outputs":
        {var: interior array}, "items": n, "run_secs": s}} for each
        TOUCHED session, extracted once after the whole queue retired.

        A session appearing in several items accumulates steps in
        program order (exactly what the same requests through the
        scheduler would do serially); its response reflects the final
        state.  Unknown sessions raise before anything runs — a bulk
        queue is one unit of work, not a best-effort sweep.
        """
        from yask_tpu.resilience.faults import fault_point, maybe_corrupt
        from yask_tpu.resilience.guard import guarded_call
        from yask_tpu.serve.scheduler import extract_outputs

        items = list(items)
        sessions = {}
        for sid, _f, _l in items:
            sessions[str(sid)] = self._registry.session(sid)
        qid = f"q{self._next_qid:04d}"
        self._next_qid += 1

        with self._dev_lock:
            fault_point("serve.resident")
            self._record(qid, "*", "resident_queue",
                         items=len(items),
                         sessions=sorted(sessions))
            t0 = time.perf_counter()
            counts: Dict[str, int] = {}
            for sid, first, last in items:
                sess = sessions[str(sid)]
                ctx = sess.ctx
                prev = ctx.set_run_state(sess.run_state)
                try:
                    guarded_call(ctx.run_solution, int(first),
                                 int(last), site="serve.resident",
                                 deadline_secs=deadline_secs)
                finally:
                    ctx.set_run_state(prev)
                counts[str(sid)] = counts.get(str(sid), 0) + 1
            # the ONE synchronization point for the whole queue: every
            # touched session's rings retire together (guarded — a
            # dying relay hangs the sync with nothing else to kill it)
            import jax
            for sess in sessions.values():
                ctx = sess.ctx
                prev = ctx.set_run_state(sess.run_state)
                try:
                    guarded_call(jax.block_until_ready, ctx._state,
                                 site="serve.resident",
                                 deadline_secs=deadline_secs)
                finally:
                    ctx.set_run_state(prev)
            run_secs = time.perf_counter() - t0

            results: Dict[str, Dict] = {}
            for sid, sess in sessions.items():
                ctx = sess.ctx
                prev = ctx.set_run_state(sess.run_state)
                try:
                    outs = extract_outputs(ctx, tuple(outputs),
                                           sub_sizes=sess.sub_sizes)
                finally:
                    ctx.set_run_state(prev)
                outs = maybe_corrupt("serve.resident", outs)
                results[sid] = {"outputs": outs,
                                "items": counts.get(sid, 0),
                                "run_secs": run_secs}
                self._record(qid, sid, "resident_done",
                             items=counts.get(sid, 0),
                             run_secs=round(run_secs, 6),
                             outputs=sorted(outs))
            return results


def run_per_request(scheduler, items: Sequence[WorkItem],
                    outputs: Sequence[str] = (),
                    timeout: Optional[float] = None) -> Dict[str, Dict]:
    """The per-request-dispatch baseline arm of the resident A/B: the
    SAME work list pushed through ``scheduler.request`` one item at a
    time (queue + window + snapshot + per-item extraction each).
    Returns the final response per session in the resident result
    shape, so the A/B compares like with like."""
    from yask_tpu.serve.api import ServeRequest
    results: Dict[str, Dict] = {}
    counts: Dict[str, int] = {}
    for sid, first, last in items:
        resp = scheduler.request(
            ServeRequest(session=str(sid), first_step=int(first),
                         last_step=int(last), outputs=tuple(outputs)),
            timeout=timeout)
        if resp.status not in ("ok", "degraded"):
            raise YaskException(
                f"per-request arm failed on {sid} [{first},{last}]: "
                f"{resp.status}: {resp.error}")
        counts[str(sid)] = counts.get(str(sid), 0) + 1
        results[str(sid)] = {"outputs": resp.outputs,
                             "items": counts[str(sid)],
                             "run_secs": resp.run_secs}
    return results
