"""The in-process server: registry + scheduler + metrics under one
facade.

Lifecycle::

    srv = StencilServer()                      # owns worker thread
    sid = srv.open_session(stencil="iso3dfd", radius=2, g=16,
                           mode="jit", wf=2)   # prepares ONCE per
                                               # profile; later tenants
                                               # share the executable
    srv.set_var(sid, "vel", 0.5)               # state lives server-side
    srv.set_var_slice(sid, "pressure", arr, first, last)
    resp = srv.request(ServeRequest(session=sid, first_step=0,
                                    last_step=3))
    srv.metrics(); srv.flush_metrics()         # PERF_LEDGER rows
    srv.shutdown()

**Warm start**: every executable a request needs is built through
``yask_tpu.cache.aot_compile``, so with ``YT_COMPILE_CACHE`` set a
restarted server's first request deserializes from disk — zero
lowerings (``cache.stats()["lowerings"] == 0``); :meth:`prewarm`
optionally pulls the compile forward to ``open_session`` time.

``open_session`` runs the checker's serve pass over the profile
(LOG-ONLY, same policy as the bench preflight: a false positive must
not refuse a tenant) — ``SERVE-BATCH-INCOMPAT`` and
``SERVE-CACHE-COLD`` findings print to stderr and are kept on
``last_preflight`` for inspection.
"""

from __future__ import annotations

import sys
import threading
from typing import Dict, List, Optional

import numpy as np

from yask_tpu.obs.metrics import Registry, percentile as _pctl
from yask_tpu.serve.api import ServeRequest, ServeResponse
from yask_tpu.serve.journal import ServeJournal
from yask_tpu.serve.registry import SessionRegistry
from yask_tpu.serve.scheduler import BatchScheduler


class StencilServer:
    def __init__(self, env=None, factory=None,
                 journal_path: Optional[str] = None,
                 window_secs: Optional[float] = None,
                 max_batch: Optional[int] = None,
                 preflight: bool = True):
        from yask_tpu import yk_factory
        self._factory = factory or yk_factory()
        self._env = env if env is not None else self._factory.new_env()
        self.journal = ServeJournal(journal_path)
        # journal growth control: a long-lived fleet worker restarts
        # onto the same SERVE_JOURNAL.w<i>.jsonl — compact it past the
        # YT_JOURNAL_MAX_MB threshold before appending more (between
        # servers is the safe compaction window).
        self.journal.compact_if_large()
        self.registry = SessionRegistry(self._factory, self._env)
        #: per-server metrics registry (obs.metrics) — the scheduler
        #: feeds it per release; ``metrics()["registry"]`` exports it.
        self.obs = Registry()
        self.scheduler = BatchScheduler(self.registry, self.journal,
                                        window_secs=window_secs,
                                        max_batch=max_batch,
                                        obs_registry=self.obs)
        self._preflight = bool(preflight)
        #: last serve-pass CheckReport (LOG-ONLY evidence).
        self.last_preflight = None
        self._lock = threading.RLock()

    # ------------------------------------------------------- sessions

    def open_session(self, stencil: str, radius: Optional[int] = None,
                     g=16, mode: str = "jit", wf: int = 2,
                     options: str = "",
                     session: Optional[str] = None,
                     bucket: Optional[bool] = None) -> str:
        """Open a tenant session.  ``bucket`` controls shape-bucket
        co-batching: None = the ``YT_SERVE_BUCKETING`` default (on),
        False = host exactly at ``g``, True = request bucketing.  A
        bucketed session is hosted on a profile at the next bucket-
        ladder rung >= g and runs as a masked sub-domain — results
        stay bit-identical to a solo run at ``g`` (the
        ``yask_tpu.serve.buckets`` contract); infeasible solutions
        (non-jit modes, IF_DOMAIN conditions) decline and open exact,
        with the structured reason journaled on every batched row."""
        from yask_tpu.serve.api import (Overloaded, serve_retry_after,
                                        serve_bucketing_enabled)
        tier = self.scheduler.overload_tier()
        if tier >= 2:
            # brownout tier 2: admission is the ONLY thing refused —
            # existing sessions and in-flight requests are untouched
            ra = serve_retry_after()
            self.obs.counter("serve.overload.rejected_sessions").inc()
            self.journal.record(session or "-", session or "-",
                                "overloaded", tier=tier,
                                retry_after=ra, stencil=str(stencil))
            raise Overloaded(
                f"server overloaded (brownout tier {tier}): not "
                f"admitting new sessions; retry after {ra:g}s",
                retry_after=ra, tier=tier)
        requested = serve_bucketing_enabled() if bucket is None \
            else bool(bucket)
        decision, sub, host_g = self._plan_bucket(
            stencil, radius, g, mode, wf, options, requested)
        prof = self.registry.get_profile(stencil, radius, host_g, mode,
                                         wf, options)
        if self._preflight:
            self._run_preflight(prof)
        return self.registry.open_session(prof, session, sub_sizes=sub,
                                          bucket=decision).sid

    def _plan_bucket(self, stencil, radius, g, mode: str, wf: int,
                     options: str, requested: bool):
        """The open-time bucketing verdict: (BucketDecision,
        sub_sizes-or-None, host geometry).  Feasibility is probed on
        an UNPREPARED solution (equations + mode are all it needs), so
        a declined session never pays a wasted bucket-rung prepare."""
        from yask_tpu.serve.buckets import BucketDecision, plan_bucket
        try:
            gi = int(g)
        except (TypeError, ValueError):
            return (BucketDecision(
                "exact", g=0,
                reason=f"non-cubic geometry {g!r} serves exact"),
                None, g)
        if not requested:
            return (BucketDecision("exact", g=gi,
                                   reason="bucketing not requested"),
                    None, g)
        probe = self._factory.new_solution(self._env, stencil=stencil,
                                           radius=radius)
        probe.get_settings().mode = mode
        decision = plan_bucket(probe, gi, True)
        if decision.decision != "bucketed":
            return decision, None, g
        sub = None
        if decision.bucket != gi:
            sub = {d: gi for d in probe._opts.global_domain_sizes}
        return decision, sub, decision.bucket

    def _run_preflight(self, prof) -> None:
        """Serve-pass checks over the profile, log-only (the bench
        preflight policy: findings print, the tenant is admitted)."""
        try:
            from yask_tpu.checker import run_checks
            report = run_checks(prof.ctx, passes=("serve",))
            self.last_preflight = report
            if report.errors or report.warnings:
                sys.stderr.write(report.render())
        except Exception as e:  # noqa: BLE001 - a checker bug must
            sys.stderr.write(   # never refuse a tenant
                f"serve preflight: internal failure "
                f"({type(e).__name__}: {e}); skipped\n")

    def close_session(self, sid: str) -> None:
        self.registry.close_session(sid)

    def session_mode(self, sid: str) -> str:
        return self.registry.session(sid).mode

    # ----------------------------------------------- state in/out

    def set_var(self, sid: str, var: str, value: float) -> None:
        with self.scheduler.session_ctx(sid) as ctx:
            ctx.get_var(var).set_all_elements_same(value)

    def set_var_slice(self, sid: str, var: str, buf,
                      first_indices, last_indices) -> int:
        with self.scheduler.session_ctx(sid) as ctx:
            return ctx.get_var(var).set_elements_in_slice(
                np.asarray(buf), list(first_indices),
                list(last_indices))

    def get_var_slice(self, sid: str, var: str, first_indices,
                      last_indices):
        with self.scheduler.session_ctx(sid) as ctx:
            return ctx.get_var(var).get_elements_in_slice(
                list(first_indices), list(last_indices))

    def init_vars(self, sid: str) -> None:
        """The standard nonzero initial conditions
        (``init_solution_vars``) for this session's state — over the
        tenant's SUB-domain when the session is bucket-hosted, so a
        bucketed tenant starts bit-identical to its solo twin."""
        from yask_tpu.runtime.init_utils import init_solution_vars
        sess = self.registry.session(sid)
        with self.scheduler.session_ctx(sid) as ctx:
            init_solution_vars(ctx, sub_sizes=sess.sub_sizes)

    def session_bucket(self, sid: str) -> Dict:
        """The session's structured bucketing verdict (empty for the
        pre-bucketing open path)."""
        b = self.registry.session(sid).bucket
        return b.as_detail() if b is not None else {}

    # ----------------------------------------------------- requests

    def submit(self, req: ServeRequest, on_stream=None):
        return self.scheduler.submit(req, on_stream=on_stream)

    def wait(self, handle, timeout: Optional[float] = None
             ) -> ServeResponse:
        return self.scheduler.wait(handle, timeout)

    def request(self, req: ServeRequest,
                timeout: Optional[float] = None) -> ServeResponse:
        return self.scheduler.request(req, timeout)

    def run(self, sid: str, first_step: int,
            last_step: Optional[int] = None,
            outputs=(), timeout: Optional[float] = None,
            flush_every: int = 0, stream_outputs: bool = False
            ) -> ServeResponse:
        return self.request(
            ServeRequest(session=sid, first_step=first_step,
                         last_step=last_step,
                         outputs=tuple(outputs),
                         flush_every=int(flush_every),
                         stream_outputs=bool(stream_outputs)), timeout)

    def submit_run(self, sid: str, first_step: int,
                   last_step: Optional[int] = None, outputs=(),
                   flush_every: int = 0, stream_outputs: bool = False):
        """Non-blocking :meth:`run` — returns the pending handle for
        :meth:`wait`.  Submitting a whole sweep before waiting is what
        lands compatible requests inside one batching window."""
        return self.submit(
            ServeRequest(session=sid, first_step=first_step,
                         last_step=last_step,
                         outputs=tuple(outputs),
                         flush_every=int(flush_every),
                         stream_outputs=bool(stream_outputs)))

    # ------------------------------------------------- checkpointing

    def snapshot(self, sid: str) -> Dict:
        """An interior-coordinate checkpoint of the session's state
        (``yask_tpu.checkpoint/1``), taken under the session's device
        lock so it never races a running chunk.  Restores
        bit-identically across modes/paddings — the fleet front banks
        these for checkpoint-backed failover."""
        from yask_tpu.resilience.checkpoint import extract_snapshot
        with self.scheduler.session_ctx(sid) as ctx:
            return extract_snapshot(ctx)

    def restore(self, sid: str, snap: Dict) -> bool:
        """Apply a banked checkpoint onto the session (ring state +
        step counters).  Returns False on a schema/shape mismatch
        (``apply_snapshot`` contract: never raises)."""
        from yask_tpu.resilience.checkpoint import apply_snapshot
        with self.scheduler.session_ctx(sid) as ctx:
            return bool(apply_snapshot(ctx, snap))

    # ----------------------------------------------------- warm start

    def prewarm(self, sid: str, steps: int) -> int:
        """Build (or disk-load) the compiled chunks a ``steps``-long
        request will need, ahead of the first request.  Returns the
        number of chunk executables touched.  With ``YT_COMPILE_CACHE``
        set and warm, this deserializes — zero lowerings."""
        from yask_tpu.resilience.guard import guarded_call
        sess = self.registry.session(sid)
        n = max(1, int(steps))
        with self.scheduler.session_ctx(sid) as ctx:
            if sess.mode not in ("jit", "pallas"):
                return 0
            wf = ctx._opts.wf_steps
            if sess.mode == "pallas":
                wf = min(max(wf, 1), n)
            elif wf <= 0:
                wf = n
            sizes = set()
            rem = n
            while rem > 0:
                k = min(wf, rem)
                sizes.add(k)
                rem -= k
            getter = ctx._get_pallas_chunk if sess.mode == "pallas" \
                else ctx._get_compiled_chunk
            for k in sorted(sizes):
                guarded_call(getter, k, site="serve.run")
            return len(sizes)

    # ------------------------------------------------------- metrics

    def metrics(self) -> Dict:
        """Serving metrics over the retained samples: queue depth,
        batch occupancy, p50/p99 latency split queue/run, cache-hit
        tiers, degradation counts."""
        samples = self.scheduler.samples()
        done = [s for s in samples if s["status"] in ("ok", "anomaly")]
        q = [s["queue_secs"] * 1e3 for s in done]
        r = [s["run_secs"] * 1e3 for s in done]
        tot = [(s["queue_secs"] + s["run_secs"]) * 1e3 for s in done]
        occ = [s["batch"] for s in done]
        hits: Dict[str, int] = {}
        for s in done:
            hits[s["cache_hit"]] = hits.get(s["cache_hit"], 0) + 1
        return {
            "queue_depth": self.scheduler.queue_depth(),
            "sessions": len(self.registry.sessions()),
            "profiles": len(self.registry.profiles()),
            "completed": len(done),
            "ok": sum(1 for s in done if s["status"] == "ok"),
            "anomalies": sum(1 for s in done
                             if s["status"] == "anomaly"),
            "degraded": sum(1 for s in done if s["degraded"]),
            "bucketed": sum(1 for s in done if s.get("bucketed")),
            "preempted": sum(1 for s in done if s.get("preempted")),
            "batch_occupancy_mean": (sum(occ) / len(occ)) if occ
            else 0.0,
            "batch_occupancy_max": max(occ) if occ else 0,
            "p50_queue_ms": round(_pctl(q, 0.50), 3),
            "p99_queue_ms": round(_pctl(q, 0.99), 3),
            "p50_run_ms": round(_pctl(r, 0.50), 3),
            "p99_run_ms": round(_pctl(r, 0.99), 3),
            "p50_total_ms": round(_pctl(tot, 0.50), 3),
            "p99_total_ms": round(_pctl(tot, 0.99), 3),
            "compile_ms_total": round(sum(s["compile_secs"]
                                          for s in done) * 1e3, 1),
            "cache_hits": hits,
            # the obs registry's own view (same percentile math —
            # obs.metrics.percentile IS the historical _pctl); rides
            # op_metrics to the fleet front as the per-worker export.
            "registry": self.obs.snapshot(),
        }

    def metrics_snapshot(self) -> Dict:
        """The per-worker telemetry unit the fleet front aggregates
        (``op metrics_snapshot``): the registry snapshot WITH raw
        histogram sample windows (so the aggregator can merge windows
        and re-rank quantiles — never average percentiles), plus
        cache/journal occupancy counters and the SLO monitor's state
        (None unless YT_SLO_* configured one)."""
        from yask_tpu.cache import compile_cache
        snap = self.obs.snapshot_full()
        snap["v"] = "yask_tpu.telemetry/1"
        snap["cache"] = compile_cache.stats()
        jrows = self.journal.rows()
        snap["journal"] = {
            "rows": len(jrows),
            "inflight": sum(1 for r in jrows
                            if r.get("event") == "received")
            - sum(1 for r in jrows
                  if r.get("event") in ("ok", "anomaly", "rejected")),
            "slo_breaches": sum(1 for r in jrows
                                if r.get("event") == "slo_breach"),
        }
        snap["occupancy"] = {
            "queue_depth": self.scheduler.queue_depth(),
            "sessions": len(self.registry.sessions()),
            "profiles": len(self.registry.profiles()),
        }
        snap["slo"] = self.scheduler.slo_summary()
        return snap

    def flush_metrics(self) -> List[Dict]:
        """Append the serving metrics to PERF_LEDGER.jsonl (source
        ``serve``; latency/occupancy units are outside the sentinel's
        guarded units by design — the guarded serving row is the
        bench suite's ``serve-batch-speedup``)."""
        from yask_tpu.perflab import capture_provenance
        from yask_tpu.perflab.sentinel import guard_and_append
        m = self.metrics()
        if not m["completed"]:
            return []
        plat = self._env.get_platform()
        prov = capture_provenance(platform=plat)
        # aggregate rows cover many requests — the distinct trace ids
        # in the sampled window ride along so a ledger row joins back
        # to the span timelines it summarizes (newest 32, bounded).
        tids: List[str] = []
        for s in self.scheduler.samples():
            t = s.get("trace")
            if t and t not in tids:
                tids.append(t)
        tids = tids[-32:]
        rows = []
        for key, value, unit in (
                ("serve p50 total latency", m["p50_total_ms"], "ms"),
                ("serve p99 total latency", m["p99_total_ms"], "ms"),
                ("serve batch occupancy mean",
                 m["batch_occupancy_mean"], "reqs"),
        ):
            try:
                rows.append(guard_and_append(
                    key, float(value), unit, plat or "cpu", "serve",
                    prov, extra={"completed": m["completed"],
                                 "ok": m["ok"],
                                 "anomalies": m["anomalies"],
                                 "degraded": m["degraded"],
                                 "p50_queue_ms": m["p50_queue_ms"],
                                 "p50_run_ms": m["p50_run_ms"],
                                 "occupancy_max":
                                     m["batch_occupancy_max"],
                                 "cache_hits": m["cache_hits"],
                                 **({"trace_ids": tids}
                                    if tids else {})}))
            except Exception:  # noqa: BLE001 - ledger I/O must never
                pass           # break serving
        return rows

    # ------------------------------------------------------ lifecycle

    def shutdown(self) -> None:
        self.scheduler.shutdown()
