"""The session registry: prepared solutions shared across tenants.

Two levels:

* :class:`Profile` — ONE prepared ``StencilContext`` per configuration
  (stencil, radius, geometry, wf_steps, extra options) *per mode*.
  The base mode's context is prepared at registration; degraded-rung
  contexts (``degradation_ladder``) are prepared lazily on first fault
  and cached, so a ladder walk re-prepares once per profile, not once
  per tenant.  The profile also exposes the batching identity — mode +
  ``ctx._pallas_variant_key()`` — the scheduler groups on.
* :class:`Session` — one tenant: a session id bound to a profile, the
  tenant's CURRENT mode (start = profile base mode; a classified
  device fault can walk it down the ladder), and the tenant's own
  :class:`~yask_tpu.runtime.run_state.RunState` allocated against that
  mode's prepared geometry.

This is the reference's "one linked kernel library, many
``yk_solution`` instances" process model with the compile cache as
the library: registering a second tenant on an existing profile costs
one zero-filled state allocation, zero compiles.

Shape bucketing (v2): ``StencilServer.open_session`` may key the
prepared context by BUCKET geometry instead of the tenant's exact one
(``yask_tpu.serve.buckets``) — the session then carries ``sub_sizes``
and rides masked sub-domain executions, so tenants at g=20 and g=24
share one profile at the g=24 rung and co-batch.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from yask_tpu.utils.exceptions import YaskException


class Profile:
    """One registered configuration and its per-mode prepared contexts."""

    def __init__(self, key: Tuple, factory, env, stencil: str,
                 radius: Optional[int], g: str, mode: str, wf: int,
                 options: str = ""):
        self.key = key
        self._factory = factory
        self._env = env
        self.stencil = stencil
        self.radius = radius
        self.g = g
        self.base_mode = mode
        self.wf = wf
        self.options = options
        self._ctxs: Dict[str, object] = {}
        self._lock = threading.RLock()

    def _build(self, mode: str):
        ctx = self._factory.new_solution(self._env, stencil=self.stencil,
                                         radius=self.radius)
        opts = f"-g {self.g} -wf_steps {self.wf}"
        if self.options:
            opts += " " + self.options
        ctx.apply_command_line_options(opts)
        ctx.get_settings().mode = mode
        # mark as server-hosted: the checker's serve pass keys on this
        ctx.get_settings().serve = True
        ctx.prepare_solution()
        return ctx

    def ctx_for(self, mode: str):
        """The prepared context for ``mode`` (lazily built + cached —
        one prepare per (profile, mode) for the server's lifetime)."""
        with self._lock:
            ctx = self._ctxs.get(mode)
            if ctx is None:
                ctx = self._ctxs[mode] = self._build(mode)
            return ctx

    @property
    def ctx(self):
        return self.ctx_for(self.base_mode)

    def variant_key(self, mode: Optional[str] = None) -> Tuple:
        """The pallas-variant component of the batching identity."""
        return self.ctx_for(mode or self.base_mode)._pallas_variant_key()

    def modes_prepared(self) -> List[str]:
        with self._lock:
            return sorted(self._ctxs)


class Session:
    """One tenant: its profile, current (possibly degraded) mode, and
    its own RunState under that mode's prepared context.

    A BUCKETED session (shape co-batching, ``yask_tpu.serve.buckets``)
    is hosted on a profile at a LARGER ladder-rung geometry than the
    tenant requested: ``sub_sizes`` holds the tenant's logical domain
    sizes ({dim: size}, low-corner anchored) and every run masks the
    state to that sub-domain — results stay bit-identical to a solo
    run at the tenant geometry.  ``bucket`` keeps the structured
    :class:`~yask_tpu.serve.buckets.BucketDecision` for journaling."""

    def __init__(self, sid: str, profile: Profile,
                 sub_sizes: Optional[Dict[str, int]] = None,
                 bucket=None):
        self.sid = sid
        self.profile = profile
        self.mode = profile.base_mode
        self.run_state = profile.ctx.new_run_state()
        #: tenant's logical domain sizes when bucket-hosted (None =
        #: the session occupies the profile's full geometry).
        self.sub_sizes = dict(sub_sizes) if sub_sizes else None
        #: the BucketDecision that placed this session (None = the
        #: pre-bucketing open path).
        self.bucket = bucket
        #: ladder rungs this session has been walked down, in order.
        self.degrade_path: List[str] = []

    @property
    def ctx(self):
        return self.profile.ctx_for(self.mode)

    @property
    def degraded(self) -> bool:
        return bool(self.degrade_path)


class SessionRegistry:
    """Profiles + sessions, with profile dedup by configuration key."""

    def __init__(self, factory, env):
        self._factory = factory
        self._env = env
        self._profiles: Dict[Tuple, Profile] = {}
        self._sessions: Dict[str, Session] = {}
        self._lock = threading.RLock()
        self._next_sid = 0

    @staticmethod
    def profile_key(stencil: str, radius: Optional[int], g,
                    mode: str, wf: int, options: str = "") -> Tuple:
        return (str(stencil), radius, str(g), str(mode), int(wf),
                str(options or "").strip())

    def get_profile(self, stencil: str, radius: Optional[int], g,
                    mode: str = "jit", wf: int = 2,
                    options: str = "") -> Profile:
        """The profile for this configuration, preparing it on first
        registration (the expensive step — later tenants share it)."""
        key = self.profile_key(stencil, radius, g, mode, wf, options)
        with self._lock:
            prof = self._profiles.get(key)
            if prof is None:
                prof = Profile(key, self._factory, self._env,
                               str(stencil), radius, str(g), str(mode),
                               int(wf), str(options or "").strip())
                prof.ctx  # prepare the base mode eagerly
                self._profiles[key] = prof
            return prof

    def open_session(self, profile: Profile,
                     session: Optional[str] = None,
                     sub_sizes: Optional[Dict[str, int]] = None,
                     bucket=None) -> Session:
        with self._lock:
            if session is None:
                session = f"s{self._next_sid:04d}"
                self._next_sid += 1
            if session in self._sessions:
                raise YaskException(
                    f"serve session {session!r} already open")
            s = Session(str(session), profile, sub_sizes=sub_sizes,
                        bucket=bucket)
            self._sessions[s.sid] = s
            return s

    def session(self, sid: str) -> Session:
        with self._lock:
            s = self._sessions.get(str(sid))
            if s is None:
                raise YaskException(f"unknown serve session {sid!r}")
            return s

    def close_session(self, sid: str) -> None:
        with self._lock:
            self._sessions.pop(str(sid), None)

    def sessions(self) -> List[Session]:
        with self._lock:
            return list(self._sessions.values())

    def profiles(self) -> List[Profile]:
        with self._lock:
            return list(self._profiles.values())
