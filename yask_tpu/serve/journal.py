"""The serving journal: append-only ``SERVE_JOURNAL.jsonl``.

Schema ``yask_tpu.serve/1`` — one row per request-lifecycle event::

    {"v": "yask_tpu.serve/1",
     "rid":     "r000007",             # request id
     "session": "tenant-3",
     "event":   "received|batched|ok|anomaly|rejected|fault|degraded"
                "|stream|preempted|worker_dead|failover|retry"
                "|snapshot|slo_breach|scale_up|scale_down|drain"
                "|shed|overloaded",
     "ts":      "2026-08-05T12:00:00Z",
     "detail":  {...}}                 # event-specific (batch size,
                                       # fault kind, ladder rung, ...)

``ok`` / ``anomaly`` / ``rejected`` are terminal (``anomaly`` = the
request ran to completion but its outputs were quarantined by the
result-sanity guards — released to the tenant flagged, never banked
clean); ``received`` / ``batched`` / ``fault`` / ``degraded`` are
lifecycle evidence; ``stream`` marks a partial-result flush at a
chunk boundary (``detail.step`` = last completed step) and
``preempted`` marks the run yielding the device between chunks
(``detail.resume_at`` = the continuation's first step).  The ``batched`` rows carry the batch occupancy —
the acceptance criterion "co-batchable requests actually batched"
reads them.  Mechanics mirror
:class:`yask_tpu.resilience.journal.SessionJournal` (append-only,
malformed lines skipped on read, atomic compact between servers).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

SERVE_SCHEMA = "yask_tpu.serve/1"
SERVE_JOURNAL_BASENAME = "SERVE_JOURNAL.jsonl"

#: terminal request states — one of these must be the last event of
#: every submitted request's lifecycle.
SERVE_TERMINAL = ("ok", "anomaly", "rejected")

SERVE_EVENTS = ("received", "batched", "ok", "anomaly", "rejected",
                "fault", "degraded", "stream", "preempted",
                # resident bulk path (yask_tpu/serve/resident.py):
                # resident_queue = a device-resident work list started
                # (detail: item count, session set), resident_done =
                # one touched session's outputs extracted after the
                # single end-of-queue sync.
                "resident_queue", "resident_done",
                # fleet supervision lifecycle (front-side journal):
                # worker_dead = a worker was declared dead/unhealthy,
                # failover = a session migrated (detail: dead worker
                # id, snapshot step, replayed step range), retry = an
                # in-flight op re-issued under its idempotency key,
                # snapshot = a checkpoint banked for a session.
                "worker_dead", "failover", "retry", "snapshot",
                # slo_breach = the LOG-ONLY SLO monitor saw every
                # burn-rate window above threshold (detail: signal,
                # budget, per-window burn; trace_id = worst offender).
                "slo_breach",
                # elastic-fleet lifecycle (front-side journal):
                # scale_up = the autoscaler warm-spawned a worker
                # (detail: worker idx, triggering signal; trace_id =
                # the breach/request that tripped it), drain = a
                # worker stopped admitting ahead of retirement
                # (detail: sessions to migrate), scale_down = the
                # drained worker was retired (detail: migrated/lost
                # session ids).  shed = a brownout tier dropped a
                # streaming flush (detail: tier), overloaded = a new
                # session was rejected with a Retry-After hint
                # (detail: tier, retry_after).
                "scale_up", "scale_down", "drain", "shed",
                "overloaded")


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def default_serve_journal_path() -> str:
    return os.environ.get("YT_SERVE_JOURNAL") or os.path.join(
        _repo_root(), SERVE_JOURNAL_BASENAME)


def serve_journal_max_bytes() -> int:
    """Size threshold for :meth:`ServeJournal.compact_if_large`
    (``YT_JOURNAL_MAX_MB``, default 64 MiB)."""
    try:
        mb = float(os.environ.get("YT_JOURNAL_MAX_MB", "") or 64.0)
    except ValueError:
        mb = 64.0
    return int(mb * (1 << 20))


def _utc_now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


class ServeJournal:
    def __init__(self, path: Optional[str] = None):
        self.path = path or default_serve_journal_path()

    # ---------------------------------------------------------- write
    def record(self, rid: str, session: str, event: str,
               trace_id: str = "", **detail) -> Dict:
        """Append one lifecycle row.  Unlike the session journal this
        never raises: serving must survive a read-only journal dir (a
        tenant's answer cannot depend on evidence I/O), so failures
        return the row un-persisted.

        ``trace_id`` joins the row against TRACE_EVENTS.jsonl — the
        scheduler passes each request's own id explicitly (one batch
        can mix traces); callers without one inherit the thread's
        active trace via ``stamp_trace``."""
        from yask_tpu.obs.tracer import stamp_trace
        if event not in SERVE_EVENTS:
            raise ValueError(f"unknown serve journal event {event!r}; "
                             f"one of {SERVE_EVENTS}")
        row = {"v": SERVE_SCHEMA, "rid": str(rid),
               "session": str(session), "event": str(event),
               "ts": _utc_now()}
        if trace_id:
            row["trace_id"] = str(trace_id)
        else:
            stamp_trace(row)
        if detail:
            row["detail"] = detail
        try:
            with open(self.path, "a") as f:
                f.write(json.dumps(row, sort_keys=True) + "\n")
        except OSError:
            pass
        return row

    # ----------------------------------------------------------- read
    def rows(self) -> List[Dict]:
        out: List[Dict] = []
        try:
            with open(self.path) as f:
                for ln in f:
                    ln = ln.strip()
                    if not ln:
                        continue
                    try:
                        row = json.loads(ln)
                    except ValueError:
                        continue
                    if isinstance(row, dict) \
                            and row.get("v") == SERVE_SCHEMA:
                        out.append(row)
        except OSError:
            pass
        return out

    def events(self, rid: str) -> List[Dict]:
        """One request's lifecycle, file order == time order."""
        return [r for r in self.rows() if r.get("rid") == rid]

    def terminal(self, rid: str) -> Optional[str]:
        """The request's terminal state, or None while in flight."""
        for r in reversed(self.events(rid)):
            if r["event"] in SERVE_TERMINAL:
                return r["event"]
        return None

    def max_occupancy(self) -> int:
        """Largest batch size any ``batched`` row records (0 when the
        server never batched) — the acceptance criterion's probe."""
        best = 0
        for r in self.rows():
            if r["event"] == "batched":
                best = max(best, int(r.get("detail", {})
                                     .get("batch", 0)))
        return best

    # ----------------------------------------------------------- admin
    def compact(self, keep_terminal_only: bool = True) -> int:
        """Atomically rewrite to the last event per rid (terminal rows
        preferred); run between servers, never during one.

        Admission control and the co-batching acceptance probe read
        ``max_occupancy()`` from ``batched`` rows, so compaction keeps
        the highest-occupancy ``batched`` row per rid alongside the
        terminal row — the occupancy evidence survives any number of
        compactions."""
        rows = self.rows()
        last: Dict[str, Dict] = {}
        best_batched: Dict[str, Dict] = {}
        order: List[str] = []
        for r in rows:
            rid = r.get("rid", "")
            if rid not in last:
                order.append(rid)
            if r["event"] == "batched":
                prev = best_batched.get(rid)
                occ = int(r.get("detail", {}).get("batch", 0))
                if prev is None or occ > int(prev.get("detail", {})
                                             .get("batch", 0)):
                    best_batched[rid] = r
            if not keep_terminal_only or r["event"] in SERVE_TERMINAL \
                    or last.get(rid, {}).get("event") \
                    not in SERVE_TERMINAL:
                last[rid] = r
        kept = 0
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            for rid in order:
                bb = best_batched.get(rid)
                if bb is not None and bb is not last[rid]:
                    f.write(json.dumps(bb, sort_keys=True) + "\n")
                    kept += 1
                f.write(json.dumps(last[rid], sort_keys=True) + "\n")
                kept += 1
        os.replace(tmp, self.path)
        return len(rows) - kept

    def compact_if_large(self, max_bytes: Optional[int] = None) -> bool:
        """Compact when the journal file exceeds ``max_bytes``
        (default :func:`serve_journal_max_bytes`).  Long-lived fleet
        workers call this at startup and between requests so
        ``SERVE_JOURNAL.w<i>.jsonl`` cannot grow unbounded.  Never
        raises — growth control must not take a worker down."""
        try:
            limit = serve_journal_max_bytes() if max_bytes is None \
                else int(max_bytes)
            if os.path.getsize(self.path) <= limit:
                return False
            self.compact()
            return True
        except (OSError, ValueError):
            return False
