"""yask_tpu.serve — the long-lived multi-tenant stencil-serving layer.

One process holds a **session registry** of prepared solutions
(:mod:`.registry`): a profile = one prepared ``StencilContext`` per
(stencil, geometry, dtype, mode, variant) configuration, a tenant =
one session id owning its own :class:`~yask_tpu.runtime.run_state.
RunState` under that shared compiled executable — the
per-run-state-out-of-StencilContext hoist finished end-to-end.  A
**dynamic micro-batching scheduler** (:mod:`.scheduler`) groups
compatible pending requests (same profile / mode / variant key / step
range) inside a bounded window into ONE vmapped ensemble execution
(:class:`~yask_tpu.runtime.ensemble.EnsembleRun` over the tenants'
existing RunStates), and a restarted server **warm-starts** from the
persistent AOT compile cache (``YT_COMPILE_CACHE``): the first request
answers with zero lowerings.

Every request runs through ``guarded_call`` at the ``serve.run`` fault
site, is journaled (schema ``yask_tpu.serve/1`` —
received/batched/ok/anomaly/rejected), passes result-sanity quarantine
before its response is released, and a classified device fault walks
the session down the PR 9 mode-degradation ladder instead of failing
the tenant.  Serving metrics (queue depth, batch occupancy, p50/p99
latency split queue/run, cache-hit tier) append PERF_LEDGER rows.

Serving v2 adds **shape-bucket co-batching** (:mod:`.buckets`):
sessions opened at different geometries are hosted on shared bucket-
ladder rung profiles and ride ONE masked vmapped ensemble, bit-
identical to their solo runs; **chunked streaming** (``flush_every``
on the request: partial-result ``stream`` events at chunk boundaries,
long runs preemptible between chunks so short requests interleave);
and a **warm-cache fleet front** (``tools/serve_fleet.py``: N workers
behind one JSON-lines front with session-affinity routing, admission
control, and a shared on-disk compile cache).

Front ends: the in-process :class:`~yask_tpu.serve.server.
StencilServer` API, and the stdio/socket JSON-lines front in
``tools/serve.py`` (client: ``tools/serve_client.py``; fleet:
``tools/serve_fleet.py``).  See ``docs/serving.md``.
"""

from yask_tpu.serve.api import (ServeRequest, ServeResponse,
                                serve_bucketing_enabled,
                                serve_deadline_secs, serve_max_batch,
                                serve_window_secs)
from yask_tpu.serve.buckets import (BucketDecision, bucket_cobatch_feasible,
                                    bucket_for, bucket_ladder, plan_bucket)
from yask_tpu.serve.journal import (SERVE_SCHEMA, SERVE_TERMINAL,
                                    ServeJournal, default_serve_journal_path)
from yask_tpu.serve.registry import SessionRegistry
from yask_tpu.serve.server import StencilServer

__all__ = ["ServeRequest", "ServeResponse", "StencilServer",
           "SessionRegistry", "ServeJournal", "SERVE_SCHEMA",
           "SERVE_TERMINAL", "default_serve_journal_path",
           "serve_window_secs", "serve_max_batch",
           "serve_deadline_secs", "serve_bucketing_enabled",
           "BucketDecision", "bucket_ladder", "bucket_for",
           "plan_bucket", "bucket_cobatch_feasible"]
