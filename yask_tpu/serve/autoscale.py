"""SLO-driven fleet autoscaling policy (the DECISION side only).

The fleet front (``tools/serve_fleet.py``) owns the mechanism — warm
spawn from the shared compile cache, drain + migrate via the
checkpoint/failover path — and calls :meth:`AutoscalePolicy.decide`
once per supervision tick with a :class:`ScaleSignals` built from the
SAME merged telemetry snapshot ``fleet_stats`` answers from.  Keeping
the policy a pure function of (signals, clock) makes every threshold,
the cooldown, and the min/max bounds unit-testable without a fleet.

Signals (see :func:`signals_from_snapshot`):

* **queue depth** — summed over FRESH per-worker blocks only;
* **SLO burn rate** — the max shortest-window burn across fresh
  workers' ``slo.burn`` summaries (0.0 when no worker runs a
  monitor);
* **staleness** — workers whose snapshot block is older than the
  exclusion horizon (``merge_snapshots`` flags them).  A tick with
  ZERO fresh workers yields NO decision: the autoscaler must not
  scale on dead data.

Policy: scale UP one worker when the per-fresh-worker queue depth
reaches ``YT_FLEET_SCALE_UP_QUEUE`` or the burn rate reaches
``YT_FLEET_SCALE_UP_BURN``; scale DOWN one worker after
``YT_FLEET_SCALE_DOWN_IDLE`` consecutive fully-idle ticks.  Both are
bounded by ``YT_FLEET_MIN_WORKERS`` / ``YT_FLEET_MAX_WORKERS`` and a
shared ``YT_FLEET_SCALE_COOLDOWN`` so the loop cannot flap — a
decision (either direction) opens the cooldown window and nothing
else fires inside it.  Every decision carries the triggering signal
values; the fleet journals them on the ``scale_up`` / ``scale_down``
rows (docs/serving.md has the policy table).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

__all__ = ["ScaleSignals", "Decision", "AutoscalePolicy",
           "signals_from_snapshot", "fleet_autoscale_enabled",
           "fleet_min_workers", "fleet_max_workers",
           "fleet_scale_cooldown", "fleet_scale_up_queue",
           "fleet_scale_up_burn", "fleet_scale_down_idle"]


def _env_num(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def fleet_autoscale_enabled() -> bool:
    """``YT_FLEET_AUTOSCALE`` master switch (default OFF — a fleet
    without the knob never changes size on its own)."""
    return os.environ.get("YT_FLEET_AUTOSCALE", "").strip().lower() \
        in ("1", "on", "true", "yes")


def fleet_min_workers() -> int:
    """``YT_FLEET_MIN_WORKERS`` (default 1): scale-down floor."""
    return max(1, int(_env_num("YT_FLEET_MIN_WORKERS", 1)))


def fleet_max_workers() -> int:
    """``YT_FLEET_MAX_WORKERS`` (default 4): scale-up ceiling."""
    return max(1, int(_env_num("YT_FLEET_MAX_WORKERS", 4)))


def fleet_scale_cooldown() -> float:
    """``YT_FLEET_SCALE_COOLDOWN`` seconds (default 30): after ANY
    scaling decision, no further decision fires until it elapses."""
    return max(0.0, _env_num("YT_FLEET_SCALE_COOLDOWN", 30.0))


def fleet_scale_up_queue() -> int:
    """``YT_FLEET_SCALE_UP_QUEUE`` (default 8): per-fresh-worker queue
    depth at/above which the fleet scales up (0 disables the queue
    trigger)."""
    return max(0, int(_env_num("YT_FLEET_SCALE_UP_QUEUE", 8)))


def fleet_scale_up_burn() -> float:
    """``YT_FLEET_SCALE_UP_BURN`` (default 1.0): max shortest-window
    SLO burn rate at/above which the fleet scales up (0 disables the
    burn trigger; 1.0 = consuming the whole error budget)."""
    return max(0.0, _env_num("YT_FLEET_SCALE_UP_BURN", 1.0))


def fleet_scale_down_idle() -> int:
    """``YT_FLEET_SCALE_DOWN_IDLE`` (default 3): consecutive
    fully-idle supervision ticks (zero queued work fleet-wide) before
    one worker drains and retires."""
    return max(1, int(_env_num("YT_FLEET_SCALE_DOWN_IDLE", 3)))


@dataclass
class ScaleSignals:
    """One tick's observation — everything :meth:`decide` may read."""
    n_workers: int = 0
    #: workers already draining (still in ``n_workers``; excluded from
    #: the scale-down headroom so one idle stretch retires one worker).
    n_draining: int = 0
    #: workers whose telemetry block was polled fresh this tick (or is
    #: younger than the staleness horizon).
    fresh_workers: int = 0
    stale_workers: List[str] = field(default_factory=list)
    #: summed queue depth over FRESH workers only.
    queue_depth: int = 0
    #: max shortest-window SLO burn across fresh workers (0.0 = no
    #: monitor anywhere, or every window still empty).
    max_burn: float = 0.0

    def detail(self) -> Dict:
        """The journal-row form (scale_up/scale_down ``detail.signal``)."""
        return {"n_workers": self.n_workers,
                "n_draining": self.n_draining,
                "fresh_workers": self.fresh_workers,
                "stale_workers": list(self.stale_workers),
                "queue_depth": self.queue_depth,
                "max_burn": round(float(self.max_burn), 4)}


@dataclass
class Decision:
    """One scaling decision: ``action`` is ``"up"`` or ``"down"``,
    ``reason`` names the trigger (``queue_depth`` / ``burn_rate`` /
    ``idle``), ``signal`` is the triggering :class:`ScaleSignals`
    detail dict journaled with the row."""
    action: str
    reason: str
    signal: Dict


def _max_shortest_window_burn(slo_summary: Optional[Dict]) -> float:
    """Max burn over every SLI's SHORTEST populated window in one
    worker's ``metrics_snapshot()["slo"]`` summary (the same shape
    :meth:`yask_tpu.obs.slo.SloMonitor.summary` exports)."""
    if not isinstance(slo_summary, dict):
        return 0.0
    best = 0.0
    for sli in (slo_summary.get("burn") or {}).values():
        wins = (sli or {}).get("windows") or {}
        keyed = []
        for k, v in wins.items():
            try:
                keyed.append((float(k), v))
            except (TypeError, ValueError):
                continue
        for _w, v in sorted(keyed):
            if int((v or {}).get("total", 0)) > 0:
                best = max(best, float((v or {}).get("burn", 0.0)))
                break  # shortest populated window only
    return best


def signals_from_snapshot(merged: Optional[Dict], n_workers: int,
                          n_draining: int = 0) -> ScaleSignals:
    """Build one tick's :class:`ScaleSignals` from the fleet's merged
    telemetry snapshot (``merge_snapshots`` output: per-worker blocks
    under ``workers``, stale ones listed in ``stale_workers`` and
    already excluded from the merged fold)."""
    sig = ScaleSignals(n_workers=int(n_workers),
                       n_draining=int(n_draining))
    if not isinstance(merged, dict):
        return sig
    stale = [str(s) for s in (merged.get("stale_workers") or [])]
    sig.stale_workers = stale
    for wid, snap in (merged.get("workers") or {}).items():
        if not isinstance(snap, dict) or wid in stale \
                or snap.get("error"):
            continue
        sig.fresh_workers += 1
        occ = snap.get("occupancy") or {}
        try:
            sig.queue_depth += int(occ.get("queue_depth", 0))
        except (TypeError, ValueError):
            pass
        sig.max_burn = max(sig.max_burn,
                           _max_shortest_window_burn(snap.get("slo")))
    return sig


class AutoscalePolicy:
    """The pure decision loop.  Stateful only in the ways the policy
    needs (last-decision timestamp for the cooldown, consecutive-idle
    counter); ``clock`` is injectable so tests never sleep."""

    def __init__(self, min_workers: Optional[int] = None,
                 max_workers: Optional[int] = None,
                 cooldown: Optional[float] = None,
                 up_queue: Optional[int] = None,
                 up_burn: Optional[float] = None,
                 down_idle: Optional[int] = None,
                 clock: Optional[Callable[[], float]] = None):
        import time
        self.min_workers = fleet_min_workers() \
            if min_workers is None else max(1, int(min_workers))
        self.max_workers = fleet_max_workers() \
            if max_workers is None else max(1, int(max_workers))
        if self.max_workers < self.min_workers:
            self.max_workers = self.min_workers
        self.cooldown = fleet_scale_cooldown() \
            if cooldown is None else max(0.0, float(cooldown))
        self.up_queue = fleet_scale_up_queue() \
            if up_queue is None else max(0, int(up_queue))
        self.up_burn = fleet_scale_up_burn() \
            if up_burn is None else max(0.0, float(up_burn))
        self.down_idle = fleet_scale_down_idle() \
            if down_idle is None else max(1, int(down_idle))
        self._clock = clock or time.monotonic
        self._last_decision_ts: Optional[float] = None
        self._idle_ticks = 0

    @classmethod
    def from_env(cls) -> "AutoscalePolicy":
        return cls()

    def _in_cooldown(self, now: float) -> bool:
        return self._last_decision_ts is not None \
            and (now - self._last_decision_ts) < self.cooldown

    def decide(self, sig: ScaleSignals) -> Optional[Decision]:
        """One tick: at most one Decision, or None (hold)."""
        if sig.fresh_workers <= 0:
            # dead data: every worker's block is stale or missing —
            # refuse to decide anything (and do not count the tick as
            # idle; an unobserved fleet is not a quiet one).
            self._idle_ticks = 0
            return None
        now = self._clock()
        per_q = sig.queue_depth / max(1, sig.fresh_workers)
        hot_q = self.up_queue > 0 and per_q >= self.up_queue
        hot_b = self.up_burn > 0 and sig.max_burn >= self.up_burn
        if hot_q or hot_b:
            self._idle_ticks = 0
            if sig.n_workers >= self.max_workers \
                    or self._in_cooldown(now):
                return None
            self._last_decision_ts = now
            reason = "queue_depth" if hot_q else "burn_rate"
            return Decision("up", reason, sig.detail())
        if sig.queue_depth == 0:
            self._idle_ticks += 1
        else:
            self._idle_ticks = 0
        if self._idle_ticks >= self.down_idle \
                and (sig.n_workers - sig.n_draining) > self.min_workers \
                and not self._in_cooldown(now):
            self._idle_ticks = 0
            self._last_decision_ts = now
            return Decision("down", "idle", sig.detail())
        return None
