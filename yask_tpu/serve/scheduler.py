"""The request queue + dynamic micro-batching scheduler.

ONE worker thread owns all device work (tenant threads only enqueue
and wait on events), so run-state swaps on the shared prepared
contexts are serialized by construction — the tenant-safe shape of
the RunState hoist.  The loop:

1. take the oldest pending request; wait up to the batching window
   (``YT_SERVE_WINDOW_MS``) for co-batchable company;
2. group requests with the same **batch key** — (profile, session
   mode, ``ctx._pallas_variant_key()``, step range) — one request per
   session, up to ``YT_SERVE_MAX_BATCH``, and only when
   :func:`~yask_tpu.runtime.ensemble.ensemble_feasible` says the mode
   batches (the ONE feasibility definition; sharded modes serve
   singly).  Bucketed sessions (``yask_tpu.serve.buckets``) share a
   bucket-rung profile, so tenants on DIFFERENT logical domains carry
   the same key and co-batch;
3. execute: occupancy > 1 — or ANY bucketed member — rides ONE
   vmapped :class:`~yask_tpu.runtime.ensemble.EnsembleRun` over the
   sessions' existing RunStates (bucketed members pass their
   ``sub_sizes`` as masked sub-domains); plain occupancy 1 is a
   ``run_solution`` under the session's state.  Both under
   ``guarded_call`` at the ``serve.run`` fault site with the
   per-request deadline.  A request with ``flush_every > 0`` splits
   the range into chunks: each chunk is guarded separately, a
   ``stream`` event (journal + wire) flushes at every chunk boundary
   (``serve.flush`` fault site, NON-fatal — a failed flush skips the
   beacon, never the run), and between chunks the batch YIELDS to any
   waiting request (``preempted`` journal event; the continuation
   re-queues BEFORE any same-session pending so per-session FIFO
   holds).  Short requests interleave with long streamed ones — the
   p99 win the bench A/B measures;
4. on a classified fault: roll each affected session back to its
   last committed chunk boundary (pre-request when nothing streamed)
   and walk it down the mode-degradation ladder (PR 9) over the
   REMAINING step range — the tenant gets a degraded-mode answer, not
   an error.  Bucket-hosted sessions never degrade (masked sub-domain
   runs are jit-only, and jit's ladder is empty by design).  A shared
   breaker (manual recording, reset on recovery — consecutive faults
   trip it) bounds runaway ladder walks;
5. release: written interiors (the tenant's SUB-domain for bucketed
   sessions) pass ``maybe_corrupt("serve.respond")`` + the
   result-sanity guards; a failed verdict releases the response
   flagged ``anomaly`` (quarantined — never banked clean).

Every lifecycle edge is journaled (schema ``yask_tpu.serve/1``).
Known limitation, documented in docs/serving.md: ``guarded_call``'s
SIGALRM deadline only arms on the main thread, so on this worker the
deadline relies on fault classification (injected hangs and real
relay errors classify; a hard in-C stall needs the subprocess front).
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from yask_tpu.obs import tracer as obs
from yask_tpu.obs.metrics import Registry
from yask_tpu.serve.api import (ServeRequest, ServeResponse,
                                serve_deadline_secs, serve_max_batch,
                                serve_window_secs)
from yask_tpu.serve.journal import ServeJournal
from yask_tpu.serve.registry import Session, SessionRegistry
from yask_tpu.utils.exceptions import YaskException

#: bound on retained latency samples (metrics percentiles).
MAX_SAMPLES = 4096


def extract_outputs(ctx, names: Tuple[str, ...] = (),
                    sub_sizes: Optional[Dict[str, int]] = None) -> Dict:
    """Newest-slot written interiors of the ACTIVE run state, by
    interior coordinates (the same geometry walk as the watchdog scan
    and ``compare_data``) — the response payload, and the oracle-side
    extraction the bit-identity tests compare against.  ``sub_sizes``
    restricts the domain slices to a bucketed tenant's low-corner
    sub-domain, so the payload is shaped exactly like the solo run's."""
    ctx._check_prepared()
    ctx._materialize_state()
    gsz = ctx._opts.global_domain_sizes
    out = {}
    for name, g in ctx._program.geoms.items():
        if names:
            if name not in names:
                continue
        elif not g.is_written or g.is_scratch:
            continue
        idx = tuple(
            slice(g.origin[dn], g.origin[dn]
                  + (int(sub_sizes.get(dn, gsz[dn]))
                     if sub_sizes else gsz[dn]))
            if kind == "domain" else slice(None)
            for dn, kind in g.axes)
        out[name] = np.asarray(ctx._state[name][-1][idx])
    missing = set(names) - set(out)
    if missing:
        raise YaskException(
            f"requested output var(s) {sorted(missing)} not in the "
            f"solution ({sorted(ctx._program.geoms)})")
    return out


class _Pending:
    """One queued request plus its rendezvous with the worker.  The
    mutable accumulators survive preemption rounds (a preempted
    request re-enters the queue as its own continuation)."""

    __slots__ = ("req", "rid", "t_received", "t_wall", "done",
                 "response", "run_secs", "compile_secs", "cache_hit",
                 "preempts", "streams", "on_stream", "trace")

    def __init__(self, req: ServeRequest, rid: str):
        self.req = req
        self.rid = rid
        self.t_received = time.perf_counter()
        self.t_wall = time.time()
        # ONE trace id per request lifecycle: the wire front's stamped
        # id wins, else an ambient activation (in-process callers),
        # else mint one when tracing is on.  "" = untraced (rows stay
        # bit-identical to the pre-obs schema).
        self.trace = (req.trace or obs.current_trace_id()
                      or (obs.new_trace_id() if obs.trace_enabled()
                          else ""))
        self.done = threading.Event()
        self.response: Optional[ServeResponse] = None
        self.run_secs = 0.0
        self.compile_secs = 0.0
        self.cache_hit = ""
        self.preempts = 0
        self.streams: List[Dict] = []
        #: optional callable(event_dict) — the wire front's push hook,
        #: invoked on the worker thread at each flush.
        self.on_stream = None

    def finish(self, resp: ServeResponse) -> None:
        self.response = resp
        self.done.set()


class BatchScheduler:
    def __init__(self, registry: SessionRegistry,
                 journal: Optional[ServeJournal] = None,
                 window_secs: Optional[float] = None,
                 max_batch: Optional[int] = None,
                 obs_registry: Optional[Registry] = None):
        from yask_tpu.resilience.faults import Breaker
        self._registry = registry
        self._journal = journal or ServeJournal()
        self._obs = obs_registry or Registry()
        self._window = serve_window_secs() if window_secs is None \
            else max(0.0, float(window_secs))
        self._max_batch = serve_max_batch() if max_batch is None \
            else max(1, int(max_batch))
        self._pending: List[_Pending] = []
        self._cond = threading.Condition()
        self._breaker = Breaker()
        # LOG-ONLY SLO monitor (None unless a YT_SLO_* knob is set —
        # the unconfigured path must cost nothing and write nothing)
        from yask_tpu.obs.slo import SloMonitor
        self._slo = SloMonitor.from_env()
        # brownout tier cache: (monotonic ts, tier) — overload_tier()
        # is probed per flush and per open, so it must stay cheap
        self._tier_cache: Optional[Tuple[float, int]] = None
        self._shutdown = False
        self._next_rid = 0
        self._samples: List[Dict] = []
        self._lock = threading.RLock()      # metrics/samples
        self._dev_lock = threading.RLock()  # all context/state access
        self._worker = threading.Thread(target=self._loop,
                                        name="yt-serve-worker",
                                        daemon=True)
        self._worker.start()

    # ------------------------------------------------------------ API

    def submit(self, req: ServeRequest, on_stream=None) -> _Pending:
        """Enqueue; returns the pending handle (wait on
        ``handle.done`` or use :meth:`wait`).  ``on_stream`` is an
        optional callable(event_dict) fired on the worker thread at
        every flush — the wire front's push hook (attached HERE, not
        after submit, so the first chunk's flush cannot race it)."""
        with self._cond:
            rid = f"r{self._next_rid:06d}"
            self._next_rid += 1
            p = _Pending(req, rid)
            p.on_stream = on_stream
            self._journal.record(rid, req.session, "received",
                                 trace_id=p.trace,
                                 first=req.steps()[0],
                                 last=req.steps()[1])
            if self._shutdown:
                p.finish(self._reject(p, "server is shut down"))
                return p
            try:
                self._registry.session(req.session)
            except YaskException as e:
                p.finish(self._reject(p, str(e)))
                return p
            self._pending.append(p)
            self._cond.notify_all()
            return p

    def wait(self, p: _Pending,
             timeout: Optional[float] = None) -> ServeResponse:
        if not p.done.wait(timeout):
            raise YaskException(
                f"request {p.rid} still in flight after {timeout}s")
        return p.response

    def request(self, req: ServeRequest,
                timeout: Optional[float] = None) -> ServeResponse:
        return self.wait(self.submit(req), timeout)

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._pending)

    def samples(self) -> List[Dict]:
        with self._lock:
            return list(self._samples)

    def slo_summary(self) -> Optional[Dict]:
        """The SLO monitor's burn-rate state (None when no YT_SLO_*
        knob configured it)."""
        if self._slo is None:
            return None
        try:
            return self._slo.summary()
        except Exception:  # noqa: BLE001 - surfacing must never raise
            return None

    def _max_burn(self) -> float:
        """Max SLO burn rate over the SHORTEST evaluation window (fast
        detection is the point of a brownout) across SLIs with events.
        0.0 without a monitor — the queue-depth fallbacks take over."""
        if self._slo is None:
            return 0.0
        try:
            rates = self._slo.burn_rates()
        except Exception:  # noqa: BLE001 - observability never breaks
            return 0.0     # serving
        best = 0.0
        for r in rates.values():
            wins = r.get("windows") or {}
            if not wins:
                continue
            w = wins[min(wins, key=lambda k: int(k))]
            if int(w.get("total", 0)) > 0:
                best = max(best, float(w.get("burn", 0.0)))
        return best

    def overload_tier(self, now: Optional[float] = None) -> int:
        """The brownout tier: 0 = normal, 1 = shed streaming flushes,
        2 = also reject NEW sessions (``Overloaded`` + Retry-After).
        Driven by the SLO burn signal (``YT_SERVE_SHED_BURN`` /
        ``YT_SERVE_REJECT_BURN``) with queue-depth fallbacks
        (``YT_SERVE_SHED_QUEUE`` / ``YT_SERVE_REJECT_QUEUE``) for
        SLO-less servers; every knob defaults off, so an unconfigured
        scheduler never sheds.  In-flight work is never abandoned by
        any tier — tier 1 drops progress beacons, tier 2 refuses
        admission, nothing touches running requests.  Cached ~250 ms:
        this is probed per flush and per open."""
        from yask_tpu.serve.api import (serve_reject_burn,
                                        serve_reject_queue,
                                        serve_shed_burn,
                                        serve_shed_queue)
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            if self._tier_cache is not None \
                    and now - self._tier_cache[0] < 0.25:
                return self._tier_cache[1]
        shed_b, rej_b = serve_shed_burn(), serve_reject_burn()
        shed_q, rej_q = serve_shed_queue(), serve_reject_queue()
        tier = 0
        if shed_b or rej_b or shed_q or rej_q:
            burn = self._max_burn() if (shed_b or rej_b) else 0.0
            depth = self.queue_depth()
            if (rej_b and burn >= rej_b) or (rej_q and depth >= rej_q):
                tier = 2
            elif (shed_b and burn >= shed_b) \
                    or (shed_q and depth >= shed_q):
                tier = 1
            self._obs.gauge("serve.overload.tier").set(tier)
        with self._lock:
            self._tier_cache = (now, tier)
        return tier

    def session_ctx(self, sid: str):
        """Contextmanager: the session's prepared context with ITS
        run state active, under the device lock — the safe window for
        var fills / reads from any tenant thread."""
        from contextlib import contextmanager
        sess = self._registry.session(sid)

        @contextmanager
        def _swap():
            with self._dev_lock:
                ctx = sess.ctx
                prev = ctx.set_run_state(sess.run_state)
                try:
                    yield ctx
                finally:
                    ctx.set_run_state(prev)
        return _swap()

    def run_resident(self, items, outputs=(), deadline_secs=None):
        """Opt-in bulk path: drain a work list of (session, first,
        last) items through the device-resident executor
        (:mod:`yask_tpu.serve.resident`) under THIS scheduler's device
        lock and journal — one sync for the whole queue instead of
        per-request dispatch.  Serializes against in-flight request
        traffic (the one-worker-owns-the-device invariant holds);
        returns {session: {"outputs": ..., "items": n, "run_secs": s}}.
        """
        from yask_tpu.serve.resident import ResidentExecutor
        with self._lock:
            ex = getattr(self, "_resident", None)
            if ex is None:
                ex = self._resident = ResidentExecutor(
                    self._registry, journal=self._journal,
                    dev_lock=self._dev_lock)
        return ex.run_queue(items, outputs=outputs,
                            deadline_secs=deadline_secs)

    def shutdown(self, timeout: float = 10.0) -> None:
        with self._cond:
            self._shutdown = True
            for p in self._pending:
                p.finish(self._reject(p, "server is shut down"))
            self._pending.clear()
            self._cond.notify_all()
        self._worker.join(timeout)

    # ---------------------------------------------------------- worker

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._shutdown:
                    self._cond.wait()
                if self._shutdown and not self._pending:
                    return
                head = self._pending[0]
            # bounded batching window: wait for co-batchable company
            if self._window > 0:
                deadline = head.t_received + self._window
                while True:
                    now = time.perf_counter()
                    if now >= deadline:
                        break
                    with self._cond:
                        if len(self._pending) >= self._max_batch \
                                or self._shutdown:
                            break
                        self._cond.wait(timeout=deadline - now)
            batch = self._collect(head)
            if not batch:
                continue
            try:
                self._execute(batch)
            except Exception as e:  # noqa: BLE001 - the worker must
                # survive anything: a scheduler bug rejects the batch,
                # it must never kill the serving loop for other tenants
                for p in batch:
                    if not p.done.is_set():
                        p.finish(self._reject(
                            p, f"{type(e).__name__}: {e}"))

    def _batch_key(self, p: _Pending) -> Optional[Tuple]:
        try:
            sess = self._registry.session(p.req.session)
        except YaskException:
            return None
        first, last = p.req.steps()
        # bucketed sessions share a bucket-rung profile, so
        # profile.key here IS the bucket key: tenants at different
        # logical domains on the same rung carry equal keys and group
        return (sess.profile.key, sess.mode,
                sess.profile.variant_key(sess.mode), first, last)

    def _collect(self, head: _Pending) -> List[_Pending]:
        """Pop the head plus every co-batchable pending request (same
        batch key, distinct sessions, feasible mode) up to the
        occupancy cap."""
        from yask_tpu.runtime.ensemble import ensemble_feasible
        with self._cond:
            self._expire_queued()
            if head not in self._pending:
                return []
            key = self._batch_key(head)
            if key is None:
                self._pending.remove(head)
                head.finish(self._reject(
                    head, f"unknown serve session {head.req.session!r}"))
                return []
            sess = self._registry.session(head.req.session)
            can_batch, _why = ensemble_feasible(sess.ctx)
            batch = [head]
            seen = {head.req.session}
            if can_batch:
                for p in self._pending:
                    if p is head or len(batch) >= self._max_batch:
                        continue
                    if p.req.session in seen:
                        continue  # same tenant: state-dependent, next round
                    if self._batch_key(p) == key:
                        batch.append(p)
                        seen.add(p.req.session)
            for p in batch:
                self._pending.remove(p)
            return batch

    def _expire_queued(self, now: Optional[float] = None) -> None:
        """Fast-fail every pending request whose deadline elapsed while
        still QUEUED — before the worker touches the device for it.
        The deadline used to bound only device work; a request that
        waited its whole budget in ``_pending`` burned it just as
        surely, and running it anyway wastes a device turn on an
        answer the tenant has already given up on.  Caller holds
        ``self._cond``."""
        now = time.perf_counter() if now is None else float(now)
        for p in list(self._pending):
            ddl = p.req.deadline_secs or serve_deadline_secs()
            if ddl <= 0 or now - p.t_received <= ddl:
                continue
            self._pending.remove(p)
            self._obs.counter(
                "serve.overload.deadline_in_queue").inc()
            p.finish(self._reject(
                p, f"deadline {ddl:g}s expired after "
                   f"{now - p.t_received:.3f}s in queue (request "
                   "never reached the device)",
                reason="deadline_in_queue"))

    # --------------------------------------------------------- execute

    def _reject(self, p: _Pending, why: str,
                reason: str = "") -> ServeResponse:
        detail = {"error": why[:200]}
        if reason:
            detail["reason"] = reason
        self._journal.record(p.rid, p.req.session, "rejected",
                             trace_id=p.trace, **detail)
        self._obs.counter("serve.requests.rejected").inc()
        self._slo_feed(p, p.req.session, ok=False)
        return ServeResponse(rid=p.rid, session=p.req.session,
                             status="rejected", error=why,
                             trace=p.trace)

    def _slo_feed(self, p: _Pending, sid: str, *, ok: bool,
                  quarantined: bool = False,
                  total_ms: Optional[float] = None,
                  occupancy: Optional[float] = None) -> None:
        """Feed the SLO monitor one released/rejected request and
        journal any NEW breach as an ``slo_breach`` row (schema
        ``yask_tpu.slo/1``) joined to the worst offender's trace id.
        LOG-ONLY by contract: breaches print and journal; nothing is
        blocked, and a monitor bug must never break serving."""
        if self._slo is None:
            return
        try:
            self._slo.record(ok=ok, quarantined=quarantined,
                             preempted=bool(p.preempts),
                             total_ms=total_ms, occupancy=occupancy,
                             trace=p.trace)
            for br in self._slo.evaluate():
                self._journal.record(
                    p.rid, sid, "slo_breach",
                    trace_id=br.get("trace") or p.trace,
                    slo_v=br["v"], signal=br["signal"],
                    budget=br["budget"], threshold=br["threshold"],
                    windows=br["windows"])
                # stderr: a worker's stdout is the JSON-lines wire
                print(f"[serve] SLO breach: {br['signal']} burning "
                      f"past {br['threshold']}x budget {br['budget']} "
                      f"in all windows (trace "
                      f"{br.get('trace') or p.trace or '-'}) "
                      "— LOG-ONLY, serving continues",
                      file=sys.stderr)
        except Exception:  # noqa: BLE001 - observability must never
            pass           # take down the serving loop

    def _execute(self, batch: List[_Pending]) -> None:
        """One scheduling turn for a collected batch: journal the
        batching decision, then run the step range — whole when no
        member streams, chunked at the smallest requested flush
        cadence otherwise, yielding to waiting requests between
        chunks."""
        sessions = [self._registry.session(p.req.session)
                    for p in batch]
        first, last = batch[0].req.steps()
        n = len(batch)
        for p, sess in zip(batch, sessions):
            detail = {"batch": n, "first": first, "last": last,
                      "mode": sess.mode,
                      "window_ms": round(self._window * 1000.0, 3)}
            if sess.bucket is not None:
                # the structured bucketing verdict rides every
                # batched row: bucketed / exact / declined-why
                detail["bucket"] = sess.bucket.as_detail()
            if p.req.flush_every > 0:
                detail["flush_every"] = int(p.req.flush_every)
            self._journal.record(p.rid, p.req.session, "batched",
                                 trace_id=p.trace, **detail)
        cadences = [int(p.req.flush_every) for p in batch
                    if p.req.flush_every > 0]
        span = abs(last - first) + 1
        cadence = min(cadences) if cadences else 0
        if cadence <= 0 or cadence >= span:
            self._execute_chunk(batch, sessions, first, last,
                                final=True)
            return
        dirn = 1 if last >= first else -1
        a = first
        while True:
            b = a + dirn * (cadence - 1)
            if (dirn > 0 and b >= last) or (dirn < 0 and b <= last):
                b = last
            final = b == last
            if not self._execute_chunk(batch, sessions, a, b,
                                       final=final):
                return  # terminal (released, recovered, or rejected)
            self._flush_batch(batch, sessions, b)
            if self._maybe_preempt(batch, b + dirn, last):
                return  # continuation re-queued
            a = b + dirn

    def _execute_chunk(self, batch: List[_Pending],
                       sessions: List[Session], first: int, last: int,
                       *, final: bool) -> bool:
        """Run one guarded chunk [first, last] for the batch.  Returns
        True when the caller should continue with the next chunk;
        False when every request reached a terminal state here."""
        from yask_tpu.resilience.checkpoint import extract_snapshot
        from yask_tpu.resilience.faults import Fault, fault_point
        from yask_tpu.resilience.guard import guarded_call
        from yask_tpu.runtime.ensemble import EnsembleRun

        ddl = min((p.req.deadline_secs or serve_deadline_secs())
                  for p in batch) or None
        n = len(batch)
        masked = any(s.sub_sizes for s in sessions)
        t_start = time.perf_counter()

        with self._dev_lock:
            ctx = sessions[0].ctx
            compile0 = ctx._compile_secs
            # rollback targets: the last committed chunk boundary
            # (pre-request when nothing has run yet) — donation
            # consumes rings on the compiled paths, a faulted chunk
            # has nothing else to restart from
            snaps = {}
            for sess in sessions:
                prev = ctx.set_run_state(sess.run_state)
                try:
                    snaps[sess.sid] = extract_snapshot(ctx)
                finally:
                    ctx.set_run_state(prev)

            batched = False
            fault: Optional[Fault] = None
            # the head's trace id scopes the batch span (a batch can
            # mix traces; journal rows carry each member's own id) —
            # activation also stamps any ledger/session-journal rows
            # the run produces underneath.
            try:
                with obs.activate(batch[0].trace), \
                        obs.span("serve.chunk", phase="compute",
                                 batch=n, first=first, last=last,
                                 mode=sessions[0].mode,
                                 rids=[p.rid for p in batch]):
                    # the batching decision's injection site: a
                    # classified fault here takes the same degrade
                    # path as serve.run
                    fault_point("serve.batch")
                    if n > 1 or masked:
                        # bucketed members run masked even at
                        # occupancy 1: a sub-domain session's state is
                        # only correct under the per-step sub-domain
                        # mask
                        ens = EnsembleRun(
                            ctx,
                            members=[s.run_state for s in sessions],
                            sub_domains=([s.sub_sizes
                                          for s in sessions]
                                         if masked else None))
                        guarded_call(ens.run, first, last,
                                     site="serve.run",
                                     deadline_secs=ddl)
                        batched = ens.batched_reason == "" and n > 1
                    else:
                        prev = ctx.set_run_state(
                            sessions[0].run_state)
                        try:
                            guarded_call(ctx.run_solution, first,
                                         last, site="serve.run",
                                         deadline_secs=ddl)
                        finally:
                            ctx.set_run_state(prev)
            except Fault as f:
                fault = f
            except YaskException as e:
                for p in batch:
                    p.finish(self._reject(p, str(e)))
                return False
            chunk_secs = time.perf_counter() - t_start
            compile_secs = ctx._compile_secs - compile0
            cache_hit = ctx._last_cache_hit or "cold"
            for p in batch:
                p.run_secs += chunk_secs
                p.compile_secs += compile_secs
                p.cache_hit = cache_hit

            if fault is not None:
                tripped = self._breaker.record(fault)
                for p, sess in zip(batch, sessions):
                    self._journal.record(
                        p.rid, sess.sid, "fault", trace_id=p.trace,
                        kind=fault.kind,
                        site=getattr(fault, "site", "serve.run"),
                        mode=sess.mode, batch=n,
                        breaker_tripped=bool(tripped))
                for p, sess in zip(batch, sessions):
                    p.finish(self._recover(p, sess, snaps[sess.sid],
                                           fault, tripped, first,
                                           last=batch[0].req.steps()[1]))
                return False

        if final:
            now = time.perf_counter()
            for p, sess in zip(batch, sessions):
                p.finish(self._release(
                    p, sess, batch=n, batched=batched,
                    queue_secs=max(0.0, now - p.t_received
                                   - p.run_secs),
                    run_secs=p.run_secs,
                    compile_secs=p.compile_secs,
                    cache_hit=p.cache_hit))
            return False
        return True

    # ------------------------------------------------ stream / preempt

    def _flush_batch(self, batch: List[_Pending],
                     sessions: List[Session], step_done: int) -> None:
        """Emit a ``stream`` event for every streaming member at a
        chunk boundary.  Flushes are guarded at the ``serve.flush``
        site but NON-fatal: a classified fault skips this beacon and
        the run continues — a tenant's answer must never be lost to
        evidence I/O (the journal's own policy, applied to streams)."""
        from yask_tpu.resilience.faults import Fault
        from yask_tpu.resilience.guard import guarded_call
        tier = self.overload_tier()
        for p, sess in zip(batch, sessions):
            if p.req.flush_every <= 0:
                continue
            if tier >= 1:
                # brownout tier >= 1: the progress beacon is the
                # cheapest load to shed — the run itself (and its
                # final answer) continues untouched
                self._obs.counter("serve.overload.shed_flush").inc()
                self._journal.record(p.rid, sess.sid, "shed",
                                     trace_id=p.trace, tier=tier,
                                     step=int(step_done))
                continue
            try:
                guarded_call(self._flush_one, p, sess, step_done,
                             site="serve.flush")
            except Fault as f:
                self._journal.record(p.rid, sess.sid, "fault",
                                     trace_id=p.trace,
                                     kind=f.kind, site="serve.flush",
                                     nonfatal=True)

    def _flush_one(self, p: _Pending, sess: Session,
                   step_done: int) -> None:
        from yask_tpu.resilience.faults import fault_point
        fault_point("serve.flush")
        ev: Dict = {"step": int(step_done)}
        if p.req.stream_outputs:
            with self._dev_lock:
                ctx = sess.ctx
                prev = ctx.set_run_state(sess.run_state)
                try:
                    ev["outputs"] = extract_outputs(
                        ctx, tuple(p.req.outputs),
                        sub_sizes=sess.sub_sizes)
                finally:
                    ctx.set_run_state(prev)
        self._journal.record(p.rid, sess.sid, "stream",
                             trace_id=p.trace,
                             step=int(step_done),
                             chunk=len(p.streams),
                             outputs=sorted(ev.get("outputs", ())))
        p.streams.append(ev)
        cb = p.on_stream
        if cb is not None:
            cb(ev)

    def _maybe_preempt(self, batch: List[_Pending], next_first: int,
                       last: int) -> bool:
        """Between chunks: if anyone is waiting, yield — re-queue the
        whole batch as its own continuation (same co-batch on the
        next turn: all members share the updated step range, hence
        the batch key).  The continuation is inserted BEFORE any
        pending request of the same session, so per-session FIFO
        ordering is preserved; with no same-session pending it goes
        to the tail, behind the requests it yielded to."""
        from yask_tpu.resilience.faults import fault_point
        with self._cond:
            if self._shutdown or not self._pending:
                return False
            fault_point("serve.batch")
            for p in batch:
                p.req.first_step = int(next_first)
                p.req.last_step = int(last)
                p.preempts += 1
                self._journal.record(p.rid, p.req.session, "preempted",
                                     trace_id=p.trace,
                                     resume_at=int(next_first),
                                     last=int(last))
            sids = {p.req.session for p in batch}
            pos = len(self._pending)
            for idx, q in enumerate(self._pending):
                if q.req.session in sids:
                    pos = idx
                    break
            self._pending[pos:pos] = batch
            self._cond.notify_all()
            return True

    # --------------------------------------------------------- recover

    def _recover(self, p: _Pending, sess: Session, snap: Dict,
                 fault, tripped: bool, first: int,
                 last: int) -> ServeResponse:
        """Walk the session down the mode-degradation ladder from its
        last committed snapshot, over the REMAINING step range; the
        tenant gets a degraded-mode answer unless the ladder (or the
        breaker) is exhausted."""
        if tripped:
            return self._reject(
                p, f"{fault.kind} at serve.run and the breaker is "
                   "tripped (repeated faults) — not degrading")
        if sess.sub_sizes:
            # masked sub-domain runs are a jit-only contract, and a
            # ladder rung's geometry would not be the bucket's —
            # bucket-hosted sessions reject instead of degrading
            return self._reject(
                p, f"{fault.kind} at serve.run on a bucket-hosted "
                   "session (masked sub-domain runs do not degrade)")
        ddl = p.req.deadline_secs or serve_deadline_secs()
        last_err: Exception = fault
        t0 = time.perf_counter()
        with obs.activate(p.trace):
            return self._recover_laddered(
                p, sess, snap, fault, first, last, ddl, last_err, t0)

    def _recover_laddered(self, p: _Pending, sess: Session, snap: Dict,
                          fault, first: int, last: int, ddl, last_err,
                          t0: float) -> ServeResponse:
        from yask_tpu.resilience.checkpoint import (apply_snapshot,
                                                    degradation_ladder)
        from yask_tpu.resilience.faults import Fault
        from yask_tpu.resilience.guard import guarded_call
        for to_mode in degradation_ladder(sess.mode):
            try:
                ctx2 = sess.profile.ctx_for(to_mode)
            except Exception as e:  # noqa: BLE001 - rung unbuildable,
                last_err = e        # try the next one
                continue
            rs2 = ctx2.new_run_state()
            prev = ctx2.set_run_state(rs2)
            try:
                if not apply_snapshot(ctx2, snap):
                    last_err = YaskException(
                        f"snapshot restore into mode {to_mode} failed")
                    continue
                compile0 = ctx2._compile_secs
                guarded_call(ctx2.run_solution, first, last,
                             site="serve.run", deadline_secs=ddl)
            except Fault as f2:
                self._journal.record(p.rid, sess.sid, "fault",
                                     trace_id=p.trace,
                                     kind=f2.kind, mode=to_mode)
                if self._breaker.record(f2):
                    last_err = f2
                    break
                last_err = f2
                continue
            finally:
                ctx2.set_run_state(prev)
            sess.mode = to_mode
            sess.run_state = rs2
            sess.degrade_path.append(to_mode)
            self._breaker.reset()
            self._journal.record(p.rid, sess.sid, "degraded",
                                 trace_id=p.trace,
                                 to_mode=to_mode, kind=fault.kind,
                                 ladder_path=list(sess.degrade_path))
            return self._release(
                p, sess, batch=1, batched=False,
                queue_secs=t0 - p.t_received,
                run_secs=time.perf_counter() - t0,
                compile_secs=ctx2._compile_secs - compile0,
                cache_hit=ctx2._last_cache_hit or "cold")
        return self._reject(
            p, f"{fault.kind} at serve.run and the degradation ladder "
               f"is exhausted ({type(last_err).__name__}: {last_err})")

    # --------------------------------------------------------- release

    def _release(self, p: _Pending, sess: Session, *, batch: int,
                 batched: bool, queue_secs: float, run_secs: float,
                 compile_secs: float, cache_hit: str) -> ServeResponse:
        """Sanity-gate the written interiors, journal the terminal
        state, record the latency sample, build the response."""
        from yask_tpu.resilience.faults import maybe_corrupt
        from yask_tpu.resilience.sanity import anomaly_fields, check_output
        resp = ServeResponse(
            rid=p.rid, session=sess.sid, batch=batch, batched=batched,
            mode=sess.mode, degraded=sess.degraded,
            queue_secs=queue_secs, run_secs=run_secs,
            compile_secs=compile_secs, cache_hit=cache_hit,
            bucket=(sess.bucket.as_detail()
                    if sess.bucket is not None else {}),
            preempted=p.preempts, streams=list(p.streams),
            trace=p.trace)
        # the queue-wait interval as a retroactive span: the phase
        # breakdown must separate waiting from running
        obs.record_span("serve.queue_wait", "queue", p.t_wall,
                        queue_secs, trace=p.trace, rid=p.rid,
                        session=sess.sid)
        try:
            with self._dev_lock:
                ctx = sess.ctx
                prev = ctx.set_run_state(sess.run_state)
                try:
                    outs = extract_outputs(ctx, tuple(p.req.outputs),
                                           sub_sizes=sess.sub_sizes)
                finally:
                    ctx.set_run_state(prev)
        except YaskException as e:
            return self._reject(p, str(e))
        outs = maybe_corrupt("serve.respond", outs)
        verdict = check_output(outs)
        resp.outputs = outs
        if verdict["ok"]:
            resp.status = "ok"
            self._journal.record(p.rid, sess.sid, "ok", batch=batch,
                                 trace_id=p.trace,
                                 batched=batched, mode=sess.mode,
                                 degraded=sess.degraded,
                                 preempted=p.preempts)
        else:
            # quarantined release: the tenant sees the data AND the
            # verdict; the journal/ledger never bank it clean (the r3
            # all-zero lesson, applied to serving)
            resp.status = "anomaly"
            resp.anomaly = anomaly_fields(verdict)["anomaly"]
            self._journal.record(p.rid, sess.sid, "anomaly",
                                 trace_id=p.trace,
                                 batch=batch, mode=sess.mode,
                                 anomalies=verdict["anomalies"])
        with self._lock:
            self._samples.append({
                "status": resp.status, "batch": batch,
                "batched": batched, "mode": sess.mode,
                "degraded": sess.degraded,
                "bucketed": bool(sess.sub_sizes),
                "preempted": p.preempts, "trace": p.trace,
                "queue_secs": queue_secs, "run_secs": run_secs,
                "compile_secs": compile_secs, "cache_hit": cache_hit})
            if len(self._samples) > MAX_SAMPLES:
                del self._samples[:len(self._samples) - MAX_SAMPLES]
        reg = self._obs
        reg.counter(f"serve.requests.{resp.status}").inc()
        reg.counter(f"serve.cache.{cache_hit}").inc()
        if sess.degraded:
            reg.counter("serve.degraded").inc()
        if p.preempts:
            reg.counter("serve.preempted").inc()
        reg.histogram("serve.queue_ms").observe(queue_secs * 1e3)
        reg.histogram("serve.run_ms").observe(run_secs * 1e3)
        reg.histogram("serve.total_ms").observe(
            (queue_secs + run_secs) * 1e3)
        reg.histogram("serve.batch_occupancy").observe(batch)
        reg.gauge("serve.queue_depth").set(self.queue_depth())
        self._slo_feed(p, sess.sid, ok=(resp.status == "ok"),
                       quarantined=(resp.status == "anomaly"),
                       total_ms=(queue_secs + run_secs) * 1e3,
                       occupancy=batch)
        return resp
