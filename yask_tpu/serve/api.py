"""Request/response types + the ``YT_SERVE_*`` environment knobs.

A :class:`ServeRequest` names a session and an inclusive step range —
state lives server-side in the session's RunState, so a request is a
"advance my simulation and hand back the written interiors" verb, the
serving analog of ``run_solution(first_t, last_t)``.  The response
carries the terminal journal state (``ok`` / ``anomaly`` /
``rejected``), the latency split (queue / run; compile seconds are
reported separately because a warm-started server's first request
should show ~0), the batch occupancy the request actually rode, and
the requested written-var interiors as numpy arrays (bit-identical to
a solo ``run_solution`` — the acceptance contract).

Env knobs (all optional; see ``docs/serving.md``):

* ``YT_SERVE_WINDOW_MS``  — micro-batching window (default 5 ms on
  CPU tests; the scheduler waits at most this long after the first
  pending request for co-batchable company);
* ``YT_SERVE_MAX_BATCH``  — occupancy cap per vmapped execution
  (default 16);
* ``YT_SERVE_DEADLINE``   — per-request deadline seconds passed to
  ``guarded_call`` (default 300; SIGALRM only fires on the main
  thread, so off-thread schedulers rely on fault classification —
  documented limitation);
* ``YT_SERVE_JOURNAL``    — journal path override (serve/journal.py);
* ``YT_SERVE_BUCKETING``  — "0" disables shape-bucket co-batching at
  ``open_session`` (default on; see ``yask_tpu/serve/buckets.py``);
* ``YT_SERVE_BUCKETS``    — bucket-ladder rung override (buckets.py).

Overload-control knobs (brownout tiers; ALL default off so an
unconfigured server sheds nothing — see docs/serving.md):

* ``YT_SERVE_SHED_BURN``   — max short-window SLO burn rate at/above
  which the scheduler enters tier 1 (shed streaming flushes);
* ``YT_SERVE_REJECT_BURN`` — burn rate for tier 2 (also reject NEW
  sessions with :class:`Overloaded` + a Retry-After hint);
* ``YT_SERVE_SHED_QUEUE`` / ``YT_SERVE_REJECT_QUEUE`` — queue-depth
  fallbacks for the same tiers, for servers without an SLO monitor;
* ``YT_SERVE_RETRY_AFTER`` — the Retry-After hint, seconds (1.0).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

DEFAULT_WINDOW_MS = 5.0
DEFAULT_MAX_BATCH = 16
DEFAULT_DEADLINE_SECS = 300.0


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def serve_window_secs() -> float:
    """The micro-batching window, seconds (``YT_SERVE_WINDOW_MS``)."""
    return max(0.0, _env_float("YT_SERVE_WINDOW_MS",
                               DEFAULT_WINDOW_MS)) / 1000.0


def serve_max_batch() -> int:
    try:
        n = int(os.environ.get("YT_SERVE_MAX_BATCH", "")
                or DEFAULT_MAX_BATCH)
    except ValueError:
        n = DEFAULT_MAX_BATCH
    return max(1, n)


def serve_deadline_secs() -> float:
    return max(0.0, _env_float("YT_SERVE_DEADLINE",
                               DEFAULT_DEADLINE_SECS))


def serve_shed_burn() -> float:
    """Tier-1 brownout threshold on the max short-window SLO burn rate
    (``YT_SERVE_SHED_BURN``; 0 = tier never engages via burn)."""
    return max(0.0, _env_float("YT_SERVE_SHED_BURN", 0.0))


def serve_reject_burn() -> float:
    """Tier-2 brownout threshold (``YT_SERVE_REJECT_BURN``; 0 = off)."""
    return max(0.0, _env_float("YT_SERVE_REJECT_BURN", 0.0))


def serve_shed_queue() -> int:
    """Tier-1 queue-depth fallback (``YT_SERVE_SHED_QUEUE``; 0 = off)
    for servers running without an SLO monitor."""
    return max(0, int(_env_float("YT_SERVE_SHED_QUEUE", 0)))


def serve_reject_queue() -> int:
    """Tier-2 queue-depth fallback (``YT_SERVE_REJECT_QUEUE``; 0=off)."""
    return max(0, int(_env_float("YT_SERVE_REJECT_QUEUE", 0)))


def serve_retry_after() -> float:
    """The Retry-After hint carried by :class:`Overloaded`
    (``YT_SERVE_RETRY_AFTER``, seconds, default 1.0)."""
    return max(0.0, _env_float("YT_SERVE_RETRY_AFTER", 1.0))


class Overloaded(RuntimeError):
    """Structured overload rejection: brownout tier 2 is refusing NEW
    sessions (or the fleet front is saturated).  Carries a Retry-After
    hint so a well-behaved client can back off instead of hammering;
    in-flight work is NEVER answered with this — admission is the only
    place it is raised."""

    def __init__(self, msg: str, retry_after: float = 1.0,
                 tier: int = 2):
        super().__init__(msg)
        self.retry_after = float(retry_after)
        self.tier = int(tier)


def serve_bucketing_enabled() -> bool:
    """Shape-bucket co-batching default for ``open_session``
    (``YT_SERVE_BUCKETING``; "0"/"off"/"false" disable)."""
    return os.environ.get("YT_SERVE_BUCKETING", "1").strip().lower() \
        not in ("0", "off", "false", "no")


@dataclass
class ServeRequest:
    """One tenant's "advance my session" request.

    ``outputs`` selects which written vars' newest-slot interiors ride
    the response (empty = all written non-scratch vars);
    ``deadline_secs`` 0 means the server default
    (:func:`serve_deadline_secs`)."""
    session: str
    first_step: int
    last_step: Optional[int] = None
    outputs: Tuple[str, ...] = ()
    deadline_secs: float = 0.0
    #: flush cadence, steps: > 0 asks the scheduler to run the range
    #: in chunks of this many steps, emitting a ``stream`` journal /
    #: wire event at every chunk boundary — and makes the run
    #: PREEMPTIBLE between chunks (short requests interleave).
    #: 0 = single guarded execution over the whole range (v1 shape).
    flush_every: int = 0
    #: carry the partial written interiors on each stream event (off
    #: by default — a stream event is a progress beacon, the payload
    #: is opt-in because extraction costs a device sync per chunk).
    stream_outputs: bool = False
    #: upstream trace id (obs.tracer) — the fleet front stamps one per
    #: client op and the worker threads it through every journal row,
    #: span, and ledger row this request produces.  "" = none (the
    #: scheduler mints one only when YT_TRACE is on).
    trace: str = ""

    def steps(self) -> Tuple[int, int]:
        last = self.first_step if self.last_step is None \
            else self.last_step
        return int(self.first_step), int(last)


@dataclass
class ServeResponse:
    """The released answer for one request (after sanity gating).

    ``status`` is the journal's terminal state: ``ok`` (released),
    ``anomaly`` (ran to completion but the sanity guards quarantined
    the outputs — they still ride the response, flagged, so the tenant
    sees WHAT happened), ``rejected`` (never produced releasable
    output: unknown session, shutdown, or an unrecoverable fault after
    the degradation ladder was exhausted — ``error`` says why)."""
    rid: str = ""
    session: str = ""
    status: str = "rejected"
    error: str = ""
    #: occupancy of the vmapped execution this request rode (1 = ran
    #: alone; >1 = micro-batched).
    batch: int = 0
    #: whether the batch actually executed vmapped (EnsembleRun can
    #: degrade to sequential members and still answer).
    batched: bool = False
    #: mode that produced the answer + whether the session was walked
    #: down the degradation ladder to get it.
    mode: str = ""
    degraded: bool = False
    queue_secs: float = 0.0
    run_secs: float = 0.0
    compile_secs: float = 0.0
    cache_hit: str = ""
    #: var → newest-slot interior (numpy), per ``ServeRequest.outputs``.
    outputs: Dict = field(default_factory=dict)
    #: sanity verdict details when status == "anomaly".
    anomaly: Dict = field(default_factory=dict)
    #: the session's structured bucketing verdict (BucketDecision
    #: detail dict; empty for pre-bucketing sessions).
    bucket: Dict = field(default_factory=dict)
    #: how many times this request was preempted between flush chunks
    #: (0 = ran to completion in one scheduling turn).
    preempted: int = 0
    #: stream events flushed for this request, oldest first (each:
    #: {"step": ..., "outputs": {...}?}) — the wire front forwards
    #: them as they happen; the in-process response also keeps them.
    streams: List[Dict] = field(default_factory=list)
    #: the trace id this request ran under ("" when untraced) — the
    #: join key against TRACE_EVENTS.jsonl / journals / PERF_LEDGER.
    trace: str = ""

    @property
    def ok(self) -> bool:
        return self.status == "ok"
