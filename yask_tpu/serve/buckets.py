"""Shape-bucket planning: map tenant geometries onto a small ladder
of shared bucket shapes so tenants on DIFFERENT domains co-batch.

The r15 scheduler only groups requests whose sessions share one exact
(profile, mode, variant) key, so two tenants at g=20 and g=24 each pay
their own prepared context and always ride occupancy-1 executions.
Bucketing closes that gap: a session opened at g=20 is hosted inside a
bucket profile at the next ladder rung (g=24 here), runs as a
*sub-domain* of the bucket geometry, and co-batches with every other
tenant on the same rung — ONE vmapped :class:`~yask_tpu.runtime.
ensemble.EnsembleRun` over bucket-padded RunStates.

Bit-identity is the gate, and it is an invariant, not a tolerance:
outside the tenant's sub-domain every cell is held identically ZERO
after every step (the physical-boundary ghost-zero contract extended
inward — pads AND the bucket remainder), so an interior point's
neighborhood reads exactly what the solo run's ghost pads would hold.
The masked step lives in :class:`~yask_tpu.runtime.ensemble.
EnsembleRun` (``sub_domains=``); tenant sub-domains anchor at the LOW
corner, so interior coordinates 0..d-1 mean the same thing in bucket
and solo geometry and index-values-as-values stay bit-identical.

Ladder policy: rungs are 8-multiples (VarGeom pads sublane origins /
totals to 8 and lane totals to 128 in every mode, so a rung never
costs extra physical padding beyond what the solo geometry already
paid), roughly geometric with steps <= 1.5x — the worst-case padded
volume a tenant pays for riding a bucket is bounded per dim.
Override with ``YT_SERVE_BUCKETS`` (comma-separated rung list).

:func:`bucket_cobatch_feasible` is the ONE feasibility definition —
the registry's open-session decision, the scheduler, and the
checker's serve pass all consult it (same contract as
:func:`~yask_tpu.runtime.ensemble.ensemble_feasible`).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

#: default bucket rungs: 8-multiples (sublane-aligned in fp32 — see
#: VarGeom), <=1.5x steps so bucket-padded volume stays bounded.
DEFAULT_LADDER = (8, 16, 24, 32, 48, 64, 96, 128, 192, 256, 384, 512)


def bucket_ladder() -> Tuple[int, ...]:
    """The active rung ladder (``YT_SERVE_BUCKETS`` override)."""
    raw = os.environ.get("YT_SERVE_BUCKETS", "").strip()
    if not raw:
        return DEFAULT_LADDER
    try:
        rungs = sorted({int(x) for x in raw.split(",") if x.strip()})
    except ValueError:
        return DEFAULT_LADDER
    return tuple(r for r in rungs if r > 0) or DEFAULT_LADDER


def bucket_for(g: int) -> Optional[int]:
    """Smallest ladder rung >= ``g`` (None when g overtops the
    ladder — such domains serve exact, they are past the
    small-domain co-batching regime anyway)."""
    g = int(g)
    for rung in bucket_ladder():
        if rung >= g:
            return rung
    return None


@dataclass
class BucketDecision:
    """The structured per-session bucketing verdict — journaled on
    every ``batched`` row so a decline is evidence, not a mystery.

    ``decision`` is one of ``bucketed`` (session rides a bucket
    profile as a sub-domain), ``exact`` (session is hosted at its own
    geometry: already on a rung, past the ladder, or bucketing was
    not requested), ``declined`` (bucketing was requested but the
    solution cannot run masked — ``reason`` says why; the session
    still opens, exact)."""
    decision: str
    reason: str = ""
    g: int = 0
    bucket: Optional[int] = None

    def as_detail(self) -> Dict:
        d = {"decision": self.decision, "g": self.g}
        if self.bucket is not None:
            d["bucket"] = self.bucket
        if self.reason:
            d["reason"] = self.reason
        return d


def bucket_cobatch_feasible(ctx) -> Tuple[bool, str]:
    """Can sessions hosted on this prepared context run as masked
    sub-domains of a shared bucket?  ``(ok, why)`` — the ONE
    definition (registry decision, scheduler, checker serve pass).

    Masked sub-domain runs interpose a zero-mask after EVERY step
    inside the scanned jit chunk, so:

    * the mode must be ``jit`` — pallas fuses wf_steps in-kernel
      (no inter-step hook), and the sharded modes already fail
      :func:`~yask_tpu.runtime.ensemble.ensemble_feasible`;
    * no equation may carry an ``IF_DOMAIN`` condition: domain
      conditions anchor to the BUCKET's bounds (e.g. a reflective
      wall at ``x == last_index``), which is not where the tenant's
      sub-domain ends — masked results would diverge from solo.
      Step conditions (t-only) are position-free and stay exact.
    """
    from yask_tpu.runtime.ensemble import ensemble_feasible
    ok, why = ensemble_feasible(ctx)
    if not ok:
        return False, why
    mode = ctx._mode or ctx._opts.mode
    if mode != "jit":
        return False, (
            f"mode '{mode}' fuses steps in-kernel; the sub-domain "
            "zero-mask must interpose after every step, which only "
            "the scanned jit chunk allows")
    for eq in ctx._soln.get_equations():
        if eq.cond is not None:
            return False, (
                f"equation writing '{eq.lhs.var_name()}' carries an "
                "IF_DOMAIN condition anchored to the bucket's domain "
                "bounds — a sub-domain tenant's boundary is elsewhere")
    return True, ""


def plan_bucket(ctx_probe, g: int, requested: bool) -> BucketDecision:
    """The open-session bucketing verdict for a tenant geometry ``g``
    given a prepared context at that geometry class (``ctx_probe`` may
    be the exact-geometry context — feasibility is a property of the
    solution + mode, not of the rung)."""
    g = int(g)
    if not requested:
        return BucketDecision("exact", g=g,
                              reason="bucketing not requested")
    rung = bucket_for(g)
    if rung is None:
        return BucketDecision(
            "exact", g=g,
            reason=f"g={g} overtops the bucket ladder "
                   f"{bucket_ladder()[-1]} — serving exact")
    ok, why = bucket_cobatch_feasible(ctx_probe)
    if not ok:
        return BucketDecision("declined", g=g, reason=why)
    if rung == g:
        # already on a rung: host on the bucket profile anyway (so it
        # co-batches with smaller tenants on the same rung) but no
        # sub-domain masking is needed — full-domain member.
        return BucketDecision("bucketed", g=g, bucket=rung,
                              reason="exact rung")
    return BucketDecision("bucketed", g=g, bucket=rung)


# mask construction lives with the masked chunk (ONE definition in
# the runtime layer; serve must not fork its own geometry walk).
from yask_tpu.runtime.ensemble import sub_domain_masks  # noqa: E402,F401
