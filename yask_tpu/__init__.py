"""yask_tpu — a TPU-native stencil-computation framework.

A from-scratch re-design of the capabilities of intel/yask for TPU:

* a stencil DSL **compiler** (``yask_tpu.compiler``): equations are built as an
  AST via operator overloading (the ``yc_*`` API surface of the reference,
  ``include/yask_compiler_api.hpp``), analyzed for dependencies, partitioned
  into parts/stages, and **lowered to JAX/XLA and Pallas** instead of
  intrinsic-laden C++;
* a kernel **runtime** (``yask_tpu.runtime``): the ``yk_*`` API surface
  (``include/yask_kernel_api.hpp``) — solutions, vars with halo/pad geometry,
  stats, auto-tuning — executing as compiled JAX programs;
* **distribution** (``yask_tpu.parallel``): the reference's MPI rank grid +
  halo exchange (``src/kernel/lib/setup.cpp``, ``halo.cpp``) becomes an N-D
  ``jax.sharding.Mesh`` with ``shard_map`` + ``lax.ppermute`` ghost-cell
  exchange over ICI;
* a **stencil library** (``yask_tpu.stencils``) covering the reference's
  ``src/stencils`` solutions (iso3dfd, ssg, fsg, awp, tti, …).

Nothing in this package is a translation of the reference's C++; file:line
citations in docstrings point at the behavior being matched, not code reused.
"""

__version__ = "0.1.0"

# Public API surface (mirrors the three reference headers:
# yask_common_api.hpp, yask_compiler_api.hpp, yask_kernel_api.hpp).
from yask_tpu.utils.exceptions import YaskException  # noqa: F401
from yask_tpu.utils.idx_tuple import IdxTuple  # noqa: F401
from yask_tpu.utils.fd_coeff import (  # noqa: F401
    get_center_fd_coefficients,
    get_forward_fd_coefficients,
    get_backward_fd_coefficients,
    get_arbitrary_fd_coefficients,
)
from yask_tpu.utils.output import yask_output_factory  # noqa: F401
from yask_tpu.utils.cli import CommandLineParser  # noqa: F401

from yask_tpu.compiler.node_api import yc_node_factory  # noqa: F401
from yask_tpu.compiler.solution import yc_factory, yc_solution  # noqa: F401
from yask_tpu.compiler.solution_base import (  # noqa: F401
    yc_solution_base,
    yc_solution_with_radius_base,
    register_solution,
    get_registered_solutions,
)

from yask_tpu.runtime.factory import yk_factory  # noqa: F401


def quick_run(stencil: str, g: int = 64, steps: int = 10, radius=None,
              mode: str = "auto", **settings):
    """One-liner demo/benchmark: build a registered stencil, seq-init its
    vars, run ``steps`` steps, and return the context (read results via
    ``ctx.get_var(...)`` / ``ctx.get_stats()``).

    >>> ctx = yask_tpu.quick_run("iso3dfd", g=128, steps=20, radius=4)
    >>> print(ctx.get_stats().format())
    """
    fac = yk_factory()
    env = fac.new_env()
    ctx = fac.new_solution(env, stencil=stencil, radius=radius)
    ctx.apply_command_line_options(f"-g {g}")
    ctx.get_settings().mode = mode
    for k, v in settings.items():
        if not hasattr(ctx.get_settings(), k):
            raise YaskException(f"unknown kernel setting '{k}'")
        setattr(ctx.get_settings(), k, v)
    ctx.prepare_solution()
    from yask_tpu.runtime.init_utils import init_solution_vars
    init_solution_vars(ctx)
    if steps > 0:
        ctx.run_solution(0, steps - 1)
    return ctx
