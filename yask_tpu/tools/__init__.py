"""Operational tooling: log scraping, launching, trace analysis.

Counterpart of the reference's ``utils/bin`` Perl tooling (SURVEY §2.4):
``yask_log_to_csv.pl``/``YaskUtils.pm`` → :mod:`yask_tpu.tools.log_to_csv`;
``yask.sh`` launcher → :mod:`yask_tpu.tools.launch`;
``analyze_trace.pl`` → :mod:`yask_tpu.tools.analyze_trace`.
"""
