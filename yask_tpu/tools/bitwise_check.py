"""Cross-backend reproducibility check (the repo's bitwise north star).

Runs the same solution on two JAX backends (e.g. CPU and TPU) from
identical initial state and reports whether results match bitwise, and if
not, the first divergent write (via the trace machinery).

Bitwise agreement requires XLA to avoid reassociation differences across
backends; stencil arithmetic here is pure add/mul chains built in a fixed
order, so divergence localizes real compiler/backend differences rather
than framework bugs — the role ``analyze_trace`` + ``compare_data`` play
for the reference.

Usage::

    python -m yask_tpu.tools.bitwise_check -stencil 3axis -g 32 -steps 4 \
        [-backends cpu,tpu]
"""

from __future__ import annotations

import sys

import numpy as np


def run_on(platform: str, stencil: str, radius, g: int, steps: int):
    import jax
    devs = list(jax.devices(platform))  # lint: devices-ok (in-window tool)
    from yask_tpu import yk_factory
    fac = yk_factory()
    env = fac.new_env(devices=devs[:1])
    ctx = fac.new_solution(env, stencil=stencil, radius=radius)
    ctx.apply_command_line_options(f"-g {g}")
    ctx.prepare_solution()
    from yask_tpu.runtime.init_utils import init_solution_vars
    init_solution_vars(ctx)
    ctx.run_solution(0, steps - 1)
    return {name: np.asarray(ring[-1])
            for name, ring in ctx._state.items()}


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    stencil, g, steps, radius = "3axis", 32, 4, None
    backends = ["cpu", "tpu"]
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "-stencil":
            stencil = argv[i + 1]; i += 2
        elif a == "-g":
            g = int(argv[i + 1]); i += 2
        elif a == "-steps":
            steps = int(argv[i + 1]); i += 2
        elif a == "-radius":
            radius = int(argv[i + 1]); i += 2
        elif a == "-backends":
            backends = argv[i + 1].split(","); i += 2
        else:
            sys.stderr.write(f"unknown arg {a}\n"); return 2

    results = []
    for b in backends:
        try:
            results.append((b, run_on(b, stencil, radius, g, steps)))
        except RuntimeError as e:
            sys.stderr.write(f"backend '{b}' unavailable: {e}\n")
            return 3
    (na, ra), (nb, rb) = results[0], results[1]
    exact = True
    for name in sorted(ra):
        x, y = ra[name], rb[name]
        if x.shape != y.shape:
            print(f"{name}: SHAPE MISMATCH {x.shape} vs {y.shape}")
            exact = False
            continue
        same = np.array_equal(
            x.view(np.uint8) if x.dtype != np.float64 else x,
            y.view(np.uint8) if y.dtype != np.float64 else y)
        if same:
            print(f"{name}: bitwise identical on {na} vs {nb}")
        else:
            d = np.abs(x.astype(np.float64) - y.astype(np.float64))
            idx = np.unravel_index(d.argmax(), d.shape)
            nbit = int((x != y).sum())
            print(f"{name}: {nbit} differing element(s); max |diff| "
                  f"{d.max():.3e} at {tuple(int(v) for v in idx)}")
            exact = False
    print("RESULT:", "BITWISE MATCH" if exact else "DIFFERS")
    return 0 if exact else 1


if __name__ == "__main__":
    sys.exit(main())
