"""Scrape harness logs into CSV.

Counterpart of ``utils/bin/yask_log_to_csv.pl`` + ``utils/lib/YaskUtils.pm``
(reference :33-58): extract the named metrics from one or more run logs into
a CSV for performance tracking, throughput keys first (the reference ranks
"mid" throughput as the primary fitness key).

Usage::

    python -m yask_tpu.tools.log_to_csv run1.log run2.log > perf.csv
"""

from __future__ import annotations

import csv
import re
import sys
from typing import Dict, List

#: Metric keys in priority order (mirrors YaskUtils.pm:40-58 ordering:
#: mid/best throughput first).
KEYS = [
    "mid-throughput (num-points/sec)",
    "best-throughput (num-points/sec)",
    "min-throughput (num-points/sec)",
    "ave-throughput (num-points/sec)",
    "stddev-throughput (num-points/sec)",
    "mid-throughput (GPts/s)",
    "throughput (num-points/sec)",
    "throughput (est-FLOPS)",
    "num-steps-done",
    "elapsed-time (sec)",
    "halo-time (sec)",
    "halo-exchange-round (sec)",
    "halo-pack (sec)",
    "halo-collective (sec)",
    "compile-time (sec)",
    "hbm-bytes-per-point (read+write)",
    "achieved-HBM (GB/s)",
    "hbm-roofline-fraction (%)",
    "pallas-tiling",
    "num-points-per-step",
    "domain",
]

_LINE = re.compile(r"^\s*([\w\- ()/+%]+?):\s*(.+?)\s*$")


def scrape(text: str) -> Dict[str, str]:
    """Pull the last value for each known key out of a log."""
    out: Dict[str, str] = {}
    for line in text.splitlines():
        m = _LINE.match(line)
        if not m:
            continue
        key, val = m.group(1).strip(), m.group(2)
        if key in KEYS:
            out[key] = val
    return out


def logs_to_csv(paths: List[str], out=None) -> None:
    out = out or sys.stdout
    rows = []
    for path in paths:
        with open(path) as f:
            row = scrape(f.read())
        row["log"] = path
        rows.append(row)
    cols = ["log"] + [k for k in KEYS if any(k in r for r in rows)]
    w = csv.DictWriter(out, fieldnames=cols, extrasaction="ignore")
    w.writeheader()
    for r in rows:
        w.writerow(r)


def main() -> None:  # pragma: no cover - thin wrapper
    if len(sys.argv) < 2:
        sys.stderr.write("usage: log_to_csv <log> [log...]\n")
        sys.exit(2)
    logs_to_csv(sys.argv[1:])


if __name__ == "__main__":
    main()
