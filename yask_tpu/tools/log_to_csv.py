"""Scrape harness logs into CSV.

Counterpart of ``utils/bin/yask_log_to_csv.pl`` + ``utils/lib/YaskUtils.pm``
(reference :33-58): extract the named metrics from one or more run logs into
a CSV for performance tracking, throughput keys first (the reference ranks
"mid" throughput as the primary fitness key).

``--ledger`` flattens the unified perf ledger (``PERF_LEDGER.jsonl``,
``yask_tpu.perflab``) instead: one CSV row per ledger row with the
provenance, guard-verdict, and roofline columns spread out — the
spreadsheet view of the append-only history.

Usage::

    python -m yask_tpu.tools.log_to_csv run1.log run2.log > perf.csv
    python -m yask_tpu.tools.log_to_csv --ledger [PERF_LEDGER.jsonl] > perf.csv
    python -m yask_tpu.tools.log_to_csv --traces [TRACE_EVENTS.jsonl] > spans.csv
"""

from __future__ import annotations

import csv
import re
import sys
from typing import Dict, List

#: Metric keys in priority order (mirrors YaskUtils.pm:40-58 ordering:
#: mid/best throughput first).
KEYS = [
    "mid-throughput (num-points/sec)",
    "best-throughput (num-points/sec)",
    "min-throughput (num-points/sec)",
    "ave-throughput (num-points/sec)",
    "stddev-throughput (num-points/sec)",
    "mid-throughput (GPts/s)",
    "throughput (num-points/sec)",
    "throughput (est-FLOPS)",
    "num-steps-done",
    "elapsed-time (sec)",
    "halo-time (sec)",
    "halo-exchange-round (sec)",
    "halo-pack (sec)",
    "halo-collective (sec)",
    "compile-time (sec)",
    "hbm-bytes-per-point (read+write)",
    "achieved-HBM (GB/s)",
    "hbm-roofline-fraction (%)",
    "pallas-tiling",
    "num-points-per-step",
    "domain",
]

_LINE = re.compile(r"^\s*([\w\- ()/+%]+?):\s*(.+?)\s*$")


def scrape(text: str) -> Dict[str, str]:
    """Pull the last value for each known key out of a log."""
    out: Dict[str, str] = {}
    for line in text.splitlines():
        m = _LINE.match(line)
        if not m:
            continue
        key, val = m.group(1).strip(), m.group(2)
        if key in KEYS:
            out[key] = val
    return out


def logs_to_csv(paths: List[str], out=None) -> None:
    out = out or sys.stdout
    rows = []
    for path in paths:
        with open(path) as f:
            row = scrape(f.read())
        row["log"] = path
        rows.append(row)
    cols = ["log"] + [k for k in KEYS if any(k in r for r in rows)]
    w = csv.DictWriter(out, fieldnames=cols, extrasaction="ignore")
    w.writeheader()
    for r in rows:
        w.writerow(r)


#: Ledger columns, identity → value → verdict → roofline →
#: attribution → push/resident → provenance.  ``trace_id`` joins back
#: to the span file; ``attr_shares`` / ``attr_root_secs`` flatten the
#: source:"attribution" rows (empty on every other source); the
#: ``push_*`` / ``resident_*`` columns flatten the pipeline-push and
#: serve-resident A/B rows (model bytes/point, per-arm seconds,
#: achieved bandwidth, queue occupancy — empty elsewhere).
LEDGER_COLS = [
    "key", "value", "unit", "platform", "source", "measured_at",
    "trace_id",
    "guard_status", "guard_baseline", "guard_remeasured",
    "roofline_frac", "hbm_gbps", "hbm_bytes_pp",
    "attr_shares", "attr_root_secs",
    "push_vars", "push_bytes_pp", "push_ratio", "push_secs",
    "achieved_gbs_push", "achieved_gbs_fused", "achieved_gbs_chained",
    "occupancy", "resident_secs", "per_request_secs",
    "git_sha", "load1", "ncpu", "calib_gpts", "cpu_model",
    "device_kind", "jax", "env_fp",
]


def ledger_to_csv(path: str = "", out=None) -> int:
    """Flatten ledger rows (see ``yask_tpu.perflab.ledger``) to CSV;
    returns the number of rows written."""
    from yask_tpu.perflab.ledger import default_ledger_path, read_rows
    out = out or sys.stdout
    rows = read_rows(path or default_ledger_path())
    w = csv.DictWriter(out, fieldnames=LEDGER_COLS, extrasaction="ignore")
    w.writeheader()
    import json

    for r in rows:
        prov = r.get("provenance", {})
        guard = r.get("guard", {})
        roof = r.get("roofline", {})
        extra = r.get("extra", {})
        load = prov.get("loadavg") or [None]
        shares = (extra.get("shares")
                  if r.get("source") == "attribution" else None)
        hbm_model = extra.get("hbm_bytes_model") or {}
        push_vars = extra.get("push_vars")
        w.writerow({
            **{k: r.get(k) for k in ("key", "value", "unit", "platform",
                                     "source", "measured_at",
                                     "trace_id")},
            "attr_shares": (json.dumps(shares, sort_keys=True)
                            if shares else None),
            "attr_root_secs": (extra.get("root_secs")
                               if shares else None),
            "push_vars": (json.dumps(push_vars)
                          if push_vars else None),
            "push_bytes_pp": hbm_model.get("fused_push_bytes_pp"),
            "push_ratio": hbm_model.get("push_ratio"),
            "push_secs": extra.get("push_secs"),
            "achieved_gbs_push": extra.get("achieved_gbs_push"),
            "achieved_gbs_fused": extra.get("achieved_gbs_fused"),
            "achieved_gbs_chained": extra.get("achieved_gbs_chained"),
            "occupancy": extra.get("occupancy"),
            "resident_secs": extra.get("resident_secs"),
            "per_request_secs": extra.get("per_request_secs"),
            "guard_status": guard.get("status"),
            "guard_baseline": guard.get("baseline"),
            "guard_remeasured": guard.get("remeasured"),
            "roofline_frac": roof.get("roofline_frac"),
            "hbm_gbps": roof.get("hbm_gbps"),
            "hbm_bytes_pp": roof.get("hbm_bytes_pp"),
            "git_sha": prov.get("git_sha"),
            "load1": load[0],
            "ncpu": prov.get("ncpu"),
            "calib_gpts": prov.get("calib_gpts"),
            "cpu_model": prov.get("cpu_model"),
            "device_kind": prov.get("device_kind"),
            "jax": prov.get("jax"),
            "env_fp": prov.get("env_fp"),
        })
    return len(rows)


#: Trace columns, identity → placement → timing → payload.
TRACE_COLS = [
    "trace", "span", "parent", "name", "phase",
    "ts", "dur", "pid", "tid", "attrs",
]


def traces_to_csv(path: str = "", out=None) -> int:
    """Flatten obs span rows (``TRACE_EVENTS.jsonl``, schema
    ``yask_tpu.trace/1``) to CSV — attrs as one JSON column; returns
    the number of rows written.  The spreadsheet analog of
    ``tools/obs_report.py``."""
    import json

    from yask_tpu.obs.tracer import default_trace_path, read_spans
    out = out or sys.stdout
    rows = read_spans(path or default_trace_path())
    w = csv.DictWriter(out, fieldnames=TRACE_COLS, extrasaction="ignore")
    w.writeheader()
    for r in rows:
        w.writerow({**{k: r.get(k) for k in TRACE_COLS if k != "attrs"},
                    "attrs": json.dumps(r.get("attrs", {}),
                                        sort_keys=True)})
    return len(rows)


def main() -> None:  # pragma: no cover - thin wrapper
    args = sys.argv[1:]
    if args and args[0] == "--ledger":
        ledger_to_csv(args[1] if len(args) > 1 else "")
        return
    if args and args[0] == "--traces":
        traces_to_csv(args[1] if len(args) > 1 else "")
        return
    if not args:
        sys.stderr.write(
            "usage: log_to_csv <log> [log...] | --ledger [path] | "
            "--traces [path]\n")
        sys.exit(2)
    logs_to_csv(args)


if __name__ == "__main__":
    main()
