"""Run launcher: host/device introspection + harness invocation.

Counterpart of the reference's ``yask.sh`` (``yask.sh:41-98,227``): where the
shell script detects arch/cores/NUMA/GPUs and synthesizes an
``mpirun … numactl … yask_kernel.exe`` command, this launcher detects the
JAX platform and device count, derives a default mesh (ranks = devices, the
way yask.sh defaults ranks to NUMA nodes), sets the environment XLA needs,
and runs the harness — printing the equivalent command line for the log.

Usage::

    python -m yask_tpu.tools.launch -stencil iso3dfd -g 512
"""

from __future__ import annotations

import os
import sys
from typing import List


def detect() -> dict:
    import jax
    devs = jax.devices()  # lint: devices-ok (TPU-session tool, in-window)
    return {
        "platform": devs[0].platform if devs else "none",
        "num_devices": len(devs),
        "device_kind": devs[0].device_kind if devs else "",
    }


def build_args(argv: List[str], info: dict) -> List[str]:
    args = list(argv)
    # Default decomposition: one rank per device over the outer-most dim
    # (yask.sh defaults ranks to NUMA nodes / GPUs the same way).
    if info["num_devices"] > 1 and "-mode" not in args \
            and not any(a.startswith("-nr") for a in args):
        args += ["-mode", "sharded", "-nr_x", str(info["num_devices"])]
    return args


def main(argv: List[str] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    info = detect()
    sys.stdout.write(
        f"yask_tpu launcher: platform={info['platform']} "
        f"devices={info['num_devices']} kind='{info['device_kind']}'\n")
    args = build_args(argv, info)
    sys.stdout.write("equivalent command: python -m yask_tpu.main "
                     + " ".join(args) + "\n")
    from yask_tpu.main import run_harness
    return run_harness(args)


if __name__ == "__main__":
    sys.exit(main())
