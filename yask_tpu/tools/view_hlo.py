"""Inspect the compiled program for a stencil step.

Counterpart of the reference's ``utils/bin/view_asm.pl`` (:26), which
annotates compiler asm output for inner-loop inspection: here the "asm" is
XLA's output — this tool prints the StableHLO (pre-optimization) or the
optimized backend HLO for one compiled step of a solution, so kernel fusion
and collective placement can be inspected.

Usage::

    python -m yask_tpu.tools.view_hlo -stencil 3axis -g 32 [-radius N]
        [-optimized] [-steps K]
"""

from __future__ import annotations

import sys
from typing import List, Optional


def view_hlo(stencil: str, g: int = 32, radius: Optional[int] = None,
             optimized: bool = False, steps: int = 1, out=None) -> str:
    import jax
    from jax import lax
    from yask_tpu.utils.idx_tuple import IdxTuple
    from yask_tpu.compiler.solution_base import create_solution

    sb = create_solution(stencil, radius=radius)
    csol = sb.get_soln().compile()
    dims = csol.ana.domain_dims
    sizes = IdxTuple({d: g for d in dims})
    prog = csol.plan(sizes)
    state = prog.alloc_state()
    dirn = csol.ana.step_dir

    def chunk(state, t0):
        def body(carry, _):
            st, t = carry
            return (prog.step(st, t), t + dirn), None
        (st, _), _ = lax.scan(body, (state, t0), None, length=steps)
        return st

    lowered = jax.jit(chunk).lower(state, 0)
    text = (lowered.compile().as_text() if optimized
            else lowered.as_text())
    if out is not None:
        out.write(text)
    return text


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    stencil, g, radius, optimized, steps = "", 32, None, False, 1
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "-stencil":
            stencil = argv[i + 1]; i += 2
        elif a == "-g":
            g = int(argv[i + 1]); i += 2
        elif a == "-radius":
            radius = int(argv[i + 1]); i += 2
        elif a == "-steps":
            steps = int(argv[i + 1]); i += 2
        elif a == "-optimized":
            optimized = True; i += 1
        else:
            sys.stderr.write(f"unknown arg {a}\n"); return 2
    if not stencil:
        sys.stderr.write("usage: view_hlo -stencil <name> [-g N] "
                         "[-radius N] [-steps K] [-optimized]\n")
        return 2
    view_hlo(stencil, g, radius, optimized, steps, out=sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
