"""Locate the first divergent write between two trace-dump directories.

Counterpart of the reference's ``utils/bin/analyze_trace.pl`` (:26): compare
every point write between two runs (e.g. optimized vs reference, or two
framework versions) and report the first step/var/coordinates where they
diverge — the debugging tool for localizing a miscompiled stencil.

Traces are produced by ``StencilContext.set_trace_dir`` (one ``.npz`` of all
written-var interiors per step). The scan uses the native C++ library when
built (``yt_first_divergence_f32``) and falls back to numpy.

Usage::

    python -m yask_tpu.tools.analyze_trace runA_trace/ runB_trace/ \
        [-rtol 1e-4] [-atol 1e-7]
"""

from __future__ import annotations

import os
import re
import sys
from typing import Optional, Tuple

import numpy as np


def _first_divergence(a: np.ndarray, b: np.ndarray, rtol: float,
                      atol: float) -> int:
    try:
        from yask_tpu import native
        if native.available() and a.dtype == np.float32 \
                and b.dtype == np.float32:
            return native.first_divergence(a, b, rtol, atol)
    except Exception:
        pass
    x = a.astype(np.float64).ravel()
    y = b.astype(np.float64).ravel()
    bad = np.abs(x - y) > (atol + rtol * np.maximum(np.abs(x), np.abs(y)))
    bad |= np.isnan(x) != np.isnan(y)
    idx = np.flatnonzero(bad)
    return int(idx[0]) if idx.size else -1


def _steps(d: str):
    pat = re.compile(r"step_(-?\d+)\.npz$")
    out = []
    for f in os.listdir(d):
        m = pat.match(f)
        if m:
            out.append((int(m.group(1)), os.path.join(d, f)))
    return sorted(out)


def compare_traces(dir_a: str, dir_b: str, rtol: float = 1e-4,
                   atol: float = 1e-7
                   ) -> Optional[Tuple[int, str, Tuple[int, ...], float, float]]:
    """Return (step, var, coords, value_a, value_b) of the first divergent
    write, or None if the traces agree."""
    sa = dict(_steps(dir_a))
    sb = dict(_steps(dir_b))
    for t in sorted(set(sa) & set(sb)):
        da = np.load(sa[t])
        db = np.load(sb[t])
        for var in sorted(set(da.files) & set(db.files)):
            a, b = da[var], db[var]
            if a.shape != b.shape:
                return (t, var, (), float("nan"), float("nan"))
            i = _first_divergence(np.ascontiguousarray(a),
                                  np.ascontiguousarray(b), rtol, atol)
            if i >= 0:
                coords = tuple(int(c) for c in
                               np.unravel_index(i, a.shape))
                return (t, var, coords,
                        float(a[coords]), float(b[coords]))
    return None


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    rtol, atol = 1e-4, 1e-7
    dirs = []
    i = 0
    while i < len(argv):
        if argv[i] == "-rtol":
            rtol = float(argv[i + 1]); i += 2
        elif argv[i] == "-atol":
            atol = float(argv[i + 1]); i += 2
        else:
            dirs.append(argv[i]); i += 1
    if len(dirs) != 2:
        sys.stderr.write("usage: analyze_trace <dirA> <dirB> "
                         "[-rtol R] [-atol A]\n")
        return 2
    res = compare_traces(dirs[0], dirs[1], rtol, atol)
    if res is None:
        print("traces agree (within tolerance)")
        return 0
    t, var, coords, va, vb = res
    print(f"FIRST DIVERGENCE: step {t}, var '{var}', point {coords}: "
          f"{va!r} vs {vb!r}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
