"""Serving pass: static checks over a server-hosted solution profile.

Gated on the ``-serve`` knob (set by ``StencilServer`` on every
context it prepares, or explicitly for checker runs) exactly like the
ckpt pass gates on the supervision knobs — a non-serving
``make check -all_stencils`` stays silent.

Rules (catalog in ``docs/checking.md``):

* ``SERVE-BATCH-INCOMPAT`` — requests against this profile can never
  co-batch: the configured mode fails
  :func:`~yask_tpu.runtime.ensemble.ensemble_feasible` (sharded modes
  decompose state over the mesh; ``ref`` is the sequential oracle).
  The server still answers — every request just rides an
  occupancy-1 execution, so the micro-batching window only adds
  latency (warn).  When the mode batches, an info records the batching
  identity (mode + pallas-variant key) requests must share to group —
  two profiles with mismatched variant keys never co-batch even at
  the same geometry.
* ``SERVE-BUCKET-INELIGIBLE`` — the profile co-batches same-geometry
  requests but can NOT host masked sub-domain tenants
  (:func:`~yask_tpu.serve.buckets.bucket_cobatch_feasible` — the ONE
  definition the open-session decision also consults): sessions at
  other geometries will decline onto exact profiles and never share
  this profile's executions (info — the server still answers; the
  structured decline reason also rides every batched journal row).
  When bucket hosting IS feasible, an info records the bucket-ladder
  rung the profile geometry maps to.
* ``SERVE-CACHE-COLD`` — ``YT_COMPILE_CACHE`` is unset for a server
  launch: warm restart is the serving layer's availability story (a
  restarted server answers its first request with zero lowerings),
  and without the disk cache every restart re-traces and re-lowers
  every profile (warn).
* ``SERVE-AUTOSCALE-BOUNDS`` — the fleet autoscaler is enabled
  (``YT_FLEET_AUTOSCALE``) with incoherent knobs:
  ``YT_FLEET_MIN_WORKERS`` above ``YT_FLEET_MAX_WORKERS`` (error —
  the policy clamps, but the operator asked for an impossible fleet),
  a zero ``YT_FLEET_SCALE_COOLDOWN`` (warn — nothing damps
  up/down flapping but the idle-tick counter), or both scale-up
  triggers disabled (warn — the fleet can only ever shrink).

Pure host work: a mode property, an equation scan, and an environment
read — no plan, no execution.
"""

from __future__ import annotations

from yask_tpu.checker.diagnostics import CheckReport

PASS = "serve"


def check_serve(report: CheckReport, ctx) -> None:
    report.ran(PASS)
    opts = ctx._opts
    if not getattr(opts, "serve", False):
        return  # not server-hosted: the pass is a true no-op

    from yask_tpu.runtime.ensemble import ensemble_feasible
    mode = getattr(ctx, "_mode", None) or opts.mode
    ok, why = ensemble_feasible(ctx)
    if not ok:
        report.add("SERVE-BATCH-INCOMPAT", "warn",
                   f"requests against this profile can never "
                   f"co-batch: {why} — every request rides an "
                   "occupancy-1 execution and the batching window "
                   "only adds latency",
                   detail={"mode": mode, "reason": why})
    else:
        try:
            variant = list(ctx._pallas_variant_key())
        except Exception:  # noqa: BLE001 - identity note must not fail
            variant = []
        report.add("SERVE-BATCH-INCOMPAT", "info",
                   f"mode '{mode}' co-batches; requests group on "
                   "(profile, mode, variant key, step range) — "
                   "profiles with different variant keys never share "
                   "a vmapped execution",
                   detail={"mode": mode, "variant_key": variant})

    from yask_tpu.serve.buckets import (bucket_cobatch_feasible,
                                        bucket_for)
    bok, bwhy = bucket_cobatch_feasible(ctx)
    if ok and not bok:
        report.add("SERVE-BUCKET-INELIGIBLE", "info",
                   f"profile co-batches same-geometry requests but "
                   f"cannot host masked sub-domain tenants: {bwhy} — "
                   "mixed-geometry sessions decline onto exact "
                   "profiles",
                   detail={"mode": mode, "reason": bwhy})
    elif ok and bok:
        try:
            gs = {d: int(v) for d, v
                  in opts.global_domain_sizes.items()}
            rungs = {d: bucket_for(v) for d, v in gs.items()}
        except Exception:  # noqa: BLE001 - identity note must not fail
            gs, rungs = {}, {}
        report.add("SERVE-BUCKET-INELIGIBLE", "info",
                   "profile can host masked sub-domain tenants; "
                   "sessions opened at smaller geometries on the same "
                   "bucket rung co-batch with it bit-identically",
                   detail={"mode": mode, "g": gs, "rung": rungs})

    from yask_tpu.cache import cache_dir
    if not cache_dir():
        report.add("SERVE-CACHE-COLD", "warn",
                   "YT_COMPILE_CACHE is unset for a server launch: a "
                   "restarted server re-traces and re-lowers every "
                   "profile instead of answering its first request "
                   "from the disk cache with zero lowerings",
                   detail={"env": "YT_COMPILE_CACHE"})

    from yask_tpu.serve.autoscale import (fleet_autoscale_enabled,
                                          fleet_max_workers,
                                          fleet_min_workers,
                                          fleet_scale_cooldown,
                                          fleet_scale_up_burn,
                                          fleet_scale_up_queue)
    if fleet_autoscale_enabled():
        lo, hi = fleet_min_workers(), fleet_max_workers()
        # the accessors clamp (max floors at min) — read the raw env
        # to catch the operator asking for an impossible fleet
        import os
        try:
            raw_hi = int(float(os.environ.get(
                "YT_FLEET_MAX_WORKERS", "") or hi))
        except ValueError:
            raw_hi = hi
        knobs = {"min_workers": lo, "max_workers": hi,
                 "cooldown_secs": fleet_scale_cooldown(),
                 "up_queue": fleet_scale_up_queue(),
                 "up_burn": fleet_scale_up_burn()}
        if raw_hi < lo:
            report.add("SERVE-AUTOSCALE-BOUNDS", "error",
                       f"YT_FLEET_MIN_WORKERS={lo} exceeds "
                       f"YT_FLEET_MAX_WORKERS={raw_hi}: the policy "
                       "clamps max up to min, but the operator asked "
                       "for an impossible fleet",
                       detail={**knobs, "raw_max_workers": raw_hi})
        elif fleet_scale_cooldown() == 0.0:
            report.add("SERVE-AUTOSCALE-BOUNDS", "warn",
                       "YT_FLEET_SCALE_COOLDOWN=0: nothing damps "
                       "up/down flapping but the idle-tick counter",
                       detail=knobs)
        elif fleet_scale_up_queue() == 0 and fleet_scale_up_burn() == 0:
            report.add("SERVE-AUTOSCALE-BOUNDS", "warn",
                       "both scale-up triggers disabled "
                       "(YT_FLEET_SCALE_UP_QUEUE=0 and "
                       "YT_FLEET_SCALE_UP_BURN=0): the fleet can only "
                       "ever shrink",
                       detail=knobs)
        else:
            report.add("SERVE-AUTOSCALE-BOUNDS", "info",
                       f"autoscaler bounds coherent: "
                       f"[{lo}, {hi}] workers, cooldown "
                       f"{fleet_scale_cooldown():g}s",
                       detail=knobs)
