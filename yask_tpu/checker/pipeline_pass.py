"""Pipeline pass: plan-only fuse/decline reproduction for
cross-solution pipeline fusion (``yask_tpu.ops.pipeline``).

Reads the SAME plan dict the executor decides from
(:func:`yask_tpu.ops.pipeline.pipeline_plan` — one code path, the
checker cannot drift from the runtime) and renders it as diagnostics:

* ``PIPELINE-ENGAGED``    (info)  — the chain fuses into one program;
  detail carries the executor's decision (``fused``), the stage list,
  and the pallas plan summary when one was made;
* ``PIPELINE-INFEASIBLE`` (warn)  — one diagnostic per decline reason
  (structural ineligibility, no feasible pallas plan, failed merge
  prepare); warn, not error, because the pipeline still RUNS — it
  auto-falls back to the host-chained schedule;
* ``PIPELINE-VMEM-SPILL`` (error) — the merged chain's live-value
  model exceeds the Mosaic scoped limit (the round-3 register-spill
  OOM class): launching the fused arm would burn a relay window on a
  doomed compile.

When the context is in a Pallas mode the plan is re-made at the
checker budget (the REAL-TPU default, never the CPU-interpret 100 MiB
— a CPU-host check must answer for Mosaic), so a laptop preflight
predicts the hardware verdict.
"""

from __future__ import annotations

from yask_tpu.checker.diagnostics import CheckReport
from yask_tpu.utils.exceptions import YaskException

PASS = "pipeline"


def check_pipeline(report: CheckReport, ctx) -> None:
    report.ran(PASS)
    pipe = getattr(ctx, "_pipeline", None)
    plan = getattr(ctx, "_pipeline_plan", None)
    if pipe is None and plan is None:
        report.add("PIPELINE-SKIPPED", "info",
                   "context is not part of a solution pipeline")
        return
    if pipe is not None:
        from yask_tpu.checker.vmem import checker_budget
        from yask_tpu.ops.pipeline import pipeline_plan
        try:
            plan = pipeline_plan(pipe, budget=checker_budget(ctx))
        except YaskException as e:
            report.add("PIPELINE-INFEASIBLE", "warn",
                       f"pipeline planning failed: {e}",
                       detail={"message": str(e)})
            return
    _render_plan(report, plan)


def check_pipeline_plan(pipe, budget=None) -> CheckReport:
    """Standalone helper: a CheckReport straight from a
    :class:`~yask_tpu.ops.pipeline.SolutionPipeline` (prepared or
    not), for callers without a context in hand — e.g. a structurally
    ineligible pipe that never built a fused context."""
    from yask_tpu.ops.pipeline import pipeline_plan
    report = CheckReport(config={"pipeline": pipe.name,
                                 "stages": list(pipe.stage_names)})
    report.ran(PASS)
    if pipe._merged is None:
        plan = {"fused": False, "eligible": False, "sig": pipe.signature(),
                "stages": list(pipe.stage_names), "mode": None,
                "reasons": [dict(r) for r in pipe._struct_reasons]}
    else:
        plan = pipeline_plan(pipe, budget=budget)
    _render_plan(report, plan)
    return report


def _render_plan(report: CheckReport, plan) -> None:
    for r in plan.get("reasons", ()):
        code = r.get("code")
        det = {k: v for k, v in r.items() if k not in ("msg",)}
        if r.get("ok"):
            # push decisions are worth surfacing even when ok: engaged
            # means stale rings (the caller should know), ineligible
            # explains why the HBM halving did not happen
            if code == "pipeline-push-engaged":
                report.add("PIPELINE-PUSH-ENGAGED", "info", r["msg"],
                           detail=det)
            elif code == "pipeline-push-ineligible":
                report.add("PIPELINE-PUSH-INFEASIBLE", "info", r["msg"],
                           detail=det)
            continue
        if code == "pipeline-vmem-spill":
            report.add("PIPELINE-VMEM-SPILL", "error", r["msg"],
                       detail=det)
        elif code == "pipeline-push-vmem-spill":
            report.add("PIPELINE-PUSH-VMEM-SPILL", "error", r["msg"],
                       detail=det)
        else:
            report.add("PIPELINE-INFEASIBLE", "warn",
                       f"[{r['code']}] {r['msg']}",
                       detail=det)
    if plan.get("fused"):
        det = {"fused": True, "sig": plan.get("sig"),
               "stages": plan.get("stages"),
               "mode": plan.get("mode")}
        if "pallas" in plan:
            det["pallas"] = plan["pallas"]
        if "hbm_model" in plan:
            det["hbm_model"] = plan["hbm_model"]
        report.add("PIPELINE-ENGAGED", "info",
                   f"{len(plan.get('stages', ()))}-stage chain fuses "
                   f"into one {plan.get('mode')} program "
                   f"(sig {plan.get('sig')})", detail=det)
    else:
        report.add("PIPELINE-ENGAGED", "info",
                   "pipeline runs the host-chained schedule "
                   "(fused=False)",
                   detail={"fused": False, "sig": plan.get("sig")})
