"""Checkpoint/supervision pass: static checks over the ``-ckpt_every``
/ ``-watchdog_every`` / ``-run_deadline`` knobs, without executing.

Rules (catalog in ``docs/checking.md``):

* ``CKPT-DIR`` — cadence is on but no checkpoint directory resolves
  (``-ckpt_dir`` empty and ``YT_CKPT_DIR`` unset): the in-memory
  rollback still works, but a killed process cannot kill-resume
  (warn); or the resolved directory cannot be created/written (error).
* ``CKPT-CADENCE`` — the cadence splits fused K-groups
  (``ckpt_every % wf_steps != 0``): every supervised chunk boundary
  forces a remainder group, so the cadence should be a multiple of the
  fusion depth (warn).
* ``CKPT-DEADLINE`` — a heartbeat deadline is set with no checkpoint
  cadence: the deadline then spans the WHOLE run in one chunk, and a
  trip loses everything back to the entry snapshot (warn).
* ``CKPT-LADDER`` — the restore-compat/ladder note (info): the
  degradation ladder the supervision loop would walk from the
  configured mode, and why cross-mode restore is sound (ring depths,
  interior geometry, and dtype derive from the solution analysis, not
  the mode — the checkpoint stores interiors only, and pads are
  identically zero in every mode).

Pure host work: settings + environment only, no plan needed.
"""

from __future__ import annotations

import os

from yask_tpu.checker.diagnostics import CheckReport

PASS = "ckpt"


def check_ckpt(report: CheckReport, ctx) -> None:
    report.ran(PASS)
    opts = ctx._opts
    cad = int(getattr(opts, "ckpt_every", 0) or 0)
    wd = int(getattr(opts, "watchdog_every", 0) or 0)
    ddl = int(getattr(opts, "run_deadline_secs", 0) or 0)
    if cad <= 0 and wd <= 0 and ddl <= 0:
        return  # supervision off: -ckpt_every 0 is a true no-op

    from yask_tpu.resilience.checkpoint import (default_ckpt_dir,
                                                degradation_ladder)
    mode = getattr(ctx, "_mode", None) or opts.mode

    if cad > 0:
        d = getattr(opts, "ckpt_dir", "") or default_ckpt_dir()
        if not d:
            report.add("CKPT-DIR", "warn",
                       f"-ckpt_every {cad} with no checkpoint directory "
                       "(-ckpt_dir / YT_CKPT_DIR): in-memory rollback "
                       "still works, but a killed process cannot "
                       "kill-resume from disk",
                       detail={"ckpt_every": cad})
        else:
            probe = d if os.path.isdir(d) else os.path.dirname(
                os.path.abspath(d)) or "."
            if not os.access(probe, os.W_OK):
                report.add("CKPT-DIR", "error",
                           f"checkpoint directory {d!r} is not writable "
                           "— every cadence save would fault",
                           detail={"dir": d})

    wf = int(getattr(opts, "wf_steps", 0) or 0)
    if cad > 0 and wf > 1 and cad % wf != 0:
        report.add("CKPT-CADENCE", "warn",
                   f"-ckpt_every {cad} is not a multiple of wf_steps "
                   f"{wf}: every supervised chunk boundary splits a "
                   "fused K-group into remainder groups",
                   detail={"ckpt_every": cad, "wf_steps": wf})

    if ddl > 0 and cad <= 0 and wd <= 0:
        report.add("CKPT-DEADLINE", "warn",
                   f"-run_deadline {ddl}s with neither a checkpoint "
                   "cadence nor a watchdog: the deadline spans the "
                   "whole run as ONE chunk, and a trip rolls back to "
                   "the entry snapshot (step 0 of this run)",
                   detail={"run_deadline_secs": ddl})

    ladder = degradation_ladder(mode)
    report.add("CKPT-LADDER", "info",
               (f"mode '{mode}' degrades via {' → '.join(ladder)} on a "
                if ladder else
                f"mode '{mode}' has no degradation ladder (already the "
                "floor) — a ")
               + "classified mid-run fault; cross-mode restore is sound "
               "because checkpoints store interiors only (ring depth, "
               "interior geometry, and dtype derive from the solution, "
               "not the mode; pads are identically zero everywhere)",
               detail={"mode": mode, "ladder": ladder,
                       "ckpt_every": cad, "watchdog_every": wd,
                       "run_deadline_secs": ddl})
