"""The checker's rule-id registry — one declared catalog per pass.

Rule ids are a public, stable contract (``docs/checking.md``: "new
rules may be added, existing ids are never re-purposed"), but until
round 21 the ids only existed as string literals scattered across the
pass modules — nothing stopped a typo'd id, a silent rename, or a rule
that fired without a catalog row.  This module declares the full set,
and ``tests/test_checker_rules.py`` enforces the contract three ways:

* every literal ``report.add("RULE", ...)`` site in ``yask_tpu/
  checker/`` names a declared rule (AST scan — a typo cannot ship);
* the *dynamically constructed* ids are declared too: the
  ``vmem._classify_plan_error`` return set, the races pass's
  ``RACE-CYCLE``/``ANALYSIS-FAILED`` pair, and every planner reason
  code (scanned out of ``build_pallas_chunk``) mapped through
  ``explain._rule_of``;
* every declared rule has a row in ``docs/checking.md``.

Ids are unique across passes; the only sanctioned sharing is
:data:`CORE` (``PALLAS-APPLICABLE`` / ``PLAN-FAILED``), which both the
``run_checks`` entry itself and the mosaic/vmem passes may emit — a
plan failure is not owned by any one pass.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Tuple

#: emitted by ``run_checks`` itself (geometry-planning failures) and
#: re-usable by any pass that surfaces the same condition
CORE: Tuple[str, ...] = ("PALLAS-APPLICABLE", "PLAN-FAILED")

MOSAIC: Tuple[str, ...] = (
    "MOSAIC-SKIPPED", "MOSAIC-ALIGN-OFF", "MOSAIC-MISC-FIRST",
    "MOSAIC-SMEM", "MOSAIC-LANE-ALIGN", "MOSAIC-MINOR-DIM",
    "MOSAIC-SUBLANE-ALIGN", "MOSAIC-KERNEL-OPS",
)

#: includes the ``_classify_plan_error`` mapping targets — the planner
#: rejection classes are vmem-pass findings
VMEM: Tuple[str, ...] = (
    "VMEM-SKIPPED", "VMEM-OK", "VMEM-SPILL", "VMEM-SPILL-MARGIN",
    "VMEM-TILE-OVER-BUDGET", "VMEM-PIPE-OVER-BUDGET",
    "PALLAS-BLOCK-FIT", "PAD-COVERAGE", "SKEW-INFEASIBLE",
    "TRAPEZOID-INFEASIBLE", "TRAPEZOID-VMEM-SPILL",
    "TRAPEZOID-RESIDENCY-OK", "TRAPEZOID-WRITE-ALIGN",
    "TRAPEZOID-WRITE-ALIGN-OK",
)

RACES: Tuple[str, ...] = (
    "RACE-MISSING-DIM", "RACE-SAME-POINT", "RACE-WAW-ORDER",
    "RING-DEPTH", "SCRATCH-HALO", "RACE-CYCLE", "ANALYSIS-FAILED",
)

DISTRIBUTED: Tuple[str, ...] = (
    "DIST-SKIPPED", "DIST-GEOMETRY", "DIST-MINOR-SHARD",
    "DIST-GHOST-PAD", "DIST-SKEW-MARGIN", "DIST-SKEW-COVERED",
    "OVERLAP-ENGAGED", "OVERLAP-INFEASIBLE", "OVERLAP-OFF",
    "COMM-PLAN", "COMM-ORDER", "COMM-DCN-ORDER", "COMM-SERIAL",
)

CACHE: Tuple[str, ...] = ("CACHE-STALE", "ENSEMBLE-INFEASIBLE")

CKPT: Tuple[str, ...] = ("CKPT-DIR", "CKPT-CADENCE", "CKPT-DEADLINE",
                         "CKPT-LADDER")

SERVE: Tuple[str, ...] = ("SERVE-BATCH-INCOMPAT",
                          "SERVE-BUCKET-INELIGIBLE", "SERVE-CACHE-COLD",
                          "SERVE-AUTOSCALE-BOUNDS")

PIPELINE: Tuple[str, ...] = ("PIPELINE-SKIPPED", "PIPELINE-INFEASIBLE",
                             "PIPELINE-VMEM-SPILL", "PIPELINE-ENGAGED",
                             "PIPELINE-PUSH-ENGAGED",
                             "PIPELINE-PUSH-INFEASIBLE",
                             "PIPELINE-PUSH-VMEM-SPILL")

#: every structured reason code ``build_pallas_chunk`` can record —
#: the explain pass republishes each as ``EXPLAIN-<CODE>``.  The
#: conformance test AST-scans the planner for ``{"code": ...}``
#: literals and fails on any code missing here (planner↔registry
#: drift check).
PLAN_REASON_CODES: Tuple[str, ...] = (
    "region_restricted",
    "skew_engaged", "skew_gate_rejected", "skew_ineligible",
    "skew_forced", "skew_disabled", "skew_fallback",
    "trapezoid_forced", "trapezoid_engaged", "trapezoid_gate_rejected",
    "trapezoid_ineligible", "trapezoid_fallback", "trapezoid_diamond",
    "block_fitted", "block_shrunk",
    "pipe_in_on", "pipe_in_off", "pipe_out_on", "pipe_out_off",
    "push_engaged", "push_ineligible", "push_disabled", "push_forced",
)


def _explain_rules() -> Tuple[str, ...]:
    from yask_tpu.checker.explain import _rule_of
    fixed = ("EXPLAIN-MODE", "EXPLAIN-PALLAS-FALLBACK",
             "EXPLAIN-PLAN-FAILED", "EXPLAIN-TILING")
    return fixed + tuple(_rule_of(c) for c in PLAN_REASON_CODES)


def all_rules() -> Dict[str, Tuple[str, ...]]:
    """Pass name → declared rule ids (``core`` holds the shared
    entry-point rules)."""
    return {
        "core": CORE,
        "mosaic": MOSAIC,
        "vmem": VMEM,
        "races": RACES,
        "distributed": DISTRIBUTED,
        "cache": CACHE,
        "ckpt": CKPT,
        "serve": SERVE,
        "pipeline": PIPELINE,
        "explain": _explain_rules(),
    }


def flat_rules() -> FrozenSet[str]:
    """Every declared rule id, flattened."""
    out = set()
    for ids in all_rules().values():
        out.update(ids)
    return frozenset(out)
