"""CLI for the static checker.

Usage::

    python -m yask_tpu.checker -stencil iso3dfd -radius 8 -g 512 \
        -mode pallas -wf_steps 2 [-vmem_mb 120] [-json] [-verbose]
    python -m yask_tpu.checker -all_stencils          # zero-false-error
    python -m yask_tpu.checker -list

All kernel options (``-g``, ``-b``, ``-mode``, ``-wf_steps``,
``-vmem_mb``, ``-nr``, …) pass through to the solution settings, same
as the harness.  Exit codes: 0 = no errors, 1 = errors found, 2 =
usage error.  Nothing executes and nothing allocates — checking a 512³
configuration costs geometry arithmetic, not gigabytes.
"""

from __future__ import annotations

import sys
from typing import List, Optional

from yask_tpu.utils.cli import CommandLineParser
from yask_tpu.utils.exceptions import YaskException


class CheckerSettings:
    def __init__(self):
        self.stencil = ""
        self.radius = 0
        self.json = False
        self.verbose = False
        self.all_stencils = False
        self.list_stencils = False
        self.help = False

    def add_options(self, p: CommandLineParser) -> None:
        p.add_string_option("stencil", "Registered stencil name.",
                            self, "stencil")
        p.add_int_option("radius", "Stencil radius (0 = default).",
                         self, "radius")
        p.add_bool_option("json", "Emit the machine-readable report "
                          "(schema yask_tpu.checker/1).", self, "json")
        p.add_bool_option("verbose", "Show info-level diagnostics "
                          "(the explain pass) in text output.",
                          self, "verbose")
        p.add_bool_option("all_stencils", "Sweep every registered "
                          "stencil (jit + pallas where applicable) with "
                          "the given kernel options; nonzero exit on "
                          "any error.", self, "all_stencils")
        p.add_bool_option("list", "List registered stencils.",
                          self, "list_stencils")
        p.add_bool_option("help", "Print help.", self, "help")


def _build(stencil: str, radius: int, extra_args: List[str]):
    from yask_tpu import yk_factory
    fac = yk_factory()
    env = fac.new_env()
    ctx = fac.new_solution(env, stencil=stencil, radius=radius or None)
    rest = ctx.apply_command_line_options(extra_args)
    if rest:
        raise YaskException(f"unrecognized options: {' '.join(rest)}")
    return ctx


def run_checker(argv: Optional[List[str]] = None, out=None) -> int:
    out = out or sys.stdout
    opts = CheckerSettings()
    p = CommandLineParser()
    opts.add_options(p)
    rest = p.parse_args(list(argv if argv is not None else sys.argv[1:]))

    if opts.help:
        out.write("yask_tpu.checker options:\n")
        p.print_help(out)
        out.write("\nplus all kernel options (-g, -d, -b, -nr, -mode, "
                  "-wf_steps, -vmem_mb, ...):\n")
        return 0
    from yask_tpu.compiler.solution_base import get_registered_solutions
    if opts.list_stencils:
        out.write("\n".join(get_registered_solutions()) + "\n")
        return 0

    from yask_tpu.checker import run_checks

    if opts.all_stencils:
        # Known-good sweep: every registered stencil in jit mode plus
        # pallas where applicable; any error fails the run.  The per-
        # stencil default radius and sizes keep each config realistic.
        from yask_tpu.ops.pallas_stencil import pallas_applicable
        if not any(a.startswith(("-g", "-d")) for a in rest):
            rest = ["-g", "32"] + list(rest)
        failures = 0
        for name in get_registered_solutions():
            for mode in ("jit", "pallas"):
                try:
                    ctx = _build(name, opts.radius, list(rest))
                except YaskException as e:
                    out.write(f"{name}: BUILD FAILED: {e}\n")
                    failures += 1
                    break
                if mode == "pallas":
                    ok, _why = pallas_applicable(ctx._csol)
                    if not ok:
                        continue  # fallback is expected, not an error
                    ctx.get_settings().wf_steps = max(
                        ctx.get_settings().wf_steps, 2)
                ctx.get_settings().mode = mode
                report = run_checks(ctx)
                n_err = len(report.errors)
                status = "FAIL" if n_err else "ok"
                out.write(f"{name:24s} {mode:7s} {status}"
                          + (f" ({n_err} error(s))" if n_err else "")
                          + "\n")
                if n_err:
                    for d in report.errors:
                        out.write("    " + d.format() + "\n")
                    failures += 1
        out.write(f"all_stencils sweep: "
                  f"{'FAIL' if failures else 'clean'}\n")
        return 1 if failures else 0

    if not opts.stencil:
        out.write("error: -stencil <name> required; -list to "
                  "enumerate, -all_stencils to sweep.\n")
        return 2

    ctx = _build(opts.stencil, opts.radius, list(rest))
    report = run_checks(ctx)
    if opts.json:
        out.write(report.json_str() + "\n")
    else:
        out.write(report.render(verbose=opts.verbose))
    return 0 if report.ok() else 1


def main() -> None:  # pragma: no cover - thin wrapper
    try:
        sys.exit(run_checker())
    except YaskException as e:
        sys.stderr.write(f"error: {e}\n")
        sys.exit(2)
    except BrokenPipeError:   # |head closed the pipe — not an error
        sys.exit(0)


if __name__ == "__main__":
    main()
