"""Mosaic-legality pass: the probed TPU TC rules as executable checks.

Provenance: every rule here encodes a constraint probed on TPU v5e
during round 3 (see CLAUDE.md "Mosaic TC rules" and docs/checking.md):
DMA windows on HBM/ANY refs need lane (last-axis) sizes and offsets
that are 128-multiples and sublane (2nd-last) 8-multiples (f32;
dtype-scaled via ``tpu_tile_dims``), misc axes must be physically
first, vars whose last domain dim is not the solution minor cannot be
windowed, and no-domain-dim vars ride SMEM.  ``VarGeom`` normally
*constructs* geometry that satisfies all of this when planned with
``mosaic_align=True``; this pass proves the property of a concrete
plan instead of trusting the construction — a planner regression (or a
plan made with ``mosaic_align=False`` fed to the pallas path) turns
into diagnostics here rather than an on-hardware Mosaic crash.
"""

from __future__ import annotations

from yask_tpu.backend import get_capability
from yask_tpu.checker.diagnostics import CheckReport

PASS = "mosaic"


def _supported_nodes():
    """Expr node types the in-kernel evaluator (``_TileEval``) lowers —
    anything outside the backend's ``kernel_expr_nodes`` vocabulary
    cannot be expressed with the legal Mosaic patterns (lax.pad +
    broadcasted_iota masks + jnp.where; no dynamic_update_slice, no
    scatter) and would die in the generator."""
    return get_capability().kernel_expr_nodes


def _walk_nodes(e):
    yield e
    for attr in ("args", ):
        for a in getattr(e, attr, ()) or ():
            yield from _walk_nodes(a)
    for attr in ("lhs", "rhs", "arg", "cond", "step_cond"):
        a = getattr(e, attr, None)
        if a is not None and hasattr(a, "skey"):
            yield from _walk_nodes(a)


def check_mosaic(report: CheckReport, ctx, program) -> None:
    """Run the Mosaic-legality rules over a planned program."""
    report.ran(PASS)
    mode = ctx._mode
    if mode not in ("pallas", "shard_pallas"):
        report.add("MOSAIC-SKIPPED", "info",
                   f"mode '{mode}' uses no manual Mosaic DMA; lane/"
                   "sublane legality does not apply")
        return

    from yask_tpu.ops.pallas_stencil import pallas_applicable
    ok, why = pallas_applicable(ctx._csol)
    if not ok:
        report.add("PALLAS-APPLICABLE", "error",
                   f"solution cannot use the {mode} path: {why}",
                   detail={"reason": why})

    if not getattr(program, "mosaic_align", True):
        report.add("MOSAIC-ALIGN-OFF", "error",
                   "program was planned with mosaic_align=False but the "
                   f"'{mode}' mode issues manual DMAs on tiled HBM "
                   "memrefs; windows would be unaligned (probed v5e "
                   "rule)")

    from yask_tpu.compiler.lowering import tpu_tile_dims
    sub_t, lane_t = tpu_tile_dims(program.dtype)
    minor = program.ana.domain_dims[-1] if program.ana.domain_dims else None

    for name in sorted(program.geoms):
        g = program.geoms[name]
        # misc axes must be physically FIRST (VarGeom invariant): a misc
        # axis in the last-two (tiled) positions of a domain-dim var
        # would put tiny extents on the lane/sublane tiles.
        seen_domain = False
        for dn, kind in g.axes:
            if kind == "domain":
                seen_domain = True
            elif seen_domain:
                report.add("MOSAIC-MISC-FIRST", "error",
                           f"misc axis '{dn}' follows a domain axis in "
                           f"the physical order of var '{name}' — misc "
                           "axes must be physically first (element/"
                           "slice APIs translate declared→physical)",
                           var=name, dim=dn)
        if not g.domain_dims:
            report.add("MOSAIC-SMEM", "info",
                       f"var '{name}' has no domain dims: rides SMEM "
                       "with static scalar reads (no DMA, no VMEM "
                       "tile)", var=name)
            continue
        if g.is_scratch:
            continue  # scratch tiles never touch HBM: unconstrained
        # lane (last physical) axis: the DMA fetches it WHOLE, and a
        # full-extent slice of an array whose lane total is not a
        # 128-multiple is itself an unaligned window (physical tiled
        # layout ≠ logical extent — probed v5e).
        lane_dim, lane_kind = g.axes[-1]
        if g.shape[-1] % lane_t != 0:
            report.add("MOSAIC-LANE-ALIGN", "error",
                       f"var '{name}' lane axis '{lane_dim}' has total "
                       f"extent {g.shape[-1]}, not a multiple of "
                       f"{lane_t} — full-extent DMA windows on it are "
                       "unaligned (tiled physical layout)",
                       var=name, dim=lane_dim,
                       detail={"extent": g.shape[-1], "lane_t": lane_t})
        if lane_kind == "domain" and minor is not None \
                and lane_dim != minor:
            report.add("MOSAIC-MINOR-DIM", "error",
                       f"var '{name}' lane axis is '{lane_dim}' but the "
                       f"solution minor is '{minor}': lane windows "
                       "would need pid-dependent non-128 offsets",
                       var=name, dim=lane_dim)
        # sublane (2nd-last) axis, when it is a lead domain dim, gets
        # 8-aligned windows: origin and total must be sub_t multiples
        # (VarGeom rounds the origin and adds 2·sub_t slab slack).
        if len(g.axes) >= 2:
            sdn, skind = g.axes[-2]
            if skind == "domain" and sdn != minor:
                if g.origin[sdn] % sub_t != 0:
                    report.add("MOSAIC-SUBLANE-ALIGN", "error",
                               f"var '{name}' sublane origin in dim "
                               f"'{sdn}' is {g.origin[sdn]}, not a "
                               f"multiple of {sub_t} — DMA window "
                               "offsets on the sublane axis must be "
                               "tile-aligned", var=name, dim=sdn,
                               detail={"origin": g.origin[sdn],
                                       "sub_t": sub_t})
                ax = g.axis_of(sdn)
                if g.shape[ax] % sub_t != 0:
                    report.add("MOSAIC-SUBLANE-ALIGN", "error",
                               f"var '{name}' sublane total extent in "
                               f"dim '{sdn}' is {g.shape[ax]}, not a "
                               f"multiple of {sub_t}", var=name,
                               dim=sdn,
                               detail={"extent": g.shape[ax],
                                       "sub_t": sub_t})

    # forbidden in-kernel patterns: the tile evaluator only lowers the
    # node vocabulary below (everything else would need
    # dynamic_update_slice / scatter, which Mosaic TC rejects — static
    # region inserts go through lax.pad + broadcasted_iota instead).
    supported = _supported_nodes()
    for eq in ctx._csol.soln.get_equations():
        for node in _walk_nodes(eq):
            tname = type(node).__name__
            if tname not in supported:
                report.add("MOSAIC-KERNEL-OPS", "error",
                           f"equation '{eq.format_simple()}' contains "
                           f"a {tname} node the in-kernel evaluator "
                           "cannot lower with Mosaic-legal patterns",
                           var=eq.lhs.var_name(),
                           detail={"node": tname})
