"""VMEM-feasibility pass: the static budget model, per ladder rung.

Runs the REAL pallas planner (``build_pallas_chunk(plan_only=True)``)
for each VMEM-budget rung the configuration may use and applies the
live-value model on top: Mosaic keeps roughly a second copy of the
tiles as live SSA values (probed v5e, round 3), so a kernel whose tiles
fit the planning budget can still die in compile when
``2 × tile_bytes`` exceeds the scoped limit the runtime passes
(``vmem_limit_bytes = min(128 MiB, 2 × budget)``) — the register-spill
OOM that cost a round-3 relay window at 512³ r=8 K=2.  That class is
flagged ``error`` here, statically, before any launch.

The plan dict already accounts for input rings, workspace, scratch,
skew carry rings, and pipeline parity staging (input prefetch doubling
+ parity-doubled output tiles), because it comes from the planner
itself — the model cannot drift from the code it predicts.
"""

from __future__ import annotations

from yask_tpu.backend import get_capability
from yask_tpu.checker.diagnostics import CheckReport
from yask_tpu.utils.exceptions import YaskException

PASS = "vmem"

#: spill-headroom fraction: live ≥ this share of the limit gets a warn
#: even when it still fits (compile-time register allocation is not
#: exactly 2×; leave margin for the model's own error).
_NEAR_LIMIT = 0.9


# THE limit formula the kernel's CompilerParams uses — not a mirror,
# the same function (hoisted into pallas_stencil so the model cannot
# drift from the runtime)
from yask_tpu.ops.pallas_stencil import vmem_limit_bytes  # noqa: F401,E402


def checker_budget(ctx) -> int:
    """The budget the static model evaluates: the explicit ``-vmem_mb``
    knob, else the REAL-TPU default — the checker answers Mosaic
    feasibility, so the CPU-interpret planning budget (a loose 100 MiB,
    VMEM emulated) must not leak in when the check runs on a CPU
    host."""
    opts = ctx._opts
    if opts.vmem_budget_mb > 0:
        return opts.vmem_budget_mb * 2 ** 20
    from yask_tpu.ops.pallas_stencil import default_vmem_budget
    return default_vmem_budget("tpu")


def budget_rungs(ctx) -> list:
    """The VMEM budgets (bytes) this configuration may plan with: the
    explicit ``-vmem_mb`` knob, else the auto-tuner's ladder when it
    will sweep one, else the TPU default."""
    opts = ctx._opts
    if opts.vmem_budget_mb > 0:
        return [opts.vmem_budget_mb * 2 ** 20]
    if opts.do_auto_tune and getattr(opts, "tune_vmem_ladder", False):
        from yask_tpu.runtime.auto_tuner import AutoTuner
        return [mb * 2 ** 20 for mb in AutoTuner.VMEM_LADDER_MIB]
    return [checker_budget(ctx)]


def plan_pallas(ctx, program, budget: int):
    """One plan_only planner run at the context's configured (K, block,
    skew) for ``budget`` — shared by this pass and the explain pass.
    For shard_pallas the PER-SHARD program is planned (rank domain +
    radius×K ghost pads, skew restricted to unsharded dims), mirroring
    ``_prep_shard_pallas`` — the global program is not what the inner
    kernel tiles."""
    from yask_tpu.ops.pallas_stencil import build_pallas_chunk
    opts = ctx._opts
    K = max(opts.wf_steps, 1)
    _key, blk, skw = ctx._pallas_build_key(K)
    # the same trapezoid argument _get_pallas_chunk passes: None lets
    # the build's profit gate decide, False disables — the plan must
    # reflect the tiling the runtime would actually choose
    trz = None if getattr(opts, "trapezoid_tiling", False) else False
    # likewise the push argument: ctx._push_arg() is the single
    # resolution of the push_memory knob — the static plan must show
    # the same DMA-path partition the runtime would build
    psh = ctx._push_arg()
    if ctx._mode == "shard_pallas":
        ana = ctx._ana
        dims = ana.domain_dims
        nr = {d: opts.num_ranks[d] for d in dims}
        rad = ana.fused_step_radius()
        hK = {d: rad.get(d, 0) * K for d in dims}
        local_prog = ctx._csol.plan(
            opts.rank_domain_sizes, global_sizes=opts.global_domain_sizes,
            extra_pad={d: (hK[d], hK[d]) for d in dims})
        unsh = tuple(d for d in dims[:-1] if nr.get(d, 1) == 1)
        return build_pallas_chunk(
            local_prog, fuse_steps=K, block=blk, distributed=True,
            vmem_budget=budget, skew=skw,
            vinstr_cap=opts.max_tile_vinstr, unsharded_dims=unsh,
            max_skew_dims=opts.skew_dims_max, trapezoid=trz,
            push=psh, plan_only=True)
    return build_pallas_chunk(
        program, fuse_steps=K, block=blk, vmem_budget=budget,
        skew=skw, vinstr_cap=opts.max_tile_vinstr,
        max_skew_dims=opts.skew_dims_max, trapezoid=trz,
        push=psh, plan_only=True)


def _classify_plan_error(msg: str) -> str:
    if msg.startswith("pallas fuse_steps"):
        return "PAD-COVERAGE"
    if msg.startswith("no feasible pallas block"):
        return "PALLAS-BLOCK-FIT"
    if msg.startswith("pallas pipelined tiles need"):
        return "VMEM-PIPE-OVER-BUDGET"
    if msg.startswith("pallas tile needs"):
        return "VMEM-TILE-OVER-BUDGET"
    if "skewed wavefront needs" in msg:
        return "SKEW-INFEASIBLE"
    if msg.startswith("trapezoid tiling") or "pallas diamond band" in msg:
        return "TRAPEZOID-INFEASIBLE"
    if msg.startswith("push-memory fusion infeasible"):
        return "PIPELINE-PUSH-INFEASIBLE"
    return "PLAN-FAILED"


def check_vmem(report: CheckReport, ctx, program) -> None:
    report.ran(PASS)
    mode = ctx._mode
    if mode not in ("pallas", "shard_pallas"):
        report.add("VMEM-SKIPPED", "info",
                   f"mode '{mode}' allocates no Pallas VMEM tiles")
        return

    for budget in budget_rungs(ctx):
        mb = budget / 2 ** 20
        limit = vmem_limit_bytes(budget)
        try:
            plan = plan_pallas(ctx, program, budget)
        except YaskException as e:
            rule = _classify_plan_error(str(e))
            report.add(rule, "error",
                       f"rung {mb:.0f} MiB: {e}",
                       detail={"vmem_budget": budget, "message": str(e)})
            continue
        tile = plan["tile_bytes"]
        live = get_capability().vmem_live_multiplier * tile
        det = {"vmem_budget": budget, "vmem_limit": limit,
               "tile_bytes": tile, "live_model_bytes": live,
               "block": plan["block"], "fuse_steps": plan["fuse_steps"],
               "in_tile_bytes": plan["in_tile_bytes"],
               "work_bytes": plan["work_bytes"],
               "carry_bytes": plan["carry_bytes"],
               "ostage_bytes": plan["ostage_bytes"],
               "push": plan.get("push", False),
               "push_vars": plan.get("push_vars", []),
               "push_tile_bytes": plan.get("push_tile_bytes", 0)}
        if live > limit:
            report.add(
                "VMEM-SPILL", "error",
                f"rung {mb:.0f} MiB: live-value model "
                f"{live / 2**20:.1f} MiB (2 × {tile / 2**20:.1f} MiB "
                f"tiles) exceeds the scoped Mosaic limit "
                f"{limit / 2**20:.0f} MiB — the round-3 register-spill "
                "OOM class (spill slots > vmem_limit); shrink block, "
                "fuse_steps, or the budget", detail=det)
        elif (get_capability().vmem_live_multiplier * budget > limit
              and live > _NEAR_LIMIT * limit):
            # only in the cap-bound regime (budget > 64 MiB): below it
            # live = 2·tile ≤ 2·budget = limit holds by construction,
            # and the default budget is DESIGNED to fill it exactly
            report.add(
                "VMEM-SPILL-MARGIN", "warn",
                f"rung {mb:.0f} MiB: live-value model "
                f"{live / 2**20:.1f} MiB is within "
                f"{100 * (1 - _NEAR_LIMIT):.0f}% of the "
                f"{limit / 2**20:.0f} MiB scoped limit; the 2× model "
                "has error bars — expect possible Mosaic OOM",
                detail=det)
        else:
            report.add(
                "VMEM-OK", "info",
                f"rung {mb:.0f} MiB: tiles {tile / 2**20:.1f} MiB, "
                f"live model {live / 2**20:.1f} MiB of "
                f"{limit / 2**20:.0f} MiB limit "
                f"(block {plan['block']}, K={plan['fuse_steps']})",
                detail=det)
        if plan.get("trapezoid"):
            _check_trapezoid(report, ctx, program, plan, budget, limit)


def _check_trapezoid(report: CheckReport, ctx, program, plan,
                     budget: int, limit: int) -> None:
    """TRAPEZOID rule family: the two-phase VMEM residency and the
    write-window sublane alignment, proved statically off the plan and
    the same :class:`TilePlan` the build derives its windows from.

    Phase 1 (upright trapezoids) and each phase-2 diamond fill run as
    SEPARATE ``pallas_call``s on a parallel grid, so each must fit the
    live-value model independently — a diamond band whose tile busts
    the limit is the same register-spill OOM class as the main kernel
    (``VMEM-SPILL``), reported per pass here."""
    from yask_tpu.compiler.lowering import tpu_tile_dims
    from yask_tpu.ops.tile_planner import TilePlan
    mb = budget / 2 ** 20
    K = plan["fuse_steps"]
    trap_dims = plan.get("trap_dims", [])
    for sub in plan.get("diamond", []):
        stile = sub["tile_bytes"]
        slive = get_capability().vmem_live_multiplier * stile
        sdet = {"vmem_budget": budget, "vmem_limit": limit,
                "tile_bytes": stile, "live_model_bytes": slive,
                "diamond_dim": sub.get("diamond_dim"),
                "band": sub.get("band"), "nbounds": sub.get("nbounds")}
        if slive > limit:
            report.add(
                "TRAPEZOID-VMEM-SPILL", "error",
                f"rung {mb:.0f} MiB: diamond fill pass in "
                f"'{sub.get('diamond_dim')}' models "
                f"{slive / 2**20:.1f} MiB live "
                f"(2 × {stile / 2**20:.1f} MiB band tiles) over the "
                f"{limit / 2**20:.0f} MiB scoped limit — shrink block "
                "or fuse_steps", detail=sdet)
        else:
            report.add(
                "TRAPEZOID-RESIDENCY-OK", "info",
                f"rung {mb:.0f} MiB: diamond pass in "
                f"'{sub.get('diamond_dim')}' fits "
                f"({slive / 2**20:.2f} MiB live of "
                f"{limit / 2**20:.0f} MiB; band {sub.get('band')}, "
                f"{sub.get('nbounds')} boundaries)", detail=sdet)
    # write-window alignment: phase-1 level writes shrink by
    # write_shrink(d, lvl) per side and phase-2 stitches copy
    # ±cl(d, lvl) around each boundary; on the sublane axis both must
    # be sublane-tile multiples or the staged write-back DMA is an
    # unaligned Mosaic window (hard compile failure on v5e)
    sub_t = tpu_tile_dims(program.dtype)[0]
    lead = program.ana.domain_dims[:-1]
    tp = TilePlan(program, K, trap_dims=trap_dims)
    bad = []
    for d in trap_dims:
        if d != lead[-1]:
            continue   # only the sublane axis carries the constraint
        for lvl in range(1, K + 1):
            for val, what in ((tp.write_shrink(d, lvl), "write-shrink"),
                              (tp.cl(d, lvl), "diamond half-width")):
                if val % sub_t != 0:
                    bad.append((d, lvl, what, val))
    if bad:
        report.add(
            "TRAPEZOID-WRITE-ALIGN", "error",
            f"trapezoid write windows not sublane-aligned "
            f"(sub_t={sub_t}): {bad} — the staged write-back DMA "
            "would be an unaligned Mosaic window",
            detail={"violations": bad, "sub_t": sub_t})
    else:
        report.add(
            "TRAPEZOID-WRITE-ALIGN-OK", "info",
            f"all phase-1 write shrinks and phase-2 stitch half-widths "
            f"are sublane-aligned (sub_t={sub_t}, K={K}, "
            f"dims {trap_dims})",
            detail={"sub_t": sub_t, "trap_dims": trap_dims,
                    "fuse_steps": K})
