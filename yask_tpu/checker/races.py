"""Dependency/race pass: equation-level scans plus distributed proofs.

The analysis pipeline (``SolutionAnalysis``) RAISES on the races it
knows about, which is right for ``prepare_solution`` but useless for a
diagnostic tool — one bad equation would hide every other finding.
This pass re-runs the same rules non-raising, directly over
``soln.get_equations()`` (so it works on solutions whose ``analyze()``
would throw), sharing the single rule definitions where they exist
(``analysis.missing_dim_race``, ``Var.min_step_alloc_size``).

The distributed sub-pass turns the shard planner's runtime raises
(``_prep_shard_pallas``) and the ghost-pad coverage argument from the
round-5 distributed-skew work into static proofs: per mesh-decomposed
dim the rank domain must cover the fused ghost width radius×K, the
minor dim may not be sharded at K>1, and each engaged skew dim's
margins (K·r left, r+E_sk right) must fit inside the radius×K ghost
pads — which holds exactly when the profit gate engaged it.
"""

from __future__ import annotations

from yask_tpu.checker.diagnostics import CheckReport
from yask_tpu.compiler.analysis import missing_dim_race
from yask_tpu.compiler.expr import PointVisitor

PASS = "races"
PASS_DIST = "distributed"


def _reads_of(eq):
    pv = PointVisitor()
    eq.rhs.accept(pv)
    if eq.cond is not None:
        eq.cond.accept(pv)
    if eq.step_cond is not None:
        eq.step_cond.accept(pv)
    return pv.points


def check_races(report: CheckReport, ctx, ana_error=None) -> None:
    report.ran(PASS)
    soln = ctx._csol.soln if ctx._csol is not None else ctx._soln
    eqs = soln.get_equations()
    domain_dims = soln.domain_dim_names()

    # writers per var this step (non-scratch), for WAW + same-point
    writers = {}
    step_dir = 0
    for eq in eqs:
        writers.setdefault(eq.lhs.var_name(), []).append(eq)
        so = eq.lhs.step_offset()
        if so in (1, -1) and step_dir == 0:
            step_dir = so
    if step_dir == 0:
        step_dir = 1

    for eq in eqs:
        var = eq.lhs.get_var()
        # RACE-MISSING-DIM: the single shared rule definition.
        varying = missing_dim_race(eq, domain_dims)
        if varying:
            report.add(
                "RACE-MISSING-DIM", "error",
                f"'{eq.format_simple()}' writes var '{var.get_name()}' "
                f"(no dim {sorted(varying)}) but its RHS/condition "
                f"varies along {sorted(varying)} — every point of the "
                "missing extent would demand a different value for the "
                "single stored slab (intra-step race)",
                var=var.get_name(), dim=sorted(varying)[0],
                detail={"dims": sorted(varying)})
        # RACE-SAME-POINT: reading the value being computed this step
        # with no other equation to order against (analysis raises the
        # same condition when the dependency checker is enabled).
        vname = eq.lhs.var_name()
        if not var.is_scratch() and len(writers.get(vname, ())) == 1:
            for p in _reads_of(eq):
                if p.var_name() != vname:
                    continue
                if p.step_offset() == step_dir:
                    report.add(
                        "RACE-SAME-POINT", "error",
                        f"'{eq.format_simple()}' reads the value of "
                        f"'{vname}' it is writing in the same step "
                        "(intra-step race; the reference rejects this, "
                        "Eqs.cpp:364-470)", var=vname)
                    break

    # RACE-WAW-ORDER: several equations write the same var this step —
    # legal, with deterministic registration-order (last-write-wins)
    # semantics; surfaced so multi-writer solutions are a visible
    # choice, not an accident.
    for vname, ws in sorted(writers.items()):
        if len(ws) > 1:
            report.add(
                "RACE-WAW-ORDER", "info",
                f"{len(ws)} equations write var '{vname}' in one step; "
                "they execute in registration order (later writers "
                "win where conditions overlap)", var=vname,
                detail={"count": len(ws)})

    # RING-DEPTH: a manual set_step_alloc_size below what the step
    # accesses need silently drops a live time level.
    for v in soln.get_vars():
        manual = getattr(v, "_step_alloc", None)
        if manual is not None:
            need = v.min_step_alloc_size()
            if manual < need:
                report.add(
                    "RING-DEPTH", "error",
                    f"var '{v.get_name()}' has a manual step_alloc of "
                    f"{manual} but its step accesses need {need} "
                    "slots; a live time level would be evicted early",
                    var=v.get_name(),
                    detail={"manual": manual, "needed": need})

    # SCRATCH-HALO: the computed scratch write-halos must cover every
    # read demand (reader offset + the reader's own write-halo when it
    # writes scratch).  The analysis fixpoint guarantees this by
    # construction; the rule re-derives the demand independently so an
    # invariant drift (or a hand-mutated analysis) is caught instead of
    # silently under-computing the expanded region.
    ana = getattr(ctx, "_ana", None)
    swh = getattr(ana, "scratch_write_halo", None) if ana else None
    if swh is not None:
        for eq in eqs:
            lhs_var = eq.lhs.get_var()
            lhs_wh = swh.get(lhs_var.get_name())
            for p in _reads_of(eq):
                rv = p.get_var()
                if not rv.is_scratch():
                    continue
                wh = swh.get(rv.get_name(), {})
                for d, ofs in p.domain_offsets().items():
                    if d not in wh:
                        continue
                    base_l = base_r = 0
                    if lhs_wh is not None and d in lhs_wh:
                        base_l, base_r = lhs_wh[d]
                    need_l = base_l + max(0, -ofs)
                    need_r = base_r + max(0, ofs)
                    have_l, have_r = wh[d]
                    if have_l < need_l or have_r < need_r:
                        report.add(
                            "SCRATCH-HALO", "error",
                            f"scratch var '{rv.get_name()}' write-halo "
                            f"({have_l},{have_r}) in dim '{d}' does "
                            f"not cover the ({need_l},{need_r}) demand "
                            f"of '{eq.format_simple()}' — the expanded "
                            "in-tile region would read uncomputed "
                            "cells", var=rv.get_name(), dim=d,
                            detail={"have": [have_l, have_r],
                                    "need": [need_l, need_r]})

    # Analysis-level failures the equation scans cannot reproduce
    # (cycles, malformed LHS forms) arrive as the captured exception.
    if ana_error is not None:
        msg = str(ana_error)
        rule = ("RACE-CYCLE" if "circular dependency" in msg
                else "ANALYSIS-FAILED")
        already = ("intra-step race" in msg
                   and any(d.rule.startswith("RACE-")
                           for d in report.diagnostics))
        if not already:
            report.add(rule, "error", f"solution analysis failed: {msg}",
                       detail={"message": msg})


def check_distributed(report: CheckReport, ctx) -> None:
    """Static halo-sufficiency proofs for the sharded execution modes."""
    report.ran(PASS_DIST)
    mode = getattr(ctx, "_mode", None) or ctx._opts.mode
    if mode not in ("sharded", "shard_map", "shard_pallas"):
        report.add("DIST-SKIPPED", "info",
                   f"mode '{mode}' is single-device; no shard geometry "
                   "to prove")
        return
    opts = ctx._opts
    ana = ctx._ana
    dims = ana.domain_dims
    minor = dims[-1]
    nr = {d: opts.num_ranks[d] for d in dims}
    lsizes = opts.rank_domain_sizes
    K = max(opts.wf_steps, 1) if mode == "shard_pallas" else 1
    rad = ana.fused_step_radius()
    hK = {d: rad.get(d, 0) * K for d in dims}

    if mode in ("shard_map", "shard_pallas"):
        from yask_tpu.parallel.decomp import validate_shard_geometry
        from yask_tpu.utils.exceptions import YaskException
        try:
            validate_shard_geometry(ctx._csol, opts)
        except YaskException as e:
            report.add("DIST-GEOMETRY", "error",
                       f"shard geometry invalid: {e}",
                       detail={"message": str(e)})

    if mode == "shard_pallas" and K > 1 and nr.get(minor, 1) > 1:
        report.add(
            "DIST-MINOR-SHARD", "error",
            f"shard_pallas with wf_steps={K} > 1 cannot shard the "
            f"minor dim '{minor}' (its in-tile region never shrinks); "
            "use wf_steps 1 or keep the minor dim whole", dim=minor,
            detail={"wf_steps": K, "nr": nr.get(minor, 1)})

    for d in dims:
        if nr.get(d, 1) > 1 and hK[d] > 0 and lsizes[d] < hK[d]:
            report.add(
                "DIST-GHOST-PAD", "error",
                f"rank domain {lsizes[d]} in dim '{d}' is smaller than "
                f"the fused ghost width {hK[d]} (radius × wf_steps): "
                "one exchange cannot provide the halo the fused steps "
                "consume", dim=d,
                detail={"rank_domain": lsizes[d], "ghost": hK[d]})

    # Overlapped-exchange decision: replay the EXACT runtime gate
    # (shard_step.overlap_decision — one definition, so the checker and
    # the executor can never drift) statically.  Engage/auto-off are
    # informational; a forced "on" that the geometry cannot honor is
    # the error class _prep_shard_pallas would raise at build time.
    if mode == "shard_pallas":
        from yask_tpu.parallel.shard_step import overlap_decision
        setting = getattr(opts, "overlap_exchange", "auto")
        try:
            ov_ok, ov_core, ov_shells, ov_reasons = \
                overlap_decision(ctx, K)
        except Exception:
            ov_ok, ov_reasons = False, None  # geometry reported above
        if ov_ok:
            report.add(
                "OVERLAP-ENGAGED", "info",
                f"overlapped halo exchange engages (overlap_x="
                f"{setting}): core "
                f"{ {d: list(v) for d, v in sorted(ov_core.items())} } "
                "computes on pre-exchange state while the previous "
                f"group's collectives land; {len(ov_shells)} shell "
                "slab(s) of width radius×K patch the faces from the "
                "post-exchange state",
                detail={"core": {d: list(v)
                                 for d, v in sorted(ov_core.items())},
                        "shells": [[d, lo, hi]
                                   for d, lo, hi in ov_shells],
                        "setting": setting})
        elif ov_reasons is not None:
            why = "; ".join(r.get("cause", r.get("code", ""))
                            for r in ov_reasons)
            if setting == "on":
                report.add(
                    "OVERLAP-INFEASIBLE", "error",
                    f"overlap_x=on is forced but the core/shell split "
                    f"cannot engage: {why} — the build would raise; "
                    "use auto (falls back to the serial schedule) or "
                    "fix the geometry",
                    detail={"reasons": ov_reasons})
            else:
                report.add(
                    "OVERLAP-OFF", "info",
                    f"overlapped halo exchange stays off "
                    f"(overlap_x={setting}): {why}",
                    detail={"reasons": ov_reasons})

    # Communication schedule: the SAME CommPlan the executors consume
    # (ctx.comm_plan — one definition, checker and runtime cannot
    # drift).  Plan errors are the class run_shard_map/_prep_shard_pallas
    # raise at build time; order/coalesce decisions surface as info so a
    # sweep log records which schedule actually ran.
    if mode in ("shard_map", "shard_pallas"):
        try:
            plan = ctx.comm_plan(K)
        except Exception as e:  # plan construction itself must not kill
            plan = None
            report.add("COMM-PLAN", "warn",
                       f"comm plan construction failed: {e}",
                       detail={"message": str(e)})
        if plan is not None:
            for msg in plan.errors:
                report.add(
                    "COMM-ORDER", "error",
                    f"comm schedule invalid: {msg} — the build would "
                    "raise; fix -comm_order or leave it empty for the "
                    "cost-model ordering",
                    detail={"message": msg, "order": list(plan.order)})
            if not plan.errors:
                kinds = {a: plan.axes[a].get("kind", "ici")
                         for a in plan.order}
                # A DCN (cross-process) axis scheduled after an ICI axis
                # serializes the slow hop behind fast ones — only an
                # explicit -comm_order can produce this (auto sorts DCN
                # first).
                seen_ici = None
                for a in plan.order:
                    if kinds[a] == "ici":
                        seen_ici = a
                    elif kinds[a] == "dcn" and seen_ici is not None:
                        report.add(
                            "COMM-DCN-ORDER", "warn",
                            f"DCN axis '{a}' is ordered after ICI axis "
                            f"'{seen_ici}': the slowest link starts "
                            "last, so its latency cannot hide behind "
                            "the ICI rounds; put DCN axes first",
                            dim=a,
                            detail={"order": list(plan.order),
                                    "kinds": kinds})
                if plan.coalesce:
                    report.add(
                        "COMM-PLAN", "info",
                        f"comm schedule: order {list(plan.order)}, "
                        f"coalesced — {plan.rounds} collective round(s) "
                        f"per exchange vs {plan.rounds_serial} serial "
                        "(one ppermute per buffer slab)",
                        detail=plan.record())
                elif plan.order:
                    report.add(
                        "COMM-SERIAL", "info",
                        f"comm schedule: order {list(plan.order)}, "
                        "serial per-buffer collectives "
                        f"({plan.rounds_serial} per exchange; "
                        f"coalescing would issue {2 * len(plan.order)})",
                        detail=plan.record())

    # Distributed skew-margin proof: each dim the profit gate would
    # engage (restricted to unsharded dims) needs K·r left and r+E_sk
    # right inside the radius×K ghost pads — right-cover holds exactly
    # when E_sk ≤ (K−1)·r, which the gate implies; prove it anyway.
    if mode == "shard_pallas" and K > 1 and opts.skew_wavefront:
        from yask_tpu.ops.pallas_stencil import (skew_engaged_dims,
                                                 skew_extra_widths)
        try:
            local_prog = ctx._csol.plan(
                lsizes, global_sizes=opts.global_domain_sizes,
                extra_pad={d: (hK[d], hK[d]) for d in dims})
        except Exception:
            return  # geometry errors already reported above
        unsh = tuple(d for d in dims[:-1] if nr.get(d, 1) == 1)
        e_sk = skew_extra_widths(local_prog, K)
        for d in skew_engaged_dims(local_prog, K, unsharded=unsh,
                                   max_dims=opts.skew_dims_max):
            r = rad.get(d, 0)
            if r + e_sk.get(d, 0) > hK[d]:
                report.add(
                    "DIST-SKEW-MARGIN", "error",
                    f"skew dim '{d}': right margin r+E_sk = "
                    f"{r + e_sk.get(d, 0)} exceeds the ghost pad "
                    f"{hK[d]}; the carry would read unexchanged "
                    "cells", dim=d,
                    detail={"r": r, "E_sk": e_sk.get(d, 0),
                            "ghost": hK[d]})
            else:
                report.add(
                    "DIST-SKEW-COVERED", "info",
                    f"skew dim '{d}': margins K·r={hK[d]} (left), "
                    f"r+E_sk={r + e_sk.get(d, 0)} (right) are covered "
                    f"by the radius×K={hK[d]} ghost pads; the carry "
                    "never crosses a shard boundary", dim=d)
