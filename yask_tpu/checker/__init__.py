"""yask_tpu.checker — static analysis over a configured solution.

Runs over a solution context + settings WITHOUT executing anything (no
state allocation, no kernel trace, no device work — planning is pure
geometry) and emits structured diagnostics.  Five passes:

* ``mosaic``      — the probed v5e TC legality rules (lane-128/
                    sublane-8 DMA alignment, misc-first physical order,
                    SMEM constraints, in-kernel pattern vocabulary);
* ``vmem``        — the static VMEM budget model per ladder rung,
                    including the live-value (register-spill) limit the
                    round-3 OOM violated;
* ``races``       — equation-level race rules (missing-dim, same-point,
                    WAW order, ring depth, scratch write-halo) plus the
                    distributed halo-sufficiency proofs;
* ``cache``       — persistent compile-cache hygiene (stale/corrupt
                    entry scan) and ensemble-batching feasibility for
                    the configured mode;
* ``ckpt``        — supervised-run configuration (checkpoint cadence vs
                    deadline budget, writable snapshot dir, fused
                    K-group alignment, restore-compat ladder proof);
* ``serve``       — server-hosted profile checks (micro-batching
                    compatibility of the configured mode, compile-cache
                    warmth for warm restart); gated on ``-serve``;
* ``pipeline``    — cross-solution pipeline fusion feasibility (fuse vs
                    host-chain, fused VMEM spill) from the same plan
                    dict the executor decides from; skipped for
                    contexts outside a pipeline;
* ``explain``     — every pallas/skew/pipelining decision and fallback
                    as a structured reason.

Entry points: :func:`run_checks` (library), ``python -m
yask_tpu.checker`` (CLI), :func:`preflight` (driver-tool gate —
``bench.py`` and ``tools/tpu_session.py`` call it before spending a
relay window on a statically-infeasible config).

See ``docs/checking.md`` for the rule catalog and JSON schema.
"""

from __future__ import annotations

import sys
from typing import Optional

from yask_tpu.checker.diagnostics import CheckReport, Diagnostic, SCHEMA
from yask_tpu.utils.exceptions import YaskException

__all__ = ["CheckReport", "Diagnostic", "SCHEMA", "run_checks",
           "preflight"]

PASSES = ("mosaic", "vmem", "races", "distributed", "cache", "ckpt",
          "serve", "pipeline", "explain")


def _dtype_name(dt) -> str:
    try:
        import numpy as np
        return np.dtype(dt).name if dt is not None else ""
    except Exception:
        return str(dt or "")


def run_checks(ctx, passes=None) -> CheckReport:
    """Run the static passes over a (prepared or unprepared) solution
    context.  Never allocates state: an unprepared context is planned
    through ``_plan_geometry()`` (pure geometry), so a 512³ feasibility
    question costs no memory.  Never raises for findings — everything
    becomes a diagnostic."""
    want = set(passes or PASSES)
    bad = want - set(PASSES)
    if bad:
        raise YaskException(f"unknown checker pass(es) {sorted(bad)}; "
                            f"available: {list(PASSES)}")

    program = getattr(ctx, "_program", None)
    plan_error: Optional[YaskException] = None
    if program is None:
        try:
            program = ctx._plan_geometry()
        except YaskException as e:
            plan_error = e

    from yask_tpu.backend import get_capability
    opts = ctx._opts
    report = CheckReport(config={
        "stencil": ctx.get_name(),
        "sizes": opts.global_domain_sizes.make_val_str("x"),
        "mode": getattr(ctx, "_mode", None) or opts.mode,
        "wf_steps": opts.wf_steps,
        "vmem_mb": opts.vmem_budget_mb or 0,
        "dtype": _dtype_name(getattr(ctx._csol, "dtype", None)),
        "backend": get_capability().name,
    })

    if plan_error is not None:
        msg = str(plan_error)
        if "cannot use the pallas" in msg or "cannot use the " in msg:
            report.add("PALLAS-APPLICABLE", "error", msg,
                       detail={"message": msg})
        else:
            report.add("PLAN-FAILED", "error",
                       f"geometry planning failed: {msg}",
                       detail={"message": msg})

    # races first: its rules hold at the yc level and do not need a
    # plan, so a plan failure never hides a race finding
    if "races" in want:
        from yask_tpu.checker.races import check_races
        ana_error = None
        if getattr(ctx, "_ana", None) is None:
            try:
                from yask_tpu.compiler.analysis import SolutionAnalysis
                SolutionAnalysis(ctx._csol.soln)
            except YaskException as e:
                ana_error = e
        check_races(report, ctx, ana_error=ana_error)
    if "distributed" in want:
        from yask_tpu.checker.races import check_distributed
        check_distributed(report, ctx)
    # cache pass needs no plan either: entry-metadata scan + the
    # ensemble feasibility mode property
    if "cache" in want:
        from yask_tpu.checker.cache_pass import check_cache
        check_cache(report, ctx)
    # ckpt pass is plan-free too: cadence/deadline/dir arithmetic over
    # the settings + the mode-degradation ladder
    if "ckpt" in want:
        from yask_tpu.checker.ckpt_pass import check_ckpt
        check_ckpt(report, ctx)
    # serve pass: batching feasibility + compile-cache warmth for a
    # server-hosted profile (gated on the -serve knob; plan-free)
    if "serve" in want:
        from yask_tpu.checker.serve_pass import check_serve
        check_serve(report, ctx)
    # pipeline pass: fuse/decline reproduction off the executor's own
    # plan dict (pipeline_plan does its own geometry planning; plan-free
    # here, and a no-pipeline context just gets a skip note)
    if "pipeline" in want:
        from yask_tpu.checker.pipeline_pass import check_pipeline
        check_pipeline(report, ctx)

    if program is not None:
        if "mosaic" in want:
            from yask_tpu.checker.mosaic import check_mosaic
            check_mosaic(report, ctx, program)
        if "vmem" in want:
            from yask_tpu.checker.vmem import check_vmem
            check_vmem(report, ctx, program)
        if "explain" in want:
            from yask_tpu.checker.explain import check_explain
            check_explain(report, ctx, program)

    return report


def preflight(ctx, out=None, verbose: bool = False) -> bool:
    """Driver-tool gate: run the checks, print errors/warnings, return
    whether the configuration is statically sound.  Honors the
    ``-preflight`` setting (returns True without checking when the
    user turned it off).  Never raises — a checker bug must not cost a
    bench run, so internal failures report True with a note."""
    out = out or sys.stderr
    if not getattr(ctx._opts, "preflight", True):
        return True
    try:
        report = run_checks(ctx)
    except Exception as e:  # never let the gate kill the launch path
        import traceback
        out.write(f"checker: internal failure ({type(e).__name__}: {e}); "
                  "skipping preflight\n")
        # the full traceback, so a swallowed checker bug is debuggable
        # from the session log instead of silently vanishing
        out.write(traceback.format_exc())
        # ...and a journal row, so a crashing pass is VISIBLE in the
        # session evidence instead of only scrolling past on stderr
        # (LOG-ONLY contract unchanged: the launch still proceeds)
        try:
            from yask_tpu.resilience.journal import (SessionJournal,
                                                     default_journal_path)
            SessionJournal(default_journal_path()).record(
                "preflight", case=ctx.get_name(),
                outcome="preflight_error",
                error_type=type(e).__name__, error=str(e)[:500])
        except Exception:
            pass  # the journal must never cost the launch either
        return True
    if report.errors or report.warnings or verbose:
        out.write(report.render(verbose=verbose))
    return report.ok()
