"""Diagnostic model for the static checker.

Every rule violation (or informational note) becomes a
:class:`Diagnostic`: a stable rule id, a severity, the subject
(var/dim/stage when applicable), a human message, and an optional
machine-readable ``detail`` dict.  A :class:`CheckReport` collects the
diagnostics of one checker run plus the configuration they were produced
against, and serializes to the JSON schema documented in
``docs/checking.md`` (``yask_tpu.checker/1``).

The severity policy (also in ``docs/checking.md``):

* ``error``  — the configuration will fail or corrupt results if run
  (Mosaic would reject the kernel, VMEM cannot fit, a race breaks
  cross-mode equivalence).  Preflight prints these and returns False.
* ``warn``   — the configuration runs but not the way the user asked
  (auto-fallbacks, near-limit budgets).
* ``info``   — explanation of decisions taken (profit gates, pipelining,
  SMEM routing); the explain pass emits mostly these.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

SCHEMA = "yask_tpu.checker/1"
SEVERITIES = ("error", "warn", "info")


@dataclass
class Diagnostic:
    rule: str                      # stable id, e.g. "MOSAIC-LANE-ALIGN"
    severity: str                  # error | warn | info
    message: str                   # human-readable, one line
    var: Optional[str] = None      # subject var, when applicable
    dim: Optional[str] = None      # subject dim, when applicable
    stage: Optional[int] = None    # subject stage index, when applicable
    detail: Optional[dict] = None  # machine-readable extras

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(f"severity must be one of {SEVERITIES}, "
                             f"got {self.severity!r}")

    def to_dict(self) -> dict:
        out = {"rule": self.rule, "severity": self.severity,
               "message": self.message}
        for k in ("var", "dim", "stage", "detail"):
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        return out

    def format(self) -> str:
        subj = "".join(
            f" [{k}={v}]" for k, v in (("var", self.var), ("dim", self.dim),
                                       ("stage", self.stage))
            if v is not None)
        return f"{self.severity:5s} {self.rule}{subj}: {self.message}"


@dataclass
class CheckReport:
    """All diagnostics of one checker run over one configuration."""

    config: Dict[str, object] = field(default_factory=dict)
    passes: List[str] = field(default_factory=list)
    diagnostics: List[Diagnostic] = field(default_factory=list)

    def add(self, rule: str, severity: str, message: str, **kw) -> Diagnostic:
        d = Diagnostic(rule=rule, severity=severity, message=message, **kw)
        self.diagnostics.append(d)
        return d

    def ran(self, pass_name: str) -> None:
        if pass_name not in self.passes:
            self.passes.append(pass_name)

    def by_severity(self, severity: str) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == severity]

    @property
    def errors(self) -> List[Diagnostic]:
        return self.by_severity("error")

    @property
    def warnings(self) -> List[Diagnostic]:
        return self.by_severity("warn")

    def rules_fired(self) -> List[str]:
        seen, out = set(), []
        for d in self.diagnostics:
            if d.rule not in seen:
                seen.add(d.rule)
                out.append(d.rule)
        return out

    def ok(self) -> bool:
        return not self.errors

    def to_json(self) -> dict:
        return {
            "schema": SCHEMA,
            "config": dict(self.config),
            "passes": list(self.passes),
            "diagnostics": [d.to_dict() for d in self.diagnostics],
            "summary": {s: len(self.by_severity(s)) for s in SEVERITIES},
        }

    def json_str(self) -> str:
        return json.dumps(self.to_json(), indent=2, default=str)

    def render(self, verbose: bool = False) -> str:
        """Human text: errors and warnings always, infos with
        ``verbose`` (the explain pass is info-heavy)."""
        lines = []
        cfg = self.config
        head = " ".join(f"{k}={v}" for k, v in cfg.items())
        lines.append(f"checker: {head}")
        shown = 0
        for d in self.diagnostics:
            if d.severity == "info" and not verbose:
                continue
            lines.append("  " + d.format())
            shown += 1
        n_err, n_warn = len(self.errors), len(self.warnings)
        n_info = len(self.by_severity("info"))
        if not verbose and n_info:
            lines.append(f"  ({n_info} info note(s) — -verbose or -json "
                         "to see them)")
        lines.append(f"checker result: {'FAIL' if n_err else 'ok'} "
                     f"({n_err} error(s), {n_warn} warning(s), "
                     f"{n_info} info)")
        return "\n".join(lines) + "\n"
