"""Compile-amortization pass: persistent-cache hygiene + ensemble
feasibility, without executing anything.

Two rules:

* ``CACHE-STALE`` — scans the persistent compile cache
  (``YT_COMPILE_CACHE``) through :func:`yask_tpu.cache.iter_entries`
  and reports entries that can never be hit again under the current
  jax/jaxlib/code fingerprint (the fingerprint is hashed into the
  content address, so a stale entry is dead weight the LRU eviction
  will cycle out, not a correctness risk) plus unreadable/corrupt
  files (``aot_compile`` falls back to a fresh compile on these, but
  they waste an eviction slot each).  One aggregate diagnostic per
  group — a 64-entry cache must not produce 64 findings.
* ``ENSEMBLE-INFEASIBLE`` — when ``-ensemble N`` (N>1) is set, asks
  :func:`yask_tpu.runtime.ensemble.ensemble_feasible` — the ONE
  feasibility definition the runtime itself consults — whether the
  configured mode can batch.  A decline is an error: the user asked
  for a batched sweep and would silently get nothing (the knob only
  takes effect through ``new_ensemble``, which raises at run time;
  this surfaces it at preflight instead).

Both rules are pure host work: the cache scan reads entry metadata
(payloads are never deserialized) and feasibility is a mode property.
"""

from __future__ import annotations

from yask_tpu.checker.diagnostics import CheckReport

PASS = "cache"

#: fingerprint fields that decide whether an entry can still be hit;
#: ``platform`` is excluded — an entry for another platform is simply
#: another platform's entry, not a stale one.
_STATIC_FP_FIELDS = ("jax", "jaxlib", "code")


def check_cache(report: CheckReport, ctx) -> None:
    report.ran(PASS)
    opts = ctx._opts

    n = int(getattr(opts, "ensemble", 1) or 1)
    if n > 1:
        from yask_tpu.runtime.ensemble import ensemble_feasible
        ok, why = ensemble_feasible(ctx)
        mode = getattr(ctx, "_mode", None) or opts.mode
        if not ok:
            report.add("ENSEMBLE-INFEASIBLE", "error",
                       f"ensemble={n} cannot batch: {why}",
                       detail={"ensemble": n, "mode": mode,
                               "reason": why})
        else:
            report.add("ENSEMBLE-INFEASIBLE", "info",
                       f"ensemble={n} batches under mode '{mode}'",
                       detail={"ensemble": n, "mode": mode})

    from yask_tpu.cache import backend_fingerprint, cache_dir, \
        iter_entries
    d = cache_dir()
    if not d:
        return
    cur = backend_fingerprint()
    cur_static = {k: cur.get(k, "") for k in _STATIC_FP_FIELDS}
    stale, unreadable, total = [], [], 0
    for path, meta in iter_entries(d):
        total += 1
        if "unreadable" in meta:
            unreadable.append((path, meta["unreadable"]))
            continue
        fp = meta.get("fingerprint") or {}
        if {k: fp.get(k, "") for k in _STATIC_FP_FIELDS} != cur_static:
            stale.append((path, {k: fp.get(k, "")
                                 for k in _STATIC_FP_FIELDS}))
    if stale:
        report.add("CACHE-STALE", "warn",
                   f"{len(stale)}/{total} persisted executable(s) in "
                   f"{d} were built under a different jax/jaxlib/code "
                   "fingerprint and can never be hit again — dead "
                   "weight until LRU eviction cycles them out",
                   detail={"dir": d, "current": cur_static,
                           "stale": [{"path": p, "fingerprint": f}
                                     for p, f in stale[:8]],
                           "stale_count": len(stale)})
    if unreadable:
        report.add("CACHE-STALE", "warn",
                   f"{len(unreadable)}/{total} cache file(s) in {d} "
                   "are unreadable/corrupt (aot_compile falls back to "
                   "a fresh compile, but each wastes an eviction slot)",
                   detail={"dir": d,
                           "unreadable": [{"path": p, "error": e}
                                          for p, e in unreadable[:8]],
                           "unreadable_count": len(unreadable)})
