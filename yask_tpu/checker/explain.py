"""Explain pass: surface every planning decision as a diagnostic.

The pallas planner records a structured reason code at each decision
point (skew engage/reject per dim, each step of the 2-D → 1-D →
uniform fallback ladder, block shrinks, DMA-pipelining on/off) — see
``build_pallas_chunk``'s ``reasons`` parameter.  This pass replays the
planner in ``plan_only`` mode at the configured budget and republishes
those codes as ``EXPLAIN-*`` diagnostics: fallbacks are ``warn`` (the
kernel runs, but not the tiling that was asked for or modeled),
decisions are ``info``.  On the XLA modes it instead explains why the
pallas fast path is NOT in play (mode choice or applicability).
"""

from __future__ import annotations

from yask_tpu.checker.diagnostics import CheckReport
from yask_tpu.utils.exceptions import YaskException

PASS = "explain"

#: reason code → severity; everything else is info.
_SEVERITY = {
    "skew_fallback": "warn",
    "block_shrunk": "warn",
    "trapezoid_fallback": "warn",
}


def _rule_of(code: str) -> str:
    return "EXPLAIN-" + code.upper().replace("_", "-")


def check_explain(report: CheckReport, ctx, program) -> None:
    report.ran(PASS)
    mode = ctx._mode
    if mode not in ("pallas", "shard_pallas"):
        from yask_tpu.ops.pallas_stencil import pallas_applicable
        ok, why = pallas_applicable(ctx._csol)
        if ok:
            report.add("EXPLAIN-MODE", "info",
                       f"mode '{mode}' selected; the pallas fused path "
                       "is applicable but not requested")
        else:
            report.add("EXPLAIN-PALLAS-FALLBACK", "info",
                       f"the pallas fused path cannot apply: {why}",
                       detail={"reason": why})
        return

    from yask_tpu.checker.vmem import checker_budget, plan_pallas
    try:
        plan = plan_pallas(ctx, program, checker_budget(ctx))
    except YaskException as e:
        # infeasibility itself is the vmem pass's finding; here it just
        # means there are no decisions to explain
        report.add("EXPLAIN-PLAN-FAILED", "info",
                   f"planner rejected the configuration ({e}); see the "
                   "vmem pass diagnostics")
        return

    for r in plan["reasons"]:
        code = r.get("code", "unknown")
        det = {k: v for k, v in r.items() if k != "code"}
        bits = []
        if "dim" in r:
            bits.append(f"dim '{r['dim']}'")
        if "cause" in r:
            bits.append(r["cause"])
        if "detail" in r:
            bits.append(str(r["detail"]))
        if code == "skew_fallback":
            bits.append(f"{r.get('from_dims')} -> {r.get('to')}")
        msg = code.replace("_", " ") + (": " + "; ".join(bits)
                                        if bits else "")
        report.add(_rule_of(code), _SEVERITY.get(code, "info"), msg,
                   dim=r.get("dim"), detail=det)

    report.add(
        "EXPLAIN-TILING", "info",
        f"final plan: K={plan['fuse_steps']}, block {plan['block']}, "
        f"grid {plan['grid']}, skew={plan['skew']} "
        f"{plan['skew_dims']}, "
        f"trapezoid={plan.get('trapezoid', False)} "
        f"{plan.get('trap_dims', [])}, "
        f"semantics={plan.get('dimension_semantics')}, "
        f"pipe_in={plan['pipeline_dmas']}, "
        f"pipe_out={plan['pipeline_out']}, tiles "
        f"{plan['tile_bytes'] / 2**20:.1f} MiB",
        detail={k: plan.get(k) for k in
                ("fuse_steps", "block", "grid", "skew", "skew_dims",
                 "trapezoid", "trap_dims", "dimension_semantics",
                 "pipeline_dmas", "pipeline_out", "tile_bytes")})
