"""Hand-tiled TPU kernels (Pallas).

The performance layer of the framework: where the reference's compiler
emits AVX-intrinsic nano/pico loops with vector folding and temporal
wave-front tiling (``src/compiler/lib/CppIntrin.*``, ``context.hpp:331``),
this package generates Pallas kernels — halo tiles DMA'd HBM→VMEM, K
time-steps fused in VMEM (temporal tiling), tile shapes searchable by the
auto-tuner.
"""

from yask_tpu.ops.pallas_stencil import (
    pallas_applicable,
    build_pallas_chunk,
)

__all__ = ["pallas_applicable", "build_pallas_chunk"]
