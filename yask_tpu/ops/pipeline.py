"""Cross-solution pipeline fusion: producer→consumer solution DAGs.

Real applications chain several solutions per time step (RTM: forward
wavefield → imaging condition → smoothing filter).  Run naively, every
stage round-trips its full state through HBM/host and the next stage
re-fetches it — N× the interior HBM traffic of the fused equivalent
(see ``docs/performance.md``).  The Pallas path already fuses
*intra*-solution multi-stage chains in-tile: a read at step offset
``step_dir`` on a written var is a "computed read", the analysis
places the consumer equation into a later stage, and
``build_pallas_chunk`` expands producer tiles by the consumer's write
halo (the scratch-var chain machinery).  This module generalizes that
to *whole solutions*:

* :class:`SolutionPipeline` — an ordered DAG of solutions plus
  declared producer→consumer var **bindings** (consumer's step-free
  read-only input var ← producer's freshly written field);
* **fusion by source-level merge** — eligible chains are rewritten
  into ONE merged ``yc_solution`` (vars renamed ``stage__var``, bound
  input vars eliminated, every read of one becoming a computed read of
  the producer at ``+step_dir``), so ALL existing machinery — analysis
  staging, :class:`~yask_tpu.ops.tile_planner.TilePlan` dataflow,
  VMEM budgeting, skew, the AOT cache — applies unchanged;
* :func:`pipeline_plan` — the shared plan-only decision record
  (structured ``reasons`` for every fuse/decline, the same dict the
  checker's ``pipeline`` pass reads — the checker cannot drift from
  the executor);
* **auto-fallback** — ineligible or infeasible chains run the unfused
  host-chained schedule (per step, per stage: push bindings, run one
  step), which is also the bit-equality oracle for the fused arm.

Device-facing work routes through ``guarded_call`` at the
``pipeline.run`` fault site (``docs/resilience.md``).
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from yask_tpu.utils.exceptions import YaskException
from yask_tpu.compiler import expr as E
from yask_tpu.compiler.expr import IndexType, VarPoint
from yask_tpu.compiler.solution import yc_factory, yc_solution
from yask_tpu.resilience.guard import guarded_call

__all__ = ["PipelineBinding", "SolutionPipeline", "pipeline_plan",
           "merge_solutions", "pipeline_hbm_model", "rtm_chain",
           "SEP", "PIPELINE_SCHEMA"]

#: stage/var separator in merged-var names; stage names must not
#: contain it (``fwd__pressure`` ← stage ``fwd``, var ``pressure``).
SEP = "__"

PIPELINE_SCHEMA = "yask_tpu.pipeline/1"


class PipelineBinding:
    """One producer→consumer edge: the consumer stage's step-free
    read-only var ``consumer_var`` is fed each step by the producer
    stage's freshly written ``producer_var`` (its ``+step_dir``
    value)."""

    __slots__ = ("consumer_stage", "consumer_var",
                 "producer_stage", "producer_var")

    def __init__(self, consumer_stage: str, consumer_var: str,
                 producer_stage: str, producer_var: str):
        self.consumer_stage = consumer_stage
        self.consumer_var = consumer_var
        self.producer_stage = producer_stage
        self.producer_var = producer_var

    def as_tuple(self) -> Tuple[str, str, str, str]:
        return (self.consumer_stage, self.consumer_var,
                self.producer_stage, self.producer_var)

    def __repr__(self):
        return (f"{self.producer_stage}.{self.producer_var} -> "
                f"{self.consumer_stage}.{self.consumer_var}")


def _norm_bindings(bindings) -> List[PipelineBinding]:
    out = []
    for b in bindings or ():
        if isinstance(b, PipelineBinding):
            out.append(b)
        elif isinstance(b, dict):
            out.append(PipelineBinding(
                b["consumer_stage"], b["consumer_var"],
                b["producer_stage"], b["producer_var"]))
        else:
            out.append(PipelineBinding(*b))
    return out


def _soln_of(source) -> yc_solution:
    """Accept a yc_solution or a yc_solution_base (define() run)."""
    if isinstance(source, yc_solution):
        return source
    if hasattr(source, "run_define") and hasattr(source, "get_soln"):
        source.run_define()
        return source.get_soln()
    raise YaskException(
        f"pipeline stage needs a yc_solution or yc_solution_base, "
        f"got {type(source).__name__}")


def _written_names(soln: yc_solution) -> set:
    return {eq.lhs.var.get_name() for eq in soln.get_equations()}


def _read_points(soln: yc_solution) -> List[VarPoint]:
    pv = E.PointVisitor()
    for eq in soln.get_equations():
        eq.rhs.accept(pv)
        if eq.cond is not None:
            eq.cond.accept(pv)
        if eq.step_cond is not None:
            eq.step_cond.accept(pv)
    return pv.points


# ---------------------------------------------------------------------------
# structural eligibility
# ---------------------------------------------------------------------------


def _check_structure(stage_names, solns, bindings) -> List[Dict]:
    """All failed structural checks as reason dicts (``ok: False``);
    empty list = structurally fusable.  Collects EVERYTHING rather than
    short-circuiting — a decline must name every blocker at once."""
    bad: List[Dict] = []

    def no(code, msg, **kw):
        d = {"code": code, "ok": False, "msg": msg}
        d.update(kw)
        bad.append(d)

    if len(stage_names) < 2:
        no("stage-count", f"need >=2 stages, got {len(stage_names)}")
    seen = set()
    for s in stage_names:
        if not s.isidentifier() or SEP in s:
            no("stage-name", f"stage name {s!r} must be an identifier "
               f"without {SEP!r}", stage=s)
        if s in seen:
            no("stage-name", f"duplicate stage name {s!r}", stage=s)
        seen.add(s)

    anas = {}
    for s in stage_names:
        try:
            anas[s] = solns[s].analyze()
        except YaskException as e:
            no("stage-analyze", f"stage {s!r} fails analysis: {e}",
               stage=s)
    if bad:
        return bad

    # shared dims, step dim, direction
    s0 = stage_names[0]
    dd0 = list(anas[s0].domain_dims)
    sd0 = anas[s0].step_dim
    dir0 = anas[s0].step_dir
    for s in stage_names[1:]:
        a = anas[s]
        if list(a.domain_dims) != dd0:
            no("dims-mismatch",
               f"stage {s!r} domain dims {list(a.domain_dims)} != "
               f"stage {s0!r} dims {dd0}", stage=s)
        if a.step_dim != sd0:
            no("dims-mismatch",
               f"stage {s!r} step dim {a.step_dim!r} != {sd0!r}",
               stage=s)
        if a.step_dir != dir0:
            no("step-dir-mismatch",
               f"stage {s!r} steps {a.step_dir:+d}, stage {s0!r} "
               f"steps {dir0:+d}", stage=s)

    # index-name/type conflicts across stages (x as domain in one
    # stage, misc in another, cannot share one merged index)
    itypes: Dict[str, IndexType] = {}
    for s in stage_names:
        for v in solns[s].get_vars():
            for d in v.get_dims():
                t = itypes.setdefault(d.name, d.type)
                if t != d.type:
                    no("index-type-conflict",
                       f"index {d.name!r} is {t.value} in one stage, "
                       f"{d.type.value} in stage {s!r}", stage=s,
                       dim=d.name)

    # bindings
    order = {s: i for i, s in enumerate(stage_names)}
    targets = set()
    for b in bindings:
        loc = repr(b)
        if b.consumer_stage not in order or b.producer_stage not in order:
            no("binding-unknown-stage", f"binding {loc}: unknown stage")
            continue
        csol, psol = solns[b.consumer_stage], solns[b.producer_stage]
        try:
            cv = csol.get_var(b.consumer_var)
        except YaskException:
            no("binding-unknown-var",
               f"binding {loc}: consumer stage has no var "
               f"{b.consumer_var!r}")
            continue
        try:
            pv = psol.get_var(b.producer_var)
        except YaskException:
            no("binding-unknown-var",
               f"binding {loc}: producer stage has no var "
               f"{b.producer_var!r}")
            continue
        if order[b.producer_stage] >= order[b.consumer_stage]:
            no("binding-order",
               f"binding {loc}: producer stage must come before the "
               f"consumer in the stage list (DAG is acyclic by "
               f"construction)")
        key = (b.consumer_stage, b.consumer_var)
        if key in targets:
            no("binding-duplicate",
               f"binding {loc}: {b.consumer_var!r} already bound")
        targets.add(key)
        if pv.get_name() not in _written_names(psol) or pv.is_scratch():
            no("binding-producer",
               f"binding {loc}: producer var must be a written "
               f"non-scratch var")
        if pv.step_dim() is None:
            no("binding-producer",
               f"binding {loc}: producer var needs a step dim (its "
               f"fresh +step value is what the consumer reads)")
        if pv.misc_dim_names():
            no("binding-producer",
               f"binding {loc}: producer var must have no misc dims")
        if cv.get_name() in _written_names(csol):
            no("binding-consumer",
               f"binding {loc}: consumer input var must be read-only")
        if cv.step_dim() is not None or cv.misc_dim_names():
            no("binding-consumer",
               f"binding {loc}: consumer input var must be step-free "
               f"with no misc dims (a pure per-step input slot)")
        if cv.domain_dim_names() != pv.domain_dim_names() \
                or cv.domain_dim_names() != dd0:
            no("binding-consumer",
               f"binding {loc}: consumer/producer domain dims must "
               f"both equal the solution dims {dd0}")
    return bad


def _binding_pushable(solns, stage_order, b) -> bool:
    """Whether the host-chained arm can physically push this binding
    (stages and vars exist, the producer is a written step var that
    runs EARLIER in the step — its fresh value must exist when
    pushed).  A superset of full structural eligibility: a chain that
    declines for other reasons still pushes its well-formed
    bindings."""
    if b.consumer_stage not in stage_order \
            or b.producer_stage not in stage_order:
        return False
    if stage_order[b.producer_stage] >= stage_order[b.consumer_stage]:
        return False
    try:
        cv = solns[b.consumer_stage].get_var(b.consumer_var)
        pv = solns[b.producer_stage].get_var(b.producer_var)
    except YaskException:
        return False
    return (pv.get_name() in _written_names(solns[b.producer_stage])
            and pv.step_dim() is not None
            and cv.step_dim() is None)


# ---------------------------------------------------------------------------
# source-level merge
# ---------------------------------------------------------------------------


def merge_solutions(name: str, stages: Sequence[Tuple[str, yc_solution]],
                    bindings: Sequence[PipelineBinding],
                    step_dir: int) -> yc_solution:
    """Build ONE merged ``yc_solution`` from structurally eligible
    stages: vars renamed ``stage__var``, bound consumer inputs
    eliminated — every read of one is rewritten onto the producer's
    merged var at step offset ``+step_dir`` (the computed-read form the
    analysis already stages and the Pallas builder already fuses
    in-tile over write-halo-expanded regions)."""
    merged = yc_factory().new_solution(name)
    solns = dict(stages)
    order = [s for s, _ in stages]

    # shared indices (by name; types verified by _check_structure)
    idx: Dict[str, E.IndexExpr] = {}

    def index_for(d) -> E.IndexExpr:
        if d.name not in idx:
            if d.type == IndexType.STEP:
                idx[d.name] = merged.new_step_index(d.name)
            elif d.type == IndexType.DOMAIN:
                idx[d.name] = merged.new_domain_index(d.name)
            else:
                idx[d.name] = merged.new_misc_index(d.name)
        return idx[d.name]

    ana0 = solns[order[0]].analyze()
    step_idx = None
    if ana0.step_dim:
        step_idx = index_for(
            E.IndexExpr(ana0.step_dim, IndexType.STEP))
    dom_idx = [index_for(E.IndexExpr(d, IndexType.DOMAIN))
               for d in ana0.domain_dims]
    merged.set_domain_dims(dom_idx)

    bound = {(b.consumer_stage, b.consumer_var): b for b in bindings}

    # vars (declared dim order preserved; bound inputs eliminated)
    vmap: Dict[Tuple[str, str], object] = {}
    for s in order:
        for v in solns[s].get_vars():
            if (s, v.get_name()) in bound:
                continue
            dims = [index_for(d) for d in v.get_dims()]
            mk = (merged.new_scratch_var if v.is_scratch()
                  else merged.new_var)
            vmap[(s, v.get_name())] = mk(f"{s}{SEP}{v.get_name()}", dims)

    def rw_point(s: str, vp: VarPoint):
        key = (s, vp.var.get_name())
        if key in bound:
            b = bound[key]
            mvar = vmap[(b.producer_stage, b.producer_var)]
            shift = step_dir
        else:
            mvar = vmap[key]
            shift = None
        args = []
        for d in mvar.get_dims():
            if d.type == IndexType.STEP:
                off = (shift if shift is not None
                       else vp.offsets[d.name])
                args.append(idx[d.name] if off == 0
                            else idx[d.name] + off)
            elif d.type == IndexType.DOMAIN:
                off = vp.offsets[d.name]
                args.append(idx[d.name] if off == 0
                            else idx[d.name] + off)
            else:
                args.append(vp.offsets[d.name])
        return mvar(*args)

    def rw(s: str, node):
        """Rebuild the expression tree onto merged vars/indices,
        preserving structure exactly (same node types, same arg
        order), so the lowered op sequence — and therefore the
        floating-point result — is bit-identical to the unfused
        stage's."""
        if node is None or isinstance(node, E.ConstExpr):
            return node
        if isinstance(node, VarPoint):
            return rw_point(s, node)
        if isinstance(node, E.IndexExpr):
            return idx[node.name]
        if isinstance(node, E.FirstIndexExpr):
            return E.FirstIndexExpr(idx[node.dim.name])
        if isinstance(node, E.LastIndexExpr):
            return E.LastIndexExpr(idx[node.dim.name])
        if isinstance(node, E.NegExpr):
            return E.NegExpr(rw(s, node.arg))
        if isinstance(node, (E.AddExpr, E.MultExpr)):
            return type(node)([rw(s, a) for a in node.args])
        if isinstance(node, (E.SubExpr, E.DivExpr, E.ModExpr)):
            return type(node)(rw(s, node.lhs), rw(s, node.rhs))
        if isinstance(node, E.FuncExpr):
            return E.FuncExpr(node.name, [rw(s, a) for a in node.args])
        if isinstance(node, E.CompExpr):
            return E.CompExpr(node.op, rw(s, node.lhs), rw(s, node.rhs))
        if isinstance(node, E.AndExpr):
            return E.AndExpr(rw(s, node.lhs), rw(s, node.rhs))
        if isinstance(node, E.OrExpr):
            return E.OrExpr(rw(s, node.lhs), rw(s, node.rhs))
        if isinstance(node, E.NotExpr):
            return E.NotExpr(rw(s, node.arg))
        raise YaskException(
            f"pipeline merge: unhandled expression node "
            f"{type(node).__name__}")

    for s in order:
        for eq in solns[s].get_equations():
            merged.add_eq(rw(s, eq.lhs), rw(s, eq.rhs),
                          cond=rw(s, eq.cond),
                          step_cond=rw(s, eq.step_cond))
    return merged


# ---------------------------------------------------------------------------
# plan (the single fuse/decline decision record)
# ---------------------------------------------------------------------------


def pipeline_plan(pipe: "SolutionPipeline",
                  budget: Optional[int] = None) -> Dict:
    """Plan-only fuse/decline decision for a pipeline: structural
    eligibility, then (for Pallas modes) the REAL planner via
    ``build_pallas_chunk(plan_only=True)`` over the merged program —
    one code path shared with the executor (``prepare`` stores the
    result on ``fused_ctx._pipeline_plan``) and the checker's
    ``pipeline`` pass (which re-runs this with the TPU checker
    budget).  ``plan["fused"]`` IS the executor decision at the given
    budget; every contributing check lands in ``plan["reasons"]``."""
    plan: Dict = {
        "schema": PIPELINE_SCHEMA,
        "sig": pipe.signature(),
        "stages": list(pipe.stage_names),
        "bindings": [b.as_tuple() for b in pipe.bindings],
        "eligible": pipe.structurally_eligible,
        "fused": False,
        "mode": None,
        "reasons": [dict(r) for r in pipe._struct_reasons],
    }
    reasons = plan["reasons"]
    if not pipe.structurally_eligible:
        return plan
    reasons.append({"code": "structure-ok", "ok": True,
                    "msg": f"{len(plan['stages'])} stages, "
                           f"{len(plan['bindings'])} binding(s) merge "
                           f"cleanly"})

    fctx = pipe._ensure_fused_ctx()
    try:
        program = fctx._program if fctx._program is not None \
            else fctx._plan_geometry()
    except YaskException as e:
        reasons.append({"code": "plan-failed", "ok": False,
                        "msg": f"merged geometry planning failed: {e}"})
        return plan
    mode = getattr(fctx, "_mode", None) or fctx._opts.mode
    plan["mode"] = mode

    if mode in ("pallas", "shard_pallas"):
        from yask_tpu.checker.vmem import plan_pallas
        from yask_tpu.ops.pallas_stencil import vmem_limit_bytes
        b = budget if budget is not None else fctx.vmem_budget()
        try:
            pplan = plan_pallas(fctx, program, b)
        except YaskException as e:
            reasons.append({"code": "pallas-plan-failed", "ok": False,
                            "msg": f"merged chain has no feasible "
                                   f"pallas plan: {e}",
                            "vmem_budget": b})
            return plan
        tile = pplan.get("tile_bytes", 0)
        limit = vmem_limit_bytes(b)
        push_vars = list(pplan.get("push_vars") or [])
        plan["pallas"] = {"vmem_budget": b, "vmem_limit": limit,
                          "tile_bytes": tile,
                          "live_model_bytes": 2 * tile,
                          "fuse_steps": pplan.get("fuse_steps"),
                          "block": pplan.get("block"),
                          "grid": pplan.get("grid"),
                          "skew": pplan.get("skew"),
                          "push": bool(pplan.get("push")),
                          "push_vars": push_vars,
                          "push_tile_bytes":
                              pplan.get("push_tile_bytes", 0)}
        if 2 * tile > limit:
            # attribute the spill to push when push tiles are what
            # tipped the live model over — dropping them would fit
            if push_vars and 2 * (tile - pplan.get(
                    "push_tile_bytes", 0)) <= limit:
                reasons.append(
                    {"code": "pipeline-push-vmem-spill", "ok": False,
                     "msg": f"pushed stage tiles "
                            f"({pplan.get('push_tile_bytes', 0)} B) tip "
                            f"the live model 2x{tile} B over the vmem "
                            f"limit {limit} B",
                     "tile_bytes": tile, "vmem_limit": limit,
                     "push_vars": push_vars})
            else:
                reasons.append(
                    {"code": "pipeline-vmem-spill", "ok": False,
                     "msg": f"live model 2x{tile} B exceeds "
                            f"vmem limit {limit} B (the round-3 "
                            f"register-spill OOM class)",
                     "tile_bytes": tile, "vmem_limit": limit})
            return plan
        if push_vars:
            reasons.append(
                {"code": "pipeline-push-engaged", "ok": True,
                 "msg": f"push-memory fusion: {push_vars} consumed "
                        f"in-VMEM (no HBM round-trip)",
                 "push_vars": push_vars})
        else:
            why = [r for r in pplan.get("reasons", ())
                   if r.get("code") in ("push_ineligible",
                                        "push_disabled")]
            reasons.append(
                {"code": "pipeline-push-ineligible", "ok": True,
                 "msg": "no stage tile pushes: "
                        + ("; ".join(
                            f"{r.get('var', '*')}: {r['detail']}"
                            for r in why) or "planner declined"),
                 "detail": why})

    plan["hbm_model"] = pipeline_hbm_model(
        pipe, push_vars=(plan.get("pallas") or {}).get("push_vars"))
    plan["fused"] = True
    reasons.append({"code": "pipeline-engaged", "ok": True,
                    "msg": f"{len(plan['stages'])}-stage chain fuses "
                           f"into one {mode} program"})
    return plan


def pipeline_hbm_model(pipe: "SolutionPipeline", push_vars=None) -> Dict:
    """Per-point per-step HBM traffic model, chained vs fused: the
    chained arm streams every stage's read/write var set AND pays the
    binding push (one read + one write per bound var); fusion
    eliminates the bound vars entirely and streams the union once.
    Interior traffic only — margin overhead per extra stage is the
    TilePlan ``stage_widths`` story (``docs/performance.md``).

    ``push_vars`` (merged ``stage__var`` names the planner's push gate
    engaged, from ``plan["pallas"]["push_vars"]``) extends the model
    with ``fused_push_bytes_pp``: a pushed var is consumed in-VMEM, so
    its HBM write-back leaves the fused traffic too (its consumer reads
    were already dropped with the bound vars).  Always present —
    equal to ``fused_bytes_pp`` when nothing pushes."""
    eb = 4
    for _s, soln in pipe.stages:
        eb = soln._settings.elem_bytes or eb
        break
    bound = {(b.consumer_stage, b.consumer_var) for b in pipe.bindings}
    chained = 0
    fused = 0
    for s, soln in pipe.stages:
        writes = _written_names(soln)
        reads = {p.var.get_name() for p in _read_points(soln)}
        chained += (len(reads) + len(writes)) * eb
        f_reads = {v for v in reads if (s, v) not in bound}
        fused += (len(f_reads) + len(writes)) * eb
    chained += 2 * eb * len(pipe.bindings)
    n_push = len(push_vars or ())
    fused_push = max(fused - n_push * eb, eb)
    return {"elem_bytes": eb, "chained_bytes_pp": chained,
            "fused_bytes_pp": fused,
            "ratio": (chained / fused) if fused else 0.0,
            "push_vars": sorted(push_vars or ()),
            "fused_push_bytes_pp": fused_push,
            "push_ratio": (chained / fused_push) if fused_push else 0.0}


# ---------------------------------------------------------------------------
# the pipeline object
# ---------------------------------------------------------------------------


class SolutionPipeline:
    """An ordered producer→consumer DAG of solutions with declared var
    bindings, runnable fused (one merged program) or host-chained (the
    unfused oracle), with auto-fallback and a shared plan record.

    >>> stages, bindings = rtm_chain(radius=2)
    >>> pipe = SolutionPipeline(env, stages, bindings)
    >>> pipe.apply_command_line_options("-g 32 -mode jit")
    >>> pipe.prepare()
    >>> pipe.run(0, 3)
    """

    def __init__(self, env, stages, bindings=(), dtype=None,
                 name: Optional[str] = None):
        self._env = env
        self._dtype = dtype
        self.stages: List[Tuple[str, yc_solution]] = [
            (s, _soln_of(src)) for s, src in stages]
        self.stage_names = [s for s, _ in self.stages]
        self._solns = dict(self.stages)
        self.bindings = _norm_bindings(bindings)
        self.name = name or f"pipe_{'_'.join(self.stage_names)}"

        self._struct_reasons = _check_structure(
            self.stage_names, self._solns, self.bindings)
        self.structurally_eligible = not self._struct_reasons
        # the host-chained fallback honors only well-formed bindings
        # (both vars exist, producer is a written step var) — malformed
        # ones are already named in the decline reasons and cannot be
        # pushed at all
        order = {s: i for i, s in enumerate(self.stage_names)}
        self._pushable = [b for b in self.bindings
                          if _binding_pushable(self._solns, order, b)]
        self._merged: Optional[yc_solution] = None
        if self.structurally_eligible:
            dir0 = self._solns[self.stage_names[0]].analyze().step_dir
            self._merged = merge_solutions(
                self.name, self.stages, self.bindings, dir0)

        self._cli: List[str] = []
        self._fused_ctx = None
        self._stage_ctxs: Optional[Dict[str, object]] = None
        self._fused: Optional[bool] = None   # None until prepare()
        self._plan: Optional[Dict] = None
        self._prepared = False

    # -- configuration -------------------------------------------------

    def apply_command_line_options(self, args: str) -> None:
        """Stash shared kernel options (applied to every context this
        pipeline builds — both arms must run the same geometry)."""
        if self._prepared:
            raise YaskException(
                "apply_command_line_options before prepare()")
        self._cli.append(args)

    def signature(self) -> str:
        """Stable short hash over stage names, solution names, and
        bindings — the extra AOT-cache variant dimension
        (``ctx._pipeline_sig``): a fused chain must never collide with
        an unfused solution of identical equations."""
        h = hashlib.sha256()
        for s, soln in self.stages:
            h.update(f"{s}={soln.get_name()};".encode())
        for b in self.bindings:
            h.update(f"{b!r};".encode())
        return h.hexdigest()[:16]

    # -- context construction ------------------------------------------

    def _new_ctx(self, source, pipeline_sig=None):
        from yask_tpu.runtime.context import StencilContext
        ctx = StencilContext(self._env, source, dtype=self._dtype)
        if pipeline_sig is not None:
            ctx._pipeline_sig = pipeline_sig
        for args in self._cli:
            ctx.apply_command_line_options(args)
        return ctx

    def _ensure_fused_ctx(self):
        if self._fused_ctx is None:
            if self._merged is None:
                raise YaskException(
                    f"pipeline {self.name!r} is not structurally "
                    f"fusable: {self.decline_summary()}")
            self._fused_ctx = self._new_ctx(
                self._merged, pipeline_sig=self.signature())
            self._fused_ctx._pipeline = self
        return self._fused_ctx

    def _ensure_stage_ctxs(self) -> Dict[str, object]:
        if self._stage_ctxs is None:
            self._stage_ctxs = {}
            for s, soln in self.stages:
                ctx = self._new_ctx(soln)
                ctx.prepare_solution()
                self._stage_ctxs[s] = ctx
        return self._stage_ctxs

    # -- prepare: the fuse/decline decision ----------------------------

    def prepare(self, fuse: Optional[bool] = None) -> Dict:
        """Decide the executor (fused vs host-chained), prepare the
        winning arm, and return the plan dict.  ``fuse=None`` follows
        the plan (auto-fallback on any decline), ``True`` forces fused
        (raises when impossible), ``False`` forces the host-chained
        oracle."""
        plan = pipeline_plan(self) if self._merged is not None else {
            "schema": PIPELINE_SCHEMA, "sig": self.signature(),
            "stages": list(self.stage_names),
            "bindings": [b.as_tuple() for b in self.bindings],
            "eligible": False, "fused": False, "mode": None,
            "reasons": [dict(r) for r in self._struct_reasons],
        }
        want = plan["fused"] if fuse is None else fuse
        if fuse is True and not plan["fused"]:
            raise YaskException(
                f"pipeline {self.name!r} cannot fuse: "
                f"{self.decline_summary(plan)}")
        if fuse is False and plan["fused"]:
            plan["reasons"].append(
                {"code": "forced-unfused", "ok": True,
                 "msg": "host-chained arm forced by caller"})
            plan["fused"] = False
            want = False

        if want:
            fctx = self._ensure_fused_ctx()
            try:
                fctx.prepare_solution()
            except YaskException as e:
                if fuse is True:
                    raise
                plan["reasons"].append(
                    {"code": "prepare-failed", "ok": False,
                     "msg": f"fused prepare failed, falling back to "
                            f"host-chained: {e}"})
                plan["fused"] = False
                want = False
        if not want:
            self._ensure_stage_ctxs()

        self._fused = bool(want)
        plan["fused"] = self._fused
        self._plan = plan
        if self._fused_ctx is not None:
            self._fused_ctx._pipeline_plan = plan
        self._prepared = True
        return plan

    @property
    def fused(self) -> bool:
        self._check_prepared()
        return bool(self._fused)

    def plan(self) -> Dict:
        self._check_prepared()
        return self._plan

    def decline_summary(self, plan: Optional[Dict] = None) -> str:
        reasons = (plan or self._plan or
                   {"reasons": self._struct_reasons})["reasons"]
        bad = [r for r in reasons if not r.get("ok")]
        return "; ".join(f"[{r['code']}] {r['msg']}" for r in bad) \
            or "no decline recorded"

    def _check_prepared(self) -> None:
        if not self._prepared:
            raise YaskException("call pipeline.prepare() first")

    # -- state access --------------------------------------------------

    def pushed_vars(self) -> set:
        """Merged ``stage__var`` names the planner's push-memory gate
        engaged for the prepared fused arm (empty host-chained, or on
        any mode without a pallas plan).  Pushed vars are consumed
        in-VMEM — their HBM rings go STALE after ``run()`` and must not
        be read or compared."""
        if not self._prepared or not self._fused or not self._plan:
            return set()
        return set((self._plan.get("pallas") or {})
                   .get("push_vars") or ())

    def get_var(self, stage: str, var: str):
        """The authoritative ``yk_var`` for ``stage.var`` in whichever
        arm is prepared.  Bound consumer inputs do not exist fused
        (they were eliminated); init the producer instead.  Push-fused
        intermediates raise: their rings are stale by design."""
        self._check_prepared()
        if self._fused:
            for b in self.bindings:
                if (b.consumer_stage, b.consumer_var) == (stage, var):
                    raise YaskException(
                        f"{stage}.{var} is a bound input eliminated by "
                        f"fusion; it is fed by "
                        f"{b.producer_stage}.{b.producer_var}")
            mname = f"{stage}{SEP}{var}"
            if mname in self.pushed_vars():
                raise YaskException(
                    f"{stage}.{var} is push-fused: its tiles are "
                    f"consumed in-VMEM and never written back to HBM, "
                    f"so the ring is stale after run(); read the final "
                    f"stage's outputs, or prepare with push off "
                    f"(-push off)")
            return self._fused_ctx.get_var(mname)
        return self._stage_ctxs[stage].get_var(var)

    @property
    def fused_ctx(self):
        return self._fused_ctx

    def stage_ctx(self, stage: str):
        return self._ensure_stage_ctxs()[stage]

    # -- execution -----------------------------------------------------

    def run(self, first_step_index: int, last_step_index: int) -> None:
        """Run the prepared arm over [first, last].  Fused: one program
        step does all stages (consumers read producers in-tile/at the
        same scan step).  Host-chained: per step, per stage in order —
        push inbound bindings (producer's fresh value, interior only;
        pads stay zero by the ghost-zero invariant), then one step —
        the exact semantics the merged rewrite encodes, making this
        arm the bit-equality oracle."""
        self._check_prepared()
        if self._fused:
            guarded_call(self._fused_ctx.run_solution,
                         first_step_index, last_step_index,
                         site="pipeline.run")
            return
        self._run_chained(first_step_index, last_step_index)

    def _run_chained(self, first_step_index: int,
                     last_step_index: int) -> None:
        """The host-chained schedule, callable regardless of which arm
        is pinned (the auto-tuner times it against the fused chunk at
        the winning point)."""
        ctxs = self._ensure_stage_ctxs()
        c0 = ctxs[self.stage_names[0]]
        start, n = c0._step_seq(first_step_index, last_step_index)
        sdir = c0._ana.step_dir
        for i in range(n):
            t = start + i * sdir
            for s in self.stage_names:
                for b in self._pushable:
                    if b.consumer_stage == s:
                        self._push_binding(b, t + sdir)
                guarded_call(ctxs[s].run_solution, t, t,
                             site="pipeline.run")

    def _push_binding(self, b: PipelineBinding, t_new: int) -> None:
        ctxs = self._stage_ctxs
        pctx = ctxs[b.producer_stage]
        pv = pctx.get_var(b.producer_var)
        cv = ctxs[b.consumer_stage].get_var(b.consumer_var)
        lo, hi = [], []
        for d in pv.get_dim_names():
            if d == pctx.get_step_dim_name():
                lo.append(t_new)
                hi.append(t_new)
            else:
                lo.append(0)
                hi.append(pctx.get_overall_domain_size(d) - 1)
        buf = pv.get_elements_in_slice(lo, hi)
        dom = [d for d in pv.get_dim_names()
               if d != pctx.get_step_dim_name()]
        buf = buf.reshape([pctx.get_overall_domain_size(d) for d in dom])
        clo = [0] * len(dom)
        chi = [pctx.get_overall_domain_size(d) - 1 for d in dom]
        cv.set_elements_in_slice(buf, clo, chi)

    # -- comparison (the bit-equality gate) ----------------------------

    def written_vars(self, stage: str) -> List[str]:
        soln = self._solns[stage]
        scratch = {v.get_name() for v in soln.get_vars()
                   if v.is_scratch()}
        return sorted(_written_names(soln) - scratch)

    def _interior(self, stage: str, var: str, t: Optional[int]):
        v = self.get_var(stage, var)
        ctx = self._fused_ctx if self._fused else self._stage_ctxs[stage]
        lo, hi = [], []
        for d in v.get_dim_names():
            if v.get_step_dim_name() and d == v.get_step_dim_name():
                lo.append(t)
                hi.append(t)
            elif d in ctx.get_domain_dim_names():
                lo.append(0)
                hi.append(ctx.get_overall_domain_size(d) - 1)
            else:
                lo.append(v.get_first_misc_index(d))
                hi.append(v.get_last_misc_index(d))
        return np.asarray(v.get_elements_in_slice(lo, hi))

    def compare(self, other: "SolutionPipeline", epsilon: float = 0.0,
                abs_epsilon: float = 0.0) -> int:
        """Count mismatching interior elements of every written var of
        every stage against another pipeline that ran the same steps
        (over the step indices valid in BOTH rings).  ``epsilon=0``
        is exact bit-equality — the fused-vs-chained gate."""
        self._check_prepared()
        other._check_prepared()
        bad = 0
        # push-fused intermediates have stale rings in whichever arm
        # pushed them — only vars observable in BOTH arms participate
        skip = self.pushed_vars() | other.pushed_vars()
        for s in self.stage_names:
            for vn in self.written_vars(s):
                if f"{s}{SEP}{vn}" in skip:
                    continue
                va, vb = self.get_var(s, vn), other.get_var(s, vn)
                if va.get_step_dim_name():
                    ts = range(max(va.get_first_valid_step_index(),
                                   vb.get_first_valid_step_index()),
                               min(va.get_last_valid_step_index(),
                                   vb.get_last_valid_step_index()) + 1)
                else:
                    ts = [None]
                for t in ts:
                    a = self._interior(s, vn, t)
                    b = other._interior(s, vn, t)
                    tol = epsilon * np.maximum(np.abs(a), np.abs(b)) \
                        + abs_epsilon
                    bad += int(np.sum(~(np.abs(a - b) <= tol)))
        return bad

    # -- teardown ------------------------------------------------------

    def end(self) -> None:
        if self._fused_ctx is not None and self._fused_ctx.is_prepared():
            self._fused_ctx.end_solution()
        for ctx in (self._stage_ctxs or {}).values():
            if ctx.is_prepared():
                ctx.end_solution()


# ---------------------------------------------------------------------------
# the headline chain
# ---------------------------------------------------------------------------


def rtm_chain(radius: int = 2, accumulate: bool = True):
    """The 3-stage RTM-like chain (forward acoustic step → imaging
    condition → 3-point smoothing): ``(stages, bindings)`` ready for
    :class:`SolutionPipeline` — shared by the bench A/B, the session
    stage, tests, and the example.

    ``accumulate=False`` swaps the imaging stage for the
    non-accumulating ``rtm_img_pure`` (per-shot correlation, no
    ``img(t)`` self-read): the merged image var's only reader is then
    the smoother at ``+step_dir``, making it the push-memory fusion
    flagship — its tile never round-trips HBM."""
    from yask_tpu.compiler.solution_base import create_solution
    img = "rtm_img" if accumulate else "rtm_img_pure"
    stages = [("fwd", create_solution("rtm_fwd", radius=radius)),
              ("img", create_solution(img)),
              ("smooth", create_solution("rtm_smooth"))]
    bindings = [("img", "fwd_in", "fwd", "pressure"),
                ("smooth", "img_in", "img", "img")]
    return stages, bindings
