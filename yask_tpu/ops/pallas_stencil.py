"""Pallas stencil kernels: halo tiles in VMEM + K-step temporal fusion.

This is the TPU replacement for the reference's generated inner loops
(vector folding + nano/pico loops, ``YaskKernel.cpp:574-676``) *and* its
temporal wave-front tiling (``context.hpp:331-347``): one kernel invocation

1. DMAs an (bx+2·r·K, by+2·r·K, Nz_padded) halo tile of each input var
   from HBM into VMEM (the fold/tile planner's job: the minor-most dim
   stays whole so it rides the 128-lane axis);
2. applies **K fused time steps** entirely in VMEM — the compute region
   shrinks by the stencil radius each sub-step (the trapezoid/wavefront
   shape), and a global-domain mask keeps physical-boundary ghosts at
   zero between sub-steps (matching the runtime's ghost semantics);
3. writes the final (and, for 2-slot rings, the previous) time level's
   interior block back.

HBM traffic per K steps ≈ one read + one write of each var, versus K of
each for the unfused path — the same arithmetic-intensity win wave-front
tiling buys the reference.

Applicability (checked by :func:`pallas_applicable`): every var's last
domain dim must be the solution minor (Mosaic lane-DMA alignment) and
its domain dims must follow solution order.  Multi-stage chains, sub-
domain/step conditions, scratch-var chains (evaluated in-tile over
write-halo-expanded regions), misc-dim vars, partial-dim vars (read,
written, or scratch — their RHS is constant along the missing dims per
the analysis race rule), 1-D solutions (one full-lane tile), and
arbitrary ring depth are all handled in-kernel; the rest falls back to
the XLA-fused path.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Dict, List, Optional, Tuple

from yask_tpu.utils.exceptions import YaskException
from yask_tpu.ops.tile_planner import _INTERPRET_PLAN_BUDGET
from yask_tpu.compiler.expr import (
    AddExpr,
    AndExpr,
    CompExpr,
    ConstExpr,
    DivExpr,
    Expr,
    FirstIndexExpr,
    FuncExpr,
    IndexExpr,
    LastIndexExpr,
    ModExpr,
    MultExpr,
    NegExpr,
    NotExpr,
    OrExpr,
    SubExpr,
    VarPoint,
)


def pallas_applicable(csol) -> Tuple[bool, str]:
    """Can this solution run on the Pallas fused path? Supported: multi-
    stage chains (ssg/fsg-class), sub-domain/step conditions (awp-class —
    lowered to in-tile masks over global coordinates), index-value
    expressions, partial-dim vars — read-only coefficients (sponge
    factors), written, and scratch alike, their RHS being constant
    along the missing dims per the analysis race rule — scratch-var
    chains evaluated in-tile over expanded regions (tti/swe2d-class),
    misc-dim vars including written ones (filter kernels — constant LHS
    misc values pin the write), 1-D solutions (one full-lane tile,
    empty grid), and any ring allocation (deep time reads,
    2nd-order-in-time schemes). Excluded: vars whose last domain dim is
    not the solution minor (Mosaic lane-DMA alignment) and written vars
    with no domain dims at all."""
    ana = csol.ana
    if not ana.domain_dims:
        return False, "needs >= 1 domain dim"
    # 1-D solutions tile as a single full-lane block (empty grid): the
    # whole padded line is one VMEM tile, K-fusion included
    minor = ana.domain_dims[-1]
    for v in csol.soln.get_vars():
        dd = v.domain_dim_names()
        if v.is_written:
            # Partial-dim written vars are supported when they keep the
            # minor (lane) dim: the RHS is constant along the missing
            # lead dims (same rule the XLA path's _to_var_layout
            # applies), so every tile computes the identical slab and
            # the sequential grid's repeated write-back is benign.  A
            # written var missing the minor dim would need lane-axis
            # DMA windows at non-128 offsets (Mosaic rule below).
            if not dd:
                return False, (f"written var '{v.get_name()}' has no "
                               "domain dims (per-step scalar reduction "
                               "stays on the XLA path)")
            if dd[-1] != minor:
                return False, (f"written var '{v.get_name()}' lacks the "
                               f"minor dim '{minor}' as its last domain "
                               "dim (Mosaic lane-DMA alignment)")
            if dd != [d for d in ana.domain_dims if d in dd]:
                return False, (f"var '{v.get_name()}' declares domain "
                               "dims out of solution order")
        else:
            # Mosaic DMA windows constrain the lane (last physical) axis
            # to 128-aligned full-extent fetches; a read-only var whose
            # lane axis is a *lead* dim would need pid-dependent lane
            # offsets, which TC vector loads cannot do (probed on v5e).
            if dd and dd[-1] != minor:
                return False, (f"read-only var '{v.get_name()}' lacks the "
                               f"minor dim '{minor}' as its last domain "
                               "dim (Mosaic lane-DMA alignment)")
            if dd and dd != [d for d in ana.domain_dims if d in dd]:
                return False, (f"var '{v.get_name()}' declares domain dims "
                               "out of solution order")

    return True, "ok"


# ---------------------------------------------------------------------------


class _TileEval:
    """Evaluate the stencil AST on VMEM tile values.

    ``tiles[name]`` is the ring of tile arrays (oldest→newest); a read at
    offset ``o`` over compute-region ``lo..hi`` (tile coords, leading
    dims; interior-relative for the minor dim) slices ``[lo+o : hi+o]``
    with the var's own origins. Partial-dim read-only vars broadcast into
    the region; index expressions produce *global* coordinate arrays so
    conditions behave identically to the XLA path.
    """

    def __init__(self, jnp, program, minor: str,
                 minor_origin: Dict[str, int],
                 resid: Optional[Dict[Tuple[str, str], int]] = None):
        self.jnp = jnp
        self.program = program
        self.resid = resid or {}   # (var, lead dim) -> static tile shift
        self.dims = program.ana.domain_dims
        self.minor = minor
        self.step_dir = program.ana.step_dir
        self.minor_origin = minor_origin
        from yask_tpu.compiler.lowering import JnpOps
        self.ops = JnpOps()
        # set per-(stage, sub-step) by the kernel before evaluation:
        self.region = None          # [(lo,hi)] per solution dim
        self.gidx_base = None       # per lead dim: traced global offset of
        #                             tile position 0 (pid*block - hK)
        self.t = None               # step-index value (traced or None)
        self.scratch = {}           # scratch var -> full-tile value
        self.misc_env = {}          # current equation's LHS misc binding

    def global_index(self, d: str):
        """Global coordinate array for dim d over the current region,
        broadcast-shaped. ``gidx_base`` maps tile position 0 to the
        global-problem coordinate (it includes the shard offset in
        distributed mode)."""
        di = self.dims.index(d)
        lo, hi = self.region[di]
        shape = [1] * len(self.dims)
        shape[di] = hi - lo
        # broadcasted_iota, not 1-D arange+reshape: Mosaic TC crashes on
        # non-lane-axis 1-D iota (probed on TPU v5e)
        from jax import lax
        ar = lax.broadcasted_iota(self.jnp.int32, tuple(shape), di) + lo
        base = self.gidx_base.get(d)
        if base is not None:
            ar = ar + base
        return ar

    def read(self, p: VarPoint, tiles, computed):
        name = p.var_name()
        g = self.program.geoms[name]
        so = p.step_offset()
        region = self.region
        if g.is_scratch:
            # Scratch values live as full-tile arrays computed earlier in
            # this sub-step over an expanded region, so offset slicing
            # works exactly like ring tiles.
            arr = self.scratch[name]
        elif name in computed and so is not None and so == self.step_dir:
            # Same-step read of an earlier stage's output: computed values
            # are kept as FULL tiles (written via .at[region].set on the
            # evicted base), so offset slicing works exactly like rings.
            arr = computed[name]
        else:
            ring = tiles[name]
            if so is None or not g.is_written:
                arr = ring[-1]
            else:
                idx = len(ring) - 1 + so * self.step_dir
                if not (0 <= idx < len(ring)):
                    # mirror the XLA path's bounds check — a negative
                    # Python index would silently wrap to the newest slot
                    raise YaskException(
                        f"step offset {so} of '{name}' outside its "
                        f"allocation {len(ring)}")
                arr = ring[idx]
        offs = p.domain_offsets()
        misc = p.misc_vals()
        idxs = []
        for dn, kind in g.axes:   # var's own axis order
            if kind == "misc":
                idxs.append(misc[dn] - g.misc_lo[dn])
                continue
            di = self.dims.index(dn)
            lo, hi = region[di]
            o = offs.get(dn, 0)
            if dn == self.minor:
                base = self.minor_origin[name]
                idxs.append(slice(base + lo + o, base + hi + o))
            else:
                rs = self.resid.get((name, dn), 0)
                idxs.append(slice(rs + lo + o, rs + hi + o))
        if not g.axes:
            out = arr[0]   # 0-dim var rides SMEM as shape (1,)
        else:
            out = arr[tuple(idxs)]

        var_dd = g.domain_dims
        if var_dd != self.dims:
            # partial-dim (or reordered) var: transpose into solution
            # order, insert singleton axes, broadcast over the region
            present = [d for d in self.dims if d in var_dd]
            perm = [var_dd.index(d) for d in present]
            if perm != list(range(len(perm))):
                out = out.transpose(perm)
            shape = []
            for d in self.dims:
                di = self.dims.index(d)
                lo, hi = region[di]
                shape.append(hi - lo if d in var_dd else 1)
            out = out.reshape(tuple(shape))
            tgt = tuple(hi - lo for lo, hi in region)
            out = self.jnp.broadcast_to(out, tgt)
        return out

    def eval(self, e: Expr, tiles, computed, memo):
        k = e.skey()   # structural: CSE across equations within a sub-step
        if k in memo:
            return memo[k]
        jnp = self.jnp
        ev = lambda a: self.eval(a, tiles, computed, memo)
        if isinstance(e, ConstExpr):
            r = e.value
        elif isinstance(e, VarPoint):
            r = self.read(e, tiles, computed)
        elif isinstance(e, IndexExpr):
            if e.type.value == "step":
                r = self.t
            elif e.type.value == "domain":
                r = self.global_index(e.name)
            else:
                # per-equation LHS-pinned constant; never memoized (the
                # node recurs in sibling eqs with different bindings)
                mv = self.misc_env or {}
                if e.name not in mv:
                    raise YaskException(
                        f"misc index '{e.name}' used as a value outside "
                        "an equation that pins it on the LHS")
                return mv[e.name]
        elif isinstance(e, FirstIndexExpr):
            r = 0
        elif isinstance(e, LastIndexExpr):
            r = self.program.global_last[e.dim.name]
        elif isinstance(e, NegExpr):
            r = -ev(e.arg)
        elif isinstance(e, AddExpr):
            r = ev(e.args[0])
            for a in e.args[1:]:
                r = r + ev(a)
        elif isinstance(e, MultExpr):
            r = ev(e.args[0])
            for a in e.args[1:]:
                r = r * ev(a)
        elif isinstance(e, SubExpr):
            r = ev(e.lhs) - ev(e.rhs)
        elif isinstance(e, DivExpr):
            r = ev(e.lhs) / ev(e.rhs)
        elif isinstance(e, ModExpr):
            r = ev(e.lhs) % ev(e.rhs)
        elif isinstance(e, FuncExpr):
            from yask_tpu.compiler.expr import paired_func_eval
            r = paired_func_eval(
                self.ops.func, e, [ev(a) for a in e.args], memo,
                getattr(self.program.ana, "sincos_args", ()))
        elif isinstance(e, CompExpr):
            a, b = ev(e.lhs), ev(e.rhs)
            r = {"==": lambda: a == b, "!=": lambda: a != b,
                 "<": lambda: a < b, "<=": lambda: a <= b,
                 ">": lambda: a > b, ">=": lambda: a >= b}[e.op]()
        elif isinstance(e, AndExpr):
            r = jnp.logical_and(ev(e.lhs), ev(e.rhs))
        elif isinstance(e, OrExpr):
            r = jnp.logical_or(ev(e.lhs), ev(e.rhs))
        elif isinstance(e, NotExpr):
            r = jnp.logical_not(ev(e.arg))
        else:  # pragma: no cover - excluded by pallas_applicable
            raise YaskException(f"pallas path cannot evaluate {type(e)}")
        memo[k] = r
        return r


# ---------------------------------------------------------------------------


def skew_eligible_dims(program, fuse_steps: int) -> List[str]:
    """The lead dims the skewed wavefront CAN run on (lead order),
    feasibility only.  Candidates are the innermost grid dim
    (``lead[-1]``, consecutive sequential steps — strips carry tile to
    tile) and the second-innermost (``lead[-2]``, one grid row back —
    strips carry through a row-length buffer).  Deeper lead dims keep
    the uniform shrink.  A dim qualifies when its fused radius is > 0;
    the whole set is empty unless K ≥ 2 and every written var spans all
    domain dims (a partial-dim write slab's slice index would become
    pid-dependent under skewed regions)."""
    ana = program.ana
    lead = ana.domain_dims[:-1]
    if fuse_steps < 2 or not lead:
        return []
    for g in program.geoms.values():
        if g.is_written and not g.is_scratch \
                and g.domain_dims != ana.domain_dims:
            return []
    rad = ana.fused_step_radius()
    return [d for d in lead[-2:] if rad.get(d, 0) > 0]


def skew_eligible(program, fuse_steps: int) -> bool:
    """CAN the skewed wavefront run at all for this (program, K)?
    Feasibility only — an explicit ``skew=True`` needs just this; the
    auto-engage decision additionally applies the per-dim profit gate
    (:func:`skew_engaged_dims`)."""
    lead = program.ana.domain_dims[:-1]
    return bool(lead) and lead[-1] in skew_eligible_dims(
        program, fuse_steps)


def skew_extra_width(dtype, r: int) -> int:
    """E_sk: the extra computed sublane-dim width a skewed region needs
    when the radius is not a sublane multiple (write-back shifts round
    DOWN to the tile and the window widens by one tile; need
    E ≥ d + sub_t with d = shift−floor(shift) < sub_t ⇒ 2·sub_t).
    THE single definition — the profit gate, the planner hints, the
    build's margins, and the runtime's pad planning must all agree."""
    from yask_tpu.compiler.lowering import tpu_tile_dims
    sub_t, _ = tpu_tile_dims(dtype)
    return 2 * sub_t if r % sub_t != 0 else 0


def skew_extra_widths(program, fuse_steps: int) -> Dict[str, int]:
    """Per-dim E_sk for every skew-eligible dim.  Only the stream dim
    (``lead[-1]``) is the sublane (8-aligned-window) axis of the
    written full-dim vars, so only it pays the rounding widening; the
    second dim is an untiled leading DMA axis on TPU — offsets there
    are unconstrained and its write shifts express exactly (E_sk=0)."""
    ana = program.ana
    lead = ana.domain_dims[:-1]
    rad = ana.fused_step_radius()
    out = {}
    for d in skew_eligible_dims(program, fuse_steps):
        out[d] = (skew_extra_width(program.dtype, rad.get(d, 0))
                  if d == lead[-1] else 0)
    return out


def skew_engaged_dims(program, fuse_steps: int, unsharded=None,
                      max_dims: int = 2) -> List[str]:
    """The dims ``build_pallas_chunk`` auto-engages (``skew=None``),
    lead order: eligible AND per-dim profit gate — a skewed dim
    computes (K+1)·r + E_sk extra width per tile vs 2·K·r for uniform
    shrink, so each dim engages independently (misaligned small stream
    radii lose to their own E_sk widening; the second dim has E_sk=0
    and profits whenever r > 0 at K ≥ 2).  ``unsharded`` restricts to
    mesh-undecomposed dims (carry strips cannot cross shards); ``None``
    = all unsharded (single device).  ``max_dims`` bounds the candidate
    WINDOW from the innermost dim out (the ``-skew_dims`` knob): 1 =
    the stream dim only — exactly the pre-multi-dim behavior, so the
    1-D A/B arm never silently swaps in the outer dim.  THE shared
    definition for the build, planner hints, and the HBM traffic
    model, so bench/stats describe the tiling actually run."""
    ana = program.ana
    lead = ana.domain_dims[:-1]
    rad = ana.fused_step_radius()
    e_sk = skew_extra_widths(program, fuse_steps)
    K = fuse_steps
    if max_dims <= 0:
        return []
    window = lead[-max_dims:]
    picked = []
    for d in skew_eligible_dims(program, fuse_steps):
        if d not in window:
            continue
        if unsharded is not None and d not in unsharded:
            continue
        r = rad.get(d, 0)
        if (K + 1) * r + e_sk[d] < 2 * K * r:
            picked.append(d)
    return picked


def skew_auto_engages(program, fuse_steps: int) -> bool:
    """Back-compat boolean: would the STREAM dim auto-engage
    (``skew=None``, single device)?  Same stream-dim gate as
    :func:`skew_engaged_dims` — callers that need the full per-dim
    decision use that directly."""
    lead = program.ana.domain_dims[:-1]
    return bool(lead) and lead[-1] in skew_engaged_dims(
        program, fuse_steps)


def skew_plan_hints(program, fuse_steps: int, engaged=None):
    """(min_block, margin_override) for :func:`plan_blocks` when the
    skewed wavefront engages — THE shared definition for the build and
    the auto-tuner's seed plan: each engaged dim's block is floored at
    the carry minimum (ring+1)·r, and its margin modeled as the
    (K+1)·r + E_sk the skew actually fetches (not 2·K·r).  ``engaged``
    overrides the auto decision: ``None`` = auto
    (:func:`skew_engaged_dims`), ``True`` = the stream dim (the legacy
    forced-1-D form), ``False`` = none, or an explicit list of dims
    (the build passes its resolved skew set).  Returns (None, None)
    when skew won't run."""
    ana = program.ana
    lead = ana.domain_dims[:-1]
    if engaged is None:
        engaged = skew_engaged_dims(program, fuse_steps)
    elif engaged is True:
        engaged = [lead[-1]] if lead else []
    elif engaged is False:
        engaged = []
    if not engaged:
        return None, None
    rad = ana.fused_step_radius()
    e_sk = skew_extra_widths(program, fuse_steps)
    # the TilePlan is THE margin-math source: hints are read off the
    # dataflow plan rather than recomputed here
    from yask_tpu.ops.tile_planner import TilePlan
    e_full = {d: e_sk.get(d, skew_extra_width(program.dtype,
                                              rad.get(d, 0))
                          if d == lead[-1] else 0)
              for d in engaged}
    tp = TilePlan(program, fuse_steps, skew_dims=engaged, e_sk=e_full)
    return tp.min_block(), tp.margin_override()


def trapezoid_eligible_dims(program, fuse_steps: int) -> List[str]:
    """The lead dims the two-phase trapezoid/diamond tiling CAN run on
    (lead order), feasibility only.  The geometric constraints are the
    skew set's (K ≥ 2, radius > 0, full-dim written vars, the two
    innermost grid dims): phase-1 upright trapezoids reuse the uniform
    region machinery with one-step margins, and the diamond fill pass
    reuses it with uniform margins, so anything the skew carries could
    tile, independent trapezoids can too.  Distribution and region
    restrictions are rejected by the build (the fill pass assumes the
    full span of a single device)."""
    return skew_eligible_dims(program, fuse_steps)


def trapezoid_pad_need(dtype, rd: int, k: int) -> int:
    """Per-side lead-dim pad the two-phase trapezoid tiling needs at
    fuse depth ``k`` (single definition — the runtime's pad planning
    and the build agree): the diamond fill tile reaches ``cl(K) + K·r``
    past each phase-1 tile boundary (half-band + uniform telescoping
    margin) plus one sublane tile of DMA slab rounding."""
    if rd <= 0 or k < 2:
        return rd * max(k, 1)
    from yask_tpu.compiler.lowering import tpu_tile_dims
    sub_t, _ = tpu_tile_dims(dtype)
    cl = -(-((k - 1) * rd) // sub_t) * sub_t
    return k * rd + cl + 2 * sub_t


def default_vmem_budget(platform: str) -> int:
    """Device-derived Pallas VMEM *tile* budget (overridable via
    ``-vmem_mb``). Probed on v5e: ≥120 MiB VMEM is usable once the
    kernel raises Mosaic's 16 MiB default scoped limit via
    ``vmem_limit_bytes``. The tile model budgets 64 MiB so live SSA
    values (≈ a second copy of the tiles) still fit under the raised
    limit. Under CPU interpret VMEM is emulated and the budget only
    shapes planning. Single definition for the runtime context, harness
    tools, and bench — reads the backend capability table."""
    from yask_tpu.backend import capability_for_platform
    return capability_for_platform(platform).plan_budget_bytes()


def vmem_limit_bytes(vmem_budget: int) -> int:
    """Scoped Mosaic VMEM limit requested for a given tile budget:
    live-multiplier × the budget (live SSA values ≈ a second copy of
    the tiles), capped safely below the probed v5e ceiling.  Single
    definition — the kernel's CompilerParams and the static checker's
    spill model both use it; the numbers live in the capability table."""
    from yask_tpu.backend import get_capability
    return get_capability().vmem_limit_bytes(vmem_budget)


def push_eligible_vars(program) -> Dict[str, str]:
    """Per written non-scratch var: ``"ok"`` when its VMEM output tile
    can be PUSHED to its consumers inside the grid step (no input DMA,
    no write-back — the push-memory tile-graph fusion), else the reason
    it cannot.  THE single eligibility definition — the build, the
    pipeline planner, and the checker's explain pass all read it.

    A var is pushable exactly when every read of it anywhere in the
    program is a same-sub-step read of the value written this sub-step
    (step offset ``+step_dir`` — the read rides the kernel's
    ``computed`` dict, never a ring tile), its writes are unconditional
    over the full domain (so the in-kernel zero-seeded base tile is
    bit-equivalent to the HBM ghost-zero pads on every cell a consumer
    can reach), and it has at least one such reader (a never-read
    written var is a final OUTPUT — it must stay on the write-DMA
    path).  Full-dim, misc-free vars only: partial-dim write slabs and
    misc-pinned writes leave base cells the zero seed cannot
    reproduce."""
    from yask_tpu.compiler.expr import PointVisitor
    ana = program.ana
    dims = ana.domain_dims
    sd = ana.step_dir
    # reads per var across EVERY equation (rhs + conditions, scratch
    # eqs included): step offsets seen anywhere in the program
    read_offs: Dict[str, set] = {}
    writers: Dict[str, List] = {}
    for eq in ana.eqs:
        name = eq.lhs.var_name()
        writers.setdefault(name, []).append(eq)
        pv = PointVisitor()
        eq.rhs.accept(pv)
        if eq.cond is not None:
            eq.cond.accept(pv)
        if eq.step_cond is not None:
            eq.step_cond.accept(pv)
        for p in pv.points:
            read_offs.setdefault(p.var_name(), set()).add(
                p.step_offset())
    out: Dict[str, str] = {}
    for n in sorted(program.geoms):
        g = program.geoms[n]
        if not g.is_written or g.is_scratch:
            continue
        if g.domain_dims != dims:
            out[n] = ("partial-dim written var (zero-seeded base tile "
                      "cannot reproduce the repeated-write slab)")
            continue
        if any(kind == "misc" for _dn, kind in g.axes):
            out[n] = ("misc axes (unwritten misc slices would read the "
                      "zero seed instead of the HBM values)")
            continue
        offs = read_offs.get(n, set())
        if not offs:
            out[n] = "never read (final output stays on the DMA path)"
            continue
        if offs != {sd}:
            bad = sorted(o if o is not None else 0
                         for o in offs if o != sd)
            out[n] = (f"read at step offsets {bad} (ring/same-level "
                      "reads need the HBM ring state)")
            continue
        if any(eq.cond is not None or eq.step_cond is not None
               for eq in writers.get(n, [])):
            out[n] = ("conditional write (unselected cells keep the "
                      "base tile, which a pushed var seeds with zeros)")
            continue
        out[n] = "ok"
    return out


def build_pallas_chunk(program, fuse_steps: int = 1,
                       block: Optional[Tuple[int, ...]] = None,
                       interpret: bool = False,
                       vmem_budget: int = _INTERPRET_PLAN_BUDGET,
                       distributed: bool = False,
                       pipeline_dmas: Optional[bool] = None,
                       skew=None,
                       vinstr_cap: int = 300_000,
                       stream_unsharded: bool = False,
                       unsharded_dims=None,
                       max_skew_dims: int = 2,
                       plan_only: bool = False,
                       reasons: Optional[List[dict]] = None,
                       region: Optional[Dict[str, Tuple[int, int]]] = None,
                       trapezoid=False,
                       push=False,
                       _diamond: Optional[dict] = None):
    """Build ``chunk(state, t0) -> state`` advancing ``fuse_steps`` steps
    in one fused Pallas sweep.

    ``program`` must be planned with ``extra_pad`` ≥ the fused halo
    (radius × fuse_steps) in the leading dims — the runtime arranges this.
    Returns (chunk_fn, tile_bytes).

    With ``distributed=True`` the chunk is the per-shard inner kernel of
    the shard_map+pallas path: it takes a third argument ``offsets`` (an
    i32 vector of this shard's global origin per domain dim, traced from
    ``lax.axis_index``) and the zero-outside-domain mask uses GLOBAL
    coordinates — so points in exchanged shard ghosts update through the
    fused sub-steps while true physical boundaries stay zero. ``program``
    must then be the per-shard plan built with ``global_sizes`` (its
    ``global_last`` drives last_domain_index conditions).

    ``skew`` selects the streaming skewed-wavefront tiling: in each
    skewed grid dim a fused sub-step's compute region shifts left by the
    step radius instead of shrinking symmetrically, and the inter-tile
    boundary strips each sub-step needs from its already-computed
    neighbor ride a persistent VMEM carry.  This removes BOTH the
    redundant margin recompute and the 2·r·K-wide halo DMA of the
    uniform shrink in that dim — the TPU-native answer to the
    reference's multi-dim trapezoid blocking (``setup.cpp:863``,
    ``context.cpp:838``), whose phase coloring exists to create *thread*
    parallelism a sequential Pallas grid does not need.  Up to TWO dims
    skew (``max_skew_dims``, the ``-skew_dims`` knob): the innermost
    grid dim (``lead[-1]`` — consecutive sequential steps, a single
    carry strip) and the second-innermost (``lead[-2]`` — the neighbor
    ran one grid row earlier, so its carry buffers a whole inner row,
    indexed by the inner program id).  The lane-minor dim always keeps
    the uniform shrink (Mosaic 128-lane window alignment).  ``None`` =
    auto: each eligible dim engages independently when its margin model
    says it pays (``skew_engaged_dims``); ``True`` = force the stream
    dim only (the legacy 1-D A/B form); a list of dims = force exactly
    those (raising when infeasible); ``False`` = uniform shrink.
    Distributed chunks may skew too, but only along UNSHARDED dims
    (``unsharded_dims`` / legacy ``stream_unsharded``): the carry then
    never crosses a shard boundary and the radius×K ghost pads cover
    the skew margins whenever the profit gate engages (mR = r+E_sk ≤
    r·K exactly when E_sk < (K−1)·r); mesh-decomposed dims keep the
    uniform shrink.

    Every planning decision (skew engage/reject, ladder fallback, block
    shrink, DMA-pipelining on/off) appends a structured reason code to
    ``reasons`` — surfaced through ``chunk.tiling["reasons"]`` and read
    by the static checker's explain pass.  ``plan_only=True`` stops
    after planning (no kernel is traced, nothing allocates) and returns
    the plan dict instead of ``(chunk, tile_bytes)``.

    ``region`` restricts the OUTPUT sub-range per leading dim to
    ``{dim: (lo, hi)}`` in interior coordinates: the grid covers only
    the restricted span, fetch margins are re-derived from the
    restricted origin, and the global-coordinate mask stays exact.
    This is the core/shell split primitive of the overlapped
    shard_pallas exchange schedule (the fused-chunk analog of the
    reference's interior/exterior MPI overlap, ``context.cpp:377-478``).
    Correctness contract for callers: only interior cells inside the
    region (plus ceil-coverage window overshoot, whose values are NOT
    valid) are written — the scheduler must patch every cell outside
    the region from another chunk's output before use.  A restricted
    dim that is some written var's sublane axis must have a
    ``sub_t``-aligned ``lo`` (output DMA windows keep 8-aligned
    offsets on real Mosaic — raises otherwise), and restricted dims
    never skew (their carry geometry assumes the full span).

    ``trapezoid`` selects the two-phase trapezoid/diamond temporal
    tiling (the reference's trapezoidal blocking, ``setup.cpp:863``,
    recast for a parallel Pallas grid): phase 1 decomposes each
    K-group along the selected dims into carry-free upright trapezoids
    (one-step fetch margins; level ``lvl``'s write window shrinks by
    (lvl−1)·r per side) that are mutually independent — so those grid
    dims are declared ``"parallel"`` instead of ``"arbitrary"`` — and
    phase 2 fills the inter-tile gap bands with inverted trapezoids
    (diamonds) centered on every tile boundary, recomputed from the
    level-0 input state (no carries, any ring depth / stage count).
    ``False`` = off (the default), ``None`` = auto via the TilePlan
    profit gate (trapezoid vs skew vs uniform volumes), ``True`` =
    force the eligible window dims, a list = force exactly those.
    Trapezoid and skew are mutually exclusive (carries impose the
    sequential grid the trapezoid exists to remove); engaged trapezoid
    also disables both DMA pipelines (the linear-index prefetch
    assumes sequential order).  Single-device, unrestricted builds
    only.  ``_diamond`` is the internal fill-pass parametrization (the
    build recurses once per trapezoid dim); its chunk returns raw
    per-boundary band arrays the outer chunk stitches host-side.

    ``push`` selects the push-memory tile-graph fusion: an eligible
    intermediate var's VMEM output tile is consumed by its reader
    stages inside the grid step (the kernel's ``computed`` dict already
    carries it) and the var leaves BOTH HBM paths — its input tiles are
    never DMA'd in and its outputs never written back, so each K-group
    saves one full read + one full write of the var (the pipeline HBM
    model's 48→24 bytes/pt halving on the RTM chain).  Eligibility is
    :func:`push_eligible_vars` (every read program-wide at step offset
    ``+step_dir``, unconditional full-dim misc-free writes, ≥ 1
    reader); trapezoid/diamond builds decline (the fill pass recomputes
    from level-0 HBM state a pushed var no longer has) and so do
    distributed builds (scope: single device).  ``False`` = off (the
    default — a pushed var's HBM ring goes STALE, so plain solutions
    keep every var observable); ``None`` = auto-engage every eligible
    var (the pipeline runtime's fused path); ``True`` = force (raises
    when nothing is eligible); a list = force exactly those vars
    (raising on any ineligible name).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    ana = program.ana
    dims = ana.domain_dims
    K = fuse_steps
    if reasons is None:
        reasons = []
    from yask_tpu.compiler.expr import uses_misc_index
    has_misc_value = any(
        uses_misc_index(eq.rhs, eq.cond, eq.step_cond) for eq in ana.eqs)
    lead = dims[:-1]
    minor = dims[-1]

    # Per-stage, per-leading-dim read radius: within one fused sub-step a
    # stage consumes its radius of tile margin (same-step chains eat
    # margin stage by stage — the trapezoid accounting of the reference's
    # temporal blocking, setup.cpp:863).
    nstages = len(ana.stages)
    stage_r: List[Dict[str, int]] = []
    for si in range(nstages):
        sr = {d: 0 for d in lead}
        for vname, widths in program.stage_reads[si].items():
            for d, (l, r) in widths.items():
                if d in sr:
                    sr[d] = max(sr[d], l, r)
        stage_r.append(sr)
    # full-step shrink per dim = sum over stages; fused halo = K x that
    # (fused_step_radius is the single source both here and in the
    # runtime's pad planning)
    rad_all = ana.fused_step_radius()
    rad = {d: rad_all.get(d, 0) for d in lead}
    hK = {d: rad[d] * K for d in lead}

    sizes = {d: program.sizes[d] for d in dims}

    # Streaming skew rides the innermost grid dim (the one consecutive
    # sequential grid steps advance by +1, so the VMEM carry written by
    # step i is what step i+1 patches in).
    sdim = lead[-1] if lead else None
    ring_read_vars = set()
    for sr_ in program.stage_reads:
        ring_read_vars.update(sr_.keys())
    from yask_tpu.compiler.lowering import tpu_tile_dims
    sub_t, _lane_t = tpu_tile_dims(program.dtype)

    # ---- region restriction (core/shell split) -------------------------
    # reg_lo shifts every window origin; span replaces sizes[d] in the
    # grid/coverage math.  The minor dim always rides whole (lane-axis
    # windows cannot restrict), and a restricted dim that is a written
    # var's sublane axis needs a sub_t-aligned lower bound or the output
    # DMA offsets become 8-unaligned — a hardware-only crash the CPU
    # interpreter cannot catch, so it is rejected statically here.
    region = dict(region) if region else {}
    for d, bounds in region.items():
        if d not in lead:
            raise YaskException(
                f"region restriction on '{d}' is not a leading domain "
                f"dim of this solution ({lead}); the minor (lane) dim "
                "always rides whole")
        lo_, hi_ = bounds
        if not (0 <= lo_ < hi_ <= sizes[d]):
            raise YaskException(
                f"region ({lo_},{hi_}) in dim '{d}' outside the "
                f"interior [0,{sizes[d]})")
    reg_lo = {d: region.get(d, (0, sizes[d]))[0] for d in lead}
    span = {d: (region.get(d, (0, sizes[d]))[1]
                - region.get(d, (0, sizes[d]))[0]) for d in lead}
    restricted = {d for d in lead
                  if (reg_lo[d], span[d]) != (0, sizes[d])}
    if restricted:
        sub_constrained = set()
        for g_ in program.geoms.values():
            if g_.is_scratch or len(g_.axes) < 2:
                continue
            dn_, kind_ = g_.axes[-2]
            if kind_ == "domain" and dn_ != minor:
                sub_constrained.add(dn_)
        for d in restricted & sub_constrained:
            if reg_lo[d] % sub_t != 0:
                raise YaskException(
                    f"region lower bound {reg_lo[d]} in dim '{d}' is "
                    f"not a multiple of the sublane tile {sub_t}: "
                    "output DMA windows would be 8-unaligned on real "
                    "Mosaic (align the core/shell split boundaries)")
        reasons.append({"code": "region_restricted",
                        "region": {d: list(region[d])
                                   for d in sorted(restricted)}})
    # carry depth per var = its ring allocation (an upper bound on how
    # many sub-steps back its levels are read).  The per-level write
    # windows shift by r per sub-step; the stream dim is the sublane
    # (tiled) axis of every full-dim var, so its HBM write windows must
    # keep 8-aligned offsets.  Sublane-multiple radii (r=8 fp32) shift
    # exactly; other radii round the shift DOWN to the sublane tile and
    # widen the window by one tile (E_sk extra computed width on the
    # right makes the widened span valid; consecutive sequential tiles
    # overwrite the sub_t-wide overlap with identical valid values).
    # The second skew candidate (lead[-2]) is an untiled leading DMA
    # axis — its shifts express exactly, E=0.
    elig_dims = skew_eligible_dims(program, K)
    E_all = skew_extra_widths(program, K)
    # Distributed chunks may skew only along UNSHARDED dims (asserted
    # by the shard planner): the carry strips then never cross a shard
    # boundary, each shard spans those dims' full extents, and the r·K
    # ghost pads already cover the skew margins K·r (left) and r+E_sk
    # (right, ≤ (K−1)·r whenever the profit gate engages).  This is the
    # distributed temporal-blocking analog of the reference's
    # rank-level wave-fronts (setup.cpp:863).
    if unsharded_dims is None:
        if not distributed:
            unsharded_dims = set(lead)
        else:
            unsharded_dims = ({sdim} if (stream_unsharded
                                         and sdim is not None) else set())
    unsharded_dims = set(unsharded_dims)
    # restricted dims never skew: their carry buffers and shifted write
    # windows assume the full span.  (In the distributed overlap split
    # this is automatic — restricted dims are the sharded dims — but a
    # direct caller could combine them; removing them from the eligible
    # set makes forced skew on a restricted dim raise below.)
    unsharded_dims -= restricted

    # ---- trapezoid/diamond resolution ----------------------------------
    # Resolved BEFORE skew: engaged trapezoid excludes the carries (the
    # parallel grid has no sequential order for them to ride).  Every
    # decision is a TilePlan comparison — there is no second
    # margin-math path.
    from yask_tpu.ops.tile_planner import TilePlan
    trap_dims: List[str] = []
    trap_forced = (trapezoid is True
                   or (isinstance(trapezoid, (list, tuple, set,
                                              frozenset)) and trapezoid))
    if _diamond is not None:
        trapezoid = False
    elig_trap = ([] if (distributed or restricted or _diamond is not None)
                 else trapezoid_eligible_dims(program, K))
    if isinstance(trapezoid, (list, tuple, set, frozenset)) \
            and not trapezoid:
        trapezoid = False
    if trapezoid is not False and trapezoid is not None:
        # forced: True = the eligible window dims; a list = exactly those
        want_t = (list(elig_trap) if trapezoid is True
                  else [d for d in lead if d in set(trapezoid)])
        bad_t = [d for d in want_t if d not in elig_trap]
        if trapezoid is not True and len(want_t) != len(set(trapezoid)):
            bad_t += sorted(set(trapezoid) - set(want_t))
        if bad_t or not want_t:
            raise YaskException(
                f"trapezoid tiling needs K >= 2, a single-device "
                f"unrestricted build, radius > 0 in each dim (only "
                f"lead[-2:] can tile), and all written vars spanning "
                f"every domain dim; got K={K}, "
                f"requested={want_t or trapezoid}, eligible={elig_trap}, "
                f"distributed={distributed}, "
                f"restricted={sorted(restricted)}")
        trap_dims = want_t
        reasons.append({"code": "trapezoid_forced",
                        "dims": list(trap_dims)})
    elif trapezoid is None and elig_trap:
        # auto: TilePlan volume gate — trapezoid vs skew vs uniform, each
        # variant costed at ITS OWN planned block (trapezoid's 2r fetch
        # margins admit larger tiles than uniform's 2Kr at high K) and
        # normalized per useful cell (compute credited with the
        # parallel-grid cores, fetch not; hardware A/B rows arbitrate)
        from yask_tpu.ops.tile_planner import plan_blocks as _pb
        skw_alt = skew_engaged_dims(program, K, unsharded=unsharded_dims,
                                    max_dims=max_skew_dims)

        def _plan_cost(tp):
            try:
                blk = _pb(program, fuse_steps=K, vmem_budget=vmem_budget,
                          vinstr_cap=vinstr_cap,
                          min_block=tp.min_block(),
                          margin_override=tp.margin_override())
            except YaskException:
                return float("inf")
            # a floor the planner could not honor (vinstr cap, domain
            # size) means the variant cannot actually build — the gate
            # must agree with the build's feasibility check
            for d, mn in (tp.min_block() or {}).items():
                if blk.get(d, 0) < mn:
                    return float("inf")
            u, comp, fetch = tp.volumes(blk)
            cores = TilePlan.PARALLEL_CORES if tp.trap_dims else 1
            return (comp / cores + fetch) / max(u, 1)

        cost_uni = _plan_cost(TilePlan(program, K))
        cost_skw = (_plan_cost(TilePlan(program, K, skew_dims=skw_alt,
                                        e_sk=E_all))
                    if skw_alt else float("inf"))
        cost_trp = _plan_cost(TilePlan(program, K, trap_dims=elig_trap))
        alt = min(cost_uni, cost_skw)
        gate_det = (f"trap {cost_trp:.2f} vs uniform {cost_uni:.2f}, "
                    f"skew {cost_skw:.2f} (cells/useful cell, compute/"
                    f"{TilePlan.PARALLEL_CORES} + fetch, per-variant "
                    f"planned blocks)")
        if cost_trp < alt:
            trap_dims = list(elig_trap)
            for d in trap_dims:
                reasons.append({"code": "trapezoid_engaged", "dim": d,
                                "detail": gate_det})
        else:
            for d in elig_trap:
                reasons.append({"code": "trapezoid_gate_rejected",
                                "dim": d, "detail": gate_det})
    elif trapezoid is None:
        for d in lead:
            why = ("mesh-decomposed or region-restricted build"
                   if (distributed or restricted) else
                   "ineligible (K<2, radius 0, or partial-dim "
                   "written vars)")
            reasons.append({"code": "trapezoid_ineligible", "dim": d,
                            "detail": why})
    trap_set = set(trap_dims)
    skew_req = skew
    if trap_dims:
        skew = False   # parallel grid: no sequential order for carries

    def _trap_fallback(cause: str):
        """Auto-engaged trapezoid that turned out infeasible falls back
        to the skew/uniform resolution the caller asked for."""
        reasons.append({"code": "trapezoid_fallback", "cause": cause,
                        "from_dims": list(trap_dims)})
        return build_pallas_chunk(
            program, fuse_steps=fuse_steps, block=block_arg,
            interpret=interpret, vmem_budget=vmem_budget,
            distributed=distributed, pipeline_dmas=pipeline_dmas,
            skew=skew_req, vinstr_cap=vinstr_cap,
            stream_unsharded=stream_unsharded,
            unsharded_dims=unsharded_dims,
            max_skew_dims=max_skew_dims, plan_only=plan_only,
            reasons=reasons, region=region or None, trapezoid=False,
            push=push_req)

    if isinstance(skew, (list, tuple, set, frozenset)) and not skew:
        skew = False   # an explicit empty dim list = uniform shrink
    forced = skew is True or isinstance(skew, (list, tuple, set,
                                               frozenset))
    if skew is None:
        # Auto-engage per the shared per-dim profit gate (the r4
        # cube-wavefront proxy regression came from engaging
        # unprofitable misaligned small radii); explicit skew still
        # forces the path for A/B measurement.
        skew_dims = skew_engaged_dims(program, K,
                                      unsharded=unsharded_dims,
                                      max_dims=max_skew_dims)
    elif skew is False:
        skew_dims = []
    elif skew is True:
        # legacy force: the stream dim only (the 1-D-skew A/B form)
        skew_dims = [sdim] if sdim is not None else []
    else:
        want = set(skew)
        skew_dims = [d for d in lead if d in want]
        if len(skew_dims) != len(want):
            raise YaskException(
                f"skew dims {sorted(want - set(skew_dims))} are not "
                f"leading domain dims of this solution ({lead})")
    if forced:
        bad = [d for d in skew_dims
               if d not in elig_dims or d not in unsharded_dims]
        if bad or not skew_dims:
            raise YaskException(
                f"skewed wavefront needs K >= 2, unsharded skew dims "
                f"(carry strips cannot cross shard boundaries), a "
                f"radius > 0 in each skewed dim (only lead[-2:] can "
                f"skew), and all written vars spanning every domain "
                f"dim; got K={K}, requested={skew_dims or skew}, "
                f"eligible={elig_dims}, distributed={distributed}, "
                f"unsharded={sorted(unsharded_dims)}, partial-written="
                f"{sorted(g.name for g in program.geoms.values() if g.is_written and not g.is_scratch and g.domain_dims != dims)}")
    use_skew = bool(skew_dims)
    skew_set = set(skew_dims)
    # Structured reason codes for the skew decision (explain pass): one
    # per leading dim under auto-engage, one summary line when forced or
    # disabled.  Codes, not prose, so tools can branch on them.
    if skew is None:
        window = set(lead[-max_skew_dims:]) if max_skew_dims > 0 else set()
        for d in lead:
            if d in skew_set:
                reasons.append({
                    "code": "skew_engaged", "dim": d,
                    "detail": f"profit gate ({K}+1)*{rad[d]}"
                              f"+{E_all.get(d, 0)} < 2*{K}*{rad[d]}"})
            elif d in elig_dims and d in unsharded_dims and d in window:
                reasons.append({
                    "code": "skew_gate_rejected", "dim": d,
                    "detail": f"({K}+1)*{rad[d]}+{E_all.get(d, 0)} >= "
                              f"2*{K}*{rad[d]}"})
            else:
                why = ("outside max_skew_dims window" if d not in window
                       else "mesh-decomposed (carry cannot cross shards)"
                       if d not in unsharded_dims else
                       "ineligible (K<2, radius 0, or partial-dim "
                       "written vars)")
                reasons.append({"code": "skew_ineligible", "dim": d,
                                "detail": why})
    elif forced:
        reasons.append({"code": "skew_forced", "dims": list(skew_dims)})
    else:
        reasons.append({"code": "skew_disabled",
                        "detail": ("trapezoid engaged (parallel grid "
                                   "excludes carries)" if trap_dims
                                   else "skew=False requested")})

    # ---- push-memory resolution ----------------------------------------
    # Same gate shape as skew/trapezoid: False = off, None = auto-engage
    # every eligible var, True/list = force (raise when infeasible).
    # Pushed vars leave BOTH HBM paths (no input DMA, no write-back);
    # their rings in the returned state are STALE — only the pipeline
    # runtime, which hides bound intermediates, turns this on.
    push_req = push
    if isinstance(push, (list, tuple, set, frozenset)) and not push:
        push = False
    push_forced = push is True or isinstance(push, (list, tuple, set,
                                                    frozenset))
    pushed: List[str] = []
    if push is False:
        reasons.append({"code": "push_disabled",
                        "detail": "push=False requested"})
    else:
        push_block = ("trapezoid/diamond build (the fill pass "
                      "recomputes from level-0 HBM state)"
                      if (trap_dims or _diamond is not None)
                      else "distributed build (scope: single device)"
                      if distributed else None)
        elig_push = ({} if push_block is not None
                     else push_eligible_vars(program))
        if push_forced:
            want_p = (sorted(n for n, why in elig_push.items()
                             if why == "ok")
                      if push is True else sorted(set(push)))
            bad_p = [n for n in want_p
                     if elig_push.get(n, "not a written non-scratch "
                                      "var of this program") != "ok"]
            if push_block is not None or bad_p or not want_p:
                if push_block is not None:
                    why_p = push_block
                elif bad_p:
                    why_p = "; ".join(
                        f"'{n}': {elig_push.get(n, 'unknown var')}"
                        for n in bad_p)
                else:
                    why_p = f"no eligible vars (candidates: {elig_push})"
                raise YaskException(
                    f"push-memory fusion infeasible: {why_p}")
            pushed = want_p
            reasons.append({"code": "push_forced", "vars": list(pushed)})
        else:   # auto
            if push_block is not None:
                reasons.append({"code": "push_ineligible",
                                "detail": push_block})
            else:
                for n in sorted(elig_push):
                    if elig_push[n] == "ok":
                        pushed.append(n)
                        reasons.append({"code": "push_engaged",
                                        "var": n,
                                        "detail": "all reads at "
                                                  "+step_dir ride the "
                                                  "in-step computed "
                                                  "tile"})
                    else:
                        reasons.append({"code": "push_ineligible",
                                        "var": n,
                                        "detail": elig_push[n]})
    pushed_set = set(pushed)
    use_push = bool(pushed)

    R = dict(rad)
    # Misaligned (non-sublane-multiple) stream radii: every skewed
    # region carries E_sk extra computed width on its right so the
    # sublane-rounded write windows (shift floored to sub_t, size
    # +sub_t) stay inside the level's valid span: need E ≥ d + sub_t
    # with d = shift−floor(shift) < sub_t ⇒ 2·sub_t suffices.
    E = {d: (E_all.get(d, skew_extra_width(program.dtype, R.get(d, 0))
             if d == sdim else 0) if d in skew_set else 0)
         for d in lead}
    # per-dim tile margins from THE dataflow plan: uniform shrink =
    # radius×K both sides; a skewed dim keeps K·r on the left (write
    # regions shift left by r per sub-step) but only r (+E_sk) on the
    # right; a trapezoid dim reads one step radius per side (the
    # per-level shrink happens in the write windows)
    tplan = TilePlan(program, K, skew_dims=skew_dims,
                     trap_dims=trap_dims, e_sk=E)
    mL, mR = tplan.margins()

    # Every var's leading-dim pads must cover the fused halo, or the DMA
    # start/end would clamp silently and corrupt results: the runtime
    # plans extra_pad = radius*K at prepare time, so a K larger than
    # planned must be rejected here (the auto-tuner relies on this to
    # skip infeasible candidates).
    for n, g in program.geoms.items():
        if n in pushed_set:
            continue  # pushed vars have no HBM DMA windows to cover
        for d in lead:
            if d not in g.domain_dims:
                continue  # partial-dim var lacks this axis
            pl_, pr_ = g.pads[d]
            if pl_ < mL[d] or pr_ < mR[d]:
                raise YaskException(
                    f"pallas fuse_steps={K} needs pad >= {mL[d]} in dim "
                    f"'{d}' but var '{n}' has ({pl_},{pr_}); re-prepare "
                    "with wf_steps set to the desired fusion depth")

    # default block: from the tile planner (fold hints → VREG mapping)
    block_arg = tuple(block) if block is not None else None
    explicit_block = block is not None
    if block is None:
        from yask_tpu.ops.tile_planner import plan_blocks
        # per-dim floors (skew carry, trapezoid band) + engaged-dim
        # margin models, all read off THE TilePlan (the auto-tuner's
        # seed plan reads the same object via skew_plan_hints)
        block = plan_blocks(program, fuse_steps=K, vmem_budget=vmem_budget,
                            vinstr_cap=vinstr_cap,
                            min_block=tplan.min_block(),
                            margin_override=tplan.margin_override())
    else:
        block = {d: min(b, span[d]) for d, b in zip(lead, block)}

    # ---- Mosaic DMA slab geometry ---------------------------------------
    # HBM memrefs carry a tiled (sublane×lane) layout; DMA windows must
    # have tile-aligned sizes AND offsets on the last two physical axes
    # (probed on TPU v5e). The lane axis of every DMA-able var is the
    # solution minor (pallas_applicable) and rides WHOLE — VarGeom pads
    # its total to a 128-multiple. Each var's sublane axis gets an
    # 8-aligned window: the static part of the slab start is rounded
    # down, the residual becomes a static in-tile shift, and the slab
    # size is rounded up (VarGeom's sublane slack guarantees room).
    def _sub_dim(g):
        """The var's sublane (2nd-last physical) axis, when it is a lead
        domain dim (the constrained window case)."""
        if len(g.axes) >= 2:
            dn, kind = g.axes[-2]
            if kind == "domain" and dn != minor:
                return dn
        return None

    non_scratch_geoms = [g for g in program.geoms.values()
                         if not g.is_scratch]
    # pushed vars have no HBM windows: they neither constrain the
    # right-edge overshoot nor the pad coverage (block sublane
    # alignment still honors every non-scratch geom — conservative)
    window_geoms = [g for g in non_scratch_geoms
                    if g.name not in pushed_set]

    # In the diamond fill pass one dim's grid walks tile BOUNDARIES:
    # its tiles are band-wide (block = 2·half) but advance by the
    # phase-1 block (stride), centered on each boundary j·stride.
    dd = _diamond["dim"] if _diamond else None

    def _goff(d):
        """Interior-coordinate offset of tile position 0 relative to
        pid·stride (diamond tiles center on the boundary)."""
        return reg_lo[d] - mL[d] - (_diamond["half"] if d == dd else 0)

    def _gcount(d, b):
        """Grid extent in dim d: ceil coverage of the (possibly
        region-restricted) span; each skewed dim needs (K−1)·r more
        tiles on the right because the final-level write regions sit
        shifted left by (K−1)·r (skew and region are disjoint); the
        diamond dim visits every tile boundary, edges included."""
        if d == dd:
            return _diamond["nbounds"]
        sp = span[d] + ((K - 1) * R[d] if d in skew_set else 0)
        return -(-sp // b)

    def _slab_geom(g, d, b):
        """(base, resid, slab_size) of dim-d windows for var g at block
        size b (window origins shift by the region's lower bound)."""
        s = g.origin[d] + _goff(d)
        if _sub_dim(g) == d:
            base = (s // sub_t) * sub_t
            r = s - base
            sz = -(-(b + mL[d] + mR[d] + r) // sub_t) * sub_t
        else:
            base, r, sz = s, 0, b + mL[d] + mR[d]
        return base, r, sz

    def _overshoot_ok(d, b):
        """Ceil-coverage grids let the right-edge window run into the
        right pad; every var's allocation must contain it."""
        gcount = _gcount(d, b)
        st = _diamond["stride"] if d == dd else b
        for g in window_geoms:
            if d not in g.domain_dims:
                continue
            if g.origin[d] + _goff(d) < 0:
                return False
            base, _r, sz = _slab_geom(g, d, b)
            if (gcount - 1) * st + base + sz > g.shape[g.axis_of(d)]:
                return False
        return True

    def _fit_block(d, b):
        if d == dd:
            # the diamond dim's block IS the band width — never fitted;
            # pads that cannot hold the centered windows fail the build
            # (the outer trapezoid build falls back)
            if not _overshoot_ok(d, b):
                raise YaskException(
                    f"pallas diamond band in dim '{d}' exceeds the "
                    "planned pads; re-prepare with trapezoid pad needs")
            return b
        sub = any(_sub_dim(g) == d for g in non_scratch_geoms)
        step = sub_t if sub else 1
        b = max(step, min(b, span[d]))
        if sub:
            b = max(step, (b // step) * step)
        while b > step and not _overshoot_ok(d, b):
            b -= step
        if not _overshoot_ok(d, b):
            raise YaskException(
                f"no feasible pallas block in dim '{d}': pads too small "
                "for DMA slab rounding; re-prepare with larger wf_steps "
                "pads or different block sizes")
        return b

    def _fallback(cause: str):
        """Auto-engaged skew that turned out infeasible steps DOWN the
        ladder — 2-D → 1-D → uniform — rather than failing a
        configuration a narrower tiling still fits.  Each step records a
        structured reason (the ladder is no longer silent)."""
        reasons.append({
            "code": "skew_fallback", "cause": cause,
            "from_dims": list(skew_dims),
            "to": ("1-D skew" if len(skew_dims) >= 2 else
                   "uniform shrink")})
        return build_pallas_chunk(
            program, fuse_steps=fuse_steps, block=block_arg,
            interpret=interpret, vmem_budget=vmem_budget,
            distributed=distributed, pipeline_dmas=pipeline_dmas,
            skew=(None if len(skew_dims) >= 2 else False),
            vinstr_cap=vinstr_cap, stream_unsharded=stream_unsharded,
            unsharded_dims=unsharded_dims,
            max_skew_dims=max(len(skew_dims) - 1, 0),
            plan_only=plan_only, reasons=reasons, region=region or None,
            push=push_req)

    try:
        _block_req = dict(block)
        for d in lead:
            block[d] = _fit_block(d, block[d])
        if block != _block_req:
            reasons.append({
                "code": "block_fitted", "from": _block_req,
                "to": dict(block),
                "detail": "sublane/overshoot alignment fit"})
    except YaskException:
        if use_skew and not forced:
            # auto-engaged skew whose wider slabs don't fit the planned
            # pads (small misaligned radii): narrower tilings still fit
            return _fallback("DMA slab rounding exceeds planned pads")
        raise

    var_order = [n for n in sorted(program.geoms)
                 if not program.geoms[n].is_scratch]
    written = [n for n in var_order if program.geoms[n].is_written]
    scratch_vars = [n for n in sorted(program.geoms)
                    if program.geoms[n].is_scratch]
    # vars with no domain dims (scalars, misc-only parameter tables) ride
    # SMEM and are read by static scalar indexing — no DMA, no VMEM tile
    smem_vars = {n for n in var_order
                 if not program.geoms[n].domain_dims}
    # pushed vars ride neither DMA path: no input fetch (consumers read
    # the in-step computed tile) and no write-back (their HBM rings go
    # stale — the pipeline runtime hides them)
    dma_vars = [n for n in var_order
                if n not in smem_vars and n not in pushed_set]
    written_out = [n for n in written if n not in pushed_set]

    base_off: Dict[Tuple[str, str], int] = {}
    resid: Dict[Tuple[str, str], int] = {}
    slab: Dict[Tuple[str, str], int] = {}

    def _plan_slabs():
        base_off.clear()
        resid.clear()
        slab.clear()
        for n, g in program.geoms.items():
            for d in g.domain_dims:
                if d == minor:
                    continue
                if g.is_scratch or n in pushed_set:
                    # scratch and pushed tiles never touch HBM:
                    # unconstrained (no DMA window alignment)
                    base_off[n, d], resid[n, d] = 0, 0
                    slab[n, d] = block[d] + mL[d] + mR[d]
                else:
                    base_off[n, d], resid[n, d], slab[n, d] = \
                        _slab_geom(g, d, block[d])

    _plan_slabs()

    # tile geometry per var (its own axes): lead dims are DMA slabs, the
    # minor (lane) dim and misc axes ride their whole padded extents
    def tile_shape(name):
        g = program.geoms[name]
        shp = []
        for i, (dn, kind) in enumerate(g.axes):
            if kind == "misc" or dn == minor:
                shp.append(g.shape[i])
            else:
                shp.append(slab[name, dn])
        return tuple(shp) if shp else (1,)  # 0-dim vars ride as (1,)

    dtype = program.dtype
    esize = jnp.dtype(dtype).itemsize
    slots: Dict[str, int] = {}
    for n in var_order:
        slots[n] = len(program_state_slots(program, n))

    # skewed-wavefront carry: per (skewed dim, ring-read written var),
    # the (D+1)·r-wide boundary strips of levels 1..K−1 that the
    # neighboring tile patches in.  Single-buffered: a level's strip is
    # saved at the top of the LAST sub-step that patches it, AFTER the
    # patches — so the reader's final read of a slot precedes the
    # overwrite, and (with two skewed dims) the strip's corner cells
    # have already received the OTHER dim's patch for that level, which
    # is what makes the diagonal-neighbor data propagate.  The stream
    # dim's reader is the very next sequential step (one strip); the
    # outer dim's reader runs a whole inner row later, so its carry
    # keeps one strip per inner-grid position.
    # Carry EVERY written var that is read back at all — not just the
    # offset-read set (``stage_reads`` omits pure same-point reads, but
    # a same-point consumer at the next sub-step still reads the slid
    # region's left strip, which only the neighboring tile computed:
    # awp's anelastic memory vars corrupted a radius-wide band when
    # they were left out of the carry).
    # Pushed vars never carry: their only reads are same-sub-step
    # ``computed`` reads, which never touch the ring tiles the carry
    # strips patch.
    carry_vars = ([n for n in written
                   if (n in ring_read_vars
                       or n in ana.read_var_names())
                   and n not in pushed_set]
                  if use_skew else [])
    carr_base: Dict[Tuple[str, str], int] = {}
    for _d in skew_dims:
        for _n in carry_vars:
            # vars without the skewed dim (misc-only SMEM riders) have
            # no strip geometry in it — their values are domain-
            # independent and recomputed identically by every tile
            if not any(dn == _d for dn, _k in program.geoms[_n].axes):
                continue
            carr_base[_d, _n] = len(carr_base)

    def carry_shape(dim, name):
        shp = list(tile_shape(name))
        g = program.geoms[name]
        ax = [i for i, (dn, _k) in enumerate(g.axes) if dn == dim][0]
        shp[ax] = (slots[name] + 1) * R[dim]
        head = (max(K - 1, 1),)
        if dim != sdim:
            # one strip per inner-grid position (written at j =
            # pid[-1], read back by the next row's tile at the same j)
            head = head + (_gcount(lead[-1], block[lead[-1]]),)
        return head + tuple(shp)

    def _tile_bytes():
        in_b = sum(slots[n] * int(math.prod(tile_shape(n))) * esize
                   for n in dma_vars)
        # workspace for sub-step results (rough: one extra tile per
        # written var) and the in-tile scratch values
        work_b = sum(int(math.prod(tile_shape(n))) * esize
                     for n in written)
        work_b += sum(int(math.prod(tile_shape(n))) * esize
                      for n in scratch_vars)
        # pushed vars have no DMA scratch refs, but their ring values
        # (zero seed → rotated computed tiles) stay LIVE across the
        # sub-steps — one tile per slot, in the work accounting (they
        # never double-buffer, so the pipe model must not 2× them)
        work_b += sum(slots[n] * int(math.prod(tile_shape(n))) * esize
                      for n in pushed)
        work_b += sum(int(math.prod(carry_shape(d_, n_))) * esize
                      for (d_, n_) in carr_base)
        return in_b, work_b

    in_tile_bytes, work_bytes = _tile_bytes()
    _block0 = dict(block)
    # planner-chosen blocks auto-shrink until the tile model fits (its
    # model can undercount misc slots / alignment rounding); explicitly
    # requested blocks fail fast instead — the auto-tuner relies on the
    # raise to mark infeasible candidates
    while in_tile_bytes + work_bytes > vmem_budget and not explicit_block:
        shrinkable = [d for d in lead
                      if block[d] > (sub_t if any(
                          _sub_dim(g) == d for g in non_scratch_geoms)
                          else 1)]
        if not shrinkable:
            break
        d = max(shrinkable, key=lambda dd: block[dd])
        nb = _fit_block(d, max(1, block[d] // 2))
        if nb >= block[d]:
            break
        block[d] = nb
        _plan_slabs()
        in_tile_bytes, work_bytes = _tile_bytes()
    if block != _block0:
        reasons.append({"code": "block_shrunk", "from": _block0,
                        "to": dict(block),
                        "detail": "tile model over VMEM budget"})
    # Skew feasibility: each skewed dim's carry save-strips must come
    # from the tile's own valid region (block[d] ≥ (D+1)·r, D = deepest
    # carried ring), and the carry buffers must fit the budget
    # alongside the tiles.  Auto-engaged skew steps down the ladder
    # (2-D → 1-D → uniform) rather than failing a configuration a
    # narrower tiling still fits.
    if use_skew:
        d_max = max((slots[n] for n in carry_vars), default=0)
        infeasible = any(carry_vars and block[d] < (d_max + 1) * R[d]
                         for d in skew_dims) or \
            (in_tile_bytes + work_bytes > vmem_budget)
        if infeasible:
            if forced:   # explicitly requested: surface the constraint
                raise YaskException(
                    f"skewed wavefront needs block[d] >= "
                    f"{[(d, (d_max + 1) * R[d]) for d in skew_dims]} "
                    f"(ring {d_max} × radius) and carry within the "
                    f"VMEM budget; got "
                    f"block {[(d, block[d]) for d in skew_dims]}, "
                    f"{(in_tile_bytes + work_bytes)/2**20:.1f} MiB")
            return _fallback("carry floor (ring+1)*r or carry VMEM "
                             "does not fit")

    # Trapezoid feasibility: the deepest level's write window needs
    # block > 2·shrink, and the fill pass needs a uniform boundary
    # stride (plan_blocks always yields divisors; an explicit
    # non-divisor block cannot center the diamonds).
    if trap_dims:
        for d in trap_dims:
            unit = sub_t if d == lead[-1] else 1
            floor_b = 2 * tplan.cl(d, K) + unit
            bad_t = (f"block {block[d]} does not divide span {span[d]} "
                     f"in '{d}'" if span[d] % block[d] != 0 else
                     f"block {block[d]} < band floor {floor_b} in '{d}'"
                     if block[d] < floor_b else None)
            if bad_t is None:
                continue
            if trap_forced:
                raise YaskException(
                    f"trapezoid tiling infeasible: {bad_t}")
            return _trap_fallback(bad_t)

    tile_bytes = in_tile_bytes + work_bytes
    if tile_bytes > vmem_budget:
        raise YaskException(
            f"pallas tile needs {tile_bytes/2**20:.1f} MiB VMEM "
            f"(budget {vmem_budget/2**20:.0f}); shrink block or fuse_steps")

    # ceil coverage: edge windows overshoot into the (validated) right
    # pads; overshoot cells read zero ghosts and mask to zero writes
    grid = tuple(_gcount(d, block[d]) for d in lead)
    total_steps = int(math.prod(grid)) if grid else 1

    # Double-buffer the input-tile DMAs across grid steps: while step i
    # computes on buffer i%2, step i+1's halo tiles stream into the other
    # buffer (reference prefetch/early-load machinery, Cpp.hpp:263-287).
    # Costs 2x input-tile VMEM; auto-disabled when that busts the budget
    # or there's only one grid step. Grid dims are declared "arbitrary"
    # (sequential) so the linear-index prefetch is sound.
    _pipe_req = pipeline_dmas
    _trap_no_pipe = bool(trap_dims) or _diamond is not None
    if _trap_no_pipe:
        # the cross-step linear-index prefetch (and the in-flight output
        # staging) assume the sequential grid order the parallel
        # trapezoid grid no longer provides
        pipeline_dmas = False
    if pipeline_dmas is None:
        pipeline_dmas = (total_steps > 1
                         and 2 * in_tile_bytes + work_bytes <= vmem_budget)
    use_pipe = bool(pipeline_dmas) and total_steps > 1
    reasons.append(
        {"code": "pipe_in_on",
         "detail": "forced" if _pipe_req else "auto (2*in+work fits)"}
        if use_pipe else
        {"code": "pipe_in_off",
         "detail": ("parallel trapezoid grid" if _trap_no_pipe
                    else "pipeline_dmas=False requested"
                    if _pipe_req is False
                    else "single grid step" if total_steps <= 1
                    else "2*in+work over VMEM budget")})
    if use_pipe:
        tile_bytes = 2 * in_tile_bytes + work_bytes
        if tile_bytes > vmem_budget:   # explicitly-requested pipelining
            raise YaskException(
                f"pallas pipelined tiles need {tile_bytes/2**20:.1f} MiB "
                f"VMEM (budget {vmem_budget/2**20:.0f}); shrink block or "
                "fuse_steps, or disable pipeline_dmas")
    # Pipelined WRITE-back: output DMAs source DEDICATED parity-doubled
    # staging tiles (not the consumed input scratch), so they stay in
    # flight through the whole next grid step's compute — the input
    # prefetch never touches them and each store retires two steps
    # later, just before its parity's staging is re-filled.  Staging
    # through the input scratch cannot overlap anything: the li+1
    # prefetch targets the same parity the li−1 stores source, forcing
    # retirement at the body top with zero instructions since the
    # start.  Costs 2× an output-tile set; auto-disabled when that
    # busts the budget (outputs then stage through the input scratch
    # and drain at the end of each grid step).
    ostage_bytes = 2 * sum(int(math.prod(tile_shape(n))) * esize
                           * min(K, slots[n]) for n in written_out)
    use_pipe_out = use_pipe and (2 * in_tile_bytes + work_bytes
                                 + ostage_bytes <= vmem_budget)
    if use_pipe_out:
        tile_bytes += ostage_bytes
    reasons.append(
        {"code": "pipe_out_on",
         "detail": "parity-doubled staging fits the budget"}
        if use_pipe_out else
        {"code": "pipe_out_off",
         "detail": ("input pipelining off" if not use_pipe
                    else "staging tiles over VMEM budget")})
    # Grid semantics: the sequential ("arbitrary") order exists for the
    # skew carries, the linear-index DMA prefetch, and the in-flight
    # output staging.  A trapezoid build (and its diamond fill pass)
    # uses none of them — every grid step fetches, computes, stores and
    # drains synchronously on disjoint output windows — so ALL grid
    # dims are declared "parallel" (megacore partitioning; scratch is
    # per-core).  Recorded in the plan/tiling for the checker and the
    # equivalence tests; applied to CompilerParams on real Mosaic only.
    dim_sem = tuple(("parallel" if _trap_no_pipe else "arbitrary")
                    for _ in lead)

    # ---- diamond fill-pass sub-builds (phase 2) -------------------------
    # One recursive build per trapezoid dim: the UNIFORM kernel (full
    # K·r margins in every dim, level-0 input state) with that dim's
    # grid walking every phase-1 tile BOUNDARY (edges included), its
    # block the diamond band 2·cl(K), advancing by the phase-1 block
    # (stride).  Output: per-boundary band arrays the outer chunk
    # stitches host-side.  With two trapezoid dims each pass keeps
    # uniform margins in the OTHER dim, so the corner bands are
    # recomputed identically by both passes (elementwise determinism).
    dia_subs: List[tuple] = []
    if trap_dims:
        try:
            for d in trap_dims:
                dia = tplan.diamond(d)
                nbounds = span[d] // block[d] + 1
                cls = {lvl: tplan.cl(d, lvl) for lvl in range(1, K + 1)}
                dblock = tuple(dia["band"] if d2 == d else block[d2]
                               for d2 in lead)
                sub = build_pallas_chunk(
                    program, fuse_steps=K, block=dblock,
                    interpret=interpret, vmem_budget=vmem_budget,
                    pipeline_dmas=False, skew=False,
                    vinstr_cap=vinstr_cap, plan_only=plan_only,
                    reasons=[],
                    _diamond={"dim": d, "stride": block[d],
                              "nbounds": nbounds, "half": dia["half"],
                              "band": dia["band"], "cls": cls})
                if not plan_only:
                    sub = sub[0]   # (chunk, tile_bytes) → the chunk fn
                dia_subs.append((d, block[d], nbounds, dia["half"],
                                 cls, sub))
                reasons.append({"code": "trapezoid_diamond", "dim": d,
                                "band": dia["band"], "nbounds": nbounds,
                                "stride": block[d]})
        except YaskException as e:
            if trap_forced:
                raise YaskException(
                    f"trapezoid tiling infeasible (fill pass): {e}")
            return _trap_fallback(f"diamond fill pass: {e}")

    if plan_only:
        # The checker's window into the REAL planner: everything above
        # ran (skew ladder, slab rounding, budget shrink, pipelining)
        # but nothing traced or allocated.  Keys are plain
        # JSON-serializable values.
        return {
            "fuse_steps": K,
            "block": dict(block),
            "grid": list(grid),
            "total_steps": total_steps,
            "skew": bool(use_skew),
            "skew_dims": list(skew_dims),
            "push": bool(use_push),
            "push_vars": list(pushed),
            "trapezoid": bool(trap_dims),
            "trap_dims": list(trap_dims),
            "dimension_semantics": list(dim_sem),
            "diamond": [s[-1] for s in dia_subs],
            **({"diamond_dim": _diamond["dim"],
                "stride": _diamond["stride"],
                "nbounds": _diamond["nbounds"],
                "half": _diamond["half"],
                "band": _diamond["band"],
                "cls": {str(l): v
                        for l, v in _diamond["cls"].items()}}
               if _diamond is not None else {}),
            "region": {d: list(region[d]) for d in sorted(restricted)},
            "mL": dict(mL), "mR": dict(mR), "E": dict(E),
            "radius": dict(rad),
            "sizes": dict(sizes),
            "minor": minor,
            "sub_t": sub_t,
            "lane_t": _lane_t,
            "pipeline_dmas": use_pipe,
            "pipeline_out": use_pipe_out,
            "in_tile_bytes": in_tile_bytes,
            "work_bytes": work_bytes,
            "push_tile_bytes": sum(
                slots[n] * int(math.prod(tile_shape(n))) * esize
                for n in pushed),
            "ostage_bytes": ostage_bytes if use_pipe_out else 0,
            "carry_bytes": sum(
                int(math.prod(carry_shape(d_, n_))) * esize
                for (d_, n_) in carr_base),
            "tile_bytes": tile_bytes,
            "vmem_budget": vmem_budget,
            "smem_vars": sorted(smem_vars),
            "dma_vars": list(dma_vars),
            "written": list(written),
            "written_out": list(written_out),
            "scratch_vars": list(scratch_vars),
            "slots": dict(slots),
            "carry_vars": list(carry_vars),
            "tile_shapes": {n: list(tile_shape(n)) for n in var_order},
            "base_off": {f"{n}/{d}": v for (n, d), v in base_off.items()},
            "resid": {f"{n}/{d}": v for (n, d), v in resid.items()},
            "slab": {f"{n}/{d}": v for (n, d), v in slab.items()},
            "reasons": list(reasons),
        }
    minor_origin = {n: (g.pads[minor][0]
                        if minor in g.domain_dims else 0)
                    for n, g in program.geoms.items()}
    ev = _TileEval(jnp, program, minor, minor_origin, resid)

    dirn = ana.step_dir

    # global-problem extents for the zero-outside-domain mask; in
    # distributed mode the shard's origin arrives as a traced vector
    gdom = {d: program.global_last[d] + 1 for d in dims}
    nscalars = 2 if distributed else 1  # t0 (+offsets)

    n_inputs = sum(slots[n] for n in var_order) + nscalars

    in_base: Dict[str, int] = {}   # var -> first input-ref index
    _ii = 0
    for _n in var_order:
        in_base[_n] = _ii
        _ii += slots[_n]
    si_base: Dict[str, int] = {}   # DMA var -> first scratch-tile index
    _si = 0
    for _n in dma_vars:
        si_base[_n] = _si
        _si += slots[_n]

    def kernel(*refs):
        # refs: t0 (SMEM), [offsets (SMEM)], inputs (ANY/HBM) ...,
        #       outputs (ANY/HBM, padded shapes) ..., scratch tiles ...,
        #       input-DMA sem, output-DMA sem
        t0_ref = refs[0]
        off_ref = refs[1] if distributed else None
        ins = refs[nscalars:n_inputs]
        nout = sum(min(K, slots[n]) for n in written_out)
        outs = refs[n_inputs:n_inputs + nout]
        n_tiles = sum(slots[n] for n in dma_vars)
        scratch = refs[n_inputs + nout:n_inputs + nout + n_tiles]
        _cb = n_inputs + nout + n_tiles
        carr = refs[_cb:_cb + len(carr_base)]
        ostage = refs[_cb + len(carr_base):-2]
        sem = refs[-2]
        out_sem = refs[-1]

        pid = [pl.program_id(i) for i in range(len(lead))]

        def _coords(step):
            """Decode a linear sequential-grid index into per-dim
            coordinates (shared by the prefetch / retire / drain
            paths)."""
            cs = []
            rem_ = step
            for i in range(len(lead) - 1, -1, -1):
                cs.append(rem_ % grid[i])
                rem_ = rem_ // grid[i]
            return cs[::-1]

        def out_dmas(coords, par):
            """The full set of output copies for grid position ``coords``
            and staging parity ``par`` — reconstructed identically to
            start and to wait (the wait may happen one grid step later,
            see the pipelined retirement below)."""
            cps = []
            oi = 0
            for name in written_out:
                g = program.geoms[name]
                nback = min(K, slots[name])
                for s in range(nback):
                    lvl = K - nback + s + 1   # time level this slot holds
                    if dd is not None and _diamond["cls"][lvl] == 0:
                        # cl(1)=0: phase 1 wrote this level's full
                        # blocks valid (zero shrink) — no gap band
                        oi += 1
                        continue
                    if use_pipe_out:
                        sref = ostage[oi].at[par]
                        osem = out_sem.at[par, oi]
                    elif use_pipe:
                        sref = scratch[si_base[name] + s].at[par]
                        osem = out_sem.at[oi]
                    else:
                        sref = scratch[si_base[name] + s]
                        osem = out_sem.at[oi]
                    src_idxs = []
                    dst_idxs = []
                    for dn, kind in g.axes:
                        if kind == "misc" or dn == minor:
                            src_idxs.append(slice(None))
                            dst_idxs.append(slice(None))
                        elif dn in skew_set:
                            # level lvl's write region sits shifted left
                            # by (lvl−1)·r.  On the var's sublane axis,
                            # sublane-multiple shifts express exactly;
                            # others round the shift DOWN to the sublane
                            # tile and widen the window by one tile:
                            # both ends stay inside the level's valid
                            # span (E_sk budgeted it), and the sub_t
                            # overlap with the next sequential tile
                            # re-writes identical valid values (src and
                            # dst starts share the same residue,
                            # g.origin ≡ mL+resid (mod 8)).  Outer skew
                            # dims are untiled leading DMA axes: the
                            # shift expresses exactly.
                            shift = (lvl - 1) * R[dn]
                            if _sub_dim(g) == dn:
                                sh_al = (shift // sub_t) * sub_t
                                wsz = block[dn] + (sub_t if sh_al != shift
                                                   else 0)
                            else:
                                sh_al, wsz = shift, block[dn]
                            src_idxs.append(pl.ds(
                                mL[dn] - sh_al + resid[name, dn], wsz))
                            dst_idxs.append(pl.ds(
                                g.origin[dn] - sh_al
                                + coords[lead.index(dn)] * block[dn],
                                wsz))
                        elif dn == dd:
                            # diamond fill: level lvl's gap band,
                            # centered on the boundary this grid step
                            # covers, lands in the band output's own
                            # axis.  half and cl are both sublane-
                            # aligned on the sublane axis, so offsets
                            # stay 8-aligned.
                            clv = _diamond["cls"][lvl]
                            src_idxs.append(pl.ds(
                                mL[dn] + resid[name, dn]
                                + _diamond["half"] - clv, 2 * clv))
                            dst_idxs.append(pl.ds(
                                _diamond["half"] - clv, 2 * clv))
                        elif dn in trap_set:
                            # upright trapezoid: level lvl's write
                            # window shrinks by (lvl−1)·r per side,
                            # rounded DOWN to the sublane tile on the
                            # sublane axis (the sub-tile smear lands
                            # inside the diamond band, which the fill
                            # pass overwrites with valid values)
                            fl = tplan.write_shrink(dn, lvl)
                            src_idxs.append(pl.ds(
                                mL[dn] + resid[name, dn] + fl,
                                block[dn] - 2 * fl))
                            dst_idxs.append(pl.ds(
                                g.origin[dn] + reg_lo[dn]
                                + coords[lead.index(dn)] * block[dn]
                                + fl, block[dn] - 2 * fl))
                        else:
                            di = lead.index(dn)
                            src_idxs.append(pl.ds(
                                mL[dn] + resid[name, dn], block[dn]))
                            dst_idxs.append(pl.ds(
                                g.origin[dn] + reg_lo[dn]
                                + coords[di] * block[dn],
                                block[dn]))
                    dref = outs[oi]
                    if dd is not None:
                        # per-boundary band output: lead axis indexed by
                        # this grid step's boundary position (a traced
                        # index — the skew carry's pid[-1] precedent)
                        dref = dref.at[(coords[lead.index(dd)],)
                                       + tuple(dst_idxs)]
                    else:
                        dref = dref.at[tuple(dst_idxs)]
                    cps.append(pltpu.make_async_copy(
                        sref.at[tuple(src_idxs)], dref, osem))
                    oi += 1
            return cps

        # 1) DMA halo tiles HBM → VMEM (double-buffered across grid
        #    steps when use_pipe: compute on buffer li%2 while the next
        #    step's tiles stream into the other buffer).
        def in_dmas(coords, buf):
            """The full set of input-tile copies for grid position
            ``coords`` into buffer ``buf`` (reconstructed identically to
            start and to wait)."""
            out = []
            for n in dma_vars:
                g = program.geoms[n]
                for s in range(slots[n]):
                    si = si_base[n] + s
                    src = ins[in_base[n] + s]
                    idxs = []
                    for dn, kind in g.axes:
                        if kind == "misc" or dn == minor:
                            idxs.append(slice(None))  # full (lane) extent
                        else:
                            di = lead.index(dn)
                            # sublane-aligned window; the sub-tile
                            # residual is a static shift the kernel
                            # applies at read/write time.  The diamond
                            # dim's band tiles advance by the phase-1
                            # block (stride), not their own width.
                            st_ = (_diamond["stride"] if dn == dd
                                   else block[dn])
                            start = coords[di] * st_ + base_off[n, dn]
                            idxs.append(pl.ds(start, slab[n, dn]))
                    if use_pipe:
                        dst = scratch[si].at[buf]
                        s_at = sem.at[buf, si]
                    else:
                        dst = scratch[si]
                        s_at = sem.at[si]
                    out.append(pltpu.make_async_copy(
                        src.at[tuple(idxs)] if idxs else src, dst, s_at))
            return out

        if use_pipe:
            li = pid[0]
            for i in range(1, len(lead)):
                li = li * grid[i] + pid[i]
            cur = li % 2

            @pl.when(li == 0)
            def _warmup():
                for dma in in_dmas(pid, 0):
                    dma.start()

            nxt = li + 1
            nxt_coords = _coords(nxt)

            if use_pipe_out:
                # Retire the li−2 output DMAs (same staging parity as
                # this step, cur) before this step's staging re-fills
                # it.  Those copies got a full grid step (li−1's
                # compute) of flight time, so this wait is ~free —
                # the store path no longer serializes the grid.
                pp_coords = _coords(li - 2)

                @pl.when(li >= 2)
                def _retire_out():
                    for cp in out_dmas(pp_coords, cur):
                        cp.wait()

            @pl.when(nxt < total_steps)
            def _prefetch():
                for dma in in_dmas(nxt_coords, nxt % 2):
                    dma.start()

            for dma in in_dmas(pid, cur):
                dma.wait()
        else:
            cur = None
            for dma in in_dmas(pid, None):
                dma.start()
            for dma in in_dmas(pid, None):
                dma.wait()

        def buf_ref(si):
            return scratch[si].at[cur] if use_pipe else scratch[si]

        # tiles as values; SMEM vars stay as refs (scalar static reads).
        # Pushed vars were never DMA'd: their ring seeds are ZERO tiles
        # — bit-equivalent to the HBM state on every cell a consumer
        # can reach (out-of-domain cells are ghost-zero in HBM too, and
        # every read is a same-sub-step ``computed`` read that never
        # touches these seeds).
        tiles: Dict[str, List] = {}
        for n in var_order:
            if n in smem_vars:
                tiles[n] = [ins[in_base[n] + s] for s in range(slots[n])]
            elif n in pushed_set:
                tiles[n] = [jnp.zeros(tile_shape(n), dtype)
                            for _ in range(slots[n])]
            else:
                tiles[n] = [buf_ref(si_base[n] + s)[...]
                            for s in range(slots[n])]

        # 2) K fused sub-steps; within each, every stage consumes its read
        #    radius of tile margin (trapezoid shrink) and writes a FULL
        #    tile (base.at[region].set) so later stages read it at offsets.
        def region_idxs(name, region, misc=None):
            """Index tuple over the var's own axes: domain axes sliced to
            the region (minor shifted by the var's pad origin), misc axes
            pinned to the LHS misc values (ints — they collapse, so the
            result of base[idxs] is region-shaped)."""
            g = program.geoms[name]
            idxs = []
            for dn, kind in g.axes:
                if kind == "misc":
                    idxs.append((misc or {})[dn] - g.misc_lo[dn])
                elif dn == minor:
                    mo = g.pads[minor][0]
                    idxs.append(slice(mo + region[-1][0],
                                      mo + region[-1][1]))
                else:
                    lo, hi = region[dims.index(dn)]
                    rs = resid.get((name, dn), 0)
                    idxs.append(slice(rs + lo, rs + hi))
            return tuple(idxs)

        def to_var_region(name, val, region):
            """Slice a full-region value down to a partial-dim var's own
            axes.  The RHS is constant along the missing lead dims
            (XLA-path `_to_var_layout` contract), so the cell at global
            coordinate pid·block — in-domain for every tile by the ceil
            grid construction — is taken."""
            g = program.geoms[name]
            if g.domain_dims == dims:
                return val
            idx = []
            for di, d in enumerate(dims):
                if d in g.domain_dims:
                    idx.append(slice(None))
                else:
                    lo, _hi = region[di]
                    idx.append(mL[d] - lo)
            return val[tuple(idx)]

        def tile_update(base, idxs, val):
            # Mosaic TC implements neither dynamic_update_slice nor
            # scatter (probed on TPU v5e), so embed the statically-
            # bounded region by lax.pad to tile shape + iota-mask select
            # — pure vector ops. Integer (misc) axes become size-1
            # update axes.
            from jax import lax
            bounds = []
            shape = []
            for s in idxs:
                if isinstance(s, slice):
                    bounds.append((s.start, s.stop))
                    shape.append(s.stop - s.start)
                else:
                    bounds.append((s, s + 1))
                    shape.append(1)
            val = val.reshape(tuple(shape))
            pads = [(lo, base.shape[i] - hi, 0)
                    for i, (lo, hi) in enumerate(bounds)]
            padded = lax.pad(val, jnp.array(0, base.dtype), pads)
            mask = None
            for i, (lo, hi) in enumerate(bounds):
                if lo == 0 and hi == base.shape[i]:
                    continue
                ax = lax.broadcasted_iota(jnp.int32, base.shape, i)
                m = (ax >= lo) & (ax < hi)
                mask = m if mask is None else mask & m
            if mask is None:
                return padded
            return jnp.where(mask, padded, base)

        ev.gidx_base = {d: pid[lead.index(d)]
                        * (_diamond["stride"] if d == dd else block[d])
                        + _goff(d) for d in lead}
        if distributed:
            for di, d in enumerate(dims):
                ev.gidx_base[d] = ev.gidx_base.get(d, 0) + off_ref[di]

        # ---- skewed-wavefront carry helpers -------------------------
        # Sub-step s writes W_s = [i·B − (s−1)·r, i·B + B − (s−1)·r) in
        # a skewed dim; reading level ℓ at sub-step s needs [W_s.lo −
        # r, …) — below this tile's own computed span.  Those cells are
        # the neighboring tile's freshly-computed right edge: it saved
        # them into the carry, and this tile patches them in before
        # each sub-step (width 2r for a level's first patch — its
        # computed validity starts 2r right of the read edge — then r
        # per later sub-step while it stays live; (D+1)·r total).
        # Single-buffered with a DELAYED save: level ℓ's strip is
        # stored at the top of sub-step min(ℓ+D−1, K−1) — after that
        # sub-step's patches, i.e. after the reader's LAST read of the
        # slot (so no parity double-buffer is needed) and after the
        # OTHER skewed dim's level-ℓ patch landed in this tile (so the
        # strip's corner cells carry the diagonal neighbor's data —
        # the 2-D correctness requirement).
        def _strip_idx(name, dim, lo, width):
            g = program.geoms[name]
            shp = tile_shape(name)
            idxs = []
            for i, (dn, kind) in enumerate(g.axes):
                if kind == "domain" and dn == dim:
                    rs_ = resid.get((name, dn), 0)
                    idxs.append(slice(rs_ + lo, rs_ + lo + width))
                else:
                    idxs.append(slice(0, shp[i]))
            return tuple(idxs)

        def _carry_idx(name, dim, lvl, off, width):
            g = program.geoms[name]
            idxs = [lvl - 1]
            if dim != sdim:
                # the outer dim's carry holds one strip per inner-grid
                # position; the reader (next row, same position) indexes
                # the same traced slot
                idxs.append(pid[-1])
            for dn, kind in g.axes:
                if kind == "domain" and dn == dim:
                    idxs.append(slice(off, off + width))
                else:
                    idxs.append(slice(None))
            return tuple(idxs)

        if use_skew and carry_vars:
            pid_d = {d: pid[lead.index(d)] for d in skew_dims}

        for k in range(K):
            computed: Dict[str, object] = {}
            ev.scratch = {}   # scratch values are per-sub-step
            consumed = {d: rad[d] * k for d in lead}
            ev.t = t0_ref[0] + k * dirn

            # patch the live ring levels' left strips from the
            # neighboring tiles' carries before computing sub-step k+1
            if use_skew and carry_vars and k >= 1:
                for dim in skew_dims:
                    for n in carry_vars:
                        if (dim, n) not in carr_base:
                            continue
                        Dn = slots[n]
                        ring = tiles[n]
                        for j in range(len(ring)):
                            lvl = k - (len(ring) - 1 - j)
                            if lvl < 1:
                                continue
                            width = (2 if lvl == k else 1) * R[dim]
                            lo = (K - k - 1) * R[dim]
                            coff = (lvl + Dn - k - 1) * R[dim]
                            cref = carr[carr_base[dim, n]]
                            strip = cref[_carry_idx(n, dim, lvl, coff,
                                                    width)]
                            # dim start: the left margin is
                            # out-of-domain ghost (and for the outer
                            # dim, pid 0 also marks a fresh row whose
                            # stale strips must not leak) — zero
                            strip = jnp.where(pid_d[dim] > 0, strip,
                                              jnp.zeros_like(strip))
                            ring[j] = tile_update(
                                ring[j], _strip_idx(n, dim, lo, width),
                                strip)
                # delayed saves: store every level whose last patch was
                # this sub-step's (above) — reads precede the overwrite
                for dim in skew_dims:
                    for n in carry_vars:
                        if (dim, n) not in carr_base:
                            continue
                        Dn = slots[n]
                        ring = tiles[n]
                        if k < K - 1:
                            lvls = ([k - Dn + 1] if k - Dn + 1 >= 1
                                    else [])
                        else:
                            lvls = list(range(max(1, K - Dn), K))
                        for lvl in lvls:
                            j = Dn - 1 - (k - lvl)
                            lo = block[dim] + (K - lvl - Dn) * R[dim]
                            width = (Dn + 1) * R[dim]
                            strip = ring[j][_strip_idx(n, dim, lo,
                                                       width)]
                            cref = carr[carr_base[dim, n]]
                            cref[_carry_idx(n, dim, lvl, 0, width)] = \
                                strip

            for si_stage in range(nstages):
                for d in lead:
                    consumed[d] += stage_r[si_stage][d]
                region = []
                for d in lead:
                    if d in skew_set:
                        # skew: fixed-width region sliding left by r per
                        # sub-step; stages still consume their margins.
                        # E_sk extra right width (misaligned radii) rides
                        # every region so the telescoping validity spans
                        # keep covering the widened write windows.
                        c_stage = consumed[d] - rad[d] * k
                        lo = mL[d] - (k + 1) * R[d] + c_stage
                        region.append((lo, lo + block[d]
                                       + 2 * (R[d] - c_stage) + E[d]))
                    else:
                        region.append((consumed[d],
                                       block[d] + mL[d] + mR[d]
                                       - consumed[d]))
                # minor: interior-relative (per-var pad origin applied at
                # read/write time); pads stay zero
                region.append((0, sizes[minor]))
                rshape = tuple(hi - lo for lo, hi in region)

                # global-domain mask over the region's leading dims: in
                # distributed mode bounds are the GLOBAL problem, so
                # shard-ghost points keep updating while physical edges
                # stay zero
                mask = None
                for di, d in enumerate(lead):
                    lo, hi = region[di]
                    shape = [1] * len(dims)
                    shape[di] = hi - lo
                    # broadcasted_iota: Mosaic TC crashes on non-lane
                    # 1-D iota (probed on TPU v5e)
                    gidx = (lax.broadcasted_iota(
                                jnp.int32, tuple(shape), di)
                            + lo + pid[di]
                            * (_diamond["stride"] if d == dd
                               else block[d])
                            + _goff(d))
                    if distributed:
                        gidx = gidx + off_ref[di]
                        bound = gdom[d]
                    else:
                        bound = sizes[d]
                    m = (gidx >= 0) & (gidx < bound)
                    mask = m if mask is None else mask & m

                memo: Dict = {}
                for part in ana.stages[si_stage].parts:
                    if part.is_scratch:
                        # Scratch eqs evaluate over the stage region
                        # EXPANDED by their write-halo (mirrors
                        # _eval_part's scratch branch; stage_read_widths
                        # already budgeted the margin for the chain) and
                        # persist as full-tile values for offset reads.
                        for eq in part.eqs:
                            ev.misc_env = eq.lhs.misc_vals()
                            name = eq.lhs.var_name()
                            wh = ana.scratch_write_halo.get(name, {})
                            sregion = []
                            for di, d in enumerate(lead):
                                wl, wr = wh.get(d, (0, 0))
                                lo, hi = region[di]
                                sregion.append((lo - wl, hi + wr))
                            wl_m, wr_m = wh.get(minor, (0, 0))
                            sregion.append((-wl_m, sizes[minor] + wr_m))
                            ev.region = sregion
                            smemo: Dict = {}   # region differs: own memo
                            val = ev.eval(eq.rhs, tiles, computed, smemo)
                            val = jnp.asarray(val, dtype=dtype)
                            srshape = tuple(hi - lo for lo, hi in sregion)
                            val = jnp.broadcast_to(val, srshape)
                            # partial-dim scratch vars collapse to their
                            # own axes (RHS/cond constant along missing
                            # dims — analysis race rule)
                            val = to_var_region(name, val, sregion)
                            base = ev.scratch.get(
                                name, jnp.zeros(tile_shape(name), dtype))
                            sidx = region_idxs(name, sregion,
                                               eq.lhs.misc_vals())
                            if eq.cond is not None:
                                cm = ev.eval(eq.cond, tiles, computed,
                                             smemo)
                                cm = jnp.broadcast_to(cm, srshape)
                                cm = to_var_region(name, cm, sregion)
                                val = jnp.where(cm, val, base[sidx])
                            ev.scratch[name] = tile_update(base, sidx, val)
                        continue

                    ev.region = region
                    # misc-as-value evaluates per LHS binding: such parts
                    # memoize per equation (mirrors _eval_part's scoping)
                    part_misc = has_misc_value and any(
                        uses_misc_index(eq.rhs, eq.cond, eq.step_cond)
                        for eq in part.eqs)
                    for eq in part.eqs:
                        if part_misc:
                            memo = {}
                        ev.misc_env = eq.lhs.misc_vals()
                        name = eq.lhs.var_name()
                        lmisc = eq.lhs.misc_vals()
                        val = ev.eval(eq.rhs, tiles, computed, memo)
                        val = jnp.asarray(val, dtype=dtype)
                        val = jnp.broadcast_to(val, rshape)
                        base = computed.get(name, tiles[name][0])
                        base_slice = base[region_idxs(name, region, lmisc)]
                        sel = mask
                        if eq.cond is not None:
                            cm = ev.eval(eq.cond, tiles, computed, memo)
                            cm = jnp.broadcast_to(cm, rshape)
                            sel = cm if sel is None else sel & cm
                        if eq.step_cond is not None:
                            sc = ev.eval(eq.step_cond, tiles, computed,
                                         memo)
                            sc = jnp.broadcast_to(sc, rshape)
                            sel = sc if sel is None else sel & sc
                        # unselected points keep the base (evicted-slot /
                        # earlier-write) values — ghosts there are zero,
                        # so the zero-outside-domain invariant holds.
                        # Partial-dim vars collapse to their own axes
                        # FIRST (the RHS/conditions are constant along
                        # the missing dims — analysis race rule), so the
                        # select runs at var width.
                        val = to_var_region(name, val, region)
                        if sel is not None:
                            sel = to_var_region(name, sel, region)
                            val = jnp.where(sel, val, base_slice)
                        computed[name] = tile_update(
                            base, region_idxs(name, region, lmisc), val)

            # rotate rings with the sub-step's outputs
            for name in written:
                ring = tiles[name]
                newest = computed[name]
                if slots[name] >= 2:
                    tiles[name] = ring[1:] + [newest]
                else:
                    tiles[name] = [newest]

        # 3) write back the slots the K sub-steps actually produced (the
        #    newest min(K, alloc)); untouched older slots merely shifted
        #    and are rebuilt host-side from the existing padded inputs.
        #    Outputs are PADDED arrays written by manual DMA: BlockSpec
        #    windows cannot express the pad-origin offset, and manual
        #    windows keep sublane offsets 8-aligned. Lane rows ride whole
        #    so lane pads inherit the tile's zeros. The produced value is
        #    first staged into the var's (already consumed) input scratch
        #    tile, because DMA sources must be refs.
        #    NOTE: outputs are deliberately NOT aliased onto evicted ring
        #    slots — every tile DMA fetches halo margins from every slot,
        #    so an in-place interior write by one grid step would corrupt
        #    a later step's margin reads on real (aliasing) hardware.

        _oi = 0
        for name in written_out:
            ring = tiles[name]
            nback = min(K, slots[name])
            for s in range(nback):
                val = ring[len(ring) - nback + s]
                if use_pipe_out:
                    ostage[_oi].at[cur][...] = val
                else:
                    buf_ref(si_base[name] + s)[...] = val
                _oi += 1
        for cp in out_dmas(pid, cur):
            cp.start()
        if use_pipe_out:
            # the copies stay in flight through the next grid step's
            # compute (retired at step li+2's top, _retire_out); the
            # final step drains the outstanding two parities so the
            # kernel never ends with a DMA in flight
            @pl.when(li == total_steps - 1)
            def _drain_out():
                # use_pipe_out implies total_steps > 1, so the final
                # step always has a predecessor whose copies are the
                # other outstanding parity
                prv_coords = _coords(li - 1)
                for cp in out_dmas(prv_coords, (li - 1) % 2):
                    cp.wait()
                for cp in out_dmas(pid, cur):
                    cp.wait()
        else:
            # staging rides the consumed input scratch: the copies must
            # land before the next grid step re-fills those tiles
            for cp in out_dmas(pid, cur):
                cp.wait()

    # ---- pallas_call assembly -------------------------------------------

    # outputs are full padded arrays written by in-kernel manual DMA
    # (pushed vars have NO outputs — their tiles die in VMEM)
    out_shapes = []
    out_specs = []
    for name in written_out:
        g = program.geoms[name]
        oshape = list(g.shape)
        if dd is not None and dd in g.domain_dims:
            # diamond fill: one band per boundary — the dim's axis
            # narrows to the band, a leading per-boundary axis is
            # prepended; every other axis keeps the padded extent so
            # the slab geometry is shared with phase 1
            oshape[g.axis_of(dd)] = _diamond["band"]
            oshape = [_diamond["nbounds"]] + oshape
        for _ in range(min(K, slots[name])):
            out_shapes.append(jax.ShapeDtypeStruct(tuple(oshape), dtype))
            out_specs.append(pl.BlockSpec(memory_space=pl.ANY))
    nout_total = len(out_shapes)

    # leading scalars (step index, shard offsets) and domain-dim-less
    # vars ride SMEM; DMA-able arrays stay in HBM (ANY)
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] * nscalars
    for n in var_order:
        space = pltpu.SMEM if n in smem_vars else pl.ANY
        in_specs += [pl.BlockSpec(memory_space=space)] * slots[n]
    scratch_shapes = []
    for n in dma_vars:
        for _ in range(slots[n]):
            shp = tile_shape(n)
            if use_pipe:
                shp = (2,) + shp
            scratch_shapes.append(pltpu.VMEM(shp, dtype))
    # skewed-wavefront carry strips persist across the sequential grid
    for (d_, n_) in carr_base:
        scratch_shapes.append(pltpu.VMEM(carry_shape(d_, n_), dtype))
    # dedicated parity-doubled output staging (pipelined write-back)
    if use_pipe_out:
        for name in written_out:
            for _ in range(min(K, slots[name])):
                scratch_shapes.append(
                    pltpu.VMEM((2,) + tile_shape(name), dtype))
    n_arrays = sum(slots[n] for n in dma_vars)
    scratch_shapes.append(pltpu.SemaphoreType.DMA(
        (2, n_arrays) if use_pipe else (n_arrays,)))
    scratch_shapes.append(pltpu.SemaphoreType.DMA(
        (2, max(nout_total, 1)) if use_pipe_out
        else (max(nout_total, 1),)))

    kwargs = {}
    if not interpret:
        # Sequential grid for skew/pipelined builds: staging the outputs
        # reuses the input scratch tiles (racy under megacore
        # partitioning when steps interleave), and the linear-index DMA
        # prefetch additionally requires it.  Trapezoid/diamond builds
        # declare every grid dim "parallel" (dim_sem): no carries, no
        # prefetch, synchronous per-step drains on disjoint windows.
        # The VMEM limit is raised above Mosaic's 16 MiB default scope
        # (v5e takes ≥120 MiB, probed): tiles budget vmem_budget, live
        # SSA values roughly double it.
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=dim_sem,
            vmem_limit_bytes=vmem_limit_bytes(vmem_budget))

    call = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        scratch_shapes=scratch_shapes,
        interpret=interpret,
        **kwargs,
    )

    def chunk(state, t0, offsets=None):
        flat = [jnp.asarray(t0, dtype=jnp.int32).reshape(1)]
        if distributed:
            flat.append(jnp.asarray(offsets, dtype=jnp.int32))
        for n in var_order:
            for a in state[n]:
                flat.append(a.reshape(1) if a.ndim == 0 else a)
        outs = call(*flat)
        if _diamond is not None:
            # fill pass: raw per-boundary band arrays — the outer
            # trapezoid chunk stitches them host-side
            return list(outs)
        # pushed vars are ABSENT from the outputs: their rings in
        # new_state keep the (now stale) input arrays — the pipeline
        # runtime never exposes them, and compare/get_var guard them
        new_state = dict(state)
        oi = 0
        for name in written_out:
            g = program.geoms[name]
            nback = min(K, slots[name])
            news = []
            for s in range(nback):
                a = outs[oi]
                # outputs come back already padded (no re-pad copy); the
                # lead-dim pad bands are re-zeroed to keep the
                # ghost-zero invariant (lane pads ride whole and inherit
                # tile zeros; window cells outside the global problem —
                # ceil overshoot, skewed-level shift — were masked to
                # zero in-kernel, so zeroing the whole out-of-interior
                # band is equivalent and covers both tilings)
                for dn, kind in g.axes:
                    if kind != "domain" or dn == minor:
                        continue
                    ax = g.axis_of(dn)
                    o = g.origin[dn]
                    hiw = o + sizes[dn]
                    if o > 0:
                        idx = [slice(None)] * a.ndim
                        idx[ax] = slice(0, o)
                        a = a.at[tuple(idx)].set(0)
                    if hiw < a.shape[ax]:
                        idx = [slice(None)] * a.ndim
                        idx[ax] = slice(hiw, a.shape[ax])
                        a = a.at[tuple(idx)].set(0)
                news.append(a)
                oi += 1
            # ring after K steps = surviving (already padded) input slots
            # shifted down, plus the newly produced ones
            new_state[name] = list(state[name][nback:]) + news
        # ---- diamond fill pass (phase 2): stitch the gap bands ------
        # Each fill chunk recomputes, from the SAME level-0 input
        # state, the band around every phase-1 tile boundary where the
        # shrunken write windows left stale/smeared cells; the bands
        # overwrite those cells with the oracle values.  Windows clip
        # to the interior (band cells beyond the other dims' grid
        # coverage are unwritten; out-of-domain band cells are zero by
        # the in-kernel mask, and the pad re-zero above already holds).
        for (d_t, stride, nbounds, half, cls, sub) in dia_subs:
            bouts = sub(state, t0, offsets)
            bi = 0
            for name in written:
                g = program.geoms[name]
                ax = g.axis_of(d_t)
                nback = min(K, slots[name])
                for s in range(nback):
                    lvl = K - nback + s + 1
                    clv = cls[lvl]
                    bnd = bouts[bi]
                    bi += 1
                    if clv == 0:
                        continue   # phase 1 wrote this level in full
                    a = new_state[name][slots[name] - nback + s]
                    for j in range(nbounds):
                        s_lo = max(0, j * stride - clv)
                        s_hi = min(sizes[d_t], j * stride + clv)
                        if s_hi <= s_lo:
                            continue
                        didx = [slice(None)] * a.ndim
                        didx[ax] = slice(g.origin[d_t] + s_lo,
                                         g.origin[d_t] + s_hi)
                        sidx = [j] + [slice(None)] * a.ndim
                        sidx[1 + ax] = slice(half + s_lo - j * stride,
                                             half + s_hi - j * stride)
                        for dn2, kind2 in g.axes:
                            if kind2 != "domain" or dn2 in (minor, d_t):
                                continue
                            ax2 = g.axis_of(dn2)
                            didx[ax2] = slice(g.origin[dn2],
                                              g.origin[dn2]
                                              + sizes[dn2])
                            sidx[1 + ax2] = didx[ax2]
                        a = a.at[tuple(didx)].set(bnd[tuple(sidx)])
                    new_state[name][slots[name] - nback + s] = a
        return new_state

    # Report the tiling ACTUALLY chosen (skew/pipelining can auto-fall
    # back during planning) so stats/bench model the kernel that runs,
    # not the one eligibility predicted (ADVICE r3).  margin_overhead =
    # redundant computed volume / useful volume per K-group, from the
    # exact per-(sub-step, stage) region widths — the number the skew
    # tiling exists to shrink (reference reports the analogous
    # wave-front overlap in its temporal-tiling stats).
    if trap_dims:
        # trapezoid: THE dataflow plan's cost model (phase-1 shrinking
        # regions + the diamond fill-pass recompute) — the same numbers
        # the profit gate compared
        _useful, _computed, _f = tplan.volumes(block)
    else:
        _useful = _computed = 0
        for _k in range(K):
            _cons = {d: rad[d] * _k for d in lead}
            for _si in range(nstages):
                for d in lead:
                    _cons[d] += stage_r[_si][d]
                _v = _u = 1
                for d in lead:
                    if d in skew_set:
                        _cst = _cons[d] - rad[d] * _k
                        _v *= block[d] + 2 * (R[d] - _cst) + E[d]
                    else:
                        _v *= block[d] + mL[d] + mR[d] - 2 * _cons[d]
                    _u *= block[d]
                _computed += _v
                _useful += _u
    chunk.tiling = {"fuse_steps": K, "block": dict(block),
                    "skew": bool(use_skew),
                    "skew_dims": list(skew_dims),
                    "push": bool(use_push),
                    "push_vars": list(pushed),
                    "push_tile_bytes": sum(
                        slots[n] * int(math.prod(tile_shape(n))) * esize
                        for n in pushed),
                    "trapezoid": bool(trap_dims),
                    "trap_dims": list(trap_dims),
                    "dimension_semantics": list(dim_sem),
                    "diamond": [{"dim": s[0], "stride": s[1],
                                 "nbounds": s[2], "half": s[3]}
                                for s in dia_subs],
                    "region": ({d: list(region[d]) for d in sorted(restricted)}
                               if restricted else None),
                    "pipeline_dmas": use_pipe,
                    "pipeline_out": use_pipe_out,
                    "tile_bytes": tile_bytes,
                    "margin_overhead":
                        round(_computed / max(_useful, 1) - 1, 4),
                    "reasons": list(reasons)}
    return chunk, tile_bytes


def program_state_slots(program, name: str) -> List[int]:
    g = program.geoms[name]
    n = g.num_slots
    return list(range(n))
