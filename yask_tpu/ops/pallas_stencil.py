"""Pallas stencil kernels: halo tiles in VMEM + K-step temporal fusion.

This is the TPU replacement for the reference's generated inner loops
(vector folding + nano/pico loops, ``YaskKernel.cpp:574-676``) *and* its
temporal wave-front tiling (``context.hpp:331-347``): one kernel invocation

1. DMAs an (bx+2·r·K, by+2·r·K, Nz_padded) halo tile of each input var
   from HBM into VMEM (the fold/tile planner's job: the minor-most dim
   stays whole so it rides the 128-lane axis);
2. applies **K fused time steps** entirely in VMEM — the compute region
   shrinks by the stencil radius each sub-step (the trapezoid/wavefront
   shape), and a global-domain mask keeps physical-boundary ghosts at
   zero between sub-steps (matching the runtime's ghost semantics);
3. writes the final (and, for 2-slot rings, the previous) time level's
   interior block back.

HBM traffic per K steps ≈ one read + one write of each var, versus K of
each for the unfused path — the same arithmetic-intensity win wave-front
tiling buys the reference.

Applicability (checked by :func:`pallas_applicable`): single stage, no
sub-domain/step conditions, no scratch vars, no index-value expressions,
ring allocation ≤ 2, every var spanning all domain dims in the same order.
Everything else falls back to the XLA-fused path.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Dict, List, Optional, Tuple

from yask_tpu.utils.exceptions import YaskException
from yask_tpu.compiler.expr import (
    AddExpr,
    AndExpr,
    CompExpr,
    ConstExpr,
    DivExpr,
    Expr,
    FirstIndexExpr,
    FuncExpr,
    IndexExpr,
    LastIndexExpr,
    ModExpr,
    MultExpr,
    NegExpr,
    NotExpr,
    OrExpr,
    SubExpr,
    VarPoint,
)


def pallas_applicable(csol) -> Tuple[bool, str]:
    """Can this solution run on the Pallas fused path? Supported: multi-
    stage chains (ssg/fsg-class), sub-domain/step conditions (awp-class —
    lowered to in-tile masks over global coordinates), index-value
    expressions, and partial-dim read-only coefficient vars (sponge
    factors). Excluded: scratch vars, misc dims, partial-dim *written*
    vars, ring allocation > 2."""
    ana = csol.ana
    if len(ana.domain_dims) < 2:
        return False, "needs >= 2 domain dims"
    for v in csol.soln.get_vars():
        if v.is_scratch():
            return False, "has scratch vars"
        if v.misc_dim_names():
            return False, "has misc dims"
        if v.is_written:
            if v.domain_dim_names() != ana.domain_dims:
                return False, (f"written var '{v.get_name()}' must span "
                               "all domain dims")
            if v.get_step_alloc_size() > 2:
                return False, "ring allocation > 2"
    return True, "ok"


# ---------------------------------------------------------------------------


class _TileEval:
    """Evaluate the stencil AST on VMEM tile values.

    ``tiles[name]`` is the ring of tile arrays (oldest→newest); a read at
    offset ``o`` over compute-region ``lo..hi`` (tile coords, leading
    dims; interior-relative for the minor dim) slices ``[lo+o : hi+o]``
    with the var's own origins. Partial-dim read-only vars broadcast into
    the region; index expressions produce *global* coordinate arrays so
    conditions behave identically to the XLA path.
    """

    def __init__(self, jnp, program, minor: str,
                 minor_origin: Dict[str, int]):
        self.jnp = jnp
        self.program = program
        self.dims = program.ana.domain_dims
        self.minor = minor
        self.step_dir = program.ana.step_dir
        self.minor_origin = minor_origin
        from yask_tpu.compiler.lowering import JnpOps
        self.ops = JnpOps()
        # set per-(stage, sub-step) by the kernel before evaluation:
        self.region = None          # [(lo,hi)] per solution dim
        self.gidx_base = None       # per lead dim: traced global offset of
        #                             tile position 0 (pid*block - hK)
        self.t = None               # step-index value (traced or None)

    def global_index(self, d: str):
        """Global coordinate array for dim d over the current region,
        broadcast-shaped."""
        di = self.dims.index(d)
        lo, hi = self.region[di]
        ar = self.jnp.arange(lo, hi, dtype=self.jnp.int32)
        if d != self.minor:
            ar = ar + self.gidx_base[d]
        shape = [1] * len(self.dims)
        shape[di] = hi - lo
        return ar.reshape(shape)

    def read(self, p: VarPoint, tiles, computed):
        name = p.var_name()
        g = self.program.geoms[name]
        so = p.step_offset()
        region = self.region
        if name in computed and so is not None and so == self.step_dir:
            # Same-step read of an earlier stage's output: computed values
            # are kept as FULL tiles (written via .at[region].set on the
            # evicted base), so offset slicing works exactly like rings.
            arr = computed[name]
        else:
            ring = tiles[name]
            if so is None or not g.is_written:
                arr = ring[-1]
            else:
                idx = len(ring) - 1 + so * self.step_dir
                arr = ring[idx]
        offs = p.domain_offsets()
        idxs = []
        for dn, kind in g.axes:   # var's own axis order
            di = self.dims.index(dn)
            lo, hi = region[di]
            o = offs.get(dn, 0)
            if dn == self.minor:
                base = self.minor_origin[name]
                idxs.append(slice(base + lo + o, base + hi + o))
            else:
                idxs.append(slice(lo + o, hi + o))
        out = arr[tuple(idxs)]

        var_dd = g.domain_dims
        if var_dd != self.dims:
            # partial-dim (or reordered) var: transpose into solution
            # order, insert singleton axes, broadcast over the region
            present = [d for d in self.dims if d in var_dd]
            perm = [var_dd.index(d) for d in present]
            if perm != list(range(len(perm))):
                out = out.transpose(perm)
            shape = []
            for d in self.dims:
                di = self.dims.index(d)
                lo, hi = region[di]
                shape.append(hi - lo if d in var_dd else 1)
            out = out.reshape(tuple(shape))
            tgt = tuple(hi - lo for lo, hi in region)
            out = self.jnp.broadcast_to(out, tgt)
        return out

    def eval(self, e: Expr, tiles, computed, memo):
        k = e.skey()   # structural: CSE across equations within a sub-step
        if k in memo:
            return memo[k]
        jnp = self.jnp
        ev = lambda a: self.eval(a, tiles, computed, memo)
        if isinstance(e, ConstExpr):
            r = e.value
        elif isinstance(e, VarPoint):
            r = self.read(e, tiles, computed)
        elif isinstance(e, IndexExpr):
            if e.type.value == "step":
                r = self.t
            elif e.type.value == "domain":
                r = self.global_index(e.name)
            else:  # pragma: no cover - excluded by pallas_applicable
                raise YaskException("misc index as value on pallas path")
        elif isinstance(e, FirstIndexExpr):
            r = 0
        elif isinstance(e, LastIndexExpr):
            r = self.program.global_last[e.dim.name]
        elif isinstance(e, NegExpr):
            r = -ev(e.arg)
        elif isinstance(e, AddExpr):
            r = ev(e.args[0])
            for a in e.args[1:]:
                r = r + ev(a)
        elif isinstance(e, MultExpr):
            r = ev(e.args[0])
            for a in e.args[1:]:
                r = r * ev(a)
        elif isinstance(e, SubExpr):
            r = ev(e.lhs) - ev(e.rhs)
        elif isinstance(e, DivExpr):
            r = ev(e.lhs) / ev(e.rhs)
        elif isinstance(e, ModExpr):
            r = ev(e.lhs) % ev(e.rhs)
        elif isinstance(e, FuncExpr):
            r = self.ops.func(e.name, [ev(a) for a in e.args])
        elif isinstance(e, CompExpr):
            a, b = ev(e.lhs), ev(e.rhs)
            r = {"==": lambda: a == b, "!=": lambda: a != b,
                 "<": lambda: a < b, "<=": lambda: a <= b,
                 ">": lambda: a > b, ">=": lambda: a >= b}[e.op]()
        elif isinstance(e, AndExpr):
            r = jnp.logical_and(ev(e.lhs), ev(e.rhs))
        elif isinstance(e, OrExpr):
            r = jnp.logical_or(ev(e.lhs), ev(e.rhs))
        elif isinstance(e, NotExpr):
            r = jnp.logical_not(ev(e.arg))
        else:  # pragma: no cover - excluded by pallas_applicable
            raise YaskException(f"pallas path cannot evaluate {type(e)}")
        memo[k] = r
        return r


# ---------------------------------------------------------------------------


def build_pallas_chunk(program, fuse_steps: int = 1,
                       block: Optional[Tuple[int, ...]] = None,
                       interpret: bool = False,
                       vmem_budget: int = 100 * 2 ** 20):
    """Build ``chunk(state) -> state`` advancing ``fuse_steps`` steps in one
    fused Pallas sweep.

    ``program`` must be planned with ``extra_pad`` ≥ the fused halo
    (radius × fuse_steps) in the leading dims — the runtime arranges this.
    Returns (chunk_fn, tile_bytes).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    ana = program.ana
    dims = ana.domain_dims
    K = fuse_steps
    lead = dims[:-1]
    minor = dims[-1]

    # Per-stage, per-leading-dim read radius: within one fused sub-step a
    # stage consumes its radius of tile margin (same-step chains eat
    # margin stage by stage — the trapezoid accounting of the reference's
    # temporal blocking, setup.cpp:863).
    nstages = len(ana.stages)
    stage_r: List[Dict[str, int]] = []
    for si in range(nstages):
        sr = {d: 0 for d in lead}
        for vname, widths in program.stage_reads[si].items():
            for d, (l, r) in widths.items():
                if d in sr:
                    sr[d] = max(sr[d], l, r)
        stage_r.append(sr)
    # full-step shrink per dim = sum over stages; fused halo = K x that
    # (fused_step_radius is the single source both here and in the
    # runtime's pad planning)
    rad_all = ana.fused_step_radius()
    rad = {d: rad_all.get(d, 0) for d in lead}
    hK = {d: rad[d] * K for d in lead}

    sizes = {d: program.sizes[d] for d in dims}

    # Every var's leading-dim pads must cover the fused halo, or the DMA
    # start/end would clamp silently and corrupt results: the runtime
    # plans extra_pad = radius*K at prepare time, so a K larger than
    # planned must be rejected here (the auto-tuner relies on this to
    # skip infeasible candidates).
    for n, g in program.geoms.items():
        for d in lead:
            if d not in g.domain_dims:
                continue  # partial-dim var lacks this axis
            pl_, pr_ = g.pads[d]
            if pl_ < hK[d] or pr_ < hK[d]:
                raise YaskException(
                    f"pallas fuse_steps={K} needs pad >= {hK[d]} in dim "
                    f"'{d}' but var '{n}' has ({pl_},{pr_}); re-prepare "
                    "with wf_steps set to the desired fusion depth")

    # default block: from the tile planner (fold hints → VREG mapping)
    if block is None:
        from yask_tpu.ops.tile_planner import plan_blocks
        block = plan_blocks(program, fuse_steps=K, vmem_budget=vmem_budget)
    else:
        block = {d: min(b, sizes[d]) for d, b in zip(lead, block)}
    for d in lead:
        if sizes[d] % block[d] != 0:
            # shrink to a divisor
            b = block[d]
            while sizes[d] % b != 0:
                b -= 1
            block[d] = b

    var_order = sorted(program.geoms)
    written = [n for n in var_order if program.geoms[n].is_written]

    # tile geometry per var (its own axes): leading dims it has are sized
    # block+2hK; the minor dim (if present) is its full padded extent
    def tile_shape(name):
        g = program.geoms[name]
        shp = []
        for dn, kind in g.axes:
            if dn == minor:
                pl_, pr_ = g.pads[minor]
                shp.append(sizes[minor] + pl_ + pr_)
            else:
                shp.append(block[dn] + 2 * hK[dn])
        return tuple(shp) if shp else (1,)  # 0-dim vars ride as (1,)

    dtype = program.dtype
    esize = jnp.dtype(dtype).itemsize
    tile_bytes = 0
    slots: Dict[str, int] = {}
    for n in var_order:
        g = program.geoms[n]
        nslots = len(program_state_slots(program, n))
        slots[n] = nslots
        tile_bytes += nslots * int(
            math.prod(tile_shape(n))) * esize
    # workspace for sub-step results (rough: one extra tile per written var)
    tile_bytes += sum(int(math.prod(tile_shape(n))) * esize for n in written)
    if tile_bytes > vmem_budget:
        raise YaskException(
            f"pallas tile needs {tile_bytes/2**20:.1f} MiB VMEM "
            f"(budget {vmem_budget/2**20:.0f}); shrink block or fuse_steps")

    grid = tuple(sizes[d] // block[d] for d in lead)
    minor_origin = {n: (program.geoms[n].pads[minor][0]
                        if minor in program.geoms[n].domain_dims else 0)
                    for n in var_order}
    ev = _TileEval(jnp, program, minor, minor_origin)

    stage_eqs = [[eq for part in st.parts for eq in part.eqs]
                 for st in ana.stages]

    dirn = ana.step_dir

    n_inputs = sum(slots[n] for n in var_order) + 1  # +1: t0 scalar

    def kernel(*refs):
        # refs: t0 (SMEM), inputs (ANY/HBM) ..., outputs (VMEM blocks),
        #       scratch tiles ..., sem
        t0_ref = refs[0]
        ins = refs[1:n_inputs]
        nout = sum(min(slots[n], 2) for n in written)
        outs = refs[n_inputs:n_inputs + nout]
        scratch = refs[n_inputs + nout:-1]
        sem = refs[-1]

        pid = [pl.program_id(i) for i in range(len(lead))]

        # 1) DMA halo tiles HBM → VMEM.
        dmas = []
        si = 0
        for n in var_order:
            g = program.geoms[n]
            for s in range(slots[n]):
                src = ins[si]
                idxs = []
                for dn, kind in g.axes:
                    if dn == minor:
                        idxs.append(slice(None))  # full padded extent
                    else:
                        di = lead.index(dn)
                        start = (pid[di] * block[dn]
                                 + g.origin[dn] - hK[dn])
                        idxs.append(pl.ds(start, block[dn] + 2 * hK[dn]))
                dma = pltpu.make_async_copy(
                    src.at[tuple(idxs)] if idxs else src,
                    scratch[si], sem.at[si])
                dma.start()
                dmas.append(dma)
                si += 1
        for dma in dmas:
            dma.wait()

        # tiles as values
        tiles: Dict[str, List] = {}
        si = 0
        for n in var_order:
            tiles[n] = []
            for s in range(slots[n]):
                tiles[n].append(scratch[si][...])
                si += 1

        # 2) K fused sub-steps; within each, every stage consumes its read
        #    radius of tile margin (trapezoid shrink) and writes a FULL
        #    tile (base.at[region].set) so later stages read it at offsets.
        def region_idxs(name, region):
            mo = program.geoms[name].pads[minor][0]
            return tuple(slice(lo, hi) for lo, hi in region[:-1]) \
                + (slice(mo + region[-1][0], mo + region[-1][1]),)

        ev.gidx_base = {d: pid[lead.index(d)] * block[d] - hK[d]
                        for d in lead}
        for k in range(K):
            computed: Dict[str, object] = {}
            consumed = {d: rad[d] * k for d in lead}
            ev.t = t0_ref[0] + k * dirn
            for si_stage in range(nstages):
                for d in lead:
                    consumed[d] += stage_r[si_stage][d]
                region = []
                for d in lead:
                    region.append((consumed[d],
                                   block[d] + 2 * hK[d] - consumed[d]))
                # minor: interior-relative (per-var pad origin applied at
                # read/write time); pads stay zero
                region.append((0, sizes[minor]))
                ev.region = region
                rshape = tuple(hi - lo for lo, hi in region)

                # global-domain mask over the region's leading dims
                mask = None
                for di, d in enumerate(lead):
                    lo, hi = region[di]
                    gidx = (jnp.arange(lo, hi)
                            + pid[di] * block[d] - hK[d])
                    m = (gidx >= 0) & (gidx < sizes[d])
                    shape = [1] * len(dims)
                    shape[di] = hi - lo
                    m = m.reshape(shape)
                    mask = m if mask is None else mask & m

                memo: Dict = {}
                for eq in stage_eqs[si_stage]:
                    name = eq.lhs.var_name()
                    val = ev.eval(eq.rhs, tiles, computed, memo)
                    val = jnp.asarray(val, dtype=dtype)
                    val = jnp.broadcast_to(val, rshape)
                    base = computed.get(name, tiles[name][0])
                    base_slice = base[region_idxs(name, region)]
                    sel = mask
                    if eq.cond is not None:
                        cm = ev.eval(eq.cond, tiles, computed, memo)
                        cm = jnp.broadcast_to(cm, rshape)
                        sel = cm if sel is None else sel & cm
                    if eq.step_cond is not None:
                        sc = ev.eval(eq.step_cond, tiles, computed, memo)
                        sc = jnp.broadcast_to(sc, rshape)
                        sel = sc if sel is None else sel & sc
                    # unselected points keep the base (evicted-slot /
                    # earlier-write) values — ghosts there are zero, so
                    # the zero-outside-domain invariant is preserved
                    if sel is not None:
                        val = jnp.where(sel, val, base_slice)
                    computed[name] = base.at[region_idxs(name, region)] \
                        .set(val)

            # rotate rings with the sub-step's outputs
            for name in written:
                ring = tiles[name]
                newest = computed[name]
                if slots[name] >= 2:
                    tiles[name] = ring[1:] + [newest]
                else:
                    tiles[name] = [newest]

        # 3) write final interior block(s).
        oi = 0
        for name in written:
            g = program.geoms[name]
            ring = tiles[name]
            keep = min(slots[name], 2)
            for s in range(keep):
                src = ring[len(ring) - keep + s]
                idxs = []
                for d in lead:
                    idxs.append(slice(hK[d], hK[d] + block[d]))
                mlo = g.pads[minor][0]
                idxs.append(slice(mlo, mlo + sizes[minor]))
                outs[oi][...] = src[tuple(idxs)]
                oi += 1

    # ---- pallas_call assembly -------------------------------------------

    out_shapes = []
    out_specs = []
    for name in written:
        keep = min(slots[name], 2)
        for _ in range(keep):
            out_shapes.append(jax.ShapeDtypeStruct(
                tuple(sizes[d] for d in dims), dtype))
            out_specs.append(pl.BlockSpec(
                tuple(block[d] for d in lead) + (sizes[minor],),
                lambda *pid: tuple(pid) + (0,)))

    # input 0 is the step-index scalar in SMEM; the rest stay in HBM
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] \
        + [pl.BlockSpec(memory_space=pl.ANY)] * (n_inputs - 1)
    scratch_shapes = []
    for n in var_order:
        for _ in range(slots[n]):
            scratch_shapes.append(pltpu.VMEM(tile_shape(n), dtype))
    scratch_shapes.append(pltpu.SemaphoreType.DMA((n_inputs - 1,)))

    call = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        scratch_shapes=scratch_shapes,
        interpret=interpret,
    )

    def chunk(state, t0):
        flat = [jnp.asarray(t0, dtype=jnp.int32).reshape(1)]
        for n in var_order:
            for a in state[n]:
                flat.append(a.reshape(1) if a.ndim == 0 else a)
        outs = call(*flat)
        new_state = dict(state)
        oi = 0
        for name in written:
            g = program.geoms[name]
            keep = min(slots[name], 2)
            ring = list(state[name])
            pads = []
            for d in dims:
                pads.append(g.pads[d])
            news = []
            for s in range(keep):
                news.append(jnp.pad(outs[oi], pads))
                oi += 1
            # ring after K steps: oldest slots beyond `keep` are dropped
            # (alloc ≤ 2 enforced), newest two replaced
            if len(ring) == 1:
                new_state[name] = [news[-1]]
            else:
                new_state[name] = news[-2:]
        return new_state

    return chunk, tile_bytes


def program_state_slots(program, name: str) -> List[int]:
    g = program.geoms[name]
    n = g.alloc if (g.has_step and g.is_written) else 1
    return list(range(n))
