"""Pallas stencil kernels: halo tiles in VMEM + K-step temporal fusion.

This is the TPU replacement for the reference's generated inner loops
(vector folding + nano/pico loops, ``YaskKernel.cpp:574-676``) *and* its
temporal wave-front tiling (``context.hpp:331-347``): one kernel invocation

1. DMAs an (bx+2·r·K, by+2·r·K, Nz_padded) halo tile of each input var
   from HBM into VMEM (the fold/tile planner's job: the minor-most dim
   stays whole so it rides the 128-lane axis);
2. applies **K fused time steps** entirely in VMEM — the compute region
   shrinks by the stencil radius each sub-step (the trapezoid/wavefront
   shape), and a global-domain mask keeps physical-boundary ghosts at
   zero between sub-steps (matching the runtime's ghost semantics);
3. writes the final (and, for 2-slot rings, the previous) time level's
   interior block back.

HBM traffic per K steps ≈ one read + one write of each var, versus K of
each for the unfused path — the same arithmetic-intensity win wave-front
tiling buys the reference.

Applicability (checked by :func:`pallas_applicable`): ≥ 2 domain dims and
written vars spanning all domain dims (misc axes on them are fine — the
LHS misc values pin the write position). Multi-stage chains, sub-
domain/step conditions, scratch-var chains (evaluated in-tile over
write-halo-expanded regions), misc-dim and partial-dim read-only vars,
and arbitrary ring depth are all handled in-kernel; the rest falls back
to the XLA-fused path.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Dict, List, Optional, Tuple

from yask_tpu.utils.exceptions import YaskException
from yask_tpu.compiler.expr import (
    AddExpr,
    AndExpr,
    CompExpr,
    ConstExpr,
    DivExpr,
    Expr,
    FirstIndexExpr,
    FuncExpr,
    IndexExpr,
    LastIndexExpr,
    ModExpr,
    MultExpr,
    NegExpr,
    NotExpr,
    OrExpr,
    SubExpr,
    VarPoint,
)


def pallas_applicable(csol) -> Tuple[bool, str]:
    """Can this solution run on the Pallas fused path? Supported: multi-
    stage chains (ssg/fsg-class), sub-domain/step conditions (awp-class —
    lowered to in-tile masks over global coordinates), index-value
    expressions, partial-dim read-only coefficient vars (sponge factors),
    scratch-var chains evaluated in-tile over expanded regions
    (tti/swe2d-class), misc-dim vars including written ones (filter
    kernels — constant LHS misc values pin the write), and any ring
    allocation (deep time reads, 2nd-order-in-time schemes). Excluded:
    partial-dim *written* vars (a tile owner for a var lacking grid dims
    is ambiguous) and 1-D solutions (nothing to tile)."""
    ana = csol.ana
    if len(ana.domain_dims) < 2:
        return False, "needs >= 2 domain dims"
    for v in csol.soln.get_vars():
        if v.is_written:
            if v.domain_dim_names() != ana.domain_dims:
                return False, (f"written var '{v.get_name()}' must span "
                               "all domain dims")

    # misc indices used as VALUES have no tile lowering — reject at
    # prepare time with the fallback hint, not at first-run trace time
    from yask_tpu.compiler.expr import ExprVisitor, IndexType

    class _MiscValue(ExprVisitor):
        found = False

        def visit_index(self, node):
            if node.type == IndexType.MISC:
                self.found = True

    mv = _MiscValue()
    for eq in ana.eqs:
        eq.rhs.accept(mv)
        if eq.cond is not None:
            eq.cond.accept(mv)
        if eq.step_cond is not None:
            eq.step_cond.accept(mv)
    if mv.found:
        return False, "uses a misc index as a value"
    return True, "ok"


# ---------------------------------------------------------------------------


class _TileEval:
    """Evaluate the stencil AST on VMEM tile values.

    ``tiles[name]`` is the ring of tile arrays (oldest→newest); a read at
    offset ``o`` over compute-region ``lo..hi`` (tile coords, leading
    dims; interior-relative for the minor dim) slices ``[lo+o : hi+o]``
    with the var's own origins. Partial-dim read-only vars broadcast into
    the region; index expressions produce *global* coordinate arrays so
    conditions behave identically to the XLA path.
    """

    def __init__(self, jnp, program, minor: str,
                 minor_origin: Dict[str, int]):
        self.jnp = jnp
        self.program = program
        self.dims = program.ana.domain_dims
        self.minor = minor
        self.step_dir = program.ana.step_dir
        self.minor_origin = minor_origin
        from yask_tpu.compiler.lowering import JnpOps
        self.ops = JnpOps()
        # set per-(stage, sub-step) by the kernel before evaluation:
        self.region = None          # [(lo,hi)] per solution dim
        self.gidx_base = None       # per lead dim: traced global offset of
        #                             tile position 0 (pid*block - hK)
        self.t = None               # step-index value (traced or None)
        self.scratch = {}           # scratch var -> full-tile value

    def global_index(self, d: str):
        """Global coordinate array for dim d over the current region,
        broadcast-shaped. ``gidx_base`` maps tile position 0 to the
        global-problem coordinate (it includes the shard offset in
        distributed mode)."""
        di = self.dims.index(d)
        lo, hi = self.region[di]
        ar = self.jnp.arange(lo, hi, dtype=self.jnp.int32)
        base = self.gidx_base.get(d)
        if base is not None:
            ar = ar + base
        shape = [1] * len(self.dims)
        shape[di] = hi - lo
        return ar.reshape(shape)

    def read(self, p: VarPoint, tiles, computed):
        name = p.var_name()
        g = self.program.geoms[name]
        so = p.step_offset()
        region = self.region
        if g.is_scratch:
            # Scratch values live as full-tile arrays computed earlier in
            # this sub-step over an expanded region, so offset slicing
            # works exactly like ring tiles.
            arr = self.scratch[name]
        elif name in computed and so is not None and so == self.step_dir:
            # Same-step read of an earlier stage's output: computed values
            # are kept as FULL tiles (written via .at[region].set on the
            # evicted base), so offset slicing works exactly like rings.
            arr = computed[name]
        else:
            ring = tiles[name]
            if so is None or not g.is_written:
                arr = ring[-1]
            else:
                idx = len(ring) - 1 + so * self.step_dir
                if not (0 <= idx < len(ring)):
                    # mirror the XLA path's bounds check — a negative
                    # Python index would silently wrap to the newest slot
                    raise YaskException(
                        f"step offset {so} of '{name}' outside its "
                        f"allocation {len(ring)}")
                arr = ring[idx]
        offs = p.domain_offsets()
        misc = p.misc_vals()
        idxs = []
        for dn, kind in g.axes:   # var's own axis order
            if kind == "misc":
                idxs.append(misc[dn] - g.misc_lo[dn])
                continue
            di = self.dims.index(dn)
            lo, hi = region[di]
            o = offs.get(dn, 0)
            if dn == self.minor:
                base = self.minor_origin[name]
                idxs.append(slice(base + lo + o, base + hi + o))
            else:
                idxs.append(slice(lo + o, hi + o))
        out = arr[tuple(idxs)]

        var_dd = g.domain_dims
        if var_dd != self.dims:
            # partial-dim (or reordered) var: transpose into solution
            # order, insert singleton axes, broadcast over the region
            present = [d for d in self.dims if d in var_dd]
            perm = [var_dd.index(d) for d in present]
            if perm != list(range(len(perm))):
                out = out.transpose(perm)
            shape = []
            for d in self.dims:
                di = self.dims.index(d)
                lo, hi = region[di]
                shape.append(hi - lo if d in var_dd else 1)
            out = out.reshape(tuple(shape))
            tgt = tuple(hi - lo for lo, hi in region)
            out = self.jnp.broadcast_to(out, tgt)
        return out

    def eval(self, e: Expr, tiles, computed, memo):
        k = e.skey()   # structural: CSE across equations within a sub-step
        if k in memo:
            return memo[k]
        jnp = self.jnp
        ev = lambda a: self.eval(a, tiles, computed, memo)
        if isinstance(e, ConstExpr):
            r = e.value
        elif isinstance(e, VarPoint):
            r = self.read(e, tiles, computed)
        elif isinstance(e, IndexExpr):
            if e.type.value == "step":
                r = self.t
            elif e.type.value == "domain":
                r = self.global_index(e.name)
            else:  # pragma: no cover - excluded by pallas_applicable
                raise YaskException("misc index as value on pallas path")
        elif isinstance(e, FirstIndexExpr):
            r = 0
        elif isinstance(e, LastIndexExpr):
            r = self.program.global_last[e.dim.name]
        elif isinstance(e, NegExpr):
            r = -ev(e.arg)
        elif isinstance(e, AddExpr):
            r = ev(e.args[0])
            for a in e.args[1:]:
                r = r + ev(a)
        elif isinstance(e, MultExpr):
            r = ev(e.args[0])
            for a in e.args[1:]:
                r = r * ev(a)
        elif isinstance(e, SubExpr):
            r = ev(e.lhs) - ev(e.rhs)
        elif isinstance(e, DivExpr):
            r = ev(e.lhs) / ev(e.rhs)
        elif isinstance(e, ModExpr):
            r = ev(e.lhs) % ev(e.rhs)
        elif isinstance(e, FuncExpr):
            r = self.ops.func(e.name, [ev(a) for a in e.args])
        elif isinstance(e, CompExpr):
            a, b = ev(e.lhs), ev(e.rhs)
            r = {"==": lambda: a == b, "!=": lambda: a != b,
                 "<": lambda: a < b, "<=": lambda: a <= b,
                 ">": lambda: a > b, ">=": lambda: a >= b}[e.op]()
        elif isinstance(e, AndExpr):
            r = jnp.logical_and(ev(e.lhs), ev(e.rhs))
        elif isinstance(e, OrExpr):
            r = jnp.logical_or(ev(e.lhs), ev(e.rhs))
        elif isinstance(e, NotExpr):
            r = jnp.logical_not(ev(e.arg))
        else:  # pragma: no cover - excluded by pallas_applicable
            raise YaskException(f"pallas path cannot evaluate {type(e)}")
        memo[k] = r
        return r


# ---------------------------------------------------------------------------


def default_vmem_budget(platform: str) -> int:
    """Device-derived Pallas VMEM budget: ~16 MiB/core on real TPU (the
    hardware guide's figure; overridable via ``-vmem_mb``), a loose
    100 MiB under CPU interpret where VMEM is emulated and the budget
    only shapes planning. Single definition for the runtime context,
    harness tools, and bench."""
    return 16 * 2 ** 20 if platform == "tpu" else 100 * 2 ** 20


def build_pallas_chunk(program, fuse_steps: int = 1,
                       block: Optional[Tuple[int, ...]] = None,
                       interpret: bool = False,
                       vmem_budget: int = 100 * 2 ** 20,
                       distributed: bool = False,
                       pipeline_dmas: Optional[bool] = None):
    """Build ``chunk(state, t0) -> state`` advancing ``fuse_steps`` steps
    in one fused Pallas sweep.

    ``program`` must be planned with ``extra_pad`` ≥ the fused halo
    (radius × fuse_steps) in the leading dims — the runtime arranges this.
    Returns (chunk_fn, tile_bytes).

    With ``distributed=True`` the chunk is the per-shard inner kernel of
    the shard_map+pallas path: it takes a third argument ``offsets`` (an
    i32 vector of this shard's global origin per domain dim, traced from
    ``lax.axis_index``) and the zero-outside-domain mask uses GLOBAL
    coordinates — so points in exchanged shard ghosts update through the
    fused sub-steps while true physical boundaries stay zero. ``program``
    must then be the per-shard plan built with ``global_sizes`` (its
    ``global_last`` drives last_domain_index conditions).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    ana = program.ana
    dims = ana.domain_dims
    K = fuse_steps
    lead = dims[:-1]
    minor = dims[-1]

    # Per-stage, per-leading-dim read radius: within one fused sub-step a
    # stage consumes its radius of tile margin (same-step chains eat
    # margin stage by stage — the trapezoid accounting of the reference's
    # temporal blocking, setup.cpp:863).
    nstages = len(ana.stages)
    stage_r: List[Dict[str, int]] = []
    for si in range(nstages):
        sr = {d: 0 for d in lead}
        for vname, widths in program.stage_reads[si].items():
            for d, (l, r) in widths.items():
                if d in sr:
                    sr[d] = max(sr[d], l, r)
        stage_r.append(sr)
    # full-step shrink per dim = sum over stages; fused halo = K x that
    # (fused_step_radius is the single source both here and in the
    # runtime's pad planning)
    rad_all = ana.fused_step_radius()
    rad = {d: rad_all.get(d, 0) for d in lead}
    hK = {d: rad[d] * K for d in lead}

    sizes = {d: program.sizes[d] for d in dims}

    # Every var's leading-dim pads must cover the fused halo, or the DMA
    # start/end would clamp silently and corrupt results: the runtime
    # plans extra_pad = radius*K at prepare time, so a K larger than
    # planned must be rejected here (the auto-tuner relies on this to
    # skip infeasible candidates).
    for n, g in program.geoms.items():
        for d in lead:
            if d not in g.domain_dims:
                continue  # partial-dim var lacks this axis
            pl_, pr_ = g.pads[d]
            if pl_ < hK[d] or pr_ < hK[d]:
                raise YaskException(
                    f"pallas fuse_steps={K} needs pad >= {hK[d]} in dim "
                    f"'{d}' but var '{n}' has ({pl_},{pr_}); re-prepare "
                    "with wf_steps set to the desired fusion depth")

    # default block: from the tile planner (fold hints → VREG mapping)
    if block is None:
        from yask_tpu.ops.tile_planner import plan_blocks
        block = plan_blocks(program, fuse_steps=K, vmem_budget=vmem_budget)
    else:
        block = {d: min(b, sizes[d]) for d, b in zip(lead, block)}
    for d in lead:
        if sizes[d] % block[d] != 0:
            # shrink to a divisor
            b = block[d]
            while sizes[d] % b != 0:
                b -= 1
            block[d] = b

    var_order = [n for n in sorted(program.geoms)
                 if not program.geoms[n].is_scratch]
    written = [n for n in var_order if program.geoms[n].is_written]
    scratch_vars = [n for n in sorted(program.geoms)
                    if program.geoms[n].is_scratch]

    # tile geometry per var (its own axes): leading dims it has are sized
    # block+2hK; the minor dim (if present) is its full padded extent;
    # misc axes ride whole
    def tile_shape(name):
        g = program.geoms[name]
        shp = []
        for i, (dn, kind) in enumerate(g.axes):
            if kind == "misc":
                shp.append(g.shape[i])
            elif dn == minor:
                pl_, pr_ = g.pads[minor]
                shp.append(sizes[minor] + pl_ + pr_)
            else:
                shp.append(block[dn] + 2 * hK[dn])
        return tuple(shp) if shp else (1,)  # 0-dim vars ride as (1,)

    dtype = program.dtype
    esize = jnp.dtype(dtype).itemsize
    in_tile_bytes = 0
    slots: Dict[str, int] = {}
    for n in var_order:
        g = program.geoms[n]
        nslots = len(program_state_slots(program, n))
        slots[n] = nslots
        in_tile_bytes += nslots * int(
            math.prod(tile_shape(n))) * esize
    # workspace for sub-step results (rough: one extra tile per written
    # var) and the in-tile scratch values
    work_bytes = sum(int(math.prod(tile_shape(n))) * esize
                     for n in written)
    work_bytes += sum(int(math.prod(tile_shape(n))) * esize
                      for n in scratch_vars)
    tile_bytes = in_tile_bytes + work_bytes
    if tile_bytes > vmem_budget:
        raise YaskException(
            f"pallas tile needs {tile_bytes/2**20:.1f} MiB VMEM "
            f"(budget {vmem_budget/2**20:.0f}); shrink block or fuse_steps")

    grid = tuple(sizes[d] // block[d] for d in lead)
    total_steps = int(math.prod(grid)) if grid else 1

    # Double-buffer the input-tile DMAs across grid steps: while step i
    # computes on buffer i%2, step i+1's halo tiles stream into the other
    # buffer (reference prefetch/early-load machinery, Cpp.hpp:263-287).
    # Costs 2x input-tile VMEM; auto-disabled when that busts the budget
    # or there's only one grid step. Grid dims are declared "arbitrary"
    # (sequential) so the linear-index prefetch is sound.
    if pipeline_dmas is None:
        pipeline_dmas = (total_steps > 1
                         and 2 * in_tile_bytes + work_bytes <= vmem_budget)
    use_pipe = bool(pipeline_dmas) and total_steps > 1
    if use_pipe:
        tile_bytes = 2 * in_tile_bytes + work_bytes
        if tile_bytes > vmem_budget:   # explicitly-requested pipelining
            raise YaskException(
                f"pallas pipelined tiles need {tile_bytes/2**20:.1f} MiB "
                f"VMEM (budget {vmem_budget/2**20:.0f}); shrink block or "
                "fuse_steps, or disable pipeline_dmas")
    minor_origin = {n: (g.pads[minor][0]
                        if minor in g.domain_dims else 0)
                    for n, g in program.geoms.items()}
    ev = _TileEval(jnp, program, minor, minor_origin)

    dirn = ana.step_dir

    # global-problem extents for the zero-outside-domain mask; in
    # distributed mode the shard's origin arrives as a traced vector
    gdom = {d: program.global_last[d] + 1 for d in dims}
    nscalars = 2 if distributed else 1  # t0 (+offsets)

    n_inputs = sum(slots[n] for n in var_order) + nscalars

    def kernel(*refs):
        # refs: t0 (SMEM), [offsets (SMEM)], inputs (ANY/HBM) ...,
        #       outputs (VMEM blocks), scratch tiles ..., sem
        t0_ref = refs[0]
        off_ref = refs[1] if distributed else None
        ins = refs[nscalars:n_inputs]
        nout = sum(min(K, slots[n]) for n in written)
        outs = refs[n_inputs:n_inputs + nout]
        scratch = refs[n_inputs + nout:-1]
        sem = refs[-1]

        pid = [pl.program_id(i) for i in range(len(lead))]

        # 1) DMA halo tiles HBM → VMEM (double-buffered across grid
        #    steps when use_pipe: compute on buffer li%2 while the next
        #    step's tiles stream into the other buffer).
        def in_dmas(coords, buf):
            """The full set of input-tile copies for grid position
            ``coords`` into buffer ``buf`` (reconstructed identically to
            start and to wait)."""
            out = []
            si = 0
            for n in var_order:
                g = program.geoms[n]
                for s in range(slots[n]):
                    src = ins[si]
                    idxs = []
                    for dn, kind in g.axes:
                        if kind == "misc" or dn == minor:
                            idxs.append(slice(None))  # full extent
                        else:
                            di = lead.index(dn)
                            start = (coords[di] * block[dn]
                                     + g.origin[dn] - hK[dn])
                            idxs.append(
                                pl.ds(start, block[dn] + 2 * hK[dn]))
                    if use_pipe:
                        dst = scratch[si].at[buf]
                        s_at = sem.at[buf, si]
                    else:
                        dst = scratch[si]
                        s_at = sem.at[si]
                    out.append(pltpu.make_async_copy(
                        src.at[tuple(idxs)] if idxs else src, dst, s_at))
                    si += 1
            return out

        if use_pipe:
            li = pid[0]
            for i in range(1, len(lead)):
                li = li * grid[i] + pid[i]
            cur = li % 2

            @pl.when(li == 0)
            def _warmup():
                for dma in in_dmas(pid, 0):
                    dma.start()

            # decompose li+1 into grid coords for the prefetch
            nxt = li + 1
            nxt_coords = []
            rem_ = nxt
            for i in range(len(lead) - 1, -1, -1):
                nxt_coords.append(rem_ % grid[i])
                rem_ = rem_ // grid[i]
            nxt_coords = nxt_coords[::-1]

            @pl.when(nxt < total_steps)
            def _prefetch():
                for dma in in_dmas(nxt_coords, nxt % 2):
                    dma.start()

            for dma in in_dmas(pid, cur):
                dma.wait()
        else:
            cur = None
            for dma in in_dmas(pid, None):
                dma.start()
            for dma in in_dmas(pid, None):
                dma.wait()

        def buf_ref(si):
            return scratch[si].at[cur] if use_pipe else scratch[si]

        # tiles as values
        tiles: Dict[str, List] = {}
        si = 0
        for n in var_order:
            tiles[n] = []
            for s in range(slots[n]):
                tiles[n].append(buf_ref(si)[...])
                si += 1

        # 2) K fused sub-steps; within each, every stage consumes its read
        #    radius of tile margin (trapezoid shrink) and writes a FULL
        #    tile (base.at[region].set) so later stages read it at offsets.
        def region_idxs(name, region, misc=None):
            """Index tuple over the var's own axes: domain axes sliced to
            the region (minor shifted by the var's pad origin), misc axes
            pinned to the LHS misc values (ints — they collapse, so the
            result of base[idxs] is region-shaped)."""
            g = program.geoms[name]
            idxs = []
            for dn, kind in g.axes:
                if kind == "misc":
                    idxs.append((misc or {})[dn] - g.misc_lo[dn])
                elif dn == minor:
                    mo = g.pads[minor][0]
                    idxs.append(slice(mo + region[-1][0],
                                      mo + region[-1][1]))
                else:
                    lo, hi = region[dims.index(dn)]
                    idxs.append(slice(lo, hi))
            return tuple(idxs)

        def tile_update(base, idxs, val):
            # dynamic_update_slice, NOT .at[].set: a full-tile static
            # .at-set lowers to scatter whose empty i32 index array is a
            # captured constant pallas_call rejects. Integer (misc) axes
            # become size-1 update axes.
            from jax import lax
            starts = []
            shape = []
            for s in idxs:
                if isinstance(s, slice):
                    starts.append(s.start)
                    shape.append(s.stop - s.start)
                else:
                    starts.append(s)
                    shape.append(1)
            return lax.dynamic_update_slice(
                base, val.reshape(tuple(shape)), tuple(starts))

        ev.gidx_base = {d: pid[lead.index(d)] * block[d] - hK[d]
                        for d in lead}
        if distributed:
            for di, d in enumerate(dims):
                ev.gidx_base[d] = ev.gidx_base.get(d, 0) + off_ref[di]
        for k in range(K):
            computed: Dict[str, object] = {}
            ev.scratch = {}   # scratch values are per-sub-step
            consumed = {d: rad[d] * k for d in lead}
            ev.t = t0_ref[0] + k * dirn
            for si_stage in range(nstages):
                for d in lead:
                    consumed[d] += stage_r[si_stage][d]
                region = []
                for d in lead:
                    region.append((consumed[d],
                                   block[d] + 2 * hK[d] - consumed[d]))
                # minor: interior-relative (per-var pad origin applied at
                # read/write time); pads stay zero
                region.append((0, sizes[minor]))
                rshape = tuple(hi - lo for lo, hi in region)

                # global-domain mask over the region's leading dims: in
                # distributed mode bounds are the GLOBAL problem, so
                # shard-ghost points keep updating while physical edges
                # stay zero
                mask = None
                for di, d in enumerate(lead):
                    lo, hi = region[di]
                    gidx = (jnp.arange(lo, hi)
                            + pid[di] * block[d] - hK[d])
                    if distributed:
                        gidx = gidx + off_ref[di]
                        bound = gdom[d]
                    else:
                        bound = sizes[d]
                    m = (gidx >= 0) & (gidx < bound)
                    shape = [1] * len(dims)
                    shape[di] = hi - lo
                    m = m.reshape(shape)
                    mask = m if mask is None else mask & m

                memo: Dict = {}
                for part in ana.stages[si_stage].parts:
                    if part.is_scratch:
                        # Scratch eqs evaluate over the stage region
                        # EXPANDED by their write-halo (mirrors
                        # _eval_part's scratch branch; stage_read_widths
                        # already budgeted the margin for the chain) and
                        # persist as full-tile values for offset reads.
                        for eq in part.eqs:
                            name = eq.lhs.var_name()
                            wh = ana.scratch_write_halo.get(name, {})
                            sregion = []
                            for di, d in enumerate(lead):
                                wl, wr = wh.get(d, (0, 0))
                                lo, hi = region[di]
                                sregion.append((lo - wl, hi + wr))
                            wl_m, wr_m = wh.get(minor, (0, 0))
                            sregion.append((-wl_m, sizes[minor] + wr_m))
                            ev.region = sregion
                            smemo: Dict = {}   # region differs: own memo
                            val = ev.eval(eq.rhs, tiles, computed, smemo)
                            val = jnp.asarray(val, dtype=dtype)
                            srshape = tuple(hi - lo for lo, hi in sregion)
                            val = jnp.broadcast_to(val, srshape)
                            base = ev.scratch.get(
                                name, jnp.zeros(tile_shape(name), dtype))
                            sidx = region_idxs(name, sregion,
                                               eq.lhs.misc_vals())
                            if eq.cond is not None:
                                cm = ev.eval(eq.cond, tiles, computed,
                                             smemo)
                                cm = jnp.broadcast_to(cm, srshape)
                                val = jnp.where(cm, val, base[sidx])
                            ev.scratch[name] = tile_update(base, sidx, val)
                        continue

                    ev.region = region
                    for eq in part.eqs:
                        name = eq.lhs.var_name()
                        lmisc = eq.lhs.misc_vals()
                        val = ev.eval(eq.rhs, tiles, computed, memo)
                        val = jnp.asarray(val, dtype=dtype)
                        val = jnp.broadcast_to(val, rshape)
                        base = computed.get(name, tiles[name][0])
                        base_slice = base[region_idxs(name, region, lmisc)]
                        sel = mask
                        if eq.cond is not None:
                            cm = ev.eval(eq.cond, tiles, computed, memo)
                            cm = jnp.broadcast_to(cm, rshape)
                            sel = cm if sel is None else sel & cm
                        if eq.step_cond is not None:
                            sc = ev.eval(eq.step_cond, tiles, computed,
                                         memo)
                            sc = jnp.broadcast_to(sc, rshape)
                            sel = sc if sel is None else sel & sc
                        # unselected points keep the base (evicted-slot /
                        # earlier-write) values — ghosts there are zero,
                        # so the zero-outside-domain invariant holds
                        if sel is not None:
                            val = jnp.where(sel, val, base_slice)
                        computed[name] = tile_update(
                            base, region_idxs(name, region, lmisc), val)

            # rotate rings with the sub-step's outputs
            for name in written:
                ring = tiles[name]
                newest = computed[name]
                if slots[name] >= 2:
                    tiles[name] = ring[1:] + [newest]
                else:
                    tiles[name] = [newest]

        # 3) write back the slots the K sub-steps actually produced (the
        #    newest min(K, alloc)); untouched older slots merely shifted
        #    and are rebuilt host-side from the existing padded inputs.
        #    NOTE: outputs are deliberately NOT aliased onto evicted ring
        #    slots — every tile DMA fetches halo margins from every slot,
        #    so an in-place interior write by one grid step would corrupt
        #    a later step's margin reads on real (aliasing) hardware.
        oi = 0
        for name in written:
            g = program.geoms[name]
            ring = tiles[name]
            nback = min(K, slots[name])
            for s in range(nback):
                src = ring[len(ring) - nback + s]
                idxs = []
                for dn, kind in g.axes:
                    if kind == "misc":
                        idxs.append(slice(None))
                    elif dn == minor:
                        mlo = g.pads[minor][0]
                        idxs.append(slice(mlo, mlo + sizes[minor]))
                    else:
                        idxs.append(slice(hK[dn], hK[dn] + block[dn]))
                outs[oi][...] = src[tuple(idxs)]
                oi += 1

    # ---- pallas_call assembly -------------------------------------------

    def out_geometry(name):
        """(full shape, block shape, index_map) over the var's own axes:
        misc axes ride whole (index 0), lead axes follow the grid."""
        g = program.geoms[name]
        full, blk = [], []
        kinds = []
        for i, (dn, kind) in enumerate(g.axes):
            if kind == "misc":
                full.append(g.shape[i])
                blk.append(g.shape[i])
                kinds.append(None)
            elif dn == minor:
                full.append(sizes[minor])
                blk.append(sizes[minor])
                kinds.append(None)
            else:
                full.append(sizes[dn])
                blk.append(block[dn])
                kinds.append(lead.index(dn))

        def index_map(*pid, _kinds=tuple(kinds)):
            return tuple(0 if k is None else pid[k] for k in _kinds)
        return tuple(full), tuple(blk), index_map

    out_shapes = []
    out_specs = []
    for name in written:
        full, blk, imap = out_geometry(name)
        for _ in range(min(K, slots[name])):
            out_shapes.append(jax.ShapeDtypeStruct(full, dtype))
            out_specs.append(pl.BlockSpec(blk, imap))

    # leading scalars (step index, shard offsets) ride SMEM; arrays HBM
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] * nscalars \
        + [pl.BlockSpec(memory_space=pl.ANY)] * (n_inputs - nscalars)
    scratch_shapes = []
    for n in var_order:
        for _ in range(slots[n]):
            shp = tile_shape(n)
            if use_pipe:
                shp = (2,) + shp
            scratch_shapes.append(pltpu.VMEM(shp, dtype))
    n_arrays = n_inputs - nscalars
    scratch_shapes.append(pltpu.SemaphoreType.DMA(
        (2, n_arrays) if use_pipe else (n_arrays,)))

    kwargs = {}
    if use_pipe and not interpret:
        # sequential grid: the linear-index prefetch requires it (no
        # megacore partitioning of grid dims)
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("arbitrary",) * len(grid))

    call = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        scratch_shapes=scratch_shapes,
        interpret=interpret,
        **kwargs,
    )

    def chunk(state, t0, offsets=None):
        flat = [jnp.asarray(t0, dtype=jnp.int32).reshape(1)]
        if distributed:
            flat.append(jnp.asarray(offsets, dtype=jnp.int32))
        for n in var_order:
            for a in state[n]:
                flat.append(a.reshape(1) if a.ndim == 0 else a)
        outs = call(*flat)
        new_state = dict(state)
        oi = 0
        for name in written:
            g = program.geoms[name]
            pads = [g.pads[dn] if kind == "domain" else (0, 0)
                    for dn, kind in g.axes]
            nback = min(K, slots[name])
            news = []
            for s in range(nback):
                news.append(jnp.pad(outs[oi], pads))
                oi += 1
            # ring after K steps = surviving (already padded) input slots
            # shifted down, plus the newly produced ones
            new_state[name] = list(state[name][nback:]) + news
        return new_state

    return chunk, tile_bytes


def program_state_slots(program, name: str) -> List[int]:
    g = program.geoms[name]
    n = g.num_slots
    return list(range(n))
