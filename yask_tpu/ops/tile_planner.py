"""Tile planner: map stencil geometry onto TPU register/VMEM tiling.

Counterpart of the reference's vector-folding planner
(``src/compiler/lib/Vec.*``): where YASK chooses an N-D SIMD fold (e.g.
4×4 for 16 lanes) to maximize in-register reuse between neighboring
stencil reads, the TPU equivalent chooses which dims ride the VREG
(sublane, lane) axes and what Pallas block shape to use:

* the minor-most dim is the 128-lane axis and stays whole in each tile;
* the next-to-minor dim maps to sublanes — blocks should be multiples of
  the dtype's sublane count (8 for f32, 16 for bf16);
* remaining leading dims get small blocks sized to fit the VMEM budget
  given the fused halo (radius × fuse_steps).

User fold hints (``yc_solution.set_fold_len``, the reference's ``-fold``)
override the defaults per dim; the auto-tuner searches around the plan.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from yask_tpu.backend import get_capability

#: default planning budget for direct/test calls (the runtime passes the
#: platform's own default via ``default_vmem_budget``)
_INTERPRET_PLAN_BUDGET = get_capability("cpu:interpret").plan_budget_bytes()


def sublane_count(dtype) -> int:
    """Sublane fold unit for ``dtype`` (8 for f32, 16 for bf16) — read
    from the backend capability table (single source with VarGeom's
    alignment and the checker's models)."""
    return get_capability().sublane_count(dtype)


def _ceil_to(x: int, m: int) -> int:
    return -(-x // m) * m if m > 1 else x


class TilePlan:
    """Explicit dataflow plan for one fused K-group's tiling.

    THE single margin-math source for the pallas path (TileLoom-style:
    emit the per-tile read/write/carry sets, derive every decision from
    them).  A plan is built per (program, fuse_steps) with the resolved
    per-dim tiling choice — ``"uniform"`` symmetric shrink,
    ``"skew"`` streaming wavefront, or ``"trapezoid"`` two-phase
    upright-trapezoid + diamond fill — and answers:

    * :meth:`margins` — per-dim (left, right) fetch margins of a
      phase-1 tile (what the build's mL/mR and the DMA slabs use);
    * :meth:`min_block` / :meth:`margin_override` — the
      :func:`plan_blocks` hints (skew carry floors, trapezoid band
      floors, engaged-dim margin models);
    * :meth:`write_shift` / :meth:`write_shrink` — how level ``lvl``'s
      output window moves (skew) or shrinks per side (trapezoid);
    * :meth:`diamond` — the fill-pass geometry of a trapezoid dim
      (per-level half-band ``cl``, band width, phase-2 margins);
    * :meth:`halo` — the uniform fused halo radius×K (the overlap
      core/shell split's shrink margin);
    * :meth:`dataflow` — per-sub-step read/write/carry interval sets
      for one tile (the checker's TRAPEZOID proofs and the equivalence
      tests consume these);
    * :meth:`volumes` — (useful, computed, fetched) cell counts per
      K-group for the shared profit gates.

    ``e_sk`` is the per-dim skew extra width (E_sk) map; the builder
    passes :func:`~yask_tpu.ops.pallas_stencil.skew_extra_widths` so
    there is exactly one E_sk definition.
    """

    #: v5e TensorCores per chip exposed to a "parallel" Pallas grid
    #: dim (megacore partitioning).  The trapezoid profit gate credits
    #: compute (not fetch) with this factor; hardware A/B rows
    #: (bench_suite / tpu_session trapezoid_ab) are the arbiter.
    PARALLEL_CORES = 2

    def __init__(self, program, fuse_steps: int,
                 skew_dims=(), trap_dims=(),
                 e_sk: Optional[Dict[str, int]] = None):
        self.program = program
        ana = program.ana
        self.dims = ana.domain_dims
        self.lead = self.dims[:-1]
        self.minor = self.dims[-1]
        self.K = fuse_steps
        rad = ana.fused_step_radius()
        self.rad = {d: rad.get(d, 0) for d in self.lead}
        self.sub_t = sublane_count(program.dtype)
        self.skew_dims = list(skew_dims)
        self.trap_dims = list(trap_dims)
        self.e_sk = dict(e_sk or {})
        self.mode = {d: ("skew" if d in self.skew_dims else
                         "trapezoid" if d in self.trap_dims else
                         "uniform") for d in self.lead}
        # ring depth read back through the chain (skew carry sizing)
        ring_reads = set()
        for sr in program.stage_reads:
            ring_reads.update(sr.keys())
        self.carry_depth = max(
            (g.num_slots for n, g in program.geoms.items()
             if g.is_written and not g.is_scratch and n in ring_reads),
            default=0)

    # -- geometry primitives ------------------------------------------

    def cl(self, d: str, lvl: int) -> int:
        """Trapezoid half-band at time level ``lvl``: the per-side
        write-window shrink (lvl−1)·r rounded UP to the sublane tile
        when ``d`` is the written vars' sublane axis (output DMA
        offsets must stay 8-aligned), exact otherwise."""
        unit = self.sub_t if (self.lead and d == self.lead[-1]) else 1
        return _ceil_to((lvl - 1) * self.rad[d], unit)

    def halo(self, d: str) -> int:
        """Uniform fused halo radius×K — the single definition the
        overlap core/shell split and the uniform margins share."""
        return self.rad[d] * self.K

    def margins(self) -> Tuple[Dict[str, int], Dict[str, int]]:
        """Phase-1 per-dim (mL, mR) fetch margins."""
        mL, mR = {}, {}
        for d in self.lead:
            if self.mode[d] == "skew":
                mL[d] = self.halo(d)
                mR[d] = self.rad[d] + self.e_sk.get(d, 0)
            elif self.mode[d] == "trapezoid":
                # upright trapezoids read one step radius per side; the
                # per-level shrink happens in the write windows
                mL[d] = mR[d] = self.rad[d]
            else:
                mL[d] = mR[d] = self.halo(d)
        return mL, mR

    def write_shift(self, d: str, lvl: int) -> int:
        """Skew: level ``lvl``'s write window slides left by this."""
        return (lvl - 1) * self.rad[d] if self.mode[d] == "skew" else 0

    def write_shrink(self, d: str, lvl: int) -> int:
        """Trapezoid: level ``lvl``'s write window shrinks per side by
        (lvl−1)·r, rounded DOWN to the sublane tile on the sublane axis
        (the sub-tile smear lands inside the diamond band and is
        re-filled by the fill pass)."""
        if self.mode[d] != "trapezoid":
            return 0
        fl = (lvl - 1) * self.rad[d]
        unit = self.sub_t if (self.lead and d == self.lead[-1]) else 1
        return (fl // unit) * unit

    def diamond(self, d: str) -> Dict[str, int]:
        """Fill-pass geometry of trapezoid dim ``d``: inverted
        trapezoids centered on every phase-1 tile boundary recompute
        the inter-tile gap bands from level-0 state.  ``half`` =
        cl(K) (the widest band's half-width), ``band`` = 2·half (the
        output band extent), ``margin`` = K·r per side (uniform
        telescoping from level 0)."""
        half = self.cl(d, self.K)
        return {"half": half, "band": 2 * half,
                "margin": self.halo(d)}

    # -- planner hints -------------------------------------------------

    def min_block(self) -> Optional[Dict[str, int]]:
        """Per-dim block floors: skew carries save (ring+1)·r-wide
        strips from the tile's own valid span; trapezoid tiles should
        at least cover their own diamond band (smaller blocks stay
        correct — bands of adjacent boundaries then overlap and the
        fill pass recomputes the same cells — but forfeit the phase-1
        win the gate modeled)."""
        out = {}
        for d in self.skew_dims:
            if self.carry_depth:
                out[d] = (self.carry_depth + 1) * self.rad[d]
        for d in self.trap_dims:
            unit = self.sub_t if (self.lead and d == self.lead[-1]) else 1
            out[d] = 2 * self.cl(d, self.K) + unit
        return out or None

    def margin_override(self) -> Optional[Dict[str, int]]:
        """Per-dim TOTAL modeled tile margin for :func:`plan_blocks`
        where the engaged tiling fetches less than the uniform 2·K·r."""
        out = {}
        for d in self.skew_dims:
            out[d] = (self.K + 1) * self.rad[d] + self.e_sk.get(d, 0)
        for d in self.trap_dims:
            out[d] = 2 * self.rad[d]
        return out or None

    # -- dataflow ------------------------------------------------------

    def dataflow(self, block: Dict[str, int]) -> List[Dict]:
        """Per-sub-step interval sets of one interior tile, in
        tile-origin-relative coordinates (tile spans
        ``[0, mL + block + mR)`` per dim).  Each entry: ``{"level",
        "read": {d: (lo, hi)}, "write": {d: (lo, hi)}, "carry":
        {d: width}}``.  The write interval is the level's output DMA
        window (shrunken/shifted per the dim's mode); the read
        interval is the region the sub-step consumes.  The checker's
        TRAPEZOID rules prove residency/alignment against these, and
        the equivalence tests assert nesting (every read ⊆ the
        previous level's write ∪ margins)."""
        mL, mR = self.margins()
        steps = []
        for k in range(self.K):
            lvl = k + 1
            entry = {"level": lvl, "read": {}, "write": {}, "carry": {}}
            for d in self.lead:
                B, r = block[d], self.rad[d]
                if self.mode[d] == "skew":
                    lo = mL[d] - lvl * r
                    hi = lo + B + 2 * r + self.e_sk.get(d, 0)
                    wlo = mL[d] - self.write_shift(d, lvl)
                    entry["carry"][d] = (self.carry_depth + 1) * r
                elif self.mode[d] == "trapezoid":
                    lo = mL[d] + (lvl - 1) * r - r
                    hi = mL[d] + B - (lvl - 1) * r + r
                    wlo = mL[d] + self.write_shrink(d, lvl)
                else:
                    lo = mL[d] - (self.K - lvl) * r - r
                    lo = max(lo, 0)
                    hi = mL[d] + B + (self.K - lvl) * r + r
                    hi = min(hi, mL[d] + B + mR[d])
                    wlo = mL[d] - (self.K - lvl) * r
                entry["read"][d] = (lo, hi)
                if self.mode[d] == "trapezoid":
                    entry["write"][d] = (wlo,
                                         mL[d] + B
                                         - self.write_shrink(d, lvl))
                elif self.mode[d] == "skew":
                    entry["write"][d] = (wlo, wlo + B)
                else:
                    entry["write"][d] = (wlo, mL[d] + B
                                         + (self.K - lvl) * r)
            steps.append(entry)
        return steps

    # -- multi-stage dataflow (cross-solution pipeline fusion) ---------

    def stage_widths(self) -> List[Dict[str, int]]:
        """Per ANALYSIS stage, per lead dim: the max one-side ghost
        width that stage's reads consume — the per-stage slices of the
        fused radius, straight off ``program.stage_reads`` (the same
        ``stage_read_widths`` definition every other margin consumer
        uses).  Invariant: the per-dim sum over stages equals
        ``self.rad`` (``fused_step_radius``) — a merged
        producer→consumer chain's inter-stage halo margins are exactly
        these widths, one slice per stage."""
        out = []
        for reads in self.program.stage_reads:
            w = {d: 0 for d in self.lead}
            for vv in reads.values():
                for d, (l, r) in vv.items():
                    if d in w:
                        w[d] = max(w[d], l, r)
            out.append(w)
        return out

    def stage_flow(self, block: Dict[str, int]) -> List[Dict]:
        """Per sub-step level, per analysis stage: the stage's write
        and read intervals of one tile (tile-origin-relative, lead
        dims).  The FINAL stage writes the level's output window
        (:meth:`dataflow`'s ``write``); each upstream stage's window is
        expanded per side by the downstream tail (the sum of later
        stages' :meth:`stage_widths`) — consumer stages evaluate
        in-tile over write-halo-expanded producer windows, the
        scratch-var chain rule generalized to whole fused solutions.
        Nesting invariant: stage ``si``'s read interval equals stage
        ``si−1``'s write interval (each stage produces exactly what
        the next consumes)."""
        sw = self.stage_widths()
        tails: List[Dict[str, int]] = []
        acc = {d: 0 for d in self.lead}
        for w in reversed(sw):
            tails.append(dict(acc))
            acc = {d: acc[d] + w[d] for d in self.lead}
        tails.reverse()
        flow = []
        for entry in self.dataflow(block):
            stages = []
            for si, w in enumerate(sw):
                wr, rd = {}, {}
                for d in self.lead:
                    lo, hi = entry["write"][d]
                    t = tails[si][d]
                    wr[d] = (lo - t, hi + t)
                    rd[d] = (lo - t - w[d], hi + t + w[d])
                stages.append({"stage": si, "write": wr, "read": rd})
            flow.append({"level": entry["level"], "stages": stages})
        return flow

    # -- cost model ----------------------------------------------------

    def volumes(self, block: Dict[str, int]) -> Tuple[int, int, int]:
        """(useful, computed, fetched) cells per tile per K-group,
        diamond-pass overhead included, compute credited with the
        parallel-grid factor where every grid dim is independent.
        Feeds the shared profit gates and ``margin_overhead``."""
        mL, mR = self.margins()
        useful = computed = 0
        fetched = 1
        for d in self.lead:
            fetched *= block[d] + mL[d] + mR[d]
        for k in range(self.K):
            lvl = k + 1
            u = c = 1
            for d in self.lead:
                B, r = block[d], self.rad[d]
                u *= B
                if self.mode[d] == "skew":
                    c *= B + 2 * r + self.e_sk.get(d, 0)
                elif self.mode[d] == "trapezoid":
                    c *= B - 2 * (lvl - 1) * r + 2 * r
                else:
                    c *= B + 2 * (self.K - lvl) * r
            useful += u
            computed += c
        # diamond fill pass: per trapezoid dim, one inverted trapezoid
        # per tile boundary recomputes ~(2·cl(K) + 2·K·r) width across
        # the other dims' blocks, K levels deep
        for d in self.trap_dims:
            dia = self.diamond(d)
            w = dia["band"] + 2 * dia["margin"]
            other = 1
            for d2 in self.lead:
                if d2 != d:
                    other *= block[d2]
            computed += self.K * w * other
            fetched += w * other
        return useful, computed, fetched


def plan_blocks(program, fuse_steps: int = 1,
                vmem_budget: int = _INTERPRET_PLAN_BUDGET,
                vinstr_cap: int = 300_000,
                min_block: Optional[Dict[str, int]] = None,
                margin_override: Optional[Dict[str, int]] = None
                ) -> Dict[str, int]:
    """Choose leading-dim block sizes for the Pallas path.

    ``vinstr_cap`` bounds the estimated Mosaic vector-instruction count
    of one fused kernel (``num_ops × fuse_steps × VREGs/tile``): block
    growth stops at the cap so op-heavy kernels (ssg, awp, tti) cannot
    reach tile sizes whose Mosaic schedule blows up compile time
    (>15 min observed mid-r3 on ssg-K2).  0 disables the cap.

    ``margin_override`` replaces the default uniform ``2·r·K`` TOTAL
    tile margin per dim in the VMEM/overhead/vinstr models — the build
    passes each skewed dim's ``(K+1)·r + E_sk`` so the planner does not
    leave budget on the table modeling margins the skew never fetches
    (at 512³ r=8 K=2 this is the difference between 8-wide and 16-wide
    x blocks; with both dims skewed the margin shrinks in x AND y).

    ``min_block`` floors (the skew carry needs blocks ≥ (ring+1)·r in
    every skewed dim) are applied AFTER the initial divisor snap and
    themselves snap UP to the next divisor, so a non-divisor carry
    floor still yields a block ≥ the floor (never silently below it).
    """
    ana = program.ana
    dims = ana.domain_dims
    lead = dims[:-1]
    minor = dims[-1]
    sizes = {d: program.sizes[d] for d in dims}
    rad = ana.fused_step_radius()
    hK = {d: rad.get(d, 0) * fuse_steps for d in lead}
    # TOTAL extra tile width per dim in the models below (both-side
    # margins); the skewed stream dim fetches less than 2*hK
    marg = {d: 2 * hK[d] for d in lead}
    for d, m in (margin_override or {}).items():
        if d in marg:
            marg[d] = m
    cap = get_capability()
    sub = cap.sublane_count(program.dtype)

    fold = program.soln.get_settings().fold

    # initial guess: fold hints, else sublane multiple for next-to-minor,
    # small for outers
    block: Dict[str, int] = {}
    for i, d in enumerate(lead):
        if fold.has_dim(d) and fold[d] > 0:
            block[d] = min(fold[d], sizes[d])
        elif i == len(lead) - 1:
            block[d] = min(sub, sizes[d])
        else:
            block[d] = min(8, sizes[d])

    # fit to divisors
    for d in lead:
        b = block[d]
        while sizes[d] % b != 0:
            b -= 1
        block[d] = max(b, 1)


    # estimate VMEM need and grow blocks while they fit (bigger tiles
    # amortize halo overlap)
    import numpy as np
    esize = np.dtype(program.dtype).itemsize
    nbuf = 0
    minor_ext = 1
    for n, g in program.geoms.items():
        slots = g.num_slots
        # misc axes ride whole in every tile: they multiply the buffer
        # count, or the VMEM estimate undershoots (box/gaussian channel
        # dims) and the kernel's exact accounting rejects the plan
        misc_ext = 1
        for i, (dn, kind) in enumerate(g.axes):
            if kind == "misc":
                misc_ext *= g.shape[i]
        nbuf += (slots + (1 if g.is_written else 0)) * misc_ext
        if minor in g.domain_dims:
            pl_, pr_ = g.pads[minor]
            minor_ext = max(minor_ext, sizes[minor] + pl_ + pr_)

    # Mosaic keeps each fused sub-step's intermediate values live across
    # the K-step chain, and spills what the scoped VMEM limit cannot
    # hold (observed on v5e: a candidate whose *tiles* fit the budget
    # died in compile with 140 MiB of "register allocator spill slots").
    # Model that pressure as ~1 extra live tile per written var per
    # fused sub-step beyond the first, so the planner starts from
    # blocks a deep fusion can actually compile; the auto-tuner still
    # explores outward and the build's exact accounting (plus its
    # compile-failure infeasibility marking) remains the arbiter.
    nlive = 0
    for g in program.geoms.values():
        if not g.is_written or g.is_scratch:
            continue
        misc_ext = 1
        for i, (dn, kind) in enumerate(g.axes):
            if kind == "misc":
                misc_ext *= g.shape[i]
        nlive += misc_ext * max(fuse_steps - 1, 0)

    def tile_bytes(blk):
        per = 1
        for d in lead:
            per *= blk[d] + marg[d]
        return per * minor_ext * esize * max(nbuf + nlive, 1)

    num_ops = getattr(getattr(ana, "counters", None), "num_ops", 0)

    def vinstr(blk):
        """Estimated Mosaic vector instructions for one fused kernel:
        each scalar op per point becomes one vector op per VREG of the
        tile, repeated for every fused sub-step."""
        per = 1
        for d in lead:
            per *= blk[d] + marg[d]
        vregs = per * minor_ext / cap.tile_cells(program.dtype)
        return num_ops * fuse_steps * vregs

    # per-dim floors (the skew carry needs stream blocks ≥ (ring+1)·r —
    # without this the default plan silently forfeits the skewed
    # tiling).  The floor must not bypass the vinstr compile-time
    # guard: if the floored plan busts the cap, leave the dim alone and
    # let the build fall back to the uniform tiling.
    for d, mn in (min_block or {}).items():
        if d in block and block[d] < mn:
            b = min(mn, sizes[d])
            while sizes[d] % b != 0 and b < sizes[d]:
                b += 1
            cand = dict(block)
            cand[d] = b
            if not (vinstr_cap and num_ops
                    and vinstr(cand) > vinstr_cap):
                block[d] = b

    def overhead(blk):
        """Read-reuse model: fraction of each tile's loads + compute that
        is halo overlap recomputed by neighboring tiles — the quantity
        the reference's fold planner minimizes as 'reads per point'
        (``Vec.*``). Growing the dim with the worst surface/volume ratio
        first buys the most reuse per VMEM byte."""
        interior = 1
        padded = 1
        for d in lead:
            interior *= blk[d]
            padded *= blk[d] + marg[d]
        return (padded - interior) / max(interior, 1)

    improved = True
    while improved:
        improved = False
        best = None
        for d in lead:
            nb = block[d] * 2
            while nb <= sizes[d] and sizes[d] % nb != 0:
                nb *= 2
            if nb > sizes[d]:
                continue
            cand = dict(block)
            cand[d] = nb
            if tile_bytes(cand) >= vmem_budget // 2:
                continue
            if vinstr_cap and num_ops and vinstr(cand) > vinstr_cap:
                continue
            ov = overhead(cand)
            if best is None or ov < best[0]:
                best = (ov, cand)
        # doubling can only reduce (or, for zero-halo dims, preserve)
        # the overhead, and either way shrinks the grid — take the best
        # fitting candidate until nothing fits the VMEM target
        if best is not None:
            block = best[1]
            improved = True
    return block
