"""Tile planner: map stencil geometry onto TPU register/VMEM tiling.

Counterpart of the reference's vector-folding planner
(``src/compiler/lib/Vec.*``): where YASK chooses an N-D SIMD fold (e.g.
4×4 for 16 lanes) to maximize in-register reuse between neighboring
stencil reads, the TPU equivalent chooses which dims ride the VREG
(sublane, lane) axes and what Pallas block shape to use:

* the minor-most dim is the 128-lane axis and stays whole in each tile;
* the next-to-minor dim maps to sublanes — blocks should be multiples of
  the dtype's sublane count (8 for f32, 16 for bf16);
* remaining leading dims get small blocks sized to fit the VMEM budget
  given the fused halo (radius × fuse_steps).

User fold hints (``yc_solution.set_fold_len``, the reference's ``-fold``)
override the defaults per dim; the auto-tuner searches around the plan.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple


def sublane_count(dtype) -> int:
    import numpy as np
    size = np.dtype(dtype).itemsize
    return {4: 8, 2: 16, 1: 32}.get(size, 8)


def plan_blocks(program, fuse_steps: int = 1,
                vmem_budget: int = 100 * 2 ** 20,
                vinstr_cap: int = 300_000,
                min_block: Optional[Dict[str, int]] = None,
                margin_override: Optional[Dict[str, int]] = None
                ) -> Dict[str, int]:
    """Choose leading-dim block sizes for the Pallas path.

    ``vinstr_cap`` bounds the estimated Mosaic vector-instruction count
    of one fused kernel (``num_ops × fuse_steps × VREGs/tile``): block
    growth stops at the cap so op-heavy kernels (ssg, awp, tti) cannot
    reach tile sizes whose Mosaic schedule blows up compile time
    (>15 min observed mid-r3 on ssg-K2).  0 disables the cap.

    ``margin_override`` replaces the default uniform ``2·r·K`` TOTAL
    tile margin per dim in the VMEM/overhead/vinstr models — the build
    passes each skewed dim's ``(K+1)·r + E_sk`` so the planner does not
    leave budget on the table modeling margins the skew never fetches
    (at 512³ r=8 K=2 this is the difference between 8-wide and 16-wide
    x blocks; with both dims skewed the margin shrinks in x AND y).

    ``min_block`` floors (the skew carry needs blocks ≥ (ring+1)·r in
    every skewed dim) are applied AFTER the initial divisor snap and
    themselves snap UP to the next divisor, so a non-divisor carry
    floor still yields a block ≥ the floor (never silently below it).
    """
    ana = program.ana
    dims = ana.domain_dims
    lead = dims[:-1]
    minor = dims[-1]
    sizes = {d: program.sizes[d] for d in dims}
    rad = ana.fused_step_radius()
    hK = {d: rad.get(d, 0) * fuse_steps for d in lead}
    # TOTAL extra tile width per dim in the models below (both-side
    # margins); the skewed stream dim fetches less than 2*hK
    marg = {d: 2 * hK[d] for d in lead}
    for d, m in (margin_override or {}).items():
        if d in marg:
            marg[d] = m
    sub = sublane_count(program.dtype)

    fold = program.soln.get_settings().fold

    # initial guess: fold hints, else sublane multiple for next-to-minor,
    # small for outers
    block: Dict[str, int] = {}
    for i, d in enumerate(lead):
        if fold.has_dim(d) and fold[d] > 0:
            block[d] = min(fold[d], sizes[d])
        elif i == len(lead) - 1:
            block[d] = min(max(sub, 8), sizes[d])
        else:
            block[d] = min(8, sizes[d])

    # fit to divisors
    for d in lead:
        b = block[d]
        while sizes[d] % b != 0:
            b -= 1
        block[d] = max(b, 1)


    # estimate VMEM need and grow blocks while they fit (bigger tiles
    # amortize halo overlap)
    import numpy as np
    esize = np.dtype(program.dtype).itemsize
    nbuf = 0
    minor_ext = 1
    for n, g in program.geoms.items():
        slots = g.num_slots
        # misc axes ride whole in every tile: they multiply the buffer
        # count, or the VMEM estimate undershoots (box/gaussian channel
        # dims) and the kernel's exact accounting rejects the plan
        misc_ext = 1
        for i, (dn, kind) in enumerate(g.axes):
            if kind == "misc":
                misc_ext *= g.shape[i]
        nbuf += (slots + (1 if g.is_written else 0)) * misc_ext
        if minor in g.domain_dims:
            pl_, pr_ = g.pads[minor]
            minor_ext = max(minor_ext, sizes[minor] + pl_ + pr_)

    # Mosaic keeps each fused sub-step's intermediate values live across
    # the K-step chain, and spills what the scoped VMEM limit cannot
    # hold (observed on v5e: a candidate whose *tiles* fit the budget
    # died in compile with 140 MiB of "register allocator spill slots").
    # Model that pressure as ~1 extra live tile per written var per
    # fused sub-step beyond the first, so the planner starts from
    # blocks a deep fusion can actually compile; the auto-tuner still
    # explores outward and the build's exact accounting (plus its
    # compile-failure infeasibility marking) remains the arbiter.
    nlive = 0
    for g in program.geoms.values():
        if not g.is_written or g.is_scratch:
            continue
        misc_ext = 1
        for i, (dn, kind) in enumerate(g.axes):
            if kind == "misc":
                misc_ext *= g.shape[i]
        nlive += misc_ext * max(fuse_steps - 1, 0)

    def tile_bytes(blk):
        per = 1
        for d in lead:
            per *= blk[d] + marg[d]
        return per * minor_ext * esize * max(nbuf + nlive, 1)

    num_ops = getattr(getattr(ana, "counters", None), "num_ops", 0)

    def vinstr(blk):
        """Estimated Mosaic vector instructions for one fused kernel:
        each scalar op per point becomes one vector op per VREG of the
        tile, repeated for every fused sub-step."""
        per = 1
        for d in lead:
            per *= blk[d] + marg[d]
        vregs = per * minor_ext / (sub * 128)
        return num_ops * fuse_steps * vregs

    # per-dim floors (the skew carry needs stream blocks ≥ (ring+1)·r —
    # without this the default plan silently forfeits the skewed
    # tiling).  The floor must not bypass the vinstr compile-time
    # guard: if the floored plan busts the cap, leave the dim alone and
    # let the build fall back to the uniform tiling.
    for d, mn in (min_block or {}).items():
        if d in block and block[d] < mn:
            b = min(mn, sizes[d])
            while sizes[d] % b != 0 and b < sizes[d]:
                b += 1
            cand = dict(block)
            cand[d] = b
            if not (vinstr_cap and num_ops
                    and vinstr(cand) > vinstr_cap):
                block[d] = b

    def overhead(blk):
        """Read-reuse model: fraction of each tile's loads + compute that
        is halo overlap recomputed by neighboring tiles — the quantity
        the reference's fold planner minimizes as 'reads per point'
        (``Vec.*``). Growing the dim with the worst surface/volume ratio
        first buys the most reuse per VMEM byte."""
        interior = 1
        padded = 1
        for d in lead:
            interior *= blk[d]
            padded *= blk[d] + marg[d]
        return (padded - interior) / max(interior, 1)

    improved = True
    while improved:
        improved = False
        best = None
        for d in lead:
            nb = block[d] * 2
            while nb <= sizes[d] and sizes[d] % nb != 0:
                nb *= 2
            if nb > sizes[d]:
                continue
            cand = dict(block)
            cand[d] = nb
            if tile_bytes(cand) >= vmem_budget // 2:
                continue
            if vinstr_cap and num_ops and vinstr(cand) > vinstr_cap:
                continue
            ov = overhead(cand)
            if best is None or ov < best[0]:
                best = (ov, cand)
        # doubling can only reduce (or, for zero-halo dims, preserve)
        # the overhead, and either way shrinks the grid — take the best
        # fitting candidate until nothing fits the VMEM target
        if best is not None:
            block = best[1]
            improved = True
    return block
