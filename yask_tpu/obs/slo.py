"""SLO burn-rate monitor over the serving plane.

Classic multi-window burn-rate alerting (SRE workbook shape) over four
request-level SLIs, fed per-release by the scheduler's registry hook:

- ``latency``    — request total latency vs ``YT_SLO_P99_MS`` (an event
                   is *bad* when it exceeds the objective; with the
                   default 1% budget this is exactly a p99 objective).
- ``error_rate`` — error + anomaly/quarantine releases.
- ``preemption`` — preempted streaming requests.
- ``occupancy``  — batch occupancy below ``YT_SLO_MIN_OCCUPANCY``.

For each SLI the monitor keeps a rolling event window and computes, for
every evaluation window W (default 5m and 1h), the burn rate
``bad_fraction(W) / budget``.  A breach fires only when EVERY window
burns above ``YT_SLO_BURN`` — the short window gives fast detection,
the long window suppresses blips.  Breaches are returned as
schema-versioned dicts (``yask_tpu.slo/1``) carrying the worst
offender's trace id; the caller journals them as ``slo_breach`` rows.

LOG-ONLY by definition (same policy as preflight): the monitor never
blocks, degrades, or rejects anything — it observes, journals, and
surfaces.  It is OFF unless at least one ``YT_SLO_*`` knob is set, so
an unconfigured build has zero overhead and bit-identical artifacts.

Knobs (all env, all optional):
  YT_SLO_P99_MS            latency objective in ms (SLI off when unset)
  YT_SLO_LATENCY_BUDGET    allowed bad fraction (default 0.01)
  YT_SLO_ERROR_BUDGET      allowed error+quarantine fraction (0.01)
  YT_SLO_PREEMPT_BUDGET    allowed preemption fraction (0.05)
  YT_SLO_MIN_OCCUPANCY     occupancy objective (SLI off when unset)
  YT_SLO_OCCUPANCY_BUDGET  allowed low-occupancy fraction (0.25)
  YT_SLO_WINDOWS           comma-joined window secs (default "300,3600")
  YT_SLO_BURN              burn-rate threshold (default 1.0)
  YT_SLO_COOLDOWN          min secs between breaches per SLI (60)
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

SLO_SCHEMA = "yask_tpu.slo/1"

_KNOB_PREFIX = "YT_SLO_"


def slo_enabled(env=None) -> bool:
    env = os.environ if env is None else env
    return any(k.startswith(_KNOB_PREFIX) for k in env)


def _fenv(env, key: str, default: Optional[float]) -> Optional[float]:
    raw = env.get(key)
    if raw is None or str(raw).strip() == "":
        return default
    try:
        return float(raw)
    except (TypeError, ValueError):
        return default


class SloMonitor:
    """Rolling multi-window burn-rate evaluation; see module doc."""

    def __init__(self,
                 windows: Tuple[float, ...] = (300.0, 3600.0),
                 burn_threshold: float = 1.0,
                 cooldown_secs: float = 60.0,
                 p99_ms: Optional[float] = None,
                 latency_budget: float = 0.01,
                 error_budget: float = 0.01,
                 preempt_budget: float = 0.05,
                 min_occupancy: Optional[float] = None,
                 occupancy_budget: float = 0.25,
                 clock=time.time):
        self.windows = tuple(sorted(float(w) for w in windows))
        self.burn_threshold = float(burn_threshold)
        self.cooldown_secs = float(cooldown_secs)
        self.p99_ms = p99_ms
        self.latency_budget = float(latency_budget)
        self.error_budget = float(error_budget)
        self.preempt_budget = float(preempt_budget)
        self.min_occupancy = min_occupancy
        self.occupancy_budget = float(occupancy_budget)
        self._clock = clock
        # event: (ts, {sli: bad}, trace)
        self._events: Deque[Tuple[float, Dict[str, bool],
                                  Optional[str]]] = deque(maxlen=65536)
        self._last_bad_trace: Dict[str, Optional[str]] = {}
        self._last_breach_ts: Dict[str, float] = {}
        self._breach_count = 0
        self._lock = threading.Lock()

    @classmethod
    def from_env(cls, env=None) -> Optional["SloMonitor"]:
        """Build from ``YT_SLO_*`` knobs; ``None`` when none are set
        (the monitor must cost nothing unless asked for)."""
        env = os.environ if env is None else env
        if not slo_enabled(env):
            return None
        raw = str(env.get("YT_SLO_WINDOWS", "") or "300,3600")
        try:
            windows = tuple(float(w) for w in raw.split(",") if w.strip())
        except ValueError:
            windows = (300.0, 3600.0)
        return cls(
            windows=windows or (300.0, 3600.0),
            burn_threshold=_fenv(env, "YT_SLO_BURN", 1.0),
            cooldown_secs=_fenv(env, "YT_SLO_COOLDOWN", 60.0),
            p99_ms=_fenv(env, "YT_SLO_P99_MS", None),
            latency_budget=_fenv(env, "YT_SLO_LATENCY_BUDGET", 0.01),
            error_budget=_fenv(env, "YT_SLO_ERROR_BUDGET", 0.01),
            preempt_budget=_fenv(env, "YT_SLO_PREEMPT_BUDGET", 0.05),
            min_occupancy=_fenv(env, "YT_SLO_MIN_OCCUPANCY", None),
            occupancy_budget=_fenv(env, "YT_SLO_OCCUPANCY_BUDGET", 0.25))

    def _budgets(self) -> Dict[str, float]:
        out = {"error_rate": self.error_budget,
               "preemption": self.preempt_budget}
        if self.p99_ms is not None:
            out["latency"] = self.latency_budget
        if self.min_occupancy is not None:
            out["occupancy"] = self.occupancy_budget
        return out

    def record(self, *,
               ok: bool = True,
               quarantined: bool = False,
               preempted: bool = False,
               total_ms: Optional[float] = None,
               occupancy: Optional[float] = None,
               trace: Optional[str] = None,
               ts: Optional[float] = None) -> None:
        """Feed one released request (the scheduler's registry hook)."""
        ts = self._clock() if ts is None else float(ts)
        bad = {"error_rate": bool(quarantined or not ok),
               "preemption": bool(preempted)}
        if self.p99_ms is not None and total_ms is not None:
            bad["latency"] = float(total_ms) > self.p99_ms
        if self.min_occupancy is not None and occupancy is not None:
            bad["occupancy"] = float(occupancy) < self.min_occupancy
        with self._lock:
            self._events.append((ts, bad, trace))
            for sli, b in bad.items():
                if b and trace:
                    self._last_bad_trace[sli] = trace

    def burn_rates(self, now: Optional[float] = None) -> Dict[str, Dict]:
        """Per-SLI, per-window ``{burn, bad, total}`` over the rolling
        event log.  Windows with zero events burn at 0."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            events = list(self._events)
        out: Dict[str, Dict] = {}
        for sli, budget in self._budgets().items():
            per_win = {}
            for w in self.windows:
                cut = now - w
                total = bad = 0
                for ts, flags, _tr in events:
                    if ts < cut or sli not in flags:
                        continue
                    total += 1
                    bad += bool(flags[sli])
                frac = (bad / total) if total else 0.0
                per_win[str(int(w))] = {
                    "burn": (frac / budget) if budget > 0 else 0.0,
                    "bad": bad, "total": total}
            out[sli] = {"budget": budget, "windows": per_win}
        return out

    def evaluate(self, now: Optional[float] = None) -> List[Dict]:
        """Return NEW breaches (past per-SLI cooldown).  A breach
        requires every window to burn above the threshold."""
        now = self._clock() if now is None else float(now)
        rates = self.burn_rates(now)
        breaches: List[Dict] = []
        for sli, r in rates.items():
            wins = r["windows"]
            if not wins:
                continue
            if not all(w["total"] > 0 and
                       w["burn"] >= self.burn_threshold
                       for w in wins.values()):
                continue
            with self._lock:
                last = self._last_breach_ts.get(sli, -1e18)
                if now - last < self.cooldown_secs:
                    continue
                self._last_breach_ts[sli] = now
                self._breach_count += 1
                trace = self._last_bad_trace.get(sli)
            breaches.append({"v": SLO_SCHEMA,
                             "signal": sli,
                             "budget": r["budget"],
                             "threshold": self.burn_threshold,
                             "windows": wins,
                             "trace": trace,
                             "ts": now})
        return breaches

    def summary(self, now: Optional[float] = None) -> Dict:
        """JSON-able state for ``metrics()`` / fleet_stats surfacing."""
        return {"v": SLO_SCHEMA,
                "enabled": True,
                "breaches": self._breach_count,
                "last_breach_ts": dict(self._last_breach_ts),
                "burn": self.burn_rates(now)}
