"""Observability spine: span tracer + metrics registry + exporter
hooks.  See ``docs/observability.md``; terminal/Perfetto rendering
lives in ``tools/obs_report.py``."""

from yask_tpu.obs.tracer import (  # noqa: F401
    PHASES, TRACE_BASENAME, TRACE_SCHEMA, activate, compact_if_large,
    current_span_id, current_trace_id, default_trace_path,
    new_trace_id, phase_for_site, profile_window, read_spans,
    record_span, set_trace, span, stamp_trace, trace_enabled,
    trace_max_bytes,
)
from yask_tpu.obs.metrics import (  # noqa: F401
    REGISTRY, Counter, Gauge, Histogram, Registry, get_registry,
    percentile,
)
