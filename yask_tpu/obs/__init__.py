"""Observability spine: span tracer + metrics registry + the
interpretive layer over them (fleet telemetry merge, SLO burn-rate
monitor, roofline attribution).  See ``docs/observability.md``;
terminal/Perfetto rendering lives in ``tools/obs_report.py`` and
Prometheus exposition in ``tools/obs_export.py``."""

from yask_tpu.obs.tracer import (  # noqa: F401
    PHASES, TRACE_BASENAME, TRACE_SCHEMA, activate, compact_if_large,
    current_span_id, current_trace_id, default_trace_path,
    new_trace_id, phase_for_site, profile_window, read_spans,
    record_span, set_trace, span, stamp_trace, trace_enabled,
    trace_max_bytes,
)
from yask_tpu.obs.metrics import (  # noqa: F401
    REGISTRY, Counter, Gauge, Histogram, Registry, get_registry,
    percentile,
)
from yask_tpu.obs.telemetry import (  # noqa: F401
    TELEMETRY_SCHEMA, merge_snapshots, prom_name, to_prometheus,
)
from yask_tpu.obs.slo import (  # noqa: F401
    SLO_SCHEMA, SloMonitor, slo_enabled,
)
from yask_tpu.obs.attribution import (  # noqa: F401
    ATTRIBUTION_SCHEMA, attribute, attribute_and_bank, join_model,
)
