"""Measured-vs-modeled roofline attribution over the span trace.

This is the join r19 built the trace-id plumbing for: a perf ledger row
and the spans of the run that produced it share a trace id, so the
measured per-phase wall time (span SELF-times — duration minus direct
children, the same attribution ``tools/obs_report.py`` prints) can be
laid against what the ``perflab.roofline`` HBM model says the compute
phase *should* have cost.  The result is banked back into the ledger as
``source: "attribution"`` rows (one per run, per-phase detail in
``extra``) so phase SHARES get the same trailing-median drift guard
perf rates already have (``sentinel.check_attribution``).

The span math (:func:`pick_trace` / :func:`self_times` /
:func:`phase_breakdown` / :func:`halo_cal_status`) lives here and is
re-exported by ``tools/obs_report.py`` — one implementation for the
terminal report, the CSV exporter, and the attribution rows.

Excluded evidence, by design:

* runs whose perf row was QUARANTINED (all-zero / non-finite output —
  wall time of corrupt data attributes nothing),
* halo-cal-unstable traces are banked but flagged
  (``halo_cal_unstable``) and dropped from the ``--attribution`` table,
  matching the ledger's treatment of unstable halo splits.

Schema: ``yask_tpu.attribution/1``.  No jax import.
"""

from __future__ import annotations

from typing import Dict, List, Optional

ATTRIBUTION_SCHEMA = "yask_tpu.attribution/1"
ATTR_KEY_PREFIX = "attribution:"
ROOT_SPAN = "run.supervised"


def pick_trace(rows: List[Dict], trace: str = "") -> List[Dict]:
    """Filter rows to one trace id; default = the LATEST trace (the one
    whose newest span has the greatest wall ts); ``"all"`` keeps every
    row."""
    if trace == "all":
        return list(rows)
    if not trace:
        latest: Dict[str, float] = {}
        for r in rows:
            t = r.get("trace", "")
            latest[t] = max(latest.get(t, 0.0), float(r.get("ts", 0.0)))
        if not latest:
            return []
        trace = max(latest, key=lambda t: latest[t])
    return [r for r in rows if r.get("trace") == trace]


def self_times(rows: List[Dict]) -> Dict[str, float]:
    """span id → duration minus direct children's durations (floored
    at 0 — children on other threads can overlap their parent)."""
    child_dur: Dict[str, float] = {}
    for r in rows:
        p = r.get("parent", "")
        if p:
            child_dur[p] = child_dur.get(p, 0.0) + float(r.get("dur", 0.0))
    return {r["span"]: max(0.0, float(r.get("dur", 0.0))
                           - child_dur.get(r.get("span", ""), 0.0))
            for r in rows if "span" in r}


def phase_breakdown(rows: List[Dict]) -> Dict[str, Dict]:
    """Per-phase ``{secs, count}`` from self-times, with ``halo.share``
    exchange evidence moved out of the compute bucket (it measures a
    slice of a compute span's interval, not a nested child)."""
    selfs = self_times(rows)
    out: Dict[str, Dict] = {}
    halo_share = 0.0
    for r in rows:
        ph = r.get("phase") or "other"
        b = out.setdefault(ph, {"secs": 0.0, "count": 0})
        b["secs"] += selfs.get(r.get("span", ""), 0.0)
        b["count"] += 1
        if r.get("name") == "halo.share":
            halo_share += float(r.get("dur", 0.0))
    if halo_share > 0 and "compute" in out:
        out["compute"]["secs"] = max(
            0.0, out["compute"]["secs"] - halo_share)
        out["compute"]["halo_share_moved"] = halo_share
    return out


def halo_cal_status(rows: List[Dict]) -> Dict:
    """Aggregate the halo-calibration spans: rep/spread evidence plus
    whether any calibration came out UNSTABLE (ledger parity — an
    unstable split is noise, not a halo datum)."""
    cals = [r for r in rows if r.get("name") == "halo_cal"]
    att = [r.get("attrs", {}) for r in cals]
    return {
        "count": len(cals),
        "reps": sum(int(a.get("reps", 0) or 0) for a in att),
        "max_spread": max([float(a.get("spread", 0.0) or 0.0)
                           for a in att] or [0.0]),
        "unstable": sum(1 for a in att if a.get("unstable")),
    }


def attribute(rows: List[Dict], trace: str = "") -> Optional[Dict]:
    """Build the measured side of the attribution report for one trace:
    per-phase self-time seconds + shares, the root-span total they must
    reconcile against, and the halo-cal stability flag.  None when the
    trace has no spans."""
    rows = pick_trace(rows, trace)
    if not rows:
        return None
    tid = rows[0].get("trace", "")
    bk = phase_breakdown(rows)
    total = sum(b["secs"] for b in bk.values())
    root_secs = sum(float(r.get("dur", 0.0)) for r in rows
                    if r.get("name") == ROOT_SPAN)
    hc = halo_cal_status(rows)
    phases = {}
    for ph, b in sorted(bk.items()):
        phases[ph] = {"measured_secs": round(b["secs"], 6),
                      "share": round(b["secs"] / total, 4) if total else 0.0,
                      "count": b["count"]}
    return {"v": ATTRIBUTION_SCHEMA,
            "trace": tid,
            "phases": phases,
            "measured_total_secs": round(total, 6),
            "root_secs": round(root_secs, 6),
            "halo_cal_unstable": hc["unstable"]}


def join_model(report: Dict, roofline: Optional[Dict] = None,
               modeled: Optional[Dict] = None) -> Dict:
    """Attach the modeled side: explicit per-phase modeled seconds
    (``modeled={phase: secs}``) win; otherwise the compute phase is
    modeled from the perf row's roofline fraction (``roofline_frac`` =
    achieved/roofline rate, so the roofline-speed run would have taken
    ``measured × frac`` seconds).  ``efficiency`` = modeled/measured —
    1.0 means running exactly at the model, lower is headroom."""
    from yask_tpu.perflab.roofline import modeled_compute_secs
    frac = (roofline or {}).get("roofline_frac")
    for ph, d in report.get("phases", {}).items():
        m = (modeled or {}).get(ph)
        if m is None and ph == "compute":
            m = modeled_compute_secs(d["measured_secs"], frac)
        if m is None:
            continue
        d["modeled_secs"] = round(float(m), 6)
        if d["measured_secs"] > 0:
            d["efficiency"] = round(float(m) / d["measured_secs"], 4)
    if roofline:
        report["roofline"] = {k: v for k, v in roofline.items()
                              if v is not None}
    return report


def find_perf_row(ledger_rows: List[Dict], trace: str) -> Optional[Dict]:
    """Latest measured perf row stamped with ``trace`` (the r19 join).
    Attribution rows themselves never match.  Quarantined rows DO match
    — the caller must check ``quarantined`` and refuse to attribute
    (corrupt-output wall time attributes nothing)."""
    hit = None
    for r in ledger_rows:
        if r.get("trace_id") != trace:
            continue
        if r.get("source") == "attribution":
            continue
        if hit is None or not r.get("quarantined"):
            hit = r
        if r.get("quarantined"):
            # a quarantined row for this trace poisons the whole run
            return r
    return hit


def bank(report: Dict, *, key: str = ROOT_SPAN,
         platform: str = "cpu",
         provenance: Optional[Dict] = None,
         ledger_path: Optional[str] = None) -> Dict:
    """Append ``report`` to the perf ledger as one ``source:
    "attribution"`` row: value = measured total seconds, per-phase
    detail in ``extra``, share-drift verdict (vs the trailing clean
    median of prior attribution rows for the same key) in ``guard``."""
    from yask_tpu.perflab import ledger as _ledger
    from yask_tpu.perflab import sentinel as _sentinel
    if provenance is None:
        from yask_tpu.perflab.provenance import capture_provenance
        provenance = capture_provenance(platform=platform)
    row_key = ATTR_KEY_PREFIX + key
    history = [r for r in _ledger.read_rows(path=ledger_path, key=row_key,
                                            platform=platform)
               if r.get("source") == "attribution"]
    shares = {ph: d["share"] for ph, d in report["phases"].items()}
    guard = _sentinel.check_attribution(shares, history)
    extra = {"trace": report.get("trace", ""),
             "phases": report["phases"],
             "shares": shares,
             "root_secs": report.get("root_secs", 0.0),
             "halo_cal_unstable": report.get("halo_cal_unstable", 0)}
    row = _ledger.make_row(row_key, report["measured_total_secs"], "s",
                           platform, "attribution", provenance,
                           guard=guard,
                           roofline=report.get("roofline"),
                           extra=extra)
    return _ledger.append_row(row, path=ledger_path)


def attribute_and_bank(trace: str = "", events_path: Optional[str] = None,
                       ledger_path: Optional[str] = None,
                       key: Optional[str] = None,
                       platform: str = "cpu",
                       provenance: Optional[Dict] = None
                       ) -> Optional[Dict]:
    """The one-call producer path (harvest windows, obs_report --bank):
    read the trace, join the perf row by trace id, bank one attribution
    row.  None (nothing banked) when the trace is empty or the joined
    perf row is quarantined."""
    from yask_tpu.obs.tracer import default_trace_path, read_spans
    from yask_tpu.perflab import ledger as _ledger
    rows = read_spans(events_path or default_trace_path())
    report = attribute(rows, trace)
    if report is None:
        return None
    perf = find_perf_row(_ledger.read_rows(path=ledger_path),
                         report["trace"])
    if perf is not None and perf.get("quarantined"):
        return None
    if perf is not None:
        join_model(report, roofline=perf.get("roofline"))
        platform = perf.get("platform", platform)
    return bank(report, key=key or (perf or {}).get("key", ROOT_SPAN),
                platform=platform, provenance=provenance,
                ledger_path=ledger_path)
