"""Process-local metrics: counters / gauges / histograms with p50/p99.

The registry subsumes the ad-hoc percentile math that used to live in
``serve.server._pctl`` — :func:`percentile` IS that implementation
(nearest-rank on ``round(q*(n-1))``), hoisted so the server, the
fleet front, and any future producer compute identical quantiles.

Zero dependencies, thread-safe, JSON-able snapshots::

    reg = Registry()
    reg.counter("serve.requests.ok").inc()
    reg.histogram("serve.total_ms").observe(12.5)
    reg.snapshot()  # {"counters": {...}, "gauges": {...},
                    #  "histograms": {name: {count, mean, p50, p99,
                    #                        max, ...}}}

Histograms keep a bounded sample window (default 4096, oldest
evicted) — the same retention the scheduler applies to its samples
list, so registry quantiles match ``server.metrics()`` exactly.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Dict, List, Optional

DEFAULT_WINDOW = 4096


def percentile(xs: List[float], q: float) -> float:
    """Nearest-rank percentile, exactly the historical serve metric:
    ``sorted(xs)[min(n-1, round(q*(n-1)))]``; 0.0 on empty input."""
    if not xs:
        return 0.0
    xs = sorted(xs)
    i = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
    return xs[i]


class Counter:
    __slots__ = ("_v", "_lock")

    def __init__(self):
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> int:
        return self._v


class Gauge:
    __slots__ = ("_v", "_lock")

    def __init__(self):
        self._v = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    @property
    def value(self) -> float:
        return self._v


class Histogram:
    """Bounded-window histogram; quantiles via :func:`percentile`."""

    __slots__ = ("_xs", "_count", "_sum", "_max", "_lock")

    def __init__(self, window: int = DEFAULT_WINDOW):
        self._xs: Deque[float] = deque(maxlen=max(1, int(window)))
        self._count = 0
        self._sum = 0.0
        self._max = 0.0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._xs.append(v)
            self._count += 1
            self._sum += v
            if v > self._max:
                self._max = v

    def percentile(self, q: float) -> float:
        with self._lock:
            xs = list(self._xs)
        return percentile(xs, q)

    def samples(self) -> List[float]:
        """The current window, oldest→newest.  Fleet aggregation merges
        these raw samples across workers and re-ranks — percentiles are
        never averaged (a mean of p99s is not a p99)."""
        with self._lock:
            return list(self._xs)

    @property
    def count(self) -> int:
        return self._count

    def summary(self) -> Dict:
        with self._lock:
            xs = list(self._xs)
            count, total, mx = self._count, self._sum, self._max
        return {"count": count,
                "mean": (total / count) if count else 0.0,
                "p50": percentile(xs, 0.50),
                "p99": percentile(xs, 0.99),
                "max": mx,
                "window": len(xs)}


class Registry:
    """Named metric instruments, created on first touch."""

    def __init__(self):
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._hists: Dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter()
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge()
            return g

    def histogram(self, name: str,
                  window: int = DEFAULT_WINDOW) -> Histogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(window)
            return h

    def snapshot(self) -> Dict:
        """JSON-able view of every instrument — what the fleet's
        ``op_metrics`` exports per worker."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = dict(self._hists)
        return {"counters": {k: c.value
                             for k, c in sorted(counters.items())},
                "gauges": {k: g.value
                           for k, g in sorted(gauges.items())},
                "histograms": {k: h.summary()
                               for k, h in sorted(hists.items())}}

    def snapshot_full(self) -> Dict:
        """Like :meth:`snapshot` but each histogram also carries its raw
        sample window (``samples``) so an aggregator can merge windows
        across processes and re-rank quantiles."""
        snap = self.snapshot()
        with self._lock:
            hists = dict(self._hists)
        for k, h in hists.items():
            snap["histograms"][k]["samples"] = h.samples()
        return snap


#: the process-local default registry (import-cheap; producers that
#: need isolation — e.g. one server per test — build their own).
REGISTRY = Registry()


def get_registry() -> Registry:
    return REGISTRY
