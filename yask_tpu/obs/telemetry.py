"""Fleet-wide telemetry: merge per-worker metric snapshots, export Prometheus.

The fleet front polls every worker for its registry snapshot (the new
``op metrics_snapshot``) on the heartbeat cadence and folds the results
into ONE fleet view with :func:`merge_snapshots`.  The merge rule that
matters: histograms are merged by pooling their raw sample windows and
re-ranking — percentiles are NEVER averaged (the mean of two worker
p99s is not the fleet p99).  Counters sum; gauges sum (queue depths and
occupancies are additive across workers) with per-worker values kept in
the ``workers`` block for anything that is not.

:func:`to_prometheus` renders any snapshot (per-worker or merged) as
Prometheus text exposition.  Metric names are derived mechanically from
registry names (``serve.total_ms`` → ``yt_serve_total_ms``) so they are
stable as long as the registry names are — ``tests/test_telemetry.py``
pins the flagship set.

Schema: ``yask_tpu.telemetry/1``.  Everything here is pure-Python and
JSON-able; nothing imports jax.
"""

from __future__ import annotations

import json
import re
from typing import Dict, List, Optional

from .metrics import percentile

TELEMETRY_SCHEMA = "yask_tpu.telemetry/1"

#: registry names every serving build must keep exporting — renames are
#: dashboard-breaking changes and fail tests/test_telemetry.py.  The
#: scheduler also emits two dynamic families whose PREFIXES are the
#: stable contract: ``serve.requests.<status>`` (ok/anomaly/rejected…)
#: and ``serve.cache.<tier>`` (cold/memory/disk).
STABLE_COUNTERS = (
    "serve.requests.ok",
    "serve.requests.anomaly",
    "serve.requests.rejected",
    "serve.degraded",
    "serve.preempted",
)
STABLE_COUNTER_PREFIXES = ("serve.requests.", "serve.cache.",
                           "serve.overload.")
STABLE_GAUGES = ("serve.queue_depth",)
STABLE_HISTOGRAMS = (
    "serve.queue_ms",
    "serve.run_ms",
    "serve.total_ms",
    "serve.batch_occupancy",
)


def _merged_hist(summaries: List[Dict]) -> Dict:
    """Fold per-worker histogram summaries (with raw ``samples``) into
    one summary over the pooled window."""
    xs: List[float] = []
    count = 0
    mx = 0.0
    mean_num = 0.0
    for s in summaries:
        xs.extend(s.get("samples", ()))
        count += int(s.get("count", 0))
        mx = max(mx, float(s.get("max", 0.0)))
        mean_num += float(s.get("mean", 0.0)) * int(s.get("count", 0))
    return {"count": count,
            "mean": (mean_num / count) if count else 0.0,
            "p50": percentile(xs, 0.50),
            "p99": percentile(xs, 0.99),
            "max": mx,
            "window": len(xs)}


def merge_snapshots(per_worker: Dict[str, Dict],
                    ts: Optional[float] = None) -> Dict:
    """Merge worker ``Registry.snapshot_full()`` dicts into one fleet
    snapshot.

    ``per_worker`` maps a worker id to its snapshot; extra per-worker
    keys (``occupancy``, ``cache``, ``journal``, ``slo``) ride along in
    the ``workers`` block untouched.  The ``merged`` block sums counters
    and gauges and pools histogram samples (see module doc).

    A snapshot carrying ``stale: True`` (the fleet front stamps it when
    a worker has not answered a poll for 3 heartbeat intervals — it is
    the LAST known snapshot, not a fresh one) keeps its ``workers``
    block entry for inspection but is EXCLUDED from the merged fold,
    and its worker id lands in the top-level ``stale_workers`` list:
    a hung worker's dead numbers must not ride in fleet sums forever,
    and the autoscaler must be able to refuse to act on them.
    """
    counters: Dict[str, int] = {}
    gauges: Dict[str, float] = {}
    hists: Dict[str, List[Dict]] = {}
    workers: Dict[str, Dict] = {}
    stale: List[str] = []
    for wid, snap in sorted(per_worker.items()):
        snap = snap or {}
        if snap.get("stale"):
            stale.append(str(wid))
        else:
            for k, v in (snap.get("counters") or {}).items():
                counters[k] = counters.get(k, 0) + int(v)
            for k, v in (snap.get("gauges") or {}).items():
                gauges[k] = gauges.get(k, 0.0) + float(v)
            for k, s in (snap.get("histograms") or {}).items():
                hists.setdefault(k, []).append(s)
        # per-worker view without the raw windows (they can be large)
        wsnap = dict(snap)
        wsnap["histograms"] = {
            k: {kk: vv for kk, vv in s.items() if kk != "samples"}
            for k, s in (snap.get("histograms") or {}).items()}
        workers[str(wid)] = wsnap
    out = {"v": TELEMETRY_SCHEMA,
           "workers": workers,
           "stale_workers": stale,
           "merged": {
               "counters": dict(sorted(counters.items())),
               "gauges": dict(sorted(gauges.items())),
               "histograms": {k: _merged_hist(v)
                              for k, v in sorted(hists.items())}}}
    if ts is not None:
        out["ts"] = float(ts)
    return out


_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def prom_name(name: str, prefix: str = "yt") -> str:
    """``serve.total_ms`` → ``yt_serve_total_ms`` (Prometheus charset)."""
    return f"{prefix}_{_NAME_RE.sub('_', name)}"


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def to_prometheus(snapshot: Dict, prefix: str = "yt") -> str:
    """Render a snapshot (plain registry snapshot, or the ``merged`` /
    per-worker block of a fleet snapshot) as Prometheus text exposition.

    Histograms export as summaries: ``{quantile="0.5"|"0.99"}`` series
    plus ``_count`` / ``_sum`` / ``_max``.  When given a full fleet
    snapshot (has a ``merged`` key) the merged block is exported
    unlabeled and per-worker gauges/counters get a ``worker`` label.
    """
    lines: List[str] = []
    workers = snapshot.get("workers") if "merged" in snapshot else None
    body = snapshot.get("merged", snapshot)

    for k, v in sorted((body.get("counters") or {}).items()):
        n = prom_name(k, prefix)
        lines.append(f"# TYPE {n} counter")
        lines.append(f"{n} {_fmt(v)}")
        for wid, snap in sorted((workers or {}).items()):
            wv = (snap.get("counters") or {}).get(k)
            if wv is not None:
                lines.append(f'{n}{{worker="{wid}"}} {_fmt(wv)}')
    for k, v in sorted((body.get("gauges") or {}).items()):
        n = prom_name(k, prefix)
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n} {_fmt(v)}")
        for wid, snap in sorted((workers or {}).items()):
            wv = (snap.get("gauges") or {}).get(k)
            if wv is not None:
                lines.append(f'{n}{{worker="{wid}"}} {_fmt(wv)}')
    for k, s in sorted((body.get("histograms") or {}).items()):
        n = prom_name(k, prefix)
        lines.append(f"# TYPE {n} summary")
        lines.append(f'{n}{{quantile="0.5"}} {_fmt(s.get("p50", 0.0))}')
        lines.append(f'{n}{{quantile="0.99"}} {_fmt(s.get("p99", 0.0))}')
        cnt = int(s.get("count", 0))
        lines.append(f"{n}_count {cnt}")
        lines.append(f"{n}_sum {_fmt(float(s.get('mean', 0.0)) * cnt)}")
        lines.append(f"{n}_max {_fmt(s.get('max', 0.0))}")
    return "\n".join(lines) + ("\n" if lines else "")


def snapshot_to_json(snapshot: Dict) -> str:
    return json.dumps(snapshot, sort_keys=True)
