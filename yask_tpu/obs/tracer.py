"""Zero-dependency span tracer: the repo's correlation spine.

Schema ``yask_tpu.trace/1`` — one row per completed span, appended to
``TRACE_EVENTS.jsonl`` (repo root, ``YT_TRACE_EVENTS`` override)::

    {"v": "yask_tpu.trace/1",
     "trace":  "t4f2...",          # trace id — one per request/run
     "span":   "s07ab...",         # this span
     "parent": "s0000...",         # "" at the root
     "name":   "run.chunk",
     "phase":  "compute",          # compile|exchange|compute|dma|
                                   # checkpoint|queue|front|tune|guard
     "ts":     1754486400.123,     # wall-clock epoch seconds (cross-
                                   # process placement; monotonic bases
                                   # differ between processes)
     "dur":    0.0123,             # perf_counter-measured seconds
     "pid":    1234, "tid": 5678,
     "attrs":  {...}}              # structured, producer-specific

Off by default and a TRUE no-op on the hot path: unless ``YT_TRACE``
is truthy, :func:`span` performs one env lookup and yields a shared
null object — no id generation, no clock reads, no file I/O, and no
file is ever created (the no-op guarantee is asserted by test).

Trace *ids* are independent of the enable gate: :func:`activate`
installs an upstream id (e.g. one stamped on a wire message by the
fleet front) in thread-local state so :func:`stamp_trace` can join
journal/ledger rows to the trace even in processes that do not write
spans themselves.

I/O discipline mirrors the serve journal: append-only, never raises
(an answer must not depend on evidence I/O), malformed lines skipped
on read, and :func:`compact_if_large` bounds growth
(``YT_TRACE_MAX_MB``, bad values fall back to the default, never
raises) by atomically keeping the newest tail of whole lines.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional

TRACE_SCHEMA = "yask_tpu.trace/1"
TRACE_BASENAME = "TRACE_EVENTS.jsonl"

#: canonical phase vocabulary — the obs_report breakdown groups on it.
PHASES = ("compile", "exchange", "compute", "dma", "checkpoint",
          "queue", "front", "tune", "guard")

_TRUTHY = ("1", "on", "true", "yes")


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def trace_enabled() -> bool:
    """True when span *writing* is on (``YT_TRACE`` truthy).  Read
    from the environment on every call so tests can monkeypatch."""
    return os.environ.get("YT_TRACE", "").strip().lower() in _TRUTHY


def default_trace_path() -> str:
    return os.environ.get("YT_TRACE_EVENTS") or os.path.join(
        _repo_root(), TRACE_BASENAME)


def trace_max_bytes() -> int:
    """Compaction threshold (``YT_TRACE_MAX_MB``, default 64 MiB).
    Bad values fall back to the default — same contract as the
    journals' ``compact_if_large``."""
    try:
        mb = float(os.environ.get("YT_TRACE_MAX_MB", "") or 64.0)
        if mb <= 0:
            mb = 64.0
    except ValueError:
        mb = 64.0
    return int(mb * (1 << 20))


def new_trace_id() -> str:
    return "t" + uuid.uuid4().hex[:15]


def _new_span_id() -> str:
    return "s" + uuid.uuid4().hex[:15]


# ------------------------------------------------------------- context
# Thread-local: the active trace id plus the open-span stack.  Worker
# threads/processes join an upstream trace via activate(); nothing is
# inherited implicitly (the scheduler's device thread activates the
# request's id explicitly around each batch).
_tls = threading.local()


def _stack() -> List[str]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_trace_id() -> str:
    """The active trace id ("" when none)."""
    return getattr(_tls, "trace", "") or ""


def current_span_id() -> str:
    st = _stack()
    return st[-1] if st else ""


def set_trace(trace_id: str) -> None:
    _tls.trace = trace_id or ""


@contextmanager
def activate(trace_id: str) -> Iterator[str]:
    """Install ``trace_id`` as the thread's active trace for the
    duration (no-op passthrough on an empty id).  This is how an id
    stamped on a wire message by the fleet front propagates into a
    worker's journal/ledger rows via :func:`stamp_trace`."""
    if not trace_id:
        yield ""
        return
    prev = current_trace_id()
    _tls.trace = trace_id
    try:
        yield trace_id
    finally:
        _tls.trace = prev


def stamp_trace(row: Dict) -> Dict:
    """Set ``row["trace_id"]`` when a trace id is active; returns the
    row either way.  Journal/ledger append sites call this so every
    artifact joins against TRACE_EVENTS — repo_lint's TRACE-ID rule
    checks the call is present."""
    tid = current_trace_id()
    if tid:
        row["trace_id"] = tid
    return row


# --------------------------------------------------------------- spans
class Span:
    """A live span handle; ``set()`` merges attrs before close."""

    __slots__ = ("trace", "span", "parent", "name", "phase", "attrs",
                 "_t_wall", "_t0")

    def __init__(self, trace: str, parent: str, name: str, phase: str,
                 attrs: Dict):
        self.trace = trace
        self.span = _new_span_id()
        self.parent = parent
        self.name = name
        self.phase = phase
        self.attrs = attrs
        self._t_wall = time.time()
        self._t0 = time.perf_counter()

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self


class _NullSpan:
    """Shared no-op handle yielded when tracing is off."""

    __slots__ = ()
    trace = span = parent = name = phase = ""
    attrs: Dict = {}

    def set(self, **attrs) -> "_NullSpan":
        return self


_NULL = _NullSpan()

_compact_checked = False


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return repr(v)


def _write_row(row: Dict) -> None:
    """Append one span row; never raises (evidence I/O must not cost
    an answer).  First write per process checks the size bound."""
    global _compact_checked
    path = default_trace_path()
    try:
        if not _compact_checked:
            _compact_checked = True
            compact_if_large(path)
        with open(path, "a") as f:  # lint: trace-id-ok
            f.write(json.dumps(row, sort_keys=True) + "\n")
    except (OSError, ValueError, TypeError):
        pass


@contextmanager
def span(name: str, phase: str = "", trace: str = "",
         **attrs) -> Iterator[Span]:
    """Open a span.  A true no-op unless ``YT_TRACE`` is set: one env
    lookup, then a shared null handle — no clocks, ids, or I/O."""
    if not trace_enabled():
        yield _NULL
        return
    tid = trace or current_trace_id() or new_trace_id()
    sp = Span(tid, current_span_id(), name, phase,
              {k: _jsonable(v) for k, v in attrs.items()})
    prev_trace = current_trace_id()
    _tls.trace = tid
    st = _stack()
    st.append(sp.span)
    try:
        yield sp
    finally:
        dur = time.perf_counter() - sp._t0
        st.pop()
        _tls.trace = prev_trace
        _write_row({"v": TRACE_SCHEMA, "trace": sp.trace,
                    "span": sp.span, "parent": sp.parent,
                    "name": sp.name, "phase": sp.phase,
                    "ts": sp._t_wall, "dur": dur,
                    "pid": os.getpid(),
                    "tid": threading.get_ident(),
                    "attrs": {k: _jsonable(v)
                              for k, v in sp.attrs.items()}})


def record_span(name: str, phase: str, start_wall: float, dur: float,
                trace: str = "", parent: str = "", **attrs) -> None:
    """Record a retroactive span from already-measured times (e.g. the
    queue-wait interval computed at release, or the halo share of a
    timed program call).  Same gate and I/O discipline as live spans."""
    if not trace_enabled():
        return
    _write_row({"v": TRACE_SCHEMA,
                "trace": trace or current_trace_id() or new_trace_id(),
                "span": _new_span_id(), "parent": parent,
                "name": name, "phase": phase,
                "ts": float(start_wall), "dur": float(dur),
                "pid": os.getpid(), "tid": threading.get_ident(),
                "attrs": {k: _jsonable(v) for k, v in attrs.items()}})


#: site-prefix → phase, for spans named after guarded_call sites.
_SITE_PHASES = (("ckpt.", "checkpoint"), ("cache.", "compile"),
                ("compile", "compile"), ("exchange", "exchange"),
                ("halo", "exchange"), ("comm", "exchange"),
                ("tuner.", "tune"), ("tune", "tune"),
                ("fleet.", "front"), ("serve.flush", "front"),
                ("state.", "dma"), ("dma", "dma"),
                ("serve.", "compute"), ("run.", "compute"),
                ("bench.", "compute"), ("session.", "compute"),
                ("multihost.", "compute"), ("pipeline.", "compute"),
                ("suite.", "compute"), ("watch.", "front"),
                ("load.", "front"))


def phase_for_site(site: str) -> str:
    for prefix, phase in _SITE_PHASES:
        if site.startswith(prefix):
            return phase
    return "guard"


# ---------------------------------------------------------------- read
def read_spans(path: Optional[str] = None) -> List[Dict]:
    """All span rows, file order; malformed lines skipped, never
    fatal (a producer may have crashed mid-write)."""
    path = path or default_trace_path()
    out: List[Dict] = []
    try:
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    row = json.loads(ln)
                except ValueError:
                    continue
                if isinstance(row, dict) \
                        and row.get("v") == TRACE_SCHEMA:
                    out.append(row)
    except OSError:
        pass
    return out


def compact_if_large(path: Optional[str] = None,
                     max_bytes: Optional[int] = None) -> bool:
    """Bound file growth: when over the limit, atomically keep the
    newest tail of whole lines that fits half the limit (spans have no
    per-key identity to dedupe on — recency is the value).  Never
    raises; bad ``YT_TRACE_MAX_MB`` values use the default."""
    path = path or default_trace_path()
    try:
        limit = trace_max_bytes() if max_bytes is None \
            else int(max_bytes)
        if limit <= 0 or os.path.getsize(path) <= limit:
            return False
        with open(path, "rb") as f:
            lines = f.readlines()
        budget = limit // 2
        kept: List[bytes] = []
        total = 0
        for ln in reversed(lines):
            if total + len(ln) > budget and kept:
                break
            total += len(ln)
            kept.append(ln)
        kept.reverse()
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.writelines(kept)
        os.replace(tmp, path)
        return True
    except (OSError, ValueError):
        return False


# ------------------------------------------------------- jax profiler
@contextmanager
def profile_window(logdir: Optional[str] = None) -> Iterator[None]:
    """Optionally bracket a traced region in ``jax.profiler.trace``
    so a healthy relay window banks an on-device profile alongside
    the span timeline.  Engages when ``logdir`` is given or
    ``YT_JAX_PROFILE`` names a directory; otherwise (and on ANY
    profiler failure) a plain no-op — profiling must never take a
    run down."""
    logdir = logdir or os.environ.get("YT_JAX_PROFILE", "")
    if not logdir:
        yield
        return
    started = False
    try:
        try:
            import jax
            jax.profiler.start_trace(logdir)
            started = True
        except Exception:
            pass
        yield
    finally:
        if started:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass
