"""perflab: benchmark provenance, the unified perf ledger, and the
regression sentinel.

The reference's identity is its measured trial protocol
(``yask_main.cpp:131-139``); this package is what makes the numbers that
protocol produces *actionable* across sessions and machines:

* :mod:`yask_tpu.perflab.provenance` — machine/load context + a
  calibration micro-kernel rate attached to every measurement, so rows
  taken under different load or on different hosts are comparable;
* :mod:`yask_tpu.perflab.ledger` — the append-only ``PERF_LEDGER.jsonl``
  every perf producer in the repo writes through (bench.py contract
  line, ``tools/bench_suite.py`` rows, harness ``-ledger`` runs,
  ``tools/tpu_session.py`` hardware rows), with query helpers;
* :mod:`yask_tpu.perflab.sentinel` — per-row regression guards
  (trailing-median relative tolerance + absolute floors) with an
  automatic single re-measure on breach and a noise-vs-regression
  verdict recorded in the row;
* :mod:`yask_tpu.perflab.roofline` — the single HBM-roofline model the
  harness, bench, suite, and session all consume.

``tools/perf_bisect.py`` replays one ledger row-key across a git
revision range to localize regressions the sentinel flags.
"""

from yask_tpu.perflab.ledger import (append_row, default_ledger_path,
                                     make_row, read_rows, trailing_median,
                                     validate_row)
from yask_tpu.perflab.provenance import capture_provenance
from yask_tpu.perflab.roofline import roofline
from yask_tpu.perflab.sentinel import (DEFAULT_RULES, GuardRule, check_row,
                                       guard_and_append, is_clean)

__all__ = [
    "append_row", "default_ledger_path", "make_row", "read_rows",
    "trailing_median", "validate_row", "capture_provenance", "roofline",
    "DEFAULT_RULES", "GuardRule", "check_row", "guard_and_append",
    "is_clean",
]
