"""The single HBM-roofline model every perf producer consumes.

Before perflab, the same three lines of arithmetic (modeled bytes/point ×
achieved rate vs the chip's aggregate peak) were duplicated — with
drifting key names — in ``yask_tpu/main.py`` (harness print),
``bench.py`` (contract line: ``hbm_roofline``), ``tools/bench_suite.py``
(none at all), and ``tools/tpu_session.py`` (``roofline_frac``).  This
module is the hoist: one function, one set of keys, recorded under
``roofline`` on every ledger row that has a traffic model.
"""

from __future__ import annotations

from typing import Dict, Optional


def roofline(rate_gpts: float, bytes_pp: float,
             peak_bytes_per_sec: float = 0.0, ndev: int = 1) -> Dict:
    """Roofline context for one measured rate.

    ``rate_gpts``  — achieved global throughput in GPts/s;
    ``bytes_pp``   — modeled HBM bytes per point per step (read+write,
                     from ``ctx.hbm_model_bytes_pp()``);
    ``peak_bytes_per_sec`` — per-chip peak HBM bandwidth
                     (``env.get_hbm_peak_bytes_per_sec()``; 0 = unknown,
                     e.g. the CPU proxy mesh);
    ``ndev``       — chips the rate is aggregated over (the roofline
                     denominator scales with the mesh).

    Returns ``{"hbm_bytes_pp", "hbm_gbps", "roofline_frac"}``;
    ``roofline_frac`` is None when the peak is unknown (the ledger drops
    None entries, so CPU rows simply lack the key rather than carrying
    a fake 0).
    """
    bpp = float(bytes_pp)
    gbps = float(rate_gpts) * bpp        # 1 GPt/s × B/pt == 1 GB/s
    out = {
        "hbm_bytes_pp": round(bpp, 2),
        "hbm_gbps": round(gbps, 1),
        "roofline_frac": None,
    }
    peak = float(peak_bytes_per_sec) * max(int(ndev), 1)
    if peak > 0:
        out["roofline_frac"] = round(gbps * 1e9 / peak, 4)
    return out


def ctx_roofline(ctx, env, rate_gpts: float) -> Dict:
    """Roofline context straight from a prepared solution context: the
    configured execution path's traffic model + the environment's peak.
    Producers that hold a context call this instead of re-deriving the
    inputs."""
    rb, wb = ctx.hbm_model_bytes_pp()
    return roofline(rate_gpts, rb + wb,
                    env.get_hbm_peak_bytes_per_sec(),
                    ndev=env.get_num_ranks())


def modeled_compute_secs(measured_secs: float,
                         roofline_frac: Optional[float]
                         ) -> Optional[float]:
    """The attribution join's modeled compute time: ``roofline_frac``
    is achieved/roofline rate, so a run at exactly the model's HBM
    roofline would have finished the same work in ``measured × frac``
    seconds.  None when the peak (and hence the fraction) is unknown —
    the attribution row then carries measured time only.  Lives here so
    measured-vs-modeled comparisons share the ONE roofline definition
    with every other producer."""
    if roofline_frac is None:
        return None
    return float(measured_secs) * float(roofline_frac)


def format_roofline(roof: Dict) -> str:
    """The harness' human-readable lines for one roofline dict (the
    log keys ``tools/log_to_csv.py`` scrapes)."""
    lines = [f"  hbm-bytes-per-point (read+write): "
             f"{roof['hbm_bytes_pp']:.6g}\n",
             f"  achieved-HBM (GB/s): {roof['hbm_gbps']:.6g}\n"]
    frac = roof.get("roofline_frac")
    if frac is not None:
        lines.append(f"  hbm-roofline-fraction (%): {100.0 * frac:.4g}\n")
    return "".join(lines)


# ---------------------------------------------------------------------------
# ICI/DCN link model (the comm-side analog of the HBM peak table above in
# env.py): per-axis link bandwidth + latency by device kind, consumed by
# the CommPlan scheduler (yask_tpu/parallel/comm_plan.py) to order mesh
# axes and decide message coalescing.  Pure numbers — this module never
# imports jax (provenance invariant), so the checker and the CPU proxy
# can cost a plan without a backend.
# ---------------------------------------------------------------------------

# (substring match on jax device_kind, lowercased) -> (GB/s per link
# direction, one-way latency in µs).  ICI figures follow the public
# per-chip interconnect specs (per-direction share of the torus links);
# DCN is the inter-host data-center network — orders of magnitude more
# latency, so axes that cross hosts must start their flight first.
_ICI_LINKS = (
    (("v5 lite", "v5e"), (45.0, 1.0)),
    (("v5p", "v5"), (90.0, 1.0)),
    (("v6", "trillium"), (90.0, 1.0)),
    (("v4",), (50.0, 1.0)),
    (("v3",), (35.0, 1.0)),
    (("v2",), (25.0, 1.0)),
)
_DCN_LINK = (12.5, 25.0)          # ~100 Gb/s NIC share, host-to-host RTT/2
_ICI_DEFAULT = (40.0, 1.0)        # unknown chip (CPU proxy mesh): any
#                                   positive numbers — only the ici/dcn
#                                   asymmetry matters for ordering there


def link_model(device_kind: str = "", kind: str = "ici") -> Dict:
    """Modeled link characteristics for one mesh axis.

    ``device_kind`` — jax's ``device_kind`` string ("" = unknown, e.g.
    the CPU proxy mesh); ``kind`` — ``"ici"`` for on-slice torus axes,
    ``"dcn"`` for axes that cross host processes.  Returns
    ``{"kind", "gbps", "latency_us"}``.
    """
    if kind == "dcn":
        gbps, lat = _DCN_LINK
    else:
        kd = (device_kind or "").lower()
        gbps, lat = _ICI_DEFAULT
        for keys, spec in _ICI_LINKS:
            if any(k in kd for k in keys):
                gbps, lat = spec
                break
    return {"kind": kind, "gbps": gbps, "latency_us": lat}


def link_secs(nbytes: float, link: Dict) -> float:
    """Modeled one-way flight time of an ``nbytes`` payload on ``link``
    (latency + bytes/bandwidth)."""
    return (link["latency_us"] * 1e-6
            + float(nbytes) / (link["gbps"] * 1e9))


def order_comm_axes(axis_costs: Dict[str, Dict]) -> list:
    """Exchange ordering off the link model: DCN axes first (their
    longer flight time needs the most compute to hide under — the
    rank-order pumping stance of the reference's halo loop,
    ``context.cpp:377-478``), then ICI axes by descending modeled
    flight time; ties keep the input (domain-dim) order.

    ``axis_costs`` maps dim -> {"kind": "ici"|"dcn", "secs": float}.
    """
    dims = list(axis_costs)
    return sorted(
        dims,
        key=lambda d: (0 if axis_costs[d]["kind"] == "dcn" else 1,
                       -axis_costs[d]["secs"], dims.index(d)))


def vmem_sweep_margin_model(stencil: str = "iso3dfd", radius: int = 8,
                            g: int = 512, fuse_steps: int = 2,
                            budgets_mib=(64, 96, 120),
                            dtype_bytes: Optional[int] = None,
                            max_skew_dims: int = 2) -> Dict:
    """Modeled (block, margin_overhead) per VMEM budget — the relay-down
    variant of the ``-vmem_mb`` hardware sweep (VERDICT r5 item 7) and
    the model behind the auto-tuner's vmem ladder: runs the actual tile
    planner + margin model on the CPU, no backend needed.  Returns
    {budget_mib: {"block": {...}, "margin_overhead": f}}.

    The numbers come from the ACTUAL kernel build (``build_pallas_chunk``
    in interpret mode — planning + tracing setup only, nothing runs):
    ``chunk.tiling`` is the same exact per-(sub-step, stage) accounting
    a hardware run would report, so the modeled table and a later
    measured one are directly comparable.  ``max_skew_dims`` mirrors
    the ``-skew_dims`` knob (2 = multi-dim skew allowed; 1 = the 1-D
    A/B arm); each row records which dims actually engaged.
    """
    from yask_tpu.compiler.solution_base import create_solution
    from yask_tpu.ops.pallas_stencil import build_pallas_chunk
    from yask_tpu.utils.idx_tuple import IdxTuple

    sb = create_solution(stencil, radius=radius)
    if dtype_bytes:
        sb.get_soln().set_element_bytes(dtype_bytes)
    csol = sb.get_soln().compile()
    sizes = IdxTuple(**{d: g for d in csol.ana.domain_dims})
    K = fuse_steps
    rK = {d: csol.ana.fused_step_radius().get(d, 0) * K
          for d in csol.ana.domain_dims[:-1]}
    prog = csol.plan(sizes, extra_pad={d: (m, m) for d, m in rK.items()})
    out = {}
    for mib in budgets_mib:
        chunk, tile_bytes = build_pallas_chunk(
            prog, fuse_steps=K, interpret=True,
            vmem_budget=int(mib) * 2 ** 20,
            max_skew_dims=max_skew_dims)
        t = chunk.tiling
        out[int(mib)] = {
            "block": dict(t["block"]), "skew": t["skew"],
            "skew_dims": list(t.get("skew_dims", [])),
            "margin_overhead": t["margin_overhead"],
            "tile_mib": round(tile_bytes / 2 ** 20, 1),
        }
    return out
