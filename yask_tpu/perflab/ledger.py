"""The unified perf ledger: one append-only JSONL every producer writes.

``PERF_LEDGER.jsonl`` (repo root, override via ``YT_PERF_LEDGER``) is
the single place perf numbers live between sessions.  One row schema
covers every producer — the bench.py contract line, the
``tools/bench_suite.py`` BASELINE rows, harness ``-ledger`` runs, the
multichip dryrun, and hardware rows from ``tools/tpu_session.py``
(legacy ``TPU_RESULTS.jsonl`` records convert via :func:`from_legacy`).

Row schema (version 1)::

    {"v": 1,
     "key":      "iso3dfd r=8 128^3 fp32 cpu throughput (jit)",  # row-key
     "value":    0.114, "unit": "GPts/s",
     "platform": "cpu",
     "source":   "bench",            # bench|suite|harness|tpu_session|...
     "measured_at": "2026-08-05T12:00:00Z",
     "provenance": {loadavg, ncpu, cpu_model, governor, jax, jaxlib,
                    git_sha, env_fp, calib_gpts, ...},
     # optional:
     "guard":  {...}                 # sentinel verdict (sentinel.py)
     "roofline": {hbm_bytes_pp, hbm_gbps, roofline_frac}
     "extra":  {...}}                # producer-specific context (tiling,
                                     # k1/k4 rates, halo %, error, ...)

The *row-key* is the stable identity a measurement series shares: the
sentinel's trailing median, ``tools/log_to_csv.py --ledger`` grouping,
and ``tools/perf_bisect.py`` replay all key on it.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, List, Optional

SCHEMA_VERSION = 1
LEDGER_BASENAME = "PERF_LEDGER.jsonl"

#: who measured the row; new producers register here so query tooling
#: can enumerate them.
KNOWN_SOURCES = ("bench", "suite", "harness", "tpu_session", "multichip",
                 "bisect", "perfcheck", "test", "bench_seed",
                 "attribution", "load")

_REQUIRED = ("v", "key", "value", "unit", "platform", "source",
             "measured_at", "provenance")
#: provenance keys every row must carry (the acceptance bar: rows are
#: useless for cross-session comparison without them).
_REQUIRED_PROV = ("loadavg", "cpu_model", "git_sha")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def default_ledger_path() -> str:
    return os.environ.get("YT_PERF_LEDGER") or os.path.join(
        repo_root(), LEDGER_BASENAME)


def utc_now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def make_row(key: str, value: float, unit: str, platform: str,
             source: str, provenance: Dict, guard: Optional[Dict] = None,
             roofline: Optional[Dict] = None,
             extra: Optional[Dict] = None,
             measured_at: Optional[str] = None) -> Dict:
    """Build (and validate) one schema-v1 ledger row."""
    row = {
        "v": SCHEMA_VERSION,
        "key": str(key),
        "value": float(value),
        "unit": str(unit),
        "platform": str(platform),
        "source": str(source),
        "measured_at": measured_at or utc_now(),
        "provenance": dict(provenance),
    }
    if guard:
        row["guard"] = dict(guard)
    if roofline:
        row["roofline"] = {k: v for k, v in roofline.items()
                           if v is not None}
    if extra:
        row["extra"] = dict(extra)
    validate_row(row)
    return row


def validate_row(row: Dict) -> None:
    """Raise ValueError unless ``row`` conforms to the v1 schema."""
    if not isinstance(row, dict):
        raise ValueError(f"ledger row must be a dict, got {type(row)}")
    missing = [k for k in _REQUIRED if k not in row]
    if missing:
        raise ValueError(f"ledger row missing field(s) {missing}: "
                         f"{sorted(row)}")
    if row["v"] != SCHEMA_VERSION:
        raise ValueError(f"unknown ledger schema version {row['v']!r}")
    if not isinstance(row["value"], (int, float)) \
            or isinstance(row["value"], bool):
        raise ValueError(f"row value must be numeric, got "
                         f"{row['value']!r}")
    if not row["key"]:
        raise ValueError("row key must be non-empty")
    prov = row["provenance"]
    if not isinstance(prov, dict):
        raise ValueError("provenance must be a dict")
    pmissing = [k for k in _REQUIRED_PROV if k not in prov]
    if pmissing:
        raise ValueError(f"provenance missing {pmissing} "
                         f"(capture_provenance supplies them)")


def from_legacy(rec: Dict, source: str, provenance: Dict) -> Dict:
    """Convert a legacy bench/TPU_RESULTS record ({"metric": ...,
    "value": ..., "unit": ...}) into a v1 ledger row; roofline context
    and leftover fields land in ``roofline``/``extra``."""
    rec = dict(rec)
    roof = {}
    for src_k, dst_k in (("hbm_bytes_pp", "hbm_bytes_pp"),
                         ("hbm_gbps", "hbm_gbps"),
                         ("hbm_roofline", "roofline_frac"),
                         ("roofline_frac", "roofline_frac")):
        if src_k in rec:
            roof[dst_k] = rec.pop(src_k)
    key = rec.pop("metric", rec.pop("key", ""))
    value = rec.pop("value", 0.0)
    unit = rec.pop("unit", "")
    platform = rec.pop("platform", provenance.get("platform", ""))
    measured_at = rec.pop("measured_at", None)
    return make_row(key, value, unit, platform, source, provenance,
                    roofline=roof or None, extra=rec or None,
                    measured_at=measured_at)


def append_row(row: Dict, path: Optional[str] = None) -> Dict:
    """Validate + append one row; returns the row.  Append-only by
    contract: nothing in the repo rewrites or deletes ledger lines.
    Rows appended under an active trace gain ``trace_id`` (optional
    field, schema-compatible) so perf evidence joins the span
    timeline."""
    from yask_tpu.obs.tracer import stamp_trace
    validate_row(row)
    stamp_trace(row)
    with open(path or default_ledger_path(), "a") as f:
        f.write(json.dumps(row, sort_keys=True) + "\n")
    return row


def read_rows(path: Optional[str] = None, key: Optional[str] = None,
              platform: Optional[str] = None,
              source: Optional[str] = None,
              sha: Optional[str] = None) -> List[Dict]:
    """All (optionally filtered) rows, file order == time order.
    Malformed lines are skipped, never fatal — the ledger must stay
    readable even if a producer crashed mid-write."""
    path = path or default_ledger_path()
    rows: List[Dict] = []
    try:
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    row = json.loads(ln)
                except ValueError:
                    continue
                if not isinstance(row, dict):
                    continue
                if key is not None and row.get("key") != key:
                    continue
                if platform is not None \
                        and row.get("platform") != platform:
                    continue
                if source is not None and row.get("source") != source:
                    continue
                if sha is not None and not str(
                        row.get("provenance", {}).get("git_sha", "")
                        ).startswith(sha):
                    continue
                rows.append(row)
    except OSError:
        pass
    return rows


def seed_rows_from_bench(key: str, platform: str,
                         root: Optional[str] = None) -> List[Dict]:
    """Baseline rows for ``key`` recovered from the committed
    ``BENCH_*.json`` artifacts at the repo root (rows keyed
    ``metric``, converted via :func:`from_legacy`), oldest file first.

    ``PERF_LEDGER.jsonl`` is a runtime artifact and no longer ships in
    git, so a fresh clone has no ledger history — the sentinel seeds
    its trailing median from these committed bench snapshots instead
    of judging every first measurement as ``no_history``."""
    import glob
    root = root or repo_root()
    rows: List[Dict] = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(doc, dict):
            continue
        plat = doc.get("platform", "")
        if platform and plat and plat != platform:
            continue
        for rec in doc.get("rows", []):
            if not isinstance(rec, dict) \
                    or rec.get("metric") != key:
                continue
            rec = dict(rec)
            prov = dict(rec.pop("provenance", None) or {})
            prov.setdefault("loadavg", [])
            prov.setdefault("cpu_model", "")
            prov.setdefault("git_sha", "")
            guard = rec.pop("guard", None)
            try:
                row = from_legacy(rec, "bench_seed", prov)
            except ValueError:
                continue
            if guard:
                # the snapshot's own verdict rides along so is_clean
                # keeps a recorded regression out of the baseline
                row["guard"] = guard
            rows.append(row)
    return rows


def trailing_median(rows: List[Dict], n: int = 5,
                    accept: Optional[Callable[[Dict], bool]] = None
                    ) -> Optional[float]:
    """Median value of the last ``n`` rows passing ``accept`` (default:
    all) — the sentinel's baseline.  None with no accepted history."""
    vals = [float(r["value"]) for r in rows
            if accept is None or accept(r)][-n:]
    if not vals:
        return None
    vals.sort()
    return vals[len(vals) // 2]
