"""Regression sentinel: per-row guards over the perf ledger.

Generalizes the lone ad-hoc cube-wavefront guard the round-5 verdict
called out (``tools/bench_suite.py:172-179`` then; a :class:`GuardRule`
now): every produced row is checked against

* a **relative tolerance vs the trailing median** of the last N clean
  same-platform rows for its key (clean = prior guard did not say
  regression, and the machine was not overloaded — :func:`is_clean`),
* optional **absolute floors** for the sentinel rows whose collapse has
  bitten before (the r3 mosaic-geometry slide on the 128³ jit headline,
  the r4 skew mis-engage on the cube wavefront),

and on a breach performs one **automatic re-measure**: if the second
sample clears, the verdict is ``noise`` (both values recorded); if it
also breaches, ``regression``.  The verdict dict rides IN the row, so
the artifact itself says whether a low number was load noise or a real
slide — the question round 5 could not answer.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from yask_tpu.perflab import ledger as _ledger

#: a 1-minute load average above this many times the CPU count marks the
#: row dirty: its value reflects contention, not the code under test.
LOAD_CLEAN_MAX = 1.5

#: units the sentinel guards (throughput and speedup rows; error/skip
#: marker rows pass through as ``unguarded``).
GUARDED_UNITS = ("GPts/s", "x")


@dataclass
class GuardRule:
    """One guard: matches row keys by substring (optionally per
    platform), enforces a relative tolerance vs the trailing clean
    median and/or an absolute floor."""
    name: str
    pattern: str = ""               # substring of the row key ("" = all)
    rel_tol: float = 0.35           # breach below (1−tol)×median
    floor: Optional[float] = None   # absolute breach threshold
    window: int = 5                 # trailing-median depth
    platforms: Optional[Tuple[str, ...]] = None   # None = any
    direction: str = "higher"       # "higher"|"lower" is better

    def matches(self, key: str, platform: str) -> bool:
        if self.pattern and self.pattern not in key:
            return False
        return self.platforms is None or platform in self.platforms

    def breaches(self, value: float, baseline: Optional[float]) -> bool:
        lo = self.direction == "lower"
        if self.floor is not None:
            if (value > self.floor) if lo else (value < self.floor):
                return True
        if baseline is not None and baseline > 0:
            lim = ((1.0 + self.rel_tol) * baseline if lo
                   else (1.0 - self.rel_tol) * baseline)
            if (value > lim) if lo else (value < lim):
                return True
        return False


#: Absolute floor for the 128³ jit CPU-proxy headline.  Set from the
#: round-6 recorded-load re-measure (2026-08-05, 1-core proxy host,
#: load1 0.2–0.4, calib ≈1.0 GPts/s): clean median 0.066 GPts/s over 5
#: samples (span 0.061–0.076).  perf_bisect replayed the row-key across
#: r4→r5 revisions on this same host: r5 code is 1.29× FASTER than r4
#: (0.0689 vs 0.0536), so the r4→r5 artifact slide (0.114→0.087) was
#: machine environment, not code — absolute floors must therefore sit
#: well under cross-host variance.  0.8× the clean median catches a
#: halving-class regression without tripping on host differences.
ISO3DFD_128_JIT_FLOOR = 0.052

#: Cube wavefront-speedup floor (was the lone ad-hoc guard in
#: tools/bench_suite.py).  perf_bisect across r3→r5 on one host:
#: r3-end 1.35×, r4 0.93× (the skew mis-engage halving — exactly what
#: this floor exists to catch), r5 profit-gate 1.67×, HEAD 1.74× — the
#: recorded 2.07×→1.82× "residue" is host-environmental; HEAD is the
#: best revision on equal footing (docs/performance.md "cube wavefront
#: residue").  1.5 catches the r4-class halving without flagging
#: cross-host variance.
CUBE_WAVEFRONT_FLOOR = 1.5

#: PROVISIONAL floor for the 2-D-vs-1-D skew speedup ratio
#: (bench_suite ``skew2d-speedup``).  No hardware history yet (relay
#: down since r4); the failure class it guards is the r4 cube lesson
#: one dim up — the outer-dim carry mis-engaging and HALVING the rate
#: instead of helping.  0.75 flags a halving-class slide while
#: tolerating the CPU proxy's margin-model inversion (interpret-mode
#: carries are copies, not DMA savings).  Re-base from clean TPU rows
#: once tpu_session banks them.
SKEW2D_SPEEDUP_FLOOR = 0.75

#: PROVISIONAL floor for the shard_pallas overlapped-halo-exchange A/B
#: (bench_suite ``sp-overlap-speedup``: core/shell split forced on vs
#: the serial chunk→exchange schedule).  The failure class: the split
#: costs two extra kernel launches and a merge per K-group, so a
#: schedule bug (or a core region mis-shrunk to nothing) shows as the
#: ratio collapsing.  TPU-scoped: the CPU proxy measures 0.68–0.81×
#: BY CONSTRUCTION (ppermutes are same-host memcpys — there is no
#: collective latency to hide, only the extra launches to pay), so a
#: floor there would alarm on every suite run; the CPU arm stays
#: under the trailing-median backstop instead.  Re-base from clean
#: TPU rows once tpu_session banks the overlap_ab stage — on hardware
#: the ratio is the point of the feature and should clear 1.
SP_OVERLAP_SPEEDUP_FLOOR = 0.95

#: PROVISIONAL floor for the trapezoid-vs-skew/uniform A/B
#: (bench_suite ``trap-speedup``: the two-phase trapezoid/diamond
#: tiling forced via -trapezoid against the same config with the knob
#: off).  The failure class: the parallel-grid win is megacore
#: partitioning + the 2r fetch margin, and both evaporate if the
#: diamond fill passes grow past their model (band recompute is real
#: work) — a collapse of this ratio means the gate engaged where it
#: should not.  TPU-scoped: the CPU interpret proxy has no megacore
#: (cores=2 credit is pure overhead there) and serializes the fill
#: passes, so the proxy ratio sits below 1 BY CONSTRUCTION and only
#: the trailing-median backstop guards that arm.  Re-base from clean
#: TPU rows once tpu_session banks the trapezoid_ab stage.
TRAP_SPEEDUP_FLOOR = 0.9

#: PROVISIONAL floor for the ensemble batched-vs-sequential A/B
#: (bench_suite ``ensembleN-speedup``: N instances as one vmapped
#: program vs N fresh contexts each paying its own trace+lower+
#: compile).  The win has two legs — compile amortization (one build
#: for N members) and device saturation on small domains — and the
#: CPU proxy only measures the FIRST leg (an 8-wide vmap on one core
#: runs the math serially), so compile dominating at 64³ makes ≥2×
#: honest there.  The failure class this guards: the vmapped build
#: silently degrading to the sequential fallback (batched_reason
#: set), which pays N compiles again and collapses the ratio toward
#: 1.  CPU-scoped: re-base on hardware once tpu_session banks the
#: ensemble_ab stage — on a real chip the saturation leg should push
#: the ratio well past the compile-only bound.
ENSEMBLE_SPEEDUP_FLOOR = 2.0

#: PROVISIONAL floor for the serving-layer batched A/B (bench_suite
#: ``serve-batchN-speedup``: N tenants through ONE StencilServer —
#: submit-all-then-wait-all, co-batched by the scheduler window — vs N
#: fresh solo contexts each paying its own compile).  Same
#: compile-amortization leg as the ensemble floor, MINUS the serving
#: machinery's per-request tax (worker handoff, pre-request snapshots,
#: journal rows, sanity gating), which is exactly what this row
#: tracks: a regression here with a healthy ensemble row means the
#: server got expensive, not the batching.  CPU-scoped; re-base on
#: hardware.
SERVE_BATCH_SPEEDUP_FLOOR = 1.5

#: PROVISIONAL floor for the cross-PROFILE shape-bucket serving A/B
#: (bench_suite ``serve-bucket8-speedup``: 8 tenants across >=3
#: DISTINCT geometries through ONE server with bucketing ON — all
#: hosted on one bucket-rung profile, co-batched masked — vs the same
#: traffic with bucketing OFF, where each geometry pays its own
#: prepared profile and only same-geometry requests share a batch).
#: The win is compile amortization across geometries (G profiles ->
#: 1) plus occupancy (three small batches -> one big one); the CPU
#: proxy measures the compile leg.  Bit-identity against solo oracles
#: gates the row before any timing counts.  The failure class this
#: guards: open-session silently declining feasible tenants (every
#: session "exact" -> the arms converge toward 1x) or the masked
#: vmapped path degrading to sequential members.  CPU-scoped;
#: re-base on hardware.
SERVE_BUCKET_SPEEDUP_FLOOR = 1.5

#: PROVISIONAL floor for the cross-solution pipeline-fusion A/B
#: (bench_suite ``pipeline-fusion-speedup``: the 3-stage RTM chain —
#: forward iso wave, imaging correlation, 3-point smoothing — as ONE
#: merged program vs the host-chained schedule that round-trips every
#: binding through HBM plus host slice copies each step).  The HBM
#: model says 2× traffic for this chain (bound vars stream once
#: instead of write+read), and the chained arm additionally pays the
#: host push per binding per step, so ≥1.2× is conservative on the
#: CPU proxy where the push tax dominates.  The failure class this
#: guards: the merge silently falling back to host-chaining (fused
#: False in the ledger row) or a rewrite pessimization making the
#: merged program slower than its parts.  CPU-scoped: re-base on
#: hardware once tpu_session banks the pipeline_fusion_ab stage.
PIPELINE_FUSION_FLOOR = 1.2

#: PROVISIONAL floor for the push-memory tile-graph fusion A/B
#: (bench_suite ``pipeline-push-speedup``: the PURE rtm chain — no
#: img(t) self-read, so the merged image var is pushable — fused with
#: push ON vs the same fused program with ``-push off``, both at the
#: pallas K=1 schedule where every arm is bit-exact vs the
#: host-chained oracle).  The HBM model says the pushed var leaves
#: BOTH HBM paths (fused 20 → fused_push 16 B/pt on this chain), but
#: the CPU interpret proxy realizes only part of that as wall-clock
#: (VMEM tiles are numpy copies there), so the floor sits at parity:
#: the failure class it guards is push ENGAGING AND LOSING — a
#: pessimization where keeping the tile in VMEM costs more than the
#: round-trip it saves (extra seeding, margin recompute), which must
#: never bank as a win.  Engagement itself is asserted by the section
#: (a silent decline raises, it cannot bank 1.0×).  CPU-scoped;
#: re-base from clean TPU rows once tpu_session banks the push_ab
#: stage — on hardware the traffic drop is the point.
PIPELINE_PUSH_FLOOR = 1.0

#: PROVISIONAL floor for the device-resident bulk-serving A/B
#: (bench_suite ``serve-resident-speedup``: the same 4-session x
#: 4-item work list drained by ResidentExecutor.run_queue — one
#: device-lock hold, one end-of-queue sync, one extraction per
#: session — vs per-request scheduler dispatch).  The acceptance bar
#: is "strictly faster at occupancy >= 4"; measured CPU rows sit at
#: 4–6×.  1.5 flags the failure class — the resident path regrowing
#: per-item synchronization (a block_until_ready or host extraction
#: sneaking into the item loop) — without tripping on scheduler-window
#: jitter.  Responses are bit-gated identical across arms before the
#: row banks.  CPU-scoped; re-base on hardware.
SERVE_RESIDENT_FLOOR = 1.5

#: PROVISIONAL floor for the load harness's goodput fraction
#: (tools/load_harness.py ``load-goodput``: completed-ok responses /
#: offered requests on a seeded open-loop run, unit "x" so the
#: sentinel guards it).  The harness's deterministic --check scenario
#: offers load a 1–2 worker CPU fleet can absorb after scale-up, so a
#: healthy run completes (nearly) everything: deadline fast-fails,
#: brownout session rejections, and saturation errors all subtract
#: from goodput, which is exactly the failure class this guards — an
#: overload-control bug silently rejecting admissible traffic, or an
#: autoscaler that stops responding to pressure.  0.9 tolerates a
#: straggler request dying at harness shutdown while flagging any
#: systematic shedding.  CPU-scoped; chaos-soak runs (injected kills/
#: hangs/corruption lower goodput BY DESIGN) bank with distinct
#: ``load-soak`` keys that this pattern does not match.
LOAD_GOODPUT_FLOOR = 0.9

DEFAULT_RULES: List[GuardRule] = [
    GuardRule(name="iso3dfd-128-jit-floor",
              pattern="128^3 fp32 cpu throughput",
              floor=ISO3DFD_128_JIT_FLOOR, rel_tol=0.25,
              platforms=("cpu",)),
    GuardRule(name="cube-wavefront-floor",
              pattern="wavefront-speedup",
              floor=CUBE_WAVEFRONT_FLOOR, rel_tol=0.25),
    GuardRule(name="skew2d-speedup-floor",
              pattern="skew2d-speedup",
              floor=SKEW2D_SPEEDUP_FLOOR, rel_tol=0.25),
    GuardRule(name="sp-overlap-speedup-floor",
              pattern="sp-overlap-speedup",
              floor=SP_OVERLAP_SPEEDUP_FLOOR, rel_tol=0.25,
              platforms=("axon", "tpu")),
    GuardRule(name="trap-speedup-floor",
              pattern="trap-speedup",
              floor=TRAP_SPEEDUP_FLOOR, rel_tol=0.25,
              platforms=("axon", "tpu")),
    GuardRule(name="ensemble-speedup-floor",
              pattern="ensemble",
              floor=ENSEMBLE_SPEEDUP_FLOOR, rel_tol=0.25,
              platforms=("cpu",)),
    GuardRule(name="serve-batch-speedup-floor",
              pattern="serve-batch",
              floor=SERVE_BATCH_SPEEDUP_FLOOR, rel_tol=0.25,
              platforms=("cpu",)),
    GuardRule(name="serve-bucket-speedup-floor",
              pattern="serve-bucket",
              floor=SERVE_BUCKET_SPEEDUP_FLOOR, rel_tol=0.25,
              platforms=("cpu",)),
    GuardRule(name="pipeline-fusion-floor",
              pattern="pipeline-fusion",
              floor=PIPELINE_FUSION_FLOOR, rel_tol=0.25,
              platforms=("cpu",)),
    GuardRule(name="pipeline-push-floor",
              pattern="pipeline-push",
              floor=PIPELINE_PUSH_FLOOR, rel_tol=0.25,
              platforms=("cpu",)),
    GuardRule(name="serve-resident-floor",
              pattern="serve-resident",
              floor=SERVE_RESIDENT_FLOOR, rel_tol=0.25,
              platforms=("cpu",)),
    GuardRule(name="load-goodput-floor",
              pattern="load-goodput",
              floor=LOAD_GOODPUT_FLOOR, rel_tol=0.25,
              platforms=("cpu",)),
    # the backstop every throughput/speedup row gets: trailing clean
    # median, generous tolerance (CPU-proxy trial noise is real)
    GuardRule(name="trailing-median", rel_tol=0.35),
]


def is_clean(row: Dict) -> bool:
    """Usable as regression baseline: the row's own guard did not say
    regression/breach, the result passed the sanity guards (quarantined
    rows carry anomalous — e.g. all-zero — data whose wall-clock is
    meaningless), and the machine was not overloaded when measured."""
    if row.get("quarantined"):
        return False
    st = row.get("guard", {}).get("status", "ok")
    if st in ("regression", "breach", "anomaly"):
        return False
    prov = row.get("provenance", {})
    load = prov.get("loadavg") or []
    ncpu = prov.get("ncpu") or 0
    if load and ncpu:
        try:
            if float(load[0]) / float(ncpu) > LOAD_CLEAN_MAX:
                return False
        except (TypeError, ValueError):
            return False
    return True


def _applicable(rules: List[GuardRule], key: str,
                platform: str) -> List[GuardRule]:
    return [r for r in rules if r.matches(key, platform)]


def check_row(key: str, value: float, unit: str, platform: str,
              history: List[Dict],
              rules: Optional[List[GuardRule]] = None,
              remeasure: Optional[Callable[[], float]] = None) -> Dict:
    """Evaluate one measurement against its guards; returns the verdict
    dict stored under the row's ``guard`` field.

    ``history`` is this key's prior ledger rows (same platform, file
    order); only clean rows feed the trailing median.  On a breach,
    ``remeasure`` (when given) is called ONCE for a second sample:
    clearing → ``noise``, still breaching → ``regression``; without a
    re-measure hook the verdict stays ``breach``.
    """
    if unit not in GUARDED_UNITS:
        return {"status": "unguarded", "unit": unit}
    rules = DEFAULT_RULES if rules is None else rules
    match = _applicable(rules, key, platform)
    if not match:
        return {"status": "unguarded"}
    verdict: Dict = {"rules": [r.name for r in match]}
    baselines = {}
    for r in match:
        b = _ledger.trailing_median(history, n=r.window, accept=is_clean)
        baselines[r.name] = b
        if r.floor is not None:
            verdict["floor"] = r.floor
    bl = next((b for b in baselines.values() if b is not None), None)
    if bl is not None:
        verdict["baseline"] = round(bl, 4)
        if bl > 0:
            verdict["ratio"] = round(float(value) / bl, 4)

    def breached(v: float) -> List[str]:
        return [r.name for r in match if r.breaches(v, baselines[r.name])]

    first = breached(float(value))
    if not first:
        verdict["status"] = "ok" if bl is not None or any(
            r.floor is not None for r in match) else "no_history"
        return verdict
    verdict["breached"] = first
    if remeasure is None:
        verdict["status"] = "breach"
        return verdict
    try:
        v2 = float(remeasure())
    except Exception as e:  # noqa: BLE001 - verdict must still record
        verdict["status"] = "regression"
        verdict["remeasure_error"] = str(e)[:160]
        return verdict
    verdict["remeasured"] = round(v2, 4)
    verdict["status"] = "regression" if breached(v2) else "noise"
    return verdict


#: absolute phase-share drift (in share points, 0..1) that flags an
#: attribution row against the trailing clean median of its key — a
#: phase quietly growing from 10% to 30% of the run is exactly the
#: "where did the time go" regression the span trace exists to catch.
ATTRIBUTION_SHARE_TOL = 0.15


def check_attribution(shares: Dict[str, float], history: List[Dict],
                      tol: float = ATTRIBUTION_SHARE_TOL,
                      window: int = 5) -> Dict:
    """Guard an attribution row's per-phase SHARES against the trailing
    clean median of prior ``source: "attribution"`` rows for the same
    key.  Shares are compared absolutely (share points), not
    relatively — a 1%→3% phase tripling is noise, a 10%→30% one is a
    drift.  Verdict statuses mirror :func:`check_row`:
    ``no_history`` / ``ok`` / ``drift`` (with the offending phases and
    their medians recorded in the verdict)."""
    clean = [r for r in history if is_clean(r)][-window:]
    if not clean:
        return {"status": "no_history", "rule": "attribution-share-drift"}
    meds: Dict[str, float] = {}
    for ph in shares:
        vals = sorted(
            float((r.get("extra", {}).get("shares") or {}).get(ph, 0.0))
            for r in clean)
        meds[ph] = vals[len(vals) // 2]
    drifted = {ph: {"share": round(s, 4), "median": round(meds[ph], 4)}
               for ph, s in shares.items()
               if abs(s - meds[ph]) > tol}
    verdict: Dict = {"rule": "attribution-share-drift", "tol": tol,
                     "window": len(clean)}
    if drifted:
        verdict["status"] = "drift"
        verdict["drifted"] = drifted
    else:
        verdict["status"] = "ok"
    return verdict


def guard_and_append(key: str, value: float, unit: str, platform: str,
                     source: str, provenance: Dict,
                     rules: Optional[List[GuardRule]] = None,
                     remeasure: Optional[Callable[[], float]] = None,
                     roofline: Optional[Dict] = None,
                     extra: Optional[Dict] = None,
                     path: Optional[str] = None,
                     sanity: Optional[Dict] = None) -> Dict:
    """The one-call producer path: look up this key's history in the
    ledger, evaluate the guards (with optional re-measure), build the
    row with the verdict inside, append it, return it.

    ``sanity`` is a result-sanity verdict from
    :func:`yask_tpu.resilience.check_output`: a failed one quarantines
    the row (``quarantined: true`` + structured ``anomaly`` field,
    guard status ``anomaly``) instead of guarding it — no re-measure is
    attempted (re-timing corrupt data proves nothing) and
    :func:`is_clean` keeps it out of every trailing-median baseline.

    ``source="bisect"`` rows are excluded from the history: they replay
    HISTORICAL revisions (tools/perf_bisect.py) and must not shift the
    trailing median the current code is judged against."""
    if sanity and not sanity.get("ok", True):
        from yask_tpu.resilience import anomaly_fields
        af = anomaly_fields(sanity)
        guard = {"status": "anomaly",
                 "anomalies": af["anomaly"]["anomalies"]}
        row = _ledger.make_row(key, value, unit, platform, source,
                               provenance, guard=guard,
                               roofline=roofline, extra=extra)
        row.update(af)
        _ledger.append_row(row, path=path)
        return row
    history = [r for r in
               _ledger.read_rows(path=path, key=key, platform=platform)
               if r.get("source") != "bisect"]
    if not any(is_clean(r) for r in history):
        # fresh clone / untracked ledger: seed the baseline from the
        # committed BENCH_*.json snapshots (older than any live row)
        history = _ledger.seed_rows_from_bench(key, platform) + history
    guard = check_row(key, value, unit, platform, history, rules=rules,
                      remeasure=remeasure)
    row = _ledger.make_row(key, value, unit, platform, source,
                           provenance, guard=guard, roofline=roofline,
                           extra=extra)
    _ledger.append_row(row, path=path)
    return row
