"""Measurement provenance: the machine/load context attached to every
perf row.

The round-5 verdict found the CPU proxy regressed −24 % across the board
with *no investigation possible* because nothing recorded load context —
"possibly machine load, but that is exactly the point".  Every ledger row
now carries:

* fresh load average + CPU count (the noise axis on a shared host);
* static machine identity (CPU model, frequency governor, jax/jaxlib
  versions, platform/device kind, git SHA, env fingerprint) — cached per
  process, it cannot change mid-run;
* a calibration micro-kernel rate: a fixed pure-numpy 3-point stencil
  sweep whose throughput tracks the host's effective memory/compute
  speed, so two rows for the same key are comparable even across hosts
  ("same config, calib 0.9× → the 0.9× headline delta is the machine").

Tests stub the ``/proc``/``/sys`` roots; nothing here imports jax (the
version lookup uses importlib.metadata) so capture works even when the
backend is unusable.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import time
from typing import Dict, Optional

#: env vars whose values change jax/XLA behavior enough to make perf
#: rows non-comparable — fingerprinted (hashed) into every row.
_ENV_KEYS = ("JAX_PLATFORMS", "XLA_FLAGS", "JAX_ENABLE_X64",
             "PALLAS_AXON_POOL_IPS", "OMP_NUM_THREADS",
             "XLA_PYTHON_CLIENT_PREALLOCATE")

_CALIB_PTS = 1 << 20       # 1 Mi points per calibration sweep
_CALIB_REPS = 3

_static_cache: Dict[str, dict] = {}


def _read_first_line(path: str) -> str:
    try:
        with open(path) as f:
            return f.readline().strip()
    except OSError:
        return ""


def cpu_model(proc_root: str = "/proc") -> str:
    """`model name` from cpuinfo (first hit), '' when unavailable."""
    fallback = ""
    try:
        with open(os.path.join(proc_root, "cpuinfo")) as f:
            for line in f:
                low = line.lower()
                if ":" not in line:
                    continue
                val = line.split(":", 1)[1].strip()
                if low.startswith("model name"):
                    return val
                # ARM /proc/cpuinfo has no "model name"
                if low.startswith(("hardware", "cpu implementer")) \
                        and not fallback:
                    fallback = val
    except OSError:
        pass
    return fallback


def cpu_governor(sys_root: str = "/sys") -> str:
    return _read_first_line(os.path.join(
        sys_root, "devices/system/cpu/cpu0/cpufreq/scaling_governor"))


def loadavg(proc_root: str = "/proc") -> list:
    """[1m, 5m, 15m] load averages (prefers the stubbable proc file)."""
    line = _read_first_line(os.path.join(proc_root, "loadavg"))
    if line:
        try:
            return [float(x) for x in line.split()[:3]]
        except ValueError:
            pass
    try:
        return list(os.getloadavg())
    except (OSError, AttributeError):
        return [0.0, 0.0, 0.0]


def git_sha(repo_root: Optional[str] = None) -> str:
    """Short HEAD SHA (+ '-dirty' when the tree differs), '' off-repo."""
    root = repo_root or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=root,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, timeout=10).stdout.strip()
        if not sha:
            return ""
        dirty = subprocess.run(
            ["git", "status", "--porcelain", "--untracked-files=no"],
            cwd=root, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, timeout=10).stdout.strip()
        return sha + ("-dirty" if dirty else "")
    except Exception:
        return ""


def _pkg_version(name: str) -> str:
    try:
        from importlib.metadata import version
        return version(name)
    except Exception:
        return ""


def env_fingerprint() -> str:
    """Stable digest of the perf-relevant environment variables."""
    blob = "\n".join(f"{k}={os.environ.get(k, '')}" for k in _ENV_KEYS)
    return hashlib.sha1(blob.encode()).hexdigest()[:12]


def calibration_gpts(reps: int = _CALIB_REPS) -> float:
    """Median throughput (GPts/s) of a fixed pure-numpy 1-D 3-point
    stencil sweep — the per-row yardstick for host speed under the load
    actually present at measurement time.  Pure numpy: independent of
    jax/XLA state, a few milliseconds total."""
    import numpy as np
    a = np.linspace(0.0, 1.0, _CALIB_PTS, dtype=np.float32)
    out = np.empty_like(a)
    rates = []
    for _ in range(max(reps, 1)):
        t0 = time.perf_counter()
        np.add(a[:-2], a[2:], out=out[1:-1])
        np.add(out[1:-1], a[1:-1], out=out[1:-1])
        out[1:-1] *= np.float32(1.0 / 3.0)
        dt = time.perf_counter() - t0
        rates.append(_CALIB_PTS / max(dt, 1e-12) / 1e9)
    rates.sort()
    return round(rates[len(rates) // 2], 4)


def _static_context(proc_root: str, sys_root: str) -> dict:
    key = f"{proc_root}|{sys_root}"
    if key not in _static_cache:
        _static_cache[key] = {
            "cpu_model": cpu_model(proc_root),
            "ncpu": os.cpu_count() or 0,
            "governor": cpu_governor(sys_root),
            "jax": _pkg_version("jax"),
            "jaxlib": _pkg_version("jaxlib"),
            "git_sha": git_sha(),
            "env_fp": env_fingerprint(),
        }
    return dict(_static_cache[key])


def capture_provenance(platform: str = "", device_kind: str = "",
                       calibrate: bool = True,
                       proc_root: str = "/proc",
                       sys_root: str = "/sys") -> dict:
    """One provenance dict for a row measured *now*: static machine
    identity (cached per process) + fresh load + calibration rate.

    ``platform``/``device_kind`` come from the producer's ``yk_env``
    (importing jax here could hang on the relay — see CLAUDE.md).
    ``calibrate=False`` skips the micro-kernel (e.g. per-row refresh
    where the suite-level calibration already stands).
    """
    prov = _static_context(proc_root, sys_root)
    prov["loadavg"] = loadavg(proc_root)
    if platform:
        prov["platform"] = platform
    if device_kind:
        prov["device_kind"] = device_kind
    if calibrate:
        prov["calib_gpts"] = calibration_gpts()
    return prov
