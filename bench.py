#!/usr/bin/env python
"""Headline benchmark: iso3dfd order-16 (radius 8) single-device throughput.

Mirrors the reference harness' trial protocol (``yask_main.cpp:53-66``):
warmup (excluded, covers XLA compile), then N timed trials; report the
"mid" (median) throughput in GPts/s — the reference's primary fitness
metric (``context.cpp:449-460``, ``YaskUtils.pm:40``).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "GPts/s", "vs_baseline": N}
vs_baseline is measured against the BASELINE.md target of 500 GPts/s/chip.
"""

import json
import sys
import time


def main():
    import jax
    import numpy as np
    from yask_tpu import yk_factory

    fac = yk_factory()
    env = fac.new_env()
    platform = env.get_platform()

    # Pick the largest domain that fits; 512^3 is the reference's
    # single-device headline config (BASELINE.md).
    sizes = [512, 384, 256] if platform == "tpu" else [128]
    steps_per_trial = 10 if platform == "tpu" else 2
    trials = 3

    last_err = None
    for g in sizes:
        try:
            ctx = fac.new_solution(env, stencil="iso3dfd", radius=8)
            ctx.apply_command_line_options(f"-g {g}")
            ctx.prepare_solution()
            ctx.get_var("pressure").set_element(
                1.0, [0, g // 2, g // 2, g // 2])
            ctx.get_var("vel").set_all_elements_same(0.1)

            # Warmup: compiles the chunk and runs it once.
            ctx.run_solution(0, steps_per_trial - 1)
            ctx.clear_stats()

            rates = []
            t = steps_per_trial
            for _ in range(trials):
                t0 = time.perf_counter()
                ctx.run_solution(t, t + steps_per_trial - 1)
                dt = time.perf_counter() - t0
                t += steps_per_trial
                rates.append(g ** 3 * steps_per_trial / dt / 1e9)
            rates.sort()
            mid = rates[len(rates) // 2]

            # sanity: field stayed finite
            s = ctx.get_var("pressure").get_elements_in_slice(
                [t, g // 2 - 1, g // 2 - 1, g // 2 - 1],
                [t, g // 2 + 1, g // 2 + 1, g // 2 + 1])
            if not np.isfinite(s).all():
                raise RuntimeError("non-finite field")

            print(json.dumps({
                "metric": f"iso3dfd r=8 {g}^3 fp32 {platform} throughput",
                "value": round(mid, 3),
                "unit": "GPts/s",
                "vs_baseline": round(mid / 500.0, 4),
            }))
            return 0
        except Exception as e:  # try a smaller domain
            last_err = e
    print(json.dumps({
        "metric": "iso3dfd bench failed",
        "value": 0.0,
        "unit": "GPts/s",
        "vs_baseline": 0.0,
        "error": str(last_err)[:200],
    }))
    return 1


if __name__ == "__main__":
    sys.exit(main())
