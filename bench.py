#!/usr/bin/env python
"""Headline benchmark: iso3dfd order-16 (radius 8) single-device throughput.

Mirrors the reference harness' trial protocol (``yask_main.cpp:53-66``):
warmup (excluded, covers XLA compile), then N timed trials; report the
"mid" (median) throughput in GPts/s — the reference's primary fitness
metric (``context.cpp:449-460``, ``YaskUtils.pm:40``).

After the XLA-path measurement it opportunistically tries the fused
Pallas path (temporal fusion, K=wf_steps): the candidate is first
validated against the XLA path on a small domain, then timed; the best
mode wins. Any Pallas failure falls back to the XLA number.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "GPts/s", "vs_baseline": N}
vs_baseline is measured against the BASELINE.md target of 500 GPts/s/chip.
"""

import json
import os
import sys
import time

from yask_tpu.resilience import (Fault, anomaly_fields, check_output,
                                 guarded_call, maybe_corrupt,
                                 python_cmd, run_deadlined)


def _probe_platform(default_timeout: float = 240.0):
    """Decide the jax platform WITHOUT risking a hang in this process.

    The default backend dials a TPU relay that, when unreachable, hangs
    for minutes inside backend init — so the probe runs in a killable
    subprocess (yask_tpu.resilience.run_deadlined: process group + hard
    kill, because subprocess.run(timeout=) can block forever in
    communicate() when the backend plugin spawns a grandchild that
    keeps the pipe open).  Returns the backend name ('tpu', 'cpu', ...)
    or None when the default backend is unusable.
    """
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        return "cpu"  # explicit CPU: no probe needed, it can't hang
    cached = os.environ.get("YT_PROBED_PLATFORM")  # one probe per
    if cached is not None:                          # process tree
        return cached or None  # "" caches a failed probe
    try:
        timeout = float(os.environ.get("YT_TPU_PROBE_TIMEOUT",
                                       str(default_timeout)))
    except ValueError:
        timeout = default_timeout
    code = "import jax; print('PLATFORM=' + jax.default_backend())"
    try:
        _, out = run_deadlined(python_cmd(code), timeout,
                               site="bench.probe")
        for line in (out or "").splitlines():
            if line.startswith("PLATFORM="):
                plat = line.split("=", 1)[1].strip()
                os.environ["YT_PROBED_PLATFORM"] = plat
                return plat
    except Fault:
        os.environ["YT_PROBED_PLATFORM"] = ""  # cache the failure
        return None
    except Exception:
        pass
    return None


def _force_cpu_env():
    """Point this process firmly at the CPU backend.

    sitecustomize (relay bootstrap) may already have imported jax at
    interpreter start, in which case the env var alone is too late —
    platform choice was read at import, so also override via config.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""  # don't dial the relay
    if "jax" in sys.modules:
        import jax
        jax.config.update("jax_platforms", "cpu")


def _reexec_on_cpu():
    """Last-resort fallback: restart this script on the CPU backend.

    Needed when jax was already initialized against a half-broken TPU
    backend in this process (platform choice is sticky after init).
    """
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["YT_BENCH_NO_REEXEC"] = "1"
    os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)],
              env)


def _tpu_results_path() -> str:
    """TPU_RESULTS.jsonl location (``YT_TPU_RESULTS`` overrides — the
    fault-injection tests exercise the recording path on CPU without
    touching the real artifact)."""
    return os.environ.get("YT_TPU_RESULTS") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "TPU_RESULTS.jsonl")


def _record_tpu_result(line: dict) -> None:
    """Append a hardware-measured bench line (with timestamp) to the
    persistent log — the source for ``last_tpu_measured`` when a later
    capture falls back to CPU. Never fatal."""
    try:
        from yask_tpu.obs.tracer import stamp_trace
        rec = dict(line)
        rec["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                           time.gmtime())
        stamp_trace(rec)
        with open(_tpu_results_path(), "a") as f:
            f.write(json.dumps(rec) + "\n")
    except Exception:
        pass


def _last_tpu_result():
    """Newest END-TO-END hardware-measured record (falls back to the
    newest per-chunk microbench when no end-to-end record exists —
    chunk timings exclude host/trial overhead and are not directly
    comparable). Quarantined rows (sanity-guard anomalies: all-zero /
    non-finite fields) never surface as "last measured". Never fatal."""
    newest = newest_chunk = newest_iso_chunk = None
    try:
        with open(_tpu_results_path()) as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                rec = json.loads(ln)
                if rec.get("quarantined"):
                    continue   # anomalous data must not resurface
                m = rec.get("metric", "")
                if " chunk " in m:
                    newest_chunk = rec   # file order == time order
                    if "iso3dfd" in m:   # flagship over A/B side stencils
                        newest_iso_chunk = rec
                else:
                    newest = rec
    except Exception:
        pass
    return newest or newest_iso_chunk or newest_chunk


def build(fac, env, g, mode="jit", wf=0, radius=8):
    ctx = fac.new_solution(env, stencil="iso3dfd", radius=radius)
    ctx.apply_command_line_options(f"-g {g}")
    ctx.get_settings().mode = mode
    ctx.get_settings().wf_steps = wf
    # static preflight (default-on, -no-preflight to skip): surfaces
    # Mosaic/VMEM/race findings up front but never blocks the bench —
    # the contract line must survive even a checker bug
    from yask_tpu.checker import preflight
    if not preflight(ctx):
        print(f"bench: preflight found errors for mode={mode} "
              f"(see above); attempting the run anyway", file=sys.stderr)
    ctx.prepare_solution()
    ctx.get_var("pressure").set_element(1.0, [0, g // 2, g // 2, g // 2])
    ctx.get_var("vel").set_all_elements_same(0.1)
    return ctx


def measure(ctx, g, steps_per_trial, trials, sanity=None):
    # warmup (compile)
    ctx.run_solution(0, steps_per_trial - 1)
    rates = []
    t = steps_per_trial
    for _ in range(trials):
        t0 = time.perf_counter()
        ctx.run_solution(t, t + steps_per_trial - 1)
        dt = time.perf_counter() - t0
        t += steps_per_trial
        rates.append(g ** 3 * steps_per_trial / dt / 1e9)
    # result-sanity guard on the interior slice around the impulse
    # (nonzero after any step on a live device): all-zero / NaN fields
    # must never yield a clean throughput number.  With a ``sanity``
    # dict the verdict is returned for the caller to quarantine the row
    # (the contract line still prints, labeled ANOMALY); without one a
    # bad verdict raises, so pallas candidates and re-measures reject.
    s = ctx.get_var("pressure").get_elements_in_slice(
        [t, g // 2 - 1, g // 2 - 1, g // 2 - 1],
        [t, g // 2 + 1, g // 2 + 1, g // 2 + 1])
    s = maybe_corrupt("bench.result", s)
    verdict = check_output(s)
    if sanity is not None:
        sanity.clear()
        sanity.update(verdict)
    elif not verdict["ok"]:
        raise RuntimeError("result anomaly: "
                           + ",".join(verdict["anomalies"]))
    rates.sort()
    return rates[len(rates) // 2]


def _ckpt_ab(fac, env, g, steps_per_trial, trials, base_rate, platform,
             ddl):
    """Checkpoint-cadence overhead A/B on the jit headline config: the
    SAME build re-measured with the supervision cadence on (snapshots
    to a throwaway dir).  The ratio rides the ledger under the
    sentinel, so a hot-path regression — ``-ckpt_every 0`` must stay a
    true no-op, and the cadence cost is one device→host snapshot pull
    per N steps — is caught in the artifact, never the contract line
    (the caller isolates this whole probe)."""
    import tempfile
    from yask_tpu.perflab import capture_provenance
    from yask_tpu.perflab.sentinel import guard_and_append
    with tempfile.TemporaryDirectory(prefix="yt_ckpt_ab_") as td:
        ctx = build(fac, env, g, "jit")
        o = ctx.get_settings()
        o.ckpt_every = max(1, steps_per_trial // 2)
        o.ckpt_dir = td
        rate = guarded_call(measure, ctx, g, steps_per_trial, trials,
                            site="bench.ckpt_ab", deadline_secs=ddl)
        cadence = o.ckpt_every
        del ctx
    overhead = max(0.0, 1.0 - rate / base_rate) if base_rate > 0 else 0.0
    prov = capture_provenance(
        platform=platform,
        device_kind=(getattr(env.get_devices()[0], "device_kind", "")
                     if env.get_devices() else ""))
    guard_and_append(
        f"iso3dfd r=8 {g}^3 fp32 {platform} jit ckpt-cadence A/B",
        round(rate, 3), "GPts/s", platform, "bench", prov,
        extra={"ckpt_every": cadence,
               "baseline_gpts": round(base_rate, 3),
               "overhead_frac": round(overhead, 4)})
    return overhead


def try_pallas(fac, env, g, steps_per_trial, trials, candidates=(2, 4)):
    """Validated + timed fused-Pallas attempt; returns (rate, K) or None."""
    best = None
    small = 64
    nval = 2 * max(candidates)
    ref = None
    for K in candidates:
        try:
            # correctness gate on a small domain first (one shared jit ref)
            if ref is None:
                ref = build(fac, env, small, "jit")
                ref.run_solution(0, nval - 1)
            b = build(fac, env, small, "pallas", wf=K)
            b.run_solution(0, nval - 1)
            if ref.compare_data(b, epsilon=1e-3, abs_epsilon=1e-4):
                continue
            ctx = build(fac, env, g, "pallas", wf=K)
            rate = measure(ctx, g, steps_per_trial, trials)
            if best is None or rate > best[0]:
                # traffic model + compile cost of the kernel actually
                # benchmarked (cache_hit tells cold vs memory vs disk)
                best = (rate, K, sum(ctx.hbm_model_bytes_pp()),
                        round(ctx._compile_secs * 1000.0, 1),
                        ctx._last_cache_hit or "cold")
        except Exception:
            continue
    return best


def _run_suite_rows():
    """The BASELINE-table rows beyond the headline (cube wavefront
    speedup, ssg, awp + halo %, pallas-K2): printed as JSON lines BEFORE
    the contract line (which stays last for the driver's parser);
    ``tools/bench_suite.py`` also persists them to
    BENCH_suite_latest.json so the round artifact records the suite, not
    one number (VERDICT r2 weak 6).

    Runs under yask_tpu.resilience.run_deadlined (process-group hard
    kill) so a hung section can never forfeit the already-measured
    contract line; on deadline the rows measured before the hang are
    drained — a partial suite beats losing everything. Never fatal."""
    if os.environ.get("YT_BENCH_SUITE", "1") != "1":
        return
    try:
        budget = float(os.environ.get("YT_SUITE_BUDGET", "900"))
    except ValueError:
        budget = 900.0   # never fatal: the contract line must still print
    suite = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "tools", "bench_suite.py")
    try:
        try:
            _, out = run_deadlined([sys.executable, suite], budget,
                                   site="bench.suite")
        except Fault as f:
            out = (getattr(f, "partial_stdout", "") or "") + "\n" \
                + json.dumps({"metric": "bench_suite timeout",
                              "value": 0.0, "unit": "error",
                              "fault": f.kind})
        for line in (out or "").splitlines():
            if line.strip():
                print(line, flush=True)
    except Exception as e:
        print(json.dumps({"metric": "bench_suite failed", "value": 0.0,
                          "unit": "error", "error": str(e)[:160]}),
              flush=True)


def main():
    if _probe_platform() is None:
        # default backend unreachable (relay down): run the bench on CPU
        # rather than crashing without the contract JSON line.
        _force_cpu_env()

    import numpy as np  # noqa: F401
    from yask_tpu import yk_factory

    try:
        fac = yk_factory()
        env = fac.new_env()
        platform = env.get_platform()
    except Exception as e:
        if os.environ.get("YT_BENCH_NO_REEXEC") != "1":
            _reexec_on_cpu()  # does not return
        print(json.dumps({
            "metric": "iso3dfd bench failed (env setup)",
            "value": 0.0,
            "unit": "GPts/s",
            "vs_baseline": 0.0,
            "error": str(e)[:200],
        }))
        return 0

    on_tpu = platform == "tpu"  # yk_env normalizes axon → tpu
    sizes = [512, 384, 256] if on_tpu else [128]
    steps_per_trial = 10 if on_tpu else 2
    trials = 3

    last_err = None
    for g in sizes:
        try:
            sanity = {}
            ctx = build(fac, env, g, "jit")
            # deadline around the in-process device work: the probe only
            # proves the backend ANSWERED — a relay that dies after init
            # would otherwise hang run_solution inside this process with
            # nothing to kill it (the driver's outer timeout then loses
            # the whole artifact, not one size)
            try:
                ddl = float(os.environ.get("YT_BENCH_MEASURE_DEADLINE",
                                           "900"))
            except ValueError:
                ddl = 900.0
            rate = guarded_call(measure, ctx, g, steps_per_trial, trials,
                                site="bench.measure", deadline_secs=ddl,
                                sanity=sanity)
            mode = "jit"
            bytes_pp = sum(ctx.hbm_model_bytes_pp())
            hbm_peak = env.get_hbm_peak_bytes_per_sec()
            compile_ms = round(ctx._compile_secs * 1000.0, 1)
            cache_hit = ctx._last_cache_hit or "cold"
            del ctx
            # checkpoint-cadence overhead A/B (acceptance: ≤5% on the
            # jit headline); telemetry only — never the contract line
            try:
                _ckpt_ab(fac, env, g, steps_per_trial, trials, rate,
                         platform, ddl)
            except Exception as e:  # noqa: BLE001
                print(f"bench: ckpt A/B failed ({str(e)[:120]})",
                      file=sys.stderr)
            # interpret-mode Pallas can never beat XLA off-TPU: only try
            # the fused path on real hardware (override via env for tests)
            want_pallas = os.environ.get(
                "YT_BENCH_PALLAS", "1" if on_tpu else "0")
            if want_pallas == "1":
                # no deadline here: try_pallas isolates each K candidate
                # with its own try/except, which would swallow the alarm
                # — the site still classifies faults + takes injection
                p = guarded_call(try_pallas, fac, env, g,
                                 steps_per_trial, trials,
                                 site="bench.pallas")
                if p is not None and p[0] > rate:
                    rate, mode = p[0], f"pallas-K{p[1]}"
                    bytes_pp = p[2]   # model of the winning kernel
                    compile_ms, cache_hit = p[3], p[4]
            _run_suite_rows()
            metric = (f"iso3dfd r=8 {g}^3 fp32 {platform} "
                      f"throughput ({mode})")
            # roofline context (VERDICT r2 item 8) via the shared
            # perflab model; provenance + sentinel verdict make the
            # contract line self-explaining (an r5-style slide reads as
            # "noise" or "regression" in the artifact itself)
            from yask_tpu.perflab import capture_provenance
            from yask_tpu.perflab.roofline import roofline as _roofline
            from yask_tpu.perflab.sentinel import guard_and_append
            roof = _roofline(rate, bytes_pp, hbm_peak)
            prov = capture_provenance(
                platform=platform,
                device_kind=(getattr(env.get_devices()[0],
                                     "device_kind", "")
                             if env.get_devices() else ""))
            # re-measure hook (breach → noise-vs-regression verdict):
            # rebuild the winning configuration from scratch so the
            # second sample shares nothing with the first
            if mode == "jit":
                remeasure = lambda: measure(  # noqa: E731
                    build(fac, env, g, mode="jit"), g,
                    steps_per_trial, trials)
            else:
                K = int(mode.rsplit("K", 1)[-1])
                remeasure = lambda: measure(  # noqa: E731
                    build(fac, env, g, mode="pallas", wf=K), g,
                    steps_per_trial, trials)
            guard = {"status": "unrecorded"}
            try:
                lrow = guard_and_append(
                    metric, round(rate, 3), "GPts/s", platform, "bench",
                    prov, roofline=roof,
                    extra={"mode": mode,
                           "vs_baseline": round(rate / 500.0, 4),
                           "compile_ms": compile_ms,
                           "cache_hit": cache_hit},
                    remeasure=remeasure, sanity=sanity)
                guard = lrow["guard"]
            except Exception:
                pass  # ledger I/O must never cost the contract line
            line = {
                "metric": metric,
                "value": round(rate, 3),
                "unit": "GPts/s",
                # platform as a FIELD, not only in the metric string: a
                # CPU-fallback vs_baseline of ~0.0001 must be readable
                # as "relay was down", not a perf collapse (VERDICT r3)
                "platform": platform,
                "vs_baseline": round(rate / 500.0, 4),
                "hbm_bytes_pp": roof["hbm_bytes_pp"],
                "hbm_gbps": roof["hbm_gbps"],
                "provenance": prov,
                "guard": guard,
                # compile amortization telemetry: cold = fresh Mosaic/XLA
                # build, disk = the persistent cache paid it in an
                # earlier process (see docs/performance.md)
                "compile_ms": compile_ms,
                "cache_hit": cache_hit,
            }
            if roof.get("roofline_frac") is not None:
                line["hbm_roofline"] = roof["roofline_frac"]
            if sanity and not sanity.get("ok", True):
                # the contract line survives but labeled: an all-zero /
                # NaN field is an ANOMALY row, quarantined everywhere
                # (excluded from sentinel baselines and never surfaced
                # by _last_tpu_result)
                line.update(anomaly_fields(sanity))
            if on_tpu:
                _record_tpu_result(line)
            else:
                # Relay down at capture time: attach the most recent
                # hardware-measured result (clearly labeled, with its
                # timestamp) so the artifact still carries a TPU datum.
                prev = _last_tpu_result()
                if prev is not None:
                    line["last_tpu_measured"] = prev
            print(json.dumps(line))
            return 0
        except Exception as e:  # try a smaller domain
            last_err = e
    if platform != "cpu" and os.environ.get("YT_BENCH_NO_REEXEC") != "1":
        _reexec_on_cpu()  # every size failed on the accelerator: CPU retry
    print(json.dumps({
        "metric": "iso3dfd bench failed",
        "value": 0.0,
        "unit": "GPts/s",
        "vs_baseline": 0.0,
        "error": str(last_err)[:200],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
