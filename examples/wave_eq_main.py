"""2-D wave-equation mini-app.

Counterpart of the reference's ``src/examples/wave_eq_main.cpp``: runs the
``wave2d`` stencil from the library with a Gaussian initial displacement and
self-checks propagation + stability (example-tests analog).

Run: ``python examples/wave_eq_main.py [-g N] [-steps N]``
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from yask_tpu import yk_factory


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    g, steps = 128, 100
    i = 0
    while i < len(argv):
        if argv[i] == "-g":
            g = int(argv[i + 1]); i += 2
        elif argv[i] == "-steps":
            steps = int(argv[i + 1]); i += 2
        else:
            print(f"unknown arg {argv[i]}"); return 2

    fac = yk_factory()
    env = fac.new_env()
    ctx = fac.new_solution(env, stencil="wave2d", radius=2)
    ctx.apply_command_line_options(f"-g {g}")
    ctx.prepare_solution()

    yy, xx = np.mgrid[0:g, 0:g].astype(np.float32)
    c = g / 2.0
    u0 = np.exp(-((xx - c) ** 2 + (yy - c) ** 2) / (g / 16.0) ** 2)
    u0 = u0.astype(np.float32)
    # both retained steps start from the same displacement (zero velocity)
    ctx.get_var("u").set_elements_in_slice(u0, [0, 0, 0], [0, g-1, g-1])
    ctx.get_var("u").set_elements_in_slice(u0, [-1, 0, 0], [-1, g-1, g-1])
    ctx.get_var("c2").set_all_elements_same(0.2)  # CFL-stable (c·dt/h)²

    ctx.run_solution(0, steps - 1)
    u = ctx.get_var("u").get_elements_in_slice(
        [steps, 0, 0], [steps, g - 1, g - 1])

    assert np.isfinite(u).all(), "unstable"
    center_now = abs(float(u[g // 2, g // 2]))
    ring = float(np.abs(u[g // 2]).max())
    print(f"wave2d: {steps} steps on {g}x{g}; |u(center)|={center_now:.4f}; "
          f"max |u| on center row={ring:.4f}")
    assert ring > 1e-4, "wave vanished"
    print("wave2d example: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
