"""Parameter-sweep driver against the serving front.

Spawns ``tools/serve.py`` as a stdio child, opens N iso3dfd sessions
on ONE profile (one compiled executable serves all of them), gives
each tenant its own velocity constant + random initial pressure, and
submits the whole sweep through ``run_many`` so compatible requests
co-batch into one vmapped execution.

Self-check: every response must be BIT-identical to a solo
``run_solution`` with the same fills (float32 survives the JSON wire
exactly), and the serve journal must show batch occupancy > 1 —
otherwise the batching window never did its job.

Run: ``python examples/serve_sweep_main.py [-g N] [-steps N] [-n N]``
(CPU runs want the usual ``PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu``
prefix; the child inherits the environment.)
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from tools.serve_client import ServeClient


def solo_oracle(g: int, steps: int, vel: float, pressure):
    """The answer a lone ``run_solution`` gives for the same fills."""
    from yask_tpu import yk_factory
    from yask_tpu.serve.scheduler import extract_outputs
    fac = yk_factory()
    ctx = fac.new_solution(fac.new_env(), stencil="iso3dfd", radius=2)
    ctx.apply_command_line_options(f"-g {g} -wf_steps 2")
    ctx.prepare_solution()
    ctx.get_var("vel").set_all_elements_same(vel)
    ctx.get_var("pressure").set_elements_in_slice(
        pressure, [0, 0, 0, 0], [0, g - 1, g - 1, g - 1])
    ctx.run_solution(0, steps - 1)
    return extract_outputs(ctx)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    g, steps, n = 16, 4, 6
    i = 0
    while i < len(argv):
        if argv[i] == "-g":
            g = int(argv[i + 1]); i += 2
        elif argv[i] == "-steps":
            steps = int(argv[i + 1]); i += 2
        elif argv[i] == "-n":
            n = int(argv[i + 1]); i += 2
        else:
            print(f"unknown arg {argv[i]}"); return 2

    vels = [0.3 + 0.1 * k for k in range(n)]        # the sweep axis
    seeds = [np.random.RandomState(100 + k)
             .rand(1, g, g, g).astype(np.float32) for k in range(n)]

    with ServeClient.spawn(stderr=sys.stderr) as c:
        sids = []
        for k in range(n):
            sid = c.open(stencil="iso3dfd", radius=2, g=g,
                         mode="jit", wf=2)
            c.fill(sid, "vel", vels[k])
            c.fill_slice(sid, "pressure", seeds[k],
                         [0, 0, 0, 0], [0, g - 1, g - 1, g - 1])
            sids.append(sid)
        resps = c.run_many([(sid, 0, steps - 1) for sid in sids],
                           timeout=600)
        m = c.metrics()

    occupancies = sorted(r["batch"] for r in resps)
    print(f"serve sweep: {n} tenants x {steps} steps on {g}^3; "
          f"occupancies={occupancies}; "
          f"p50 total {m['p50_total_ms']:.1f} ms")

    bad = 0
    for k, r in enumerate(resps):
        assert r["status"] == "ok", f"tenant {k}: {r}"
        want = solo_oracle(g, steps, vels[k], seeds[k])
        for var, arr in want.items():
            if not np.array_equal(arr, r["outputs"][var]):
                bad += 1
                print(f"tenant {k} var {var}: NOT bit-identical "
                      f"to the solo oracle")
    assert bad == 0, f"{bad} mismatched outputs"
    assert max(occupancies) > 1, \
        "no request ever co-batched — the window never grouped anything"
    print("serve sweep example: PASS "
          f"(all {n} tenants bit-identical to solo run_solution)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
