"""RTM-mini: a 3-stage producer→consumer pipeline fused into one program.

The classic reverse-time-migration shape, miniaturized: a forward
acoustic wave (``rtm_fwd``, order-2r Laplacian), an imaging
correlation that accumulates the squared wavefield (``rtm_img``), and
a 27-point box smoothing of the image (``rtm_smooth``).  Run as three
separate solutions, the wavefield and the raw image each round-trip
HBM — and host copies — between stages every step.  Declared as a
``SolutionPipeline`` with two bindings::

    img.fwd_in    <- fwd.pressure     (the fresh wavefield)
    smooth.img_in <- img.img          (the fresh image)

the three stages merge into ONE program per mode: 2× less modeled
HBM traffic (48 → 24 bytes/point fp32) and zero host pushes.

Self-check: the fused arm must be BIT-identical to the host-chained
oracle (per step, per stage, bindings pushed through host interior
copies) on the same temporal schedule, and the plan's structured
``reasons`` must record the engage decision.

Run: ``python examples/rtm_pipeline_main.py [-g N] [-steps N]
[-mode jit|pallas] [-radius N]`` (CPU runs want the usual
``PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu`` prefix.)
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def make_pipe(env, g, mode, radius, fuse):
    from yask_tpu.ops.pipeline import SolutionPipeline, rtm_chain
    stages, bindings = rtm_chain(radius=radius)
    pipe = SolutionPipeline(env, stages, bindings)
    pipe.apply_command_line_options(f"-g {g} -mode {mode} -wf_steps 1")
    pipe.prepare(fuse=fuse)
    # a localized source burst in the wavefield, every ring slot
    v = pipe.get_var("fwd", "pressure")
    rng = np.random.RandomState(42)
    src = (rng.rand(g, g, g).astype(np.float32) - 0.5) * 0.1
    for t in range(v.get_first_valid_step_index(),
                   v.get_last_valid_step_index() + 1):
        v.set_elements_in_slice(src, [t, 0, 0, 0],
                                [t, g - 1, g - 1, g - 1])
    return pipe


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    g, steps, mode, radius = 24, 6, "jit", 2
    i = 0
    while i < len(argv):
        if argv[i] == "-g":
            g = int(argv[i + 1]); i += 2
        elif argv[i] == "-steps":
            steps = int(argv[i + 1]); i += 2
        elif argv[i] == "-mode":
            mode = argv[i + 1]; i += 2
        elif argv[i] == "-radius":
            radius = int(argv[i + 1]); i += 2
        else:
            print(f"unknown arg {argv[i]}"); return 2

    from yask_tpu import yk_factory
    env = yk_factory().new_env()

    fused = make_pipe(env, g, mode, radius, fuse=True)
    chained = make_pipe(env, g, mode, radius, fuse=False)
    engage = [r for r in fused.plan()["reasons"]
              if r["code"] == "pipeline-engaged"]
    print(f"plan: fused={fused.fused} "
          f"({engage[0]['msg'] if engage else 'no engage reason'})")

    # first window warms both arms (compile + cache); second is timed
    fused.run(0, steps - 1)
    chained.run(0, steps - 1)
    t0 = time.perf_counter()
    fused.run(steps, 2 * steps - 1)
    t_fused = time.perf_counter() - t0
    t0 = time.perf_counter()
    chained.run(steps, 2 * steps - 1)
    t_chain = time.perf_counter() - t0

    bad = fused.compare(chained)   # epsilon=0: exact bit-equality
    from yask_tpu.ops.pipeline import pipeline_hbm_model
    m = pipeline_hbm_model(fused)
    print(f"rtm3 r={radius} {g}^3 {mode}: fused {t_fused:.3f}s, "
          f"host-chained {t_chain:.3f}s "
          f"({t_chain / max(t_fused, 1e-12):.2f}x), "
          f"hbm model {m['chained_bytes_pp']}->{m['fused_bytes_pp']} "
          f"bytes/pt ({m['ratio']:.1f}x)")
    if bad:
        print(f"FAIL: fused arm differs from the host-chained oracle "
              f"({bad} mismatching elements)")
        return 1
    img = fused._interior("smooth", "smooth",
                          fused.get_var("smooth", "smooth")
                          .get_last_valid_step_index())
    print(f"self-check OK: bit-identical arms; final image "
          f"max={float(np.abs(img).max()):.3e}")
    fused.end()
    chained.end()
    return 0


if __name__ == "__main__":
    sys.exit(main())
