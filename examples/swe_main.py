"""Shallow-water-equation mini-app.

Counterpart of the reference's ``src/examples/swe_main.cpp`` (654 LoC):
drives the kernel API end-to-end — env → solution → domain sizes → prepare →
init vars (dam-break column) → step loop → slice extraction — and
self-checks conservation, like the example-tests target.

Run: ``python examples/swe_main.py [-g N] [-steps N] [-plot]``
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from yask_tpu import yk_factory


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    g, steps, plot = 64, 50, False
    it = iter(range(len(argv)))
    i = 0
    while i < len(argv):
        if argv[i] == "-g":
            g = int(argv[i + 1]); i += 2
        elif argv[i] == "-steps":
            steps = int(argv[i + 1]); i += 2
        elif argv[i] == "-plot":
            plot = True; i += 1
        else:
            print(f"unknown arg {argv[i]}"); return 2

    fac = yk_factory()
    env = fac.new_env()
    ctx = fac.new_solution(env, stencil="swe2d")
    ctx.apply_command_line_options(f"-g {g}")
    ctx.prepare_solution()

    # Dam break: a raised column of water in a calm pool.
    h0 = np.ones((g, g), dtype=np.float32)
    cx = g // 2
    r = g // 8
    yy, xx = np.mgrid[0:g, 0:g]
    h0[(xx - cx) ** 2 + (yy - cx) ** 2 < r * r] = 2.0
    ctx.get_var("h").set_elements_in_slice(h0, [0, 0, 0], [0, g-1, g-1])
    ctx.get_var("hu").set_all_elements_same(0.0)
    ctx.get_var("hv").set_all_elements_same(0.0)
    # dt/dx chosen for CFL stability with c = sqrt(g·h) ≈ sqrt(2·2)
    ctx.get_var("lam").set_element(0.2, [])
    ctx.get_var("grav").set_element(1.0, [])

    mass0 = float(h0.sum())
    ctx.run_solution(0, steps - 1)
    h = ctx.get_var("h").get_elements_in_slice(
        [steps, 0, 0], [steps, g - 1, g - 1])

    # Self-checks (the reference example-tests style): finite field and
    # near-conserved interior mass (LxF loses a little at open borders).
    assert np.isfinite(h).all(), "field went non-finite"
    mass = float(h.sum())
    drift = abs(mass - mass0) / mass0
    print(f"swe2d: {steps} steps on {g}x{g}; mass drift {drift:.3%}; "
          f"h in [{h.min():.3f}, {h.max():.3f}]")
    assert drift < 0.2, "mass drifted implausibly"
    assert h.std() > 1e-3, "wave did not propagate"

    if plot:
        # crude ASCII contour
        q = np.linspace(h.min(), h.max(), 5)
        chars = " .:*#"
        for row in h[:: max(g // 32, 1)]:
            print("".join(
                chars[int(np.searchsorted(q, v, side="right")) - 1]
                for v in row[:: max(g // 64, 1)]))
    print("swe2d example: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
