"""Shallow-water-equation mini-app.

Counterpart of the reference's ``src/examples/swe_main.cpp`` (654 LoC,
``/root/reference/src/examples/swe_main.cpp:80-562``): drives the whole
kernel API the way that app does — factory → env (ranks, barriers,
debug/trace routing) → app-level command-line parser (+ the library's
own option help) → solution introspection (domain/rank/block geometry,
element bytes) → var init by interior slices → validation *and*
benchmark modes (the latter with auto-tune + stats, the reference's
``-bench``) → per-interval step loop with slice extraction → manual
halo exchange → checkpoint/resume → ``end_solution`` / ``finalize``.

Validation mode self-checks conservation and wave propagation
(the reference checks against its MATLAB twin's invariants);
benchmark mode reports points/s from ``yk_stats``.

Run: ``python examples/swe_main.py [-g N] [-steps N] [-bench]
[-nr_x N] [-nr_y N] [-plot] [-yask_debug] [-help]``
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from yask_tpu import yk_factory
from yask_tpu.utils.cli import CommandLineParser


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)

    # ---- app options, via the same typed parser the library CLIs use
    # (reference: command_line_parser in swe_main.cpp:104-127) ----------
    class Opts:
        g = 64
        steps = 50
        interval = 0        # steps per run_solution call (0 = all)
        bench = False
        plot = False
        nr_x = 1
        nr_y = 1
        yask_debug = False
        help = False
        checkpoint = ""

    o = Opts()
    parser = CommandLineParser()
    parser.add_int_option("g", "Global domain size per dim.", o, "g")
    parser.add_int_option("steps", "Total steps to run.", o, "steps")
    parser.add_int_option("interval", "Steps per run_solution interval "
                          "(0 = one interval).", o, "interval")
    parser.add_bool_option("bench", "Benchmark mode: auto-tune + stats "
                           "instead of validation.", o, "bench")
    parser.add_bool_option("plot", "ASCII contour of the final height "
                           "field.", o, "plot")
    parser.add_int_option("nr_x", "Mesh ranks along x.", o, "nr_x")
    parser.add_int_option("nr_y", "Mesh ranks along y.", o, "nr_y")
    parser.add_bool_option("yask_debug", "Enable library trace output.",
                           o, "yask_debug")
    parser.add_bool_option("help", "Print help.", o, "help")
    parser.add_string_option("checkpoint", "Round-trip a checkpoint "
                             "through this path mid-run.", o,
                             "checkpoint")
    rem = parser.parse_args(argv)
    if rem:
        print(f"unknown args: {rem}")
        return 2

    fac = yk_factory()
    env = fac.new_env()
    rank = env.get_rank_index()
    if o.yask_debug:
        env.set_trace_enabled(True)

    ctx = fac.new_solution(env, stencil="swe2d")
    if o.help:
        # app options, then the library's own (reference swe_main
        # prints both via print_usage + get_command_line_help)
        import sys as _sys
        parser.print_help(_sys.stdout)
        print(ctx.get_command_line_help())
        return 0

    g, steps = o.g, o.steps
    ctx.apply_command_line_options(f"-g {g}")
    if o.nr_x * o.nr_y > 1:
        ctx.set_num_ranks("x", o.nr_x)
        ctx.set_num_ranks("y", o.nr_y)
        ctx.get_settings().mode = "shard_map"
    if o.bench:
        ctx.get_settings().do_auto_tune = True
    ctx.prepare_solution()

    # ---- geometry introspection (reference swe_main.cpp:361-404:
    # overall vs rank domain, block sizes, element bytes) ---------------
    dims = ctx.get_domain_dim_names()
    lo = [ctx.get_first_rank_domain_index(d) for d in dims]
    hi = [ctx.get_last_rank_domain_index(d) for d in dims]
    print(f"swe2d '{ctx.get_name()}' on {env.get_num_ranks()} device(s); "
          f"overall {[ctx.get_overall_domain_size(d) for d in dims]}, "
          f"rank {rank} owns {list(zip(lo, hi))}, "
          f"blocks {[ctx.get_block_size(d) for d in dims]}, "
          f"{ctx.get_element_bytes()} B/elem")

    # ---- init: dam break (raised column in a calm pool), written by
    # interior-coordinate slices exactly like the reference's buffer
    # writes (swe_main.cpp:431-470) -------------------------------------
    h0 = np.ones((g, g), dtype=np.float32)
    cx = g // 2
    r = g // 8
    yy, xx = np.mgrid[0:g, 0:g]
    h0[(xx - cx) ** 2 + (yy - cx) ** 2 < r * r] = 2.0
    ctx.get_var("h").set_elements_in_slice(h0, [0, 0, 0], [0, g-1, g-1])
    ctx.get_var("hu").set_all_elements_same(0.0)
    ctx.get_var("hv").set_all_elements_same(0.0)
    # dt/dx chosen for CFL stability with c = sqrt(g·h) ≈ sqrt(2·2)
    ctx.get_var("lam").set_element(0.2, [])
    ctx.get_var("grav").set_element(1.0, [])
    env.global_barrier()

    # a manual ghost refresh is legal any time (reference exchange_halos)
    ctx.exchange_halos()

    mass0 = float(h0.sum())
    interval = o.interval if o.interval > 0 else steps
    t = 0
    probe = []   # wave height at the domain center after each interval
    while t < steps:
        t1 = min(t + interval, steps)
        ctx.run_solution(t, t1 - 1)
        t = t1
        probe.append(float(ctx.get_var("h").get_element([t, cx, cx])))
        if o.checkpoint and t < steps:
            # mid-run checkpoint round-trip (npz/orbax aux subsystem)
            ctx.save_checkpoint(o.checkpoint)
            ctx.load_checkpoint(o.checkpoint)

    h = ctx.get_var("h").get_elements_in_slice(
        [steps, 0, 0], [steps, g - 1, g - 1])

    if o.bench:
        st = ctx.get_stats()
        print(f"bench: {st.get_num_steps_done()} steps, "
              f"{st.get_pts_per_sec() / 1e6:.1f} MPts/s "
              f"(auto-tuned wf_steps={ctx.get_settings().wf_steps})")
        ctx.reset_auto_tuner(False)
    else:
        # ---- self-checks (the reference example-tests style) ----------
        assert np.isfinite(h).all(), "field went non-finite"
        mass = float(h.sum())
        drift = abs(mass - mass0) / mass0
        print(f"swe2d: {steps} steps on {g}x{g}; mass drift {drift:.3%}; "
              f"h in [{h.min():.3f}, {h.max():.3f}]")
        assert drift < 0.2, "mass drifted implausibly"
        assert h.std() > 1e-3, "wave did not propagate"
        # the dam-break column collapses: center height must fall, and
        # the rarefaction must reach the quarter-domain ring
        assert probe[-1] < 2.0, "dam column never collapsed"
        ring = float(h[cx, cx + g // 4])
        assert abs(ring - 1.0) > 1e-4, "wave never reached r=g/4"

    if o.plot:
        # crude ASCII contour, normalized so even a nearly-flat field
        # shows its structure
        chars = " .:*#"
        hf = np.nan_to_num(h, nan=0.0, posinf=0.0, neginf=0.0)
        span = max(float(hf.max() - hf.min()), 1e-12)
        lv = np.clip(((hf - hf.min()) / span * (len(chars) - 1)) + 0.5,
                     0, len(chars) - 1).astype(int)
        for row in lv[:: max(g // 32, 1)]:
            print("".join(chars[v] for v in row[:: max(g // 64, 1)]))

    ctx.end_solution()
    env.finalize()
    print("swe2d example: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
