"""Distributed iso3dfd mini-app: the multi-chip scaling recipe.

Counterpart of the reference's MPI-launched kernel runs (``yask.sh
-ranks N``, ``src/kernel/yask_main.cpp`` under ``mpirun``): decomposes an
acoustic wavefield over every available device with the ``shard_pallas``
path — ghost pads sized radius×K, one ppermute exchange per K fused
steps — seeds a point source, advances, and self-checks propagation,
stability, and cross-mode agreement with ``shard_map``.

Run on hardware:  ``python examples/distributed_iso3dfd_main.py -g 256``
Run anywhere:     ``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
                    python examples/distributed_iso3dfd_main.py``
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from yask_tpu import yk_factory


def build(fac, env, mode, g, radius, wf, nx, ny):
    ctx = fac.new_solution(env, stencil="iso3dfd", radius=radius)
    ctx.apply_command_line_options(f"-g {g} -wf_steps {wf} -measure_halo")
    ctx.get_settings().mode = mode
    ctx.set_num_ranks("x", nx)
    ctx.set_num_ranks("y", ny)
    ctx.prepare_solution()
    ctx.get_var("pressure").set_element(1.0, [0, g // 2, g // 2, g // 2])
    ctx.get_var("vel").set_all_elements_same(0.08)
    return ctx


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    g, steps, radius, wf = 64, 16, 2, 2
    i = 0
    while i < len(argv):
        if argv[i] == "-g":
            g = int(argv[i + 1]); i += 2
        elif argv[i] == "-steps":
            steps = int(argv[i + 1]); i += 2
        elif argv[i] == "-radius":
            radius = int(argv[i + 1]); i += 2
        elif argv[i] == "-wf_steps":
            wf = int(argv[i + 1]); i += 2
        else:
            print(f"usage: {sys.argv[0]} [-g N] [-steps N] [-radius R] "
                  f"[-wf_steps K]")
            return 2

    fac = yk_factory()
    env = fac.new_env()
    ndev = env.get_num_ranks()
    # the library's TPU-first compact factorization (minor dim whole)
    from yask_tpu.parallel.decomp import factorize_rank_grid
    grid = factorize_rank_grid(ndev, ["x", "y", "z"])
    nx, ny = grid["x"], grid["y"]
    print(f"iso3dfd on {env.get_platform()} x {ndev} device(s): "
          f"mesh {nx}x{ny}, g={g}^3, radius {radius}, K={wf}")

    ctx = build(fac, env, "shard_pallas", g, radius, wf, nx, ny)
    ctx.run_solution(0, steps - 1)
    st = ctx.get_stats()
    print(f"throughput: {st.get_pts_per_sec() / 1e9:.4g} GPts/s, "
          f"halo fraction: "
          f"{100 * st.get_halo_secs() / max(st.get_elapsed_secs(), 1e-12):.3g}%")

    field = ctx.get_var("pressure").get_elements_in_slice(
        [steps, 0, 0, 0], [steps, g - 1, g - 1, g - 1])
    assert np.isfinite(field).all(), "field diverged"
    spread = np.count_nonzero(np.abs(field) > 1e-12)
    assert spread > 100, f"wave did not propagate (spread {spread})"

    # cross-mode check: the explicit-exchange path must agree
    twin = build(fac, env, "shard_map", g, radius, 0, nx, ny)
    twin.run_solution(0, steps - 1)
    bad = ctx.compare_data(twin, epsilon=1e-3, abs_epsilon=1e-4)
    assert bad == 0, f"{bad} mismatches vs shard_map"
    print(f"self-check passed: finite, spread {spread} points, "
          "shard_pallas == shard_map")
    return 0


if __name__ == "__main__":
    sys.exit(main())
