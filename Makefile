# Top-level build orchestration (counterpart of the reference's GNU-make
# driver; the device "build" is XLA tracing at runtime, so make targets
# cover the native library, tests, benches, and docs artifacts).

PY ?= python
TEST_ENV ?= PALLAS_AXON_POOL_IPS=

.PHONY: all native capi test test-fast scratch-tests boundary-tests \
        stages-tests mode-tests bench perfcheck faultcheck commcheck \
        cachecheck servecheck obscheck telemetrycheck examples clean \
        list-stencils lint check conformance conformance-quick loadcheck \
        pushcheck

all: native test

native:
	$(MAKE) -C yask_tpu/native

capi:
	$(MAKE) -C yask_tpu/native capi

test:
	$(TEST_ENV) $(PY) -m pytest tests/ -q

test-fast:
	$(TEST_ENV) $(PY) -m pytest tests/ -q -x -k "not stencil_validates"

# focused suites (reference scratch-tests/boundary-tests/stages-tests,
# src/kernel/Makefile:1186-1192)
scratch-tests:
	$(TEST_ENV) $(PY) -m pytest tests/ -q -k "scratch"

boundary-tests:
	$(TEST_ENV) $(PY) -m pytest tests/ -q -k "boundary"

stages-tests:
	$(TEST_ENV) $(PY) -m pytest tests/ -q -k "stages or stage"

mode-tests:
	$(TEST_ENV) $(PY) -m pytest tests/test_modes.py tests/test_pallas.py -q

bench:
	$(PY) bench.py

# repo-specific AST rules always run; ruff runs when installed (the
# container does not ship it — the config in pyproject.toml is for
# hosts that do)
lint:
	$(PY) tools/repo_lint.py
	@if command -v ruff >/dev/null 2>&1; then \
		ruff check .; \
	else \
		echo "ruff not installed; skipped (repo_lint ran)"; \
	fi

# the persistent AOT compile cache end-to-end: digest/memo/disk units,
# the cross-process reuse acceptance test (second process lowers ZERO
# times), eviction bounds, corrupt-entry and injected cache.load /
# cache.store fault fallback (see docs/performance.md)
cachecheck: lint
	$(TEST_ENV) JAX_PLATFORMS=cpu $(PY) -m pytest \
		tests/test_cache.py tests/test_ensemble.py -q

# the serving layer end-to-end on the CPU mesh: the multi-tenant
# acceptance path (two prepared stencils, 8 concurrent tenants,
# bit-identity + occupancy > 1 + warm-restart zero lowerings), the
# injected serve.run degradation ladder, sanity quarantine on release,
# journal schema, the SERVE-* checker rules, shape-bucket co-batching
# bit-identity, streaming/preemption, and the warm-cache worker fleet
# (see docs/serving.md)
servecheck: lint
	$(TEST_ENV) JAX_PLATFORMS=cpu $(PY) -m pytest \
		tests/test_serve.py tests/test_serve_buckets.py \
		tests/test_fleet.py -q

# the observability spine: tracer no-op guarantee (YT_TRACE unset =>
# bit-identical run, no file), span nesting/attrs, metrics percentile
# parity with the old server quantiles, end-to-end trace_id joins
# across journal/ledger/trace artifacts, Perfetto export validity,
# trace compaction bounds (see docs/observability.md)
obscheck: lint
	$(TEST_ENV) JAX_PLATFORMS=cpu $(PY) -m pytest \
		tests/test_obs.py -q

# the telemetry plane over the obs spine: fleet snapshot merging
# (pooled histogram samples, never averaged percentiles), Prometheus
# exposition + name stability, SLO burn-rate breach/non-breach
# windows, the measured-vs-modeled attribution join on a traced run,
# and the no-op guarantee with YT_TRACE unset (see
# docs/observability.md)
telemetrycheck: lint
	$(TEST_ENV) JAX_PLATFORMS=cpu $(PY) -m pytest \
		tests/test_telemetry.py -q

# seeded deterministic elastic-fleet closed loop on CPU: latency-burn
# spike -> journaled scale_up -> warm spawn (zero lowerings) ->
# admission recovery -> idle drain scale_down with sessions migrated
# zero-lost (see docs/serving.md "Autoscaling"; the chaos soak and
# trace replay are the slow-marked pytest side of the same harness)
loadcheck: lint
	$(TEST_ENV) JAX_PLATFORMS=cpu $(PY) tools/load_harness.py --check

# push-memory tile-graph fusion + device-resident bulk serving: the
# eligibility oracle, pallas push bit-equality vs the host-chained
# oracle, plan_only byte pin, PIPELINE-PUSH-* checker rules, tuner
# push A/B, the resident-queue bit-identity/journal/fault-site
# acceptance, and the push matrix axis (see docs/performance.md
# "Push-memory tile-graph fusion")
pushcheck: lint
	$(TEST_ENV) JAX_PLATFORMS=cpu $(PY) -m pytest \
		tests/test_pipeline.py tests/test_resident.py -q
	$(TEST_ENV) JAX_PLATFORMS=cpu $(PY) -m pytest \
		tests/test_config_matrix.py -q -k "pipeline"

# static checker over the flagship configs: Mosaic legality, VMEM
# feasibility (incl. the round-3 spill-OOM class), races, explain.
# See docs/checking.md; nonzero exit on any error-severity finding.
check: cachecheck servecheck obscheck telemetrycheck conformance-quick \
       loadcheck pushcheck
	$(TEST_ENV) JAX_PLATFORMS=cpu $(PY) -m yask_tpu.checker \
		-stencil iso3dfd -radius 8 -g 256 -mode pallas -wf_steps 2
	$(TEST_ENV) JAX_PLATFORMS=cpu $(PY) -m yask_tpu.checker -all_stencils

# differential checker-soundness harness (docs/checking.md): random
# solution+config per seed, static verdict vs an actual pallas-vs-jit
# run on the interpret host; nonzero exit on any unsound/overstrict
# disagreement (minimized repro JSONs land under tools/logs/).
# `check` carries the 16-seed quick subset; the 200-seed sweep is the
# pre-merge / nightly gate.
conformance:
	$(TEST_ENV) JAX_PLATFORMS=cpu $(PY) tools/checker_conformance.py

conformance-quick: lint
	$(TEST_ENV) JAX_PLATFORMS=cpu $(PY) tools/checker_conformance.py --quick

# quick bench rows through the regression sentinel: nonzero exit on an
# unexplained breach (see tools/perfcheck.py; ledger = PERF_LEDGER.jsonl)
perfcheck: lint
	$(TEST_ENV) JAX_PLATFORMS=cpu $(PY) tools/perfcheck.py

# the resilience layer end-to-end on the CPU mesh: fault taxonomy /
# guards / journal / checkpoint units plus the acceptance paths —
# injected relay-drop resume, all-zero quarantine, SIGKILL-mid-run
# kill-resume (same-mode and cross-mode restore), the injected
# device-hang pallas → jit degradation ladder, and the fleet failover
# chaos acceptance (chaos-killed worker → checkpoint-backed session
# failover bit-identical to an uninterrupted twin, exactly-once
# in-flight retry, heartbeat-miss replacement — see docs/resilience.md)
faultcheck: lint
	$(TEST_ENV) JAX_PLATFORMS=cpu $(PY) -m pytest \
		tests/test_resilience.py tests/test_fleet_failover.py -q

# the communication scheduler end-to-end on the CPU mesh: plan
# construction, coalescing/order bit-equality, corner composition,
# measured collective rounds, COMM-* checker rules, multihost launcher
# (see docs/performance.md "ICI/DCN comm scheduling")
commcheck: lint
	$(TEST_ENV) JAX_PLATFORMS=cpu $(PY) -m pytest \
		tests/test_comm_schedule.py -q

examples:
	$(TEST_ENV) JAX_PLATFORMS=cpu $(PY) examples/swe_main.py
	$(TEST_ENV) JAX_PLATFORMS=cpu $(PY) examples/wave_eq_main.py

list-stencils:
	$(TEST_ENV) JAX_PLATFORMS=cpu $(PY) -m yask_tpu.compiler -list

clean:
	$(MAKE) -C yask_tpu/native clean
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null || true
