# Top-level build orchestration (counterpart of the reference's GNU-make
# driver; the device "build" is XLA tracing at runtime, so make targets
# cover the native library, tests, benches, and docs artifacts).

PY ?= python
TEST_ENV ?= PALLAS_AXON_POOL_IPS=

.PHONY: all native capi test test-fast scratch-tests boundary-tests \
        stages-tests mode-tests bench perfcheck examples clean \
        list-stencils

all: native test

native:
	$(MAKE) -C yask_tpu/native

capi:
	$(MAKE) -C yask_tpu/native capi

test:
	$(TEST_ENV) $(PY) -m pytest tests/ -q

test-fast:
	$(TEST_ENV) $(PY) -m pytest tests/ -q -x -k "not stencil_validates"

# focused suites (reference scratch-tests/boundary-tests/stages-tests,
# src/kernel/Makefile:1186-1192)
scratch-tests:
	$(TEST_ENV) $(PY) -m pytest tests/ -q -k "scratch"

boundary-tests:
	$(TEST_ENV) $(PY) -m pytest tests/ -q -k "boundary"

stages-tests:
	$(TEST_ENV) $(PY) -m pytest tests/ -q -k "stages or stage"

mode-tests:
	$(TEST_ENV) $(PY) -m pytest tests/test_modes.py tests/test_pallas.py -q

bench:
	$(PY) bench.py

# quick bench rows through the regression sentinel: nonzero exit on an
# unexplained breach (see tools/perfcheck.py; ledger = PERF_LEDGER.jsonl)
perfcheck:
	$(TEST_ENV) JAX_PLATFORMS=cpu $(PY) tools/perfcheck.py

examples:
	$(TEST_ENV) JAX_PLATFORMS=cpu $(PY) examples/swe_main.py
	$(TEST_ENV) JAX_PLATFORMS=cpu $(PY) examples/wave_eq_main.py

list-stencils:
	$(TEST_ENV) JAX_PLATFORMS=cpu $(PY) -m yask_tpu.compiler -list

clean:
	$(MAKE) -C yask_tpu/native clean
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null || true
