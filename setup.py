"""Package installer (counterpart of the reference's setup.py, which builds
the compiler + SWIG bindings on install; here the native host library builds
lazily on first use via yask_tpu.native)."""

from setuptools import find_packages, setup

setup(
    name="yask_tpu",
    version="0.1.0",
    description=("TPU-native stencil-computation framework: stencil DSL "
                 "compiler + JAX/XLA/Pallas kernel runtime with device-mesh "
                 "domain decomposition"),
    packages=find_packages(include=["yask_tpu", "yask_tpu.*"]),
    package_data={"yask_tpu.native": ["host.cpp", "Makefile"]},
    python_requires=">=3.10",
    install_requires=["jax", "numpy"],
    extras_require={"orbax": ["orbax-checkpoint"]},
    entry_points={
        "console_scripts": [
            "yask-tpu=yask_tpu.main:main",
            "yask-tpu-compiler=yask_tpu.compiler.__main__:main",
        ],
    },
)
