"""Element-size / dtype coverage (reference real_bytes=4|8 builds +
bf16 as the TPU-native half precision)."""

import numpy as np
import pytest

from yask_tpu import yk_factory
from yask_tpu.compiler.solution_base import create_solution


@pytest.fixture(scope="module")
def env():
    return yk_factory().new_env()


def run_heat(env, elem_bytes, g=12):
    sb = create_solution("3axis", radius=1)
    sb.get_soln().set_element_bytes(elem_bytes)
    ctx = yk_factory().new_solution(env, sb)
    ctx.apply_command_line_options(f"-g {g}")
    ctx.prepare_solution()
    ctx.get_var("A").set_elements_in_seq(0.1)
    ctx.run_solution(0, 2)
    return ctx.get_var("A").get_elements_in_slice(
        [3, 0, 0, 0], [3, g - 1, g - 1, g - 1])


def test_bf16(env):
    import jax.numpy as jnp
    a16 = run_heat(env, 2)
    a32 = run_heat(env, 4)
    assert a16.dtype == jnp.bfloat16
    # bf16 has ~3 decimal digits; averaging stays close
    np.testing.assert_allclose(a16.astype(np.float64),
                               a32.astype(np.float64), rtol=0.05, atol=0.05)


def test_fp32_default(env):
    a = run_heat(env, 4)
    assert a.dtype == np.float32


def test_invalid_elem_bytes():
    from yask_tpu.utils.exceptions import YaskException
    sb = create_solution("3axis", radius=1)
    with pytest.raises(YaskException):
        sb.get_soln().set_element_bytes(3)
