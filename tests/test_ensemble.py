"""Ensemble batching (yask_tpu/runtime/ensemble.py): a batched run
must produce, per member, the same bits as that member run alone
(vmap adds a leading axis, never changes per-lane arithmetic); the
feasibility verdict is a single definition; member() swaps the active
RunState; a failed vmapped build degrades to sequential members."""

import numpy as np
import pytest

from yask_tpu import yk_factory
from yask_tpu.runtime.ensemble import (BATCHED_MODES, EnsembleRun,
                                       ensemble_feasible)
from yask_tpu.utils.exceptions import YaskException

G = 16
STEPS = 4   # two wf=2 chunks


@pytest.fixture(scope="module")
def env():
    return yk_factory().new_env()


def make_ctx(env, mode, i=None, wf=2, extra=""):
    """One prepared iso3dfd context; ``i`` selects that member's
    initial condition (None = leave init_solution_vars-free zeros so
    seeding is fully controlled here)."""
    ctx = yk_factory().new_solution(env, stencil="iso3dfd", radius=2)
    ctx.apply_command_line_options(f"-g {G} -wf_steps {wf} {extra}")
    ctx.get_settings().mode = mode
    ctx.prepare_solution()
    ctx.get_var("vel").set_all_elements_same(0.5)
    if i is not None:
        seed_member(ctx, i)
    return ctx


def seed_member(ctx, i):
    rng = np.random.RandomState(100 + i)
    arr = (rng.rand(G, G, G).astype(np.float32) - 0.5) * 0.1
    ctx.get_var("pressure").set_elements_in_slice(
        arr, [0, 0, 0, 0], [0, G - 1, G - 1, G - 1])


def state_snapshot(ctx):
    return {n: [np.asarray(a) for a in ring]
            for n, ring in ctx._state.items()}


def assert_states_equal(a, b, label):
    for n in a:
        for s, (x, y) in enumerate(zip(a[n], b[n])):
            assert np.array_equal(x, y), \
                f"{label}: var {n} slot {s} differs " \
                f"(maxdiff {np.abs(x - y).max()})"


def run_ensemble(env, mode, n):
    ctx = make_ctx(env, mode, i=0)
    ens = ctx.new_ensemble(n)
    ctx.get_var("vel").set_all_elements_same(0.5)  # member 0 re-seeded
    seed_member(ctx, 0)
    for i in range(1, n):
        with ens.member(i) as c:
            c.get_var("vel").set_all_elements_same(0.5)
            seed_member(c, i)
    ens.run(0, STEPS - 1)
    return ctx, ens


@pytest.mark.parametrize("mode", BATCHED_MODES)
def test_batched_bit_identical_to_sequential(env, mode):
    n = 3
    seq = []
    for i in range(n):
        c = make_ctx(env, mode, i=i)
        c.run_solution(0, STEPS - 1)
        seq.append(state_snapshot(c))
        del c
    ctx, ens = run_ensemble(env, mode, n)
    assert ens.batched_reason == "", ens.batched_reason
    for i in range(n):
        with ens.member(i) as c:
            assert_states_equal(seq[i], state_snapshot(c),
                                f"{mode} member {i}")
            assert c._cur_step == STEPS
            assert c._steps_done == STEPS


def test_member_swap_isolation(env):
    ctx = make_ctx(env, "jit", i=0)
    before = ctx.get_var("pressure").get_element([0, 4, 4, 4])
    ens = ctx.new_ensemble(2)
    with ens.member(1) as c:
        # fresh member states are zero-filled, distinct from member 0
        assert c.get_var("pressure").get_element([0, 4, 4, 4]) == 0.0
        c.get_var("pressure").set_element(3.25, [0, 4, 4, 4])
        assert c.get_var("pressure").get_element([0, 4, 4, 4]) == 3.25
    # member 0 (the context's original state) is untouched
    assert ctx.get_var("pressure").get_element([0, 4, 4, 4]) == before
    assert before != 3.25
    with ens.member(1) as c:
        assert c.get_var("pressure").get_element([0, 4, 4, 4]) == 3.25


def test_feasibility_single_definition(env):
    ctx = make_ctx(env, "jit")
    assert ensemble_feasible(ctx) == (True, "")
    ctx.get_settings().mode = "ref"
    ctx._mode = "ref"
    ok, why = ensemble_feasible(ctx)
    assert not ok and "oracle" in why
    for mode in ("sharded", "shard_map", "shard_pallas"):
        ctx._mode = mode
        ok, why = ensemble_feasible(ctx)
        assert not ok and "mesh" in why


def test_infeasible_mode_raises_with_reason(env):
    ctx = make_ctx(env, "ref")
    with pytest.raises(YaskException, match="oracle"):
        ctx.new_ensemble(2)
    ctx2 = make_ctx(env, "jit")
    with pytest.raises(YaskException, match=">= 1"):
        EnsembleRun(ctx2, 0)


def test_settings_knob_feeds_new_ensemble(env):
    ctx = make_ctx(env, "jit", extra="-ensemble 3")
    assert ctx.get_settings().ensemble == 3
    ens = ctx.new_ensemble()   # size from the knob
    assert ens.n == 3


def test_masked_sub_domain_bit_identical_to_solo(env):
    """A member hosted as a masked sub-domain of a larger geometry
    produces, over its own domain, the same bits as a solo run at that
    geometry — the serve-side shape-bucketing contract.  Full-domain
    members co-batching with it stay exact too."""
    sub = 12
    solo_sub = yk_factory().new_solution(env, stencil="iso3dfd",
                                         radius=2)
    solo_sub.apply_command_line_options(f"-g {sub} -wf_steps 2")
    solo_sub.get_settings().mode = "jit"
    solo_sub.prepare_solution()
    solo_sub.get_var("vel").set_all_elements_same(0.5)
    rng = np.random.RandomState(100)
    arr = (rng.rand(sub, sub, sub).astype(np.float32) - 0.5) * 0.1
    solo_sub.get_var("pressure").set_elements_in_slice(
        arr, [0, 0, 0, 0], [0, sub - 1, sub - 1, sub - 1])
    solo_sub.run_solution(0, STEPS - 1)

    solo_full = make_ctx(env, "jit", i=1)
    solo_full.run_solution(0, STEPS - 1)
    full_snap = state_snapshot(solo_full)

    ctx = make_ctx(env, "jit")
    # member 0: the 12^3 tenant (vel fill strays over the whole bucket
    # on purpose — the initial-state mask must zero the stray region)
    ctx.get_var("pressure").set_elements_in_slice(
        arr, [0, 0, 0, 0], [0, sub - 1, sub - 1, sub - 1])
    ens = EnsembleRun(ctx, 2,
                      sub_domains=[dict(x=sub, y=sub, z=sub), None])
    assert ens.masked
    with ens.member(1) as c:
        c.get_var("vel").set_all_elements_same(0.5)
        seed_member(c, 1)
    ens.run(0, STEPS - 1)
    assert ens.batched_reason == "", ens.batched_reason

    got = np.asarray(ctx.get_var("pressure").get_elements_in_slice(
        [STEPS, 0, 0, 0], [STEPS, sub - 1, sub - 1, sub - 1]))
    want = np.asarray(solo_sub.get_var("pressure").get_elements_in_slice(
        [STEPS, 0, 0, 0], [STEPS, sub - 1, sub - 1, sub - 1]))
    assert np.array_equal(got, want), \
        f"masked member diverged (maxdiff {np.abs(got - want).max()})"
    with ens.member(1) as c:
        assert_states_equal(full_snap, state_snapshot(c),
                            "full-domain co-member")


def test_masked_sub_domain_requires_jit(env):
    ctx = make_ctx(env, "pallas")
    with pytest.raises(YaskException, match="mask"):
        EnsembleRun(ctx, 2, sub_domains=[dict(x=12, y=12, z=12), None])


def test_vmapped_failure_degrades_to_sequential(env, monkeypatch):
    n = 2
    seq = []
    for i in range(n):
        c = make_ctx(env, "jit", i=i)
        c.run_solution(0, STEPS - 1)
        seq.append(state_snapshot(c))
        del c
    ctx = make_ctx(env, "jit", i=0)
    ens = ctx.new_ensemble(n)
    with ens.member(1) as c:
        c.get_var("vel").set_all_elements_same(0.5)
        seed_member(c, 1)

    def boom(start, nsteps):
        raise RuntimeError("no batching rule for prim")
    monkeypatch.setattr(ens, "_run_batched", boom)
    ens.run(0, STEPS - 1)   # must not raise
    assert "no batching rule" in ens.batched_reason
    for i in range(n):
        with ens.member(i) as c:
            assert_states_equal(seq[i], state_snapshot(c),
                                f"degraded member {i}")
