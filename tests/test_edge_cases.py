"""Edge-case battery: user step-alloc overrides, reversed run ranges,
negative-step tracing, multiple writers with overlapping conditions."""

import os

import numpy as np
import pytest

from yask_tpu import yk_factory
from yask_tpu.compiler.solution import yc_factory


@pytest.fixture(scope="module")
def env():
    return yk_factory().new_env()


def test_user_step_alloc_override(env):
    soln = yc_factory().new_solution("alloc_override")
    t = soln.new_step_index("t")
    x = soln.new_domain_index("x")
    u = soln.new_var("u", [t, x])
    u.set_step_alloc_size(4)   # keep 4 time levels live
    u(t + 1, x).EQUALS(0.5 * (u(t, x - 1) + u(t, x + 1)))
    ctx = yk_factory().new_solution(env, soln)
    ctx.apply_command_line_options("-g 16")
    ctx.prepare_solution()
    assert len(ctx._state["u"]) == 4
    ctx.get_var("u").set_elements_in_seq(0.1)
    ctx.run_solution(0, 5)
    # steps 3..6 retained with alloc 4
    v = ctx.get_var("u")
    for tt in (3, 4, 5, 6):
        v.get_element([tt, 0])
    with pytest.raises(Exception):
        v.get_element([2, 0])


def test_reversed_range_argument_order(env):
    a = yk_factory().new_solution(env, stencil="3axis", radius=1)
    a.apply_command_line_options("-g 10")
    a.prepare_solution()
    a.get_var("A").set_elements_in_seq(0.1)
    a.run_solution(3, 0)     # same as (0, 3)
    b = yk_factory().new_solution(env, stencil="3axis", radius=1)
    b.apply_command_line_options("-g 10")
    b.prepare_solution()
    b.get_var("A").set_elements_in_seq(0.1)
    b.run_solution(0, 3)
    assert a.compare_data(b) == 0


def test_reverse_time_trace_negative_steps(env, tmp_path):
    ctx = yk_factory().new_solution(env, stencil="test_reverse_2d")
    ctx.apply_command_line_options("-g 8")
    ctx.prepare_solution()
    ctx.get_var("A").set_elements_in_seq(0.1)
    ctx.set_trace_dir(str(tmp_path / "tr"))
    # reverse stepping evaluates t = 2, 1, 0 → writes steps 1, 0, -1
    ctx.run_solution(0, 2)
    files = sorted(os.listdir(tmp_path / "tr"))
    assert "step_1.npz" in files and "step_-1.npz" in files
    from yask_tpu.tools.analyze_trace import compare_traces
    assert compare_traces(str(tmp_path / "tr"), str(tmp_path / "tr")) is None


def test_overlapping_condition_writers_last_wins(env):
    soln = yc_factory().new_solution("overlap_writers")
    t = soln.new_step_index("t")
    x = soln.new_domain_index("x")
    u = soln.new_var("u", [t, x])
    u(t + 1, x).EQUALS(1.0)
    u(t + 1, x).EQUALS(2.0).IF_DOMAIN(x < 8)
    u(t + 1, x).EQUALS(3.0).IF_DOMAIN(x < 4)   # overlaps the previous
    for mode in ("jit", "ref"):
        ctx = yk_factory().new_solution(env, soln)
        ctx.apply_command_line_options("-g 16")
        ctx.get_settings().mode = mode
        ctx.prepare_solution()
        ctx.run_solution(0, 0)
        got = ctx.get_var("u").get_elements_in_slice([1, 0], [1, 15])
        want = np.array([3.0] * 4 + [2.0] * 4 + [1.0] * 8, np.float32)
        np.testing.assert_array_equal(got, want)
