"""Tests for the common substrate: CLI parser, FD coefficients, outputs,
exceptions (reference api/unit tests for src/common)."""

import math
import os

import pytest

from yask_tpu.utils.cli import CommandLineParser
from yask_tpu.utils.exceptions import YaskException
from yask_tpu.utils.fd_coeff import (
    get_center_fd_coefficients,
    get_forward_fd_coefficients,
    get_backward_fd_coefficients,
    get_arbitrary_fd_coefficients,
)
from yask_tpu.utils.idx_tuple import IdxTuple
from yask_tpu.utils.output import yask_output_factory


class Cfg:
    def __init__(self):
        self.flag = False
        self.n = 1
        self.rate = 0.5
        self.name = "a"
        self.names = []
        self.sizes = IdxTuple(x=0, y=0)


def make_parser(cfg):
    p = CommandLineParser()
    p.add_bool_option("flag", "A flag.", cfg, "flag")
    p.add_int_option("n", "An int.", cfg, "n")
    p.add_float_option("rate", "A float.", cfg, "rate")
    p.add_string_option("name", "A string.", cfg, "name")
    p.add_string_list_option("names", "A list.", cfg, "names")
    p.add_idx_option("s", "Sizes.", cfg, "sizes")
    return p


def test_parser_types_and_leftovers():
    cfg = Cfg()
    p = make_parser(cfg)
    rest = p.parse_args(["-flag", "-n", "7", "-rate", "0.25", "-name", "bob",
                         "-names", "a,b,c", "-s", "64", "-s_y", "32",
                         "positional", "-unknown", "v"])
    assert cfg.flag is True and cfg.n == 7 and cfg.rate == 0.25
    assert cfg.name == "bob" and cfg.names == ["a", "b", "c"]
    assert cfg.sizes["x"] == 64 and cfg.sizes["y"] == 32
    assert rest == ["positional", "-unknown", "v"]


def test_parser_bool_negation_and_errors():
    cfg = Cfg()
    p = make_parser(cfg)
    p.parse_args(["-flag"])
    assert cfg.flag
    p.parse_args(["-no-flag"])
    assert not cfg.flag
    with pytest.raises(YaskException):
        p.parse_args(["-n"])          # missing value
    with pytest.raises(YaskException):
        p.parse_args(["-n", "abc"])   # bad int
    help_text = p.print_help()
    assert "-[no-]flag" in help_text and "-s <val>" in help_text


def test_fd_center_second_derivative():
    # r=1: the classic [1, -2, 1]
    c = get_center_fd_coefficients(2, 1)
    assert c == pytest.approx([1.0, -2.0, 1.0])
    # r=2: [-1/12, 4/3, -5/2, 4/3, -1/12]
    c = get_center_fd_coefficients(2, 2)
    assert c == pytest.approx([-1 / 12, 4 / 3, -5 / 2, 4 / 3, -1 / 12])


def test_fd_first_derivative_forms():
    assert get_center_fd_coefficients(1, 1) == pytest.approx([-0.5, 0, 0.5])
    assert get_forward_fd_coefficients(1, 1) == pytest.approx([-1.0, 1.0])
    assert get_backward_fd_coefficients(1, 1) == pytest.approx([-1.0, 1.0])
    # staggered 4th-order: ±1/24, ∓9/8 pattern
    c = get_arbitrary_fd_coefficients(1, 0.0, [-1.5, -0.5, 0.5, 1.5])
    assert c == pytest.approx([1 / 24, -9 / 8, 9 / 8, -1 / 24])


def test_fd_errors():
    with pytest.raises(YaskException):
        get_center_fd_coefficients(2, 0)
    with pytest.raises(YaskException):
        get_arbitrary_fd_coefficients(3, 0.0, [0.0, 1.0])  # too few points


def test_outputs(tmp_path):
    fac = yask_output_factory()
    s = fac.new_string_output()
    s.write("hello")
    assert s.get_string() == "hello"
    s.discard()
    assert s.get_string() == ""
    f = fac.new_file_output(str(tmp_path / "o.txt"))
    f.write("data")
    f.close()
    assert (tmp_path / "o.txt").read_text() == "data"
    fac.new_null_output().write("dropped")


def test_exception_accretion():
    e = YaskException("base")
    e.add_message(" more")
    assert e.get_message() == "base more"
    assert "more" in str(e)
