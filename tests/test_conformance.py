"""Differential checker-soundness harness: the tier-1 slice.

``tools/checker_conformance.py`` compares the static checker's verdict
against what actually happens on the interpret host; ``make
conformance`` runs the full 200-seed sweep.  Tier-1 keeps:

* the 16-seed ``--quick`` subset (one param per seed, so a regression
  names the seed that caught it — replay with
  ``python tools/checker_conformance.py --replay <repro json>``);
* the planner↔checker byte-equality pin: the ``tile_bytes`` the vmem
  pass reports in its ``VMEM-OK`` detail must equal the ``tile_bytes``
  of the chunk the runtime actually builds at the same budget —
  the "one code path, the model cannot drift" invariant, asserted
  down to the byte;
* generator determinism + a forced agreement-by-refusal case.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import checker_conformance as conf  # noqa: E402

from yask_tpu import yk_factory
from yask_tpu.checker import run_checks


@pytest.fixture(scope="module")
def env():
    return yk_factory().new_env()


# ------------------------------------------------------------- quick
@pytest.mark.parametrize("seed", range(conf.QUICK_SEEDS))
def test_quick_seed_agrees(env, seed):
    """Static and dynamic verdicts agree on every quick-subset seed."""
    res = conf.run_case(env, conf.gen_config(seed))
    assert res["verdict"].startswith("agree"), (
        f"seed {seed} {res['verdict']}: static={res['static']} "
        f"dynamic={res['dynamic']}")


# --------------------------------------------------------- generator
def test_gen_config_deterministic_and_replayable():
    """Same seed → identical config, and the config survives a JSON
    round trip (the repro files depend on both)."""
    for seed in (0, 7, 1234):
        a = conf.gen_config(seed)
        b = conf.gen_config(seed)
        assert a == b
        assert json.loads(json.dumps(a)) == a
        assert a["schema"] == conf.SCHEMA


def test_quick_subset_covers_features():
    """The 16 quick seeds exercise a non-trivial feature mix — if the
    generator's distribution shifts, this names what went dark."""
    cfgs = [conf.gen_config(s) for s in range(conf.QUICK_SEEDS)]
    on = {f for c in cfgs for f, v in c["features"].items() if v}
    assert len(on) >= 4, f"quick subset only covers {sorted(on)}"
    assert {c["ndims"] for c in cfgs} == {2, 3}
    assert any(c["wf"] > 1 for c in cfgs)


def test_forced_refusal_is_agreement(env):
    """A var missing the minor dim: the mosaic pass must flag it AND
    the pallas mode must refuse — agreement by predicted refusal, the
    error arm of the taxonomy."""
    cfg = conf.gen_config(3)
    cfg["features"] = {f: False for f in conf._FEATURES}
    cfg["features"]["partial_no_minor"] = True
    res = conf.run_case(env, cfg)
    assert res["verdict"] == "agree-error", res
    assert not res["static"]["clean"]
    assert res["static"]["rules"], "refusal must carry rule ids"


# ------------------------------------------------- byte-equality pin
def _configured(env, vmem_mb):
    ctx = yk_factory().new_solution(env, stencil="iso3dfd", radius=4)
    ctx.apply_command_line_options("-g 32")
    o = ctx.get_settings()
    o.mode = "pallas"
    o.wf_steps = 2
    o.vmem_budget_mb = vmem_mb
    return ctx


def test_checker_tile_bytes_matches_runtime(env):
    """The vmem pass's VMEM-OK ``tile_bytes`` equals the executed
    chunk's ``tiling["tile_bytes"]`` at the same explicit budget.  Both
    come from ``build_pallas_chunk`` (plan_only vs real build) — this
    pins that they STAY one code path, byte for byte."""
    from yask_tpu.runtime.init_utils import init_solution_vars

    report = run_checks(_configured(env, 64), passes=("vmem",))
    oks = [d for d in report.diagnostics if d.rule == "VMEM-OK"]
    assert oks, [d.rule for d in report.diagnostics]
    checked = oks[0].detail["tile_bytes"]
    assert checked > 0

    ctx = _configured(env, 64)
    ctx.prepare_solution()
    init_solution_vars(ctx)
    ctx.run_solution(0, 1)
    tilings = [t for t in ctx._pallas_tiling.values() if t]
    assert tilings, "pallas run recorded no tiling"
    built = tilings[0]["tile_bytes"]
    assert built == checked, (
        f"checker modeled {checked} B/tile but the runtime built "
        f"{built} B/tile — plan_only and the real build diverged")
    # same blocks too, not just a byte coincidence
    ok_block = list(oks[0].detail["block"])
    assert list(tilings[0]["block"]) == ok_block
