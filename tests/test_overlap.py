"""Overlapped halo exchange for shard_pallas (core/shell split of the
fused K-group): the overlap arm must be BIT-identical to the serial
schedule (``compare_data(epsilon=0)``) and agree with the jit oracle in
every engaged configuration — K>1, 2-D meshes, skew-engaged, remainder
groups — while the auto gate must reject rank domains < 2·hK with the
serial fallback, and forcing ``on`` on an infeasible geometry must
raise.  Also covers the resident slice-API fast path (open item riding
this round): all-interior slice reads/writes must ride the
device-resident ring without materializing the padded state.
"""

import numpy as np
import pytest

from yask_tpu import yk_factory
from yask_tpu.utils.exceptions import YaskException


@pytest.fixture(scope="module")
def env():
    e = yk_factory().new_env()
    if e.get_num_ranks() < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    return e


def _mk(env, mode, ovx="auto", wf=2, g=(32, 8, 16), radius=2,
        ranks=(("x", 2),), spans=((0, 3),)):
    from yask_tpu.runtime.init_utils import init_solution_vars
    ctx = yk_factory().new_solution(env, stencil="iso3dfd", radius=radius)
    gx, gy, gz = g
    ctx.apply_command_line_options(f"-g_x {gx} -g_y {gy} -g_z {gz}")
    s = ctx.get_settings()
    s.mode = mode
    if mode in ("pallas", "shard_pallas"):
        s.wf_steps = wf
        s.overlap_exchange = ovx
        for d, n in ranks:
            ctx.set_num_ranks(d, n)
    ctx.prepare_solution()
    init_solution_vars(ctx)
    for a, b in spans:
        ctx.run_solution(a, b)
    return ctx


def _tiling(ctx):
    til = ctx.get_stats().get_tiling()
    assert til is not None
    return til


_oracles = {}


def _oracle(env, g, radius, spans=((0, 3),)):
    key = (g, radius, spans)
    if key not in _oracles:
        _oracles[key] = _mk(env, "jit", g=g, radius=radius, spans=spans)
    return _oracles[key]


# ---- engaged configurations: bitwise on == off, both match jit ---------

def test_engaged_k2_matches_serial_and_oracle(env):
    # lsize_x = 16 ≥ 2·hK = 8 (r=2, K=2) → auto engages
    on = _mk(env, "shard_pallas", "on")
    off = _mk(env, "shard_pallas", "off")
    til = _tiling(on)
    assert til["overlap_exchange"] is True
    assert "x" in til["overlap_core"]
    assert _tiling(off)["overlap_exchange"] is False
    assert on.compare_data(off, epsilon=0.0, abs_epsilon=0.0) == 0
    assert on.compare_data(_oracle(env, (32, 8, 16), 2),
                           epsilon=1e-3, abs_epsilon=1e-4) == 0


def test_auto_arm_engages_and_matches(env):
    auto = _mk(env, "shard_pallas", "auto")
    assert _tiling(auto)["overlap_exchange"] is True
    off = _mk(env, "shard_pallas", "off")
    assert auto.compare_data(off, epsilon=0.0, abs_epsilon=0.0) == 0


def test_overlap_remainder_group(env):
    # 5 steps under K=2 → two full groups + a 1-step remainder group:
    # a single fused step has no core compute window, so the schedule
    # runs it whole on post-exchange state (recorded reason) and the
    # bit-equality with the serial arm must survive the mixed schedule
    spans = ((0, 4),)
    on = _mk(env, "shard_pallas", "on", spans=spans)
    off = _mk(env, "shard_pallas", "off", spans=spans)
    til = _tiling(on)
    assert any(r.get("code") == "overlap_rem_unsplit"
               for r in til["overlap_reasons"])
    assert on.compare_data(off, epsilon=0.0, abs_epsilon=0.0) == 0
    assert on.compare_data(_oracle(env, (32, 8, 16), 2, spans),
                           epsilon=1e-3, abs_epsilon=1e-4) == 0


def test_overlap_split_remainder_group(env):
    # 5 steps under K=3 → one full group + a 2-step remainder group
    # that DOES re-derive the core/shell split (rem ≥ 2)
    spans = ((0, 4),)
    on = _mk(env, "shard_pallas", "on", wf=3, spans=spans)
    off = _mk(env, "shard_pallas", "off", wf=3, spans=spans)
    til = _tiling(on)
    assert til["overlap_exchange"] is True
    assert not any(r.get("code") == "overlap_rem_unsplit"
                   for r in til["overlap_reasons"])
    assert on.compare_data(off, epsilon=0.0, abs_epsilon=0.0) == 0


def test_overlap_2d_mesh_sublane_alignment(env):
    # y is the sublane dim: core bounds snap to 8-multiples, so the y
    # split needs lsize_y = 24 (lo=8, hi=16); x keeps unit alignment
    g, ranks = (32, 48, 16), (("x", 2), ("y", 2))
    on = _mk(env, "shard_pallas", "on", g=g, ranks=ranks)
    off = _mk(env, "shard_pallas", "off", g=g, ranks=ranks)
    til = _tiling(on)
    assert til["overlap_exchange"] is True
    assert set(til["overlap_core"]) == {"x", "y"}
    assert on.compare_data(off, epsilon=0.0, abs_epsilon=0.0) == 0
    assert on.compare_data(_oracle(env, g, 2),
                           epsilon=1e-3, abs_epsilon=1e-4) == 0


def test_overlap_skew_engaged(env):
    # r=8 K=2 engages the skewed wavefront (stream radius % sublane
    # tile == 0) AND the split: lsize_x = 36 ≥ 2·hK = 32 + alignment
    g = (72, 48, 32)
    on = _mk(env, "shard_pallas", "on", g=g, radius=8)
    off = _mk(env, "shard_pallas", "off", g=g, radius=8)
    til = _tiling(on)
    assert til["skew"] is True
    assert til["overlap_exchange"] is True
    assert on.compare_data(off, epsilon=0.0, abs_epsilon=0.0) == 0
    assert on.compare_data(_oracle(env, g, 8),
                           epsilon=1e-3, abs_epsilon=1e-4) == 0


# ---- the auto gate: small rank domains must reject, not corrupt --------

def test_auto_gate_rejects_small_domain(env):
    # lsize_x = 6 < 2·hK = 8: auto must fall back to the serial
    # schedule (and say why), and the answer must still be right
    g, ranks = (24, 8, 16), (("x", 4),)
    auto = _mk(env, "shard_pallas", "auto", g=g, ranks=ranks)
    til = _tiling(auto)
    assert til["overlap_exchange"] is False
    assert any("overlap" in r.get("code", "")
               for r in til["overlap_reasons"])
    assert auto.compare_data(_oracle(env, g, 2),
                             epsilon=1e-3, abs_epsilon=1e-4) == 0


def test_forced_on_infeasible_raises(env):
    with pytest.raises(YaskException, match="overlap"):
        _mk(env, "shard_pallas", "on", g=(24, 8, 16), ranks=(("x", 4),))


def test_single_step_groups_never_split(env):
    # K=1 groups are one fused step: nothing to hide an exchange
    # under — auto stays serial (with a reason), forcing "on" raises
    auto = _mk(env, "shard_pallas", "auto", wf=1)
    til = _tiling(auto)
    assert til["overlap_exchange"] is False
    assert any("single-step" in r.get("cause", "")
               for r in til["overlap_reasons"])
    with pytest.raises(YaskException, match="overlap"):
        _mk(env, "shard_pallas", "on", wf=1)


# ---- resident slice fast path (device-resident shard state) ------------

def test_resident_slice_fast_path(env):
    ctx = _mk(env, "shard_pallas", "auto")
    v = ctx.get_var("pressure")
    assert ctx._resident is not None
    # all-interior box: must ride the resident ring, no materialize
    box = ([3, 4, 0, 2], [3, 27, 7, 13])
    a_fast = v.get_elements_in_slice(*box)
    assert ctx._resident is not None
    # interior write stays resident too
    v.set_elements_in_slice(a_fast * 2.0, *box)
    assert ctx._resident is not None
    b_fast = v.get_elements_in_slice(*box)
    assert np.array_equal(b_fast, a_fast * 2.0)
    v.set_elements_in_slice(a_fast, *box)
    # pad-touching box: falls back to the strict materializing path
    pad = v.get_elements_in_slice([3, -1, 0, 0], [3, 0, 0, 0])
    assert ctx._resident is None
    assert pad[0].item() == 0.0   # ghost pads are identically zero
    # the strict path must agree with what the fast path returned
    a_strict = v.get_elements_in_slice(*box)
    assert np.array_equal(a_strict, a_fast)


# ---- region= builds under the pipelined write-back (r10 shell slabs) ----
#
# The overlap schedule's core/shell chunks are region-restricted builds;
# the output-DMA pipeline (use_pipe_out) stages their writes through
# parity-doubled VMEM tiles that retire two grid steps later.  A
# region build changes the grid span and the write windows, so the
# combination gets direct bit-equality coverage here: the region cells
# of a restricted chunk must match the full build EXACTLY, with the
# pipeline engaged on both sides.

def _mk_single(env, g=(32, 48, 16), radius=2, wf=2):
    from yask_tpu.runtime.init_utils import init_solution_vars
    ctx = yk_factory().new_solution(env, stencil="iso3dfd", radius=radius)
    gx, gy, gz = g
    ctx.apply_command_line_options(f"-g_x {gx} -g_y {gy} -g_z {gz}")
    s = ctx.get_settings()
    s.mode = "pallas"
    s.wf_steps = wf
    ctx.prepare_solution()
    init_solution_vars(ctx)
    return ctx


def _region_bit_equal(prog, out_full, out_reg, region, extent, wf):
    """Region-interior cells of every written ring slot must agree to
    the last bit (cells outside the region are contract-unwritten)."""
    checked = 0
    for k, g in prog.geoms.items():
        if not g.is_written:
            continue
        L = len(out_full[k])
        for s in range(L - min(wf, L), L):
            a = np.asarray(out_full[k][s])
            b = np.asarray(out_reg[k][s])
            idx = [slice(None)] * a.ndim
            for d in g.domain_dims:
                lo, hi = region.get(d, (0, extent[d]))
                idx[g.axis_of(d)] = slice(g.origin[d] + lo,
                                          g.origin[d] + hi)
            np.testing.assert_array_equal(a[tuple(idx)], b[tuple(idx)])
            checked += 1
    assert checked


def test_region_core_box_pipe_out_bit_equal(env):
    from yask_tpu.ops.pallas_stencil import build_pallas_chunk
    g = (32, 48, 16)
    ctx = _mk_single(env, g=g)
    prog = ctx._program
    blk = (8, 16)
    region = {"x": (4, 28), "y": (8, 40)}     # core box (y lo 8-aligned)
    full, _ = build_pallas_chunk(prog, fuse_steps=2, block=blk,
                                 interpret=True, pipeline_dmas=True)
    part, _ = build_pallas_chunk(prog, fuse_steps=2, block=blk,
                                 interpret=True, pipeline_dmas=True,
                                 region=region)
    # the pipelined write-back must actually be engaged on both arms
    assert full.tiling["pipeline_out"] is True
    assert part.tiling["pipeline_out"] is True
    assert part.tiling["region"] == {d: list(v)
                                     for d, v in region.items()}
    st = {k: list(v) for k, v in ctx._state.items()}
    _region_bit_equal(prog, full(st, 0), part(st, 0), region,
                      dict(zip(("x", "y", "z"), g)), 2)


@pytest.mark.parametrize("region", [{"x": (0, 4)}, {"x": (28, 32)},
                                    {"y": (0, 8)}, {"y": (40, 48)}],
                         ids=["x-lo", "x-hi", "y-lo", "y-hi"])
def test_region_shell_slab_pipe_out_bit_equal(env, region):
    # the exact shape the overlap scheduler builds: one thin slab per
    # split-dim boundary (width hK = r·K = 4, y slabs 8-aligned), with
    # the output pipeline staging through parity tiles
    from yask_tpu.ops.pallas_stencil import build_pallas_chunk
    g = (32, 48, 16)
    ctx = _mk_single(env, g=g)
    prog = ctx._program
    blk = (8, 16)
    full, _ = build_pallas_chunk(prog, fuse_steps=2, block=blk,
                                 interpret=True, pipeline_dmas=True)
    slab, _ = build_pallas_chunk(prog, fuse_steps=2, block=blk,
                                 interpret=True, pipeline_dmas=True,
                                 region=region)
    assert slab.tiling["pipeline_out"] is True
    st = {k: list(v) for k, v in ctx._state.items()}
    _region_bit_equal(prog, full(st, 0), slab(st, 0), region,
                      dict(zip(("x", "y", "z"), g)), 2)


def test_region_pipe_arms_bit_equal(env):
    """The output pipeline must never change values: the same region
    build with the pipeline off agrees to the last bit."""
    from yask_tpu.ops.pallas_stencil import build_pallas_chunk
    g = (32, 48, 16)
    ctx = _mk_single(env, g=g)
    prog = ctx._program
    region = {"x": (8, 24)}
    kw = dict(fuse_steps=2, block=(8, 16), interpret=True, region=region)
    on, _ = build_pallas_chunk(prog, pipeline_dmas=True, **kw)
    off, _ = build_pallas_chunk(prog, pipeline_dmas=False, **kw)
    assert on.tiling["pipeline_out"] is True
    assert off.tiling["pipeline_out"] is False
    st = {k: list(v) for k, v in ctx._state.items()}
    _region_bit_equal(prog, on(st, 0), off(st, 0), region,
                      dict(zip(("x", "y", "z"), g)), 2)


def test_region_sublane_misaligned_lo_raises(env):
    # y is the sublane axis: a region lo that is not an 8-multiple would
    # be an unaligned Mosaic output window — the planner must refuse
    from yask_tpu.ops.pallas_stencil import build_pallas_chunk
    ctx = _mk_single(env)
    with pytest.raises(YaskException, match="align"):
        build_pallas_chunk(ctx._program, fuse_steps=2, block=(8, 16),
                           interpret=True, region={"y": (4, 20)})
