"""The observability spine (yask_tpu/obs/ + the exporters).

The contract under test, end to end:

* **No-op guarantee** — with ``YT_TRACE`` unset, ``span()`` yields a
  shared null handle, NO trace file is ever created, and a supervised
  run produces bit-identical state to a traced twin (tracing must be
  free to not use).
* **One trace id** joins every artifact: a request's id propagates
  front → scheduler → journal rows → ledger rows → span rows, and
  survives a fleet worker crash into the replacement's (gen+1)
  journal via the re-issued wire message.
* **Metrics parity** — ``obs.metrics.percentile`` IS the historical
  ``server._pctl`` (nearest-rank on ``round(q*(n-1))``), asserted
  value-for-value.
* **Exporters** — ``tools/obs_report.py`` renders a per-phase
  self-time breakdown (queue/exchange separated from compute,
  halo-cal instability surfaced) and valid Chrome/Perfetto JSON;
  ``log_to_csv --traces`` flattens the same rows.

Wired into ``make obscheck`` (and ``make check``).
"""

import csv
import io
import json
import os

import numpy as np
import pytest

from yask_tpu.obs import metrics as obs_metrics
from yask_tpu.obs import tracer
from yask_tpu.resilience.faults import reset_faults

G = 12
STEPS = 4


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    monkeypatch.delenv("YT_FAULT_PLAN", raising=False)
    monkeypatch.delenv("YT_TRACE", raising=False)
    monkeypatch.delenv("YT_TRACE_EVENTS", raising=False)
    monkeypatch.delenv("YT_TRACE_MAX_MB", raising=False)
    # re-arm the once-per-process compaction probe per test
    monkeypatch.setattr(tracer, "_compact_checked", False)
    reset_faults()
    yield
    reset_faults()


@pytest.fixture()
def trace_file(tmp_path, monkeypatch):
    p = tmp_path / "TRACE_EVENTS.jsonl"
    monkeypatch.setenv("YT_TRACE_EVENTS", str(p))
    monkeypatch.setenv("YT_TRACE", "1")
    return p


def _mk_iso(mode="jit", g=G, **knobs):
    """Small prepared iso3dfd context with deterministic interiors."""
    from yask_tpu import yk_factory
    fac = yk_factory()
    env = fac.new_env()
    ctx = fac.new_solution(env, stencil="iso3dfd", radius=2)
    ctx.apply_command_line_options(f"-g {g}")
    o = ctx.get_settings()
    o.mode = mode
    for k, v in knobs.items():
        setattr(o, k, v)
    ctx.prepare_solution()
    rng = np.random.RandomState(7)
    for vn in ctx.get_var_names():
        v = ctx.get_var(vn)
        if vn == "vel":
            v.set_all_elements_same(0.05)
        else:
            arr = rng.rand(g, g, g).astype(np.float32)
            v.set_elements_in_slice(arr, [0, 0, 0, 0],
                                    [0, g - 1, g - 1, g - 1])
    return ctx


# -------------------------------------------------- the no-op guarantee

def test_disabled_tracer_is_noop_and_creates_no_file(tmp_path,
                                                     monkeypatch):
    p = tmp_path / "T.jsonl"
    monkeypatch.setenv("YT_TRACE_EVENTS", str(p))
    assert not tracer.trace_enabled()
    with tracer.span("x", phase="compute", a=1) as sp:
        assert sp is tracer._NULL
        assert sp.set(b=2) is sp
        with tracer.span("y") as inner:
            assert inner is tracer._NULL
    tracer.record_span("z", "queue", 0.0, 1.0)
    assert not p.exists()
    assert tracer.current_trace_id() == ""
    # journal rows stay bit-identical: no trace_id key appears
    from yask_tpu.serve.journal import ServeJournal
    row = ServeJournal(str(tmp_path / "J.jsonl")).record(
        "r0", "s0", "received")
    assert "trace_id" not in row


def test_disabled_supervised_run_bit_identical_to_traced(tmp_path,
                                                         monkeypatch):
    """YT_TRACE on vs off around the SAME supervised run: identical
    state; off writes no file, on writes a joined span tree."""
    off_file = tmp_path / "off.jsonl"
    monkeypatch.setenv("YT_TRACE_EVENTS", str(off_file))
    plain = _mk_iso("jit", ckpt_every=2, ckpt_dir=str(tmp_path))
    plain.run_solution(0, STEPS - 1)
    assert not off_file.exists()
    # the telemetry plane is off too: no YT_SLO_* knob → no monitor
    from yask_tpu.obs.slo import SloMonitor, slo_enabled
    for k in list(os.environ):
        if k.startswith("YT_SLO_"):
            monkeypatch.delenv(k)
    assert not slo_enabled()
    assert SloMonitor.from_env() is None

    on_file = tmp_path / "on.jsonl"
    monkeypatch.setenv("YT_TRACE_EVENTS", str(on_file))
    monkeypatch.setenv("YT_TRACE", "1")
    traced = _mk_iso("jit", ckpt_every=2, ckpt_dir=str(tmp_path))
    traced.run_solution(0, STEPS - 1)
    assert traced.compare_data(plain) == 0

    rows = tracer.read_spans(str(on_file))
    names = {r["name"] for r in rows}
    assert "run.supervised" in names
    assert "guard:run.chunk" in names
    assert "ckpt.save" in names
    sup = next(r for r in rows if r["name"] == "run.supervised")
    # every chunk is a child of the supervised root, same trace id
    chunks = [r for r in rows if r["name"] == "guard:run.chunk"]
    assert chunks and all(r["trace"] == sup["trace"]
                          and r["parent"] == sup["span"]
                          for r in chunks)
    assert all(r["v"] == tracer.TRACE_SCHEMA for r in rows)
    ck = next(r for r in rows if r["name"] == "ckpt.save")
    assert ck["phase"] == "checkpoint"
    # session-journal evidence written under the trace joins it
    from yask_tpu.resilience.journal import SessionJournal
    with tracer.activate(sup["trace"]):
        row = SessionJournal(str(tmp_path / "J.jsonl")).record(
            "validate", case="obs")
    assert row["trace_id"] == sup["trace"]


# --------------------------------------------------- span fundamentals

def test_span_nesting_parent_links_and_attrs(trace_file):
    with tracer.span("outer", phase="compute", k=2) as a:
        with tracer.span("inner", phase="dma") as b:
            b.set(bytes=4096, arr=np.float32(1.5))
        a.set(done=True)
    rows = tracer.read_spans(str(trace_file))
    assert [r["name"] for r in rows] == ["inner", "outer"]  # close order
    inner, outer = rows
    assert inner["parent"] == outer["span"]
    assert outer["parent"] == ""
    assert inner["trace"] == outer["trace"]
    assert outer["attrs"] == {"k": 2, "done": True}
    assert inner["attrs"]["bytes"] == 4096
    assert isinstance(inner["attrs"]["arr"], (str, float))  # jsonable
    assert all(r["dur"] >= 0 and r["ts"] > 0 for r in rows)
    assert all(r["pid"] == os.getpid() for r in rows)


def test_activate_and_stamp_work_without_enablement(monkeypatch):
    # ids are independent of the write gate: propagation still works
    # when span-writing is off (a worker joining an upstream trace)
    assert not tracer.trace_enabled()
    row = {}
    with tracer.activate("t123"):
        assert tracer.current_trace_id() == "t123"
        tracer.stamp_trace(row)
        with tracer.activate(""):  # empty id = passthrough
            assert tracer.current_trace_id() == "t123"
    assert row == {"trace_id": "t123"}
    assert tracer.current_trace_id() == ""
    assert tracer.stamp_trace({}) == {}


def test_phase_for_site_table():
    assert tracer.phase_for_site("ckpt.save") == "checkpoint"
    assert tracer.phase_for_site("cache.load") == "compile"
    assert tracer.phase_for_site("halo_cal.rep") == "exchange"
    assert tracer.phase_for_site("tuner.measure") == "tune"
    assert tracer.phase_for_site("fleet.route") == "front"
    assert tracer.phase_for_site("run.chunk") == "compute"
    assert tracer.phase_for_site("serve.run") == "compute"
    assert tracer.phase_for_site("state.to_device") == "dma"
    assert tracer.phase_for_site("mystery.site") == "guard"


def test_compaction_bounds_growth_and_bad_env_never_raises(
        tmp_path, monkeypatch):
    p = tmp_path / "T.jsonl"
    lines = [json.dumps({"v": tracer.TRACE_SCHEMA, "trace": f"t{i}",
                         "span": f"s{i}", "parent": "", "name": "n",
                         "phase": "compute", "ts": float(i), "dur": 0.1,
                         "pid": 1, "tid": 1, "attrs": {}})
             for i in range(200)]
    p.write_text("\n".join(lines) + "\n")
    size = p.stat().st_size
    assert tracer.compact_if_large(str(p), max_bytes=size // 4)
    kept = tracer.read_spans(str(p))
    assert 0 < len(kept) < 200
    assert kept[-1]["trace"] == "t199"          # newest tail survives
    assert p.stat().st_size <= size // 8 + 200  # half the limit-ish
    # bad env values: default, never a raise
    monkeypatch.setenv("YT_TRACE_MAX_MB", "garbage")
    assert tracer.trace_max_bytes() == 64 << 20
    monkeypatch.setenv("YT_TRACE_MAX_MB", "-3")
    assert tracer.trace_max_bytes() == 64 << 20
    monkeypatch.setenv("YT_TRACE_MAX_MB", "0.0001")
    assert tracer.trace_max_bytes() == int(0.0001 * (1 << 20))
    assert tracer.compact_if_large(str(tmp_path / "missing.jsonl")) \
        is False


# ------------------------------------------------------------- metrics

def _old_pctl(xs, q):
    """The historical serve.server._pctl, verbatim."""
    if not xs:
        return 0.0
    xs = sorted(xs)
    i = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
    return xs[i]


def test_percentile_matches_old_server_pctl_exactly():
    rng = np.random.RandomState(3)
    for n in (1, 2, 3, 7, 100, 101):
        xs = [float(x) for x in rng.rand(n) * 100]
        for q in (0.0, 0.5, 0.9, 0.99, 1.0):
            assert obs_metrics.percentile(xs, q) == _old_pctl(xs, q)
    assert obs_metrics.percentile([], 0.5) == 0.0


def test_registry_instruments_and_snapshot():
    reg = obs_metrics.Registry()
    reg.counter("req.ok").inc()
    reg.counter("req.ok").inc(2)
    reg.gauge("depth").set(7)
    h = reg.histogram("lat_ms")
    xs = [5.0, 1.0, 9.0, 3.0]
    for x in xs:
        h.observe(x)
    snap = reg.snapshot()
    assert snap["counters"]["req.ok"] == 3
    assert snap["gauges"]["depth"] == 7.0
    s = snap["histograms"]["lat_ms"]
    assert s["count"] == 4 and s["max"] == 9.0
    assert s["p50"] == _old_pctl(xs, 0.50)
    assert s["p99"] == _old_pctl(xs, 0.99)
    assert s["mean"] == pytest.approx(4.5)
    json.dumps(snap)  # JSON-able, whole
    # bounded window: evicts oldest, count keeps the lifetime total
    hb = obs_metrics.Histogram(window=2)
    for x in (1.0, 2.0, 3.0):
        hb.observe(x)
    assert hb.count == 3 and hb.summary()["window"] == 2
    assert hb.percentile(0.0) == 2.0


# ------------------------------------- serve: one trace id, end to end

def test_scheduler_propagates_trace_through_artifacts(tmp_path,
                                                      monkeypatch,
                                                      trace_file):
    monkeypatch.setenv("YT_PERF_LEDGER", str(tmp_path / "L.jsonl"))
    from yask_tpu.serve import ServeRequest, StencilServer
    srv = StencilServer(journal_path=str(tmp_path / "SJ.jsonl"),
                        window_secs=0.05, preflight=False)
    try:
        sid = srv.open_session(stencil="iso3dfd", radius=1, g=8,
                               mode="jit", wf=2)
        srv.init_vars(sid)
        tid = "t0123456789abcde"
        h = srv.submit(ServeRequest(session=sid, first_step=0,
                                    last_step=STEPS - 1, trace=tid))
        resp = srv.wait(h, timeout=600)
        assert resp.ok
        assert resp.trace == tid                     # rides the response
        events = srv.journal.events(resp.rid)
        assert events and all(e.get("trace_id") == tid for e in events)
        rows = tracer.read_spans(str(trace_file))
        mine = [r for r in rows if r["trace"] == tid]
        names = {r["name"] for r in mine}
        assert "serve.chunk" in names                # batch execution
        assert "serve.queue_wait" in names           # retroactive span
        qw = next(r for r in mine if r["name"] == "serve.queue_wait")
        assert qw["phase"] == "queue"
        # the registry saw the release
        m = srv.metrics()
        assert m["registry"]["counters"]["serve.requests.ok"] == 1
        assert m["registry"]["histograms"]["serve.total_ms"]["count"] \
            == 1
        # ledger aggregate rows join back via extra.trace_ids
        assert srv.flush_metrics()
        with open(tmp_path / "L.jsonl") as f:
            banked = [json.loads(ln) for ln in f if ln.strip()]
        assert any(tid in r.get("extra", {}).get("trace_ids", ())
                   for r in banked)
    finally:
        srv.shutdown()


def test_untraced_request_mints_id_only_when_enabled(tmp_path,
                                                     monkeypatch):
    from yask_tpu.serve.scheduler import _Pending
    from yask_tpu.serve import ServeRequest
    req = ServeRequest(session="s", first_step=0, last_step=0)
    assert _Pending(req, "r0").trace == ""          # off: stays ""
    monkeypatch.setenv("YT_TRACE", "1")
    monkeypatch.setenv("YT_TRACE_EVENTS",
                       str(tmp_path / "T.jsonl"))
    assert _Pending(req, "r1").trace.startswith("t")  # on: minted
    req2 = ServeRequest(session="s", first_step=0, last_step=0,
                        trace="twire")
    assert _Pending(req2, "r2").trace == "twire"     # wire id wins


# --------------------------------------- fleet: survival across gen+1

def test_fleet_trace_survives_worker_failover(tmp_path, monkeypatch):
    """One front-stamped trace id rides open/run wire msgs, lands in
    the gen-0 worker's journal, survives the chaos kill into the
    replacement's (gen+1) re-issued run, and joins the span file
    across processes."""
    trace_path = tmp_path / "TRACE_EVENTS.jsonl"
    for k, v in (("JAX_PLATFORMS", "cpu"), ("PALLAS_AXON_POOL_IPS", ""),
                 ("YT_TRACE", "1"), ("YT_TRACE_EVENTS", str(trace_path)),
                 ("YT_PERF_LEDGER", str(tmp_path / "L.jsonl"))):
        monkeypatch.setenv(k, v)
    from tools.serve_fleet import ServeFleet
    chaos_env = dict(os.environ)
    # probes: run1 entry, run2 entry, run2 flush 1 (passes), run2
    # flush 2 -> os._exit mid-op (same plan as the failover suite)
    chaos_env["YT_FAULT_PLAN"] = "fleet.kill_worker:worker_dead:1:3"
    fl = ServeFleet(n_workers=1, cache_dir=str(tmp_path / "cache"),
                    journal_dir=str(tmp_path),
                    worker_args=["--no-preflight", "--window_ms", "5"],
                    env=chaos_env)
    fl._base_env.pop("YT_FAULT_PLAN")   # replacements spawn clean
    try:
        o = fl.handle({"op": "open", "stencil": "iso3dfd", "radius": 1,
                       "g": 8, "wf": 2})
        assert o["ok"], o
        sid = o["sid"]
        assert fl.handle({"op": "init", "sid": sid})["ok"]
        r1 = fl.handle({"op": "run", "sid": sid, "first": 0, "last": 3})
        assert r1["ok"], r1
        gen0 = fl.workers[0]
        msg2 = {"op": "run", "sid": sid, "first": 4, "last": 9,
                "flush_every": 2}
        r2 = fl.handle(msg2, emit=lambda _ln: None)
        assert r2["ok"], r2
        tid = msg2["trace"]                    # front-stamped
        assert tid and r2["trace"] == tid
        assert fl.workers[0].gen == gen0.gen + 1   # failover happened

        # gen+1 evidence: the replacement finished the SAME trace —
        # the worker journal (shared path across gens) holds a
        # terminal ok for it, which only the replacement could write
        from yask_tpu.serve.journal import ServeJournal
        wrows = ServeJournal(
            str(tmp_path / "SERVE_JOURNAL.w0.jsonl")).rows()
        mine = [r for r in wrows if r.get("trace_id") == tid]
        assert any(r["event"] == "ok" for r in mine), mine
        # the front's retry row carries the id too
        frows = ServeJournal(
            str(tmp_path / "SERVE_JOURNAL.fleet.jsonl")).rows()
        retries = [r for r in frows if r["event"] == "retry"]
        assert retries and retries[0].get("trace_id") == tid

        # span file: front process + worker process(es), one trace
        spans = [r for r in tracer.read_spans(str(trace_path))
                 if r["trace"] == tid]
        names = {r["name"] for r in spans}
        assert "fleet.run" in names            # the front's span
        assert "serve.chunk" in names          # a worker's span
        assert len({r["pid"] for r in spans}) >= 2
    finally:
        fl.close()


# ----------------------------------------------------------- exporters

def _synthetic_rows():
    mk = lambda **kw: {"v": tracer.TRACE_SCHEMA, "trace": "tA",
                       "parent": "", "pid": 10, "tid": 1, "attrs": {},
                       **kw}
    return [
        mk(span="s1", name="run.supervised", phase="compute",
           ts=100.0, dur=1.0),
        mk(span="s2", parent="s1", name="serve.chunk", phase="compute",
           ts=100.1, dur=0.6),
        mk(span="s3", parent="s2", name="ckpt.save", phase="checkpoint",
           ts=100.5, dur=0.1),
        mk(span="s4", name="serve.queue_wait", phase="queue",
           ts=99.8, dur=0.2),
        mk(span="s5", name="halo_cal", phase="exchange", ts=99.0,
           dur=0.3, attrs={"unstable": True, "spread": 4.2, "reps": 7}),
        mk(span="s6", name="halo.share", phase="exchange", ts=100.2,
           dur=0.15, attrs={"frac": 0.25}),
        # a second, older trace — the default must pick tA (newest)
        mk(span="s7", trace="tOLD", name="fleet.run", phase="front",
           ts=50.0, dur=0.5, pid=11),
    ]


@pytest.fixture()
def synthetic_trace(tmp_path):
    p = tmp_path / "T.jsonl"
    with open(p, "w") as f:
        for r in _synthetic_rows():
            f.write(json.dumps(r) + "\n")
    return p


def test_obs_report_phase_table_and_self_time(synthetic_trace):
    import importlib
    obs_report = importlib.import_module("tools.obs_report")
    rows = obs_report.pick_trace(
        tracer.read_spans(str(synthetic_trace)))
    assert {r["trace"] for r in rows} == {"tA"}     # latest trace wins
    selfs = obs_report.self_times(rows)
    assert selfs["s1"] == pytest.approx(0.4)        # 1.0 - child 0.6
    assert selfs["s2"] == pytest.approx(0.5)        # 0.6 - child 0.1
    bk = obs_report.phase_breakdown(rows)
    # compute self-time 0.9 minus the 0.15 halo.share evidence
    assert bk["compute"]["secs"] == pytest.approx(0.75)
    assert bk["queue"]["secs"] == pytest.approx(0.2)
    assert bk["exchange"]["secs"] == pytest.approx(0.45)
    assert bk["checkpoint"]["secs"] == pytest.approx(0.1)
    buf = io.StringIO()
    obs_report.report(rows, top=3, out=buf)
    text = buf.getvalue()
    for needle in ("compute", "queue", "exchange", "checkpoint",
                   "UNSTABLE", "halo.share moved"):
        assert needle in text, text


def test_obs_report_perfetto_export_is_valid(synthetic_trace,
                                             tmp_path, capsys):
    import importlib
    obs_report = importlib.import_module("tools.obs_report")
    out = tmp_path / "perfetto.json"
    rc = obs_report.main(["--path", str(synthetic_trace),
                          "--trace", "all",
                          "--perfetto", str(out)])
    assert rc == 0
    doc = json.loads(out.read_text())
    evs = doc["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X"]
    ms = [e for e in evs if e["ph"] == "M"]
    assert len(xs) == len(_synthetic_rows())
    assert {e["pid"] for e in ms} == {10, 11}       # one lane per pid
    chunk = next(e for e in xs if e["name"] == "serve.chunk")
    assert chunk["ts"] == pytest.approx(100.1e6)    # µs wall clock
    assert chunk["dur"] == pytest.approx(0.6e6)
    assert chunk["cat"] == "compute"
    assert chunk["args"]["parent"] == "s1"
    capsys.readouterr()


def test_log_to_csv_traces_flattens(synthetic_trace):
    from yask_tpu.tools.log_to_csv import TRACE_COLS, traces_to_csv
    buf = io.StringIO()
    n = traces_to_csv(str(synthetic_trace), out=buf)
    assert n == len(_synthetic_rows())
    rows = list(csv.DictReader(io.StringIO(buf.getvalue())))
    assert len(rows) == n
    assert list(rows[0]) == TRACE_COLS
    cal = next(r for r in rows if r["name"] == "halo_cal")
    assert json.loads(cal["attrs"])["unstable"] is True
