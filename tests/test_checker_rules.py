"""Rule-registry contract tests (round 21).

Rule ids are a public, stable contract; these tests pin the three ways
it can silently rot: an undeclared id shipping from a pass, a declared
id losing its ``docs/checking.md`` catalog row, and the ``--json``
report drifting from its schema.
"""

import ast
import json
import os

import pytest

from yask_tpu import yk_factory
from yask_tpu.checker import SCHEMA, run_checks
from yask_tpu.checker.rules import (CORE, PLAN_REASON_CODES, all_rules,
                                    flat_rules)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKER_DIR = os.path.join(REPO, "yask_tpu", "checker")


@pytest.fixture(scope="module")
def env():
    return yk_factory().new_env()


def _checker_sources():
    for fn in sorted(os.listdir(CHECKER_DIR)):
        if not fn.endswith(".py"):
            continue
        path = os.path.join(CHECKER_DIR, fn)
        with open(path, encoding="utf-8") as f:
            yield fn, ast.parse(f.read(), filename=path)


def _add_rule_literals(tree):
    """First-arg string literals of every ``report.add(...)`` /
    ``<x>.add(...)`` call."""
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add" and node.args):
            a0 = node.args[0]
            if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                yield a0.value


# ---------------------------------------------------------------- ids
def test_rule_ids_unique_across_passes():
    """No id belongs to two passes — except the declared CORE pair,
    which the entry point and any pass may share."""
    seen = {}
    for pass_name, ids in all_rules().items():
        assert len(ids) == len(set(ids)), f"duplicate id inside {pass_name}"
        for rid in ids:
            if rid in CORE:
                continue
            assert rid not in seen, (
                f"rule {rid} declared by both {seen[rid]} and {pass_name}")
            seen[rid] = pass_name


def test_rule_id_style():
    for rid in flat_rules():
        assert rid.upper() == rid and " " not in rid, rid
        assert all(c.isalnum() or c == "-" for c in rid), rid


def test_every_add_site_is_declared():
    """AST scan: a literal rule id at any ``report.add`` site in the
    checker package must be declared — a typo'd id cannot ship."""
    declared = flat_rules()
    undeclared = []
    for fn, tree in _checker_sources():
        for rid in _add_rule_literals(tree):
            if rid not in declared:
                undeclared.append((fn, rid))
    assert not undeclared, f"undeclared rule ids at add sites: {undeclared}"


def test_dynamic_rule_families_declared():
    """The three dynamically-built id families are covered by the
    registry: the vmem plan-error classifier's return set, the races
    analysis-failure pair, and every planner reason code mapped
    through the explain pass."""
    declared = flat_rules()

    # vmem._classify_plan_error: every `return "X"` literal
    with open(os.path.join(CHECKER_DIR, "vmem.py"), encoding="utf-8") as f:
        tree = ast.parse(f.read())
    fn = next(n for n in ast.walk(tree)
              if isinstance(n, ast.FunctionDef)
              and n.name == "_classify_plan_error")
    returns = {n.value.value for n in ast.walk(fn)
               if isinstance(n, ast.Return)
               and isinstance(n.value, ast.Constant)}
    assert returns, "classifier grew no literal returns?"
    assert returns <= declared, returns - declared

    assert {"RACE-CYCLE", "ANALYSIS-FAILED"} <= declared

    from yask_tpu.checker.explain import _rule_of
    for code in PLAN_REASON_CODES:
        assert _rule_of(code) in declared


def test_planner_reason_codes_complete():
    """Planner↔registry drift check: every ``{"code": "..."}`` literal
    ``build_pallas_chunk`` records must be a declared reason code, so
    a new planner decision cannot ship without its EXPLAIN rule."""
    path = os.path.join(REPO, "yask_tpu", "ops", "pallas_stencil.py")
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    recorded = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        for k, v in zip(node.keys, node.values):
            if (isinstance(k, ast.Constant) and k.value == "code"
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)):
                recorded.add(v.value)
    assert recorded, "no reason codes found in the planner?"
    missing = recorded - set(PLAN_REASON_CODES)
    assert not missing, (
        f"planner records reason codes with no declared EXPLAIN rule: "
        f"{sorted(missing)} — add them to rules.PLAN_REASON_CODES and "
        "docs/checking.md")


# ---------------------------------------------------------------- docs
def test_catalog_documents_every_rule():
    """Every declared rule id (and every planner reason code) appears
    in docs/checking.md — the catalog cannot silently fall behind."""
    with open(os.path.join(REPO, "docs", "checking.md"),
              encoding="utf-8") as f:
        doc = f.read()
    missing = [rid for rid in sorted(flat_rules())
               if not rid.startswith("EXPLAIN-") and rid not in doc]
    # EXPLAIN-* rules are documented by their reason CODE rows
    missing += [c for c in PLAN_REASON_CODES if c not in doc]
    assert not missing, f"docs/checking.md missing catalog rows: {missing}"


# ------------------------------------------------------------- schema
def _report(env, **settings):
    ctx = yk_factory().new_solution(env, stencil="iso3dfd", radius=4)
    ctx.apply_command_line_options("-g 32")
    o = ctx.get_settings()
    o.mode = settings.pop("mode", "pallas")
    for k, v in settings.items():
        setattr(o, k, v)
    return run_checks(ctx)


def test_json_round_trip_schema(env):
    """``to_json`` → dumps → loads reproduces a valid
    ``yask_tpu.checker/1`` document: required keys, declared rules,
    valid severities, summary counts that add up."""
    report = _report(env, wf_steps=2)
    blob = json.loads(json.dumps(report.to_json()))
    assert blob["schema"] == SCHEMA == "yask_tpu.checker/1"
    for key in ("config", "passes", "diagnostics", "summary"):
        assert key in blob, key
    assert blob["config"]["backend"]      # the capability entry name
    assert set(blob["passes"]) and isinstance(blob["passes"], list)

    declared = flat_rules()
    counts = {"error": 0, "warn": 0, "info": 0}
    assert blob["diagnostics"], "expected at least the info decisions"
    for d in blob["diagnostics"]:
        assert d["rule"] in declared, d["rule"]
        assert d["severity"] in counts, d["severity"]
        assert d["message"]
        counts[d["severity"]] += 1
    assert blob["summary"] == counts


def test_json_round_trip_error_case(env):
    """An error-carrying report round-trips too (deep-ring spill class:
    big grid, forced big blocks, tiny budget)."""
    report = _report(env, wf_steps=2, vmem_budget_mb=1)
    blob = json.loads(json.dumps(report.to_json()))
    declared = flat_rules()
    assert all(d["rule"] in declared for d in blob["diagnostics"])
