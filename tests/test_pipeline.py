"""Cross-solution pipeline fusion: fused arm vs host-chained oracle.

The load-bearing property: the merged program (bound consumer inputs
eliminated, reads rewritten to the producer's fresh +step value) is
BIT-identical to the host-chained schedule — per step, per stage in
order, each binding pushed through host interior copies — whenever the
two arms run the same temporal schedule.  The pallas K>1 *chunked*
schedule is only tolerance-equal to stepwise runs (a pre-existing
FMA-reassociation property of temporal chunking, independent of
fusion), so the K=2 chunked case gates at the repo's standard
tolerance and the bit gates run schedule-matched.
"""

import numpy as np
import pytest

from yask_tpu import yk_factory


@pytest.fixture(scope="module")
def env():
    return yk_factory().new_env()


def _mk_pipe(env, cli, fuse=None, radius=2, g=16, seed=7,
             accumulate=True):
    from yask_tpu.ops.pipeline import SolutionPipeline, rtm_chain
    stages, bindings = rtm_chain(radius=radius, accumulate=accumulate)
    pipe = SolutionPipeline(env, stages, bindings)
    pipe.apply_command_line_options(f"-g {g} " + cli)
    pipe.prepare(fuse=fuse)
    v = pipe.get_var("fwd", "pressure")
    rng = np.random.RandomState(seed)
    arr = (rng.rand(g, g, g).astype(np.float32) - 0.5) * 0.1
    for t in range(v.get_first_valid_step_index(),
                   v.get_last_valid_step_index() + 1):
        v.set_elements_in_slice(arr, [t, 0, 0, 0],
                                [t, g - 1, g - 1, g - 1])
    return pipe


# ---- bit-equality gates ---------------------------------------------------

@pytest.mark.parametrize("wf", [1, 2])
def test_jit_fused_bitequal_chained(env, wf):
    fused = _mk_pipe(env, f"-mode jit -wf_steps {wf}", fuse=True)
    chained = _mk_pipe(env, f"-mode jit -wf_steps {wf}", fuse=False)
    assert fused.fused and not chained.fused
    fused.run(0, 3)
    chained.run(0, 3)
    assert fused.compare(chained) == 0


def test_pallas_k1_fused_bitequal_chained(env):
    fused = _mk_pipe(env, "-mode pallas -wf_steps 1", fuse=True)
    chained = _mk_pipe(env, "-mode pallas -wf_steps 1", fuse=False)
    fused.run(0, 3)
    chained.run(0, 3)
    assert fused.compare(chained) == 0


def test_pallas_wf2_stepwise_bitequal_chunked_tolerance(env):
    # schedule-matched: fused wf=2 driven one step at a time is
    # bit-identical to the (intrinsically stepwise) chained oracle;
    # the K=2 *chunked* schedule is tolerance-equal only — the same
    # 1-ulp property the standalone pallas K>1 path already has vs
    # its own stepwise runs.
    fused = _mk_pipe(env, "-mode pallas -wf_steps 2", fuse=True)
    chained = _mk_pipe(env, "-mode pallas -wf_steps 1", fuse=False)
    for t in range(4):
        fused.run(t, t)
    chained.run(0, 3)
    assert fused.compare(chained) == 0

    chunked = _mk_pipe(env, "-mode pallas -wf_steps 2", fuse=True)
    chunked.run(0, 3)
    assert chunked.compare(chained, epsilon=1e-3, abs_epsilon=1e-4) == 0


def test_fused_vs_chained_cross_mode_tolerance(env):
    # fused pallas vs chained-jit: cross-mode, standard tolerance
    fused = _mk_pipe(env, "-mode pallas -wf_steps 2", fuse=True)
    chained = _mk_pipe(env, "-mode jit -wf_steps 1", fuse=False)
    fused.run(0, 3)
    chained.run(0, 3)
    assert fused.compare(chained, epsilon=1e-3, abs_epsilon=1e-4) == 0


# ---- plan geometry --------------------------------------------------------

def test_tileplan_stage_widths_sum_to_fused_radius(env):
    from yask_tpu.ops.tile_planner import TilePlan
    pipe = _mk_pipe(env, "-mode pallas -wf_steps 2", fuse=True)
    prog = pipe.fused_ctx._program
    tp = TilePlan(prog, 2)
    sw = tp.stage_widths()
    assert len(sw) == len(prog.stage_reads)
    for d in tp.rad:
        assert sum(w.get(d, 0) for w in sw) == tp.rad[d]


def test_tileplan_stage_flow_nesting(env):
    # inter-stage halo nesting: within one fused sub-step, stage si's
    # read interval must equal stage si-1's write interval (the
    # producer's fresh strip is exactly what the consumer consumes)
    from yask_tpu.ops.tile_planner import TilePlan
    pipe = _mk_pipe(env, "-mode pallas -wf_steps 2", fuse=True)
    tp = TilePlan(pipe.fused_ctx._program, 2)
    flow = tp.stage_flow({d: 8 for d in tp.rad})
    assert flow
    for entry in flow:
        sts = entry["stages"]
        for si in range(1, len(sts)):
            assert sts[si]["read"] == sts[si - 1]["write"]
        # every write nests inside the same stage's read
        for st in sts:
            for d, (lo, hi) in st["write"].items():
                rlo, rhi = st["read"][d]
                assert rlo <= lo and hi <= rhi


def test_hbm_model_rtm_chain_halves_traffic(env):
    from yask_tpu.ops.pipeline import pipeline_hbm_model
    pipe = _mk_pipe(env, "-mode jit -wf_steps 1")
    m = pipeline_hbm_model(pipe)
    assert m["ratio"] == pytest.approx(2.0)
    assert m["fused_bytes_pp"] < m["chained_bytes_pp"]


# ---- ineligibility fallback matrix ---------------------------------------

def _pipe_with(env, stages, bindings, cli="-g 16 -mode jit -wf_steps 1"):
    from yask_tpu.ops.pipeline import SolutionPipeline
    pipe = SolutionPipeline(env, stages, bindings)
    pipe.apply_command_line_options(cli)
    return pipe


def _rtm(radius=2):
    from yask_tpu.ops.pipeline import rtm_chain
    return rtm_chain(radius=radius)


@pytest.mark.parametrize("mutate,code", [
    # producer var not written (vel is read-only)
    (lambda s, b: (s, [("img", "fwd_in", "fwd", "vel")]),
     "binding-producer"),
    # consumer var unknown
    (lambda s, b: (s, [("img", "nope", "fwd", "pressure")]),
     "binding-unknown-var"),
    # producer stage not earlier than consumer
    (lambda s, b: (s, [("fwd", "vel", "img", "img")]),
     "binding-order"),
    # duplicate consumer binding
    (lambda s, b: (s, [b[0], b[0]] + b[1:]), "binding-duplicate"),
    # single stage
    (lambda s, b: (s[:1], []), "stage-count"),
    # reserved separator in a stage name
    (lambda s, b: ([("a__b", s[0][1])] + s[1:], b), "stage-name"),
], ids=["producer-unwritten", "unknown-var", "order", "duplicate",
        "one-stage", "bad-name"])
def test_ineligible_chain_declines_and_falls_back(env, mutate, code):
    stages, bindings = _rtm()
    s2, b2 = mutate(stages, bindings)
    pipe = _pipe_with(env, s2, b2)
    plan = pipe.prepare()
    assert not pipe.fused
    codes = {r["code"] for r in plan["reasons"] if not r.get("ok")}
    assert code in codes, codes
    # the host-chained fallback still executes
    pipe.run(0, 0)
    # and forcing fusion raises with the decline in the message
    from yask_tpu.utils.exceptions import YaskException
    pipe2 = _pipe_with(env, s2, b2)
    with pytest.raises(YaskException):
        pipe2.prepare(fuse=True)


def test_forced_unfused_records_reason(env):
    pipe = _mk_pipe(env, "-mode jit -wf_steps 1", fuse=False)
    assert not pipe.fused
    codes = {r["code"] for r in pipe.plan()["reasons"]}
    assert "forced-unfused" in codes


# ---- checker pass ---------------------------------------------------------

def test_checker_pipeline_engaged(env):
    from yask_tpu.checker import run_checks
    pipe = _mk_pipe(env, "-mode pallas -wf_steps 2", fuse=True)
    rep = run_checks(pipe.fused_ctx)
    assert "pipeline" in rep.passes
    eng = [d for d in rep.diagnostics if d.rule == "PIPELINE-ENGAGED"]
    assert eng and eng[0].detail["fused"]
    assert eng[0].detail["pallas"]["fuse_steps"] == 2


def test_checker_pipeline_infeasible(env):
    from yask_tpu.checker.pipeline_pass import check_pipeline_plan
    stages, _ = _rtm()
    pipe = _pipe_with(env, stages,
                      [("img", "fwd_in", "fwd", "vel")])
    rep = check_pipeline_plan(pipe)
    rules = {d.rule for d in rep.diagnostics}
    assert "PIPELINE-INFEASIBLE" in rules
    assert rep.ok()   # warn-severity: the chain still runs host-chained


def test_checker_pipeline_vmem_spill(env):
    # the round-3 spill shape on the merged chain: explicit 64x64
    # blocks at -vmem_mb 120 on a 512^3 domain — tiles pass the
    # planning budget, the live-value model exceeds the Mosaic scoped
    # limit.  Static decline, nothing allocated.
    from yask_tpu.checker.pipeline_pass import check_pipeline_plan
    from yask_tpu.ops.pipeline import SolutionPipeline, rtm_chain
    stages, bindings = rtm_chain(radius=2)
    pipe = SolutionPipeline(env, stages, bindings)
    pipe.apply_command_line_options(
        "-g 512 -mode pallas -wf_steps 2 -b 64 -vmem_mb 120")
    rep = check_pipeline_plan(pipe)
    spills = [d for d in rep.errors if d.rule == "PIPELINE-VMEM-SPILL"]
    assert spills, rep.render(verbose=True)


def test_checker_skips_non_pipeline_ctx(env):
    from yask_tpu.checker import run_checks
    fac = yk_factory()
    ctx = fac.new_solution(env, stencil="iso3dfd", radius=2)
    ctx.apply_command_line_options("-g 16")
    rep = run_checks(ctx, passes=["pipeline"])
    assert {d.rule for d in rep.diagnostics} == {"PIPELINE-SKIPPED"}


# ---- push-memory tile-graph fusion ----------------------------------------
#
# The PURE rtm chain (rtm_img_pure: img(t+1) = fwd², no self-read)
# makes the merged image var's only reader the smoother at +step — the
# push flagship.  The standard (accumulating) chain's image reads
# itself at offset 0, so push must DECLINE there.

def test_push_eligible_vars_oracle(env):
    from yask_tpu.ops.pallas_stencil import push_eligible_vars
    pure = _mk_pipe(env, "-mode pallas -wf_steps 1", fuse=True,
                    accumulate=False)
    elig = push_eligible_vars(pure.fused_ctx._program)
    assert elig["img__img"] == "ok"
    # the final output must stay on the write-DMA path
    assert "never read" in elig["smooth__smooth"]
    acc = _mk_pipe(env, "-mode pallas -wf_steps 1", fuse=True)
    acc_elig = push_eligible_vars(acc.fused_ctx._program)
    assert "ok" not in acc_elig.values(), acc_elig
    # the accumulating image reads itself at offset 0
    assert "step offsets" in acc_elig["img__img"]


def test_push_engages_on_pure_chain(env):
    pipe = _mk_pipe(env, "-mode pallas -wf_steps 1 -push on",
                    fuse=True, accumulate=False)
    pal = pipe.plan()["pallas"]
    assert pal["push"] and pal["push_vars"] == ["img__img"]
    assert pal["push_tile_bytes"] > 0
    assert pipe.pushed_vars() == {"img__img"}
    codes = {r["code"] for r in pipe.plan()["reasons"]}
    assert "pipeline-push-engaged" in codes
    m = pipe.plan()["hbm_model"]
    assert m["fused_push_bytes_pp"] < m["fused_bytes_pp"]
    assert m["push_ratio"] > m["ratio"]


def test_push_declines_on_accumulating_chain(env):
    pipe = _mk_pipe(env, "-mode pallas -wf_steps 1 -push on", fuse=True)
    pal = pipe.plan()["pallas"]
    assert not pal["push"] and pipe.pushed_vars() == set()
    codes = {r["code"] for r in pipe.plan()["reasons"]}
    assert "pipeline-push-ineligible" in codes
    # the decline arm still runs bit-identical to the oracle
    chained = _mk_pipe(env, "-mode pallas -wf_steps 1", fuse=False)
    pipe.run(0, 3)
    chained.run(0, 3)
    assert pipe.compare(chained) == 0


def test_push_bitequal_chained_pallas_k1(env):
    push = _mk_pipe(env, "-mode pallas -wf_steps 1 -push on",
                    fuse=True, accumulate=False)
    chained = _mk_pipe(env, "-mode pallas -wf_steps 1 -push off",
                       fuse=False, accumulate=False)
    push.run(0, 3)
    chained.run(0, 3)
    assert push.compare(chained) == 0


def test_push_stepwise_bitequal_chunked_tolerance(env):
    # schedule-matched K=2: push-fused driven stepwise is bit-identical
    # to the chained oracle; the K=2 chunked schedule gates at the
    # repo's standard temporal-chunking tolerance.
    push = _mk_pipe(env, "-mode pallas -wf_steps 2 -push on",
                    fuse=True, accumulate=False)
    chained = _mk_pipe(env, "-mode pallas -wf_steps 1", fuse=False,
                       accumulate=False)
    for t in range(4):
        push.run(t, t)
    chained.run(0, 3)
    assert push.compare(chained) == 0

    chunked = _mk_pipe(env, "-mode pallas -wf_steps 2 -push on",
                       fuse=True, accumulate=False)
    chunked.run(0, 3)
    assert chunked.compare(chained, epsilon=1e-3, abs_epsilon=1e-4) == 0


def test_push_jit_bitequal_any_k(env):
    # push is a pallas-tile concept: jit mode never pushes, stays exact
    for wf in (1, 2):
        fused = _mk_pipe(env, f"-mode jit -wf_steps {wf} -push on",
                         fuse=True, accumulate=False)
        chained = _mk_pipe(env, f"-mode jit -wf_steps {wf}",
                           fuse=False, accumulate=False)
        assert fused.pushed_vars() == set()
        fused.run(0, 3)
        chained.run(0, 3)
        assert fused.compare(chained) == 0


def test_push_off_keeps_var_observable(env):
    pipe = _mk_pipe(env, "-mode pallas -wf_steps 1 -push off",
                    fuse=True, accumulate=False)
    assert pipe.pushed_vars() == set()
    pipe.run(0, 1)
    assert pipe.get_var("img", "img") is not None


def test_get_var_raises_for_pushed(env):
    from yask_tpu.utils.exceptions import YaskException
    pipe = _mk_pipe(env, "-mode pallas -wf_steps 1 -push on",
                    fuse=True, accumulate=False)
    with pytest.raises(YaskException, match="push-fused"):
        pipe.get_var("img", "img")
    # the final stage's outputs stay readable
    assert pipe.get_var("smooth", "smooth") is not None


def test_push_bad_cli_value_raises(env):
    # a typo'd -push must not silently resolve to auto (every other
    # engage/decline is observable; so is a bad knob)
    from yask_tpu.utils.exceptions import YaskException
    with pytest.raises(YaskException, match="bad -push value"):
        _mk_pipe(env, "-mode pallas -wf_steps 1 -push banana",
                 fuse=True, accumulate=False)


def test_push_plan_only_bytes_match_executed(env):
    # plan_only=True's VMEM byte breakdown must byte-match the executed
    # chunk's tiling — one code path, the model cannot drift (the
    # conformance pin, extended to the push fields).  plan_pallas is
    # the checker's mirrored plan entry (same K/block/skew/push as the
    # runtime build).
    from yask_tpu.checker.vmem import plan_pallas
    pipe = _mk_pipe(env, "-mode pallas -wf_steps 1 -push on",
                    fuse=True, accumulate=False)
    ctx = pipe.fused_ctx
    pplan = plan_pallas(ctx, ctx._program, ctx.vmem_budget())
    pipe.run(0, 1)
    tilings = [t for t in ctx._pallas_tiling.values() if t]
    assert tilings, "pallas run recorded no tiling"
    til = tilings[0]
    assert til["push"] and til["push_vars"] == pplan["push_vars"]
    assert til["push_tile_bytes"] == pplan["push_tile_bytes"] > 0
    assert til["tile_bytes"] == pplan["tile_bytes"], (
        f"plan_only modeled {pplan['tile_bytes']} B/tile but the "
        f"runtime built {til['tile_bytes']} B/tile")


def test_checker_push_rules(env):
    from yask_tpu.checker.pipeline_pass import check_pipeline_plan
    pure = _mk_pipe(env, "-mode pallas -wf_steps 1 -push on",
                    fuse=True, accumulate=False)
    rules = {d.rule for d in check_pipeline_plan(pure).diagnostics}
    assert "PIPELINE-PUSH-ENGAGED" in rules
    acc = _mk_pipe(env, "-mode pallas -wf_steps 1 -push on", fuse=True)
    rules = {d.rule for d in check_pipeline_plan(acc).diagnostics}
    assert "PIPELINE-PUSH-INFEASIBLE" in rules


def test_tuner_push_ab_records_measurement(env):
    from yask_tpu.runtime.auto_tuner import AutoTuner
    pipe = _mk_pipe(env, "-mode pallas -wf_steps 1 -push on",
                    fuse=True, accumulate=False)
    ctx = pipe.fused_ctx
    tuner = AutoTuner(ctx)
    tuner.trial_secs = 0.05
    tuner.best_rate = None
    tuner._push_ab(1)
    assert any(k[0] == "push" for k in tuner.results), tuner.results
    assert ctx._opts.push_memory in ("on", "off")


def test_tuner_push_ab_noop_when_not_engaged(env):
    from yask_tpu.runtime.auto_tuner import AutoTuner
    pipe = _mk_pipe(env, "-mode pallas -wf_steps 1 -push on", fuse=True)
    tuner = AutoTuner(pipe.fused_ctx)
    tuner._push_ab(1)   # accumulating chain: nothing engages, no arms
    assert not any(k[0] == "push" for k in tuner.results)


# ---- AOT cache key --------------------------------------------------------

def test_pipeline_signature_in_variant_key(env):
    pipe = _mk_pipe(env, "-mode pallas -wf_steps 1", fuse=True)
    ctx = pipe.fused_ctx
    key = ctx._pallas_variant_key()
    assert key[-1] == pipe.signature()
    saved = ctx._pipeline_sig
    try:
        ctx._pipeline_sig = None
        assert ctx._pallas_variant_key() != key
    finally:
        ctx._pipeline_sig = saved


def test_signature_distinguishes_chains(env):
    from yask_tpu.ops.pipeline import SolutionPipeline, rtm_chain
    stages, bindings = rtm_chain(radius=2)
    a = SolutionPipeline(env, stages, bindings)
    b = SolutionPipeline(env, rtm_chain(radius=2)[0], bindings[:1])
    assert a.signature() != b.signature()


# ---- tuner A/B ------------------------------------------------------------

def test_tuner_pipeline_ab_records_verdict(env):
    from yask_tpu.runtime.auto_tuner import AutoTuner
    pipe = _mk_pipe(env, "-mode pallas -wf_steps 1", fuse=True)
    ctx = pipe.fused_ctx
    tuner = AutoTuner(ctx)
    tuner.trial_secs = 0.05
    tuner.best_rate = None
    tuner._pipeline_ab(1)
    verdicts = [r for r in pipe.plan()["reasons"]
                if r["code"] == "pipeline-ab"]
    assert verdicts
    v = verdicts[0]
    assert v["fused_secs_per_step"] > 0
    assert v["chained_secs_per_step"] > 0
    # the pinned arm agrees with the measured winner
    assert pipe.fused == (v["fused_secs_per_step"]
                          <= v["chained_secs_per_step"])


def test_tuner_ab_skips_non_pipeline_ctx(env):
    from yask_tpu.runtime.auto_tuner import AutoTuner
    fac = yk_factory()
    ctx = fac.new_solution(env, stencil="iso3dfd", radius=2)
    ctx.apply_command_line_options("-g 16 -mode pallas -wf_steps 1")
    ctx.prepare_solution()
    tuner = AutoTuner(ctx)
    tuner._pipeline_ab(1)   # no pipeline: must be a silent no-op
    assert not tuner.results
