"""Kernel runtime tests: var API, lifecycle, stats, validation — the analog
of the reference's kernel API tests (``src/kernel/tests/yask_kernel_api_test
.py:84-327``: slice get/set via numpy, fixed-size vars, reductions, steps)."""

import numpy as np
import pytest

from yask_tpu import yk_factory, YaskException
from yask_tpu.compiler.solution import yc_factory


@pytest.fixture(scope="module")
def env():
    return yk_factory().new_env()


def make_heat(env, g=16, mode=None, **opts):
    ctx = yk_factory().new_solution(env, stencil="3axis", radius=1)
    ctx.apply_command_line_options(f"-g {g}")
    if mode:
        ctx.get_settings().mode = mode
    for k, v in opts.items():
        setattr(ctx.get_settings(), k, v)
    ctx.prepare_solution()
    return ctx


def test_lifecycle_and_var_geometry(env):
    ctx = make_heat(env)
    assert ctx.is_prepared()
    assert ctx.get_step_dim_name() == "t"
    assert ctx.get_domain_dim_names() == ["x", "y", "z"]
    v = ctx.get_var("A")
    assert v.get_dim_names() == ["t", "x", "y", "z"]
    assert v.get_halo_size("x") == 1
    assert v.get_left_pad_size("x") >= 1
    assert v.get_alloc_size("x") >= 16 + 2
    assert v.get_alloc_size("t") == 2
    assert v.is_storage_allocated()


def test_element_and_slice_access(env):
    ctx = make_heat(env)
    v = ctx.get_var("A")
    v.set_element(3.5, [0, 5, 6, 7])
    assert v.get_element([0, 5, 6, 7]) == pytest.approx(3.5)
    v.add_to_element(1.0, [0, 5, 6, 7])
    assert v.get_element([0, 5, 6, 7]) == pytest.approx(4.5)

    data = np.arange(4 * 4 * 4, dtype=np.float32).reshape(4, 4, 4)
    n = v.set_elements_in_slice(data, [0, 2, 2, 2], [0, 5, 5, 5])
    assert n == 64
    back = v.get_elements_in_slice([0, 2, 2, 2], [0, 5, 5, 5])
    np.testing.assert_allclose(back, data)

    assert v.reduce_elements_in_slice(
        "sum", [0, 2, 2, 2], [0, 5, 5, 5]) == pytest.approx(float(data.sum()))
    assert v.reduce_elements_in_slice(
        "max", [0, 2, 2, 2], [0, 5, 5, 5]) == pytest.approx(63.0)
    with pytest.raises(YaskException):
        v.reduce_elements_in_slice("bogus", [0, 2, 2, 2], [0, 5, 5, 5])


def test_run_and_oracle_match(env):
    ctx = make_heat(env)
    ctx.get_var("A").set_elements_in_seq(0.1)
    ctx.run_solution(0, 4)
    ref = make_heat(env, mode="ref")
    ref.get_var("A").set_elements_in_seq(0.1)
    ref.run_solution(0, 4)
    assert ctx.compare_data(ref) == 0
    st = ctx.get_stats()
    assert st.get_num_steps_done() == 5
    assert st.get_num_elements() == 16 ** 3
    assert st.get_elapsed_secs() > 0
    assert st.get_pts_per_sec() > 0
    assert "throughput" in st.format()


def test_step_indexing_after_run(env):
    ctx = make_heat(env)
    ctx.get_var("A").set_all_elements_same(1.0)
    ctx.run_solution(0, 2)
    v = ctx.get_var("A")
    # after 3 steps, steps 2 (older) and 3 (newest) are retained
    v.get_element([3, 0, 0, 0])
    v.get_element([2, 0, 0, 0])
    with pytest.raises(YaskException):
        v.get_element([0, 0, 0, 0])   # evicted step


def test_reverse_time_step_index_ordering(env):
    """ADVICE r3: for step_dir=-1 the oldest slot has the LARGER step
    index; first/last must stay numerically ordered so
    are_indices_local range checks hold."""
    ctx = yk_factory().new_solution(env, stencil="test_reverse_2d")
    ctx.apply_command_line_options("-g 8")
    ctx.prepare_solution()
    ctx.get_vars()[0].set_elements_in_seq(0.1)  # non-zero: sums differ
    ctx.run_solution(0, 2)   # reverse: cur_step walks downward
    v = ctx.get_vars()[0]
    first = v.get_first_valid_step_index()
    last = v.get_last_valid_step_index()
    assert first <= last
    assert v.are_indices_local([first, 0, 0])
    assert v.are_indices_local([last, 0, 0])
    assert not v.are_indices_local([last + 1, 0, 0])
    # reductions must cover the NEWEST step (cur_step, numerically the
    # SMALLER index under reverse time), not the numeric max
    import numpy as np
    cur = first  # 3 reverse steps from 0 → newest = -3 = min
    newest = v.get_elements_in_slice([cur, 0, 0], [cur, 7, 7]) \
        .astype(np.float64)
    assert v.get_sum() == pytest.approx(newest.sum(), rel=1e-5)


def test_end_solution_reports_clear_error(env):
    """ADVICE r3: after end_solution, accessors must say so (not the
    misleading 'state was lost' / AttributeError)."""
    ctx = make_heat(env, g=8)
    ctx.get_var("A").set_all_elements_same(1.0)
    ctx.run_solution(0, 1)
    v = ctx.get_var("A")
    ctx.end_solution()
    with pytest.raises(YaskException, match="end_solution was called"):
        ctx.run_solution(2, 3)
    with pytest.raises(YaskException, match="end_solution was called"):
        v.get_element([2, 0, 0, 0])
    # re-prepare brings the solution back to life
    ctx.prepare_solution()
    ctx.get_var("A").set_all_elements_same(1.0)
    ctx.run_solution(0, 1)


def test_wf_chunking_equivalence(env):
    a = make_heat(env)
    a.get_var("A").set_elements_in_seq(0.1)
    a.run_solution(0, 5)
    b = make_heat(env, wf_steps=2)
    b.get_var("A").set_elements_in_seq(0.1)
    b.run_solution(0, 5)
    assert a.compare_data(b) == 0


def test_boundary_ghosts_are_zero(env):
    ctx = make_heat(env, g=8)
    v = ctx.get_var("A")
    v.set_all_elements_same(2.0)
    # pads are excluded from fills: reading just outside the domain gives 0
    assert v.get_element([0, -1, 0, 0]) == 0.0
    assert v.get_element([0, 8, 3, 3]) == 0.0


def test_hooks(env):
    calls = []
    ctx = yk_factory().new_solution(env, stencil="3axis", radius=1)
    ctx.apply_command_line_options("-g 8")
    ctx.call_before_prepare_solution(lambda c: calls.append("bp"))
    ctx.call_after_prepare_solution(lambda c: calls.append("ap"))
    ctx.call_before_run_solution(lambda c: calls.append("br"))
    ctx.call_after_run_solution(lambda c: calls.append("ar"))
    ctx.prepare_solution()
    ctx.run_solution(0, 0)
    assert calls == ["bp", "ap", "br", "ar"]


def test_cli_help_and_env(env):
    ctx = yk_factory().new_solution(env, stencil="3axis", radius=1)
    h = ctx.get_command_line_help()
    assert "-g <val>" in h and "-mode <val>" in h
    assert env.get_num_ranks() >= 1
    env.global_barrier()
    assert env.sum_over_ranks(3) == 3
    assert yk_factory().get_version_string()


def test_custom_solution_object(env):
    soln = yc_factory().new_solution("custom")
    t = soln.new_step_index("t")
    x = soln.new_domain_index("x")
    u = soln.new_var("u", [t, x])
    u(t + 1, x).EQUALS(0.5 * (u(t, x - 1) + u(t, x + 1)))
    ctx = yk_factory().new_solution(env, soln)
    ctx.apply_command_line_options("-g 32")
    ctx.prepare_solution()
    arr = np.sin(np.arange(32, dtype=np.float32))
    ctx.get_var("u").set_elements_in_slice(arr, [0, 0], [0, 31])
    ctx.run_solution(0, 0)
    got = ctx.get_var("u").get_elements_in_slice([1, 0], [1, 31])
    pad = np.pad(arr, 1)
    want = 0.5 * (pad[:-2] + pad[2:])
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_auto_tune_preserves_run_semantics(env):
    """Online tuning must not replay step indices or skew stats: a tuned
    run of a t-dependent stencil (IF_STEP) must equal the untuned oracle,
    with step bookkeeping identical (ADVICE r1: tuner step replay)."""
    def build(**opts):
        ctx = yk_factory().new_solution(env, stencil="test_step_cond_1d")
        ctx.apply_command_line_options("-g 24")
        for k, v in opts.items():
            setattr(ctx.get_settings(), k, v)
        ctx.prepare_solution()
        ctx.get_var("A").set_elements_in_seq(0.1)
        return ctx

    tuned = build(do_auto_tune=True, auto_tune_trial_secs=0.02)
    tuned.run_solution(0, 5)
    oracle = build(force_scalar=True)
    oracle.run_solution(0, 5)

    assert tuned.compare_data(oracle) == 0
    assert tuned._cur_step == oracle._cur_step == 6
    assert tuned.get_stats().get_num_steps_done() == 6


def test_checkpoint_extensionless_path(env, tmp_path):
    """save/load round trip with a path missing '.npz' (ADVICE r1)."""
    ctx = make_heat(env, g=12)
    ctx.get_var("A").set_elements_in_seq(0.2)
    ctx.run_solution(0, 1)
    ck = str(tmp_path / "snap")  # no extension
    ctx.save_checkpoint(ck)
    other = make_heat(env, g=12)
    other.load_checkpoint(ck)
    assert other._cur_step == ctx._cur_step
    assert other.compare_data(ctx) == 0


def test_shard_map_cache_keyed_on_overlap(env):
    """Toggling -overlap_comms between equal-length runs must not reuse
    the other strategy's compiled body (ADVICE r1: stale jit cache)."""
    ctx = yk_factory().new_solution(env, stencil="3axis", radius=1)
    ctx.apply_command_line_options("-g 16")
    ctx.get_settings().mode = "shard_map"
    ctx.set_num_ranks("x", 2)
    ctx.prepare_solution()
    ctx.get_var("A").set_elements_in_seq(0.1)
    ctx.get_settings().overlap_comms = False
    ctx.run_solution(0, 1)
    ctx.get_settings().overlap_comms = True
    ctx.run_solution(2, 3)
    keys = [k for k in ctx._jit_cache if k[0] == "shard_map"]
    assert len(keys) == 2 and len({k[2] for k in keys}) == 2


def _halo_measured_ctx(env):
    ctx = yk_factory().new_solution(env, stencil="3axis", radius=1)
    # overlap off so exchange cost cannot be fully hidden (a perfectly
    # overlapped run may legitimately calibrate to a zero fraction)
    ctx.apply_command_line_options(
        "-g 64 -measure_halo -no-overlap_comms")
    ctx.get_settings().mode = "shard_map"
    ctx.set_num_ranks("x", 4)
    ctx.prepare_solution()
    ctx.get_var("A").set_elements_in_seq(0.1)
    ctx.run_solution(0, 7)
    st = ctx.get_stats()
    # variant key = (mode, steps, overlap) + the comm-schedule plan key
    frac = ctx._halo_frac.get(
        ("shard_map", 8, False) + ctx.comm_plan().key())
    return ctx, st, frac


def test_halo_time_measured(env):
    """-measure_halo calibrates a no-exchange twin and attributes a real,
    plausible halo fraction of shard_map run time (VERDICT r1 item 7)."""
    ctx, st, frac = _halo_measured_ctx(env)
    if (frac is None or st.get_halo_exchange_secs() <= 0.0
            or st.get_halo_pack_secs() <= 0.0):
        # ONE bounded re-measure, mirroring halo-cal's own outlier
        # re-time: under the full parallel tier-1 run, suite load can
        # make the no-exchange twin split twice-unstable (frac None)
        # or clamp a timed component to 0 — neither says the
        # measurement plumbing is broken, only that this sample was
        # noise.  A second clean sample is a real pass; a second noisy
        # one is a real failure.
        ctx, st, frac = _halo_measured_ctx(env)
    # the calibrated fraction is wall-clock-derived: bound it rather
    # than demanding strict positivity (timing noise can clamp it to 0)
    assert frac is not None and 0.0 <= frac < 1.0
    assert st.get_halo_secs() <= st.get_elapsed_secs()
    assert "halo-fraction" in st.format()
    # second calibration point: one bare exchange round timed alone
    # (collective cost without compute/overlap), VERDICT r2 item 8
    assert st.get_halo_exchange_secs() > 0.0
    assert "halo-exchange-round" in st.format()
    # third/fourth components (VERDICT r3 item 6): the round split into
    # slab-pack (collectives elided) vs collective-wait (round − pack)
    assert st.get_halo_pack_secs() > 0.0
    assert st.get_halo_collective_secs() >= 0.0
    assert st.get_halo_collective_secs() \
        == pytest.approx(max(0.0, st.get_halo_exchange_secs()
                             - st.get_halo_pack_secs()))
    assert "halo-pack" in st.format()
    assert "halo-collective" in st.format()
    # log_to_csv scrapes the new components
    from yask_tpu.tools.log_to_csv import scrape
    scraped = scrape(st.format())
    assert "halo-pack (sec)" in scraped
    assert "halo-collective (sec)" in scraped
    # modeled HBM traffic: 3axis has 1 var x 2 slots read + 1 written
    # (write-back) -> 12 B/pt at f32; the model reports pad-inclusive
    # array bytes so it must be at least that
    assert st.get_hbm_bytes_per_point() >= 12.0
    assert "hbm-bytes-per-point" in st.format()

    # correctness is untouched by measurement
    oracle = yk_factory().new_solution(env, stencil="3axis", radius=1)
    oracle.apply_command_line_options("-g 64")
    oracle.get_settings().force_scalar = True
    oracle.prepare_solution()
    oracle.get_var("A").set_elements_in_seq(0.1)
    oracle.run_solution(0, 7)
    assert ctx.compare_data(oracle) == 0

    # attribution mechanism, deterministically: pin the fraction and
    # check the run attributes that share of the program time
    ctx._halo_frac[("shard_map", 8, False)] = 0.5
    before = ctx.get_stats().get_halo_secs()
    ctx.run_solution(8, 15)
    assert ctx.get_stats().get_halo_secs() > before


def test_shard_state_stays_device_resident(env):
    """Repeated shard-mode runs hand interiors over directly — no
    per-call strip/re-pad (VERDICT r1 item 9); host var access
    materializes lazily and stays correct."""
    def build(mode):
        ctx = yk_factory().new_solution(env, stencil="3axis", radius=1)
        ctx.apply_command_line_options("-g 32")
        ctx.get_settings().mode = mode
        ctx.set_num_ranks("x", 4)
        ctx.prepare_solution()
        ctx.get_var("A").set_elements_in_seq(0.1)
        return ctx

    for mode in ("shard_map", "shard_pallas"):
        ctx = build(mode)
        ctx.run_solution(0, 1)
        # interiors parked on device, padded state not rebuilt
        assert ctx._resident is not None and ctx._state is None
        ctx.run_solution(2, 3)   # second run consumes the resident set
        assert ctx._resident is not None

        oracle = yk_factory().new_solution(env, stencil="3axis", radius=1)
        oracle.apply_command_line_options("-g 32")
        oracle.get_settings().force_scalar = True
        oracle.prepare_solution()
        oracle.get_var("A").set_elements_in_seq(0.1)
        oracle.run_solution(0, 3)
        # compare_data materializes the resident interiors lazily
        assert ctx.compare_data(
            oracle, epsilon=1e-3, abs_epsilon=1e-4) == 0
        assert ctx._resident is None and ctx._state is not None
        # and a var write after materialization still round-trips
        ctx.get_var("A").set_element(2.5, [4, 7, 7, 7])
        assert ctx.get_var("A").get_element([4, 7, 7, 7]) == 2.5


def test_vars_in_constructor_pattern_runs_define(env):
    """The reference's canonical pattern — vars created in the
    constructor, equations in define() (Iso3dfdStencil's MAKE_VAR
    members) — must not be treated as already-defined (ADVICE r2:
    a silent zero-equation no-op)."""
    from yask_tpu.compiler.solution_base import yc_solution_base

    class VarsInCtor(yc_solution_base):
        def __init__(self):
            super().__init__("vars_in_ctor_test")
            self._t = self.new_step_index("t")
            self._x = self.new_domain_index("x")
            self.A = self.new_var("A", [self._t, self._x])

        def define(self):
            t, x = self._t, self._x
            self.A(t + 1, x).EQUALS(self.A(t, x) + 1.0)

    s = VarsInCtor()
    s.run_define()
    assert s.get_soln().get_num_equations() == 1
    s.run_define()   # idempotent
    assert s.get_soln().get_num_equations() == 1


def test_direct_define_call_not_rerun():
    """A user may call define() directly before handing the object to
    the runtime; run_define must then not re-run it (vars-only
    solutions would raise duplicate-var on the second pass)."""
    from yask_tpu.stencils.test_stencils import TestEmpty2d
    s = TestEmpty2d()
    s.define()          # creates var A, zero equations
    s.run_define()      # must be a no-op, not a duplicate-var error
    assert len(s.get_soln().get_vars()) == 1


def test_checkpoint_orbax_backend(env, tmp_path):
    """Orbax round trip: resume mid-run and finish identical to an
    uninterrupted run (async-capable storage backend for distributed
    states; the npz path stays the default)."""
    import pytest as _pt0
    _pt0.importorskip("orbax.checkpoint")
    ctx = make_heat(env, g=12)
    ctx.get_var("A").set_elements_in_seq(0.2)
    ctx.run_solution(0, 2)
    ck = str(tmp_path / "orbax_snap")
    ctx.save_checkpoint(ck, backend="orbax")
    ctx.run_solution(3, 5)

    other = make_heat(env, g=12)
    other.load_checkpoint(ck, backend="orbax")
    assert other._cur_step == 3
    other.run_solution(3, 5)
    assert other.compare_data(ctx) == 0

    import pytest as _pt
    from yask_tpu import YaskException
    with _pt.raises(YaskException, match="backend"):
        ctx.save_checkpoint(ck, backend="hdf5")
