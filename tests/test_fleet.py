"""Fleet front (tools/serve_fleet.py): N server workers behind one
JSON-lines front.  Acceptance contract: admission spreads fresh
sessions across workers; a shared YT_COMPILE_CACHE means worker 2's
first run is WARM (lowerings == 0, disk hits > 0) off worker 1's cold
compile, with bit-identical outputs; session affinity pins every sid
to exactly one worker journal; an injected ``fleet.route`` fault is
answered (ok=False), never crashes the front.

One module-scoped fleet amortizes the two worker-interpreter spawns
(each imports jax) across every test here."""

import json
import os

import numpy as np
import pytest

from tools.serve_fleet import ServeFleet
from yask_tpu.resilience.faults import reset_faults

STEPS = 4


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("fleet")
    saved = {}
    env = {
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        # workers flush run metrics to the perf ledger on shutdown —
        # keep test rows out of the tracked PERF_LEDGER.jsonl
        "YT_PERF_LEDGER": str(tmp / "ledger.jsonl"),
    }
    for k, v in env.items():
        saved[k] = os.environ.get(k)
        os.environ[k] = v
    fl = ServeFleet(n_workers=2, cache_dir=str(tmp / "cache"),
                    journal_dir=str(tmp),
                    worker_args=["--no-preflight", "--window_ms", "5"])
    try:
        yield fl
    finally:
        fl.close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


@pytest.fixture(scope="module")
def sessions(fleet):
    """Two identical-profile sessions; admission must spread them."""
    out = []
    for _ in range(2):
        s = fleet.handle({"op": "open", "stencil": "iso3dfd",
                          "radius": 1, "g": 8, "wf": 2})
        assert s["ok"], s
        assert fleet.handle({"op": "init", "sid": s["sid"]})["ok"]
        out.append(s)
    return out


def test_admission_spreads_across_workers(sessions):
    assert sessions[0]["worker"] != sessions[1]["worker"], \
        "least-loaded admission put both sessions on one worker"


def test_shared_cache_warm_start_and_bit_identity(fleet, sessions):
    s1, s2 = sessions
    r1 = fleet.handle({"op": "run", "sid": s1["sid"],
                       "first": 0, "last": STEPS - 1})
    assert r1["ok"], r1
    cs = fleet.handle({"op": "cache_stats"})["stats"]
    assert cs[str(s1["worker"])]["lowerings"] > 0, \
        "worker 1's first run should be the cold compile"

    r2 = fleet.handle({"op": "run", "sid": s2["sid"],
                       "first": 0, "last": STEPS - 1})
    assert r2["ok"], r2
    cs = fleet.handle({"op": "cache_stats"})["stats"]
    w2 = cs[str(s2["worker"])]
    assert w2["lowerings"] == 0, \
        f"worker 2 re-lowered instead of warm-starting: {w2}"
    assert w2["disk_hits"] > 0, w2

    for name in r1["outputs"]:
        a = np.asarray(r1["outputs"][name]["data"])
        b = np.asarray(r2["outputs"][name]["data"])
        assert np.array_equal(a, b), \
            f"{name}: warm-cache run diverged from cold run"


def test_session_affinity_via_worker_journals(fleet, sessions):
    for s in sessions:
        assert fleet.handle({"op": "run", "sid": s["sid"],
                             "first": STEPS, "last": 2 * STEPS - 1})["ok"]
    placed = {}
    for w in fleet.workers:
        with open(w.journal_path) as f:
            for ln in f:
                placed.setdefault(json.loads(ln)["session"],
                                  set()).add(w.idx)
    for s in sessions:
        assert placed.get(s["sid"]) == {s["worker"]}, \
            f"session {s['sid']} left worker {s['worker']}: " \
            f"{placed.get(s['sid'])}"


def test_fleet_stats_and_metrics_aggregate(fleet, sessions):
    fs = fleet.handle({"op": "fleet_stats"})
    assert fs["ok"] and len(fs["workers"]) == 2
    m = fleet.handle({"op": "metrics"})["metrics"]
    assert m["sessions"] == 2
    assert m["completed"] >= 4


def test_route_fault_is_answered_not_fatal(fleet, sessions):
    os.environ["YT_FAULT_PLAN"] = "fleet.route:relay_down:1"
    reset_faults()
    try:
        r = fleet.handle({"op": "run", "sid": sessions[0]["sid"],
                          "first": 2 * STEPS, "last": 2 * STEPS})
        assert not r["ok"] and "error" in r, r
    finally:
        del os.environ["YT_FAULT_PLAN"]
        reset_faults()
    # the front survives and the session keeps serving
    r = fleet.handle({"op": "run", "sid": sessions[0]["sid"],
                      "first": 2 * STEPS, "last": 2 * STEPS})
    assert r["ok"], r
