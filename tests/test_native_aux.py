"""Native library, tracing, trace-diff, and checkpoint/resume tests."""

import os

import numpy as np
import pytest

from yask_tpu import yk_factory
from yask_tpu import native


@pytest.fixture(scope="module")
def env():
    return yk_factory().new_env()


# ---------------------------------------------------------------------------
# native library (built on demand by the loader; g++ is present in CI)
# ---------------------------------------------------------------------------


def test_native_builds_and_loads():
    assert native.available(), "native host library failed to build"
    assert native.get_lib().yt_version() >= 1


def test_native_layout_roundtrip():
    sizes = [3, 4, 5]
    pts = np.array([[0, 0, 0], [2, 3, 4], [1, 2, 3]], dtype=np.int64)
    offs = native.layout(sizes, pts)
    assert offs.tolist() == [0, 59, 33]
    back = native.unlayout(sizes, offs)
    np.testing.assert_array_equal(back, pts)
    with pytest.raises(ValueError):
        native.layout(sizes, np.array([[3, 0, 0]], dtype=np.int64))


def test_native_matches_python_fd():
    # the native path is used by get_center_fd_coefficients when available
    from yask_tpu.utils.fd_coeff import get_center_fd_coefficients
    c = get_center_fd_coefficients(2, 2)
    assert c == pytest.approx([-1 / 12, 4 / 3, -5 / 2, 4 / 3, -1 / 12])
    w = native.fd_weights(1, 0.0, [-1.0, 0.0, 1.0])
    assert w == pytest.approx([-0.5, 0.0, 0.5])


def test_native_compact_factors():
    assert sorted(native.compact_factors(12, 2)) == [3, 4]
    assert sorted(native.compact_factors(8, 3)) == [2, 2, 2]


def test_native_divergence_scan():
    a = np.zeros(100, dtype=np.float32)
    b = a.copy()
    assert native.first_divergence(a, b) == -1
    b[42] = 1.0
    assert native.first_divergence(a, b) == 42
    assert native.count_divergence(a, b) == 1
    b[7] = np.nan
    assert native.first_divergence(a, b) == 7


# ---------------------------------------------------------------------------
# tracing + analyze_trace
# ---------------------------------------------------------------------------


def _run_traced(env, tmp, tag, poison_step=None):
    ctx = yk_factory().new_solution(env, stencil="test_2d")
    ctx.apply_command_line_options("-g 12")
    ctx.prepare_solution()
    ctx.get_var("u").set_elements_in_seq(0.1)
    d = os.path.join(tmp, tag)
    ctx.set_trace_dir(d)
    ctx.run_solution(0, 3)
    if poison_step is not None:
        # corrupt one written value in the dump to emulate a divergence
        p = os.path.join(d, f"step_{poison_step}.npz")
        data = dict(np.load(p))
        data["u"][5, 6] += 1.0
        np.savez(p, **data)
    return d


def test_trace_and_analyze(env, tmp_path):
    from yask_tpu.tools.analyze_trace import compare_traces
    da = _run_traced(env, str(tmp_path), "a")
    db = _run_traced(env, str(tmp_path), "b")
    assert sorted(os.listdir(da)) == [f"step_{t}.npz" for t in range(1, 5)]
    assert compare_traces(da, db) is None
    dc = _run_traced(env, str(tmp_path), "c", poison_step=3)
    res = compare_traces(da, dc)
    assert res is not None
    t, var, coords, va, vb = res
    assert (t, var, coords) == (3, "u", (5, 6))


# ---------------------------------------------------------------------------
# checkpoint / resume
# ---------------------------------------------------------------------------


def test_checkpoint_resume(env, tmp_path):
    def fresh():
        c = yk_factory().new_solution(env, stencil="3axis", radius=1)
        c.apply_command_line_options("-g 12")
        c.prepare_solution()
        c.get_var("A").set_elements_in_seq(0.1)
        return c

    a = fresh()
    a.run_solution(0, 5)

    b = fresh()
    b.run_solution(0, 2)
    ck = str(tmp_path / "ck.npz")
    b.save_checkpoint(ck)

    c = fresh()  # different history; restore overwrites it
    c.run_solution(0, 0)
    c.load_checkpoint(ck)
    assert c._cur_step == b._cur_step
    c.run_solution(3, 5)
    assert c.compare_data(a) == 0


def test_checkpoint_shape_mismatch(env, tmp_path):
    from yask_tpu.utils.exceptions import YaskException
    a = yk_factory().new_solution(env, stencil="3axis", radius=1)
    a.apply_command_line_options("-g 12")
    a.prepare_solution()
    ck = str(tmp_path / "ck.npz")
    a.save_checkpoint(ck)
    b = yk_factory().new_solution(env, stencil="3axis", radius=1)
    b.apply_command_line_options("-g 16")
    b.prepare_solution()
    with pytest.raises(YaskException):
        b.load_checkpoint(ck)


# ---------------------------------------------------------------------------
# C/C++ kernel API (embedded-interpreter front end, reference yk_* C++ API)
# ---------------------------------------------------------------------------


def test_cpp_api_demo(tmp_path):
    """Build the C API library + demo app and run it end to end: the
    C++ front end must drive the same runtime (build, configure, seed,
    run, oracle-compare) — the analog of the reference's C++ kernel API
    test (``yask_kernel_api_test.cpp``)."""
    import shutil
    import subprocess
    import sys
    if shutil.which("g++") is None or shutil.which("make") is None:
        pytest.skip("no C++ toolchain")
    # embed THIS interpreter (the one with jax installed), not whatever
    # python3-config happens to be on PATH
    cfg = sys.executable + "-config"
    if not os.path.exists(cfg):
        cfg = os.path.join(os.path.dirname(sys.executable),
                           "python3-config")
    if not os.path.exists(cfg):
        cfg = shutil.which("python3-config")
    if cfg is None:
        pytest.skip("no python3-config for embedding")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ndir = os.path.join(repo, "yask_tpu", "native")
    r = subprocess.run(["make", "-C", ndir, "capi", f"PYCFG={cfg}"],
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr
    env_ = dict(os.environ)
    env_["PALLAS_AXON_POOL_IPS"] = ""
    env_["JAX_PLATFORMS"] = "cpu"
    env_["PYTHONPATH"] = os.pathsep.join(
        [repo] + [p for p in env_.get("PYTHONPATH", "").split(os.pathsep)
                  if p])
    r = subprocess.run([os.path.join(ndir, "capi_demo")],
                       capture_output=True, text=True, timeout=300,
                       env=env_)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "capi demo passed" in r.stdout
