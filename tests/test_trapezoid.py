"""Trapezoidal/diamond two-phase Pallas tiling tests.

The trapezoid mode decomposes each fused K-group along the tiled lead
dims into carry-free upright trapezoids (per-level write windows shrink
by r per side) running on a PARALLEL Pallas grid, plus an
inverted-trapezoid (diamond) fill pass that recomputes the inter-tile
gap bands from level-0 state — the TPU-native counterpart of the
reference's two-phase trapezoid blocking (``setup.cpp:863``,
``context.cpp:838``), trading the skew mode's sequential carry for
core-parallel tiles.  Every case must agree exactly with the uniform
tiling on the same state and with the XLA oracle end to end; all
tiling decisions must come off the TilePlan with recorded reasons.
"""

import numpy as np
import pytest

from yask_tpu import yk_factory, YaskException


@pytest.fixture(scope="module")
def env():
    return yk_factory().new_env()


def make(env, mode, name, r=8, g=48, wf=1, block=None, trap=True):
    ctx = yk_factory().new_solution(env, stencil=name, radius=r)
    ctx.apply_command_line_options(f"-g {g}")
    ctx.get_settings().mode = mode
    ctx.get_settings().wf_steps = wf
    ctx.get_settings().trapezoid_tiling = trap
    if block:
        for d, b in block.items():
            ctx.set_block_size(d, b)
    ctx.prepare_solution()
    from yask_tpu.runtime.init_utils import init_solution_vars
    init_solution_vars(ctx)
    return ctx


def _chunk_vs_uniform(env, name, r, g, wf, blk, trap_arg=True):
    """Forced trapezoid chunk must agree with the uniform tiling on the
    same state, on a parallel grid."""
    from yask_tpu.ops.pallas_stencil import build_pallas_chunk
    ctx = make(env, "pallas", name, r=r, g=g, wf=wf,
               block=dict(zip(("x", "y"), blk)))
    prog = ctx._program
    tp, _ = build_pallas_chunk(prog, fuse_steps=wf, block=blk,
                               interpret=True, trapezoid=trap_arg)
    assert tp.tiling["trapezoid"] is True
    assert tp.tiling["skew"] is False     # parallel grid: no carries
    # the emitted grid spec must be parallel in every dim, never
    # "arbitrary" (sequential) — the whole point of the two-phase split
    assert all(s == "parallel" for s in tp.tiling["dimension_semantics"])
    un, _ = build_pallas_chunk(prog, fuse_steps=wf, block=blk,
                               interpret=True, skew=False)
    st = {k: list(v) for k, v in ctx._state.items()}
    a = tp(st, 0)
    b = un(st, 0)
    for n in a:
        for x, y in zip(a[n], b[n]):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=2e-5, atol=1e-6)
    return tp.tiling


def test_trapezoid_forced_matches_uniform_r8(env):
    til = _chunk_vs_uniform(env, "iso3dfd", 8, 48, 2, (24, 24))
    assert sorted(til["trap_dims"]) == ["x", "y"]
    assert til["diamond"] and all(d["nbounds"] >= 2
                                  for d in til["diamond"])


def test_trapezoid_forced_matches_uniform_r1_k4(env):
    """Misaligned radius (r=1, sublane rounding active) at K=4."""
    til = _chunk_vs_uniform(env, "cube", 1, 32, 4, (16, 32))
    assert sorted(til["trap_dims"]) == ["x", "y"]


def test_trapezoid_forced_matches_uniform_r2_k3(env):
    _chunk_vs_uniform(env, "iso3dfd", 2, 32, 3, (16, 32))


def test_trapezoid_1d_dim_list(env):
    """trapezoid=["x"]: only the named dim decomposes."""
    til = _chunk_vs_uniform(env, "iso3dfd", 8, 48, 2, (24, 24),
                            trap_arg=["x"])
    assert til["trap_dims"] == ["x"]
    assert len(til["diamond"]) == 1 and til["diamond"][0]["dim"] == "x"


def test_trapezoid_multi_stage_and_scratch(env):
    """ssg's staged chain (per-step halo 2r) and tti's scratch-var
    chain through the diamond fill pass."""
    _chunk_vs_uniform(env, "ssg", 4, 48, 2, (24, 48))
    _chunk_vs_uniform(env, "tti", 2, 48, 2, (24, 48))


def test_trapezoid_e2e_matches_jit(env):
    """End-to-end forced trapezoid vs the XLA oracle, with a remainder
    step group (steps % K != 0)."""
    ref = make(env, "jit", "iso3dfd", r=8, g=48, trap=False)
    ref.run_solution(0, 4)
    p = make(env, "pallas", "iso3dfd", r=8, g=48, wf=2,
             block={"x": 24, "y": 24})
    p.run_solution(0, 4)
    assert p.compare_data(ref, epsilon=1e-3, abs_epsilon=1e-4) == 0


def test_trapezoid_e2e_sponge_conditions(env):
    """IF_DOMAIN sponge conditions under the band recompute (global-
    coordinate masks must hold in the diamond pass too)."""
    ref = make(env, "jit", "iso3dfd_sponge", r=8, g=48, trap=False)
    ref.run_solution(0, 3)
    p = make(env, "pallas", "iso3dfd_sponge", r=8, g=48, wf=2,
             block={"x": 24, "y": 24})
    p.run_solution(0, 3)
    assert p.compare_data(ref, epsilon=1e-3, abs_epsilon=1e-4) == 0


def test_trapezoid_e2e_2d_solution(env):
    """2-D solution: a single lead dim decomposes."""
    ref = make(env, "jit", "wave2d", r=8, g=64, trap=False)
    ref.run_solution(0, 5)
    p = make(env, "pallas", "wave2d", r=8, g=64, wf=2,
             block={"x": 32})
    p.run_solution(0, 5)
    assert p.compare_data(ref, epsilon=1e-3, abs_epsilon=1e-4) == 0


def test_trapezoid_auto_engages_and_matches_jit(env):
    """cube r=1 K=4 at g=48: the per-variant-block profit gate engages
    trapezoid on its own (trapezoid=None), the run matches the oracle,
    and the recorded tiling is the parallel two-phase plan."""
    ref = make(env, "jit", "cube", r=1, g=48, trap=False)
    ref.run_solution(0, 5)
    p = make(env, "pallas", "cube", r=1, g=48, wf=4)
    p.run_solution(0, 5)
    assert p.compare_data(ref, epsilon=1e-3, abs_epsilon=1e-4) == 0
    til = p.get_stats().get_tiling()
    assert til["trapezoid"] is True
    assert all(s == "parallel" for s in til["dimension_semantics"])
    codes = [r["code"] for r in til["reasons"]]
    assert "trapezoid_engaged" in codes
    det = next(r["detail"] for r in til["reasons"]
               if r["code"] == "trapezoid_engaged")
    # the profit-gate numbers are in the record
    assert "vs uniform" in det and "skew" in det


def test_trapezoid_full_span_block_bit_equals_uniform(env):
    """iso3dfd r=2 K=4 at g=24: the profit gate engages with block ==
    full span (degenerate single tile — nbounds=2, only the two domain
    edges bound the diamond passes, and the sublane floor zeroes every
    y write-shrink).  The trapezoid schedule must stay BIT-equal to the
    uniform pallas schedule through the runtime path — jit is the wrong
    oracle at this size (XLA reassociation drifts ~1e-3 in a few
    steps), which is exactly why the bench_suite gate compares pallas
    schedules, not modes."""
    p = make(env, "pallas", "iso3dfd", r=2, g=24, wf=4)
    p.run_solution(0, 3)
    til = p.get_stats().get_tiling()
    assert til["trapezoid"] is True
    assert til["block"] == {"x": 24, "y": 24}   # degenerate: full span
    u = make(env, "pallas", "iso3dfd", r=2, g=24, wf=4, trap=False)
    u.run_solution(0, 3)
    assert p.compare_data(u, epsilon=0.0, abs_epsilon=0.0) == 0


def test_trapezoid_gate_rejects_where_skew_wins(env):
    """iso3dfd r=8 K=2: phase-1 compute equals uniform at K=2, so the
    diamond overhead loses the gate — skew keeps the flagship, the
    rejection (with its cost numbers) is recorded, and the build is the
    skew one."""
    from yask_tpu.ops.pallas_stencil import build_pallas_chunk
    ctx = make(env, "pallas", "iso3dfd", r=8, g=48, wf=2,
               block={"x": 24, "y": 24})
    plan = build_pallas_chunk(ctx._program, fuse_steps=2, block=(24, 24),
                              interpret=True, trapezoid=None,
                              plan_only=True)
    assert plan["trapezoid"] is False and plan["trap_dims"] == []
    assert plan["skew"] is True
    rej = [r for r in plan["reasons"]
           if r["code"] == "trapezoid_gate_rejected"]
    assert rej and all("vs uniform" in r["detail"] for r in rej)


def test_trapezoid_fallback_without_pads(env):
    """Auto trapezoid on a program prepared WITHOUT the diamond-band
    pads must fall back cleanly (reason recorded); forcing raises."""
    from yask_tpu.ops.pallas_stencil import build_pallas_chunk
    ctx = make(env, "pallas", "cube", r=1, g=48, wf=4, trap=False)
    ch, _ = build_pallas_chunk(ctx._program, fuse_steps=4,
                               interpret=True, trapezoid=None)
    assert ch.tiling["trapezoid"] is False
    with pytest.raises(YaskException):
        build_pallas_chunk(ctx._program, fuse_steps=4, interpret=True,
                           trapezoid=True)


def test_trapezoid_band_floor_fallback(env):
    """A block below the diamond-band floor (2·cl(K)+unit) falls back
    in auto mode with the cause recorded, and raises when forced."""
    from yask_tpu.ops.pallas_stencil import build_pallas_chunk
    ctx = make(env, "pallas", "cube", r=1, g=48, wf=4)
    # y floor = 2·ceil(3, 8) + 8 = 24 > 16
    blk = (16, 16)
    with pytest.raises(YaskException, match="band floor"):
        build_pallas_chunk(ctx._program, fuse_steps=4, block=blk,
                           interpret=True, trapezoid=True)
    ch, _ = build_pallas_chunk(ctx._program, fuse_steps=4, block=blk,
                               interpret=True, trapezoid=None)
    assert ch.tiling["trapezoid"] is False


def test_trapezoid_cli_knob(env):
    """-trapezoid parses into settings.trapezoid_tiling."""
    ctx = yk_factory().new_solution(env, stencil="iso3dfd", radius=2)
    ctx.apply_command_line_options("-g 24 -trapezoid")
    assert ctx.get_settings().trapezoid_tiling is True
    ctx.apply_command_line_options("-no-trapezoid")
    assert ctx.get_settings().trapezoid_tiling is False


# ---- TilePlan unit coverage ---------------------------------------------


def test_tileplan_margins_and_windows(env):
    """THE dataflow-plan object: margins, write windows, diamond
    geometry and block floors for each per-dim mode."""
    from yask_tpu.ops.tile_planner import TilePlan
    ctx = make(env, "pallas", "iso3dfd", r=8, g=48, wf=2)
    prog = ctx._program
    lead = prog.ana.domain_dims[:-1]

    tp = TilePlan(prog, 2, trap_dims=list(lead))
    mL, mR = tp.margins()
    for d in lead:
        # upright trapezoids read one step radius per side
        assert mL[d] == mR[d] == 8
        assert tp.halo(d) == 16                       # radius × K
        assert tp.write_shrink(d, 1) == 0
        assert tp.write_shrink(d, 2) == 8             # (lvl−1)·r
        dia = tp.diamond(d)
        assert dia["half"] == tp.cl(d, 2) == 8
        assert dia["band"] == 16 and dia["margin"] == 16
    # band floor: 2·cl(K) + unit (sublane unit on the sublane axis)
    assert tp.min_block()[lead[-1]] == 2 * 8 + 8
    assert tp.min_block()[lead[0]] == 2 * 8 + 1
    assert tp.margin_override() == {d: 16 for d in lead}

    un = TilePlan(prog, 2)
    umL, umR = un.margins()
    assert umL == umR == {d: 16 for d in lead}        # uniform 2·r·K/2

    sk = TilePlan(prog, 2, skew_dims=[lead[-1]], e_sk={lead[-1]: 0})
    smL, smR = sk.margins()
    assert smL[lead[-1]] == 16 and smR[lead[-1]] == 8  # K·r left, r+E right


def test_tileplan_sublane_rounding(env):
    """Misaligned radius: cl ceils to the sublane tile on the sublane
    axis (write-back DMA alignment), write_shrink floors — exact on
    non-sublane dims."""
    from yask_tpu.ops.tile_planner import TilePlan
    ctx = make(env, "pallas", "cube", r=1, g=48, wf=4)
    prog = ctx._program
    lead = prog.ana.domain_dims[:-1]
    tp = TilePlan(prog, 4, trap_dims=list(lead))
    outer, subl = lead[0], lead[-1]
    assert tp.cl(outer, 4) == 3                       # exact (lvl−1)·r
    assert tp.cl(subl, 4) == 8                        # ceil(3, 8)
    assert tp.write_shrink(outer, 4) == 3
    assert tp.write_shrink(subl, 4) == 0              # floor(3, 8)


def test_tileplan_dataflow_nesting(env):
    """dataflow(): each level's read window covers the next level's
    write window expanded by the step radius — the correctness
    invariant the whole phase-1 kernel hangs on."""
    from yask_tpu.ops.tile_planner import TilePlan
    ctx = make(env, "pallas", "iso3dfd", r=8, g=48, wf=2)
    prog = ctx._program
    lead = prog.ana.domain_dims[:-1]
    tp = TilePlan(prog, 2, trap_dims=list(lead))
    steps = tp.dataflow({d: 24 for d in lead})
    assert len(steps) == 2
    for lvl0, lvl1 in zip(steps, steps[1:]):
        for d in lead:
            wlo, whi = lvl1["write"][d]
            rlo, rhi = lvl0["write"][d]
            # level l+1 writes only cells level l wrote r-coverage for
            assert rlo <= wlo - 8 + 8 and whi <= rhi + 8


def test_tileplan_volumes_model(env):
    """volumes(): trapezoid fetch is strictly below uniform fetch (2r
    vs 2rK margins) and the diamond overhead is accounted."""
    from yask_tpu.ops.tile_planner import TilePlan
    ctx = make(env, "pallas", "iso3dfd", r=8, g=48, wf=2)
    prog = ctx._program
    lead = prog.ana.domain_dims[:-1]
    blk = {d: 24 for d in lead}
    u_use, u_comp, u_fetch = TilePlan(prog, 2).volumes(blk)
    t_use, t_comp, t_fetch = TilePlan(prog, 2,
                                      trap_dims=list(lead)).volumes(blk)
    assert u_use == t_use
    assert t_comp > u_comp            # diamond recompute overhead
    # trapezoid per-lead fetch (B+2r)² < uniform (B+2rK)², but the
    # diamond bands add their own fetch; at this size the sum stays
    # below uniform's margin fetch plus half the band fetch
    assert t_fetch != u_fetch


# ---- checker integration -------------------------------------------------


def test_checker_trapezoid_rules(env):
    """The vmem pass proves the two-phase residency and write-window
    alignment statically when the plan engages trapezoid."""
    from yask_tpu.checker import run_checks
    ctx = make(env, "pallas", "cube", r=1, g=48, wf=4)
    rep = run_checks(ctx, passes=["vmem", "explain"])
    rules = {d.rule for d in rep.diagnostics}
    assert "TRAPEZOID-RESIDENCY-OK" in rules
    assert "TRAPEZOID-WRITE-ALIGN-OK" in rules
    assert "TRAPEZOID-WRITE-ALIGN" not in rules
    assert "TRAPEZOID-VMEM-SPILL" not in rules
    # the explain pass republishes the gate decision
    assert "EXPLAIN-TRAPEZOID-ENGAGED" in rules


def test_checker_trapezoid_infeasible_classified(env):
    """A forced-trapezoid plan failure classifies as
    TRAPEZOID-INFEASIBLE (not the generic PLAN-FAILED)."""
    from yask_tpu.checker.vmem import _classify_plan_error
    assert _classify_plan_error(
        "trapezoid tiling infeasible: block 16 < band floor 33 in 'x'"
    ) == "TRAPEZOID-INFEASIBLE"
    assert _classify_plan_error(
        "trapezoid tiling infeasible (fill pass): pallas diamond band "
        "in dim 'x' exceeds the planned pads"
    ) == "TRAPEZOID-INFEASIBLE"
