"""Skewed-wavefront (streaming) Pallas tiling tests.

The skew mode slides each fused sub-step's compute region left by the
step radius along the innermost (sequential) grid dim, patching the
inter-tile boundary strips from a parity-double-buffered VMEM carry —
zero redundant compute in that dim.  It is the TPU-native counterpart
of the reference's two-phase trapezoid blocking
(``/root/reference/src/kernel/lib/setup.cpp:863``,
``context.cpp:838``): the reference colors phases to create *thread*
parallelism, while a sequential Pallas grid only needs the dependency
carry.  Every case here must agree exactly with the XLA path, with
blocks small enough that several stream tiles (and therefore the
carry) are exercised."""

import numpy as np
import pytest

from yask_tpu import yk_factory, YaskException


@pytest.fixture(scope="module")
def env():
    return yk_factory().new_env()


def make(env, mode, name, r=8, g=48, wf=1, block=None, skew=None,
        steps_init=None):
    ctx = yk_factory().new_solution(env, stencil=name, radius=r)
    ctx.apply_command_line_options(f"-g {g}")
    ctx.get_settings().mode = mode
    ctx.get_settings().wf_steps = wf
    if skew is not None:
        ctx.get_settings().skew_wavefront = skew
    if block:
        for d, b in block.items():
            ctx.set_block_size(d, b)
    ctx.prepare_solution()
    from yask_tpu.runtime.init_utils import init_solution_vars
    init_solution_vars(ctx)
    return ctx


def _compare(env, name, r=8, g=48, wf=2, block=None, steps=6,
             field_epsilon=0.0):
    ref = make(env, "jit", name, r=r, g=g)
    ref.run_solution(0, steps - 1)
    p = make(env, "pallas", name, r=r, g=g, wf=wf, block=block)
    p.run_solution(0, steps - 1)
    return p.compare_data(ref, epsilon=1e-3, abs_epsilon=1e-4,
                          field_epsilon=field_epsilon)


def test_skew_engages_for_aligned_radius(env):
    """Direct chunk build with skew=True must not raise (eligibility)
    and must agree with the uniform tiling."""
    from yask_tpu.ops.pallas_stencil import build_pallas_chunk
    ctx = make(env, "pallas", "iso3dfd", r=8, g=48, wf=2,
               block={"x": 24, "y": 24})
    prog = ctx._program
    sk, _ = build_pallas_chunk(prog, fuse_steps=2, block=(24, 24),
                               interpret=True, skew=True)
    un, _ = build_pallas_chunk(prog, fuse_steps=2, block=(24, 24),
                               interpret=True, skew=False)
    st = {k: list(v) for k, v in ctx._state.items()}
    a = sk(st, 0)
    b = un(st, 0)
    for n in a:
        for x, y in zip(a[n], b[n]):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=2e-5, atol=1e-6)


def test_skew_engages_for_unaligned_radius(env):
    """r=2 (not a sublane multiple): the write-window shift rounds down
    to the sublane tile with a widened window; E_sk extra computed
    width keeps the overlap valid (round-4 eligibility lift).  The
    chunk must ENGAGE skew (not silently fall back) and agree with the
    uniform tiling."""
    from yask_tpu.ops.pallas_stencil import build_pallas_chunk
    ctx = make(env, "pallas", "iso3dfd", r=2, g=32, wf=2,
               block={"x": 16, "y": 16})
    prog = ctx._program
    sk, _ = build_pallas_chunk(prog, fuse_steps=2, block=(16, 16),
                               interpret=True, skew=True)
    assert sk.tiling["skew"] is True
    un, _ = build_pallas_chunk(prog, fuse_steps=2, block=(16, 16),
                               interpret=True, skew=False)
    st = {k: list(v) for k, v in ctx._state.items()}
    a = sk(st, 0)
    b = un(st, 0)
    for n in a:
        for x, y in zip(a[n], b[n]):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=2e-5, atol=1e-6)


# The two truly-misaligned cases (shift (lvl-1)·r % 8 != 0) take the
# widened-window path; its different reduction grouping leaves a
# handful of field-ulp differences vs the jit oracle (triaged r21:
# 3/4 isolated points, |Δ| at the f32 ulp of the field scale — not a
# dropped band; a carry-geometry bug shows O(field) banded errors and
# fails field_epsilon=1e-4 by thousands of points).  r=1 (shift rounds
# to 0) is exact and stays a hard zero-tolerance assert.
@pytest.mark.parametrize("r,wf,block,fe", [
    (1, 2, {"x": 16, "y": 16}, 0.0),  # shift 1: rounds to 0, exact
    (2, 3, {"x": 16, "y": 16}, 1e-4),  # shifts 2,4: both misaligned
    (4, 2, {"x": 16, "y": 16}, 1e-4),  # shift 4: half a sublane tile
])
def test_skew_misaligned_radius_matches_jit(env, r, wf, block, fe):
    assert _compare(env, "iso3dfd", r=r, g=32, wf=wf, block=block,
                    steps=wf * 2, field_epsilon=fe) == 0


def test_skew_misaligned_radius_cube_r1(env):
    """27-point radius-1 stencil (every shift misaligned, ring 1)."""
    assert _compare(env, "cube", r=1, g=32, wf=4,
                    block={"x": 16, "y": 16}, steps=8) == 0


@pytest.mark.parametrize("wf,block", [
    (2, {"x": 24, "y": 24}),   # 2 stream tiles per row: carry active
    (3, {"x": 48, "y": 32}),
    (4, {"x": 24, "y": 32}),   # 4 sub-steps, deeper carry levels
])
def test_skew_iso3dfd_two_slot_ring(env, wf, block):
    assert _compare(env, "iso3dfd", wf=wf, block=block) == 0


def test_skew_sponge_conditions(env):
    """IF_DOMAIN sponge conditions under skewed regions."""
    assert _compare(env, "iso3dfd_sponge", wf=2,
                    block={"x": 24, "y": 24}) == 0


def test_skew_multi_stage(env):
    """ssg's staged chain: stage margins consume within each skewed
    sub-step; cross-tile strips must still line up.  The fused chain
    reassociates the staggered sums (see test_pallas_multi_stage_ssg),
    so a few field-ulp points ride field_epsilon; strip misalignment
    would fail it by orders of magnitude."""
    assert _compare(env, "ssg", r=8, g=32, wf=2,
                    block={"x": 16, "y": 16}, steps=4,
                    field_epsilon=1e-4) == 0


def test_skew_same_point_carry(env):
    """Regression (r21): awp's anelastic mem_* vars are written AND
    read only at zero spatial offset, so they never appear in
    stage_read_widths — but a later sub-step still consumes the slid
    strip from the neighboring tile, so they MUST ride the skew carry
    (analysis.read_var_names).  Pre-fix this corrupted a radius-wide
    band (~9.5k points/step beyond field tolerance); elastic variants
    (no mem chain) never showed it."""
    from yask_tpu.runtime.init_utils import init_solution_vars

    def mk(mode, wf=1):
        ctx = yk_factory().new_solution(env, stencil="awp")
        ctx.apply_command_line_options("-g 20")
        ctx.get_settings().mode = mode
        ctx.get_settings().wf_steps = wf
        ctx.prepare_solution()
        init_solution_vars(ctx)
        ctx.run_solution(0, 3)
        return ctx

    ref = mk("jit")
    p = mk("pallas", wf=2)
    tiling = list(p._pallas_tiling.values())[0]
    assert tiling["skew"] is True      # the trigger: outer-dim skew
    assert p.compare_data(ref, field_epsilon=1e-4) == 0


def test_skew_scratch_chain(env):
    """tti evaluates scratch vars over write-halo-expanded skewed
    regions."""
    assert _compare(env, "tti", r=8, g=32, wf=2,
                    block={"x": 16, "y": 16}, steps=4) == 0


def test_skew_2d_stream_only_dim(env):
    """2-D solution: the single lead dim is the stream dim."""
    assert _compare(env, "wave2d", r=8, g=64, wf=2,
                    block={"x": 32}, steps=6) == 0


class _Reverse3dR8:
    """Ad-hoc reverse-time radius-8 stencil (writes t−1 from t)."""

    def build(self):
        from yask_tpu.compiler.solution_base import yc_solution_base

        class R(yc_solution_base):
            def __init__(self):
                super().__init__("rev3d_r8")

            def define(self):
                t = self.new_step_index("t")
                x = self.new_domain_index("x")
                y = self.new_domain_index("y")
                z = self.new_domain_index("z")
                u = self.new_var("A", [t, x, y, z])
                e = u(t, x, y, z)
                for o in (-8, 8):
                    e = e + u(t, x + o, y, z) + u(t, x, y + o, z) \
                        + u(t, x, y, z + o)
                u(t - 1, x, y, z).EQUALS(e / 7.0)
        return R()


def test_skew_reverse_time(env):
    def mk(mode, wf=1, block=None):
        ctx = yk_factory().new_solution(env, _Reverse3dR8().build())
        ctx.apply_command_line_options("-g 48")
        ctx.get_settings().mode = mode
        ctx.get_settings().wf_steps = wf
        if block:
            for d, b in block.items():
                ctx.set_block_size(d, b)
        ctx.prepare_solution()
        from yask_tpu.runtime.init_utils import init_solution_vars
        init_solution_vars(ctx)
        return ctx

    ref = mk("jit")
    ref.run_solution(5, 0)
    p = mk("pallas", wf=2, block={"x": 24, "y": 24})
    p.run_solution(5, 0)
    assert p.compare_data(ref, epsilon=1e-3, abs_epsilon=1e-4) == 0


def test_skew_off_knob(env):
    """-skew false forces the uniform tiling and still matches."""
    ref = make(env, "jit", "iso3dfd", r=8, g=48)
    ref.run_solution(0, 5)
    p = make(env, "pallas", "iso3dfd", r=8, g=48, wf=2,
             block={"x": 24, "y": 24}, skew=False)
    p.run_solution(0, 5)
    assert p.compare_data(ref, epsilon=1e-3, abs_epsilon=1e-4) == 0


def test_skew_auto_engage_is_profit_gated(env):
    """skew=None auto-engages PER DIM only when that dim's skew margin
    beats uniform shrink: (K+1)·r + E_d < 2·K·r.  Misaligned small
    stream radii (cube r=1) must keep the STREAM dim uniform —
    auto-engaging it regressed the round-4 cube-wavefront proxy
    2.07× → 1.26× (E_sk=16 extra width per 32-wide tile) — while the
    outer dim (E=0) still profits.  max_skew_dims=1 reproduces the
    pre-multi-dim stream-only arm, so the gated-out stream leaves the
    tiling fully uniform.  Explicit skew=True still forces the
    stream-dim path."""
    from yask_tpu.ops.pallas_stencil import build_pallas_chunk

    # r=8 aligned, K=2: profitable (24 vs 32) → auto-skew ON, and the
    # stream dim is among the engaged dims
    iso = make(env, "pallas", "iso3dfd", r=8, g=48, wf=2,
               block={"x": 24, "y": 24})
    ch, _ = build_pallas_chunk(iso._program, fuse_steps=2,
                               block=(24, 24), interpret=True)
    assert ch.tiling["skew"] is True
    iso_lead = iso._program.ana.domain_dims[:-1]
    assert iso_lead[-1] in ch.tiling["skew_dims"]

    # r=1 misaligned, K=4: E_sk=16 ⇒ 21 vs 8 → the stream dim stays
    # uniform; the outer dim (E=0, 5 < 8) engages on its own
    cube = make(env, "pallas", "cube", r=1, g=32, wf=4)
    lead = cube._program.ana.domain_dims[:-1]
    ch, _ = build_pallas_chunk(cube._program, fuse_steps=4,
                               interpret=True)
    assert lead[-1] not in ch.tiling["skew_dims"]

    # -skew_dims 1 = the 1-D A/B arm: stream dim ONLY — the outer dim
    # must not silently swap in, so the whole tiling is uniform
    ch1, _ = build_pallas_chunk(cube._program, fuse_steps=4,
                                interpret=True, max_skew_dims=1)
    assert ch1.tiling["skew"] is False
    assert ch1.tiling["skew_dims"] == []

    # …but an explicit skew=True still builds (stream dim forced) and
    # matches the oracle
    sk, _ = build_pallas_chunk(cube._program, fuse_steps=4,
                               interpret=True, skew=True)
    assert sk.tiling["skew"] is True
    assert sk.tiling["skew_dims"] == [lead[-1]]


def test_skew_distributed_stream_unsharded(env):
    """shard_pallas engages the skewed wavefront when the stream dim is
    not mesh-decomposed (the carry never crosses a shard boundary):
    oracle equivalence on a 2-shard mesh plus a strictly smaller
    modeled margin overhead than the uniform distributed tiling — the
    distributed temporal-blocking analog of the reference's
    update_tb_info (setup.cpp:863)."""
    from yask_tpu.runtime.init_utils import init_solution_vars

    def mk(mode, ranks=(), skew=True):
        ctx = yk_factory().new_solution(env, stencil="iso3dfd", radius=8)
        ctx.apply_command_line_options("-g 48")
        ctx.get_settings().mode = mode
        ctx.get_settings().wf_steps = 2
        ctx.get_settings().skew_wavefront = skew
        for d, r in ranks:
            ctx.set_num_ranks(d, r)
        ctx.prepare_solution()
        init_solution_vars(ctx)
        return ctx

    ref = mk("jit")
    ref.run_solution(0, 3)

    sp = mk("shard_pallas", ranks=[("x", 2)])
    sp.run_solution(0, 3)
    assert sp.compare_data(ref, epsilon=1e-3, abs_epsilon=1e-4) == 0
    til = [t for k, t in sp._pallas_tiling.items()
           if k[0] == "shard_pallas"]
    assert til and til[0]["skew"] is True
    # x is mesh-decomposed → only the (unsharded) stream dim engages
    assert til[0]["skew_dims"] == ["y"]

    un = mk("shard_pallas", ranks=[("x", 2)], skew=False)
    un.run_solution(0, 3)
    assert un.compare_data(ref, epsilon=1e-3, abs_epsilon=1e-4) == 0
    til_u = [t for k, t in un._pallas_tiling.items()
             if k[0] == "shard_pallas"]
    assert til_u and til_u[0]["skew"] is False
    assert til[0]["margin_overhead"] < til_u[0]["margin_overhead"]

    # stream dim decomposed -> the STREAM dim must not engage (its
    # carry would cross the shard boundary); the outer dim is still
    # whole on every shard and may skew on its own — equivalence holds
    sy = mk("shard_pallas", ranks=[("y", 2)])
    sy.run_solution(0, 3)
    assert sy.compare_data(ref, epsilon=1e-3, abs_epsilon=1e-4) == 0
    til_y = [t for k, t in sy._pallas_tiling.items()
             if k[0] == "shard_pallas"]
    assert til_y and "y" not in til_y[0]["skew_dims"]


# ---- multi-dim (2-D) skew ------------------------------------------------


def test_skew_per_dim_gate_and_widths(env):
    """Unit coverage for THE shared per-dim decision helpers: E_sk is
    paid only by the stream (sublane-window) dim, the profit gate
    evaluates per dim, ``max_dims`` is a positional window (1 = the
    stream dim only, never the outer dim swapped in), and ``unsharded``
    drops mesh-decomposed dims individually."""
    from yask_tpu.ops.pallas_stencil import (skew_engaged_dims,
                                             skew_extra_widths)

    cube = make(env, "pallas", "cube", r=1, g=32, wf=4)
    prog = cube._program
    lead = prog.ana.domain_dims[:-1]
    e = skew_extra_widths(prog, 4)
    assert e[lead[-1]] == 16      # r=1 misaligned: 2·sub_t widening
    assert e[lead[-2]] == 0       # outer dim is an untiled DMA axis
    # stream gate fails ((K+1)·1+16 ≥ 2·4·1); outer (E=0) passes
    assert skew_engaged_dims(prog, 4) == [lead[-2]]
    assert skew_engaged_dims(prog, 4, max_dims=1) == []
    assert skew_engaged_dims(prog, 4, max_dims=0) == []

    iso = make(env, "pallas", "iso3dfd", r=8, g=48, wf=2)
    ip = iso._program
    il = ip.ana.domain_dims[:-1]
    assert skew_engaged_dims(ip, 2) == list(il[-2:])
    assert skew_engaged_dims(ip, 2, max_dims=1) == [il[-1]]
    assert skew_engaged_dims(ip, 2, unsharded=[il[-1]]) == [il[-1]]
    assert skew_engaged_dims(ip, 2, unsharded=[il[-2]]) == [il[-2]]
    assert skew_engaged_dims(ip, 2, unsharded=[]) == []


def test_skew_plan_hints_per_dim(env):
    """Planner hints carry per-dim carry floors ((ring+1)·r) and per-dim
    skew margins ((K+1)·r + E_d) for exactly the engaged dims."""
    from yask_tpu.ops.pallas_stencil import skew_plan_hints

    iso = make(env, "pallas", "iso3dfd", r=8, g=48, wf=2)
    il = iso._program.ana.domain_dims[:-1]
    smin, smarg = skew_plan_hints(iso._program, 2)
    assert set(smarg) == set(il[-2:])
    assert smarg == {d: 3 * 8 for d in il[-2:]}   # (K+1)·r, E=0 aligned
    assert smin is not None and set(smin) == set(il[-2:])
    for d in smin:
        assert smin[d] > 0 and smin[d] % 8 == 0   # (ring+1)·8

    cube = make(env, "pallas", "cube", r=1, g=32, wf=4)
    cl = cube._program.ana.domain_dims[:-1]
    # legacy forced-1-D form: the stream dim's margin pays its E_sk
    _, sm1 = skew_plan_hints(cube._program, 4, engaged=True)
    assert sm1 == {cl[-1]: 5 * 1 + 16}
    # auto: only the outer dim engages, margin (K+1)·r with E=0
    _, sm2 = skew_plan_hints(cube._program, 4)
    assert sm2 == {cl[-2]: 5}
    # explicitly disengaged
    assert skew_plan_hints(cube._program, 4, engaged=False) == (None, None)


def test_skew2d_forced_matches_uniform(env):
    """Forcing BOTH lead dims (skew=[x, y]) must agree bit-for-bit with
    the uniform tiling on the same state — incl. the misaligned cube
    where auto would gate the stream dim out (forcing overrides the
    profit gate, not eligibility)."""
    from yask_tpu.ops.pallas_stencil import build_pallas_chunk

    for name, r, g, wf, blk in [("iso3dfd", 8, 48, 2, (24, 24)),
                                ("cube", 1, 32, 4, (16, 16))]:
        ctx = make(env, "pallas", name, r=r, g=g, wf=wf,
                   block={"x": blk[0], "y": blk[1]})
        lead = ctx._program.ana.domain_dims[:-1]
        sk, _ = build_pallas_chunk(ctx._program, fuse_steps=wf,
                                   block=blk, interpret=True,
                                   skew=list(lead))
        assert sk.tiling["skew_dims"] == list(lead)
        un, _ = build_pallas_chunk(ctx._program, fuse_steps=wf,
                                   block=blk, interpret=True, skew=False)
        st = {k: list(v) for k, v in ctx._state.items()}
        a = sk(st, 0)
        b = un(st, 0)
        for n in a:
            for x, y in zip(a[n], b[n]):
                np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                           rtol=2e-5, atol=1e-6)


def test_skew2d_auto_matches_jit(env):
    """End-to-end: default settings (skew_dims_max=2) auto-engage both
    lead dims on the aligned flagship; the run matches the XLA oracle
    and the modeled margin overhead is strictly below the uniform
    tiling's (the whole point of the second dim)."""
    ref = make(env, "jit", "iso3dfd", r=8, g=48)
    ref.run_solution(0, 3)

    p = make(env, "pallas", "iso3dfd", r=8, g=48, wf=2,
             block={"x": 24, "y": 24})
    p.run_solution(0, 3)
    assert p.compare_data(ref, epsilon=1e-3, abs_epsilon=1e-4) == 0
    til = p.get_stats().get_tiling()
    assert sorted(til["skew_dims"]) == \
        sorted(p._program.ana.domain_dims[:-1])

    un = make(env, "pallas", "iso3dfd", r=8, g=48, wf=2,
              block={"x": 24, "y": 24}, skew=False)
    un.run_solution(0, 3)
    assert un.compare_data(ref, epsilon=1e-3, abs_epsilon=1e-4) == 0
    tu = un.get_stats().get_tiling()
    assert til["margin_overhead"] < tu["margin_overhead"]


def test_skew2d_fallback_ladder(env):
    """Auto-engaged skew whose blocks sit below a dim's carry floor
    steps DOWN the ladder per dim — 2-D → 1-D (outer dim dropped) →
    uniform — while a forced request surfaces the constraint."""
    from yask_tpu.ops.pallas_stencil import (build_pallas_chunk,
                                             skew_plan_hints)

    ctx = make(env, "pallas", "iso3dfd", r=8, g=48, wf=2)
    prog = ctx._program
    lead = prog.ana.domain_dims[:-1]
    smin, _ = skew_plan_hints(prog, 2, engaged=list(lead))
    lo = {d: smin[d] - 8 for d in lead}     # below the carry floor
    hi = {d: smin[d] + 8 for d in lead}

    # outer dim below its floor → steps down to 1-D stream skew
    ch, _ = build_pallas_chunk(prog, fuse_steps=2,
                               block=(lo[lead[0]], hi[lead[1]]),
                               interpret=True)
    assert ch.tiling["skew_dims"] == [lead[-1]]

    # both below the floor → fully uniform
    ch0, _ = build_pallas_chunk(prog, fuse_steps=2,
                                block=(lo[lead[0]], lo[lead[1]]),
                                interpret=True)
    assert ch0.tiling["skew"] is False
    assert ch0.tiling["skew_dims"] == []

    # forced skew on an infeasible block raises instead of falling back
    with pytest.raises(YaskException):
        build_pallas_chunk(prog, fuse_steps=2,
                           block=(lo[lead[0]], lo[lead[1]]),
                           interpret=True, skew=list(lead))
