"""Unit tests for IdxTuple — the analog of the reference's tuple_test.cpp
(``src/common/tests/tuple_test.cpp``, target ``tuple-test``)."""

import pytest

from yask_tpu.utils.idx_tuple import (
    IdxTuple, parse_dim_val_str, n_choose_k, combination_at)
from yask_tpu.utils.exceptions import YaskException


def test_construction_and_access():
    t = IdxTuple(x=4, y=5, z=6)
    assert t.get_num_dims() == 3
    assert t.get_dim_names() == ["x", "y", "z"]
    assert t["y"] == 5
    assert t[2] == 6
    assert t.get_dim_posn("z") == 2
    with pytest.raises(YaskException):
        t["w"]


def test_product_and_arith():
    t = IdxTuple(x=4, y=5)
    assert t.product() == 20
    assert t.sum() == 9
    u = t.add_elements(IdxTuple(x=1, y=2))
    assert u.get_vals() == [5, 7]
    v = t.mult_elements(2)
    assert v.get_vals() == [8, 10]
    assert (t - IdxTuple(x=1, y=1)).get_vals() == [3, 4]
    assert t.max_elements(IdxTuple(x=10, y=0)).get_vals() == [10, 5]


def test_layout_unlayout_roundtrip():
    t = IdxTuple(x=3, y=4, z=5)
    for i in range(t.product()):
        pt = t.unlayout(i)
        assert t.layout(pt) == i
    # last dim is unit stride by default (TPU lanes convention)
    s = t.strides()
    assert s["z"] == 1 and s["y"] == 5 and s["x"] == 20
    # first_inner flips it
    t2 = IdxTuple({"x": 3, "y": 4}, first_inner=True)
    assert t2.strides()["x"] == 1


def test_layout_bounds():
    t = IdxTuple(x=3)
    with pytest.raises(YaskException):
        t.layout(IdxTuple(x=3))
    with pytest.raises(YaskException):
        t.unlayout(3)


def test_compact_factors():
    t = IdxTuple(x=0, y=0)
    f = t.get_compact_factors(12)
    assert f.product() == 12
    # compact: 3x4 (not 1x12)
    assert sorted(f.get_vals()) == [3, 4]
    f8 = IdxTuple(x=0, y=0, z=0).get_compact_factors(8)
    assert f8.product() == 8
    assert sorted(f8.get_vals()) == [2, 2, 2]


def test_parse_and_format():
    t = parse_dim_val_str("x=4, y=5")
    assert t["x"] == 4 and t["y"] == 5
    assert parse_dim_val_str(t.make_dim_val_str(sep=",")) == t
    with pytest.raises(YaskException):
        parse_dim_val_str("bogus")


def test_combinatorics():
    assert n_choose_k(5, 2) == 10
    seen = {tuple(combination_at(4, 2, i)) for i in range(n_choose_k(4, 2))}
    assert len(seen) == 6
    with pytest.raises(YaskException):
        combination_at(4, 2, 6)
