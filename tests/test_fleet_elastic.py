"""Elastic fleet: SLO-driven autoscaling, overload control, and the
load harness (ISSUE: elastic fleet under fire).

Layers under test:

* the pure :class:`AutoscalePolicy` decision loop (triggers, cooldown,
  bounds, dead-data refusal) with an injected clock — no fleet;
* telemetry snapshot staleness: ``merge_snapshots`` excludes flagged
  blocks, ``signals_from_snapshot`` never reads them, and the fleet
  front carries banked blocks forward honestly aged and surfaces
  ``stale_workers`` in ``fleet_stats``;
* worker-level overload control on an in-process ``StencilServer``:
  queue-wait deadline fast-fail (terminal ``rejected`` /
  ``deadline_in_queue``), brownout tier 1 (shed streaming flushes)
  and tier 2 (structured ``Overloaded`` + Retry-After on new
  sessions) — in-flight work never abandoned;
* fleet-level admission saturation (``YT_FLEET_MAX_QUEUE``):
  structured ``overloaded`` answer + journal row, and admission
  recovery once queues drain;
* the drain path: ``_scale_down`` migrates every session through the
  checkpoint/restore/replay machinery — zero lost, zero duplicated,
  contiguous steps after migration;
* the ``SERVE-AUTOSCALE-BOUNDS`` checker rule;
* (slow) the chaos soak and trace-replay tenant-mix reproduction via
  ``tools/load_harness.py``.

The closed-loop acceptance (burn spike -> journaled scale_up -> warm
spawn with zero lowerings -> idle drain scale_down) is
``make loadcheck`` (tools/load_harness.py --check), wired into
``make check``.
"""

import json
import os
import time

import pytest

from yask_tpu.resilience.faults import reset_faults
from yask_tpu.serve.autoscale import (AutoscalePolicy, ScaleSignals,
                                      signals_from_snapshot)

G = 8
PROFILE = {"stencil": "iso3dfd", "radius": 1, "g": G, "wf": 2}


@pytest.fixture(autouse=True)
def _clean_fault_plan(monkeypatch):
    monkeypatch.delenv("YT_FAULT_PLAN", raising=False)
    reset_faults()
    yield
    reset_faults()


# ---------------------------------------------------- policy units


def mk_policy(**kw):
    kw.setdefault("min_workers", 1)
    kw.setdefault("max_workers", 4)
    kw.setdefault("cooldown", 0.0)
    kw.setdefault("up_queue", 8)
    kw.setdefault("up_burn", 1.0)
    kw.setdefault("down_idle", 3)
    return AutoscalePolicy(**kw)


def sig(n=2, fresh=None, queue=0, burn=0.0, draining=0, stale=()):
    return ScaleSignals(n_workers=n, n_draining=draining,
                        fresh_workers=n if fresh is None else fresh,
                        stale_workers=list(stale),
                        queue_depth=queue, max_burn=burn)


def test_policy_refuses_dead_data():
    p = mk_policy(down_idle=1)
    # every worker stale: no decision, and the tick is NOT idle —
    # an unobserved fleet is not a quiet one
    for _ in range(5):
        assert p.decide(sig(fresh=0, stale=["w0", "w1"])) is None
    # the idle counter was held at zero throughout
    assert p._idle_ticks == 0


def test_policy_queue_trigger_and_max_bound():
    p = mk_policy(up_queue=8)
    d = p.decide(sig(n=2, queue=16))  # 8 per fresh worker
    assert d is not None and d.action == "up"
    assert d.reason == "queue_depth"
    assert d.signal["queue_depth"] == 16
    # at the ceiling the same signal decides nothing
    p2 = mk_policy(up_queue=8, max_workers=2)
    assert p2.decide(sig(n=2, queue=64)) is None


def test_policy_burn_trigger():
    p = mk_policy(up_burn=1.0)
    d = p.decide(sig(n=1, fresh=1, burn=2.5))
    assert d is not None and d.action == "up"
    assert d.reason == "burn_rate"
    assert d.signal["max_burn"] == 2.5
    # 0 disables the burn trigger entirely
    p2 = mk_policy(up_burn=0.0)
    assert p2.decide(sig(n=1, fresh=1, burn=99.0)) is None


def test_policy_cooldown_damps_flapping():
    now = [100.0]
    p = mk_policy(cooldown=30.0, clock=lambda: now[0])
    assert p.decide(sig(n=1, fresh=1, burn=5.0)).action == "up"
    # hot again inside the cooldown window: hold
    now[0] += 10.0
    assert p.decide(sig(n=2, burn=5.0)) is None
    # window elapsed: fires again
    now[0] += 25.0
    assert p.decide(sig(n=2, burn=5.0)).action == "up"
    # a decision in EITHER direction opens the window: idle ticks
    # accumulated during cooldown must not fire a down inside it
    now[0] += 1.0
    for _ in range(5):
        assert p.decide(sig(n=3)) is None
    now[0] += 40.0
    d = p.decide(sig(n=3))
    assert d is not None and d.action == "down"


def test_policy_idle_scale_down_and_min_floor():
    p = mk_policy(down_idle=3, min_workers=1)
    assert p.decide(sig(n=2)) is None
    assert p.decide(sig(n=2)) is None
    d = p.decide(sig(n=2))
    assert d is not None and d.action == "down" and d.reason == "idle"
    # at the floor, idleness decides nothing
    p2 = mk_policy(down_idle=1, min_workers=1)
    assert p2.decide(sig(n=1, fresh=1)) is None
    # a draining worker is excluded from the headroom
    p3 = mk_policy(down_idle=1, min_workers=1)
    assert p3.decide(sig(n=2, draining=1)) is None
    # queued work resets the idle streak
    p4 = mk_policy(down_idle=2)
    assert p4.decide(sig(n=2)) is None
    assert p4.decide(sig(n=2, queue=1)) is None
    assert p4.decide(sig(n=2)) is None


def test_signals_from_snapshot_skips_stale_and_errors():
    merged = {
        "workers": {
            "w0": {"occupancy": {"queue_depth": 3},
                   "slo": {"burn": {"latency_p99_ms": {
                       "budget": 0.01,
                       "windows": {"2": {"burn": 7.5, "bad": 3,
                                         "total": 4},
                                   "60": {"burn": 0.2, "bad": 3,
                                          "total": 90}}}}}},
            "w1": {"occupancy": {"queue_depth": 100},
                   "slo": {"burn": {"latency_p99_ms": {
                       "windows": {"2": {"burn": 50.0,
                                         "total": 10}}}}}},
            "w2": {"error": "ServeClientError: boom"},
        },
        "stale_workers": ["w1"],
    }
    s = signals_from_snapshot(merged, n_workers=3, n_draining=1)
    assert s.fresh_workers == 1          # w1 stale, w2 errored
    assert s.queue_depth == 3            # w1's 100 never counted
    assert s.max_burn == 7.5             # SHORTEST populated window
    assert s.stale_workers == ["w1"]
    assert s.n_draining == 1
    # no snapshot at all: zero fresh workers, policy will refuse
    s2 = signals_from_snapshot(None, n_workers=2)
    assert s2.fresh_workers == 0


def test_merge_snapshots_excludes_stale_blocks():
    from yask_tpu.obs.telemetry import merge_snapshots
    fresh = {"counters": {"serve.requests.completed": 5},
             "gauges": {}, "histograms": {}, "poll_age_secs": 0.0}
    stale = {"counters": {"serve.requests.completed": 100},
             "gauges": {}, "histograms": {},
             "poll_age_secs": 99.0, "stale": True}
    m = merge_snapshots({"w0": fresh, "w1": stale})
    assert m["stale_workers"] == ["w1"]
    # the stale worker's counters never entered the fold...
    assert m["merged"]["counters"]["serve.requests.completed"] == 5
    # ...but its block (honestly aged) is still visible per-worker
    assert m["workers"]["w1"]["poll_age_secs"] == 99.0


# ------------------------------------------- worker overload control


@pytest.fixture()
def server(tmp_path):
    from yask_tpu.serve import StencilServer
    srv = StencilServer(journal_path=str(tmp_path / "SERVE.jsonl"),
                        window_secs=0.01, preflight=False)
    yield srv
    srv.shutdown()


def _rows(path):
    out = []
    with open(path) as f:
        for ln in f:
            out.append(json.loads(ln))
    return out


def test_queue_deadline_fast_fail(server, tmp_path):
    """A request whose deadline expires while QUEUED is rejected with
    reason deadline_in_queue before it ever reaches the device."""
    from yask_tpu.serve import ServeRequest
    sid = server.open_session(**PROFILE)
    server.init_vars(sid)
    # head: a long first run (includes the lazy compile); second
    # request queues behind it on the same session with a deadline
    # far below the head's duration
    # 20 steps stays finite (the undamped profile grows nonfinite
    # past ~40) yet the first run's lazy compile keeps the worker
    # busy far beyond the second request's deadline
    h1 = server.submit(ServeRequest(session=sid, first_step=0,
                                    last_step=19))
    h2 = server.submit(ServeRequest(session=sid, first_step=20,
                                    last_step=20, deadline_secs=0.02))
    r1, r2 = server.wait(h1), server.wait(h2)
    assert r1.status == "ok", r1.error
    assert r2.status == "rejected", r2.status
    assert "deadline" in (r2.error or ""), r2.error
    rej = [r for r in _rows(str(tmp_path / "SERVE.jsonl"))
           if r["event"] == "rejected" and r["rid"] == r2.rid]
    assert rej and rej[-1]["detail"]["reason"] == "deadline_in_queue", rej
    snap = server.obs.snapshot()
    assert snap["counters"]["serve.overload.deadline_in_queue"] >= 1


@pytest.fixture()
def hot_slo_env(monkeypatch):
    """Every request breaches a 1 us p99 target on a short window —
    the burn rate saturates immediately and deterministically."""
    monkeypatch.setenv("YT_SLO_P99_MS", "0.001")
    monkeypatch.setenv("YT_SLO_WINDOWS", "60")
    yield


def test_brownout_tier1_sheds_flushes(hot_slo_env, monkeypatch,
                                      server, tmp_path):
    from yask_tpu.serve import ServeRequest
    sid = server.open_session(**PROFILE)
    server.init_vars(sid)
    h = server.submit(ServeRequest(session=sid, first_step=0,
                                   last_step=3, flush_every=1))
    assert server.wait(h).status == "ok"      # burn is now >> 2
    monkeypatch.setenv("YT_SERVE_SHED_BURN", "2.0")
    time.sleep(0.3)                           # tier cache ~250 ms
    assert server.scheduler.overload_tier() == 1
    h2 = server.submit(ServeRequest(session=sid, first_step=4,
                                    last_step=7, flush_every=1))
    r2 = server.wait(h2)
    # the run itself (and its final answer) is untouched...
    assert r2.status == "ok", r2.error
    rows = _rows(str(tmp_path / "SERVE.jsonl"))
    shed = [r for r in rows if r["event"] == "shed"
            and r["rid"] == r2.rid]
    streams = [r for r in rows if r["event"] == "stream"
               and r["rid"] == r2.rid]
    # ...but every progress beacon was shed, journaled with the tier
    assert shed and not streams, (shed, streams)
    assert all(r["detail"]["tier"] >= 1 for r in shed)
    snap = server.obs.snapshot()
    assert snap["counters"]["serve.overload.shed_flush"] >= len(shed)


def test_brownout_tier2_rejects_new_sessions(hot_slo_env, monkeypatch,
                                             server, tmp_path):
    from yask_tpu.serve import ServeRequest
    from yask_tpu.serve.api import Overloaded
    sid = server.open_session(**PROFILE)
    server.init_vars(sid)
    h = server.submit(ServeRequest(session=sid, first_step=0,
                                   last_step=1))
    assert server.wait(h).status == "ok"
    monkeypatch.setenv("YT_SERVE_SHED_BURN", "2.0")
    monkeypatch.setenv("YT_SERVE_REJECT_BURN", "4.0")
    monkeypatch.setenv("YT_SERVE_RETRY_AFTER", "2.5")
    time.sleep(0.3)
    assert server.scheduler.overload_tier() == 2
    with pytest.raises(Overloaded) as ei:
        server.open_session(**PROFILE)
    assert ei.value.retry_after == 2.5
    rows = [r for r in _rows(str(tmp_path / "SERVE.jsonl"))
            if r["event"] == "overloaded"]
    assert rows and rows[-1]["detail"]["tier"] == 2, rows
    snap = server.obs.snapshot()
    assert snap["counters"]["serve.overload.rejected_sessions"] >= 1
    assert snap["gauges"]["serve.overload.tier"] == 2
    # in-flight / established tenants are never abandoned: the
    # existing session still serves under tier 2
    h2 = server.submit(ServeRequest(session=sid, first_step=2,
                                    last_step=2))
    assert server.wait(h2).status == "ok"
    # burnout over: admission recovers
    monkeypatch.delenv("YT_SERVE_SHED_BURN")
    monkeypatch.delenv("YT_SERVE_REJECT_BURN")
    time.sleep(0.3)
    assert server.scheduler.overload_tier() == 0
    sid2 = server.open_session(**PROFILE)
    assert sid2


# ----------------------------------------------------- checker rule


@pytest.fixture()
def env():
    from yask_tpu import yk_factory
    return yk_factory().new_env()


def _serve_ctx(env):
    from yask_tpu import yk_factory
    ctx = yk_factory().new_solution(env, stencil="iso3dfd", radius=1)
    ctx.apply_command_line_options(f"-g {G} -wf_steps 2 -serve")
    return ctx


def _autoscale_diags(env):
    from yask_tpu.checker import run_checks
    report = run_checks(_serve_ctx(env), passes=("serve",))
    return [d for d in report.diagnostics
            if d.rule == "SERVE-AUTOSCALE-BOUNDS"]


def test_checker_autoscale_bounds(env, monkeypatch):
    # autoscale off: the rule never fires
    monkeypatch.delenv("YT_FLEET_AUTOSCALE", raising=False)
    assert not _autoscale_diags(env)
    # coherent knobs: info
    monkeypatch.setenv("YT_FLEET_AUTOSCALE", "1")
    d = _autoscale_diags(env)
    assert [x.severity for x in d] == ["info"], d
    # min above raw max: error (the policy clamps, the checker warns
    # the operator they asked for an impossible fleet)
    monkeypatch.setenv("YT_FLEET_MIN_WORKERS", "8")
    monkeypatch.setenv("YT_FLEET_MAX_WORKERS", "2")
    d = _autoscale_diags(env)
    assert [x.severity for x in d] == ["error"], d
    monkeypatch.delenv("YT_FLEET_MIN_WORKERS")
    monkeypatch.delenv("YT_FLEET_MAX_WORKERS")
    # zero cooldown: warn
    monkeypatch.setenv("YT_FLEET_SCALE_COOLDOWN", "0")
    d = _autoscale_diags(env)
    assert [x.severity for x in d] == ["warn"], d
    monkeypatch.delenv("YT_FLEET_SCALE_COOLDOWN")
    # both up-triggers disabled: warn (the fleet can only shrink)
    monkeypatch.setenv("YT_FLEET_SCALE_UP_QUEUE", "0")
    monkeypatch.setenv("YT_FLEET_SCALE_UP_BURN", "0")
    d = _autoscale_diags(env)
    assert [x.severity for x in d] == ["warn"], d


# ------------------------------------------------------ fleet level


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    from tools.serve_fleet import ServeFleet
    tmp = tmp_path_factory.mktemp("elastic")
    saved = {}
    env = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
           "YT_PERF_LEDGER": str(tmp / "ledger.jsonl")}
    for k, v in env.items():
        saved[k] = os.environ.get(k)
        os.environ[k] = v
    fl = ServeFleet(n_workers=2, cache_dir=str(tmp / "cache"),
                    journal_dir=str(tmp),
                    worker_args=["--no-preflight", "--window_ms", "5"])
    fl._tmpdir = str(tmp)
    try:
        yield fl
    finally:
        fl.close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _fleet_rows(fleet):
    return _rows(fleet.journal.path)


def test_saturation_rejects_structured_then_recovers(fleet,
                                                     monkeypatch):
    """Satellite: YT_FLEET_MAX_QUEUE saturation answers a structured
    overloaded rejection (journaled), and admission recovers once the
    queues drain."""
    from tools.serve_fleet import FleetWorker
    monkeypatch.setenv("YT_FLEET_MAX_QUEUE", "4")
    monkeypatch.setattr(
        FleetWorker, "occupancy",
        lambda self: {"queue_depth": 4, "sessions": 0, "completed": 0})
    out = fleet.handle({"op": "open", **PROFILE})
    assert not out.get("ok") and out.get("overloaded") is True, out
    assert float(out.get("retry_after", 0)) > 0, out
    assert "YT_FLEET_MAX_QUEUE" in out.get("error", ""), out
    rows = [r for r in _fleet_rows(fleet)
            if r.get("event") == "overloaded"]
    assert rows and rows[-1]["detail"]["queue_bound"] == 4, rows
    # queues drained (the monkeypatch expires): admission recovers
    monkeypatch.undo()
    monkeypatch.setenv("YT_FLEET_MAX_QUEUE", "4")
    s = fleet.handle({"op": "open", **PROFILE})
    assert s.get("ok"), s
    assert fleet.handle({"op": "init", "sid": s["sid"]})["ok"]
    r = fleet.handle({"op": "run", "sid": s["sid"],
                      "first": 0, "last": 1})
    assert r.get("ok"), r
    fleet._saturation_sid = s["sid"]          # reused by the drain test


def test_stale_worker_excluded_and_surfaced(fleet, monkeypatch):
    """Satellite: a worker whose snapshot aged past 3 heartbeat
    intervals is excluded from the merged fold and listed in
    fleet_stats.stale_workers."""
    from tools.serve_fleet import FleetWorker
    m = fleet.collect_telemetry(block=True)    # banks fresh blocks
    assert m["stale_workers"] == []
    assert m["workers"]["w0"]["poll_age_secs"] == 0.0
    # age worker 1's bank past the horizon and make its poll fail
    with fleet._lock:
        fleet._snap_bank[1]["ts"] -= fleet._stale_after() + 60.0
    real_call = FleetWorker.call

    def flaky(self, op, on_stream=None, **kw):
        if op == "metrics_snapshot" and self.idx == 1:
            raise RuntimeError("injected poll failure")
        return real_call(self, op, on_stream=on_stream, **kw)

    monkeypatch.setattr(FleetWorker, "call", flaky)
    m2 = fleet.collect_telemetry(block=True)
    assert m2["stale_workers"] == ["w1"], m2["stale_workers"]
    assert m2["workers"]["w1"]["poll_age_secs"] > fleet._stale_after()
    monkeypatch.undo()
    fs = fleet.handle({"op": "fleet_stats"})
    assert fs["ok"] and fs["stale_workers"] == ["w1"], fs
    # the autoscaler sees one fresh worker only
    s = signals_from_snapshot(m2, n_workers=2)
    assert s.fresh_workers == 1 and s.stale_workers == ["w1"]
    # a fresh poll un-stales it
    m3 = fleet.collect_telemetry(block=True)
    assert m3["stale_workers"] == []


def test_scale_down_drains_and_migrates(fleet):
    """The drain path end-to-end: sessions on the retiring tail
    worker are checkpointed and migrated (zero lost), the journal
    carries drain + scale_down rows, and migrated sessions keep
    serving contiguous steps."""
    from yask_tpu.serve.autoscale import Decision
    # place a session on the tail worker (least-loaded admission;
    # worker 0 already owns the saturation test's session)
    s = fleet.handle({"op": "open", **PROFILE})
    assert s.get("ok"), s
    assert fleet.handle({"op": "init", "sid": s["sid"]})["ok"]
    r = fleet.handle({"op": "run", "sid": s["sid"],
                      "first": 0, "last": 1})
    assert r.get("ok"), r
    tail = fleet.workers[-1]
    victims = sorted(tail.sessions)
    assert victims, "expected at least one session on the tail worker"
    fleet._scale_down(Decision("down", "idle", {"test": True}))
    assert len(fleet.workers) == 1
    rows = _fleet_rows(fleet)
    drains = [r for r in rows if r.get("event") == "drain"]
    downs = [r for r in rows if r.get("event") == "scale_down"]
    assert drains and downs, (drains, downs)
    det = downs[-1]["detail"]
    assert sorted(det["migrated"]) == victims, det
    assert det["lost"] == [], det
    assert det["reason"] == "idle"
    # every migrated session keeps serving contiguous steps on the
    # survivor
    for sid in victims:
        nxt = 2 if sid == s["sid"] else 0
        rr = fleet.handle({"op": "run", "sid": sid,
                           "first": nxt, "last": nxt})
        assert rr.get("ok"), (sid, rr)
    fs = fleet.handle({"op": "fleet_stats"})
    assert fs["ok"] and len(fs["workers"]) == 1


def test_drain_chaos_aborts_without_losing_sessions(fleet,
                                                    monkeypatch):
    """An injected fleet.drain fault aborts the scale-down: the
    worker is un-marked, nothing migrates, nothing is lost."""
    from yask_tpu.serve.autoscale import Decision
    # grow back to 2 workers first (manual mechanism call)
    fleet._scale_up(Decision("up", "queue_depth", {"test": True}))
    assert len(fleet.workers) == 2
    ups = [r for r in _fleet_rows(fleet)
           if r.get("event") == "scale_up"]
    assert ups and ups[-1]["detail"]["reason"] == "queue_depth"
    monkeypatch.setenv("YT_FAULT_PLAN", "fleet.drain:relay_down:1")
    reset_faults()
    before = {w.idx for w in fleet.workers}
    fleet._scale_down(Decision("down", "idle", {"test": True}))
    assert {w.idx for w in fleet.workers} == before
    assert not any(w.draining for w in fleet.workers)
    faults = [r for r in _fleet_rows(fleet)
              if r.get("event") == "fault"
              and r.get("detail", {}).get("site") == "fleet.drain"]
    assert faults, "aborted drain must journal a fault row"
    monkeypatch.delenv("YT_FAULT_PLAN")
    reset_faults()


# ------------------------------------------------------ load harness


def test_arrival_schedules_are_seeded_and_shaped():
    import random

    from tools.load_harness import arrivals
    a1 = arrivals("spike", 10.0, 1.0, random.Random(1))
    a2 = arrivals("spike", 10.0, 1.0, random.Random(1))
    assert a1 == a2 and len(a1) > 10
    p1 = arrivals("poisson", 20.0, 1.0, random.Random(2))
    assert all(0.0 <= t <= 1.0 for t in p1)
    s1 = arrivals("step", 10.0, 2.0, random.Random(3))
    first_half = sum(1 for t in s1 if t < 1.0)
    assert len(s1) - first_half > first_half  # rate doubles mid-run


def test_replay_reproduces_tenant_mix(fleet):
    """Replay derives (offset, tenant) pairs from recorded journal
    `received` rows — same tenants, same per-tenant request counts,
    order preserved."""
    from collections import Counter

    from tools.load_harness import replay_arrivals
    mix = Counter()
    paths = [w.journal_path for w in fleet.workers]
    for p in paths:
        for row in _rows(p):
            if row.get("event") == "received":
                mix[row["session"]] += 1
    assert mix, "fleet tests above should have recorded traffic"
    pairs = []
    for p in paths:
        pairs.extend(replay_arrivals(p))
    assert Counter(t for _off, t in pairs) == mix
    assert all(off >= 0.0 for off, _t in pairs)


@pytest.mark.slow
def test_soak_chaos_audit(tmp_path, monkeypatch):
    """The composed chaos soak: spike + worker kill + hang + zero
    output under one seeded plan, gated on exactly-once + oracle
    bit-identity + quarantine-only anomaly banking."""
    import argparse

    from tools.load_harness import run_soak
    monkeypatch.setenv("YT_PERF_LEDGER",
                       str(tmp_path / "ledger.jsonl"))
    args = argparse.Namespace(
        rate=8.0, duration=1.5, spike_mult=4.0, tenants=2, steps=2,
        flush_every=0, deadline=0.0, workers=2, seed=11,
        bank=True, no_oracle=False)
    rc = run_soak(args, str(tmp_path))
    assert rc == 0
    led = _rows(str(tmp_path / "ledger.jsonl"))
    goodput = [r for r in led if r["key"] == "load-soak-goodput"]
    assert goodput and goodput[-1]["source"] == "load"


@pytest.mark.slow
def test_load_run_banks_guarded_ledger_rows(tmp_path, monkeypatch):
    """A clean open-loop run banks p50/p99/goodput rows (source
    `load`) and the goodput row rides the sentinel floor rule."""
    import argparse

    from tools.load_harness import run_load
    monkeypatch.setenv("YT_PERF_LEDGER",
                       str(tmp_path / "ledger.jsonl"))
    args = argparse.Namespace(
        arrivals="poisson", rate=8.0, duration=1.0, spike_mult=4.0,
        tenants=2, steps=2, flush_every=0, deadline=0.0, workers=2,
        seed=7, replay="", replay_speed=1.0, bank=True,
        no_oracle=False)
    rc = run_load(args, str(tmp_path))
    assert rc == 0
    led = _rows(str(tmp_path / "ledger.jsonl"))
    byk = {}
    for r in led:
        byk.setdefault(r["key"], r)
    assert {"load-p50-ms", "load-p99-ms", "load-goodput"} <= set(byk)
    g = byk["load-goodput"]
    assert g["source"] == "load" and g["value"] >= 0.9
    from yask_tpu.perflab.sentinel import DEFAULT_RULES
    pats = [ru.pattern for ru in DEFAULT_RULES]
    assert any(p and p in "load-goodput" for p in pats), \
        "goodput floor rule must match the load-goodput key"
