"""yask_tpu.cache (persistent AOT compile cache): the trace counter
(`stats()["lowerings"]`) is the ground truth — a warm path must show
ZERO lowerings, and every failure path (corrupt entry, injected
load/store fault, eviction) must cost at most a compile, never a run.
`make cachecheck` runs this file; the cross-process test is the
acceptance criterion: a second process reuses the first's executable
without compiling once."""

import json
import os
import pickle
import subprocess
import sys

import numpy as np
import pytest

from yask_tpu import cache as ccache
from yask_tpu.cache.compile_cache import (SCHEMA, _SUFFIX,
                                          args_signature,
                                          backend_fingerprint,
                                          entry_path, key_digest)
from yask_tpu.resilience import reset_faults

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _isolate(monkeypatch):
    """Each test gets a clean memo/stats/fault plan; the disk dir is
    per-test via tmp_path where persistence is wanted."""
    monkeypatch.delenv("YT_COMPILE_CACHE", raising=False)
    monkeypatch.delenv("YT_COMPILE_CACHE_MAX", raising=False)
    monkeypatch.delenv("YT_FAULT_PLAN", raising=False)
    ccache.clear_memo()
    ccache.reset_stats()
    reset_faults()
    yield
    ccache.clear_memo()
    ccache.reset_stats()
    reset_faults()


def add3(x):
    return x + 3.0


def example():
    import jax.numpy as jnp
    return (jnp.ones((8,), dtype=jnp.float32),)


# ---------------------------------------------------------------- digests

def test_digest_covers_key_and_fingerprint():
    fp = {"jax": "1", "jaxlib": "2", "code": "abc", "platform": "cpu"}
    d1 = key_digest(("k", 1), fp)
    assert d1 == key_digest(("k", 1), dict(fp))          # stable
    assert d1 != key_digest(("k", 2), fp)                # key sensitivity
    assert d1 != key_digest(("k", 1), dict(fp, jax="9"))  # fp sensitivity
    assert len(d1) == 40


def test_fingerprint_carries_code_identity():
    fp = backend_fingerprint("tpu")
    assert fp["platform"] == "tpu"
    assert set(fp) == {"jax", "jaxlib", "code", "platform"}
    # memoized statics: a second call agrees
    assert backend_fingerprint("tpu") == fp


def test_same_key_different_shapes_do_not_collide():
    """The executable is shape-specialized: an identical caller key
    over different example shapes must be a different entry, or the
    second call would hand back an executable that raises."""
    import jax.numpy as jnp
    a = (jnp.ones((8,), dtype=jnp.float32),)
    b = (jnp.ones((16,), dtype=jnp.float32),)
    r1 = ccache.aot_compile(add3, a, key=("t", "sig"))
    r2 = ccache.aot_compile(add3, b, key=("t", "sig"))
    assert r1.digest != r2.digest
    assert r2.cache_hit is None and ccache.stats()["lowerings"] == 2
    assert float(r2.fn(*b)[0]) == 4.0


def test_same_key_different_placement_does_not_collide():
    """The round-13 regression class: a jit-oracle chunk and a
    sharded-mode chunk over identically-padded state share the caller
    key but compile sharding-incompatible executables — the args
    signature (which includes each leaf's sharding) must keep them
    apart."""
    import jax
    import jax.numpy as jnp
    devs = jax.devices()  # lint: devices-ok (conftest forces CPU mesh)
    if len(devs) < 2:
        pytest.skip("needs the multi-device CPU mesh (tests/conftest)")
    x0 = jax.device_put(jnp.ones((8,), dtype=jnp.float32), devs[0])
    x1 = jax.device_put(jnp.ones((8,), dtype=jnp.float32), devs[1])
    assert args_signature((x0,)) != args_signature((x1,))
    r1 = ccache.aot_compile(add3, (x0,), key=("t", "place"))
    r2 = ccache.aot_compile(add3, (x1,), key=("t", "place"))
    assert r1.digest != r2.digest
    assert float(r2.fn(x1)[0]) == 4.0


# ---------------------------------------------------------------- memo

def test_unkeyed_compile_counts_lowering():
    res = ccache.aot_compile(add3, example())
    assert res.cache_hit is None and res.digest is None
    assert ccache.stats()["lowerings"] == 1
    assert float(res.fn(*example())[0]) == 4.0


def test_keyed_memo_hit_is_zero_lowerings():
    r1 = ccache.aot_compile(add3, example(), key=("t", "memo"))
    r2 = ccache.aot_compile(add3, example(), key=("t", "memo"))
    assert r1.cache_hit is None and r2.cache_hit == "memory"
    assert r2.compile_secs == 0.0 and r2.fn is r1.fn
    assert ccache.stats()["lowerings"] == 1
    assert ccache.stats()["memory_hits"] == 1


def test_prejitted_callable_not_rewrapped():
    import jax
    jitted = jax.jit(add3, donate_argnums=0)
    res = ccache.aot_compile(jitted, example())
    assert float(res.fn(*example())[0]) == 4.0
    assert ccache.stats()["lowerings"] == 1


# ------------------------------------------------- cpu donation guard

def test_keyed_cpu_compile_strips_donation():
    # XLA:CPU deserialize-as-recompile mishandles donated aliased
    # buffers (freed-buffer scribble in passthrough outputs), so keyed
    # (persistable) cpu executables must be built WITHOUT donation:
    # the input survives the call.
    import jax.numpy as jnp
    x = jnp.ones((8,), jnp.float32)
    r = ccache.aot_compile(add3, (x,), key=("t", "dono"),
                           platform="cpu", donate_argnums=0)
    float(r.fn(x)[0])
    assert not x.is_deleted()
    r2 = ccache.aot_compile(add3, (x,), key=("t", "dono"), platform="cpu")
    assert r2.cache_hit == "memory"   # donation is not part of the digest


def test_unkeyed_compile_keeps_donation():
    import jax.numpy as jnp
    x = jnp.ones((8,), jnp.float32)
    r = ccache.aot_compile(add3, (x,), donate_argnums=0)
    float(r.fn(x)[0])
    assert x.is_deleted()


# ---------------------------------------------------------------- disk

def test_disk_roundtrip_within_process(tmp_path, monkeypatch):
    monkeypatch.setenv("YT_COMPILE_CACHE", str(tmp_path))
    r1 = ccache.aot_compile(add3, example(), key=("t", "disk"),
                            platform="cpu")
    assert r1.cache_hit is None and ccache.stats()["stores"] == 1
    assert os.path.exists(entry_path(r1.digest, str(tmp_path)))
    ccache.clear_memo()   # force the DISK path
    r2 = ccache.aot_compile(add3, example(), key=("t", "disk"),
                            platform="cpu")
    assert r2.cache_hit == "disk"
    assert ccache.stats()["lowerings"] == 1   # no second lowering
    assert float(r2.fn(*example())[0]) == 4.0


def test_corrupt_entry_falls_back_and_is_removed(tmp_path, monkeypatch):
    monkeypatch.setenv("YT_COMPILE_CACHE", str(tmp_path))
    r1 = ccache.aot_compile(add3, example(), key=("t", "corrupt"),
                            platform="cpu")
    path = entry_path(r1.digest, str(tmp_path))
    with open(path, "wb") as f:
        f.write(b"truncated garbage, not a pickle")
    ccache.clear_memo()
    r2 = ccache.aot_compile(add3, example(), key=("t", "corrupt"),
                            platform="cpu")
    assert r2.cache_hit is None              # fell back to a compile
    assert ccache.stats()["load_failures"] == 1
    assert ccache.stats()["lowerings"] == 2
    assert float(r2.fn(*example())[0]) == 4.0
    # the fresh result was re-stored over the corpse
    assert ccache.stats()["stores"] == 2
    with open(path, "rb") as f:
        assert pickle.load(f)["schema"] == SCHEMA


def test_stale_schema_entry_falls_back(tmp_path, monkeypatch):
    monkeypatch.setenv("YT_COMPILE_CACHE", str(tmp_path))
    r1 = ccache.aot_compile(add3, example(), key=("t", "schema"),
                            platform="cpu")
    path = entry_path(r1.digest, str(tmp_path))
    entry = pickle.load(open(path, "rb"))
    entry["schema"] = "yask_tpu.compile_cache/0"
    pickle.dump(entry, open(path, "wb"))
    ccache.clear_memo()
    r2 = ccache.aot_compile(add3, example(), key=("t", "schema"),
                            platform="cpu")
    assert r2.cache_hit is None
    assert ccache.stats()["load_failures"] == 1


def test_eviction_bounds_directory(tmp_path, monkeypatch):
    monkeypatch.setenv("YT_COMPILE_CACHE", str(tmp_path))
    monkeypatch.setenv("YT_COMPILE_CACHE_MAX", "2")
    for i in range(4):
        ccache.aot_compile(add3, example(), key=("t", "evict", i),
                           platform="cpu")
    names = [n for n in os.listdir(tmp_path) if n.endswith(_SUFFIX)]
    assert len(names) <= 2
    assert ccache.stats()["evictions"] >= 2
    assert ccache.stats()["stores"] == 4


def test_iter_entries_reports_meta_and_junk(tmp_path, monkeypatch):
    monkeypatch.setenv("YT_COMPILE_CACHE", str(tmp_path))
    ccache.aot_compile(add3, example(), key=("t", "iter"),
                       platform="cpu")
    (tmp_path / ("deadbeef" + _SUFFIX)).write_bytes(b"junk")
    (tmp_path / "ignored.txt").write_text("not an entry")
    metas = list(ccache.iter_entries(str(tmp_path)))
    assert len(metas) == 2
    good = [m for _, m in metas if "unreadable" not in m]
    bad = [m for _, m in metas if "unreadable" in m]
    assert len(good) == 1 and good[0]["schema"] == SCHEMA
    assert len(bad) == 1


# ------------------------------------------------------- fault injection

def test_injected_load_fault_degrades_to_compile(tmp_path, monkeypatch):
    monkeypatch.setenv("YT_COMPILE_CACHE", str(tmp_path))
    ccache.aot_compile(add3, example(), key=("t", "lf"), platform="cpu")
    ccache.clear_memo()
    monkeypatch.setenv("YT_FAULT_PLAN", "cache.load:compile_failed")
    reset_faults()
    r = ccache.aot_compile(add3, example(), key=("t", "lf"),
                           platform="cpu")
    assert r.cache_hit is None               # fault → fresh compile
    assert ccache.stats()["load_failures"] == 1
    assert float(r.fn(*example())[0]) == 4.0


def test_injected_store_fault_never_raises(tmp_path, monkeypatch):
    monkeypatch.setenv("YT_COMPILE_CACHE", str(tmp_path))
    monkeypatch.setenv("YT_FAULT_PLAN", "cache.store:compile_failed")
    reset_faults()
    r = ccache.aot_compile(add3, example(), key=("t", "sf"),
                           platform="cpu")
    assert float(r.fn(*example())[0]) == 4.0
    assert ccache.stats()["store_failures"] == 1
    assert ccache.stats()["stores"] == 0
    assert not [n for n in os.listdir(tmp_path) if n.endswith(_SUFFIX)]


# ------------------------------------------------- cross-process reuse

CHILD = r"""
import json, os, sys
sys.path.insert(0, {root!r})
from yask_tpu import cache as ccache
from yask_tpu import yk_factory
from yask_tpu.runtime.init_utils import init_solution_vars

fac = yk_factory()
env = fac.new_env()
ctx = fac.new_solution(env, stencil="iso3dfd", radius=2)
ctx.apply_command_line_options("-g 16 -wf_steps 2")
ctx.get_settings().mode = "jit"
ctx.prepare_solution()
init_solution_vars(ctx)
ctx.run_solution(0, 1)
mid = float(ctx.get_var("pressure").get_element([2, 8, 8, 8]))
print("STATS " + json.dumps(dict(ccache.stats(), probe=mid)))
"""


def test_cross_process_warm_cache_compiles_zero_times(tmp_path):
    """THE acceptance criterion: process 2 re-running process 1's
    config must deserialize the persisted executable and lower 0
    times (trace counter, not wall-clock)."""
    script = tmp_path / "child.py"
    script.write_text(CHILD.format(root=ROOT))
    env = dict(os.environ,
               YT_COMPILE_CACHE=str(tmp_path / "cache"),
               PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
    env.pop("YT_FAULT_PLAN", None)

    def run_child():
        out = subprocess.run(
            [sys.executable, str(script)], env=env, timeout=300,
            capture_output=True, text=True)
        assert out.returncode == 0, out.stderr[-2000:]
        line = [l for l in out.stdout.splitlines()
                if l.startswith("STATS ")][-1]
        return json.loads(line[len("STATS "):])

    cold = run_child()
    assert cold["lowerings"] >= 1 and cold["stores"] >= 1
    assert cold["disk_hits"] == 0
    warm = run_child()
    assert warm["lowerings"] == 0, warm
    assert warm["disk_hits"] >= 1 and warm["stores"] == 0
    # same executable → same numbers
    assert warm["probe"] == cold["probe"]
    entries = os.listdir(tmp_path / "cache")
    assert [n for n in entries if n.endswith(_SUFFIX)]
