"""Execution-mode equivalence: jit / sharded / shard_map / ref must agree —
the analog of the reference's MPI test arg-sets (``src/kernel/Makefile:
1044-1049``: same stencil run under varying rank layouts and compared to the
scalar reference)."""

import numpy as np
import pytest

from yask_tpu import yk_factory


@pytest.fixture(scope="module")
def env():
    e = yk_factory().new_env()
    if e.get_num_ranks() < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    return e


def make_ssg(env, mode, ranks=(), g=24, wf=0, spans=((0, 3),)):
    ctx = yk_factory().new_solution(env, stencil="ssg", radius=2)
    ctx.apply_command_line_options(f"-g {g} -wf_steps {wf}")
    ctx.get_settings().mode = mode
    for d, n in ranks:
        ctx.set_num_ranks(d, n)
    ctx.prepare_solution()
    rng = np.random.RandomState(7)
    for name in ctx.get_var_names():
        v = ctx.get_var(name)
        if name == "rho":
            v.set_all_elements_same(1.0)
        elif name in ("lambda_", "mu"):
            v.set_all_elements_same(0.01)
        elif name.startswith("v_"):
            arr = (rng.rand(g, g, g) * 0.1).astype(np.float32)
            v.set_elements_in_slice(arr, [0, 0, 0, 0], [0, g-1, g-1, g-1])
    for a, b in spans:
        ctx.run_solution(a, b)
    return ctx


@pytest.fixture(scope="module")
def ssg_ref(env):
    return make_ssg(env, "ref")


def test_jit_matches_ref(env, ssg_ref):
    assert make_ssg(env, "jit").compare_data(ssg_ref) == 0


def test_sharded_matches_ref(env, ssg_ref):
    ctx = make_ssg(env, "sharded", ranks=[("x", 4)])
    assert ctx.compare_data(ssg_ref) == 0


def test_shard_map_1d_matches_ref(env, ssg_ref):
    ctx = make_ssg(env, "shard_map", ranks=[("x", 4)])
    assert ctx.compare_data(ssg_ref) == 0


def test_shard_map_2d_mesh_matches_ref(env, ssg_ref):
    ctx = make_ssg(env, "shard_map", ranks=[("x", 2), ("y", 4)])
    assert ctx.compare_data(ssg_ref) == 0


def test_sharded_3d_mesh(env):
    """Full 3-D decomposition (2×2×2) with non-constant coefficients."""
    from yask_tpu.runtime.init_utils import init_solution_vars

    def run(mode, ranks=()):
        ctx = yk_factory().new_solution(env, stencil="fsg", radius=2)
        ctx.apply_command_line_options("-g 16")
        ctx.get_settings().mode = mode
        for d, n in ranks:
            ctx.set_num_ranks(d, n)
        ctx.prepare_solution()
        init_solution_vars(ctx)
        ctx.run_solution(0, 1)
        return ctx

    ref = run("ref")
    assert run("sharded",
               [("x", 2), ("y", 2), ("z", 2)]).compare_data(
                   ref, epsilon=1e-3, abs_epsilon=1e-4) == 0
    assert run("shard_map",
               [("x", 2), ("y", 2), ("z", 2)]).compare_data(
                   ref, epsilon=1e-3, abs_epsilon=1e-4) == 0


def test_shard_map_minor_dim_split(env, ssg_ref):
    # splitting the minor-most dim exercises lane-adjacent ghost slabs
    ctx = make_ssg(env, "shard_map", ranks=[("z", 2)])
    assert ctx.compare_data(ssg_ref) == 0


def test_auto_mode_selects_sharded(env):
    ctx = yk_factory().new_solution(env, stencil="3axis", radius=1)
    ctx.apply_command_line_options("-g 16")
    ctx.set_num_ranks("x", 2)
    ctx.prepare_solution()
    assert ctx._mode == "sharded"


def test_shard_geometry_validation(env):
    from yask_tpu.utils.exceptions import YaskException
    ctx = yk_factory().new_solution(env, stencil="3axis", radius=1)
    ctx.apply_command_line_options("-g 18")   # not divisible by 4
    ctx.get_settings().mode = "shard_map"
    ctx.set_num_ranks("x", 4)
    with pytest.raises(YaskException):
        ctx.prepare_solution()


def test_overlap_vs_no_overlap(env):
    a = make_ssg(env, "shard_map", ranks=[("x", 4)])
    ctx = yk_factory().new_solution(env, stencil="ssg", radius=2)
    ctx.apply_command_line_options("-g 24 -no-overlap_comms")
    ctx.get_settings().mode = "shard_map"
    ctx.set_num_ranks("x", 4)
    assert ctx.get_settings().overlap_comms is False
    ctx.prepare_solution()
    rng = np.random.RandomState(7)
    for name in ctx.get_var_names():
        v = ctx.get_var(name)
        if name == "rho":
            v.set_all_elements_same(1.0)
        elif name in ("lambda_", "mu"):
            v.set_all_elements_same(0.01)
        elif name.startswith("v_"):
            arr = (rng.rand(24, 24, 24) * 0.1).astype(np.float32)
            v.set_elements_in_slice(arr, [0, 0, 0, 0], [0, 23, 23, 23])
    ctx.run_solution(0, 3)
    assert ctx.compare_data(a) == 0


def test_scratch_and_conditions_sharded(env):
    """swe2d: scratch flux chains + IF_DOMAIN walls under shard_map with
    overlap — the hardest combination the exchange planner faces."""
    def run(mode, ranks=()):
        ctx = yk_factory().new_solution(env, stencil="swe2d")
        ctx.apply_command_line_options("-g 32")
        ctx.get_settings().mode = mode
        for d, nn in ranks:
            ctx.set_num_ranks(d, nn)
        ctx.prepare_solution()
        h0 = np.ones((32, 32), dtype=np.float32)
        h0[8:16, 8:16] = 2.0
        ctx.get_var("h").set_elements_in_slice(h0, [0, 0, 0], [0, 31, 31])
        ctx.get_var("hu").set_all_elements_same(0.0)
        ctx.get_var("hv").set_all_elements_same(0.0)
        ctx.get_var("lam").set_element(0.2, [])
        ctx.get_var("grav").set_element(1.0, [])
        ctx.run_solution(0, 3)
        return ctx

    ref = run("ref")
    assert run("jit").compare_data(ref) == 0
    assert run("shard_map", [("x", 4)]).compare_data(ref) == 0
    assert run("shard_map", [("x", 2), ("y", 2)]).compare_data(ref) == 0


def test_widening_ghost_widths_across_stages(env):
    """Regression: a later stage reading the same computed var with WIDER
    ghost offsets must re-exchange the union, not reuse the first stage's
    narrow refresh."""
    from yask_tpu.compiler.solution import yc_factory

    def build():
        soln = yc_factory().new_solution("widen")
        t = soln.new_step_index("t")
        x = soln.new_domain_index("x")
        y = soln.new_domain_index("y")
        a = soln.new_var("a", [t, x, y])
        b = soln.new_var("b", [t, x, y])
        c = soln.new_var("c", [t, x, y])
        a(t + 1, x, y).EQUALS(a(t, x, y) * 0.9 + 0.1)
        b(t + 1, x, y).EQUALS(a(t + 1, x - 1, y) + a(t + 1, x + 1, y))
        c(t + 1, x, y).EQUALS(a(t + 1, x - 2, y) + a(t + 1, x + 2, y)
                              + b(t + 1, x, y))
        return soln

    def run(mode, overlap=True):
        ctx = yk_factory().new_solution(env, build())
        ctx.apply_command_line_options("-g 32")
        ctx.get_settings().mode = mode
        ctx.get_settings().overlap_comms = overlap
        if mode != "ref":
            ctx.set_num_ranks("x", 4)
        ctx.prepare_solution()
        for n in ("a", "b", "c"):
            ctx.get_var(n).set_elements_in_seq(0.1)
        ctx.run_solution(0, 2)
        return ctx

    ref = run("ref")
    assert run("shard_map", overlap=True).compare_data(ref) == 0
    assert run("shard_map", overlap=False).compare_data(ref) == 0


def test_conditions_under_sharding(env):
    """Sub-domain conditions use global coordinates, so the conditional
    region must land identically however the domain is sharded."""
    from yask_tpu.compiler.solution import yc_factory

    def build():
        soln = yc_factory().new_solution("cond")
        t = soln.new_step_index("t")
        x = soln.new_domain_index("x")
        y = soln.new_domain_index("y")
        u = soln.new_var("u", [t, x, y])
        u(t + 1, x, y).EQUALS(u(t, x - 1, y) + 1.0).IF_DOMAIN(x >= 8)
        u(t + 1, x, y).EQUALS(u(t, x, y)).IF_DOMAIN(x < 8)
        return soln

    def run(mode, ranks=()):
        ctx = yk_factory().new_solution(env, build())
        ctx.apply_command_line_options("-g 16")
        ctx.get_settings().mode = mode
        for d, n in ranks:
            ctx.set_num_ranks(d, n)
        ctx.prepare_solution()
        ctx.get_var("u").set_elements_in_seq(0.1)
        ctx.run_solution(0, 2)
        return ctx

    ref = run("ref")
    assert run("jit").compare_data(ref) == 0
    assert run("shard_map", [("x", 4)]).compare_data(ref) == 0
    assert run("sharded", [("x", 4)]).compare_data(ref) == 0


# ---------------------------------------------------------------------------
# shard_pallas: shard_map outer + fused Pallas inner (the multi-chip
# scaling path — reference WF + exchange interplay, context.cpp:352-576)
# ---------------------------------------------------------------------------


def _run_sp(env, name, mode, wf=1, g=32, radius=2, ranks=None, steps=4):
    from yask_tpu.runtime.init_utils import init_solution_vars
    ctx = yk_factory().new_solution(env, stencil=name, radius=radius)
    ctx.apply_command_line_options(f"-g {g}")
    ctx.get_settings().mode = mode
    ctx.get_settings().wf_steps = wf
    for d, r in (ranks or []):
        ctx.set_num_ranks(d, r)
    ctx.prepare_solution()
    init_solution_vars(ctx)
    ctx.run_solution(0, steps - 1)
    return ctx


@pytest.mark.parametrize("wf,ranks", [
    (1, [("x", 4)]),
    (2, [("x", 4)]),
    (2, [("x", 2), ("y", 2)]),
    (3, [("x", 2), ("y", 4)]),   # K=3 exercises the remainder path (3+1)
])
def test_shard_pallas_iso3dfd_matches_oracle(env, wf, ranks):
    ref = _run_sp(env, "iso3dfd", "ref")
    sp = _run_sp(env, "iso3dfd", "shard_pallas", wf=wf, ranks=ranks)
    assert sp.compare_data(ref, epsilon=1e-3, abs_epsilon=1e-4) == 0


def test_shard_pallas_multi_stage_ssg(env):
    ref = _run_sp(env, "ssg", "ref", steps=2)
    for wf in (1, 2):
        sp = _run_sp(env, "ssg", "shard_pallas", wf=wf, steps=2,
                     ranks=[("x", 2), ("y", 2)])
        assert sp.compare_data(ref, epsilon=1e-3, abs_epsilon=1e-4) == 0


def test_shard_pallas_scratch_deep_ring_tti(env):
    """tti: scratch chain + 3-slot ring through the distributed fused
    path."""
    ref = _run_sp(env, "tti", "ref", steps=2)
    sp = _run_sp(env, "tti", "shard_pallas", wf=1, steps=2,
                 ranks=[("x", 2)])
    assert sp.compare_data(ref, epsilon=1e-2, abs_epsilon=1e-4) == 0


def test_shard_pallas_rejects_minor_split_with_fusion(env):
    from yask_tpu import YaskException
    with pytest.raises(YaskException):
        _run_sp(env, "iso3dfd", "shard_pallas", wf=2, ranks=[("z", 2)])
    # K=1 minor split is legal (exchange every step, no in-tile staleness)
    ref = _run_sp(env, "iso3dfd", "ref")
    sp = _run_sp(env, "iso3dfd", "shard_pallas", wf=1, ranks=[("z", 2)])
    assert sp.compare_data(ref, epsilon=1e-3, abs_epsilon=1e-4) == 0


# ---------------------------------------------------------------------------
# VERDICT r1 weak item 8: shard_map × wf_steps interplay, and per-dim
# asymmetric ghost widths through the overlap split's union re-exchange
# ---------------------------------------------------------------------------


def test_shard_map_with_wf_chunking(env, ssg_ref):
    """wf_steps chunking splits one run into several compiled shard_map
    programs; the chunk boundaries must be invisible."""
    ctx = make_ssg(env, "shard_map", ranks=[("x", 2), ("y", 2)])
    assert ctx.compare_data(ssg_ref) == 0
    # same span as 2-step chunks AND as two separate calls (resident
    # handover between them)
    ctx2 = make_ssg(env, "shard_map", ranks=[("x", 2), ("y", 2)],
                    wf=2, spans=((0, 1), (2, 3)))
    assert ctx2.compare_data(ssg_ref) == 0


def _asym(env, mode, ranks=(), overlap=True, g=24):
    """test_stages_3d: per-dim ASYMMETRIC stage ghost widths (x(0,1),
    y(2,1), z(1,0) then x(1,0), y(0,1), z(2,1) across two stages) — the
    union re-exchange corner of the overlap split."""
    from yask_tpu.runtime.init_utils import init_solution_vars
    ctx = yk_factory().new_solution(env, stencil="test_stages_3d")
    ctx.apply_command_line_options(f"-g {g}")
    ctx.get_settings().mode = mode
    ctx.get_settings().overlap_comms = overlap
    for d, n in ranks:
        ctx.set_num_ranks(d, n)
    ctx.prepare_solution()
    init_solution_vars(ctx)
    ctx.run_solution(0, 2)
    return ctx


@pytest.mark.parametrize("ranks,overlap", [
    ([("x", 4)], True),
    ([("x", 4)], False),
    ([("x", 2), ("y", 2)], True),
    ([("x", 2), ("y", 4)], True),
    ([("z", 2)], True),          # minor-dim split with asymmetric widths
])
def test_overlap_split_asymmetric_ghosts(env, ranks, overlap):
    ref = _asym(env, "ref")
    sm = _asym(env, "shard_map", ranks=ranks, overlap=overlap)
    assert sm.compare_data(ref, epsilon=1e-3, abs_epsilon=1e-4) == 0


def test_reverse_time_distributed(env):
    """Reverse-time stepping through both distributed paths, incl. the
    fused shard_pallas ring rotation in the negative step direction."""
    from yask_tpu.runtime.init_utils import init_solution_vars

    def run(mode, wf=0, ranks=()):
        ctx = yk_factory().new_solution(env, stencil="test_reverse_2d")
        ctx.apply_command_line_options("-g 24")
        ctx.get_settings().mode = mode
        ctx.get_settings().wf_steps = wf
        for d, r in ranks:
            ctx.set_num_ranks(d, r)
        ctx.prepare_solution()
        init_solution_vars(ctx)
        ctx.run_solution(0, 2)
        return ctx

    ref = run("ref")
    for mode, wf, ranks in (("shard_map", 0, (("x", 4),)),
                            ("shard_pallas", 1, (("x", 4),)),
                            ("shard_pallas", 2, (("x", 2),))):
        c = run(mode, wf=wf, ranks=ranks)
        assert c.compare_data(ref, epsilon=1e-3, abs_epsilon=1e-4) == 0, \
            (mode, wf)


def test_ring_reads_of_computed_var_refresh(env):
    """Regression (fuzz seed 1007): when a later stage reads an
    earlier-stage-COMPUTED var's previous-step ring values with ghost
    offsets, the refresh must exchange the ring slot too — exchanging
    only the computed array rotates stale shard ghosts into the next
    step (overlap path)."""
    from yask_tpu.compiler.solution import yc_factory

    def build():
        soln = yc_factory().new_solution("ringref")
        t = soln.new_step_index("t")
        x = soln.new_domain_index("x")
        y = soln.new_domain_index("y")
        a = soln.new_var("a", [t, x, y])
        b = soln.new_var("b", [t, x, y])
        s = soln.new_scratch_var("s", [x, y])
        # stage 0: conditional writer of a
        a(t + 1, x, y).EQUALS(a(t, x, y) * 0.5 + 0.1).IF_DOMAIN(x >= 3)
        # stage 1: scratch reads a's PREVIOUS-step ring values with
        # offsets; b consumes the scratch at an offset
        s(x, y).EQUALS(a(t, x - 1, y) + a(t - 1, x + 1, y))
        b(t + 1, x, y).EQUALS(s(x + 2, y) + b(t, x, y) * 0.5
                              + a(t + 1, x - 1, y))
        return soln

    def run(mode, overlap=True, ranks=()):
        ctx = yk_factory().new_solution(env, build())
        ctx.apply_command_line_options("-g 16")
        ctx.get_settings().mode = mode
        ctx.get_settings().overlap_comms = overlap
        for d, r in ranks:
            ctx.set_num_ranks(d, r)
        ctx.prepare_solution()
        for n in ("a", "b"):
            ctx.get_var(n).set_elements_in_seq(0.1)
        ctx.run_solution(0, 3)
        return ctx

    ref = run("ref")
    for overlap in (True, False):
        for ranks in ([("x", 2)], [("y", 4)], [("x", 2), ("y", 2)]):
            sm = run("shard_map", overlap=overlap, ranks=ranks)
            bad = sm.compare_data(ref, epsilon=1e-3, abs_epsilon=1e-4)
            assert bad == 0, (overlap, ranks, bad)


def test_resident_element_access_without_materialization(env):
    """Element get/set on device-resident shard state bypasses the
    materialize/re-pad round trip (the reference's dirty-flag cheap
    mid-run writes, yk_var.hpp:564) and matches the jit path doing the
    identical mid-run source injection."""
    def drive(mode, ranks=None):
        ctx = _run_sp(env, "iso3dfd", mode, wf=1, ranks=ranks, steps=4)
        v = ctx.get_var("pressure")
        mid = float(v.get_element([4, 16, 16, 16]))
        v.set_element(mid + 0.25, [4, 16, 16, 16])
        v.add_to_element(0.5, [4, 8, 8, 8])
        ctx.run_solution(4, 7)
        return ctx

    ref = drive("jit")
    sp = drive("shard_map", ranks=[("x", 4)])
    # state must still be device-resident after the element accesses
    # (the whole point of the escape hatch) ...
    assert sp._resident is not None and sp._state is None
    # interior slice get/set also ride the resident fast path
    box_r = ref.get_var("pressure").get_elements_in_slice(
        [8, 4, 4, 4], [8, 11, 11, 11])
    box_s = sp.get_var("pressure").get_elements_in_slice(
        [8, 4, 4, 4], [8, 11, 11, 11])
    assert sp._resident is not None and sp._state is None
    assert np.allclose(box_s, box_r, rtol=1e-3, atol=1e-4)
    for c in (ref, sp):
        c.get_var("pressure").set_elements_in_slice(
            np.full((8, 8, 8), 0.125, np.float32),
            [8, 4, 4, 4], [8, 11, 11, 11])
    assert sp._resident is not None and sp._state is None
    c2 = sp.get_var("pressure").get_elements_in_slice(
        [8, 4, 4, 4], [8, 11, 11, 11])
    assert np.all(c2 == 0.125)
    ref.run_solution(8, 9)
    sp.run_solution(8, 9)
    # ... and the physics must agree with the jit twin exactly
    assert sp.compare_data(ref, epsilon=1e-3, abs_epsilon=1e-4) == 0


def test_resident_fill_apis_without_materialization(env):
    """Whole-var fills (set_elements_in_seq / set_all_elements_same)
    ride the device-resident interiors directly — the examples'
    re-init-between-intervals pattern — instead of forcing the
    materialize/re-pad round trip, and match the jit twin doing the
    identical fills."""
    def drive(mode, ranks=None):
        ctx = _run_sp(env, "iso3dfd", mode, wf=1, ranks=ranks, steps=4)
        ctx.get_var("pressure").set_elements_in_seq(seed=0.07)
        ctx.get_var("vel").set_all_elements_same(0.375)
        if ranks:
            # the fills must not have materialized the resident state
            assert ctx._resident is not None and ctx._state is None
        ctx.run_solution(4, 7)
        return ctx

    ref = drive("jit")
    sp = drive("shard_map", ranks=[("x", 4)])
    assert sp._resident is not None and sp._state is None
    assert sp.compare_data(ref, epsilon=1e-3, abs_epsilon=1e-4) == 0


@pytest.mark.parametrize("src,dst", [
    ("jit", "shard_map"),
    ("shard_map", "jit"),
    ("jit", "pallas"),
    ("pallas", "shard_map"),
])
def test_checkpoint_portable_across_modes(env, ssg_ref, src, dst,
                                          tmp_path):
    """Interior-coordinate checkpoints are mode-portable: run 2 steps
    under one mode, checkpoint, restore into a differently-padded /
    sharded context, finish the remaining 2 steps there — and the mixed
    run is identical to the 4-step oracle (ghost zeros + interior fills
    are mode-invariant, so a snapshot carries the whole simulation)."""
    from yask_tpu.resilience import restore_checkpoint, save_checkpoint

    def build(mode, spans):
        ranks = [("x", 4)] if mode == "shard_map" else ()
        wf = 2 if mode == "pallas" else 0
        return make_ssg(env, mode, ranks=ranks, wf=wf, spans=spans)

    a = build(src, spans=((0, 1),))
    path = str(tmp_path / "ssg.ckpt.npz")
    save_checkpoint(a, path)
    b = build(dst, spans=())
    assert restore_checkpoint(b, path)
    assert b._cur_step == 2
    b.run_solution(2, 3)
    assert b.compare_data(ssg_ref) == 0
