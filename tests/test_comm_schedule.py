"""Communication-pattern scheduling (CommPlan): ordering, coalescing,
corner composition, measured collective rounds, checker rules.

The coalesced schedule packs every buffer's ghost slab for one
(axis, direction) into a single ppermute payload; ppermute only moves
bytes, so the packed schedule must be BIT-identical to the serial
per-buffer one (compare_data at zero tolerance), and axis-order
permutations must be too (either order sources the same diagonal
device's interior corner cells).  Against the jit oracle the shard
modes use the same mixed tolerance as the existing 3-D mesh test —
sharding the minor (lane) dim changes XLA's fusion layout enough for
fp32 contraction noise above the strict default epsilon.
"""

import numpy as np
import pytest

from yask_tpu import yk_factory
from yask_tpu.runtime.init_utils import init_solution_vars
from yask_tpu.utils.exceptions import YaskException


@pytest.fixture(scope="module")
def env():
    e = yk_factory().new_env()
    if e.get_num_ranks() < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    return e


def build(env, stencil, radius, g, mode, ranks=(), wf=0, opts="",
          steps=3):
    ctx = yk_factory().new_solution(env, stencil=stencil, radius=radius)
    ctx.apply_command_line_options(f"-g {g} -wf_steps {wf} " + opts)
    ctx.get_settings().mode = mode
    for d, n in ranks:
        ctx.set_num_ranks(d, n)
    ctx.prepare_solution()
    init_solution_vars(ctx)
    if steps:
        ctx.run_solution(0, steps - 1)
    return ctx


# ---- plan construction ----------------------------------------------------

def test_plan_fields_reasons_and_key(env):
    ctx = build(env, "ssg", 2, 24, "shard_map",
                ranks=[("x", 2), ("y", 2)], steps=0)
    plan = ctx.comm_plan()
    assert set(plan.order) == {"x", "y"}
    assert plan.mesh_shape == {"x": 2, "y": 2}
    # ssg moves many buffers: coalescing auto-engages and the modeled
    # round count drops to 2 per axis
    assert plan.coalesce is True
    assert plan.rounds == 2 * len(plan.order)
    assert plan.rounds_serial > plan.rounds
    codes = {r["code"] for r in plan.reasons}
    assert {"comm_axis", "comm_order",
            "comm_coalesce_engaged"} <= codes
    assert plan.errors == []
    # per-axis model fields are complete and JSON-clean
    for d in plan.order:
        a = plan.axes[d]
        assert a["kind"] in ("ici", "dcn")
        assert a["items"] > 0 and a["bytes"] > 0 and a["secs"] > 0
    import json
    json.dumps(plan.record())
    # the cache-key suffix bakes in exactly order + coalesce
    assert plan.key() == (",".join(plan.order), True)


def test_plan_explicit_order_and_append(env):
    ctx = build(env, "iso3dfd", 2, 24, "shard_map",
                ranks=[("x", 2), ("y", 2)], opts="-comm_order y",
                steps=0)
    plan = ctx.comm_plan()
    # explicit prefix honored, omitted exchanged axis appended
    assert plan.order[0] == "y" and set(plan.order) == {"x", "y"}
    assert any(r["code"] == "comm_order_appended" for r in plan.reasons)
    assert plan.errors == []


def test_invalid_comm_order_raises_at_run(env):
    ctx = build(env, "iso3dfd", 2, 24, "shard_map", ranks=[("x", 2)],
                opts="-comm_order q", steps=0)
    plan = ctx.comm_plan()
    assert plan.errors
    with pytest.raises(YaskException):
        ctx.run_solution(0, 1)


# ---- bit-equality across schedules ---------------------------------------

def test_coalesce_and_order_bitwise_2d(env):
    base = build(env, "iso3dfd", 2, 24, "shard_map",
                 ranks=[("x", 2), ("y", 2)], opts="-coalesce off")
    coal = build(env, "iso3dfd", 2, 24, "shard_map",
                 ranks=[("x", 2), ("y", 2)], opts="-coalesce on")
    perm = build(env, "iso3dfd", 2, 24, "shard_map",
                 ranks=[("x", 2), ("y", 2)],
                 opts="-coalesce on -comm_order y,x")
    assert coal.compare_data(base, epsilon=0.0, abs_epsilon=0.0) == 0
    assert perm.compare_data(base, epsilon=0.0, abs_epsilon=0.0) == 0
    ref = build(env, "iso3dfd", 2, 24, "jit")
    assert coal.compare_data(ref) == 0


def test_corner_composition_cube(env):
    """Diagonal ghosts as composed axis exchanges: the 27-point cube
    stencil reads corner neighbors, so a 2-D mesh shard needs the
    diagonal device's cells — which arrive because the y slab spans
    x's freshly filled ghosts.  No dedicated diagonal collectives:
    the plan orders {x,y} only, and the packed schedule stays
    bit-identical."""
    ref = build(env, "cube", 2, 16, "jit", steps=2)
    off = build(env, "cube", 2, 16, "shard_map",
                ranks=[("x", 2), ("y", 2)], opts="-coalesce off",
                steps=2)
    on = build(env, "cube", 2, 16, "shard_map",
               ranks=[("x", 2), ("y", 2)], opts="-coalesce on",
               steps=2)
    assert set(on.comm_plan().order) == {"x", "y"}  # no diagonal axis
    assert on.compare_data(off, epsilon=0.0, abs_epsilon=0.0) == 0
    assert on.compare_data(ref) == 0


def test_3d_mesh_sweep(env):
    """3-D virtual-mesh equivalence: shard_map (K=1) and shard_pallas
    (K=1 3-D / K=2 2-D — the minor dim may not shard at K>1) against
    the jit oracle, coalescing on and off, overlap on and off.  The
    minor-sharded cases use the mixed tolerance of the existing 3-D
    mesh test (fp32 layout noise, see module docstring); schedule
    pairs stay bitwise."""
    ref = build(env, "iso3dfd", 2, 16, "jit", steps=3)
    prev = {}
    for coal in ("off", "on"):
        for ov in ("", "-no-overlap_comms"):
            c = build(env, "iso3dfd", 2, 16, "shard_map",
                      ranks=[("x", 2), ("y", 2), ("z", 2)],
                      opts=f"-coalesce {coal} {ov}", steps=3)
            assert c.compare_data(ref, epsilon=1e-3,
                                  abs_epsilon=1e-4) == 0
            if ov in prev:
                assert c.compare_data(prev[ov], epsilon=0.0,
                                      abs_epsilon=0.0) == 0
            prev[ov] = c
    sp = build(env, "iso3dfd", 2, 16, "shard_pallas",
               ranks=[("x", 2), ("y", 2), ("z", 2)], wf=1, steps=3)
    assert sp.compare_data(ref, epsilon=1e-3, abs_epsilon=1e-4) == 0
    spk = build(env, "iso3dfd", 2, 32, "shard_pallas",
                ranks=[("x", 2), ("y", 2)], wf=2, steps=4)
    refk = build(env, "iso3dfd", 2, 32, "jit", steps=4)
    assert spk.compare_data(refk, epsilon=1e-3, abs_epsilon=1e-4) == 0
    # the K-group exchange batched through the plan stays bitwise with
    # the serial schedule
    spk2 = build(env, "iso3dfd", 2, 32, "shard_pallas",
                 ranks=[("x", 2), ("y", 2)], wf=2, steps=4,
                 opts="-coalesce on")
    assert spk2.compare_data(spk, epsilon=0.0, abs_epsilon=0.0) == 0


# ---- measured collective rounds ------------------------------------------

def test_halo_cal_counts_fewer_rounds_coalesced(env):
    """The acceptance criterion: on a 2-D mesh, halo calibration must
    report strictly fewer collectives per exchange round with
    coalescing on — counted at trace time of the exchange-only twin,
    not modeled."""
    def mk(coal):
        return build(env, "iso3dfd", 2, 24, "shard_map",
                     ranks=[("x", 2), ("y", 2)],
                     opts=f"-coalesce {coal} -measure_halo", steps=4)
    n_off = mk("off").get_stats().get_halo_collectives()
    n_on = mk("on").get_stats().get_halo_collectives()
    assert n_off > 0 and n_on > 0
    assert n_on < n_off
    # iso3dfd shard_map moves pressure (2 slots) + vel per axis: the
    # packed schedule hits the 2-per-axis floor
    assert n_on == 4


def test_ledger_fields(env):
    from yask_tpu.parallel.comm_plan import comm_ledger_fields
    ctx = build(env, "iso3dfd", 2, 24, "shard_map",
                ranks=[("x", 2), ("y", 2)],
                opts="-measure_halo", steps=4)
    f = comm_ledger_fields(ctx)
    assert f["mesh"] == {"x": 2, "y": 2}
    assert set(f["comm_order"]) == {"x", "y"}
    assert f["comm_rounds"] <= f["comm_rounds_serial"]
    assert set(f["comm_axis_kb"]) == {"x", "y"}
    assert all(v > 0 for v in f["comm_axis_kb"].values())
    assert f["comm_rounds_measured"] > 0


# ---- checker rules --------------------------------------------------------

def test_checker_comm_rules(env):
    from yask_tpu.checker import run_checks
    ctx = build(env, "ssg", 2, 24, "shard_map",
                ranks=[("x", 2), ("y", 2)], steps=0)
    rep = run_checks(ctx, passes=["races", "distributed"])
    rules = {d.rule for d in rep.diagnostics}
    assert "COMM-PLAN" in rules
    bad = build(env, "ssg", 2, 24, "shard_map", ranks=[("x", 2)],
                opts="-comm_order nope", steps=0)
    rep2 = run_checks(bad, passes=["races", "distributed"])
    assert any(d.rule == "COMM-ORDER" and d.severity == "error"
               for d in rep2.diagnostics)
    ser = build(env, "ssg", 2, 24, "shard_map", ranks=[("x", 2), ("y", 2)],
                opts="-coalesce off", steps=0)
    rep3 = run_checks(ser, passes=["races", "distributed"])
    assert any(d.rule == "COMM-SERIAL" for d in rep3.diagnostics)


def test_launch_multihost_single_process(env, tmp_path, capsys):
    """The multi-process launcher's single-process path runs end to end
    on the CPU mesh and prints the comm plan + stats."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import launch_multihost as lm
    rc = lm.main(["-stencil", "iso3dfd", "-radius", "2", "-g", "24",
                  "-mode", "shard_map", "-ranks", "x=2,y=2",
                  "-steps", "2"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "comm plan:" in out and "num-steps-done: 2" in out


def test_mesh_factory_multihost_shape(env):
    """make_mesh is the single construction site: an explicit device
    list (the jax.distributed global-list pattern) lays out the
    requested axis grid."""
    from yask_tpu.parallel.mesh import make_mesh
    devs = env.get_devices()
    m = make_mesh(devs, [("x", 2), ("y", 2), ("z", 2)])
    assert m.axis_names == ("x", "y", "z")
    assert dict(zip(m.axis_names, m.devices.shape)) == \
        {"x": 2, "y": 2, "z": 2}
    with pytest.raises(YaskException):
        make_mesh(devs[:4], [("x", 4), ("y", 2)])
