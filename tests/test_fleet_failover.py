"""Fleet supervision acceptance (tools/serve_fleet.py): worker health,
crash-restart, and checkpoint-backed session failover.

The chaos story, driven end-to-end on the CPU mesh:

* ``fleet.kill_worker`` (``YT_FAULT_PLAN`` in the worker's env)
  hard-exits the worker at the SECOND chunk-boundary flush of a
  streaming run — a mid-op crash with one stream line already
  delivered;
* the front detects the EOF, SIGKILLs the worker group, spawns a
  replacement warm-started from the shared compile cache, re-opens +
  restores the session from the last banked checkpoint, replays the
  committed ops past that boundary, and re-issues the in-flight run
  EXACTLY ONCE under its idempotency key;
* every response is bit-identical to an uninterrupted single-worker
  twin, and ``SERVE_JOURNAL.fleet.jsonl`` carries the ``worker_dead``
  → ``failover`` (dead worker id, snapshot step, replayed ranges) →
  ``retry`` trail;
* front-side ``fleet.heartbeat`` drops drive the miss-threshold
  unhealthy path into the same failover without any crash.

One module-scoped scenario amortizes the four worker-interpreter
spawns (the chaos worker, its two replacements, the twin) across every
assertion here.  Also wired into ``make faultcheck``.
"""

import os

import numpy as np
import pytest

from tools.serve_fleet import (ServeFleet, fleet_ckpt_every,
                               fleet_hb_deadline, fleet_hb_misses)
from yask_tpu.resilience.faults import reset_faults


@pytest.fixture(autouse=True)
def _fresh_faults():
    reset_faults()
    yield
    reset_faults()


def _run(fleet, sid, first, last, **extra):
    lines = []
    msg = {"op": "run", "sid": sid, "first": first, "last": last,
           **extra}
    r = fleet.handle(msg, emit=lines.append)
    return r, lines, msg


def _evs(rows, event):
    return [r for r in rows if r["event"] == event]


@pytest.fixture(scope="module")
def scenario(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("failover")
    (tmp / "A").mkdir()
    (tmp / "B").mkdir()
    saved = {}
    env = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
           "YT_PERF_LEDGER": str(tmp / "ledger.jsonl")}
    for k, v in env.items():
        saved[k] = os.environ.get(k)
        os.environ[k] = v
    reset_faults()

    # Worker-side kill plan — hits in the chaos worker's process:
    # run1 entry (1), run2 entry (2), run2 flush 1 (3, passes — its
    # stream line escapes: the at-least-once evidence), run2 flush 2
    # (4) → os._exit(17) mid-op.
    chaos_env = dict(os.environ)
    chaos_env["YT_FAULT_PLAN"] = "fleet.kill_worker:worker_dead:1:3"
    wargs = ["--no-preflight", "--window_ms", "5"]
    art = {}
    fl = ServeFleet(n_workers=1, cache_dir=str(tmp / "cache"),
                    journal_dir=str(tmp / "A"), worker_args=wargs,
                    env=chaos_env)
    # replacements must spawn WITHOUT the kill plan (a fresh process
    # would re-fire it and the single retry could never land)
    fl._base_env.pop("YT_FAULT_PLAN")
    tw = ServeFleet(n_workers=1, cache_dir=str(tmp / "cache"),
                    journal_dir=str(tmp / "B"), worker_args=wargs)
    try:
        sids = {}
        for key, f in (("a", fl), ("b", tw)):
            o = f.handle({"op": "open", "stencil": "iso3dfd",
                          "radius": 1, "g": 8, "wf": 2})
            assert o["ok"], o
            assert f.handle({"op": "init", "sid": o["sid"]})["ok"]
            sids[key] = o["sid"]
        art["sid"] = sids["a"]
        art["gen0"] = fl.workers[0]

        # run 1 (steps 0..3): committed via the pre-run snapshot @0
        for key, f in (("a", fl), ("b", tw)):
            r, _, _ = _run(f, sids[key], 0, 3)
            assert r["ok"], r

        # run 2 (steps 4..9, streaming): the chaos worker dies at the
        # second flush; the front must fail over and answer anyway
        art["r2a"], art["streams_a"], msg2 = _run(
            fl, sids["a"], 4, 9, flush_every=2)
        art["idem2"] = msg2.get("idem")
        art["gen1"] = fl.workers[0]
        art["r2b"], art["streams_b"], _ = _run(
            tw, sids["b"], 4, 9, flush_every=2)

        # run 3 (steps 10..11): service continues on the replacement
        art["r3a"], _, _ = _run(fl, sids["a"], 10, 11)
        art["r3b"], _, _ = _run(tw, sids["b"], 10, 11)

        # heartbeat drops (front-side site) → unhealthy → replaced
        os.environ["YT_FAULT_PLAN"] = "fleet.heartbeat:relay_down:2"
        reset_faults()
        try:
            fl.supervise_tick()
            art["after_tick1"] = (fl.workers[0],
                                  fl.workers[0].hb_misses)
            fl.supervise_tick()
            art["after_tick2"] = fl.workers[0]
        finally:
            del os.environ["YT_FAULT_PLAN"]
            reset_faults()

        # run 4 (steps 12..13): service continues on the 2nd repl
        art["r4a"], _, _ = _run(fl, sids["a"], 12, 13)
        art["r4b"], _, _ = _run(tw, sids["b"], 12, 13)

        art["cache0"] = fl.handle({"op": "cache_stats"})["stats"]["0"]
        art["jrows"] = fl.journal.rows()
        art["twin_jrows"] = tw.journal.rows()
        yield art
    finally:
        fl.close()
        tw.close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        reset_faults()


# ------------------------------------------------- failover acceptance

def test_crash_failover_is_bit_identical_to_twin(scenario):
    a, b = scenario["r2a"], scenario["r2b"]
    assert a["ok"], a
    assert b["ok"], b
    assert a["outputs"], "run answered without outputs"
    for name in b["outputs"]:
        x = np.asarray(a["outputs"][name]["data"])
        y = np.asarray(b["outputs"][name]["data"])
        assert np.array_equal(x, y), \
            f"{name}: failed-over run diverged from uninterrupted twin"
    # and the sessions stay bit-identical through later steps on BOTH
    # replacements (post-crash and post-heartbeat-failover)
    for ra, rb in ((scenario["r3a"], scenario["r3b"]),
                   (scenario["r4a"], scenario["r4b"])):
        assert ra["ok"] and rb["ok"], (ra, rb)
        for name in rb["outputs"]:
            assert np.array_equal(
                np.asarray(ra["outputs"][name]["data"]),
                np.asarray(rb["outputs"][name]["data"])), name
    # the twin never failed over
    twin_events = {r["event"] for r in scenario["twin_jrows"]}
    assert not twin_events & {"worker_dead", "failover", "retry"}


def test_failover_journal_trail(scenario):
    sid = scenario["sid"]
    rows = scenario["jrows"]
    dead = _evs(rows, "worker_dead")
    assert len(dead) == 2, dead
    assert dead[0]["rid"] == "w0.g0"
    assert dead[0]["detail"]["worker"] == 0
    assert dead[0]["detail"]["sessions"] == [sid]
    assert dead[1]["rid"] == "w0.g1"
    assert "missed 2 heartbeats" in dead[1]["detail"]["cause"]

    fo = _evs(rows, "failover")
    assert len(fo) == 2, fo
    assert all(r["rid"] == sid for r in fo)
    # crash failover: restored from the pre-run snapshot @0, replayed
    # the committed run 1 (0..3); the in-flight run 2 is NOT replay —
    # it is the exactly-once retry
    assert fo[0]["detail"]["dead_worker"] == 0
    assert fo[0]["detail"]["dead_gen"] == 0
    assert fo[0]["detail"]["to_gen"] == 1
    assert fo[0]["detail"]["snapshot_step"] == 0
    assert fo[0]["detail"]["replayed"] == [[0, 3]]
    # heartbeat failover: the cadence snapshot @10 (banked once run 2
    # pushed the session past YT_FLEET_CKPT_EVERY=8 steps) bounds the
    # replay to run 3 alone
    assert fo[1]["detail"]["dead_gen"] == 1
    assert fo[1]["detail"]["to_gen"] == 2
    assert fo[1]["detail"]["snapshot_step"] == 10
    assert fo[1]["detail"]["replayed"] == [[10, 11]]

    snaps = _evs(rows, "snapshot")
    assert {r["detail"]["step"] for r in snaps} >= {0, 10}, snaps


def test_inflight_retry_exactly_once(scenario):
    rows = scenario["jrows"]
    retries = _evs(rows, "retry")
    assert len(retries) == 1, retries     # re-issued exactly once
    d = retries[0]["detail"]
    assert d["op"] == "run"
    assert d["idem"] == scenario["idem2"]  # the SAME stamped key
    assert d["worker"] == 0 and d["gen"] == 1
    # streams are at-least-once across the failover: the flush line
    # that escaped before the kill repeats when the retry re-runs the
    # chunk; the step SET still matches the twin exactly
    steps_a = [ln["step"] for ln in scenario["streams_a"]]
    steps_b = [ln["step"] for ln in scenario["streams_b"]]
    assert sorted(set(steps_a)) == sorted(set(steps_b))
    assert len(set(steps_b)) == len(steps_b)   # twin: each step once
    assert len(steps_a) == len(steps_b) + 1    # one duplicated line
    assert steps_a.count(steps_b[0]) == 2      # ... the pre-kill flush


def test_heartbeat_miss_threshold_replaces_worker(scenario):
    w1, misses1 = scenario["after_tick1"]
    assert w1 is scenario["gen1"]          # first miss: counted only
    assert misses1 == 1
    w2 = scenario["after_tick2"]
    assert w2 is not scenario["gen1"]      # threshold: replaced
    assert w2.gen == 2


def test_replacement_warm_starts_from_shared_cache(scenario):
    # the gen-2 replacement replayed run 3 and served run 4 entirely
    # off the shared disk cache — zero fresh lowerings
    cs = scenario["cache0"]
    assert cs["lowerings"] == 0, cs
    assert cs["disk_hits"] > 0, cs


# ------------------------------------------------------ cheap units

def test_worker_fault_kinds(monkeypatch):
    from yask_tpu.resilience.faults import (FAULT_KINDS, WorkerDead,
                                            WorkerUnhealthy,
                                            fault_point)
    assert "worker_dead" in FAULT_KINDS
    assert "worker_unhealthy" in FAULT_KINDS
    monkeypatch.setenv("YT_FAULT_PLAN",
                       "k:worker_dead; u:worker_unhealthy")
    reset_faults()
    with pytest.raises(WorkerDead) as ei:
        fault_point("k")
    assert ei.value.kind == "worker_dead" and ei.value.site == "k"
    with pytest.raises(WorkerUnhealthy):
        fault_point("u")


def test_fleet_env_knobs(monkeypatch):
    monkeypatch.setenv("YT_FLEET_CKPT_EVERY", "3")
    assert fleet_ckpt_every() == 3
    monkeypatch.setenv("YT_FLEET_CKPT_EVERY", "junk")
    assert fleet_ckpt_every() == 8                 # bad value: default
    monkeypatch.setenv("YT_FLEET_HB_DEADLINE", "0.01")
    assert fleet_hb_deadline() == 0.1              # floored
    monkeypatch.setenv("YT_FLEET_HB_MISSES", "0")
    assert fleet_hb_misses() == 1                  # floored
