"""Tests for equation analysis: validity rules, dependencies, parts/stages,
halos, scratch propagation, lifespans (the behaviors of Eqs.cpp the reference
exercises through its stencil test suite)."""

import pytest

from yask_tpu.compiler.solution import yc_factory
from yask_tpu.utils.exceptions import YaskException


def new_soln(name="s"):
    soln = yc_factory().new_solution(name)
    t = soln.new_step_index("t")
    x = soln.new_domain_index("x")
    y = soln.new_domain_index("y")
    return soln, t, x, y


def test_halo_and_step_dir():
    soln, t, x, y = new_soln()
    u = soln.new_var("u", [t, x, y])
    u(t + 1, x, y).EQUALS(u(t, x - 2, y) + u(t, x + 3, y) + u(t, x, y - 1))
    ana = soln.analyze()
    assert ana.step_dir == 1
    assert u.halo["x"] == (2, 3)
    assert u.halo["y"] == (1, 0)
    assert u.get_step_alloc_size() == 2


def test_reverse_step_dir():
    soln, t, x, y = new_soln()
    u = soln.new_var("u", [t, x, y])
    u(t - 1, x, y).EQUALS(u(t, x + 1, y) * 0.5)
    ana = soln.analyze()
    assert ana.step_dir == -1


def test_mixed_step_dir_rejected():
    soln, t, x, y = new_soln()
    u = soln.new_var("u", [t, x, y])
    v = soln.new_var("v", [t, x, y])
    u(t + 1, x, y).EQUALS(u(t, x, y))
    v(t - 1, x, y).EQUALS(v(t, x, y))
    with pytest.raises(YaskException):
        soln.analyze()


def test_lhs_rules():
    soln, t, x, y = new_soln()
    u = soln.new_var("u", [t, x, y])
    u(t + 1, x + 1, y).EQUALS(u(t, x, y))   # offset LHS domain index
    with pytest.raises(YaskException):
        soln.analyze()

    soln2, t2, x2, y2 = new_soln("s2")
    w = soln2.new_var("w", [t2, x2, y2])
    w(t2 + 2, x2, y2).EQUALS(w(t2, x2, y2))  # step offset 2
    with pytest.raises(YaskException):
        soln2.analyze()


def test_intra_step_race_rejected_and_override():
    soln, t, x, y = new_soln()
    u = soln.new_var("u", [t, x, y])
    u(t + 1, x, y).EQUALS(u(t + 1, x - 1, y) + 1.0)  # reads own new value
    with pytest.raises(YaskException):
        soln.analyze()
    # the reference allows disabling the checker
    # (set_dependency_checker_enabled, yask_compiler_api.hpp:575)
    soln._analysis = None
    soln.set_dependency_checker_enabled(False)
    soln.analyze()


def test_same_step_dependency_makes_stages():
    soln, t, x, y = new_soln()
    a = soln.new_var("a", [t, x, y])
    b = soln.new_var("b", [t, x, y])
    a(t + 1, x, y).EQUALS(a(t, x, y) + b(t, x, y))
    b(t + 1, x, y).EQUALS(a(t + 1, x - 1, y) * 2.0)   # reads new a
    ana = soln.analyze()
    assert len(ana.stages) == 2
    first = ana.stages[0].parts[0].eqs[0].lhs.var_name()
    assert first == "a"
    # b needs fresh ghosts of the newly computed a before stage 2
    # (recorded for the exchange planner)


def test_circular_same_step_dependency_rejected():
    soln, t, x, y = new_soln()
    a = soln.new_var("a", [t, x, y])
    b = soln.new_var("b", [t, x, y])
    a(t + 1, x, y).EQUALS(b(t + 1, x, y) + 1.0)
    b(t + 1, x, y).EQUALS(a(t + 1, x, y) + 1.0)
    with pytest.raises(YaskException):
        soln.analyze()


def test_waw_ordering_preserves_registration_order():
    soln, t, x, y = new_soln()
    u = soln.new_var("u", [t, x, y])
    nfirst = u(t + 1, x, y).EQUALS(u(t, x, y) + 1.0)
    override = u(t + 1, x, y).EQUALS(0.0).IF_DOMAIN(x < 2)
    ana = soln.analyze()
    # the conditional override must be in a later (or same-order later) part
    order = []
    for st in ana.stages:
        for p in st.parts:
            order.extend(p.eqs)
    assert order.index(soln.get_equations()[0]) < \
        order.index(soln.get_equations()[1])


def test_scratch_halo_propagation():
    soln, t, x, y = new_soln()
    u = soln.new_var("u", [t, x, y])
    s = soln.new_scratch_var("s", [x, y])
    # s computed from u with radius 1; u(t+1) reads s at radius 2
    s(x, y).EQUALS(u(t, x - 1, y) + u(t, x + 1, y))
    u(t + 1, x, y).EQUALS(s(x - 2, y) + s(x + 2, y))
    ana = soln.analyze()
    # s must be computed over domain±2 (write-halo)
    assert ana.scratch_write_halo["s"]["x"] == (2, 2)
    # u's halo must cover write-halo(2) + its own read offset(1) = 3
    assert u.halo["x"][0] >= 3 and u.halo["x"][1] >= 3
    # scratch part runs in the same stage as its consumer
    assert len(ana.stages) == 1
    assert ana.stages[0].parts[0].is_scratch


def test_scratch_rules():
    soln, t, x, y = new_soln()
    with pytest.raises(YaskException):
        soln.new_scratch_var("bad", [t, x, y])  # scratch can't have step dim


def test_misc_dims():
    soln, t, x, y = new_soln()
    c = soln.new_misc_index("c")
    u = soln.new_var("u", [t, x, y])
    k = soln.new_var("k", [c, x, y])
    u(t + 1, x, y).EQUALS(k(0, x, y) * u(t, x - 1, y)
                          + k(2, x, y) * u(t, x + 1, y))
    ana = soln.analyze()
    assert k.misc_range["c"] == (0, 2)
    with pytest.raises(YaskException):
        k(c, x, y)  # misc dim must be a constant index


def test_pointwise_ring_reduction():
    # pure pointwise map needs only 1 ring slot (write-back optimization)
    soln, t, x, y = new_soln()
    u = soln.new_var("u", [t, x, y])
    u(t + 1, x, y).EQUALS(u(t, x, y) * 0.9)
    soln.analyze()
    assert u.get_step_alloc_size() == 1

    # 2nd-order-in-time with pointwise extreme read → 2 slots, not 3
    soln2, t2, x2, y2 = new_soln("s2")
    p = soln2.new_var("p", [t2, x2, y2])
    p(t2 + 1, x2, y2).EQUALS(2.0 * p(t2, x2, y2) - p(t2 - 1, x2, y2)
                             + p(t2, x2 - 1, y2))
    soln2.analyze()
    assert p.get_step_alloc_size() == 2

    # but a spatial read at the extreme offset forces the full span
    soln3, t3, x3, y3 = new_soln("s3")
    q = soln3.new_var("q", [t3, x3, y3])
    q(t3 + 1, x3, y3).EQUALS(q(t3, x3, y3) - q(t3 - 1, x3 - 1, y3))
    soln3.analyze()
    assert q.get_step_alloc_size() == 3


def test_sincos_pairing_counted_once():
    """sin(x)+cos(x) on one argument is charged a single transcendental
    (reference PairingVisitor, ExprUtils.hpp:137); both lowering
    backends materialize the pair in one visit. TTI's ti0-ti3 rotation
    trig is the motivating case."""
    from yask_tpu.compiler.solution_base import create_solution
    from yask_tpu.compiler.expr import CounterVisitor
    ana = create_solution("tti", radius=2).get_soln().compile().ana
    assert ana.sincos_args, "tti computes paired sin/cos of theta/phi"
    assert ana.counters.num_paired >= 2
    unpaired = CounterVisitor()
    for eq in ana.eqs:
        eq.accept(unpaired)
    assert ana.counters.num_ops == \
        unpaired.num_ops - ana.counters.num_paired


def test_partial_dim_write_race_rejected():
    """Writing a var that lacks a domain dim while the RHS (or a
    condition) varies along that dim is an intra-step race: every point
    of the missing extent would demand a different stored value.  The
    reference cannot express this (its loop nest is the LHS var's dims,
    Eqs.cpp:364-470); here it must raise."""
    import pytest
    from yask_tpu import YaskException
    from yask_tpu.compiler.solution import yc_factory

    def build(bad):
        soln = yc_factory().new_solution("pw_race")
        t = soln.new_step_index("t")
        x = soln.new_domain_index("x")
        y = soln.new_domain_index("y")
        a = soln.new_var("A", [t, x, y])
        p = soln.new_var("P", [t, y])
        if bad == "rhs":
            p(t + 1, y).EQUALS(a(t, x, y) * 0.5)
        elif bad == "cond":
            p(t + 1, y).EQUALS(p(t, y) * 0.5).IF_DOMAIN(x >= 4)
        else:
            p(t + 1, y).EQUALS(p(t, y) * 0.5)
        a(t + 1, x, y).EQUALS(a(t, x, y) * 0.5 + p(t, y) * 0.1)
        return soln

    build("ok").compile()   # constant along x: fine
    with pytest.raises(YaskException, match="race"):
        build("rhs").compile()
    with pytest.raises(YaskException, match="race"):
        build("cond").compile()
