"""Seeded DSL fuzz: random small solutions must agree between the
compiled path and the numpy oracle — a breadth net over lowering edge
cases beyond the hand-written fixtures (the reference gets this breadth
from ~50 stencil×config combos; we add randomized structure)."""

import numpy as np
import pytest

from yask_tpu import yk_factory
from yask_tpu.compiler.solution import yc_factory
from yask_tpu.compiler import expr as E


def random_solution(rng, idx):
    soln = yc_factory().new_solution(f"fuzz_{idx}")
    t = soln.new_step_index("t")
    nd = rng.choice([1, 2, 3])
    dims = [soln.new_domain_index(d) for d in ["x", "y", "z"][:nd]]
    nvars = rng.randint(1, 4)
    vars_ = [soln.new_var(f"v{i}", [t] + dims) for i in range(nvars)]
    # coefficient var, sometimes carrying a misc (channel-style) dim
    coeff = None
    coeff_misc = False
    if rng.rand() < 0.5:
        if rng.rand() < 0.4:
            m = soln.new_misc_index("m")
            coeff = soln.new_var("k", [m] + dims)
            coeff_misc = True
        else:
            coeff = soln.new_var("k", dims)
    # scratch var: written from the vars, read at offsets by final eqs
    scratch = soln.new_scratch_var("s", dims) if rng.rand() < 0.4 else None
    # partial-dim WRITTEN var (lacks the first domain dim, keeps the
    # minor): its RHS must be constant along the missing dim, so it only
    # reads itself/constants; full vars read it back (broadcast)
    pvar = None
    if len(dims) >= 2 and rng.rand() < 0.4:
        pvar = soln.new_var("pv", [t] + dims[1:])

    def rand_expr(depth=0, allow_scratch=False):
        r = rng.rand()
        if depth > 2 or r < 0.3:
            v = vars_[rng.randint(nvars)]
            offs = [int(rng.randint(-2, 3)) for _ in dims]
            rr = rng.rand()
            # mostly newest-slot reads; sometimes t-1, rarely t-2
            so = 0 if rr < 0.75 else (-1 if rr < 0.93 else -2)
            args = [t + so] + [d + o for d, o in zip(dims, offs)]
            p = v(*args)
            return p
        if r < 0.4:
            return E.ConstExpr(float(np.round(rng.uniform(-1, 1), 3)))
        if r < 0.5 and coeff is not None:
            if coeff_misc:
                return coeff(int(rng.randint(-1, 2)), *dims)
            return coeff(*dims)
        if r < 0.58 and allow_scratch and scratch is not None:
            offs = [int(rng.randint(-2, 3)) for _ in dims]
            return scratch(*[d + o for d, o in zip(dims, offs)])
        if r < 0.62 and pvar is not None:
            offs = [int(rng.randint(-1, 2)) for _ in dims[1:]]
            return pvar(t, *[d + o for d, o in zip(dims[1:], offs)])
        a = rand_expr(depth + 1, allow_scratch)
        b = rand_expr(depth + 1, allow_scratch)
        op = rng.choice(["+", "-", "*"])
        if op == "+":
            return a + b
        if op == "-":
            return a - b
        return a * E.ConstExpr(0.3) + b * E.ConstExpr(0.2)

    if scratch is not None:
        scratch(*dims).EQUALS(rand_expr(depth=1) * 0.3)
    if pvar is not None:
        prhs = pvar(t, *dims[1:]) * 0.6 + E.ConstExpr(0.05)
        if rng.rand() < 0.5:
            prhs = prhs + pvar(
                t, *[d + 1 for d in dims[1:]]) * 0.1
        pvar(t + 1, *dims[1:]).EQUALS(prhs)
    for v in vars_:
        rhs = rand_expr(allow_scratch=True) * 0.2 + v(t, *dims) * 0.5
        eq = v(t + 1, *dims).EQUALS(rhs)
        if rng.rand() < 0.3 and len(dims) >= 1:
            eq.IF_DOMAIN(dims[0] >= 3)
        elif rng.rand() < 0.15:
            # step-parity condition: unselected points keep evicted-slot
            # values, exercising deep-ring base semantics per mode
            eq.IF_STEP((t % 2) == 0)
            v(t + 1, *dims).EQUALS(v(t, *dims) * 0.9).IF_STEP((t % 2) == 1)
    return soln


@pytest.mark.parametrize("seed", range(10))
def test_fuzzed_solution_jit_matches_oracle(seed):
    rng = np.random.RandomState(1000 + seed)
    soln = random_solution(rng, seed)
    env = yk_factory().new_env()

    def run(mode):
        ctx = yk_factory().new_solution(env, soln)
        ctx.apply_command_line_options("-g 10")
        ctx.get_settings().mode = mode
        ctx.prepare_solution()
        from yask_tpu.runtime.init_utils import init_solution_vars
        init_solution_vars(ctx, seed=0.03)
        ctx.run_solution(0, 2)
        return ctx

    a, b = run("jit"), run("ref")
    bad = a.compare_data(b, epsilon=1e-3, abs_epsilon=1e-4)
    assert bad == 0, f"seed {seed}: {bad} mismatches\n" \
        + "\n".join(e.format_simple() for e in soln.get_equations())

    # ≥2-D eligible fuzzed solutions also exercise the fused Pallas path
    from yask_tpu.ops.pallas_stencil import pallas_applicable
    if len(soln.domain_dim_names()) >= 2 \
            and pallas_applicable(soln.compile())[0]:
        p = run("pallas")
        bad = p.compare_data(b, epsilon=1e-3, abs_epsilon=1e-4)
        assert bad == 0, f"seed {seed} (pallas): {bad} mismatches"

    # ...and the explicit distributed path (scratch/misc structures
    # through the ghost-exchange planner), BOTH refresh hooks: the
    # overlap split and the plain per-stage hook.  Partial-dim written
    # vars are sound here by construction: the analysis race rule
    # guarantees their RHS is constant along missing dims, so a var
    # lacking the sharded dim is updated identically on every rank
    # (replicated write), and one sharded along its own dims exchanges
    # like any other var.
    dims = soln.domain_dim_names()
    if len(dims) >= 2:
        def run_sharded(overlap):
            env2 = yk_factory().new_env()
            ctx = yk_factory().new_solution(env2, soln)
            ctx.apply_command_line_options("-g 10")
            ctx.get_settings().mode = "shard_map"
            ctx.get_settings().overlap_comms = overlap
            ctx.set_num_ranks(dims[0], 2)
            ctx.prepare_solution()
            from yask_tpu.runtime.init_utils import init_solution_vars
            init_solution_vars(ctx, seed=0.03)
            ctx.run_solution(0, 2)
            return ctx
        for overlap in (True, False):
            sm = run_sharded(overlap)
            bad = sm.compare_data(b, epsilon=1e-3, abs_epsilon=1e-4)
            assert bad == 0, \
                f"seed {seed} (shard_map overlap={overlap}): {bad}"


def test_fuzz_resident_reads_match_materialized():
    """Random interior/pad-straddling boxes read identically through
    the device-resident fast path and the strict materializing path —
    the equivalence contract of the r5 escape hatch (element and slice
    APIs must not depend on internal state residency)."""
    import numpy as np
    from yask_tpu import yk_factory
    from yask_tpu.runtime.init_utils import init_solution_vars

    fac = yk_factory()
    env = fac.new_env()
    g = 24
    ctx = fac.new_solution(env, stencil="iso3dfd", radius=2)
    ctx.apply_command_line_options(f"-g {g}")
    ctx.get_settings().mode = "shard_map"
    ctx.set_num_ranks("x", 4)
    ctx.prepare_solution()
    init_solution_vars(ctx)
    ctx.run_solution(0, 3)
    assert ctx._resident is not None

    rng = np.random.RandomState(11)
    v = ctx.get_var("pressure")
    boxes = []
    for _ in range(12):
        lo = [int(rng.randint(0, g - 1)) for _ in range(3)]
        hi = [int(rng.randint(l, g)) for l in lo]
        boxes.append(([4] + lo, [4] + hi))
    pts = [[4] + [int(rng.randint(0, g)) for _ in range(3)]
           for _ in range(8)]

    res_boxes = [v.get_elements_in_slice(a, b) for a, b in boxes]
    res_pts = [v.get_element(p) for p in pts]
    assert ctx._resident is not None  # reads stayed on the fast path

    ctx._materialize_state()          # force the strict path
    for (a, b), r in zip(boxes, res_boxes):
        np.testing.assert_array_equal(v.get_elements_in_slice(a, b), r)
    for p, r in zip(pts, res_pts):
        assert v.get_element(p) == r
