"""The telemetry plane over the obs spine (yask_tpu/obs/telemetry.py,
slo.py, attribution.py + tools/obs_export.py, serve_fleet aggregation).

The contract under test, end to end:

* **Merge semantics** — fleet snapshots merge histogram windows by
  POOLING raw samples and re-ranking; percentiles are never averaged
  (the mean of two worker p99s is not the fleet p99).  Counters and
  gauges sum; per-worker blocks ride along without raw windows.
* **Name stability** — the ``STABLE_*`` registry names are the
  dashboard contract; renaming one fails here.  Prometheus exposition
  derives names mechanically (``serve.total_ms`` → ``yt_serve_total_ms``).
* **SLO burn rate** — multi-window burn over budget with per-SLI
  cooldown; a breach needs EVERY window burning.  OFF (None monitor)
  unless a ``YT_SLO_*`` knob is set; LOG-ONLY when on: a breach is a
  journaled ``slo_breach`` row joined to the offending trace id,
  never a blocked request.
* **Attribution** — a traced supervised run's per-phase span
  self-times sum to the root span's wall time (within 10%), join the
  perf-ledger row by trace id, pick up the roofline model for the
  compute phase, and bank as one ``source:"attribution"`` row whose
  phase shares ride the sentinel's drift guard.  Quarantined perf rows
  poison the run; halo-cal-unstable rows are excluded from the report.
* **Fleet acceptance** — a 2-worker fleet under an injected
  ``serve.run`` device_hang merges both workers' snapshots and banks
  at least one breach row per faulted worker.

Wired into ``make telemetrycheck`` (and ``make check``).
"""

import io
import json
import os

import numpy as np
import pytest

from yask_tpu.obs import metrics as obs_metrics
from yask_tpu.obs import tracer
from yask_tpu.obs.slo import SLO_SCHEMA, SloMonitor, slo_enabled
from yask_tpu.obs.telemetry import (STABLE_COUNTER_PREFIXES,
                                    STABLE_COUNTERS, STABLE_GAUGES,
                                    STABLE_HISTOGRAMS, TELEMETRY_SCHEMA,
                                    merge_snapshots, prom_name,
                                    to_prometheus)
from yask_tpu.resilience.faults import reset_faults

G = 8
STEPS = 4


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for k in list(os.environ):
        if k.startswith("YT_SLO_"):
            monkeypatch.delenv(k)
    monkeypatch.delenv("YT_FAULT_PLAN", raising=False)
    monkeypatch.delenv("YT_TRACE", raising=False)
    monkeypatch.delenv("YT_TRACE_EVENTS", raising=False)
    monkeypatch.setattr(tracer, "_compact_checked", False)
    reset_faults()
    yield
    reset_faults()


def _hist(samples):
    xs = [float(x) for x in samples]
    return {"count": len(xs),
            "mean": sum(xs) / len(xs) if xs else 0.0,
            "p50": obs_metrics.percentile(xs, 0.50),
            "p99": obs_metrics.percentile(xs, 0.99),
            "max": max(xs) if xs else 0.0,
            "window": len(xs),
            "samples": xs}


# ----------------------------------------------------- merge semantics

def test_merge_pools_samples_never_averages():
    """The one rule that matters: the fleet p99 is the percentile of
    the POOLED window, not the mean of per-worker p99s."""
    a = {"counters": {"serve.requests.ok": 150},
         "gauges": {"serve.queue_depth": 2},
         "histograms": {"serve.total_ms": _hist([1.0] * 150)}}
    b = {"counters": {"serve.requests.ok": 50},
         "gauges": {"serve.queue_depth": 3},
         "histograms": {"serve.total_ms": _hist([1000.0] * 50)}}
    out = merge_snapshots({"w0": a, "w1": b}, ts=123.5)
    assert out["v"] == TELEMETRY_SCHEMA
    assert out["ts"] == 123.5
    m = out["merged"]["histograms"]["serve.total_ms"]
    pooled = obs_metrics.percentile([1.0] * 150 + [1000.0] * 50, 0.99)
    averaged = (1.0 + 1000.0) / 2
    assert m["p99"] == pooled == 1000.0
    assert m["p99"] != averaged
    assert m["count"] == 200
    # count-weighted mean, max of maxes
    assert m["mean"] == pytest.approx((150 * 1.0 + 50 * 1000.0) / 200)
    assert m["max"] == 1000.0
    # counters and gauges sum
    assert out["merged"]["counters"]["serve.requests.ok"] == 200
    assert out["merged"]["gauges"]["serve.queue_depth"] == 5.0


def test_merge_keeps_worker_extras_without_raw_windows():
    a = {"counters": {"c": 1},
         "histograms": {"h": _hist([1.0, 2.0])},
         "occupancy": {"sessions": 3}, "slo": None}
    out = merge_snapshots({"w0": a, "w1": {"error": "EOFError: gone"}})
    w0 = out["workers"]["w0"]
    assert w0["occupancy"] == {"sessions": 3}       # extras ride along
    assert "samples" not in w0["histograms"]["h"]   # raw window dropped
    assert out["workers"]["w1"]["error"].startswith("EOFError")
    assert out["merged"]["counters"] == {"c": 1}    # dead worker = absent
    assert json.loads(json.dumps(out)) == out       # JSON-able


# ------------------------------------------------------ name stability

def test_stable_names_pinned():
    """The dashboard contract: renaming a registry metric fails here
    first, not in a grafana panel three weeks later."""
    assert STABLE_COUNTERS == ("serve.requests.ok",
                               "serve.requests.anomaly",
                               "serve.requests.rejected",
                               "serve.degraded",
                               "serve.preempted")
    assert STABLE_COUNTER_PREFIXES == ("serve.requests.",
                                       "serve.cache.",
                                       "serve.overload.")
    assert STABLE_GAUGES == ("serve.queue_depth",)
    assert STABLE_HISTOGRAMS == ("serve.queue_ms", "serve.run_ms",
                                 "serve.total_ms",
                                 "serve.batch_occupancy")
    assert prom_name("serve.total_ms") == "yt_serve_total_ms"
    assert prom_name("serve.requests.ok", prefix="x") \
        == "x_serve_requests_ok"


def test_prometheus_exposition_fleet_and_single():
    a = {"counters": {"serve.requests.ok": 3},
         "gauges": {"serve.queue_depth": 1},
         "histograms": {"serve.total_ms": _hist([2.0, 4.0])}}
    b = {"counters": {"serve.requests.ok": 1}}
    text = to_prometheus(merge_snapshots({"w0": a, "w1": b}))
    lines = text.splitlines()
    assert "# TYPE yt_serve_requests_ok counter" in lines
    assert "yt_serve_requests_ok 4" in lines
    assert 'yt_serve_requests_ok{worker="w0"} 3' in lines
    assert 'yt_serve_requests_ok{worker="w1"} 1' in lines
    assert "# TYPE yt_serve_queue_depth gauge" in lines
    assert "# TYPE yt_serve_total_ms summary" in lines
    assert 'yt_serve_total_ms{quantile="0.99"} 4' in lines
    assert "yt_serve_total_ms_count 2" in lines
    assert "yt_serve_total_ms_sum 6" in lines
    assert "yt_serve_total_ms_max 4" in lines
    # a single worker's snapshot exports unlabeled
    solo = to_prometheus(a)
    assert "yt_serve_requests_ok 3" in solo.splitlines()
    assert "worker=" not in solo


def test_obs_export_unwraps_all_reply_shapes():
    from tools.obs_export import export_snapshot
    snap = {"counters": {"serve.requests.ok": 2}}
    for doc in (snap, {"ok": True, "snapshot": snap},
                {"ok": True, "telemetry": merge_snapshots({"w0": snap})}):
        text = export_snapshot(doc)
        assert "yt_serve_requests_ok" in text


def test_registry_snapshot_full_merges_and_exports():
    """The real Registry → snapshot_full → merge → exposition path."""
    regs = []
    for vals in ([5.0, 5.0], [50.0]):
        r = obs_metrics.Registry()
        r.counter("serve.requests.ok").inc()
        for v in vals:
            r.histogram("serve.total_ms").observe(v)
        regs.append(r.snapshot_full())
    assert regs[0]["histograms"]["serve.total_ms"]["samples"] == [5.0, 5.0]
    out = merge_snapshots({"w0": regs[0], "w1": regs[1]})
    m = out["merged"]["histograms"]["serve.total_ms"]
    assert m["p99"] == obs_metrics.percentile([5.0, 5.0, 50.0], 0.99)
    assert "yt_serve_total_ms" in to_prometheus(out)


# ------------------------------------------------------- SLO burn rate

def test_slo_off_unless_knobs(monkeypatch):
    assert not slo_enabled({})
    assert SloMonitor.from_env({}) is None
    m = SloMonitor.from_env({"YT_SLO_P99_MS": "50"})
    assert m is not None and m.p99_ms == 50.0
    assert m.windows == (300.0, 3600.0)
    # bad values fall back to defaults, never raise
    m = SloMonitor.from_env({"YT_SLO_P99_MS": "50",
                             "YT_SLO_WINDOWS": "bogus",
                             "YT_SLO_BURN": "nan-ish?"})
    assert m.windows == (300.0, 3600.0)
    assert m.burn_threshold == 1.0
    m = SloMonitor.from_env({"YT_SLO_WINDOWS": "5,60"})
    assert m.windows == (5.0, 60.0)


def test_slo_breach_requires_every_window(monkeypatch):
    now = [1000.0]
    m = SloMonitor(windows=(10.0, 100.0), burn_threshold=1.0,
                   cooldown_secs=0.0, error_budget=0.5,
                   clock=lambda: now[0])
    m.record(ok=False, trace="t-bad-1")
    # 55s later the short window is empty: no breach even though the
    # long window burns (total>0 required in EVERY window)
    now[0] = 1055.0
    assert m.evaluate() == []
    m.record(ok=False, trace="t-bad-2")
    brs = m.evaluate()
    assert len(brs) == 1
    br = brs[0]
    assert br["v"] == SLO_SCHEMA
    assert br["signal"] == "error_rate"
    assert br["budget"] == 0.5 and br["threshold"] == 1.0
    assert set(br["windows"]) == {"10", "100"}
    for w in br["windows"].values():
        assert w["total"] > 0 and w["burn"] >= 1.0
        assert set(w) == {"burn", "bad", "total"}
    # joined to the worst offender's trace id
    assert br["trace"] == "t-bad-2"


def test_slo_good_traffic_dilutes_and_cooldown_suppresses():
    now = [0.0]
    m = SloMonitor(windows=(10.0,), burn_threshold=1.0,
                   cooldown_secs=30.0, error_budget=0.5,
                   clock=lambda: now[0])
    for _ in range(10):
        m.record(ok=True)
    m.record(ok=False)
    assert m.evaluate() == []          # 1/11 < 50% budget
    for _ in range(10):
        m.record(ok=False)
    assert len(m.evaluate()) == 1      # 11/21 burns past budget
    assert m.evaluate() == []          # cooldown holds
    now[0] = 31.0
    m.record(ok=False)                 # still burning after cooldown
    assert len(m.evaluate()) == 1
    s = m.summary()
    assert s["enabled"] and s["breaches"] == 2
    assert "error_rate" in s["burn"]


def test_slo_latency_and_occupancy_slis():
    m = SloMonitor(windows=(10.0,), p99_ms=100.0, latency_budget=0.5,
                   min_occupancy=2.0, occupancy_budget=0.5,
                   cooldown_secs=0.0, clock=lambda: 5.0)
    m.record(ok=True, total_ms=500.0, occupancy=1.0, trace="t-slow")
    rates = m.burn_rates(now=5.0)
    assert rates["latency"]["windows"]["10"]["bad"] == 1
    assert rates["occupancy"]["windows"]["10"]["bad"] == 1
    signals = {b["signal"] for b in m.evaluate(now=5.0)}
    assert {"latency", "occupancy"} <= signals
    # under the objective = good events
    m.record(ok=True, total_ms=50.0, occupancy=3.0)
    rates = m.burn_rates(now=5.0)
    assert rates["latency"]["windows"]["10"]["bad"] == 1
    assert rates["latency"]["windows"]["10"]["total"] == 2


def test_slo_breach_e2e_scheduler(tmp_path, monkeypatch):
    """In-process server: an injected serve.run device_hang on a jit
    session exhausts the ladder → rejected → the LOG-ONLY monitor
    journals an slo_breach row joined to the request's trace id, and
    metrics_snapshot surfaces monitor + breach count."""
    monkeypatch.setenv("YT_SLO_ERROR_BUDGET", "0.01")
    monkeypatch.setenv("YT_SLO_WINDOWS", "60,3600")
    monkeypatch.setenv("YT_SLO_COOLDOWN", "0")
    monkeypatch.setenv("YT_TRACE", "1")
    monkeypatch.setenv("YT_TRACE_EVENTS", str(tmp_path / "T.jsonl"))
    monkeypatch.setenv("YT_FAULT_PLAN", "serve.run:device_hang:1")
    reset_faults()
    from yask_tpu.serve import StencilServer
    srv = StencilServer(journal_path=str(tmp_path / "SJ.jsonl"),
                        window_secs=0.0, preflight=False)
    try:
        sid = srv.open_session(stencil="iso3dfd", radius=1, g=G,
                               mode="jit", wf=2)
        srv.init_vars(sid)
        r = srv.run(sid, 0, STEPS - 1, timeout=600)
        assert r.status == "rejected" and r.trace
        rows = srv.journal.rows()
        brs = [x for x in rows if x.get("event") == "slo_breach"]
        assert brs, [x.get("event") for x in rows]
        br = brs[0]
        d = br["detail"]
        assert d["slo_v"] == SLO_SCHEMA
        assert d["signal"] == "error_rate"
        assert set(d["windows"]) == {"60", "3600"}
        for w in d["windows"].values():
            assert w["total"] > 0 and w["burn"] >= 1.0
        # joined to the offending request's trace, which has spans
        assert br["trace_id"] == r.trace
        spans = tracer.read_spans(str(tmp_path / "T.jsonl"))
        assert any(s["trace"] == r.trace for s in spans)
        # LOG-ONLY: the next request is served normally
        r2 = srv.run(sid, 0, STEPS - 1, timeout=600)
        assert r2.ok, f"{r2.status}: {r2.error}"
        snap = srv.metrics_snapshot()
        assert snap["v"] == TELEMETRY_SCHEMA
        assert snap["journal"]["slo_breaches"] >= 1
        assert snap["slo"]["enabled"] is True
        assert "error_rate" in snap["slo"]["burn"]
        # the registry export stays inside the stable vocabulary
        for name in snap["counters"]:
            assert name in STABLE_COUNTERS or \
                any(name.startswith(p) for p in STABLE_COUNTER_PREFIXES)
        assert set(STABLE_HISTOGRAMS) <= set(snap["histograms"])
        assert set(STABLE_GAUGES) <= set(snap["gauges"])
        for s in snap["histograms"].values():
            assert "samples" in s      # the mergeable raw window
    finally:
        srv.shutdown()


# -------------------------------------------------------- attribution

def _mk_iso(mode="jit", g=G, **knobs):
    from yask_tpu import yk_factory
    fac = yk_factory()
    env = fac.new_env()
    ctx = fac.new_solution(env, stencil="iso3dfd", radius=2)
    ctx.apply_command_line_options(f"-g {g}")
    o = ctx.get_settings()
    o.mode = mode
    for k, v in knobs.items():
        setattr(o, k, v)
    ctx.prepare_solution()
    rng = np.random.RandomState(11)
    for vn in ctx.get_var_names():
        v = ctx.get_var(vn)
        if vn == "vel":
            v.set_all_elements_same(0.05)
        else:
            arr = rng.rand(g, g, g).astype(np.float32)
            v.set_elements_in_slice(arr, [0, 0, 0, 0],
                                    [0, g - 1, g - 1, g - 1])
    return ctx


def test_attribution_acceptance(tmp_path, monkeypatch):
    """Traced supervised CPU run → one source:"attribution" ledger row:
    measured per-phase seconds reconcile with the root span (10%), the
    roofline model joins by trace id, the report renders, and a
    quarantined perf row poisons its run."""
    import tools.obs_report as obs_report
    from yask_tpu.obs import attribution
    from yask_tpu.perflab import ledger
    from yask_tpu.perflab.provenance import capture_provenance
    tfile = tmp_path / "T.jsonl"
    led = str(tmp_path / "L.jsonl")
    monkeypatch.setenv("YT_TRACE_EVENTS", str(tfile))
    monkeypatch.setenv("YT_TRACE", "1")
    ctx = _mk_iso("jit", ckpt_every=2, ckpt_dir=str(tmp_path))
    ctx.run_solution(0, STEPS - 1)
    spans = tracer.read_spans(str(tfile))
    sup = next(r for r in spans if r["name"] == "run.supervised")

    prov = capture_provenance(platform="cpu", calibrate=False)
    with tracer.activate(sup["trace"]):
        ledger.append_row(ledger.make_row(
            "iso3dfd_8_jit", 0.5, "GPts/s", "cpu", "test", prov,
            roofline={"roofline_frac": 0.5, "hbm_gbps": 10.0,
                      "hbm_bytes_pp": 20.0}), path=led)

    row = attribution.attribute_and_bank(events_path=str(tfile),
                                         ledger_path=led)
    assert row is not None
    assert row["source"] == "attribution"
    assert row["key"] == "attribution:iso3dfd_8_jit"
    ex = row["extra"]
    assert ex["trace"] == sup["trace"]
    # per-phase measured seconds reconcile with the root span's wall
    # time: self-times of a nested tree sum back to the root
    total = sum(d["measured_secs"] for d in ex["phases"].values())
    assert ex["root_secs"] > 0
    assert abs(total - ex["root_secs"]) <= 0.10 * ex["root_secs"]
    assert row["value"] == pytest.approx(total, abs=1e-4)
    # the roofline model joined onto the compute phase by trace id
    comp = ex["phases"]["compute"]
    assert comp["modeled_secs"] == pytest.approx(
        0.5 * comp["measured_secs"], rel=1e-3)
    assert comp["efficiency"] == pytest.approx(0.5, abs=1e-3)
    assert 0.0 <= comp["share"] <= 1.0
    assert row["guard"]["rule"] == "attribution-share-drift"
    # shares flatten into the CSV view
    buf = io.StringIO()
    from yask_tpu.tools.log_to_csv import ledger_to_csv
    assert ledger_to_csv(led, out=buf) == 2
    assert "attr_shares" in buf.getvalue().splitlines()[0]
    assert "compute" in buf.getvalue()

    # the report renders, worst efficiency first
    buf = io.StringIO()
    n = obs_report.attribution_report(ledger.read_rows(path=led),
                                      out=buf)
    assert n == 1
    assert "attribution:iso3dfd_8_jit" in buf.getvalue()

    # a quarantined perf row poisons its run: nothing banked
    qtrace = "t-quarantined"
    with open(tfile, "a") as f:
        f.write(json.dumps(
            {"v": tracer.TRACE_SCHEMA, "trace": qtrace, "span": "sq",
             "parent": "", "name": "run.supervised",
             "phase": "compute", "ts": sup["ts"] + 9999.0, "dur": 1.0,
             "pid": 1, "tid": 1, "attrs": {}}) + "\n")
    qrow = ledger.make_row("iso3dfd_8_jit", 0.0, "GPts/s", "cpu",
                           "test", prov)
    qrow["quarantined"] = True
    qrow["trace_id"] = qtrace
    ledger.append_row(qrow, path=led)
    assert attribution.attribute_and_bank(events_path=str(tfile),
                                          ledger_path=led) is None


def test_attribution_report_excludes_halo_cal_unstable():
    import tools.obs_report as obs_report

    def arow(key, unstable):
        return {"key": key, "source": "attribution", "value": 1.0,
                "guard": {"status": "drift"},
                "extra": {"halo_cal_unstable": unstable,
                          "phases": {"compute": {"measured_secs": 1.0,
                                                 "modeled_secs": 0.25,
                                                 "efficiency": 0.25,
                                                 "share": 1.0}}}}
    buf = io.StringIO()
    n = obs_report.attribution_report(
        [arow("attribution:a", 0), arow("attribution:b", 2)], out=buf)
    assert n == 1
    text = buf.getvalue()
    assert "attribution:a" in text and "attribution:b" not in text
    assert "1 halo-cal-unstable row(s) excluded" in text
    assert "DRIFT" in text


def test_attribution_share_drift_guard():
    from yask_tpu.perflab.sentinel import check_attribution
    hist = [{"source": "attribution", "value": 1.0,
             "extra": {"shares": {"compute": 0.8, "exchange": 0.2}}}
            for _ in range(3)]
    ok = check_attribution({"compute": 0.75, "exchange": 0.25}, hist)
    assert ok["status"] == "ok"
    bad = check_attribution({"compute": 0.4, "exchange": 0.6}, hist)
    assert bad["status"] == "drift"
    assert "exchange" in bad["drifted"]
    assert check_attribution({"compute": 0.8}, [])["status"] \
        == "no_history"


# ---------------------------------------------------- fleet acceptance

def test_fleet_telemetry_merge_and_slo_breach(tmp_path):
    """2-worker fleet under injected serve.run device_hang: each
    worker's first run rejects (jit = bottom rung) and journals an
    slo_breach row joined to its trace id; the merged fleet snapshot
    carries both workers with pooled histograms; fleet_stats surfaces
    the breach counts."""
    from tools.serve_fleet import ServeFleet
    env = {
        "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
        "YT_PERF_LEDGER": str(tmp_path / "ledger.jsonl"),
        "YT_TRACE": "1",
        "YT_TRACE_EVENTS": str(tmp_path / "trace.jsonl"),
        "YT_SLO_ERROR_BUDGET": "0.01",
        "YT_SLO_WINDOWS": "60,3600",
        "YT_SLO_COOLDOWN": "0",
        "YT_FAULT_PLAN": "serve.run:device_hang:1",
    }
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    reset_faults()
    fl = ServeFleet(n_workers=2, cache_dir=str(tmp_path / "cache"),
                    journal_dir=str(tmp_path),
                    worker_args=["--no-preflight", "--window_ms", "5"])
    try:
        sids = []
        for _ in range(2):
            s = fl.handle({"op": "open", "stencil": "iso3dfd",
                           "radius": 1, "g": G, "wf": 2})
            assert s["ok"], s
            assert fl.handle({"op": "init", "sid": s["sid"]})["ok"]
            sids.append(s)
        assert {s["worker"] for s in sids} == {0, 1}

        # each worker's first run hits its injected fault → rejected
        bad = [fl.handle({"op": "run", "sid": s["sid"],
                          "first": 0, "last": STEPS - 1,
                          "timeout": 600}) for s in sids]
        assert all(not r["ok"] for r in bad), bad
        # …then recovers: LOG-ONLY means serving continues
        good = [fl.handle({"op": "run", "sid": s["sid"],
                           "first": 0, "last": STEPS - 1,
                           "timeout": 600}) for s in sids]
        assert all(r["ok"] for r in good), good

        # each worker journal has a breach row joined to the trace of
        # its rejected request (which has spans in the shared file)
        spans = tracer.read_spans(env["YT_TRACE_EVENTS"])
        traced = {s["trace"] for s in spans}
        for w in fl.workers:
            rows = []
            with open(w.journal_path) as f:
                for ln in f:
                    rows.append(json.loads(ln))
            brs = [r for r in rows if r.get("event") == "slo_breach"]
            assert brs, f"worker {w.idx} journaled no slo_breach"
            br = brs[0]
            d = br["detail"]
            assert d["signal"] == "error_rate"
            assert set(d["windows"]) == {"60", "3600"}
            assert all(x["burn"] >= 1.0 and x["total"] > 0
                       for x in d["windows"].values())
            rej = next(r for r in rows
                       if r.get("event") == "rejected")
            assert br["trace_id"] == rej["trace_id"] != ""
            assert br["trace_id"] in traced

        # the merged fleet snapshot: both workers, pooled histograms
        tel = fl.handle({"op": "metrics_snapshot"})
        assert tel["ok"], tel
        t = tel["telemetry"]
        assert t["v"] == TELEMETRY_SCHEMA
        assert set(t["workers"]) == {"w0", "w1"}
        merged = t["merged"]
        assert merged["counters"]["serve.requests.ok"] == 2
        assert merged["counters"]["serve.requests.rejected"] == 2
        assert merged["histograms"]["serve.total_ms"]["count"] == 2
        for wsnap in t["workers"].values():
            assert wsnap["slo"]["enabled"] is True
            for s in wsnap["histograms"].values():
                assert "samples" not in s

        # exposition renders from the merged reply shape
        from tools.obs_export import export_snapshot
        text = export_snapshot(tel)
        assert "yt_serve_requests_rejected 2" in text.splitlines()
        assert 'yt_serve_requests_ok{worker="w0"} 1' \
            in text.splitlines()

        # fleet_stats surfaces per-worker SLO state + breach totals
        fs = fl.handle({"op": "fleet_stats"})
        assert fs["ok"] and fs["slo_breaches"] >= 2
        assert all(row["slo_breaches"] >= 1 and row["slo"]["enabled"]
                   for row in fs["workers"])

        # the heartbeat path banks the same merged shape
        fl.supervise_tick()
        fs = fl.handle({"op": "fleet_stats"})
        assert fs.get("telemetry_ts") is not None
    finally:
        fl.close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        reset_faults()
