"""Config-matrix sweep: block sizes × wf_steps × modes × element bytes.

The runnable analog of the reference's Makefile validation matrix
(``/root/reference/src/kernel/Makefile:1033-1079``): ~50 stencil×config
combos with varied folds/block sizes/temporal tiling plus MPI arg-sets
(``test_args0-4``, incl. ``-min_exterior 0``).  Here every case runs a
short 2-step trial (the reference's ``-trial_steps 2`` validation
stance) and must agree with a jit twin — and the jit twin itself with
the numpy oracle — on the 8-device virtual CPU mesh.

The ``overlap False`` rows are the ``-min_exterior 0`` analog: the
interior/exterior overlap split is disabled so the exchange runs on the
sequential path, exercising the other exchange schedule.
"""

import pytest

from yask_tpu import yk_factory


@pytest.fixture(scope="module")
def env():
    return yk_factory().new_env()


def _build(env, name, radius, mode, wf=1, blk=None, eb=4, ranks=(),
           overlap=True, ovx=None, trz=None, coalesce=None,
           comm_order=None):
    from yask_tpu.runtime.init_utils import init_solution_vars
    from yask_tpu.compiler.solution_base import create_solution
    fac = yk_factory()
    if eb != 4:
        sb = create_solution(name, radius=radius)
        sb.get_soln().set_element_bytes(eb)
        ctx = fac.new_solution(env, sb)
    else:
        ctx = fac.new_solution(env, stencil=name, radius=radius)
    ctx.apply_command_line_options("-g 24")
    s = ctx.get_settings()
    s.mode = mode
    s.wf_steps = wf
    s.overlap_comms = overlap
    if ovx is not None:
        s.overlap_exchange = ovx
    if trz is not None:
        s.trapezoid_tiling = trz
    if coalesce is not None:
        s.coalesce = coalesce
    if comm_order is not None:
        s.comm_order = comm_order
    for d, b in (blk or {}).items():
        ctx.set_block_size(d, b)
    for d, r in ranks:
        ctx.set_num_ranks(d, r)
    ctx.prepare_solution()
    init_solution_vars(ctx)
    return ctx


_jit_ref_cache = {}


def _check(env, name, radius, mode, wf=1, blk=None, eb=4, ranks=(),
           overlap=True, ovx=None, trz=None, coalesce=None,
           comm_order=None):
    eps = (1e-3, 1e-4) if eb == 4 else (3e-2, 3e-2)
    key = (name, radius, eb)
    if key not in _jit_ref_cache:
        ref = _build(env, name, radius, "jit", eb=eb)
        ref.run_solution(0, 1)
        if eb == 4:
            # anchor the jit twin itself to the numpy oracle once
            oracle = _build(env, name, radius, "ref")
            oracle.run_solution(0, 1)
            assert ref.compare_data(oracle, epsilon=eps[0],
                                    abs_epsilon=eps[1]) == 0
        _jit_ref_cache[key] = ref
    ctx = _build(env, name, radius, mode, wf=wf, blk=blk, eb=eb,
                 ranks=ranks, overlap=overlap, ovx=ovx, trz=trz,
                 coalesce=coalesce, comm_order=comm_order)
    ctx.run_solution(0, 1)
    assert ctx.compare_data(_jit_ref_cache[key], epsilon=eps[0],
                            abs_epsilon=eps[1]) == 0


# ---- single-device: modes × wf × blocks × element bytes -----------------

@pytest.mark.parametrize("mode", ["pallas"])
@pytest.mark.parametrize("wf", [1, 2])
@pytest.mark.parametrize("blk", [None, {"x": 8, "y": 8}],
                         ids=["autoblk", "b8"])
@pytest.mark.parametrize("eb", [4, 2], ids=["fp32", "bf16"])
def test_matrix_iso3dfd_pallas(env, mode, wf, blk, eb):
    _check(env, "iso3dfd", 2, mode, wf=wf, blk=blk, eb=eb)


@pytest.mark.parametrize("blk", [None, {"x": 8, "y": 8}, {"x": 12, "y": 4}],
                         ids=["autoblk", "b8", "b12x4"])
def test_matrix_iso3dfd_jit_blocks(env, blk):
    # jit path ignores blocks today; the sweep pins that stance (a
    # future tiled-jit emitter must keep these green)
    _check(env, "iso3dfd", 2, "jit", blk=blk)


@pytest.mark.parametrize("name,radius,wf", [
    ("cube", 1, 2), ("ssg", 1, 2), ("awp", None, 1),
    ("test_scratch_3d", None, 2), ("tti", 1, 1),
])
def test_matrix_families_pallas(env, name, radius, wf):
    _check(env, name, radius, "pallas", wf=wf)


# ---- distributed: modes × wf × mesh × overlap (min_exterior analog) -----

@pytest.mark.parametrize("mode", ["sharded", "shard_map", "shard_pallas"])
@pytest.mark.parametrize("wf", [1, 2])
@pytest.mark.parametrize("ranks", [[("x", 4)], [("x", 2), ("y", 2)]],
                         ids=["x4", "x2y2"])
def test_matrix_iso3dfd_distributed(env, mode, wf, ranks):
    if mode == "sharded" and wf > 1:
        pytest.skip("sharded mode has no temporal fusion")
    _check(env, "iso3dfd", 2, mode, wf=wf, ranks=ranks)


@pytest.mark.parametrize("overlap", [True, False],
                         ids=["overlap", "min_ext0"])
@pytest.mark.parametrize("name,radius", [("iso3dfd", 2), ("ssg", 1)])
def test_matrix_overlap_split(env, overlap, name, radius):
    _check(env, name, radius, "shard_map", ranks=[("x", 2), ("y", 2)],
           overlap=overlap)


@pytest.mark.parametrize("eb", [4, 2], ids=["fp32", "bf16"])
def test_matrix_distributed_dtypes(env, eb):
    _check(env, "iso3dfd", 2, "shard_map", eb=eb, ranks=[("x", 4)])


@pytest.mark.parametrize("trz", [True, False], ids=["trap", "notrap"])
@pytest.mark.parametrize("name,radius,wf", [("iso3dfd", 2, 2),
                                            ("cube", 1, 4)])
def test_matrix_trapezoid(env, trz, name, radius, wf):
    # trapezoid/diamond two-phase tiling as a matrix axis: the knob
    # arms the auto profit gate (trapezoid=None at build); at g=24 the
    # gate decides per config, and either outcome must stay bit-exact
    # against the jit twin (the forced-path equivalence lives in
    # tests/test_trapezoid.py)
    _check(env, name, radius, "pallas", wf=wf, trz=trz)


@pytest.mark.parametrize("coalesce", ["on", "off"])
@pytest.mark.parametrize("ranks",
                         [[("x", 4)], [("x", 2), ("y", 2)],
                          [("x", 2), ("y", 2), ("z", 2)]],
                         ids=["x4", "x2y2", "x2y2z2"])
@pytest.mark.parametrize("mode", ["shard_map", "shard_pallas"])
def test_matrix_comm_schedule(env, mode, ranks, coalesce):
    # mesh-shape × coalescing axis: the packed per-(axis,direction)
    # ppermute schedule across 1-D/2-D/3-D meshes.  shard_pallas keeps
    # K=1 here (the minor dim is sharded in the 3-D row); the K>1
    # coalesce arm lives in tests/test_comm_schedule.py
    _check(env, "iso3dfd", 2, mode, wf=1, ranks=ranks,
           coalesce=coalesce)


def test_matrix_comm_order_permutation(env):
    # explicit exchange-order permutation must agree with the oracle
    # like every other row (bit-equality between orders is proved in
    # tests/test_comm_schedule.py)
    _check(env, "iso3dfd", 2, "shard_map",
           ranks=[("x", 2), ("y", 2)], comm_order="y,x")


@pytest.mark.parametrize("ovx", ["on", "off", "auto"])
@pytest.mark.parametrize("name,radius", [("iso3dfd", 2), ("cube", 1)])
def test_matrix_overlap_exchange(env, ovx, name, radius):
    # overlapped halo exchange (core/shell split of the fused K-group)
    # as a matrix axis: x2 ranks on g=24 give lsize 12 ≥ 2·hK, so "on"
    # genuinely splits (the forced arm errors rather than silently
    # comparing serial to serial)
    _check(env, name, radius, "shard_pallas", wf=2, ranks=[("x", 2)],
           ovx=ovx)


@pytest.mark.parametrize("mode,wf", [("jit", 1), ("jit", 2),
                                     ("pallas", 1), ("pallas", 2)])
@pytest.mark.parametrize("radius", [1, 2])
def test_matrix_pipeline_fusion(env, mode, wf, radius):
    # cross-solution pipeline fusion as a matrix axis: the 3-stage RTM
    # chain fused into one program must agree with the host-chained
    # oracle on every mode × wf × radius row (bit-equality per schedule
    # lives in tests/test_pipeline.py; this sweep uses the standard
    # cross-config tolerance like every other matrix row)
    import numpy as np
    from yask_tpu.ops.pipeline import SolutionPipeline, rtm_chain

    def mk(fuse):
        pipe = SolutionPipeline(env, *rtm_chain(radius=radius))
        pipe.apply_command_line_options(
            f"-g 16 -mode {mode} -wf_steps {wf}")
        pipe.prepare(fuse=fuse)
        v = pipe.get_var("fwd", "pressure")
        rng = np.random.RandomState(3)
        arr = (rng.rand(16, 16, 16).astype(np.float32) - 0.5) * 0.1
        for t in range(v.get_first_valid_step_index(),
                       v.get_last_valid_step_index() + 1):
            v.set_elements_in_slice(arr, [t, 0, 0, 0],
                                    [t, 15, 15, 15])
        return pipe

    fused, chained = mk(True), mk(False)
    assert fused.fused and not chained.fused
    fused.run(0, 1)
    chained.run(0, 1)
    assert fused.compare(chained, epsilon=1e-3, abs_epsilon=1e-4) == 0


@pytest.mark.parametrize("push", ["on", "off", "auto"])
@pytest.mark.parametrize("mode,wf", [("jit", 2), ("pallas", 1),
                                     ("pallas", 2)])
def test_matrix_pipeline_push(env, push, mode, wf):
    # push-memory tile-graph fusion as a matrix axis: the PURE rtm
    # chain (pushable image var) with the -push knob swept against the
    # host-chained oracle on every mode × wf row.  Engagement is
    # asserted where the gate must engage (pallas + on/auto) and must
    # NOT (jit, or -push off) — a row that silently runs the wrong DMA
    # partition cannot pass (bit/tolerance equality per schedule lives
    # in tests/test_pipeline.py).
    import numpy as np
    from yask_tpu.ops.pipeline import SolutionPipeline, rtm_chain

    def mk(fuse, push_cli):
        pipe = SolutionPipeline(
            env, *rtm_chain(radius=2, accumulate=False))
        pipe.apply_command_line_options(
            f"-g 16 -mode {mode} -wf_steps {wf} {push_cli}")
        pipe.prepare(fuse=fuse)
        v = pipe.get_var("fwd", "pressure")
        rng = np.random.RandomState(3)
        arr = (rng.rand(16, 16, 16).astype(np.float32) - 0.5) * 0.1
        for t in range(v.get_first_valid_step_index(),
                       v.get_last_valid_step_index() + 1):
            v.set_elements_in_slice(arr, [t, 0, 0, 0],
                                    [t, 15, 15, 15])
        return pipe

    fused = mk(True, f"-push {push}")
    chained = mk(False, "")
    want_push = mode == "pallas" and push in ("on", "auto")
    assert (fused.pushed_vars() == {"img__img"}) == want_push
    fused.run(0, 1)
    chained.run(0, 1)
    assert fused.compare(chained, epsilon=1e-3, abs_epsilon=1e-4) == 0
