"""Tests for yask_tpu.checker: seeded-violation fixtures (each rule id
must fire), the round-3 VMEM-OOM regression shape, planner reason
recording, and the zero-false-error sweep over known-good configs."""

import io
import types

import pytest

from yask_tpu import yk_factory
from yask_tpu.checker import run_checks, preflight
from yask_tpu.checker.diagnostics import CheckReport, Diagnostic
from yask_tpu.checker.races import check_races
from yask_tpu.checker.mosaic import check_mosaic
from yask_tpu.compiler.solution import yc_factory


def build_ctx(stencil="iso3dfd", radius=8, args="-g 48"):
    fac = yk_factory()
    env = fac.new_env()
    ctx = fac.new_solution(env, stencil=stencil, radius=radius or None)
    ctx.apply_command_line_options(args)
    return ctx


def rules(report):
    return set(report.rules_fired())


def error_rules(report):
    return {d.rule for d in report.errors}


# ---- diagnostics model ----------------------------------------------------

def test_diagnostic_model():
    rep = CheckReport(config={"stencil": "s"})
    rep.add("A-RULE", "error", "broken", var="u", detail={"k": 1})
    rep.add("B-RULE", "info", "fyi")
    assert not rep.ok()
    assert [d.rule for d in rep.errors] == ["A-RULE"]
    j = rep.to_json()
    assert j["schema"] == "yask_tpu.checker/1"
    assert j["summary"] == {"error": 1, "warn": 0, "info": 1}
    assert j["diagnostics"][0]["var"] == "u"
    with pytest.raises(ValueError):
        Diagnostic(rule="X", severity="fatal", message="nope")


# ---- seeded violations: one fixture per rule class ------------------------

def test_mosaic_lane_align_fires_on_unaligned_plan():
    # Plan WITHOUT Mosaic alignment: 48 + 2*8 = 64-wide lane extents are
    # not 128-multiples, so a full-extent window is an unaligned slice
    # (physical tiled layout != logical extent — the probed v5e rule).
    ctx = build_ctx(args="-g 48 -mode pallas -wf_steps 2")
    ctx._plan_geometry()   # resolves ctx._mode = "pallas"
    prog = ctx._csol.plan(ctx._opts.global_domain_sizes,
                          mosaic_align=False)
    rep = CheckReport()
    check_mosaic(rep, ctx, prog)
    fired = error_rules(rep)
    assert "MOSAIC-ALIGN-OFF" in fired
    assert "MOSAIC-LANE-ALIGN" in fired


def test_mosaic_clean_on_aligned_plan():
    ctx = build_ctx(args="-g 48 -mode pallas -wf_steps 2")
    prog = ctx._plan_geometry()
    rep = CheckReport()
    check_mosaic(rep, ctx, prog)
    assert not rep.errors


def test_vmem_over_budget_plan():
    # Explicit blocks fail fast in the planner (the auto-tuner relies on
    # the raise); the checker classifies the message as a rule id.
    ctx = build_ctx(args="-g 128 -mode pallas -wf_steps 2 -b 128 "
                         "-vmem_mb 16")
    rep = run_checks(ctx)
    assert "VMEM-TILE-OVER-BUDGET" in error_rules(rep)


def test_race_missing_dim():
    # u has no y-extent but the RHS varies along y: every y point would
    # demand a different value of the single stored slab.
    soln = yc_factory().new_solution("racy")
    t = soln.new_step_index("t")
    x = soln.new_domain_index("x")
    y = soln.new_domain_index("y")
    u = soln.new_var("u", [t, x])
    v = soln.new_var("v", [t, x, y])
    u(t + 1, x).EQUALS(v(t, x, y + 1))
    fake = types.SimpleNamespace(_csol=None, _soln=soln, _ana=None)
    rep = CheckReport()
    check_races(rep, fake)
    fired = [d for d in rep.errors if d.rule == "RACE-MISSING-DIM"]
    assert fired and fired[0].var == "u" and fired[0].dim == "y"


def test_race_same_point():
    soln = yc_factory().new_solution("selfread")
    t = soln.new_step_index("t")
    x = soln.new_domain_index("x")
    y = soln.new_domain_index("y")
    u = soln.new_var("u", [t, x, y])
    u(t + 1, x, y).EQUALS(u(t + 1, x + 1, y) * 0.5)
    fake = types.SimpleNamespace(_csol=None, _soln=soln, _ana=None)
    rep = CheckReport()
    check_races(rep, fake)
    assert "RACE-SAME-POINT" in error_rules(rep)


def test_race_waw_order_info():
    soln = yc_factory().new_solution("waw")
    t = soln.new_step_index("t")
    x = soln.new_domain_index("x")
    y = soln.new_domain_index("y")
    u = soln.new_var("u", [t, x, y])
    u(t + 1, x, y).EQUALS(u(t, x, y))
    u(t + 1, x, y).EQUALS(u(t, x + 1, y))
    fake = types.SimpleNamespace(_csol=None, _soln=soln, _ana=None)
    rep = CheckReport()
    check_races(rep, fake)
    assert not rep.errors          # WAW is legal, ordered — info only
    assert "RACE-WAW-ORDER" in rules(rep)


def test_ring_depth_underflow():
    soln = yc_factory().new_solution("ring")
    t = soln.new_step_index("t")
    x = soln.new_domain_index("x")
    y = soln.new_domain_index("y")
    u = soln.new_var("u", [t, x, y])
    # the t-1 read carries a spatial halo, so the write-back
    # optimization cannot drop its slot: the floor is a full 3-ring
    u(t + 1, x, y).EQUALS(u(t, x, y) + u(t - 1, x + 1, y))
    soln.analyze()                 # populates step_offsets_used
    assert u.min_step_alloc_size() == 3
    u.set_step_alloc_size(2)       # a live level would be evicted
    fake = types.SimpleNamespace(_csol=None, _soln=soln, _ana=None)
    rep = CheckReport()
    check_races(rep, fake)
    fired = [d for d in rep.errors if d.rule == "RING-DEPTH"]
    assert fired and fired[0].detail == {"manual": 2, "needed": 3}


def test_scratch_halo_catches_mutated_analysis():
    # The analysis fixpoint is consistent by construction -> clean;
    # shrink a computed write-halo by hand and the re-derived demand
    # must catch the drift.
    ctx = build_ctx(stencil="test_scratch_2d", radius=2, args="-g 32")
    rep = run_checks(ctx)
    assert not rep.errors
    swh = ctx._ana.scratch_write_halo
    name = next(iter(swh))
    d = next(iter(swh[name]))
    swh[name][d] = (0, 0)
    rep2 = CheckReport()
    check_races(rep2, ctx)
    assert "SCRATCH-HALO" in error_rules(rep2)


def test_dist_ghost_pad_insufficient():
    # local domain 96/8 = 12 passes the per-step halo validation (12 >=
    # 8) but cannot hold the radius*K = 32 fused ghosts: one exchange
    # cannot feed 4 fused steps.
    ctx = build_ctx(args="-g 96 -mode shard_pallas -wf_steps 4 "
                         "-nr_x 8 -nr_y 1 -nr_z 1")
    rep = run_checks(ctx)
    fired = [d for d in rep.errors if d.rule == "DIST-GHOST-PAD"]
    assert fired and fired[0].dim == "x"
    assert fired[0].detail == {"rank_domain": 12, "ghost": 32}


# ---- cache pass: compile-cache hygiene + ensemble feasibility -------------

def test_ensemble_infeasible_fires_on_sharded_mode():
    ctx = build_ctx(args="-g 64 -mode shard_map -ensemble 4 "
                         "-nr_x 2 -nr_y 1 -nr_z 1")
    rep = run_checks(ctx, passes=["cache"])
    fired = [d for d in rep.errors if d.rule == "ENSEMBLE-INFEASIBLE"]
    assert fired and fired[0].detail["ensemble"] == 4
    assert "mesh" in fired[0].message


def test_ensemble_feasible_is_info_and_off_at_one():
    ctx = build_ctx(args="-g 32 -mode jit -ensemble 4")
    rep = run_checks(ctx, passes=["cache"])
    assert rep.ok()
    infos = [d for d in rep.by_severity("info")
             if d.rule == "ENSEMBLE-INFEASIBLE"]
    assert infos and infos[0].detail["mode"] == "jit"
    # ensemble=1 (the default) emits nothing at all
    ctx = build_ctx(args="-g 32 -mode ref")
    rep = run_checks(ctx, passes=["cache"])
    assert "ENSEMBLE-INFEASIBLE" not in rules(rep)


def test_cache_stale_scan(tmp_path, monkeypatch):
    import pickle
    from yask_tpu.cache import backend_fingerprint
    from yask_tpu.cache.compile_cache import SCHEMA as CSCHEMA
    cur = backend_fingerprint("cpu")
    stale_fp = dict(cur, jax="0.0.0-other")
    (tmp_path / "aaaa.aotc").write_bytes(pickle.dumps(
        {"schema": CSCHEMA, "key": "k1", "fingerprint": stale_fp,
         "payload": b"", "in_tree": b"", "out_tree": b""}))
    (tmp_path / "bbbb.aotc").write_bytes(pickle.dumps(
        {"schema": CSCHEMA, "key": "k2", "fingerprint": cur,
         "payload": b"", "in_tree": b"", "out_tree": b""}))
    (tmp_path / "cccc.aotc").write_bytes(b"not a pickle at all")
    monkeypatch.setenv("YT_COMPILE_CACHE", str(tmp_path))
    ctx = build_ctx(args="-g 32")
    rep = run_checks(ctx, passes=["cache"])
    assert rep.ok()   # hygiene findings are warnings, never errors
    warns = [d for d in rep.warnings if d.rule == "CACHE-STALE"]
    assert len(warns) == 2
    stale = next(d for d in warns if "fingerprint" in d.message)
    assert stale.detail["stale_count"] == 1
    corrupt = next(d for d in warns if "unreadable" in d.message)
    assert corrupt.detail["unreadable_count"] == 1


def test_cache_pass_silent_without_cache_dir(monkeypatch):
    monkeypatch.delenv("YT_COMPILE_CACHE", raising=False)
    ctx = build_ctx(args="-g 32")
    rep = run_checks(ctx, passes=["cache"])
    assert rep.diagnostics == [] and rep.passes == ["cache"]


def test_ckpt_pass_silent_when_supervision_off(monkeypatch):
    # -ckpt_every 0 is a true no-op: no knobs, no diagnostics
    monkeypatch.delenv("YT_CKPT_DIR", raising=False)
    ctx = build_ctx(args="-g 32")
    rep = run_checks(ctx, passes=["ckpt"])
    assert rep.diagnostics == [] and rep.passes == ["ckpt"]


def test_ckpt_dir_cadence_and_ladder_rules(monkeypatch, tmp_path):
    monkeypatch.delenv("YT_CKPT_DIR", raising=False)
    # cadence 3 splits the K=2 fused groups; no dir resolves
    ctx = build_ctx(args="-g 48 -mode pallas -wf_steps 2 -ckpt_every 3")
    rep = run_checks(ctx, passes=["ckpt"])
    assert {"CKPT-DIR", "CKPT-CADENCE", "CKPT-LADDER"} <= rules(rep)
    assert rep.ok()   # both findings are warnings
    lad = next(d for d in rep.diagnostics if d.rule == "CKPT-LADDER")
    assert lad.detail["ladder"] == ["jit"]
    # a writable dir + K-aligned cadence: only the ladder note remains
    ctx2 = build_ctx(args="-g 48 -mode pallas -wf_steps 2 -ckpt_every 4"
                     f" -ckpt_dir {tmp_path}")
    rep2 = run_checks(ctx2, passes=["ckpt"])
    assert rules(rep2) == {"CKPT-LADDER"}


def test_ckpt_unwritable_dir_is_error(tmp_path, monkeypatch):
    # root ignores permission bits, so force the access answer instead
    # of chmod-ing a fixture dir
    import os
    ctx = build_ctx(args=f"-g 32 -ckpt_every 2 -ckpt_dir {tmp_path}")
    monkeypatch.setattr(os, "access", lambda p, m: False)
    rep = run_checks(ctx, passes=["ckpt"])
    assert "CKPT-DIR" in {d.rule for d in rep.errors}


def test_ckpt_deadline_without_cadence_warns():
    ctx = build_ctx(args="-g 32 -run_deadline 60")
    rep = run_checks(ctx, passes=["ckpt"])
    assert "CKPT-DEADLINE" in {d.rule for d in rep.warnings}


# ---- the round-3 regression shape -----------------------------------------

def test_round3_vmem_spill_oom_flagged_statically():
    """512^3 r=8 K=2 with explicit 64x64 blocks at -vmem_mb 120: tiles
    pass the 120 MiB planning budget but the live-value model (2x)
    exceeds the 128 MiB scoped Mosaic limit — the register-spill OOM
    that crashed the round-3 joint tune.  Must be an error, found
    WITHOUT allocating the 512^3 state."""
    ctx = build_ctx(args="-g 512 -mode pallas -wf_steps 2 -b 64 "
                         "-vmem_mb 120")
    rep = run_checks(ctx)
    spills = [d for d in rep.errors if d.rule == "VMEM-SPILL"]
    assert spills, rep.render(verbose=True)
    det = spills[0].detail
    assert det["tile_bytes"] <= 120 * 2 ** 20      # planner accepted it
    assert det["live_model_bytes"] > det["vmem_limit"]
    assert ctx._state is None                      # nothing allocated
    assert not ctx.is_prepared()


def test_default_budget_is_spill_free():
    # The TPU default budget (64 MiB) keeps live = 2*tile <= limit by
    # construction; the flagship at 512^3 must check clean.
    ctx = build_ctx(args="-g 512 -mode pallas -wf_steps 2")
    rep = run_checks(ctx)
    assert rep.ok(), rep.render(verbose=True)


def test_vmem_limit_single_definition():
    # The checker imports the SAME function CompilerParams uses.
    from yask_tpu.checker.vmem import vmem_limit_bytes as a
    from yask_tpu.ops.pallas_stencil import vmem_limit_bytes as b
    assert a is b
    assert b(64 * 2 ** 20) == 128 * 2 ** 20
    assert b(120 * 2 ** 20) == 128 * 2 ** 20       # capped
    assert b(16 * 2 ** 20) == 32 * 2 ** 20


# ---- planner reason recording (the no-silent-fallback satellite) ----------

def test_reasons_one_per_ladder_step():
    """16^3 r=8 K=2: skew engages in both lead dims, the carry floor
    fails 2-D -> falls to 1-D -> fails again -> uniform shrink; each
    ladder step must record a structured reason."""
    from yask_tpu.ops.pallas_stencil import build_pallas_chunk
    ctx = build_ctx(args="-g 16 -mode pallas -wf_steps 2")
    prog = ctx._plan_geometry()
    reasons = []
    build_pallas_chunk(prog, fuse_steps=2, vmem_budget=ctx.vmem_budget(),
                       plan_only=True, reasons=reasons)
    codes = [r["code"] for r in reasons]
    falls = [r for r in reasons if r["code"] == "skew_fallback"]
    assert [f["to"] for f in falls] == ["1-D skew", "uniform shrink"]
    assert all(f["cause"] for f in falls)
    assert codes.index("skew_engaged") < codes.index("skew_fallback")
    assert "skew_disabled" in codes                # ladder bottom
    assert "pipe_in_off" in codes and "pipe_out_off" in codes


def test_reasons_in_built_chunk_tiling():
    from yask_tpu.ops.pallas_stencil import build_pallas_chunk
    ctx = build_ctx(args="-g 48 -mode pallas -wf_steps 2")
    prog = ctx._plan_geometry()
    chunk, _tb = build_pallas_chunk(prog, fuse_steps=2, interpret=True,
                                    vmem_budget=ctx.vmem_budget())
    codes = [r["code"] for r in chunk.tiling["reasons"]]
    assert "skew_engaged" in codes
    assert "pipe_in_on" in codes and "pipe_out_on" in codes


def test_plan_only_matches_built_tiling():
    from yask_tpu.ops.pallas_stencil import build_pallas_chunk
    ctx = build_ctx(args="-g 48 -mode pallas -wf_steps 2")
    prog = ctx._plan_geometry()
    plan = build_pallas_chunk(prog, fuse_steps=2,
                              vmem_budget=ctx.vmem_budget(),
                              plan_only=True)
    chunk, _tb = build_pallas_chunk(prog, fuse_steps=2, interpret=True,
                                    vmem_budget=ctx.vmem_budget())
    for k in ("block", "fuse_steps", "skew", "skew_dims"):
        assert plan[k] == chunk.tiling[k], k


# ---- run_checks / preflight plumbing --------------------------------------

def test_unknown_pass_rejected():
    from yask_tpu.utils.exceptions import YaskException
    ctx = build_ctx(args="-g 32")
    with pytest.raises(YaskException):
        run_checks(ctx, passes=["mosaic", "nope"])


def test_preflight_honors_setting_and_returns_status():
    ctx = build_ctx(args="-g 512 -mode pallas -wf_steps 2 -b 64 "
                         "-vmem_mb 120")
    buf = io.StringIO()
    assert preflight(ctx, out=buf) is False
    assert "VMEM-SPILL" in buf.getvalue()
    ctx._opts.preflight = False
    assert preflight(ctx, out=io.StringIO()) is True


def test_preflight_never_raises_on_internal_failure():
    broken = types.SimpleNamespace(_opts=types.SimpleNamespace(
        preflight=True))
    buf = io.StringIO()
    assert preflight(broken, out=buf) is True
    assert "internal failure" in buf.getvalue()


# ---- zero false errors on known-good configs ------------------------------

QUICK_GOOD = ["iso3dfd", "ssg", "tti", "wave2d", "test_misc_2d",
              "test_scratch_3d", "test_stages_2d", "test_reverse_2d"]


@pytest.mark.parametrize("name", QUICK_GOOD)
def test_no_false_errors_quick(name):
    from yask_tpu.ops.pallas_stencil import pallas_applicable
    for mode in ("jit", "pallas"):
        ctx = build_ctx(stencil=name, radius=0, args="-g 32")
        if mode == "pallas":
            ok, _ = pallas_applicable(ctx._csol)
            if not ok:
                continue
            ctx.get_settings().wf_steps = 2
        ctx.get_settings().mode = mode
        rep = run_checks(ctx)
        assert rep.ok(), f"{name}/{mode}: " + rep.render(verbose=True)


@pytest.mark.slow
def test_no_false_errors_all_stencils():
    """Every registered stencil x (jit, pallas-when-applicable) checks
    clean — the CLI sweep the Makefile `check` target also runs."""
    from yask_tpu.checker.__main__ import run_checker
    buf = io.StringIO()
    assert run_checker(["-all_stencils"], out=buf) == 0, buf.getvalue()


# ---- CLI ------------------------------------------------------------------

def test_cli_json_and_exit_codes():
    from yask_tpu.checker.__main__ import run_checker
    buf = io.StringIO()
    rc = run_checker(["-stencil", "iso3dfd", "-radius", "8", "-json",
                      "-g", "48", "-mode", "pallas", "-wf_steps", "2"],
                     out=buf)
    assert rc == 0
    import json
    j = json.loads(buf.getvalue())
    assert j["schema"] == "yask_tpu.checker/1"
    assert j["summary"]["error"] == 0

    buf = io.StringIO()
    rc = run_checker(["-stencil", "iso3dfd", "-radius", "8", "-g", "512",
                      "-mode", "pallas", "-wf_steps", "2", "-b", "64",
                      "-vmem_mb", "120"], out=buf)
    assert rc == 1 and "VMEM-SPILL" in buf.getvalue()

    assert run_checker([], out=io.StringIO()) == 2   # no stencil
